"""Scratch: per-query cProfile of the served GO path (tpu + cpu), uncontended."""
import cProfile
import pstats
import sys
import time

import numpy as np

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.flags import flags
from nebula_tpu.tools.perf_fixture import ensure_perf_space
from nebula_tpu.codec.rows import encode_row
from nebula_tpu.common.clock import inverted_version
from nebula_tpu.common.keys import KeyUtils, id_hash

n, m, steps = 1 << 17, 1 << 20, 4
rng = np.random.default_rng(42)
edge_src = rng.integers(0, n, m, dtype=np.int32)
edge_dst = rng.integers(0, n, m, dtype=np.int32)

c = LocalCluster(num_storage=1, tpu_backend=True)
space_id, _tag, etype = ensure_perf_space(c.graph_meta_client)
c.refresh_all()
kv = c.storage_nodes[0].kv
parts = kv.part_ids(space_id)
nparts = len(parts)
schema = c.schema_man.get_edge_schema(space_id, etype)
ver = inverted_version()
by_part = {p: [] for p in parts}
for i in range(m):
    s, d = int(edge_src[i]) + 1, int(edge_dst[i]) + 1
    val = encode_row(schema, {"w": i % 97})
    by_part[id_hash(s, nparts)].append(
        (KeyUtils.edge_key(id_hash(s, nparts), s, etype, 0, d, ver), val))
    by_part[id_hash(d, nparts)].append(
        (KeyUtils.edge_key(id_hash(d, nparts), d, -etype, 0, s, ver), val))
for p, kvs in by_part.items():
    for lo in range(0, len(kvs), 65536):
        kv.multi_put(space_id, p, kvs[lo:lo + 65536])

vids = rng.integers(1, n + 1, 64)
queries = [f"GO {steps} STEPS FROM {v} OVER rel" for v in vids]

g = c.client()
g.execute("USE perf")

for backend, nq in (("tpu", 40), ("cpu", 12)):
    flags.set("storage_backend", backend)
    r = g.execute(queries[0])      # warm
    assert r.ok(), r.error_msg
    t0 = time.perf_counter()
    pr = cProfile.Profile()
    pr.enable()
    for q in queries[1:1 + nq]:
        r = g.execute(q)
        assert r.ok(), r.error_msg
    pr.disable()
    dt = time.perf_counter() - t0
    print(f"\n========== {backend}: {1e3 * dt / nq:.1f} ms/query ==========",
          flush=True)
    st = pstats.Stats(pr, stream=sys.stdout)
    st.sort_stats("cumulative").print_stats(28)

c.stop()
