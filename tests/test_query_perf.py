"""query-perf tool smoke: the concurrent GO load generator must drive
both backends error-free on a small cluster and report sane stats."""
from nebula_tpu.tools import query_perf


def test_query_perf_both_backends():
    c, _ = query_perf.build_cluster(n_vertices=300, n_edges=1500)
    try:
        for backend in ("cpu", "tpu"):
            out = query_perf.run(c, steps=2, threads=4, total=24,
                                 n_vertices=300, backend=backend)
            assert out["errors"] == 0, out
            assert out["requests"] == 24
            assert out["p50_us"] > 0
        # the dispatcher must have seen the tpu queries — through the
        # windowed coalescer or the continuous seat-map tier
        d = c.tpu_runtime.dispatcher
        assert (d.stats["batched_queries"]
                + d.stats["continuous_queries"]) >= 24
    finally:
        from nebula_tpu.common.flags import flags
        flags.set("storage_backend", "tpu")
        c.stop()
