"""Crash-recovery hardening suite (docs/durability.md).

Covers the in-process half of the crash story: the CRC'd WAL v2 format
(torn tails and bit rot truncate instead of replaying garbage; flush
failures drop the un-persisted tail and surface a Status), the device
circuit breaker state machine + its end-to-end surface (degraded
declines with completeness/warnings, /healthz, events, half-open
re-admission), and the StorageClient leaderless-fallback regression.
The multi-PROCESS half (real SIGKILLs) lives in test_proc_chaos.py.
"""
import os
import time

import pytest

from nebula_tpu.common.events import journal
from nebula_tpu.common.flags import flags
from nebula_tpu.common.stats import stats
from nebula_tpu.common.status import ErrorCode
from nebula_tpu.kvstore.wal import (FileBasedWal, _HDR, _MAGIC2,
                                    _frame_crc)

pytestmark = pytest.mark.chaos


def _stat(name: str) -> float:
    return stats.read_stats(f"{name}.sum.60") or 0.0


# ============================================================= WAL v2
class TestWalCrc:
    def test_v2_roundtrip_and_replay(self, tmp_path):
        w = FileBasedWal(str(tmp_path))
        for i in range(1, 201):
            assert w.append_log(i, 1 + i // 100, b"payload-%d" % i)
        assert w.flush().ok()
        w.close()
        # new segments carry the v2 magic
        segs = sorted(p for p in os.listdir(tmp_path)
                      if p.startswith("wal."))
        with open(tmp_path / segs[0], "rb") as f:
            assert f.read(len(_MAGIC2)) == _MAGIC2
        w2 = FileBasedWal(str(tmp_path))
        assert w2.first_log_id() == 1
        assert w2.last_log_id() == 200
        assert w2._find(137).msg == b"payload-137"
        assert w2.get_term(199) == 2
        w2.close()

    def test_corrupt_frame_truncates_and_journals(self, tmp_path):
        w = FileBasedWal(str(tmp_path))
        for i in range(1, 101):
            w.append_log(i, 1, b"m%d" % i)
        assert w.flush().ok()
        w.close()
        seg = next(str(tmp_path / p) for p in os.listdir(tmp_path)
                   if p.startswith("wal."))
        data = bytearray(open(seg, "rb").read())
        flip = len(data) * 6 // 10            # past the magic, mid-log
        data[flip] ^= 0xFF
        open(seg, "wb").write(bytes(data))
        journal.clear_for_tests()
        before = _stat("recovery.wal_truncated")
        w2 = FileBasedWal(str(tmp_path))
        # truncated at the first bad frame: a contiguous verified
        # prefix survives, NOTHING after the corruption replays
        assert 0 < w2.last_log_id() < 100
        for i in range(1, w2.last_log_id() + 1):
            assert w2._find(i).msg == b"m%d" % i
        assert _stat("recovery.wal_truncated") > before
        evs = [e for e in journal.dump() if e["kind"] == "wal.truncated"]
        assert evs and evs[0]["dropped_bytes"] > 0
        # the file was PHYSICALLY cut: appends chain cleanly and a
        # third load agrees with the second
        nxt = w2.last_log_id() + 1
        assert w2.append_log(nxt, 9, b"after-repair")
        assert w2.flush().ok()
        w2.close()
        w3 = FileBasedWal(str(tmp_path))
        assert w3.last_log_id() == nxt
        assert w3._find(nxt).msg == b"after-repair"
        assert w3.get_term(nxt) == 9
        w3.close()

    def test_corruption_drops_later_segments(self, tmp_path):
        """Frames after a bad one are not contiguous with the verified
        prefix — recovery must delete LATER segment files too, or a
        stale segment would shadow the re-appends of the same ids on
        the next load."""
        w = FileBasedWal(str(tmp_path))
        for i in range(1, 51):
            w.append_log(i, 1, b"a%d" % i)
        assert w.flush().ok()
        # force a second segment by faking a full first one
        w._cur_seg_bytes = 64 * 1024 * 1024
        for i in range(51, 101):
            w.append_log(i, 1, b"b%d" % i)
        assert w.flush().ok()
        w.close()
        # numeric sort — segment names are wal.<firstId>.log
        segs = sorted((p for p in os.listdir(tmp_path)
                       if p.startswith("wal.")),
                      key=lambda p: int(p[4:-4]))
        assert len(segs) == 2
        data = bytearray(open(tmp_path / segs[0], "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(tmp_path / segs[0], "wb").write(bytes(data))
        journal.clear_for_tests()
        w2 = FileBasedWal(str(tmp_path))
        assert 0 < w2.last_log_id() < 50
        w2.close()
        left = sorted(p for p in os.listdir(tmp_path)
                      if p.startswith("wal."))
        assert segs[1] not in left

    def test_torn_tail_truncates_cleanly(self, tmp_path):
        w = FileBasedWal(str(tmp_path))
        for i in range(1, 21):
            w.append_log(i, 1, b"x" * 100)
        assert w.flush().ok()
        w.close()
        seg = next(str(tmp_path / p) for p in os.listdir(tmp_path)
                   if p.startswith("wal."))
        size = os.path.getsize(seg)
        with open(seg, "r+b") as f:
            f.truncate(size - 37)             # tear the last frame
        journal.clear_for_tests()
        w2 = FileBasedWal(str(tmp_path))
        assert w2.last_log_id() == 19
        assert any(e["kind"] == "wal.truncated" for e in journal.dump())
        assert w2.append_log(20, 2, b"rewrite")
        assert w2.flush().ok()
        w2.close()
        w3 = FileBasedWal(str(tmp_path))
        assert w3._find(20).msg == b"rewrite" and w3.get_term(20) == 2
        w3.close()

    def test_v1_segment_backward_compat_and_rotation(self, tmp_path):
        """A crc-less legacy segment replays (reader compat) and the
        first flush ROTATES to a fresh v2 segment rather than mixing
        frame formats in one file."""
        with open(tmp_path / "wal.1.log", "wb") as f:
            for i in range(1, 11):
                msg = b"legacy-%d" % i
                f.write(_HDR.pack(i, 3, len(msg)))
                f.write(msg)
        w = FileBasedWal(str(tmp_path))
        assert w.last_log_id() == 10
        assert w._find(4).msg == b"legacy-4" and w.get_term(4) == 3
        assert w.append_log(11, 3, b"fresh")
        assert w.flush().ok()
        w.close()
        segs = sorted(p for p in os.listdir(tmp_path)
                      if p.startswith("wal."))
        assert len(segs) == 2
        with open(tmp_path / segs[0], "rb") as f:
            assert f.read(len(_MAGIC2)) != _MAGIC2      # legacy untouched
        with open(tmp_path / segs[1], "rb") as f:
            assert f.read(len(_MAGIC2)) == _MAGIC2      # new one is v2
        w2 = FileBasedWal(str(tmp_path))
        assert w2.last_log_id() == 11 and w2._find(11).msg == b"fresh"
        w2.close()

    def test_flush_failure_drops_tail_and_surfaces_status(self, tmp_path,
                                                          monkeypatch):
        """Satellite: an exception mid-flush must not leave buffered
        frames acked in the tail map — the un-persisted tail drops, the
        Status says so, and disk/memory agree afterwards."""
        w = FileBasedWal(str(tmp_path))
        for i in range(1, 6):
            w.append_log(i, 1, b"durable")
        assert w.flush().ok()
        w.append_log(6, 1, b"doomed")
        w.append_log(7, 1, b"doomed-too")
        before = _stat("recovery.wal_flush_failed")

        def enospc(fd, data):
            raise OSError(28, "No space left on device")

        monkeypatch.setattr(os, "write", enospc)
        st = w.flush()
        monkeypatch.undo()
        assert not st.ok()
        assert st.code == ErrorCode.E_WAL_FAIL
        # the tail map no longer claims the entries the disk refused
        assert w.last_log_id() == 5
        assert w._find(6) is None
        assert _stat("recovery.wal_flush_failed") > before
        # recovery of the writer: same ids re-append and persist
        assert w.append_log(6, 2, b"retried")
        assert w.flush().ok()
        w.close()
        w2 = FileBasedWal(str(tmp_path))
        assert w2.last_log_id() == 6
        assert w2._find(6).msg == b"retried" and w2.get_term(6) == 2
        w2.close()

    def test_raft_append_fails_cleanly_on_wal_failure(self, tmp_path,
                                                      monkeypatch):
        """The raft driver must FAIL the batch (typed status, waiter
        woken) when the WAL refuses the flush — never ack, never hang,
        and keep serving once the disk heals."""
        import concurrent.futures
        from nebula_tpu.raftex.raft_part import RaftPart
        pool = concurrent.futures.ThreadPoolExecutor(max_workers=2)
        part = RaftPart(1, 1, "127.0.0.1:1", [], None, pool,
                        wal_dir=str(tmp_path))
        try:
            assert part.append_async(b"healthy").ok()

            def enospc(fd, data):
                raise OSError(28, "No space left on device")

            monkeypatch.setattr(os, "write", enospc)
            st = part.append_async(b"doomed")
            monkeypatch.undo()
            assert not st.ok()
            assert st.code == ErrorCode.E_WAL_FAIL
            # the disk healed: appends serve again and the log is
            # exactly the acked set
            assert part.append_async(b"healed").ok()
            msgs = [e.msg for e in part.wal.iterate(1)]
            assert b"doomed" not in msgs and b"healed" in msgs
        finally:
            part.stop()
            pool.shutdown(wait=False)

    def test_rollback_rewrites_with_crc(self, tmp_path):
        w = FileBasedWal(str(tmp_path))
        for i in range(1, 31):
            w.append_log(i, 1, b"r%d" % i)
        assert w.flush().ok()
        assert w.rollback_to_log(12)
        assert w.last_log_id() == 12
        for i in range(13, 18):
            w.append_log(i, 4, b"n%d" % i)
        assert w.flush().ok()
        w.close()
        # the rewritten segment is v2 and replays exactly
        for p in sorted(os.listdir(tmp_path)):
            if p.startswith("wal."):
                with open(tmp_path / p, "rb") as f:
                    assert f.read(len(_MAGIC2)) == _MAGIC2
        w2 = FileBasedWal(str(tmp_path))
        assert w2.last_log_id() == 17
        assert w2.get_term(12) == 1 and w2.get_term(13) == 4
        assert w2._find(15).msg == b"n15"
        w2.close()

    def test_frame_crc_covers_header_fields(self):
        # flipping ANY header field must invalidate the crc, not just
        # the payload bytes
        c = _frame_crc(5, 2, b"msg")
        assert c != _frame_crc(6, 2, b"msg")
        assert c != _frame_crc(5, 3, b"msg")
        assert c != _frame_crc(5, 2, b"msG")


# ===================================================== breaker unit
class TestDeviceBreakerUnit:
    def _mk(self):
        from nebula_tpu.storage.device import DeviceCircuitBreaker
        return DeviceCircuitBreaker()

    @pytest.fixture(autouse=True)
    def _fast_breaker(self):
        saved = (flags.get("tpu_breaker_failures"),
                 flags.get("tpu_breaker_open_s"))
        flags.set("tpu_breaker_failures", 3)
        # WIDE open window by default: tests asserting a cell STAYS
        # open must not flake when a GC pause or suite load stalls
        # longer than open_s between record_failure and admit (a 0.15s
        # window half-opens under a loaded tier-1 run); the tests that
        # need the window to ELAPSE shrink it themselves
        flags.set("tpu_breaker_open_s", 30.0)
        yield
        flags.set("tpu_breaker_failures", saved[0])
        flags.set("tpu_breaker_open_s", saved[1])

    def test_opens_after_threshold_and_fast_fails(self):
        b = self._mk()
        key = (7, "go")
        journal.clear_for_tests()
        assert b.admit(key) is None
        for _ in range(2):
            b.record_failure(key, "xla_runtime")
            assert b.admit(key) is None         # still closed
        b.record_failure(key, "xla_runtime")    # third: opens
        why = b.admit(key)
        assert why is not None and "breaker open" in why
        assert any(e["kind"] == "tpu.breaker_open"
                   for e in journal.dump())
        assert [s for k, s, _ in b.cells_snapshot() if k == key] == ["open"]

    def test_half_open_single_probe_then_reclose(self):
        b = self._mk()
        key = (7, "go")
        flags.set("tpu_breaker_open_s", 0.15)   # fixture restores
        for _ in range(3):
            b.record_failure(key, "transfer")
        assert b.admit(key) is not None
        time.sleep(0.2)                         # open window elapses
        assert b.admit(key) is None             # THE probe
        assert b.admit(key) is not None         # everyone else declines
        b.record_success(key)                   # probe succeeded
        assert b.admit(key) is None
        assert [s for k, s, _ in b.cells_snapshot()
                if k == key] == ["closed"]

    def test_probe_release_keeps_half_open(self):
        """A probe that ends in an UNCLASSIFIED error (deadline, plain
        query bug) proves nothing about device health: the token goes
        back, the NEXT query probes, and the cell must not close (a
        still-broken device would otherwise take full traffic again)."""
        b = self._mk()
        key = (7, "go")
        flags.set("tpu_breaker_open_s", 0.15)   # fixture restores
        for _ in range(3):
            b.record_failure(key, "xla_runtime")
        time.sleep(0.2)
        assert b.admit(key) is None             # probe handed out
        b.release_probe(key)                    # ...ended inconclusively
        assert [s for k, s, _ in b.cells_snapshot()
                if k == key] == ["half_open"]
        assert b.admit(key) is None             # next query re-probes
        b.record_failure(key, "xla_runtime")    # and a real failure
        assert b.admit(key) is not None         # re-opens

    def test_release_probe_does_not_clear_failure_streak(self):
        b = self._mk()
        key = (8, "go")
        b.record_failure(key, "transfer")
        b.record_failure(key, "transfer")
        b.release_probe(key)                    # neutral on closed cells
        b.record_failure(key, "transfer")       # third consecutive
        assert b.admit(key) is not None         # opened

    def test_half_open_probe_failure_reopens(self):
        b = self._mk()
        key = (7, "path")
        flags.set("tpu_breaker_open_s", 0.15)   # fixture restores
        for _ in range(3):
            b.record_failure(key, "resource_exhausted")
        time.sleep(0.2)
        assert b.admit(key) is None             # probe admitted
        b.record_failure(key, "resource_exhausted")
        assert b.admit(key) is not None         # straight back to open

    def test_success_resets_consecutive_count(self):
        b = self._mk()
        key = (1, "go")
        b.record_failure(key, "transfer")
        b.record_failure(key, "transfer")
        b.record_success(key)
        b.record_failure(key, "transfer")
        b.record_failure(key, "transfer")
        assert b.admit(key) is None             # never hit 3 in a row

    def test_reset_space_half_opens_immediately(self):
        b = self._mk()
        key = (3, "go")
        for _ in range(3):
            b.record_failure(key, "xla_runtime")
        assert b.admit(key) is not None
        b.reset_space(3)                        # mirror republished
        assert b.admit(key) is None             # probes NOW, no clock wait
        b.record_success(key)
        assert not b.is_open(key)

    def test_threshold_zero_disables(self):
        b = self._mk()
        flags.set("tpu_breaker_failures", 0)
        for _ in range(10):
            b.record_failure((9, "go"), "xla_runtime")
        assert b.admit((9, "go")) is None

    def test_keys_are_independent(self):
        b = self._mk()
        for _ in range(3):
            b.record_failure((1, "go"), "xla_runtime")
        assert b.admit((1, "go")) is not None
        assert b.admit((1, "path")) is None
        assert b.admit((2, "go")) is None


class TestClassifier:
    def test_classifies_runtime_failures(self):
        from nebula_tpu.storage.device import classify_device_failure

        class XlaRuntimeError(Exception):
            pass

        assert classify_device_failure(
            XlaRuntimeError("INTERNAL: something")) == "xla_runtime"
        assert classify_device_failure(
            RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                         "1.2G in HBM")) == "resource_exhausted"
        assert classify_device_failure(
            RuntimeError("device transfer failed mid-copy")) == "transfer"

    def test_typed_control_errors_pass_through(self):
        from nebula_tpu.common.deadline import DeadlineExceeded
        from nebula_tpu.storage.device import (DeviceExecError, TpuDecline,
                                               classify_device_failure)
        assert classify_device_failure(TpuDecline("nope")) is None
        assert classify_device_failure(DeviceExecError("bad expr")) is None
        assert classify_device_failure(DeadlineExceeded("late")) is None
        assert classify_device_failure(ValueError("plain bug")) is None


# ====================================================== breaker e2e
class TestDeviceBreakerE2E:
    def test_runtime_failure_opens_breaker_cpu_serves_probe_readmits(self):
        """Acceptance: a fault-injected device runtime failure opens
        the breaker (metric + event + /healthz visible), queries keep
        answering via the CPU fallback with completeness < 100 and a
        warning surfaced, and a half-open probe restores device serving
        without a daemon restart."""
        import json
        import urllib.error
        import urllib.request
        from nebula_tpu.cluster import LocalCluster
        from nebula_tpu.storage.web import register_web_handlers
        from nebula_tpu.webservice import WebService
        saved = (flags.get("tpu_breaker_failures"),
                 flags.get("tpu_breaker_open_s"))
        flags.set("tpu_breaker_failures", 2)
        flags.set("tpu_breaker_open_s", 30.0)
        c = LocalCluster(num_storage=1, tpu_backend="remote")
        cl = c.client()
        ws = None
        try:
            def ok(stmt):
                r = cl.execute(stmt)
                assert r.ok(), f"{stmt}: {r.error_msg}"
                return r

            ok("CREATE SPACE brk(partition_num=2, replica_factor=1)")
            c.refresh_all()
            ok("USE brk")
            ok("CREATE EDGE e(w int)")
            c.refresh_all()
            ok("INSERT EDGE e(w) VALUES 1->2:(5), 2->3:(6), 1->3:(7)")
            q = "GO 2 STEPS FROM 1 OVER e YIELD e._dst"
            expect = sorted(x[0] for x in ok(q).rows)
            svc = c.storage_nodes[0].service
            rt = svc._device_rt
            assert rt is not None, "device runtime never attached"

            class XlaRuntimeError(Exception):
                pass

            real = rt.go_batch_execute

            def boom(*a, **k):
                raise XlaRuntimeError(
                    "RESOURCE_EXHAUSTED: out of memory in HBM")

            # break BOTH dispatch pipelines: windowed batches enter
            # via go_batch_execute; continuous streams fail at the
            # next hop of their LIVE session and at every re-anchor
            # attempt (the pump's _fail_all hands the classified
            # error back to every rider)
            rt.go_batch_execute = boom
            rt.continuous_session = boom
            for _st in rt.dispatcher.continuous.streams():
                if _st.session is not None:
                    _st.session.hop = boom
            journal.clear_for_tests()
            opened_before = _stat("tpu.breaker.opened")
            for _ in range(3):
                r = ok(q)
                # the CPU fallback keeps answering, degraded-marked
                assert sorted(x[0] for x in r.rows) == expect
                assert r.completeness == 99
                assert r.warnings and "degraded" in r.warnings[0]
            assert any(s == "open" for _k, s, _r in svc.breaker_snapshot())
            assert _stat("tpu.breaker.opened") > opened_before
            assert any(e["kind"] == "tpu.breaker_open"
                       for e in journal.dump())

            # /healthz flips 503 with the open cell named
            ws = WebService("storaged-test").start()
            register_web_handlers(ws, c.storage_nodes[0])
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{ws.port}/healthz")
            assert ei.value.code == 503
            body = json.load(ei.value)
            assert not body["checks"]["device_breaker"]["ok"]
            assert "breaker open" in \
                body["checks"]["device_breaker"]["detail"]

            # heal the device; the half-open probe re-admits WITHOUT a
            # daemon restart
            rt.go_batch_execute = real
            del rt.continuous_session       # class method again
            flags.set("tpu_breaker_open_s", 0.05)
            time.sleep(0.1)
            r = ok(q)
            assert sorted(x[0] for x in r.rows) == expect
            assert r.completeness == 100 and not r.warnings
            assert all(s == "closed"
                       for _k, s, _r in svc.breaker_snapshot())
            assert _stat("tpu.breaker.reclosed") > 0
            got = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{ws.port}/healthz"))
            assert got["checks"]["device_breaker"]["ok"]
        finally:
            if ws is not None:
                ws.stop()
            flags.set("tpu_breaker_failures", saved[0])
            flags.set("tpu_breaker_open_s", saved[1])
            cl.disconnect()
            c.stop()


# ============================================ client fallback regression
class TestLeaderlessFallbackSkip:
    class _Meta:
        """Stub meta client: one space, one part, two replicas."""

        def __init__(self, peers):
            self._peers = peers

        def part_num(self, space_id):
            return 1

        def parts_alloc(self, space_id):
            return {0: list(self._peers)}

    def test_fallback_skips_just_invalidated_host(self):
        """Satellite regression (client.py:66-88): after
        invalidate_leader(X) the round-robin fallback must NOT re-dial
        X first — whatever the cursor position, the first leaderless
        pick after an invalidation lands on a DIFFERENT replica."""
        from nebula_tpu.storage.client import StorageClient
        peers = ["hostA:1", "hostB:1"]
        for spin in range(2):       # either cursor parity
            sc = StorageClient(self._Meta(peers))
            try:
                for _ in range(spin):
                    sc._leader_for(1, 0)        # advance the cursor
                dead = sc._leader_for(1, 0)     # the host that will fail
                sc.update_leader(1, 0, dead)
                assert sc._leader_for(1, 0) == dead      # cached
                sc.invalidate_leader(1, 0)
                first_retry = sc._leader_for(1, 0)
                assert first_retry != dead, (
                    f"spin={spin}: re-dialed the just-invalidated host")
            finally:
                sc.pool.shutdown(wait=False)

    def test_update_leader_clears_the_skip(self):
        from nebula_tpu.storage.client import StorageClient
        sc = StorageClient(self._Meta(["hostA:1", "hostB:1"]))
        try:
            sc.update_leader(1, 0, "hostA:1")
            sc.invalidate_leader(1, 0)
            sc.update_leader(1, 0, "hostA:1")   # a hint re-elected it
            assert sc._leader_for(1, 0) == "hostA:1"
        finally:
            sc.pool.shutdown(wait=False)

    def test_single_replica_never_starves(self):
        from nebula_tpu.storage.client import StorageClient
        sc = StorageClient(self._Meta(["only:1"]))
        try:
            sc.update_leader(1, 0, "only:1")
            sc.invalidate_leader(1, 0)
            # nothing else to dial: the lone replica must still be
            # returned (skipping it would mean no route at all)
            assert sc._leader_for(1, 0) == "only:1"
        finally:
            sc.pool.shutdown(wait=False)
