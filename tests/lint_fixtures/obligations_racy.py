"""Seeded obligation-tracking violations (lint fixture — see README).

A miniature continuous-serving module carrying the THREE historical
bug classes the pass exists to catch, plus the annotation edge cases:

  * ``go_via_device`` — the PR 7 class: a half-open probe token taken
    from the breaker leaks on an early ``return`` (the decline branch
    and the except-handler settle are the CLEAN shapes around it);
  * ``finish`` — the PR 6 class: a rider marked ``done=True`` under
    the condition with no ``notify_all`` in the locked region (the
    missed wakeup);
  * ``tick`` — the PR 15 class: a lane seat allocated, released only
    on the normal path — ``extract`` raising strands the seat and its
    waiter (no exception-edge discharge);
  * ``seat_forever`` — a seat that is never released at all;
  * ``handoff_unnamed`` — a handed-off annotation with no reason;
  * ``poison_thread`` — ``deadlines.bind`` outside a with-statement.

``handoff_ok`` and ``acquire`` prove the waivers and the canonical
try/except settle pass clean.
"""
import heapq


class TpuDecline(Exception):
    pass


class Stream:
    def go_via_device(self, key):
        why = self.breaker.admit(key)
        if why is not None:
            # decline branch: no token was taken — clean
            raise TpuDecline(why)
        if self.mirror is None:
            return None             # PR 7: the probe token leaks here
        try:
            out = self.device.run(key)
        except Exception as ex:
            self.breaker.record_failure(key, "xla_runtime")
            raise
        self.breaker.record_success(key)
        return out

    def finish(self, rider):
        with self.cond:
            rider.result = 1
            rider.done = True       # PR 6: nobody is notified

    def tick(self, rider):
        lane = self.ledger.alloc()  # PR 15: extract() raising strands
        self.seated[lane] = rider   # the seat — no except/finally
        resolver = self.sess.extract([(lane, rider)])
        self.ledger.release(lane)
        return resolver

    def seat_forever(self, rider):
        lane = self.ledger.alloc()  # never released at all
        self.seated[lane] = rider

    def handoff_unnamed(self):
        # nebulint: obligation=handed-off/
        lane = self.ledger.alloc()
        self.seated[lane] = 1

    def handoff_ok(self):
        # nebulint: obligation=handed-off/retired-with-the-stream
        lane = self.ledger.alloc()
        self.seated[lane] = 1

    def acquire(self, prio, seq):
        # the canonical _PrioritySlots shape: heap entry + slot both
        # settle on the exception edge — clean
        with self.cond:
            heapq.heappush(self._waiters, (prio, seq))
            try:
                while self._used >= self.limit:
                    self.cond.wait()
            except BaseException:
                self._waiters = [w for w in self._waiters
                                 if w[1] != seq]
                heapq.heapify(self._waiters)
                self.cond.notify_all()
                raise
            heapq.heappop(self._waiters)
            self._used += 1

    def poison_thread(self, dl):
        deadlines.bind(dl)          # bound, never unbound
        return self.run()

    def bind_ok(self, dl):
        with deadlines.bind(dl):
            return self.run()
