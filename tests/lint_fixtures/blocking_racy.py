"""Deliberately stalling class — the blocking-under-lock pass's seeded
violation (see README.md): the RPC fan-out is only reachable THROUGH a
helper call, so the lexical lock-discipline check cannot see it — the
exact shape of the PR 6 "rpc_download under the catalog write lock
would stall heartbeats" bug.  DO NOT fix."""
import threading


class RacyCatalog:
    def __init__(self, cm):
        self._lock = threading.Lock()
        self.cm = cm
        self.hosts = []

    def _fan_out(self, method):
        for h in self.hosts:
            self.cm.call(h, method, {})

    def rpc_download(self, req):
        with self._lock:
            # 120 s of peer dials under the write lock
            self._fan_out("download")
            return {"ok": True}
