"""Deliberately racy class — the guard-inference pass's seeded
violation (see README.md; test_lint.py writes this under a kvstore/
path so the scope filter applies).  DO NOT fix."""
import threading


class RacyJournal:
    def __init__(self):
        self._lock = threading.Lock()
        self._side = threading.Lock()
        self._entries = []
        self._seq = 0

    def record(self, entry):
        with self._lock:
            self._entries.append(entry)
            self._seq += 1

    def trim(self, cap):
        with self._lock:
            del self._entries[:-cap]
            self._seq += 0

    def peek(self):
        # the race: a bare read of the majority-guarded list (a
        # concurrent trim can resize it mid-iteration)
        return list(self._entries)

    def renumber(self):
        # the other race: touching guarded state under the WRONG lock
        with self._side:
            self._seq = 0
