"""Seeded protocol-registry violations (lint fixture — see README).

The SITES module of a two-module fixture: tests/test_lint.py pairs it
with a miniature ``common/protocol.py`` registry (PROTOCOL_REASONS /
TYPED_RAISES / STATE_MACHINES) and copies this file to
``<root>/storage/device.py`` so the breaker-cell state machine's
module matcher sees the real module name.  Seeds, in order: a bare
registered literal at a typed ``_shed`` site, an UNKNOWN reason, an
untyped ``AdmissionShed``, a bare literal at a ``reason=`` keyword, a
registered literal leaking into a comparison, and a state-field write
outside the declared transition methods.  ``record_failure`` and
``admit_ok`` prove variable flow and constant references pass clean.
"""


class AdmissionShed(Exception):
    pass


class Breaker:
    def __init__(self):
        self.state = "closed"           # declared writer — clean

    def record_failure(self, key, reason):
        self.state = "open"             # declared writer — clean
        journal(reason=reason)          # variable flow — clean

    def force_open(self):
        self.state = "open"             # write outside the writers


def _shed(key, reason, depth):
    raise AdmissionShed(f"shed at admission ({reason})", reason)


def admit(key, depth):
    if depth > 10:
        _shed(key, "queue_full", depth)      # bare registered literal
    if depth < 0:
        _shed(key, "weird-reason", depth)    # unknown reason
    if depth == 7:
        raise AdmissionShed("untyped")       # no reason argument


def note_absorb(space_id):
    journal(detail=f"space {space_id}",
            reason="part-moved")             # bare literal at reason=


def count_overflow(reason):
    if reason == "delta-overflow":           # literal leaks into a
        return 1                             # comparison
    return 0


def admit_ok(key, depth):
    if depth > 10:
        _shed(key, protocol.SHED_QUEUE_FULL,
              depth)                         # constant ref — clean
