"""Historical soak bugs reconstructed as nebulamc fixture scenarios.

Three concurrency bugs that shipped (and were fixed) in earlier
rounds, rebuilt in their original racy form so the model checker's
regression tests can prove it FINDS each one within a bounded budget
— and that the fixed shapes (the production scenarios plus the fixed
control here) pass the same exploration exhaustively:

* PR 6  — ``RacyPrioritySlots``: the slot-handoff missed wakeup.  A
  waiter popping itself as head while ``_free > 0`` and other waiters
  remain must hand the spare slot on (``notify_all``); without it the
  new head re-waits on a notification that never comes and the queue
  wedges.  nebulamc reports it as a DEADLOCK.
* PR 7  — ``pr7-probe-leak``: a half-open probe that ends without
  exercising the device (deadline fired, semantic decline) must hand
  the token back via ``release_probe``; the original path simply
  returned.  nebulamc reports the undischarged probe-token obligation
  at quiescence (cell left ``probing=True`` — the breaker never
  probes again).
* PR 15 — ``RacyLaneTick``: the stranded lane seat.  When the
  leave-extract fetch fails AFTER the leavers left the seat map, the
  failure path woke their waiters but never released their lanes —
  the ledger leaks a seat per failed cohort until the stream starves.
  The failure here triggers only when a JOIN lands inside the extract
  window, so finding it requires actual interleaving search.
  ``FixedLaneTick`` releases on the failure path too and passes the
  same exploration exhaustively.

Not a pytest module (no ``test_`` prefix) and not part of the
package: loaded by tests/test_mc.py and by the CLI's ``--fixtures``
flag (``python -m nebula_tpu.tools.mc run --fixtures=<this file>``).
"""
from __future__ import annotations

import heapq
from typing import Callable, List, Tuple

from nebula_tpu.common import mc_hooks
from nebula_tpu.tools.mc import McViolation, Scenario


# ------------------------------------------------------------ PR 6 bug
class RacyPrioritySlots:
    """graph/batch_dispatch._PrioritySlots as it shipped before PR 6's
    fix: no hand-on notify after popping ourselves as head."""

    def __init__(self, n: int):
        self._cond = mc_hooks.Condition("fixture.slots")
        self._free = max(1, int(n))
        self._seq = 0
        self._waiters: List[Tuple[int, int]] = []

    def acquire(self, priority: int = 1) -> None:
        with self._cond:
            self._seq += 1
            me = (int(priority), self._seq)
            heapq.heappush(self._waiters, me)
            while self._free <= 0 or self._waiters[0] != me:
                self._cond.wait()
            heapq.heappop(self._waiters)
            self._free -= 1
            # BUG (PR 6): when _free > 0 and _waiters remain, the pop
            # above created a NEW head that nobody will notify again —
            # the fixed class hands the spare slot on with notify_all

    def release(self) -> None:
        with self._cond:
            self._free += 1
            self._cond.notify_all()


def _pr6_prepare() -> dict:
    slots = RacyPrioritySlots(2)
    # two slots "held" at the horizon's start: the releaser threads
    # below model the in-flight batches completing
    slots._free = 0
    return {"slots": slots, "got": []}


def _pr6_bodies(ctx) -> List[Tuple[str, Callable]]:
    slots, got = ctx["slots"], ctx["got"]

    def releaser(tag):
        return lambda: slots.release()

    def acquirer(prio, tag):
        def body():
            slots.acquire(prio)
            got.append(tag)
        return body

    return [("rel-1", releaser(1)), ("rel-2", releaser(2)),
            ("wait-a", acquirer(0, "a")), ("wait-b", acquirer(1, "b"))]


def _pr6_quiesce(ctx) -> None:
    if len(ctx["got"]) != 2:
        raise McViolation(
            f"only {len(ctx['got'])}/2 waiters acquired "
            f"(lost slot handoff)", kind="obligation")


# ------------------------------------------------------------ PR 7 bug
def _pr7_prepare() -> dict:
    from nebula_tpu.common import protocol
    from nebula_tpu.storage.device import DeviceCircuitBreaker
    b = DeviceCircuitBreaker()
    key = (3, "go")
    b.record_failure(key, protocol.DEVFAIL_TRANSFER)
    # zero the open clock so the next admit half-opens under every
    # schedule (tpu_breaker_open_s=0.0 would read as 30.0 — falsy)
    b.reset_space(key[0])
    return {"b": b, "key": key}


def _pr7_bodies(ctx) -> List[Tuple[str, Callable]]:
    b, key = ctx["b"], ctx["key"]

    def prober_leaky():
        tok = b.admit(key)
        if tok is None:
            # BUG (PR 7): the probe ended unclassified (deadline fired
            # before the device ran) and the original code just
            # returned — no release_probe, token gone forever
            return

    def bystander():
        b.admit(key)

    return [("probe", prober_leaky), ("bystander", bystander)]


def _pr7_quiesce(ctx) -> None:
    cell = ctx["b"]._cells.get(ctx["key"])
    if cell is not None and cell.probing:
        raise McViolation(
            "probe-token obligation: half-open probe token never "
            "discharged (cell left probing=True; the breaker will "
            "never probe again)", kind="obligation")


# ----------------------------------------------------------- PR 15 bug
def _lane_tick_prepare() -> dict:
    from nebula_tpu.graph.batch_dispatch import _LaneLedger
    return {"cond": mc_hooks.Condition("fixture.stream"),
            "ledger": _LaneLedger(2), "seated": {}, "served": [],
            "joins": [0]}


def _lane_tick_bodies(ctx, release_on_failure: bool
                      ) -> List[Tuple[str, Callable]]:
    cond, ledger = ctx["cond"], ctx["ledger"]
    seated, served, joins = ctx["seated"], ctx["served"], ctx["joins"]

    def rider(tag: str):
        def body():
            with cond:
                while ledger.free_count() == 0:
                    cond.wait()
                lane = ledger.alloc()
                seated[lane] = tag
                joins[0] += 1
                cond.notify_all()
                while seated.get(lane) == tag:
                    cond.wait()
        return body

    def ticker():
        while len(served) < 2:
            with cond:
                while not seated:
                    cond.wait()
                leavers = list(seated.items())
                for lane, _tag in leavers:
                    del seated[lane]
                joins_before = joins[0]
            # the extract/clear fetch runs OUTSIDE the condition; a
            # join landing in this window moves the frontier under
            # the fetch and fails the cohort
            mc_hooks.mc_yield("fixture.extract", ledger)
            with cond:
                if joins[0] > joins_before:
                    # extract failed: wake the leavers with the error
                    for lane, tag in leavers:
                        served.append(tag)
                        if release_on_failure:
                            ledger.release(lane)
                        # BUG (PR 15, release_on_failure=False): the
                        # leavers left the seat map above, so the
                        # pump-level cleanup can no longer reach them
                        # — their lanes stay allocated forever
                    cond.notify_all()
                else:
                    for lane, tag in leavers:
                        ledger.release(lane)
                        served.append(tag)
                    cond.notify_all()

    return [("rider-a", rider("a")), ("rider-b", rider("b")),
            ("tick", ticker)]


def _lane_tick_quiesce(ctx) -> None:
    ledger = ctx["ledger"]
    if ledger.seated_count() != 0 \
            or ledger.free_count() != ledger.width:
        raise McViolation(
            f"lane-seat obligation: {ledger.seated_count()} seat(s) "
            f"stranded at quiescence "
            f"(free {ledger.free_count()}/{ledger.width})",
            kind="obligation")
    if sorted(ctx["served"]) != ["a", "b"]:
        raise McViolation(f"riders served {ctx['served']!r}",
                          kind="obligation")


FIXTURE_SCENARIOS = {s.name: s for s in (
    Scenario(
        name="pr6-slots-missed-wakeup",
        title="PR 6 regression: slot handoff without hand-on notify",
        prepare=_pr6_prepare, bodies=_pr6_bodies,
        quiesce=_pr6_quiesce,
        covers=("obligation:pipeline-slot",),
        smoke=(2, 400, 30.0), full=(2, 4000, 120.0),
    ),
    Scenario(
        name="pr7-probe-leak",
        title="PR 7 regression: unclassified probe never hands back "
              "its token",
        prepare=_pr7_prepare, bodies=_pr7_bodies,
        quiesce=_pr7_quiesce,
        covers=("obligation:probe-token",),
        flag_overrides={"tpu_breaker_failures": 1},
        smoke=(2, 400, 30.0), full=(2, 4000, 120.0),
    ),
    Scenario(
        name="pr15-lane-strand",
        title="PR 15 regression: failed extract strands the leavers' "
              "lanes",
        prepare=_lane_tick_prepare,
        bodies=lambda ctx: _lane_tick_bodies(ctx, False),
        quiesce=_lane_tick_quiesce,
        covers=("obligation:lane-seat",),
        smoke=(2, 800, 30.0), full=(2, 8000, 120.0),
    ),
    Scenario(
        name="pr15-lane-strand-fixed",
        title="PR 15 control: the failure path releases lanes too",
        prepare=_lane_tick_prepare,
        bodies=lambda ctx: _lane_tick_bodies(ctx, True),
        quiesce=_lane_tick_quiesce,
        covers=("obligation:lane-seat",),
        smoke=(2, 800, 30.0), full=(2, 8000, 120.0),
    ),
)}
