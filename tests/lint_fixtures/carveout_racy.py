"""Seeded carveout-inventory violations (lint fixture — see README).

A miniature tpu/runtime.py: the registry carries one DEAD entry, one
decline site is UNTAGGED, one cites an UNKNOWN reason, and the two
clean sites prove tagged declines and gate returns pass.  The test
copies this file to ``<root>/tpu/runtime.py`` so the pass's scope
matcher sees the real module name.
"""


class TpuDecline(Exception):
    pass


MESH_CARVEOUTS = {
    "cpu-backend": "configuration pins the space to the CPU loop",
    "plan-decline": "the planner cannot reproduce the query on device",
    "ghost-reason": "nothing cites this entry any more",
}


class Runtime:
    def can_run_go(self, space_id):
        if space_id < 0:
            return False        # nebulint: carveout=cpu-backend
        if space_id > 100:
            return False        # untagged gate decline
        return True

    def serve_go(self, space_id):
        if space_id == 7:
            # nebulint: carveout=plan-decline
            raise TpuDecline("device cannot reproduce this query")
        if space_id == 9:
            raise TpuDecline("untagged decline site")
        if space_id == 11:
            # nebulint: carveout=not-a-registered-reason
            raise TpuDecline("tag cites an unknown reason")
        return []
