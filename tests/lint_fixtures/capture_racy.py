"""Deliberately context-dropping class — the context-capture pass's
seeded violation (see README.md): a pool fan-out from span- and
deadline-bound code whose worker rebinds neither, plus a thread-local
deadline consult inside the worker that reads a binding which exited
with the submitting thread.  DO NOT fix."""
from common import tracing
from common import deadline as deadlines


class RacyFanout:
    def __init__(self, pool, cm):
        self.pool = pool
        self.cm = cm

    def collect(self, hosts):
        with tracing.span("storage.collect.pass"):
            dl = deadlines.current()
            futs = [self.pool.submit(self._worker, h, dl) for h in hosts]
            return [f.result() for f in futs]

    def _worker(self, host, dl):
        # consults the submitting thread's binding, which is gone
        timeout = deadlines.remaining_or(10.0)
        return self.cm.call(host, "bulkGet", {}, timeout=timeout)
