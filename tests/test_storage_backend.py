"""TpuStorageBackend — the mirror-backed bulk-read seam
(tpu/backend.py behind StorageService rpc_getBound / rpc_boundStats).

VERDICT round-2 missing #2 / weak #4: the seam existed as dead code;
now it must LIVE — piped GO hops, FETCH waves and pushed stats answer
from the CSR mirror — and return rows bit-identical to the CPU
processors, falling back to them for anything undeclarable.
"""
import numpy as np
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.flags import flags
from nebula_tpu.common.stats import stats


@pytest.fixture(scope="module")
def cluster():
    prev = flags.get("storage_backend")
    flags.set("storage_backend", "tpu")
    # NO graphd-side device runtime: every GO runs the per-hop CPU
    # loop, so every hop's getNeighbors RPC exercises the backend seam
    # (exactly the deployment shape where the seam matters — a graphd
    # that can't ship whole queries still gets mirror-served storage)
    c = LocalCluster(num_storage=1, tpu_backend=False)
    g = c.client()

    def ok(s):
        r = g.execute(s)
        assert r.ok(), f"{s}: {r.error_msg}"
        return r

    ok("CREATE SPACE bk(partition_num=4, replica_factor=1)")
    c.refresh_all()
    ok("USE bk")
    ok("CREATE TAG player(name string, age int)")
    ok("CREATE EDGE follow(degree int)")
    c.refresh_all()
    ok('INSERT VERTEX player(name, age) VALUES '
       '1:("a", 20), 2:("b", 30), 3:("c", 40), 4:("d", 50)')
    ok('INSERT EDGE follow(degree) VALUES 1->2:(10), 2->3:(20), '
       '2->4:(30), 3->4:(40), 4->1:(50)')
    yield c, ok
    flags.set("storage_backend", prev)
    c.stop()


PIPED = [
    "GO FROM 1 OVER follow YIELD follow._dst",
    "GO 2 STEPS FROM 1 OVER follow YIELD follow._dst, follow.degree",
    "GO FROM 2 OVER follow WHERE follow.degree > 15 "
    "YIELD follow._dst, follow.degree",
    "GO FROM 2 OVER follow WHERE $^.player.age > 25 "
    "YIELD follow._dst, $^.player.name",
    "GO FROM 2 OVER follow REVERSELY YIELD follow._dst",
    "GO FROM 1 OVER follow YIELD follow._dst AS id | "
    "GO FROM $-.id OVER follow YIELD follow._dst, $-.id",
]


class TestGetBoundParity:
    @pytest.mark.parametrize("q", PIPED)
    def test_piped_go_rows_match_cpu(self, cluster, q):
        c, ok = cluster
        # pin the per-vertex response format: this test exercises the
        # mirror-backed backend's getBound serving, which flat-eligible
        # final hops would otherwise bypass for the columnar processor
        prev_flat = flags.get("flat_bound_mode")
        flags.set("flat_bound_mode", False)
        try:
            b0 = stats.read_stats("storage.backend_bound.qps.count.3600") \
                or 0
            r = ok(q)
            backend_rows = sorted(map(tuple, r.rows))
            assert (stats.read_stats("storage.backend_bound.qps.count.3600")
                    or 0) > b0, "backend did not serve the getBound hops"
            flags.set("storage_backend", "cpu")
            try:
                r2 = ok(q)
            finally:
                flags.set("storage_backend", "tpu")
            assert backend_rows == sorted(map(tuple, r2.rows)), q
        finally:
            flags.set("flat_bound_mode", prev_flat)

    def test_get_bound_wire_parity_direct(self, cluster):
        """Byte-for-byte response parity backend vs CPU processor on the
        raw RPC (schemas, rowset blobs, vertex data)."""
        c, ok = cluster
        node = c.storage_nodes[0]
        sid = node.meta_client.get_space_id_by_name("bk").value()
        et = c.schema_man.to_edge_type(sid, "follow").value()
        tag = c.schema_man.to_tag_id(sid, "player").value()
        from nebula_tpu.common.keys import id_hash
        nparts = len(node.kv.part_ids(sid))
        parts = {}
        for vid in (1, 2, 3, 4):
            parts.setdefault(id_hash(vid, nparts), []).append(vid)
        req = {"space_id": sid, "parts": parts, "edge_types": [et],
               "vertex_props": [[tag, "age"]],
               "edge_props": {et: ["degree"]}, "filter": None}
        r_backend = node.service.rpc_getBound(dict(req))
        flags.set("storage_backend", "cpu")
        try:
            r_cpu = node.service.rpc_getBound(dict(req))
        finally:
            flags.set("storage_backend", "tpu")

        def norm(resp):
            return (resp["vertex_schema"], resp["edge_schemas"],
                    sorted((v["id"], v["vdata"],
                            sorted(v["edges"].items()))
                           for v in resp["vertices"]))
        assert norm(r_backend) == norm(r_cpu)

    def test_reverse_and_filter_parity(self, cluster):
        c, ok = cluster
        node = c.storage_nodes[0]
        sid = node.meta_client.get_space_id_by_name("bk").value()
        et = c.schema_man.to_edge_type(sid, "follow").value()
        from nebula_tpu.common.keys import id_hash
        from nebula_tpu.filter.expressions import (AliasPropExpr,
                                                   PrimaryExpr,
                                                   RelationalExpr,
                                                   encode_expr)
        filt = encode_expr(RelationalExpr(
            ">", AliasPropExpr("follow", "degree"), PrimaryExpr(15)))
        nparts = len(node.kv.part_ids(sid))
        parts = {}
        for vid in (2, 4):
            parts.setdefault(id_hash(vid, nparts), []).append(vid)
        req = {"space_id": sid, "parts": parts, "edge_types": [-et],
               "vertex_props": [], "edge_props": {-et: ["degree"]},
               "filter": filt}
        r_backend = node.service.rpc_getInBound(
            {**req, "edge_types": [et],
             "edge_props": {et: ["degree"]}})
        flags.set("storage_backend", "cpu")
        try:
            r_cpu = node.service.rpc_getInBound(
                {**req, "edge_types": [et],
                 "edge_props": {et: ["degree"]}})
        finally:
            flags.set("storage_backend", "tpu")

        def norm(resp):
            return sorted((v["id"], sorted(v["edges"].items()))
                          for v in resp["vertices"])
        assert norm(r_backend) == norm(r_cpu)


class TestBoundStatsParity:
    def test_stats_match_cpu(self, cluster):
        c, ok = cluster
        node = c.storage_nodes[0]
        sid = node.meta_client.get_space_id_by_name("bk").value()
        et = c.schema_man.to_edge_type(sid, "follow").value()
        from nebula_tpu.common.keys import id_hash
        nparts = len(node.kv.part_ids(sid))
        parts = {}
        for vid in (2, 3):
            parts.setdefault(id_hash(vid, nparts), []).append(vid)
        req = {"space_id": sid, "parts": parts, "edge_types": [et],
               "stat_props": {"d": [et, "degree"]}}
        s0 = stats.read_stats("storage.backend_stats.qps.count.3600") or 0
        r_backend = node.service.rpc_boundStats(dict(req))
        assert (stats.read_stats("storage.backend_stats.qps.count.3600")
                or 0) > s0
        flags.set("storage_backend", "cpu")
        try:
            r_cpu = node.service.rpc_boundStats(dict(req))
        finally:
            flags.set("storage_backend", "tpu")
        assert r_backend["degree"] == r_cpu["degree"]
        assert r_backend["stats"] == r_cpu["stats"]

    def test_mutation_refreshes_backend_view(self, cluster):
        """Writes must be visible to the next backend read (mirror
        version check) — the bounded-staleness contract."""
        c, ok = cluster
        ok('INSERT EDGE follow(degree) VALUES 1->3:(60)')
        r = ok("GO FROM 4 OVER follow YIELD follow._dst AS id | "
               "GO FROM $-.id OVER follow YIELD follow._dst")
        assert sorted(map(tuple, r.rows)) == [(2,), (3,)]
