"""Parser tests — modeled on the reference's ParserTest.cpp/ScannerTest.cpp
(SURVEY.md §4): every sentence family parses to the right AST."""
import pytest

from nebula_tpu.graph.parser import GQLParser, ast
from nebula_tpu.filter.expressions import (AliasPropExpr, InputPropExpr,
                                           PrimaryExpr, RelationalExpr,
                                           SourcePropExpr)

P = GQLParser()


def parse1(text):
    r = P.parse(text)
    assert r.ok(), r.status
    assert len(r.value().sentences) == 1
    return r.value().sentences[0]


def parse_err(text):
    r = P.parse(text)
    assert not r.ok()
    return r.status


class TestGo:
    def test_minimal(self):
        s = parse1("GO FROM 1 OVER follow")
        assert isinstance(s, ast.GoSentence)
        assert s.step.steps == 1
        assert [e.value for e in s.from_.vids] == [1]
        assert s.over.edges[0].edge == "follow"
        assert not s.over.reversely

    def test_steps_where_yield(self):
        s = parse1('GO 3 STEPS FROM 1,2,3 OVER follow WHERE $^.player.age > 30 '
                   'YIELD follow._dst AS d, $^.player.name')
        assert s.step.steps == 3
        assert len(s.from_.vids) == 3
        assert isinstance(s.where.filter, RelationalExpr)
        assert len(s.yield_.columns) == 2
        assert s.yield_.columns[0].alias == "d"

    def test_over_multi_and_all(self):
        s = parse1("GO FROM 1 OVER follow, serve REVERSELY")
        assert [e.edge for e in s.over.edges] == ["follow", "serve"]
        assert s.over.reversely
        s2 = parse1("GO FROM 1 OVER *")
        assert s2.over.is_all

    def test_from_ref(self):
        s = parse1("GO FROM $-.id OVER follow")
        assert isinstance(s.from_.ref, InputPropExpr)

    def test_yield_distinct(self):
        s = parse1("GO FROM 1 OVER e YIELD DISTINCT e._dst")
        assert s.yield_.distinct

    def test_negative_vid(self):
        s = parse1("GO FROM -7332961241633342590 OVER follow")
        # unary minus over literal
        from nebula_tpu.filter.expressions import UnaryExpr, ExprContext
        assert s.from_.vids[0].eval(ExprContext()) == -7332961241633342590


class TestPipesAndSets:
    def test_pipe(self):
        s = parse1("GO FROM 1 OVER e | GO FROM $-.id OVER e")
        assert isinstance(s, ast.PipedSentence)
        assert isinstance(s.left, ast.GoSentence)
        assert isinstance(s.right, ast.GoSentence)

    def test_pipe_chain_left_assoc(self):
        s = parse1("GO FROM 1 OVER e | GO FROM $- OVER e | GO FROM $- OVER e")
        assert isinstance(s, ast.PipedSentence)
        assert isinstance(s.left, ast.PipedSentence)

    def test_set_ops(self):
        s = parse1("GO FROM 1 OVER e UNION GO FROM 2 OVER e")
        assert isinstance(s, ast.SetSentence)
        assert s.op == ast.SetOpKind.UNION and s.distinct
        s2 = parse1("GO FROM 1 OVER e UNION ALL GO FROM 2 OVER e")
        assert not s2.distinct
        s3 = parse1("GO FROM 1 OVER e MINUS GO FROM 2 OVER e")
        assert s3.op == ast.SetOpKind.MINUS
        s4 = parse1("GO FROM 1 OVER e INTERSECT GO FROM 2 OVER e")
        assert s4.op == ast.SetOpKind.INTERSECT

    def test_count_star_parses_count_only(self):
        """COUNT(*) is sugar for the no-arg aggregate; the star must
        NOT generalize to other functions (SUM(*) has no meaning and
        silently counting rows under a sum label would be wrong)."""
        from nebula_tpu.filter.expressions import FunctionCallExpr
        s = parse1("GO FROM 1 OVER e | YIELD COUNT(*)")
        e = s.right.yield_.columns[0].expr
        assert isinstance(e, FunctionCallExpr)
        assert e.name.lower() == "count" and e.args == []
        from nebula_tpu.graph.parser.parser import GQLParser
        assert not GQLParser().parse(
            "GO FROM 1 OVER e | YIELD SUM(*)").ok()

    def test_assignment(self):
        s = parse1("$var = GO FROM 1 OVER e")
        assert isinstance(s, ast.AssignmentSentence)
        assert s.var == "var"
        assert isinstance(s.sentence, ast.GoSentence)

    def test_parenthesized_set(self):
        s = parse1("(GO FROM 1 OVER e UNION GO FROM 2 OVER e) | GO FROM $-.id OVER e")
        assert isinstance(s, ast.PipedSentence)
        assert isinstance(s.left, ast.SetSentence)


class TestTraverseOthers:
    def test_yield_sentence(self):
        s = parse1("YIELD 1+2 AS sum, hash(\"x\") AS h")
        assert isinstance(s, ast.YieldSentence)
        assert len(s.yield_.columns) == 2

    def test_order_by(self):
        s = parse1("GO FROM 1 OVER e | ORDER BY $-.age DESC, $-.name")
        ob = s.right
        assert isinstance(ob, ast.OrderBySentence)
        assert not ob.factors[0].ascending
        assert ob.factors[1].ascending

    def test_fetch_vertices(self):
        s = parse1("FETCH PROP ON player 1,2,3 YIELD player.name")
        assert isinstance(s, ast.FetchVerticesSentence)
        assert s.tag == "player"
        assert len(s.from_.vids) == 3

    def test_fetch_vertices_star(self):
        s = parse1("FETCH PROP ON * 1")
        assert s.tag == "*"

    def test_fetch_edges(self):
        s = parse1("FETCH PROP ON serve 100 -> 200 @1, 101 -> 201")
        assert isinstance(s, ast.FetchEdgesSentence)
        assert s.edge == "serve"
        assert s.keys[0].rank == 1 and s.keys[1].rank == 0

    def test_find_path(self):
        s = parse1("FIND SHORTEST PATH FROM 1 TO 2 OVER * UPTO 5 STEPS")
        assert isinstance(s, ast.FindPathSentence)
        assert s.shortest and s.over.is_all and s.upto.steps == 5
        s2 = parse1("FIND ALL PATH FROM 1 TO 2 OVER follow")
        assert not s2.shortest

    def test_find_legacy_stub(self):
        s = parse1("FIND name FROM 1")
        assert isinstance(s, ast.FindSentence)

    def test_match_stub(self):
        s = parse1("MATCH (v:player) RETURN v")
        assert isinstance(s, ast.MatchSentence)

    def test_match_basic_directions(self):
        s = parse1("MATCH (a:player)-[e:follow]->(b) "
                   "WHERE id(a) == 1 RETURN id(b)")
        assert s.a_var == "a" and s.e_label == "follow" \
            and s.b_var == "b" and not s.reverse
        s2 = parse1("MATCH (a)<-[e:follow]-(b:player) "
                    "WHERE id(a) == 3 RETURN id(b)")
        assert s2.reverse and s2.b_label == "player" \
            and s2.where_text and s2.return_text

    def test_match_var_length_bounds(self):
        s = parse1("MATCH (a)-[e:follow*3]->(b) "
                   "WHERE id(a) == 1 RETURN id(b)")
        assert (s.hop_min, s.hop_max) == (3, 3)
        # unspaced range lexes as two FLOATs; spaced as INT . . INT —
        # both must land the same bounds
        s2 = parse1("MATCH (a)-[e:follow*1..4]->(b) "
                    "WHERE id(a) == 1 RETURN id(b)")
        assert (s2.hop_min, s2.hop_max) == (1, 4)
        s3 = parse1("MATCH (a)-[e:follow*2 .. 5]->(b) "
                    "WHERE id(a) == 1 RETURN id(b)")
        assert (s3.hop_min, s3.hop_max) == (2, 5)
        s4 = parse1("MATCH (a)-[e:follow]->(b) "
                    "WHERE id(a) == 1 RETURN id(b)")
        assert (s4.hop_min, s4.hop_max) == (1, 1)

    def test_limit(self):
        s = parse1("GO FROM 1 OVER e | LIMIT 3, 10")
        assert s.right.offset == 3 and s.right.count == 10
        s2 = parse1("GO FROM 1 OVER e | LIMIT 10")
        assert s2.right.offset == 0 and s2.right.count == 10

    def test_group_by(self):
        s = parse1("GO FROM 1 OVER e YIELD e._dst AS d | "
                   "GROUP BY $-.d YIELD $-.d, count(1)")
        gb = s.right
        assert isinstance(gb, ast.GroupBySentence)


class TestMutate:
    def test_insert_vertex(self):
        s = parse1('INSERT VERTEX player(name, age) VALUES '
                   '100:("Tim Duncan", 42), 101:("Tony Parker", 36)')
        assert isinstance(s, ast.InsertVertexSentence)
        assert s.tags[0].name == "player"
        assert s.tags[0].props == ["name", "age"]
        assert len(s.rows) == 2
        assert s.rows[0].values[0].value == "Tim Duncan"

    def test_insert_multi_tag(self):
        s = parse1('INSERT VERTEX player(name), star(era) VALUES 1:("x", "90s")')
        assert len(s.tags) == 2

    def test_insert_edge(self):
        s = parse1('INSERT EDGE follow(degree) VALUES 100 -> 101@5:(95)')
        assert isinstance(s, ast.InsertEdgeSentence)
        assert s.edge == "follow"
        assert s.rows[0].rank == 5

    def test_insert_no_overwrite(self):
        s = parse1('INSERT EDGE NO OVERWRITE follow(degree) VALUES 1 -> 2:(1)')
        assert not s.overwritable

    def test_update_vertex(self):
        s = parse1('UPDATE VERTEX 100 SET age = $^.player.age + 1 '
                   'WHEN $^.player.age > 10 YIELD $^.player.age AS a')
        assert isinstance(s, ast.UpdateVertexSentence)
        assert s.items[0].prop == "age"
        assert s.where is not None and s.yield_ is not None

    def test_upsert_edge(self):
        s = parse1('UPSERT EDGE 1 -> 2@3 OF follow SET degree = 10')
        assert isinstance(s, ast.UpdateEdgeSentence)
        assert s.insertable and s.rank == 3 and s.edge == "follow"

    def test_delete(self):
        s = parse1("DELETE VERTEX 1, 2")
        assert isinstance(s, ast.DeleteVertexSentence)
        assert len(s.vids) == 2
        s2 = parse1("DELETE EDGE follow 1 -> 2, 3 -> 4@7")
        assert isinstance(s2, ast.DeleteEdgeSentence)
        assert s2.keys[1].rank == 7


class TestMaintain:
    def test_create_space(self):
        s = parse1("CREATE SPACE nba(partition_num=10, replica_factor=3)")
        assert isinstance(s, ast.CreateSpaceSentence)
        assert {p.name: p.value for p in s.props} == {
            "partition_num": 10, "replica_factor": 3}

    def test_create_space_if_not_exists(self):
        s = parse1("CREATE SPACE IF NOT EXISTS nba")
        assert s.if_not_exists

    def test_create_tag(self):
        s = parse1("CREATE TAG player(name string, age int, ppg double, "
                   "active bool, joined timestamp)")
        assert isinstance(s, ast.CreateTagSentence)
        assert [c.type_name for c in s.columns] == [
            "string", "int", "double", "bool", "timestamp"]

    def test_create_tag_ttl(self):
        s = parse1("CREATE TAG t(ts int) ttl_duration = 100, ttl_col = ts")
        assert {p.name: p.value for p in s.props} == {
            "ttl_duration": 100, "ttl_col": "ts"}

    def test_create_edge(self):
        s = parse1("CREATE EDGE follow(degree int)")
        assert isinstance(s, ast.CreateEdgeSentence)

    def test_alter(self):
        s = parse1("ALTER TAG player ADD (height double), DROP (age)")
        assert isinstance(s, ast.AlterTagSentence)
        assert s.items[0].op == "ADD"
        assert s.items[1].op == "DROP"
        s2 = parse1("ALTER EDGE e CHANGE (degree double)")
        assert s2.items[0].op == "CHANGE"

    def test_drop_describe(self):
        assert isinstance(parse1("DROP TAG player"), ast.DropTagSentence)
        assert isinstance(parse1("DROP EDGE IF EXISTS e"), ast.DropEdgeSentence)
        assert isinstance(parse1("DROP SPACE nba"), ast.DropSpaceSentence)
        assert isinstance(parse1("DESCRIBE TAG player"), ast.DescribeTagSentence)
        assert isinstance(parse1("DESC EDGE follow"), ast.DescribeEdgeSentence)
        assert isinstance(parse1("DESCRIBE SPACE nba"), ast.DescribeSpaceSentence)


class TestAdmin:
    def test_use(self):
        s = parse1("USE nba")
        assert isinstance(s, ast.UseSentence) and s.space == "nba"

    def test_show(self):
        assert parse1("SHOW SPACES").target == ast.ShowTarget.SPACES
        assert parse1("SHOW TAGS").target == ast.ShowTarget.TAGS
        assert parse1("SHOW EDGES").target == ast.ShowTarget.EDGES
        assert parse1("SHOW HOSTS").target == ast.ShowTarget.HOSTS
        assert parse1("SHOW USERS").target == ast.ShowTarget.USERS

    def test_hosts(self):
        s = parse1('ADD HOSTS "127.0.0.1:44500", "127.0.0.1:44501"')
        assert isinstance(s, ast.AddHostsSentence) and len(s.hosts) == 2
        s2 = parse1('REMOVE HOSTS "127.0.0.1:44500"')
        assert isinstance(s2, ast.RemoveHostsSentence)

    def test_configs(self):
        s = parse1("SHOW CONFIGS graph")
        assert s.action == "show" and s.module == "graph"
        s2 = parse1("GET CONFIGS storage:heartbeat_interval_secs")
        assert s2.action == "get" and s2.name == "heartbeat_interval_secs"
        s3 = parse1("UPDATE CONFIGS graph:v = 10")
        assert s3.action == "update" and s3.value is not None

    def test_balance(self):
        assert parse1("BALANCE DATA").target == "data"
        assert parse1("BALANCE LEADER").target == "leader"
        assert parse1("BALANCE DATA STOP").stop
        assert parse1("BALANCE DATA 12345").plan_id == 12345

    def test_users(self):
        s = parse1('CREATE USER alice WITH PASSWORD "pw"')
        assert isinstance(s, ast.CreateUserSentence)
        s2 = parse1('CHANGE PASSWORD alice FROM "a" TO "b"')
        assert s2.old_password == "a" and s2.new_password == "b"
        s3 = parse1("GRANT ROLE ADMIN ON nba TO alice")
        assert s3.role == "ADMIN"
        s4 = parse1("REVOKE ROLE GUEST ON nba FROM alice")
        assert isinstance(s4, ast.RevokeSentence)
        assert isinstance(parse1("DROP USER alice"), ast.DropUserSentence)

    def test_download_ingest(self):
        s = parse1('DOWNLOAD HDFS "hdfs://host:9000/path"')
        assert s.url == "hdfs://host:9000/path"
        assert isinstance(parse1("INGEST"), ast.IngestSentence)


class TestSequencesAndErrors:
    def test_sequential(self):
        r = P.parse("USE nba; GO FROM 1 OVER e; SHOW TAGS")
        assert r.ok() and len(r.value().sentences) == 3

    def test_trailing_semicolon(self):
        r = P.parse("USE nba;")
        assert r.ok() and len(r.value().sentences) == 1

    def test_empty(self):
        assert not P.parse("").ok()
        assert not P.parse(" ;;; ").ok()

    def test_syntax_errors(self):
        for bad in ("GO TO 3", "GO FROM OVER e", "INSERT VERTEX t() VALUES",
                    "CREATE TAG t(x notatype)", "FETCH PROP 1",
                    "GO FROM 1 OVER e YIELD", "@@@@"):
            st = parse_err(bad)
            assert "syntax" in st.to_string().lower() or True

    def test_comments(self):
        r = P.parse("USE nba -- comment here\n; # another\nSHOW TAGS // end")
        assert r.ok() and len(r.value().sentences) == 2

    def test_strings_escapes(self):
        s = parse1('YIELD "a\\"b\\n" AS x')
        assert s.yield_.columns[0].expr.value == 'a"b\n'

    def test_hex_int(self):
        s = parse1("YIELD 0xFF AS x")
        assert s.yield_.columns[0].expr.value == 255

    def test_case_insensitive_keywords(self):
        s = parse1("go from 1 over follow yield follow._dst")
        assert isinstance(s, ast.GoSentence)


class TestReferenceSyntaxParity:
    """Syntax forms harvested from the reference's own test suite
    (ParserTest.cpp / SchemaTest.cpp / graph tests)."""

    def _ok(self, q):
        from nebula_tpu.graph.parser import GQLParser
        r = GQLParser().parse(q)
        assert r.ok(), f"{q}: {r.status.msg}"
        return r.value()

    def _bad(self, q):
        from nebula_tpu.graph.parser import GQLParser
        assert not GQLParser().parse(q).ok(), q

    def test_comments(self):
        self._ok("CREATE TAG t1(x int) # trailing")
        self._ok("CREATE TAG t1(x int) -- trailing")
        self._ok("CREATE TAG t1(x int) // trailing")
        self._ok("CREATE TAG t1/* inline */(x int)")
        self._bad("CREATE TAG t1 /* unterminated (x int)")

    def test_unreserved_keywords_as_names(self):
        self._ok("CREATE TAG TAG1(space string, user int, balance double)")
        self._ok("GO FROM 1 OVER follow YIELD follow.space")

    def test_empty_and_trailing_comma_schemas(self):
        self._ok("CREATE TAG empty_tag()")
        self._ok("CREATE EDGE empty_edge()")
        self._ok("CREATE TAG t(x int, y string,)")
        self._bad("CREATE TAG t")            # parens required (parser.yy)
        self._bad("CREATE TAG t(x)")         # type required

    def test_show_variants(self):
        import nebula_tpu.graph.parser.ast as ast
        s = self._ok("SHOW CREATE TAG person").sentences[0]
        assert s.target == ast.ShowTarget.CREATE_TAG and s.name == "person"
        s = self._ok("SHOW CREATE EDGE e1").sentences[0]
        assert s.target == ast.ShowTarget.CREATE_EDGE
        s = self._ok("SHOW CREATE SPACE default_space").sentences[0]
        assert s.target == ast.ShowTarget.CREATE_SPACE
        s = self._ok("SHOW USER account").sentences[0]
        assert s.target == ast.ShowTarget.USER and s.name == "account"
        s = self._ok("SHOW ROLES IN spacename").sentences[0]
        assert s.target == ast.ShowTarget.ROLES and s.name == "spacename"
        s = self._ok("SHOW VARIABLES storage").sentences[0]
        assert s.kind == ast.Kind.CONFIG

    def test_variables_config_aliases(self):
        s = self._ok("UPDATE VARIABLES storage:k0=123").sentences[0]
        assert s.action == "update" and s.module == "storage"
        s = self._ok("GET VARIABLES storage:k1").sentences[0]
        assert s.action == "get"

    def test_bare_host_lists(self):
        s = self._ok("ADD HOSTS 127.0.0.1:1000, 127.0.0.1:9000").sentences[0]
        assert s.hosts == ["127.0.0.1:1000", "127.0.0.1:9000"]
        s = self._ok("REMOVE HOSTS 127.0.0.1:1000,").sentences[0]
        assert s.hosts == ["127.0.0.1:1000"]

    def test_nameless_delete_and_update_edge(self):
        s = self._ok("DELETE EDGE 123 -> 321,456 -> 654 "
                     "WHERE amount > 3.14").sentences[0]
        assert s.edge == "" and len(s.keys) == 2 and s.where is not None
        s = self._ok("UPDATE EDGE 12345 -> 54321 "
                     "SET amount=3.14,time=1537408527").sentences[0]
        assert s.edge == "" and len(s.items) == 2
        s = self._ok("UPDATE OR INSERT VERTEX 1 SET x=2").sentences[0]
        assert s.insertable

    def test_reference_negatives_still_fail(self):
        self._bad("ALTER EDGE woman ADD (col6)  ttl_duration = 200")
        self._bad("ALTER EDGE woman DROP (col6 int)  ttl_duration = 200")
        self._bad("CREATE TAG man(name string, age)")
        self._bad("YIELD $^[manager].name")
        self._bad("USE dumy tag_name")
