"""The serving control plane's eyes (docs/observability.md "The live
query plane" / "SLO burn rates"):

  * Live query registry unit surface — register/snapshot/overflow,
    kill marks, the /queries webservice endpoint.
  * SHOW QUERIES / KILL QUERY end-to-end: a barrier-held continuous
    rider is listed mid-flight with its lane seat and hop index, the
    kill ends it typed (E_KILLED) within one hop boundary, the lane
    frees, and the continuous ledger stays balanced.
  * Slow continuous riders land in the slow-query log WITH their seat
    markers (lane, joined_tick, hops, typed ending).
  * SLO burn rates: the multi-window engine fires/self-clears
    deterministically, and the chaos leg — an injected storage-latency
    fault pushes the go-class burn over the fast pair, slo.burn_alert
    journals, graph.slo.* gauges export, the graphd /healthz slo check
    flips 503, and healing self-clears it.
  * Per-replica load briefs: dispatcher → graph.load.* gauges →
    role=graph heartbeat → metad listDeviceBriefs graph_briefs.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common import slo
from nebula_tpu.common.events import journal
from nebula_tpu.common.flags import flags
from nebula_tpu.common.stats import stats
from nebula_tpu.common.status import ErrorCode
from nebula_tpu.common.tracing import slow_log
from nebula_tpu.graph.query_registry import (KilledError, registry)
from nebula_tpu.webservice import WebService


def _stat(name, win=600):
    return stats.read_stats(f"{name}.sum.{win}") or 0.0


# ===================================================== registry unit
class TestQueryRegistry:
    def test_register_snapshot_unregister(self):
        qid = registry.register("GO FROM 1 OVER e", session=7,
                                user="u", cls="go", space="s",
                                mode="continuous")
        assert qid is not None
        rows = {r["id"]: r for r in registry.snapshot()}
        assert qid in rows
        r = rows[qid]
        assert r["stmt"] == "GO FROM 1 OVER e"
        assert r["class"] == "go" and r["space"] == "s"
        assert r["mode"] == "continuous" and r["session"] == 7
        assert r["lane"] == -1          # never seated
        registry.unregister(qid)
        assert qid not in {x["id"] for x in registry.snapshot()}

    def test_ids_are_process_tagged_and_monotonic(self):
        a = registry.register("a")
        b = registry.register("b")
        try:
            assert b > a
            # same process tag (top bits), distinct sequence
            assert (a >> 40) == (b >> 40)
        finally:
            registry.unregister(a)
            registry.unregister(b)

    def test_overflow_cap_statement_still_runs(self):
        saved = flags.get("query_registry_size")
        flags.set("query_registry_size", 2)
        qids = []
        try:
            before = _stat("graph.query_registry.overflow")
            qids = [registry.register(f"q{i}") for i in range(3)]
            assert qids[0] is not None and qids[1] is not None
            assert qids[2] is None      # over cap: untracked, not failed
            assert _stat("graph.query_registry.overflow") > before
            # unregister of the untracked statement is a no-op
            registry.unregister(None)
        finally:
            flags.set("query_registry_size", saved)
            for q in qids:
                registry.unregister(q)

    def test_kill_marks_and_check_raises_typed(self):
        qid = registry.register("victim")
        try:
            assert registry.kill(qid) is True
            assert registry.is_killed(qid)
            with pytest.raises(KilledError):
                registry.check_killed(qid)
        finally:
            registry.unregister(qid)
        # unknown / finished ids are a miss, not an error (the metad
        # fan-out ORs per-replica answers)
        assert registry.kill(qid) is False
        assert registry.kill(123456789) is False
        registry.check_killed(None)     # untracked: never raises

    def test_seat_markers_only_after_a_seat(self):
        qid = registry.register("never seated")
        try:
            assert registry.seat_markers(qid) is None
            registry.note_seat(qid, 5, 17)
            registry.note_hop(qid, 2)
            m = registry.seat_markers(qid)
            assert m == {"lane": 5, "joined_tick": 17, "hops": 2,
                         "ending": None}
        finally:
            registry.unregister(qid)

    def test_queries_endpoint_serves_registry(self):
        ws = WebService("nebula-graphd", host="127.0.0.1").start()
        qid = registry.register("SHOW ME", user="ops")
        try:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{ws.port}/queries", timeout=30)
            body = json.load(resp)
            assert resp.status == 200
            mine = [q for q in body["queries"] if q["id"] == qid]
            assert mine and mine[0]["stmt"] == "SHOW ME"
            assert mine[0]["user"] == "ops"
        finally:
            registry.unregister(qid)
            ws.stop()


# ===================================================== slo engine unit
def _note_at(cls, ok, sec, n=1):
    """slo.note shaped into a chosen epoch second: unit tests stamp a
    FAR-FUTURE ring region so the real-time rings the e2e chaos leg
    (and every healthz probe in this process) reads stay clean."""
    for _ in range(n):
        stats._stats[f"graph.slo.{cls}.served"].add(1.0, now=sec)
        if not ok:
            stats._stats[f"graph.slo.{cls}.errors"].add(1.0, now=sec)


class TestSloEngine:
    # distinct far-future regions per test — ring aliasing is safe
    # (stamps are exact-second checked) but shared regions are not
    _BASE = int(time.time()) + 500_000

    def setup_method(self):
        slo.slo_engine.clear_for_tests()

    def teardown_method(self):
        slo.slo_engine.clear_for_tests()

    def test_note_ignores_undeclared_class(self):
        slo.note("no_such_class", 1.0, True)      # must not register

    def test_fires_on_both_fast_windows_then_self_clears(self):
        # availability burn on the admin class: errors/served over the
        # 0.01 budget — well past the fast threshold on BOTH windows
        base = self._BASE
        _note_at("admin", False, base, n=5)
        rows = slo.slo_engine.evaluate(now=base)
        mine = [r for r in rows if r["class"] == "admin"
                and r["objective"] == "availability"]
        assert mine and mine[0]["firing"] == "fast"
        ev = [e for e in journal.dump(200)
              if e["kind"] == "slo.burn_alert"][0]
        assert ev["state"] == "firing" and ev["slo_class"] == "admin"
        # past the fast pair the slow pair (600/3600 s) still sees the
        # errors: the alert degrades fast -> slow, not to silence
        rows = slo.slo_engine.evaluate(now=base + 90)
        mine = [r for r in rows if r["class"] == "admin"
                and r["objective"] == "availability"]
        assert mine and mine[0]["firing"] == "slow"
        # and once every window has aged out it SELF-CLEARS
        rows = slo.slo_engine.evaluate(now=base + 4000)
        mine = [r for r in rows if r["class"] == "admin"
                and r["objective"] == "availability"]
        assert mine and mine[0]["firing"] is None
        ev = [e for e in journal.dump(200)
              if e["kind"] == "slo.burn_alert"][0]
        assert ev["state"] == "resolved"

    def test_one_window_spike_does_not_fire(self):
        # the multi-window guard: at base+10 the errors are outside
        # the 5 s window but inside 60 s — one window alone must not
        # page
        base = self._BASE + 50_000
        _note_at("admin", False, base, n=5)
        rows = slo.slo_engine.evaluate(now=base + 10)
        mine = [r for r in rows if r["class"] == "admin"
                and r["objective"] == "availability"]
        assert mine and mine[0]["firing"] != "fast"

    def test_evaluate_memoizes_per_second(self):
        sec = int(time.time()) + 7200
        r1 = slo.slo_engine.evaluate(now=sec)
        r2 = slo.slo_engine.evaluate(now=sec + 0.4)
        assert r1 is r2                 # same epoch second: cached rows

    def test_disabled_flag_short_circuits(self):
        saved = flags.get("slo_enabled")
        flags.set("slo_enabled", False)
        try:
            assert slo.slo_engine.evaluate() == []
            ok, detail = slo.slo_engine.health()
            assert ok
        finally:
            flags.set("slo_enabled", saved)

    def test_stats_rows_shape(self):
        rows = slo.slo_engine.stats_rows()
        # two objectives per declared class, 4 burn columns + state
        assert len(rows) == 2 * len(slo.SLO_OBJECTIVES)
        for r in rows:
            assert r[0].startswith("slo.") and len(r) == 6
            assert r[5] in ("ok", "fast", "slow")


# ===================================================== cluster fixture
def _boot(seed=13, n=40, m=160):
    c = LocalCluster(num_storage=1, tpu_backend=True)
    g = c.client()

    def ok(stmt):
        r = g.execute(stmt)
        assert r.ok(), f"{stmt}: {r.error_msg}"
        return r

    ok("CREATE SPACE s(partition_num=3, replica_factor=1)")
    c.refresh_all()
    ok("USE s")
    ok("CREATE EDGE e(w int)")
    c.refresh_all()
    rng = np.random.default_rng(seed)
    src = rng.integers(1, n + 1, m)
    dst = rng.integers(1, n + 1, m)
    pairs = sorted({(int(a), int(b)) for a, b in zip(src, dst)
                    if a != b})
    vals = ", ".join(f"{a} -> {b}:({(a * 31 + b) % 97})"
                     for a, b in pairs)
    ok(f"INSERT EDGE e(w) VALUES {vals}")
    return c, g, ok


@pytest.fixture(scope="module")
def qp():
    c, g, ok = _boot()
    yield c, g, ok
    c.stop()


# ===================================================== SHOW / KILL e2e
class TestShowKillE2E:
    def test_show_queries_statement_shape(self, qp):
        c, g, ok = qp
        r = ok("SHOW QUERIES")
        assert r.column_names == ["Id", "Session", "User", "Statement",
                               "Class", "Space", "Mode", "Phase",
                               "Hop", "Lane", "Elapsed(us)",
                               "DeadlineLeft(ms)"]
        # SHOW QUERIES always sees at least itself, registered
        assert any("SHOW QUERIES" in row[3] for row in r.rows)

    def test_kill_unknown_id_is_typed_miss(self, qp):
        c, g, ok = qp
        r = g.execute("KILL QUERY 999999999999")
        assert not r.ok()
        assert "not found" in (r.error_msg or "").lower()

    def test_kill_midflight_seated_rider(self, qp):
        """The acceptance round-trip: a barrier-held continuous rider
        shows in SHOW QUERIES with its lane seat and hop index; KILL
        QUERY ends it typed within one hop boundary; the lane frees
        and the continuous ledger balances."""
        c, g, ok = qp
        ok("GO 2 STEPS FROM 1 OVER e")          # stream anchored
        d = c.tpu_runtime.dispatcher
        st = next(iter(d.continuous.streams()))
        st.tick_delay_s = 0.05
        # ledger snapshot over the full ring: the balance check below
        # must be a DELTA — absolute counters carry every join the
        # rest of the suite made in the shared windows
        j0 = _stat("graph.continuous.joins", 3600)
        l0 = _stat("graph.continuous.leaves", 3600)
        e0 = _stat("graph.continuous.evictions", 3600)
        res = []
        try:
            def rider():
                g2 = c.client()
                g2.execute("USE s")
                res.append(g2.execute(
                    "GO 6 STEPS FROM 1 OVER e YIELD e._dst"))

            t = threading.Thread(target=rider)
            t.start()
            # poll until the rider shows up seated — a fixed sleep
            # flakes on a loaded box (ticks and the rider's admission
            # stretch together, so waiting longer stays mid-flight)
            row = None
            poll_end = time.monotonic() + 8.0
            while time.monotonic() < poll_end:
                rows = ok("SHOW QUERIES").rows
                mine = [r for r in rows
                        if "6 STEPS" in r[3] and r[9] >= 0]
                if mine:
                    row = mine[0]
                    break
                time.sleep(0.02)
            assert row is not None, "rider never seated"
            qid, lane, hop = row[0], row[9], row[8]
            assert row[4] == "go" and row[6] == "continuous"
            assert lane >= 0, "rider not seated with a lane"
            assert hop >= 0
            # the metad fan-out sees the same rider, host-stamped
            mq = c.meta_service.rpc_showQueries({})
            fan = [q for q in mq["queries"] if q["id"] == qid]
            assert fan and fan[0]["host"]
            t0 = time.perf_counter()
            rk = ok(f"KILL QUERY {qid}")
            assert rk.rows == [[qid, True]]
            t.join(timeout=10)
            wall = time.perf_counter() - t0
        finally:
            st.tick_delay_s = 0.0
        assert res, "rider thread never finished"
        assert res[0].error_code == ErrorCode.E_KILLED, res[0].error_msg
        assert "KILL QUERY" in res[0].error_msg
        # "within one hop boundary": well under the 6-hop flight time
        # (generous bound — the typed E_KILLED above is the real
        # proof; this only guards against waiting out a whole flight)
        assert wall < 5.0, wall
        # journaled, typed
        kinds = [e["kind"] for e in journal.dump(200)]
        assert "query.killed" in kinds
        # lane freed: the seat map drains to zero
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if d.continuous.seat_counts() == (0, 0):
                break
            time.sleep(0.05)
        assert d.continuous.seat_counts() == (0, 0), "lane leak"
        # ledger balance: every join left or was evicted — kills ride
        # the eviction leg, so nothing leaks
        joins = _stat("graph.continuous.joins", 3600) - j0
        leaves = _stat("graph.continuous.leaves", 3600) - l0
        evics = _stat("graph.continuous.evictions", 3600) - e0
        assert joins > 0
        assert joins == leaves + evics, (joins, leaves, evics)
        # the kill fan-out through metad answers a live id too
        assert c.meta_service.rpc_killQuery({"qid": 1}) == \
            {"killed": False}

    def test_registry_empty_between_statements(self, qp):
        c, g, ok = qp
        # every statement unregisters on the way out — nothing lingers
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if not registry.snapshot():
                break
            time.sleep(0.05)
        assert registry.snapshot() == []
        fin = _stat("graph.query_registry.finished") \
            + _stat("graph.query_registry.killed")
        assert _stat("graph.query_registry.registered") <= fin + 1


# ===================================================== slow-log seats
class TestSlowRiderSeatMarkers:
    def test_slow_continuous_rider_lands_with_seat_markers(self, qp):
        c, g, ok = qp
        saved = flags.get("slow_query_threshold_ms")
        flags.set("slow_query_threshold_ms", 1)
        d = c.tpu_runtime.dispatcher
        ok("GO 2 STEPS FROM 1 OVER e")
        st = next(iter(d.continuous.streams()))
        st.tick_delay_s = 0.05                  # deliberately slowed
        try:
            ok("GO 4 STEPS FROM 2 OVER e YIELD e._dst")
        finally:
            st.tick_delay_s = 0.0
            flags.set("slow_query_threshold_ms", saved)
        entries = [e for e in slow_log.dump()
                   if "4 STEPS FROM 2" in e["stmt"]]
        assert entries, slow_log.dump()
        e = entries[0]
        assert e["lane"] >= 0
        assert e["joined_tick"] >= 0
        assert e["hops"] >= 1
        assert e["ending"] == "left-batch"      # finished, not evicted
        # windowed/unseated statements carry no seat keys at all
        plain = [x for x in slow_log.dump() if "SHOW" in x["stmt"]]
        for x in plain:
            assert "lane" not in x


# ===================================================== slo chaos e2e
@pytest.fixture(scope="module")
def chaos():
    """CPU-path cluster (GO -> storaged getBound RPC) so the wire
    injector can add real storage latency, plus a graphd-shaped ws
    wired like daemons/graphd.py."""
    c = LocalCluster(num_storage=1)
    g = c.client()

    def ok(stmt):
        r = g.execute(stmt)
        assert r.ok(), f"{stmt}: {r.error_msg}"
        return r

    ok("CREATE SPACE ch(partition_num=3, replica_factor=1)")
    c.refresh_all()
    ok("USE ch; CREATE EDGE e(w int)")
    c.refresh_all()
    edges = ", ".join(f"{i} -> {i + 1}:({i})" for i in range(48))
    ok(f"INSERT EDGE e(w) VALUES {edges}")
    ws = WebService("nebula-graphd", host="127.0.0.1").start()
    ws.register_health_check("slo", slo.slo_engine.health)
    yield c, g, ok, ws
    ws.stop()
    c.stop()


def _healthz(ws):
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{ws.port}/healthz", timeout=30)
        return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


class TestSloBurnChaos:
    def test_storage_latency_fault_fires_then_self_clears(self, chaos):
        from nebula_tpu.interface.faults import default_injector
        c, g, ok, ws = chaos
        slo.slo_engine.clear_for_tests()
        go = "GO FROM 1 OVER e YIELD e._dst"
        ok(go)                                  # healthy baseline
        code, body = _healthz(ws)
        assert code == 200 and body["checks"]["slo"]["ok"]
        # inject: every getBound pays 1.1 s — past the 1 s go-class
        # latency objective, so every GO under the fault is a breach
        default_injector.configure(
            [{"kind": "delay", "method": "getBound", "delay_s": 1.1}],
            seed=3)
        try:
            for _ in range(2):
                ok(go)
        finally:
            default_injector.clear()
        # poll across the epoch-second boundary (the evaluator memoizes
        # per second) — a single fixed-sleep probe flakes on a loaded
        # box; don't wait past the 5 s fast window or the breaches
        # age out of it
        code, body = _healthz(ws)
        poll_end = time.monotonic() + 3.0
        while code != 503 and time.monotonic() < poll_end:
            time.sleep(0.25)
            code, body = _healthz(ws)
        assert code == 503, body
        assert body["checks"]["slo"]["ok"] is False
        assert "go/latency" in body["checks"]["slo"]["detail"]
        ev = [e for e in journal.dump(300)
              if e["kind"] == "slo.burn_alert"
              and e.get("slo_class") == "go"][0]
        assert ev["state"] == "firing"
        # gauges export on scrape
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{ws.port}/metrics",
            timeout=30).read().decode()
        assert "nebula_graph_slo_burn_rate" in text
        assert 'nebula_graph_slo_firing{objective="latency",' \
               'slo_class="go"} 1' in text
        # heal: dilute the windows with fast statements until the
        # breach fraction is back inside every pair's budget
        for _ in range(250):
            ok(go)
        # here time only helps: the diluted windows keep decaying as
        # the breaches age out, so poll until the alert resolves
        code, body = _healthz(ws)
        poll_end = time.monotonic() + 15.0
        while code != 200 and time.monotonic() < poll_end:
            time.sleep(0.5)
            code, body = _healthz(ws)
        assert code == 200, body
        assert body["checks"]["slo"]["ok"] is True
        ev = [e for e in journal.dump(300)
              if e["kind"] == "slo.burn_alert"
              and e.get("slo_class") == "go"][0]
        assert ev["state"] == "resolved"

    def test_show_stats_carries_slo_rows(self, chaos):
        c, g, ok, ws = chaos
        r = ok("SHOW STATS")
        slo_rows = [row for row in r.rows
                    if str(row[1]).startswith("slo.")]
        names = {row[1] for row in slo_rows}
        assert "slo.go.latency" in names
        assert "slo.go.availability" in names
        assert len(slo_rows) == 2 * len(slo.SLO_OBJECTIVES)


# ===================================================== load briefs
class TestLoadBriefs:
    def test_dispatcher_brief_shape_and_gauges(self, qp):
        c, g, ok = qp
        ok("GO 2 STEPS FROM 1 OVER e")
        d = c.tpu_runtime.dispatcher
        brief = d.load_brief()
        assert set(brief) == {"queue_depth", "lane_seated",
                              "lane_queued", "busy_frac",
                              "shed_rate_5s"}
        assert 0.0 <= brief["busy_frac"] <= 1.0
        assert brief["queue_depth"] >= 0
        text = stats.prometheus_text()
        for k in brief:
            assert f"nebula_graph_load_{k}" in text

    def test_metad_serves_graph_briefs(self, qp):
        c, g, ok = qp
        ok("GO FROM 1 OVER e")          # dispatcher exists now
        c.refresh_all()                 # role=graph beat carries brief
        r = c.meta_service.rpc_listDeviceBriefs({})
        gb = r.get("graph_briefs", {})
        assert gb, r
        (_host, load), = list(gb.items())[:1] or [(None, None)]
        assert "busy_frac" in load and "queue_depth" in load
        # and the client-side accessor (same cached round trip as
        # device_briefs) sees the identical serving-tier map once its
        # heartbeat-window cache is expired
        c.graph_meta_client._device_briefs_at = 0.0
        assert c.graph_meta_client.graph_briefs() == gb


# ===================================================== critical path
class TestCriticalPathProfile:
    def test_profile_carries_phase_table_and_summary(self, qp):
        c, g, ok = qp
        before = stats.read_stats("graph.query.phase_us.count.600") or 0
        r = ok("PROFILE GO 3 STEPS FROM 1 OVER e YIELD e._dst")
        prof = r.raw.get("profile")
        assert prof and "critical_path" in prof, prof
        phases = prof["critical_path"]
        assert sum(phases.values()) > 0
        assert set(phases) <= {"queue", "mirror", "hop-kernel",
                               "fetch", "assemble", "other"}
        summary = prof["critical_path_summary"]
        assert "critical path" in summary
        # every finished trace feeds the fleet-wide histogram
        after = stats.read_stats("graph.query.phase_us.count.600") or 0
        assert after > before
