"""C++ GraphClient end-to-end: compile clients/cpp (g++, no deps beyond
libc) and drive real nGQL against an in-process TCP LocalCluster —
covering the msgpack codec, length-prefixed framing, session flow, and
row decoding (reference analogue: client/cpp exercised via console
tests).  Skips when no C++ toolchain is available."""
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
CPP = REPO / "clients" / "cpp"


@pytest.fixture(scope="module")
def demo_bin(tmp_path_factory):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++")
    out = tmp_path_factory.mktemp("cppclient") / "nebula_cpp_demo"
    subprocess.run(
        [gxx, "-std=c++17", "-O1", "-o", str(out),
         str(CPP / "demo.cc"), str(CPP / "graph_client.cc"),
         "-I", str(CPP)],
        check=True, capture_output=True)
    return out


def test_cpp_client_end_to_end(demo_bin):
    from nebula_tpu.cluster import LocalCluster
    c = LocalCluster(num_storage=1, use_tcp=True)
    try:
        g = c.client()
        assert g.execute(
            "CREATE SPACE s(partition_num=3, replica_factor=1)").ok()
        c.refresh_all()
        host, port = "127.0.0.1", c.graph_addr.port
        r = subprocess.run(
            [str(demo_bin), host, str(port),
             "USE s",
             "CREATE EDGE follow(w int)"],
            capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        c.refresh_all()
        r = subprocess.run(
            [str(demo_bin), host, str(port),
             "USE s",
             "INSERT EDGE follow(w) VALUES 1->2:(7), 2->3:(9)",
             "GO 2 STEPS FROM 1 OVER follow YIELD follow._dst, follow.w"],
            capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        assert "3" in r.stdout and "9" in r.stdout, r.stdout
    finally:
        c.stop()


def test_cpp_client_rejects_bad_server(demo_bin, tmp_path):
    """A non-protocol server must produce a clean error, not a crash
    (oversized-frame guard)."""
    import socket
    import threading

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def bad_server():
        conn, _ = srv.accept()
        conn.recv(65536)
        # announce an absurd 3 GiB frame
        conn.sendall(bytes([0xC0, 0, 0, 0]))
        conn.close()

    t = threading.Thread(target=bad_server, daemon=True)
    t.start()
    r = subprocess.run(
        [str(demo_bin), "127.0.0.1", str(port), "YIELD 1"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode != 0          # clean failure
    assert "Killed" not in r.stderr
    srv.close()
