"""Write-while-serve soak smoke (ISSUE 11 acceptance leg).

Drives tools/bench_suite.bench_write_serve at a short wall budget:
real subprocess daemons, bulk ingest + point mutations (inserts /
updates / deletes) under live GO / COUNT-pushdown / FIND PATH traffic,
a storaged SIGKILL mid-soak, and every invariant asserted inside the
bench itself — bit-exact parity vs the CPU-graphd oracle, zero
acked-write loss, completeness 100 after convergence, and a
zero-rebuild steady write window (absorb count > 0, rebuild count == 0,
delta_overflow == 0).

Slow-marked: scripts/chaos.sh drives it beside the kill matrix; the
recorded 180 s run lands in BENCH_SUITE_r08.json.
"""
import pytest

pytestmark = [pytest.mark.chaos, pytest.mark.slow]


def test_write_while_serve_soak_smoke(tmp_path):
    from nebula_tpu.tools.bench_suite import bench_write_serve
    results: list = []
    row = bench_write_serve(results, duration_s=40.0, chaos=True,
                            run_dir=str(tmp_path))
    # the bench asserts the hard invariants internally; pin the
    # recorded shape here so the JSON leg can't silently go hollow
    assert row["absorbs_steady_window"] > 0
    assert row["rebuilds_steady_window"] == 0
    assert row["delta_overflow"] == 0
    assert row["write_ops"] > 100
    assert row["killed_at_s"] is not None
    assert row["go_p99_ms"] is not None
    assert row["path_p99_ms"] is not None


def test_peer_serve_soak_smoke(tmp_path):
    """Multi-host leg (ISSUE 13 acceptance): 2 storaged, parts spread,
    the serving host folds its peer through the deviceScanDelta
    stream.  Beyond the shared invariants (parity, zero acked loss,
    zero steady-window rebuilds) the bench asserts peer_absorbs > 0 —
    peer writes rode the stream, not the O(m) remote rebuild."""
    from nebula_tpu.tools.bench_suite import bench_peer_serve
    results: list = []
    row = bench_peer_serve(results, duration_s=40.0,
                           run_dir=str(tmp_path))
    assert row["num_storage"] == 2
    assert row["peer_absorbs_steady_window"] > 0
    assert row["rebuilds_steady_window"] == 0
    assert row["absorbs_steady_window"] > 0
    assert row["delta_overflow"] == 0
    assert row["write_ops"] > 100
