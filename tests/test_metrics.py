"""Cluster metrics plane (docs/observability.md "Metrics & events"):

* StatsManager v2 — explicit-bucket histograms (labeled), cumulative
  totals, gauges + scrape-time collectors, dump() min/max columns.
* /metrics Prometheus text exposition on graphd/storaged/metad
  webservices, validated by a small in-repo parser (no new dependency):
  at least one raft gauge, one TPU device gauge and one latency
  histogram with monotone buckets.
* /healthz readiness — flips unhealthy when the wire-level fault
  injector blackholes the meta heartbeat.
* /events + SHOW STATS / SHOW EVENTS end-to-end through a loopback
  cluster (cluster rollup via metad fan-out, catalog-write events).
"""
import json
import re
import time
import urllib.error
import urllib.request

import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.events import EVENT_KINDS, EventJournal, journal
from nebula_tpu.common.stats import StatsManager, stats
from nebula_tpu.webservice import WebService


# ---------------------------------------------------------------------
# A minimal Prometheus text-format (0.0.4) parser: enough rigor to
# catch malformed lines, bad label escaping and non-monotone buckets.
# ---------------------------------------------------------------------
_COMMENT_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (counter|gauge|histogram|summary)$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+(-?[0-9.eE+-]+|NaN|[+-]Inf)$")
_LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')


def parse_prom(text):
    """-> (types {family: type}, samples {(metric, labelstr): value})."""
    types, samples = {}, {}
    for ln in text.splitlines():
        if not ln.strip():
            continue
        if ln.startswith("#"):
            m = _COMMENT_RE.match(ln)
            assert m, f"malformed comment line: {ln!r}"
            types[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE_RE.match(ln)
        assert m, f"malformed sample line: {ln!r}"
        labelstr = m.group(2) or ""
        if labelstr:
            for kv in labelstr[1:-1].split(","):
                assert _LABEL_RE.match(kv), \
                    f"malformed label {kv!r} in {ln!r}"
        key = (m.group(1), labelstr)
        assert key not in samples, f"duplicate series {key}"
        samples[key] = float(m.group(3))
    return types, samples


def _bucket_series(samples, fam):
    """[(le, value)] of one histogram family's unlabeled-extra buckets,
    grouped by their non-le labels."""
    groups = {}
    for (name, labelstr), v in samples.items():
        if name != f"{fam}_bucket":
            continue
        le = None
        rest = []
        for kv in labelstr[1:-1].split(","):
            k, val = kv.split("=", 1)
            if k == "le":
                le = val.strip('"')
            else:
                rest.append(kv)
        groups.setdefault(tuple(rest), []).append(
            (float("inf") if le == "+Inf" else float(le), v))
    return {k: sorted(vs) for k, vs in groups.items()}


# ---------------------------------------------------------------------
# StatsManager v2 units
# ---------------------------------------------------------------------
class TestStatsHistograms:
    def test_histogram_buckets_and_totals(self):
        m = StatsManager()
        m.register_histogram("lat", buckets=(10, 100, 1000))
        for v in (5, 50, 500, 5000):
            m.add_value("lat", v)
        st = m._stats["lat"]
        cell = st.cells[()]
        assert cell.counts == [1, 1, 1]       # per-bound, 5000 overflows
        assert cell.count == 4 and cell.sum == 5555
        assert cell.min == 5 and cell.max == 5000
        assert st.cum_count == 4 and st.cum_sum == 5555

    def test_labeled_observe_children(self):
        m = StatsManager()
        m.register_histogram("disp", buckets=(10, 100))
        m.observe("disp", 7, width=128)
        m.observe("disp", 70, width=128)
        m.observe("disp", 7, width=1024)
        st = m._stats["disp"]
        assert st.cells[(("width", 128),)].count == 2
        assert st.cells[(("width", 1024),)].count == 1
        # the windowed reservoir aggregates across labels (feeds the
        # p95/p99 /get_stats columns)
        total, count, vals = st.window(60)
        assert count == 3 and sorted(vals) == [7, 7, 70]

    def test_prometheus_text_histogram_shape(self):
        m = StatsManager()
        m.register_histogram("lat", buckets=(10, 100))
        m.register_stats("qps")
        for v in (5, 50, 500):
            m.add_value("lat", v)
        m.add_value("qps")
        m.add_value("qps")
        types, samples = parse_prom(m.prometheus_text())
        assert types["nebula_lat"] == "histogram"
        assert types["nebula_qps"] == "counter"
        assert samples[("nebula_qps_total", "")] == 2.0
        assert samples[("nebula_lat_count", "")] == 3.0
        assert samples[("nebula_lat_sum", "")] == 555.0
        for _labels, series in _bucket_series(samples, "nebula_lat").items():
            vals = [v for _le, v in series]
            assert vals == sorted(vals), "buckets must be cumulative"
            assert series[-1][1] == 3.0       # +Inf == count

    def test_gauges_and_collectors(self):
        m = StatsManager()
        calls = []

        def collector():
            calls.append(1)
            m.set_gauge("raft.term", 7, space=1, part=2, host="h")

        m.register_collector(collector)
        rows = m.gauges()
        assert calls and rows == [
            ("raft.term", (("host", "h"), ("part", 2), ("space", 1)), 7.0)]
        # stale series vanish: the table is re-set every scrape
        m.unregister_collector(collector)
        assert m.gauges() == []

    def test_concurrent_scrapes_never_lose_series(self):
        """Scrapes serialize: an overlapping scrape's table clear must
        not wipe series another scrape's collectors just set (the
        webservice is threaded; stats is process-global)."""
        import threading
        m = StatsManager()

        def collector():
            m.set_gauge("raft.term", 1)
            time.sleep(0.005)       # widen the clear->snapshot window

        m.register_collector(collector)
        outs = []

        def scrape():
            outs.append(m.gauges())

        threads = [threading.Thread(target=scrape) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(len(o) == 1 for o in outs), outs

    def test_collector_weakref_drops_with_owner(self):
        m = StatsManager()

        class Owner:
            def collect(self):
                m.set_gauge("raft.term", 1)

        o = Owner()
        m.register_collector(o.collect)
        assert len(m.gauges()) == 1
        del o
        import gc
        gc.collect()
        assert m.gauges() == []

    def test_dump_min_max_columns(self):
        m = StatsManager()
        m.register_stats("lat")
        now = time.time()
        for v in (3, 900, 12):
            m._stats["lat"].add(v, now)
        d = m.dump(now)["lat"]
        assert d["min.60"] == 3.0 and d["max.60"] == 900.0
        assert d["count.60"] == 3.0 and d["sum.60"] == 915.0
        # empty window: min/max present but zero (like p95/p99)
        m.register_stats("idle")
        assert m.dump(now)["idle"]["min.60"] == 0.0
        assert m.dump(now)["idle"]["max.60"] == 0.0

    def test_dump_min_max_survive_reservoir_cap(self):
        """min/max come from per-bucket columns, not the (256-sample
        capped) reservoir — an outlier past the cap must still show."""
        m = StatsManager()
        m.register_stats("lat")
        now = time.time()
        st = m._stats["lat"]
        for _ in range(300):
            st.add(10, now)
        st.add(99999, now)          # beyond the sample cap
        d = m.dump(now)["lat"]
        assert d["max.60"] == 99999.0
        assert d["min.60"] == 10.0


# ---------------------------------------------------------------------
# Event journal units
# ---------------------------------------------------------------------
class TestEventJournal:
    def test_record_and_ring(self):
        j = EventJournal()
        for i in range(5):
            j.record("query.slow", detail=str(i))
        out = j.dump(limit=3)
        assert [e["detail"] for e in out] == ["4", "3", "2"]
        assert all(e["kind"] == "query.slow" for e in out)

    def test_unknown_kind_refused(self):
        j = EventJournal()
        with pytest.raises(ValueError):
            j.record("not.a.kind")

    def test_since_cursor(self):
        j = EventJournal()
        j.record("query.slow", detail="a")
        evs, last = j.since(0)
        assert [e["detail"] for e in evs] == ["a"]
        evs2, last2 = j.since(last)
        assert evs2 == [] and last2 == last
        j.record("query.slow", detail="b")
        evs3, _ = j.since(last)
        assert [e["detail"] for e in evs3] == ["b"]

    def test_since_burst_drains_without_loss(self):
        """A burst larger than one beat's budget must drain OLDEST
        first over several cursor advances — the cap must never skip
        the head of the backlog (the cursor tracks what was actually
        returned, not the ring tail)."""
        j = EventJournal()
        for i in range(100):
            j.record("query.slow", detail=str(i))
        seen, cursor = [], 0
        for _ in range(5):
            evs, cursor = j.since(cursor, limit=64)
            if not evs:
                break
            seen.extend(e["detail"] for e in evs)
        assert seen == [str(i) for i in range(100)]


# ---------------------------------------------------------------------
# Endpoints + nGQL, end to end over a loopback cluster
# ---------------------------------------------------------------------
@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_storage=1, use_raft=True, tpu_backend=True)
    client = c.client()

    def ok(stmt, tries=40):
        last = None
        for _ in range(tries):
            last = client.execute(stmt)
            if last.ok():
                return last
            time.sleep(0.1)
        raise AssertionError(f"{stmt}: {last.error_msg}")

    ok("CREATE SPACE mp(partition_num=2, replica_factor=1)")
    c.refresh_all()
    ok("USE mp; CREATE EDGE e(w int)")
    c.refresh_all()
    edges = ", ".join(f"{i} -> {i + 1}:({i})" for i in range(32))
    ok(f"INSERT EDGE e(w) VALUES {edges}")
    ok("GO FROM 1 OVER e YIELD e._dst")
    c.refresh_all()           # heartbeat: parts brief + events to metad
    c.ok = ok
    yield c
    client.disconnect()
    c.stop()


@pytest.fixture(scope="module")
def webservices(cluster):
    """graphd/storaged/metad-shaped WebServices, wired like the daemons
    (storage/web.py register_web_handlers; metad /events override)."""
    from nebula_tpu.storage.web import register_web_handlers
    out = {}
    s_ws = WebService("nebula-storaged", host="127.0.0.1").start()
    register_web_handlers(s_ws, cluster.storage_nodes[0])
    out["storaged"] = s_ws
    m_ws = WebService("nebula-metad", host="127.0.0.1").start()
    m_ws.register_handler(
        "/events", lambda q, b: (200, cluster.meta_service.rpc_listEvents(
            {"limit": q.get("limit", 200)})))
    out["metad"] = m_ws
    g_ws = WebService("nebula-graphd", host="127.0.0.1").start()
    out["graphd"] = g_ws
    yield out
    for ws in out.values():
        ws.stop()


def _get(ws, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{ws.port}{path}", timeout=30)


class TestMetricsEndpoint:
    def test_all_daemons_serve_valid_exposition(self, webservices):
        for name, ws in webservices.items():
            types, samples = parse_prom(_get(ws, "/metrics").read().decode())
            assert types, f"{name}: empty exposition"

    def test_storaged_raft_gauges(self, webservices):
        _types, samples = parse_prom(
            _get(webservices["storaged"], "/metrics").read().decode())
        terms = {k: v for k, v in samples.items()
                 if k[0] == "nebula_raft_term"}
        assert terms, "no raft term gauge exported"
        assert any('space="' in k[1] and 'part="' in k[1] for k in terms)
        lags = [v for k, v in samples.items()
                if k[0] == "nebula_raft_commit_lag"]
        assert lags and all(v >= 0 for v in lags)
        assert any(k[0] == "nebula_raft_is_leader" and v == 1.0
                   for k, v in samples.items())

    def test_tpu_device_gauges(self, webservices):
        _types, samples = parse_prom(
            _get(webservices["storaged"], "/metrics").read().decode())
        # series carry the runtime-role label: a storaged holds TWO
        # runtimes (deviceGo + bulk-read backend) whose collectors
        # would otherwise shadow each other's gauge values
        assert any(k[0] == "nebula_tpu_jit_cache_size"
                   and 'runtime="' in k[1] for k in samples), samples
        assert any(k[0] == "nebula_tpu_compile_count"
                   for k in samples)

    def test_latency_histogram_shape(self, webservices):
        types, samples = parse_prom(
            _get(webservices["graphd"], "/metrics").read().decode())
        assert types["nebula_graph_latency_us"] == "histogram"
        series = _bucket_series(samples, "nebula_graph_latency_us")
        assert series
        for labels, buckets in series.items():
            vals = [v for _le, v in buckets]
            assert vals == sorted(vals), "buckets must be cumulative"
        count = samples[("nebula_graph_latency_us_count", "")]
        assert count >= 1
        assert samples[("nebula_graph_latency_us_sum", "")] > 0

    def test_fault_counters_present(self, webservices):
        _types, samples = parse_prom(
            _get(webservices["storaged"], "/metrics").read().decode())
        assert ("nebula_rpc_fault_injected_total", "") in samples


class TestHealthz:
    def test_healthy_cluster_is_ready(self, cluster, webservices):
        resp = _get(webservices["storaged"], "/healthz")
        body = json.load(resp)
        assert resp.status == 200 and body["healthy"] is True
        assert set(body["checks"]) == {"meta", "parts", "device",
                                       "device_breaker", "peer_mirror"}
        assert body["checks"]["device_breaker"]["ok"]
        assert body["checks"]["peer_mirror"]["ok"]

    def test_no_checks_means_bare_liveness(self, webservices):
        resp = _get(webservices["graphd"], "/healthz")
        assert resp.status == 200 and json.load(resp)["healthy"] is True

    def test_flips_unhealthy_under_fault_injection(self, cluster,
                                                   webservices):
        from nebula_tpu.interface.faults import default_injector
        default_injector.configure(
            [{"kind": "blackhole", "method": "heartBeat"}], seed=7)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(webservices["storaged"], "/healthz")
            assert ei.value.code == 503
            body = json.load(ei.value)
            assert body["healthy"] is False
            assert body["checks"]["meta"]["ok"] is False
        finally:
            default_injector.clear()
        # the injection itself is journaled
        kinds = {e["kind"] for e in journal.dump(limit=200)}
        assert "fault.injected" in kinds
        # and recovery is observable
        resp = _get(webservices["storaged"], "/healthz")
        assert resp.status == 200


class TestEventsEndpoint:
    def test_events_listing(self, cluster, webservices):
        body = json.load(_get(webservices["storaged"], "/events?limit=50"))
        assert isinstance(body["events"], list) and body["events"]
        for e in body["events"]:
            assert e["kind"] in EVENT_KINDS
            assert "time_us" in e and "id" in e
        times = [e["time_us"] for e in body["events"]]
        assert times == sorted(times, reverse=True)

    def test_metad_serves_cluster_aggregation(self, cluster, webservices):
        body = json.load(_get(webservices["metad"], "/events?limit=200"))
        kinds = {e["kind"] for e in body["events"]}
        assert "meta.catalog_write" in kinds


class TestShowStatsEvents:
    def test_show_stats_cluster_rollup(self, cluster):
        r = cluster.ok("SHOW STATS")
        assert r.column_names[:2] == ["Host", "Stat"]
        hosts = {row[0] for row in r.rows}
        assert "<cluster>" in hosts and "metad" in hosts
        qps = [row for row in r.rows
               if row[0] == "<cluster>" and row[1] == "graph.qps"]
        assert qps and qps[0][2] >= 1       # Sum(60s)

    def test_show_events_catalog_writes(self, cluster):
        r = cluster.ok("SHOW EVENTS")
        assert r.column_names == ["Time(us)", "Host", "Kind", "Detail"]
        kinds = {row[2] for row in r.rows}
        assert "meta.catalog_write" in kinds
        details = {row[3] for row in r.rows if row[2] == "meta.catalog_write"}
        assert any("createSpace" in d for d in details)

    def test_show_parts_replication_columns(self, cluster):
        r = cluster.ok("SHOW PARTS")
        assert r.column_names == ["Partition ID", "Leader", "Term",
                                  "Committed", "Last Log", "Peers"]
        assert len(r.rows) == 2
        # single-replica raft parts: this node leads, positions are ints
        # (the heartbeat in the fixture's refresh_all delivered them)
        leaders = {row[1] for row in r.rows}
        assert leaders == {cluster.storage_nodes[0].host}
        for row in r.rows:
            assert isinstance(row[3], int) and isinstance(row[4], int)
            assert row[3] <= row[4]         # committed <= last log


class TestMicroBenchMetricsPath:
    def test_metrics_path_within_budget(self):
        from nebula_tpu.tools.micro_bench import bench_metrics
        out = bench_metrics(20)
        assert out["within_budget"], out
        assert out["render_bytes"] > 0
