"""Batched ELL traversal engine tests — parity against an independent
numpy frontier-advance and against the edge-list kernels, single-chip
and sharded over the 8-device CPU mesh (conftest).  Mirrors the
reference's strategy of checking the storage hot path against
known-good row sets (QueryBoundTest.cpp) — here the known-good is the
per-query numpy expansion."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nebula_tpu.tpu import ell as E  # noqa: E402
from nebula_tpu.tpu import kernels as K  # noqa: E402


def run_go(ix, steps, etypes, f0):
    """Build + invoke the batched GO kernel with the round-3 calling
    convention (tables as args); returns the raw int8 frontier."""
    k = E.make_batched_go_kernel(ix, steps, etypes)
    return np.asarray(k(jnp.asarray(f0), *ix.kernel_args()))


def run_bfs(ix, max_steps, etypes, f0, t0, stop_when_found=True):
    k = E.make_batched_bfs_kernel(ix, max_steps, etypes,
                                  stop_when_found=stop_when_found)
    d = np.asarray(k(jnp.asarray(f0), jnp.asarray(t0), *ix.kernel_args()))
    if d.dtype == np.int8:           # in-kernel compression (-1 = INF)
        d = np.where(d < 0, E.INT16_INF, d).astype(np.int16)
    return d


def run_adaptive(ix, steps, etypes, K, start_new_ids):
    k = E.make_adaptive_go_kernel(ix, steps, etypes, K=K)
    hub = jnp.asarray(ix.hub_table())
    packed = np.asarray(k(start_new_ids, hub, *ix.kernel_args()))
    return E.unpack_bits(packed[:, None], ix.n_rows + 1)[:, 0]


def np_multi_hop(n, es, ed, ok, starts_per_query, steps):
    nq = len(starts_per_query)
    fr = np.zeros((n, nq), bool)
    for q, s in enumerate(starts_per_query):
        fr[np.asarray(s), q] = True
    for _ in range(steps - 1):
        nxt = np.zeros_like(fr)
        for q in range(nq):
            act = fr[es, q] & ok
            nxt[ed[act], q] = True
        fr = nxt
    return fr


@pytest.mark.parametrize("cap,min_d", [(4, 1), (16, 8), (512, 8)])
def test_batched_go_parity_random(cap, min_d):
    rng = np.random.default_rng(11)
    for _ in range(3):
        n = int(rng.integers(5, 300))
        m = int(rng.integers(0, 2000))
        es = rng.integers(0, n, m).astype(np.int32)
        ed = rng.integers(0, n, m).astype(np.int32)
        ee = rng.choice([1, 2, -1, 3], m).astype(np.int32)
        etypes = (1, 3)
        steps = int(rng.integers(2, 5))
        starts = [rng.integers(0, n, int(rng.integers(1, 6)))
                  for _ in range(5)]
        ok = np.isin(ee, etypes)
        exp = np_multi_hop(n, es, ed, ok, starts, steps)

        ix = E.EllIndex.build(es, ed, ee, n, cap=cap, min_d=min_d)
        f0 = ix.start_frontier([np.asarray(s) for s in starts], B=128)
        got = ix.to_old(run_go(ix, steps, etypes, f0))[:, :5] > 0
        np.testing.assert_array_equal(got, exp)

        # packed output variant must round-trip to the same frontier
        kp = E.make_batched_go_kernel(ix, steps, etypes, pack=True)
        packed = np.asarray(kp(jnp.asarray(f0), *ix.kernel_args()))
        unp = E.unpack_bits(packed, ix.n_rows + 1)
        np.testing.assert_array_equal(ix.to_old(unp)[:, :5], exp)


def test_hub_rows_split_and_merge():
    # one mega-hub: in-degree 50 with cap 8 -> extra rows + fix-up
    n = 60
    es = np.arange(50, dtype=np.int32)          # 0..49 -> hub 55
    ed = np.full(50, 55, dtype=np.int32)
    ee = np.ones(50, dtype=np.int32)
    ix = E.EllIndex.build(es, ed, ee, n, cap=8, min_d=1)
    assert len(ix.extra_owner) >= 1
    f0 = ix.start_frontier([np.asarray([49])], B=128)
    got = ix.to_old(run_go(ix, 2, (1,), f0))[:, 0] > 0
    exp = np.zeros(n, bool)
    exp[55] = True                               # only the hub reached
    np.testing.assert_array_equal(got, exp)
    # start that is NOT an in-neighbor reaches nothing
    f0 = ix.start_frontier([np.asarray([55])], B=128)
    got = ix.to_old(run_go(ix, 2, (1,), f0))[:, 0] > 0
    assert not got.any()


def test_batched_vs_edge_list_kernel():
    rng = np.random.default_rng(3)
    n, m = 128, 700
    es = rng.integers(0, n, m).astype(np.int32)
    ed = rng.integers(0, n, m).astype(np.int32)
    ee = rng.choice([1, 2], m).astype(np.int32)
    steps = 3
    ix = E.EllIndex.build(es, ed, ee, n, cap=16, min_d=4)
    start = np.arange(6, dtype=np.int32)
    f0 = ix.start_frontier([start], B=128)
    got = ix.to_old(run_go(ix, steps, (1,), f0))[:, 0] > 0

    ref = K.make_go_kernel(n, steps, (1,))(
        jnp.asarray(es), jnp.asarray(ed), jnp.asarray(ee),
        jnp.asarray(start))
    np.testing.assert_array_equal(got, np.asarray(ref[1]))


def test_batched_bfs_depths():
    # line graph 0->1->...->9 plus shortcut 0->5
    es = np.array([0, 1, 2, 3, 4, 5, 6, 7, 8, 0], np.int32)
    ed = np.array([1, 2, 3, 4, 5, 6, 7, 8, 9, 5], np.int32)
    ee = np.ones(10, np.int32)
    n = 10
    ix = E.EllIndex.build(es, ed, ee, n, cap=4, min_d=1)
    f0 = ix.start_frontier([np.asarray([0]), np.asarray([3])], B=128)
    t0 = ix.start_frontier([np.asarray([9]), np.asarray([9])], B=128)
    d = run_bfs(ix, 8, (1,), f0, t0, stop_when_found=False)[ix.perm]
    # query 0: depth of 9 is 0->5(1) ..9 => 1+4=5
    assert d[9, 0] == 5
    assert d[5, 0] == 1
    # query 1: from 3: 9 at depth 6
    assert d[9, 1] == 6
    assert d[0, 1] == E.INT16_INF


def test_bfs_early_exit_shortest():
    es = np.array([0, 1], np.int32)
    ed = np.array([1, 2], np.int32)
    ee = np.ones(2, np.int32)
    ix = E.EllIndex.build(es, ed, ee, 3, cap=2, min_d=1)
    f0 = ix.start_frontier([np.asarray([0])], B=128)
    t0 = ix.start_frontier([np.asarray([1])], B=128)
    d = run_bfs(ix, 100, (1,), f0, t0, stop_when_found=True)[ix.perm]
    assert d[1, 0] == 1     # target found; loop exited without error


def test_sharded_batched_go_parity():
    from jax.sharding import Mesh
    rng = np.random.default_rng(5)
    n, m = 100, 600
    es = rng.integers(0, n, m).astype(np.int32)
    ed = rng.integers(0, n, m).astype(np.int32)
    ee = rng.choice([1, -1], m).astype(np.int32)
    ix = E.EllIndex.build(es, ed, ee, n, cap=8, min_d=2)
    steps = 3
    starts = [rng.integers(0, n, 3) for _ in range(4)]
    # f0 stays a HOST array and each kernel call converts its own
    # device copy — the runtime's dispatch paths build theirs with
    # donate=True (single-use), which a shared device f0 would break
    f0 = ix.start_frontier([np.asarray(s) for s in starts], B=128)
    ref = run_go(ix, steps, (1,), f0)

    mesh = Mesh(np.array(jax.devices()[:8]), ("parts",))
    nbrs, ets, reals = E.shard_ell(mesh, "parts", ix)
    go = E.make_sharded_batched_go_kernel(mesh, "parts", ix, steps, (1,),
                                          nbrs, ets, reals)
    eslot, hrows = ix.hub_merge()
    got = np.asarray(go(jnp.asarray(E.pack_lanes_host(f0)),
                        jnp.asarray(eslot), jnp.asarray(hrows),
                        *nbrs, *ets))
    np.testing.assert_array_equal(E.unpack_lanes_host(got, 128),
                                  np.asarray(ref) > 0)


def test_runtime_go_batch_small_cluster():
    """go_batch/bfs_batch through the full runtime on a real in-process
    cluster (the batched dispatch graphd-level batching rides on)."""
    from nebula_tpu.cluster import LocalCluster
    c = LocalCluster(num_storage=1, tpu_backend=True)
    g = c.client()
    for stmt in ("CREATE SPACE s(partition_num=3, replica_factor=1)",):
        assert g.execute(stmt).ok()
    c.refresh_all()
    assert g.execute("USE s").ok()
    assert g.execute("CREATE EDGE follow(w int)").ok()
    c.refresh_all()
    assert g.execute(
        "INSERT EDGE follow(w) VALUES 1->2:(1), 2->3:(1), "
        "3->4:(1), 1->5:(1)").ok()

    rt = c.tpu_runtime
    sid = c.graph_meta_client.get_space_id_by_name("s").value()
    et = c.schema_man.to_edge_type(sid, "follow").value()
    out = rt.go_batch(sid, [[1], [2], [1]], [et], 2)
    m = rt.mirror(sid)

    def vids_of(row):
        return {int(m.vids[i]) for i in np.nonzero(row)[0]}

    assert vids_of(out[0]) == {3}
    assert vids_of(out[1]) == {4}
    assert vids_of(out[2]) == {3}

    d = rt.bfs_batch(sid, [[1]], [[4]], [et], 10, shortest=True)
    dense4 = int(m.to_dense([4])[0])
    assert d[0, dense4] == 3


def test_async_mirror_refresh_serves_stale_then_updates():
    """mirror_refresh_mode=async keeps answering from the stale mirror
    and swaps in the rebuilt one off-thread (the reference's bounded
    staleness: caches refresh every load_data_interval_secs)."""
    import time
    from nebula_tpu.cluster import LocalCluster
    from nebula_tpu.common.flags import flags

    c = LocalCluster(num_storage=1, tpu_backend=True)
    g = c.client()
    for stmt in ("CREATE SPACE s2(partition_num=3, replica_factor=1)",):
        assert g.execute(stmt).ok()
    c.refresh_all()
    assert g.execute("USE s2").ok()
    assert g.execute("CREATE TAG p(x int)").ok()
    assert g.execute("CREATE EDGE e(w int)").ok()
    c.refresh_all()
    assert g.execute("INSERT EDGE e(w) VALUES 1->2:(1)").ok()

    rt = c.tpu_runtime
    sid = c.graph_meta_client.get_space_id_by_name("s2").value()
    m1 = rt.mirror(sid)
    assert m1.m >= 1

    flags.set("mirror_refresh_mode", "async")
    try:
        # a NEW-vertex write changes the vertex plan, which absorption
        # declines (docs/durability.md decision table), so it
        # exercises the async rebuild path
        assert g.execute('INSERT VERTEX p(x) VALUES 9:(5)').ok()
        stale = rt.mirror(sid)          # triggers bg rebuild, serves stale
        assert stale is m1
        deadline = time.time() + 30
        while time.time() < deadline:
            m2 = rt.mirror(sid)
            if m2 is not m1:
                break
            time.sleep(0.05)
        assert m2 is not m1, "background rebuild never landed"
        assert m2.n > m1.n              # the new vertex landed
    finally:
        flags.set("mirror_refresh_mode", "sync")
    c.stop()


def test_runtime_mesh_sharded_parity():
    """tpu_mesh_devices=8 must produce the same nGQL results as the
    single-device path — the runtime-level multi-chip check (the
    kernel-level one is test_sharded_batched_go_parity)."""
    from nebula_tpu.cluster import LocalCluster
    from nebula_tpu.common.flags import flags

    c = LocalCluster(num_storage=1, tpu_backend=True)
    g = c.client()
    assert g.execute(
        "CREATE SPACE sm(partition_num=3, replica_factor=1)").ok()
    c.refresh_all()
    assert g.execute("USE sm").ok()
    assert g.execute("CREATE EDGE e(w int)").ok()
    c.refresh_all()
    rng = np.random.default_rng(13)
    vals = ", ".join(f"{a}->{b}:({i})" for i, (a, b) in
                     enumerate(zip(rng.integers(1, 60, 300),
                                   rng.integers(1, 60, 300))))
    assert g.execute(f"INSERT EDGE e(w) VALUES {vals}").ok()

    queries = [
        "GO 3 STEPS FROM 1 OVER e YIELD e._dst",
        "GO 2 STEPS FROM 5 OVER e WHERE e.w > 100 YIELD e._dst, e.w",
        "FIND SHORTEST PATH FROM 1 TO 59 OVER e",
    ]
    single = [sorted(map(tuple, g.execute(q).rows)) for q in queries]
    flags.set("tpu_mesh_devices", 8)
    try:
        for mode in ("sparse", "dense"):
            flags.set("tpu_mesh_mode", mode)
            for q, exp in zip(queries, single):
                r = g.execute(q)
                assert r.ok(), f"[{mode}] {q}: {r.error_msg}"
                assert sorted(map(tuple, r.rows)) == exp, (mode, q)
        # the frontier-sharded paths must have actually served, and
        # mesh-served FIND PATH must count in path_device like every
        # other device BFS (the serving accounting the benches report)
        assert c.tpu_runtime.stats.get("go_mesh_sparse", 0) > 0
        assert c.tpu_runtime.stats.get("bfs_mesh_sparse", 0) > 0
        assert c.tpu_runtime.stats.get("path_device", 0) > 0
        # live-vs-declared ICI accounting (common/flight.py): a healthy
        # 8-way dryrun stays IN-BOUND on every sharded kernel's
        # KernelSpec.ici_bytes model and the tpu.model_drift gauges
        # read zero — the declared models hold on live dispatches
        from nebula_tpu.common.flight import recorder
        from nebula_tpu.common.stats import stats as _stats
        mesh_kernels = ("ell_go_sharded", "ell_bfs_sharded",
                        "mesh_sparse_go", "mesh_sparse_bfs")
        cells = {k: v for k, v in recorder.drift_cells().items()
                 if k.split("/", 1)[-1] in mesh_kernels}
        assert cells, "mesh dispatches never folded ICI accounting"
        for k, cell in cells.items():
            assert 0 < cell["live"] <= cell["declared"], (k, cell)
            assert not cell["over"], (k, cell)
        drift = {labels: v for name, labels, v in _stats.gauges()
                 if name == "tpu.model_drift.ici"
                 and labels[0][1] in mesh_kernels}
        assert drift and all(v == 0.0 for v in drift.values()), drift
    finally:
        flags.set("tpu_mesh_devices", 0)
        flags.set("tpu_mesh_mode", "sparse")
    c.stop()


def test_native_builder_identical():
    """The C++ ELL builder must produce byte-identical tables to the
    numpy oracle across degree shapes incl. hubs and empty graphs."""
    from nebula_tpu.native import ensure_built, lib
    if not ensure_built() or lib() is None:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(42)
    cases = []
    for _ in range(4):
        n = int(rng.integers(1, 500))
        m = int(rng.integers(0, 4000))
        cases.append((rng.integers(0, n, m).astype(np.int32),
                      rng.integers(0, n, m).astype(np.int32),
                      rng.choice([1, 2, -1], m).astype(np.int32), n))
    # hub case: one vertex with in-degree 900 at cap 64
    es = rng.integers(0, 50, 900).astype(np.int32)
    cases.append((es, np.full(900, 7, np.int32),
                  np.ones(900, np.int32), 50))
    cases.append((np.zeros(0, np.int32), np.zeros(0, np.int32),
                  np.zeros(0, np.int32), 0))
    for es, ed, ee, n in cases:
        for cap, min_d in ((8, 1), (64, 8), (512, 8)):
            a = E.EllIndex.build(es, ed, ee, n, cap=cap, min_d=min_d,
                                 use_native=False)
            b = E.EllIndex.build(es, ed, ee, n, cap=cap, min_d=min_d,
                                 use_native=True)
            assert a.n_rows == b.n_rows and a.bucket_D == b.bucket_D
            np.testing.assert_array_equal(a.perm, b.perm)
            np.testing.assert_array_equal(a.inv, b.inv)
            np.testing.assert_array_equal(a.extra_owner, b.extra_owner)
            for x, y in zip(a.bucket_nbr, b.bucket_nbr):
                np.testing.assert_array_equal(x, y)
            for x, y in zip(a.bucket_et, b.bucket_et):
                np.testing.assert_array_equal(x, y)


def test_adaptive_kernel_parity_random():
    """Adaptive sparse-frontier kernel vs the batched kernel on random
    mirror-shaped graphs (both directions present), across K values
    that force mid-query overflow to the dense pull."""
    rng = np.random.default_rng(23)
    for _ in range(5):
        n = int(rng.integers(10, 400))
        m = int(rng.integers(0, 3000))
        es = rng.integers(0, n, m).astype(np.int32)
        ed = rng.integers(0, n, m).astype(np.int32)
        ee = rng.choice([1, 2], m).astype(np.int32)
        es2 = np.concatenate([es, ed])
        ed2 = np.concatenate([ed, es])
        ee2 = np.concatenate([ee, -ee])
        steps = int(rng.integers(2, 6))
        K = int(rng.choice([16, 64, 2048]))
        ix = E.EllIndex.build(es2, ed2, ee2, n, cap=int(rng.choice([8, 64])),
                              min_d=4)
        starts = rng.integers(0, n, int(rng.integers(1, 5)))
        exp = ix.to_old(run_go(ix, steps, (1,),
                               ix.start_frontier([starts],
                                                 B=128)))[:, 0] > 0
        got = ix.to_old(run_adaptive(ix, steps, (1,), K,
                                     ix.perm[starts])) > 0
        np.testing.assert_array_equal(got, exp)


def test_adaptive_runtime_single_query():
    """A lone GO through the runtime rides the adaptive kernel and
    returns the same rows as the batched path."""
    from nebula_tpu.cluster import LocalCluster
    from nebula_tpu.common.flags import flags
    c = LocalCluster(num_storage=1, tpu_backend=True)
    g = c.client()
    assert g.execute("CREATE SPACE ak(partition_num=3, replica_factor=1)").ok()
    c.refresh_all()
    assert g.execute("USE ak").ok()
    assert g.execute("CREATE EDGE e(w int)").ok()
    c.refresh_all()
    assert g.execute("INSERT EDGE e(w) VALUES 1->2:(1), 2->3:(1), "
                     "3->4:(1), 2->5:(1)").ok()
    r1 = g.execute("GO 2 STEPS FROM 1 OVER e YIELD e._dst")
    assert r1.ok() and sorted(x[0] for x in r1.rows) == [3, 5]
    # same query with the adaptive path disabled must match
    flags.set("tpu_adaptive_single", False)
    try:
        r2 = g.execute("GO 2 STEPS FROM 1 OVER e YIELD e._dst")
    finally:
        flags.set("tpu_adaptive_single", True)
    assert sorted(map(tuple, r1.rows)) == sorted(map(tuple, r2.rows))
    c.stop()


def test_adaptive_hub_in_frontier_switches_dense():
    """A frontier containing a hub vertex (slots spilling into extra
    rows) must produce exact results — the kernel switches to the
    dense pull for that hop instead of materializing hub-degree-scaled
    candidate lists."""
    rng = np.random.default_rng(9)
    n = 300
    # hub vertex 7: 200 out-edges; plus background edges
    hub_dst = rng.integers(0, n, 200).astype(np.int32)
    es = np.concatenate([np.full(200, 7, np.int32),
                         rng.integers(0, n, 500).astype(np.int32)])
    ed = np.concatenate([hub_dst, rng.integers(0, n, 500).astype(np.int32)])
    ee = np.ones(len(es), np.int32)
    es2 = np.concatenate([es, ed]); ed2 = np.concatenate([ed, es])
    ee2 = np.concatenate([ee, -ee])
    ix = E.EllIndex.build(es2, ed2, ee2, n, cap=16, min_d=4)
    assert len(ix.extra_owner) > 0                 # hub rows exist
    for steps in (2, 4):
        exp = ix.to_old(run_go(ix, steps, (1,),
                               ix.start_frontier([np.asarray([7])],
                                                 B=128)))[:, 0] > 0
        got = ix.to_old(run_adaptive(ix, steps, (1,), 64,
                                     ix.perm[np.asarray([7])])) > 0
        np.testing.assert_array_equal(got, exp)


def test_sparse_batched_go_parity_random():
    """Sparse pair-list batched GO vs the dense kernel on random
    mirror-shaped graphs.  Small caps must REPORT overflow (the caller
    then reruns dense) — never return silently-wrong pairs; roomy caps
    must match the dense frontier exactly."""
    rng = np.random.default_rng(31)
    verified = 0
    for trial in range(8):
        n = int(rng.integers(10, 400))
        m = int(rng.integers(0, 2500))
        es = rng.integers(0, n, m).astype(np.int32)
        ed = rng.integers(0, n, m).astype(np.int32)
        ee = rng.choice([1, 2], m).astype(np.int32)
        es2 = np.concatenate([es, ed])
        ed2 = np.concatenate([ed, es])
        ee2 = np.concatenate([ee, -ee])
        steps = int(rng.integers(2, 5))
        ix = E.EllIndex.build(es2, ed2, ee2, n,
                              cap=int(rng.choice([16, 64])), min_d=4)
        nq = int(rng.integers(1, 6))
        starts = [np.unique(rng.integers(0, n, int(rng.integers(1, 4))))
                  for _ in range(nq)]
        exp = ix.to_old(run_go(ix, steps, (1,),
                               ix.start_frontier(starts,
                                                 B=128)))[:, :nq] > 0
        d_max = max(ix.bucket_D) if ix.bucket_D else 1
        c0 = 64
        cap = int(rng.choice([64, 1 << 17]))     # tight cap forces overflow
        caps = E.sparse_caps(c0, d_max, steps, cap)
        kern = E.make_batched_sparse_go_kernel(ix, steps, (1,), caps)
        ids = np.full(c0, ix.n_rows, np.int32)
        qid = np.zeros(c0, np.int32)
        o = 0
        for q, s in enumerate(starts):
            newi = np.sort(ix.perm[s])
            ids[o:o + len(newi)] = newi
            qid[o:o + len(newi)] = q
            o += len(newi)
        ecnt, e0 = (jnp.asarray(a) for a in ix.hub_expansion())
        out = np.asarray(kern(jnp.asarray(ids), jnp.asarray(qid), ecnt,
                              e0, *ix.kernel_args()[1:]))
        _cnt, overflow, qids, vnew = E.sparse_go_pairs(kern, out)
        if overflow:    # overflow reported — dense fallback covers it
            continue
        got = np.zeros((n, nq), bool)
        if len(qids):
            got[ix.inv[vnew], qids] = True
        np.testing.assert_array_equal(got, exp, err_msg=f"trial {trial}")
        verified += 1
    assert verified >= 2, "every trial overflowed; caps too tight to test"


def test_sparse_hub_push_exact():
    """Hub vertices (slot-spill extra rows) are pushed EXACTLY by the
    sparse kernel: the device expands every frontier hub into its
    extra-row run before the gather, so a hub as a push source is no
    longer an overflow condition (round-4 behavior) — the kernel's
    answer must bit-match the dense pull."""
    # chain: 0 -> 1 -> hub(2) -> {3..149}; hub spills at cap=16
    n = 200
    es = [0, 1] + [2] * 147
    ed = [1, 2] + [i for i in range(3, 150)]
    ee = [1] * len(es)
    es, ed, ee = (np.asarray(es, np.int32), np.asarray(ed, np.int32),
                  np.asarray(ee, np.int32))
    es2 = np.concatenate([es, ed]); ed2 = np.concatenate([ed, es])
    ee2 = np.concatenate([ee, -ee])
    ix = E.EllIndex.build(es2, ed2, ee2, n, cap=16, min_d=4)
    assert len(ix.extra_owner) > 0
    ecnt, e0 = (jnp.asarray(a) for a in ix.hub_expansion())
    for steps in (3, 4):    # hub in final set; hub as a push SOURCE
        caps = E.sparse_caps(64, max(ix.bucket_D), steps, 1 << 12)
        kern = E.make_batched_sparse_go_kernel(ix, steps, (1,), caps)
        ids = np.full(caps[0], ix.n_rows, np.int32)
        qid = np.zeros(caps[0], np.int32)
        ids[0] = ix.perm[0]
        out = np.asarray(kern(jnp.asarray(ids), jnp.asarray(qid), ecnt,
                              e0, *ix.kernel_args()[1:]))
        _cnt, overflow, qids, vids = E.sparse_go_pairs(kern, out)
        assert not overflow, f"steps={steps}: hub push must not overflow"
        got = np.zeros(n, bool)
        got[ix.inv[vids]] = True
        exp = ix.to_old(run_go(ix, steps, (1,),
                               ix.start_frontier([np.asarray([0])],
                                                 B=128)))[:, 0] > 0
        np.testing.assert_array_equal(got, exp, err_msg=f"steps={steps}")


def test_sparse_hub_expansion_overflow_reported():
    """A frontier whose hubs carry more extra rows than the hop budget
    must REPORT overflow (dense rerun), never drop slots silently."""
    # one vertex with in-degree 8 at cap=4 -> extra rows; budget c0=4
    # is smaller than the expansion
    n = 40
    es = list(range(1, 33))
    ed = [0] * 32
    ee = [1] * 32
    es, ed, ee = (np.asarray(es, np.int32), np.asarray(ed, np.int32),
                  np.asarray(ee, np.int32))
    es2 = np.concatenate([es, ed]); ed2 = np.concatenate([ed, es])
    ee2 = np.concatenate([ee, -ee])
    ix = E.EllIndex.build(es2, ed2, ee2, n, cap=4, min_d=4)
    assert len(ix.extra_owner) >= 4
    ecnt, e0 = (jnp.asarray(a) for a in ix.hub_expansion())
    steps = 2
    caps = (4, 1 << 10)     # hub expansion (7 extras) exceeds EX=c0=4
    kern = E.make_batched_sparse_go_kernel(ix, steps, (1,), caps)
    ids = np.full(caps[0], ix.n_rows, np.int32)
    qid = np.zeros(caps[0], np.int32)
    ids[0] = ix.perm[0]     # start ON the hub
    out = np.asarray(kern(jnp.asarray(ids), jnp.asarray(qid), ecnt, e0,
                          *ix.kernel_args()[1:]))
    assert out[1] == 1, "hub expansion past the budget must overflow"


def test_frontier_sharded_sparse_go_bitmatch():
    """The frontier-sharded sparse kernel (per-device pair lists,
    all_to_all candidate exchange, sharded hub metadata) must bit-match
    the single-device dense pull on randomized hub-bearing graphs over
    an 8-virtual-device mesh — and hold NO dense frontier anywhere."""
    import jax
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:8]), ("parts",))
    rng = np.random.default_rng(17)
    verified = 0
    for trial in range(6):
        n = int(rng.integers(50, 500))
        m = int(rng.integers(100, 3000))
        es = rng.integers(0, n, m).astype(np.int32)
        ed = rng.integers(0, n, m).astype(np.int32)
        # a deliberate hub: vertex 0 receives/sends a burst
        hub_m = int(rng.integers(0, 120))
        es = np.concatenate([es, np.zeros(hub_m, np.int32)])
        ed = np.concatenate([ed, rng.integers(0, n, hub_m).astype(np.int32)])
        ee = rng.choice([1, 2], len(es)).astype(np.int32)
        es2 = np.concatenate([es, ed])
        ed2 = np.concatenate([ed, es])
        ee2 = np.concatenate([ee, -ee])
        steps = int(rng.integers(2, 5))
        ix = E.EllIndex.build(es2, ed2, ee2, n, cap=16, min_d=4)
        sh = E.build_sharded_ell(ix, 8)
        nq = int(rng.integers(1, 6))
        starts = [np.unique(rng.integers(0, n, int(rng.integers(1, 4))))
                  for _ in range(nq)]
        exp = ix.to_old(run_go(ix, steps, (1,),
                               ix.start_frontier(starts,
                                                 B=128)))[:, :nq] > 0
        caps = tuple(min(1 << 12, 8 * (16 ** h) * 8)
                     for h in range(steps))
        kern = E.make_frontier_sharded_sparse_go_kernel(
            mesh, "parts", sh, steps, (1,), caps,
            cap_x=1 << 11, cap_e=64)
        new_ids, qids = [], []
        for q, s in enumerate(starts):
            new_ids.extend(ix.perm[s].tolist())
            qids.extend([q] * len(s))
        placed = E.split_start_pairs_by_owner(
            sh, np.asarray(new_ids, np.int32),
            np.asarray(qids, np.int32), caps[0])
        assert placed is not None
        args = E.sharded_device_args(mesh, "parts", sh)
        out = kern(jnp.asarray(placed[0]), jnp.asarray(placed[1]),
                   args[0], args[1], args[2], *args[3], *args[4])
        overflow, oq, ou = E.sharded_sparse_pairs(np.asarray(out))
        if overflow:
            continue
        got = np.zeros((n, nq), bool)
        if len(oq):
            got[ix.inv[ou], oq] = True
        np.testing.assert_array_equal(got, exp, err_msg=f"trial {trial}")
        verified += 1
    assert verified >= 3, "too many overflows; caps too tight to test"


def test_frontier_sharded_sparse_bfs_bitmatch():
    """The frontier-sharded BFS (per-device depth chunks, all_to_all
    level exchange) must reproduce the single-device batched BFS depths
    on randomized hub-bearing graphs."""
    import jax
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:8]), ("parts",))
    rng = np.random.default_rng(23)
    for trial in range(4):
        n = int(rng.integers(40, 300))
        m = int(rng.integers(60, 1500))
        es = rng.integers(0, n, m).astype(np.int32)
        ed = rng.integers(0, n, m).astype(np.int32)
        hub_m = int(rng.integers(0, 80))
        es = np.concatenate([es, np.zeros(hub_m, np.int32)])
        ed = np.concatenate([ed, rng.integers(0, n, hub_m).astype(np.int32)])
        ee = np.ones(len(es), np.int32)
        es2 = np.concatenate([es, ed]); ed2 = np.concatenate([ed, es])
        ee2 = np.concatenate([ee, -ee])
        ix = E.EllIndex.build(es2, ed2, ee2, n, cap=16, min_d=4)
        sh = E.build_sharded_ell(ix, 8)
        nq = int(rng.integers(1, 5))
        max_steps = int(rng.integers(2, 7))
        shortest = bool(rng.integers(0, 2))
        starts = [np.unique(rng.integers(0, n, 2)) for _ in range(nq)]
        targets = [np.unique(rng.integers(0, n, 2)) for _ in range(nq)]
        f0 = ix.start_frontier(starts, B=128)
        t0 = ix.start_frontier(targets, B=128)
        ref = run_bfs(ix, max_steps, (1,), f0, t0,
                      stop_when_found=shortest)

        builder = E.make_frontier_sharded_sparse_bfs_kernel(
            mesh, "parts", sh, max_steps, (1,), cap=1 << 11,
            cap_x=1 << 10, cap_e=64, stop_when_found=shortest)
        kern = builder(128)
        ni, qi, ti, tq = [], [], [], []
        for q, s in enumerate(starts):
            ni.extend(ix.perm[s].tolist()); qi.extend([q] * len(s))
        for q, t in enumerate(targets):
            ti.extend(ix.perm[t].tolist()); tq.extend([q] * len(t))
        ps = E.split_start_pairs_by_owner(
            sh, np.asarray(ni, np.int32), np.asarray(qi, np.int32),
            1 << 11)
        pt = E.split_start_pairs_by_owner(
            sh, np.asarray(ti, np.int32), np.asarray(tq, np.int32),
            1 << 11)
        a = E.sharded_device_args(mesh, "parts", sh)
        dep, ovf = kern(jnp.asarray(ps[0]), jnp.asarray(ps[1]),
                        jnp.asarray(pt[0]), jnp.asarray(pt[1]),
                        a[0], a[1], a[2], *a[3], *a[4])
        assert not np.asarray(ovf).any()
        got = np.asarray(dep).reshape(8 * sh.chunk, 128)[:ix.n_rows + 1]
        # strict equality incl. shortest mode: both kernels run whole
        # levels and the all-targets-found level is deterministic, so
        # early exit lands on the same level
        np.testing.assert_array_equal(
            ix.to_old(got)[:, :nq], ix.to_old(ref)[:, :nq],
            err_msg=f"trial {trial} shortest={shortest}")
