"""Cross-process device serving (storage/device.py + rpc_deviceGo).

The round-2 flagship seam: the standalone graphd ships whole GO /
FIND PATH queries over the StorageService RPC boundary to storaged's
device runtime (tpu/runtime.py serve_go), replacing round 1's
in-process-only attachment.  Tests cover:

  * row parity remote-device vs CPU per-hop path, over loopback AND
    over real TCP sockets (full wire serialization);
  * the device counters increment (proof the device actually served);
  * graceful decline → CPU fallback (multi-host placement, $-input);
  * hard errors surface as query errors, not CPU fallbacks.
"""
import time

import numpy as np
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.flags import flags
from nebula_tpu.common.stats import stats


def _seed(c, cl):
    def ok(s):
        r = cl.execute(s)
        assert r.ok(), f"{s}: {r.error_msg}"
        return r
    ok("CREATE SPACE dev(partition_num=4, replica_factor=1)")
    c.refresh_all()
    ok("USE dev")
    ok("CREATE TAG player(name string, age int)")
    ok("CREATE EDGE follow(degree int)")
    c.refresh_all()
    ok('INSERT VERTEX player(name, age) VALUES '
       '100:("Tim", 42), 101:("Tony", 36), 102:("Manu", 41), '
       '103:("LeBron", 34)')
    ok('INSERT EDGE follow(degree) VALUES '
       '100->101:(95), 101->102:(90), 102->100:(90), 100->102:(80), '
       '102->103:(70)')
    return ok


QUERIES = [
    "GO FROM 100 OVER follow",
    "GO UPTO 2 STEPS FROM 100 OVER follow YIELD follow._dst",
    "GO 2 STEPS FROM 100 OVER follow YIELD follow._dst, follow.degree",
    "GO 3 STEPS FROM 100 OVER follow WHERE follow.degree > 85 "
    "YIELD follow._dst, $$.player.name",
    "GO FROM 100, 102 OVER follow WHERE $^.player.age > 40 "
    "YIELD DISTINCT follow._dst",
    "GO FROM 102 OVER follow REVERSELY YIELD follow._dst",
    "FIND SHORTEST PATH FROM 100 TO 103 OVER follow UPTO 5 STEPS",
    "FIND ALL PATH FROM 100 TO 102 OVER follow UPTO 3 STEPS",
]


@pytest.fixture(scope="module",
                params=[(False, 1), (True, 1), (False, 2)],
                ids=["loopback", "tcp", "loopback-2storaged"])
def remote_cluster(request):
    use_tcp, num_storage = request.param
    prev = flags.get("storage_backend")
    flags.set("storage_backend", "tpu")
    c = LocalCluster(num_storage=num_storage, use_tcp=use_tcp,
                     tpu_backend="remote")
    cl = c.client()
    _seed(c, cl)
    yield c, cl
    flags.set("storage_backend", prev)
    c.stop()


class TestRemoteParity:
    @pytest.mark.parametrize("query", QUERIES)
    def test_same_rows_as_cpu(self, remote_cluster, query):
        _, cl = remote_cluster
        r = cl.execute(query)
        assert r.ok(), f"{query}: {r.error_msg}"
        device_rows = sorted(map(tuple, r.rows))
        flags.set("storage_backend", "cpu")
        try:
            r2 = cl.execute(query)
        finally:
            flags.set("storage_backend", "tpu")
        assert r2.ok(), f"{query}: {r2.error_msg}"
        assert device_rows == sorted(map(tuple, r2.rows)), query

    def test_device_counters_increment(self, remote_cluster):
        _, cl = remote_cluster
        go0 = stats.read_stats("storage.device_go.qps.count.3600") or 0
        path0 = stats.read_stats("storage.device_path.qps.count.3600") or 0
        assert cl.execute("GO 2 STEPS FROM 100 OVER follow").ok()
        assert cl.execute("FIND SHORTEST PATH FROM 100 TO 103 OVER follow "
                          "UPTO 5 STEPS").ok()
        assert (stats.read_stats("storage.device_go.qps.count.3600")
                or 0) > go0
        assert (stats.read_stats("storage.device_path.qps.count.3600")
                or 0) > path0


class TestReducePushdownWire:
    """LIMIT/COUNT pushdown over the deviceGo RPC boundary: the reduce
    descriptor rides the request, the response carries the reduced
    shape + capability echo (storage/device.py, docs/roofline.md)."""

    def test_limit_over_rpc(self, remote_cluster):
        _, cl = remote_cluster
        base = "GO 2 STEPS FROM 100 OVER follow YIELD follow._dst AS d"
        full = cl.execute(base)
        assert full.ok()
        fset = {tuple(r) for r in full.rows}
        r = cl.execute(base + " | LIMIT 1")
        assert r.ok(), r.error_msg
        assert len(r.rows) == min(1, len(full.rows))
        assert all(tuple(row) in fset for row in r.rows)

    def test_count_over_rpc_matches_cpu(self, remote_cluster):
        _, cl = remote_cluster
        q = ("GO 2 STEPS FROM 100, 102 OVER follow "
             "YIELD follow._dst AS d | YIELD COUNT(*) AS n")
        go0 = stats.read_stats("storage.device_go.qps.count.3600") or 0
        r = cl.execute(q)
        assert r.ok(), r.error_msg
        assert (stats.read_stats("storage.device_go.qps.count.3600")
                or 0) > go0, "count pipe must still serve on device"
        flags.set("storage_backend", "cpu")
        try:
            r2 = cl.execute(q)
        finally:
            flags.set("storage_backend", "tpu")
        assert r2.ok()
        assert r.column_names == r2.column_names == ["n"]
        assert sorted(map(tuple, r.rows)) == sorted(map(tuple, r2.rows))


class TestDeclineFallback:
    def test_piped_input_runs_cpu(self, remote_cluster):
        """$- input is gated client-side; the piped GO must still return
        correct rows via the CPU per-hop loop."""
        _, cl = remote_cluster
        r = cl.execute("GO FROM 100 OVER follow YIELD follow._dst AS id | "
                       "GO FROM $-.id OVER follow YIELD follow._dst")
        assert r.ok(), r.error_msg
        assert sorted(map(tuple, r.rows)) == [(100,), (102,), (103,)]

    def test_multi_host_space_serves_on_device(self):
        """Parts spread over two storaged hosts: the chosen storaged
        folds the peer's parts into its mirror through deviceScan and
        answers on the device (VERDICT round-2 missing #1 — the gate
        that silently degraded distributed clusters to CPU is gone)."""
        prev = flags.get("storage_backend")
        flags.set("storage_backend", "tpu")
        c = LocalCluster(num_storage=2, tpu_backend="remote")
        try:
            cl = c.client()
            ok = _seed(c, cl)
            # both storageds must actually hold parts of the space
            sid = c.graph_meta_client.get_space_id_by_name("dev").value()
            owned = [len(n.kv.part_ids(sid)) for n in c.storage_nodes]
            assert all(o > 0 for o in owned), owned
            go0 = stats.read_stats("storage.device_go.qps.count.3600") or 0
            r = ok("GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            assert sorted(map(tuple, r.rows)) == [(100,), (102,), (103,)]
            assert (stats.read_stats("storage.device_go.qps.count.3600")
                    or 0) > go0, "device did not serve the 2-host space"
            # writes through the OTHER host must be visible on the next
            # device query (version poll → rebuild)
            ok("INSERT EDGE follow(degree) VALUES 103->100:(60)")
            r2 = ok("GO 2 STEPS FROM 102 OVER follow YIELD follow._dst")
            assert (100,) in set(map(tuple, r2.rows))
        finally:
            flags.set("storage_backend", prev)
            c.stop()

    def test_multi_host_peer_down_falls_back_cpu(self):
        """A peer holding parts becomes unreachable: the serving host
        can't cover the space, declines, and the CPU scatter-gather
        path still answers from the surviving... (the CPU path needs
        the peer too, so here we only assert the DECLINE is clean and
        an error-free response comes back once the peer returns)."""
        prev = flags.get("storage_backend")
        flags.set("storage_backend", "tpu")
        c = LocalCluster(num_storage=2, tpu_backend="remote")
        try:
            cl = c.client()
            ok = _seed(c, cl)
            ok("GO FROM 100 OVER follow")          # device-served once
            # cut peer RPC: the serving host's deviceScan/deviceVersion
            # to the other node now fail
            from nebula_tpu.interface.common import HostAddr
            victims = []
            for n in c.storage_nodes[1:]:
                addr = HostAddr.parse(n.host)
                victims.append((addr, n.handler))
                c.cm.unregister_loopback(addr)   # crash-simulate peer
            # a fresh write bumps versions so the mirror must rebuild —
            # which now fails → decline; the CPU path also needs the
            # peer, so the query errors (partial storage) or succeeds
            # only if the serving host leads every part
            go0 = stats.read_stats("storage.device_go.qps.count.3600") or 0
            r = cl.execute("GO 2 STEPS FROM 100 OVER follow")
            # no NEW device serve happened against a stale/unreachable view
            assert (stats.read_stats("storage.device_go.qps.count.3600")
                    or 0) == go0
            for addr, h in victims:
                c.cm.register_loopback(addr, h)
            r = cl.execute("GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            assert r.ok() and sorted(map(tuple, r.rows)) == \
                [(100,), (102,), (103,)]
        finally:
            flags.set("storage_backend", prev)
            c.stop()

    def test_cpu_flag_disables_device(self, remote_cluster):
        _, cl = remote_cluster
        flags.set("storage_backend", "cpu")
        try:
            go0 = stats.read_stats("storage.device_go.qps.count.3600") or 0
            r = cl.execute("GO FROM 100 OVER follow")
            assert r.ok()
            assert (stats.read_stats("storage.device_go.qps.count.3600")
                    or 0) == go0
        finally:
            flags.set("storage_backend", "tpu")


class TestServeGoWire:
    """serve_go's wire decode path directly (no graphd executor)."""

    def test_decline_reasons_on_wire(self, remote_cluster):
        c, _ = remote_cluster
        node = c.storage_nodes[0]
        # non-existent part in the client's view → gate declines
        resp = node.service.rpc_deviceGo({
            "space_id": 1, "parts": [999], "start_vids": [100],
            "etypes": [1], "steps": 1, "etype_to_alias": {1: "follow"},
            "yield": [], "distinct": False, "where": None,
            "pushed_mode": False})
        assert resp["ok"] is False and "999" in resp["reason"]

    def test_undecodable_expression_declines(self, remote_cluster):
        c, _ = remote_cluster
        node = c.storage_nodes[0]
        space_id = node.meta_client.get_space_id_by_name("dev").value()
        parts = sorted(node.kv.part_ids(space_id))
        resp = node.service.rpc_deviceGo({
            "space_id": space_id, "parts": parts, "start_vids": [100],
            "etypes": [1], "steps": 1, "etype_to_alias": {1: "follow"},
            "yield": [[b"\x00garbage", None]], "distinct": False,
            "where": None, "pushed_mode": False})
        assert resp["ok"] is False and resp.get("reason")


class TestTornScanGuard:
    """RemoteStoreView.prefix: a write landing BETWEEN scan chunks gives
    the peer's mirror a torn view of a multi-key commit — the version
    echo must fail the scan (build fails → CPU fallback → next query
    rebuilds) instead of serving torn rows."""

    class _FakeCM:
        def __init__(self, rows_per_chunk=2, bump_at_chunk=None):
            self.rows = [(b"k%02d" % i, b"v%d" % i) for i in range(6)]
            self.per = rows_per_chunk
            self.bump_at = bump_at_chunk
            self.version = 7
            self.chunks_served = 0

        def call(self, addr, method, payload, timeout=None):
            assert method == "deviceScan"
            if self.bump_at is not None \
                    and self.chunks_served == self.bump_at:
                self.version += 1         # a commit landed mid-scan
            cur = payload.get("cursor")
            start = 0
            if cur is not None:
                start = next(i for i, (k, _v) in enumerate(self.rows)
                             if k == cur) + 1
            chunk = self.rows[start:start + self.per]
            self.chunks_served += 1
            return {"ok": True, "rows": chunk,
                    "cursor": chunk[-1][0] if chunk else cur,
                    "done": start + self.per >= len(self.rows),
                    "version": self.version}

    def _view(self, cm):
        from nebula_tpu.interface.common import HostAddr
        from nebula_tpu.storage.device import RemoteStoreView
        return RemoteStoreView(HostAddr("p", 1), 1, cm)

    def test_stable_version_streams_all_rows(self):
        cm = self._FakeCM()
        got = list(self._view(cm).prefix(1, 1, b"k"))
        assert got == cm.rows

    def test_mid_scan_version_bump_fails_the_scan(self):
        from nebula_tpu.interface.rpc import RpcError
        cm = self._FakeCM(bump_at_chunk=2)
        with pytest.raises(RpcError):
            list(self._view(cm).prefix(1, 1, b"k"))


class TestUptoRpcSkew:
    """The deviceGo response must ECHO the upto field: an older
    storaged that ignores it would silently serve exact depth, so a
    missing echo is a decline (cached per space — the round trip is
    not re-paid per query)."""

    def _runtime(self, responses):
        from types import SimpleNamespace

        from nebula_tpu.storage.device import RemoteDeviceRuntime

        rt = RemoteDeviceRuntime(meta_client=None, schema_man=None,
                                 client_manager=None)
        calls = []

        def fake_call(host, method, req, ExcType):
            calls.append(req)
            return responses.pop(0)

        rt._call = fake_call
        rt._device_hosts = lambda sid: [(("h", 1), [1])]
        rt.calls = calls
        return rt

    def _go(self, rt, upto):
        from types import SimpleNamespace

        from nebula_tpu.filter.expressions import PrimaryExpr
        sentence = SimpleNamespace(step=SimpleNamespace(steps=3,
                                                        upto=upto))
        executor = SimpleNamespace(sentence=sentence)
        return rt.run_go(executor, 7, [1], [1], 3, {1: "e"},
                         [SimpleNamespace(expr=PrimaryExpr(1),
                                          alias="c")],
                         False, None, {}, [], upto=upto)

    def test_missing_echo_declines_and_caches(self):
        from nebula_tpu.storage.device import TpuDecline

        import pytest as _pytest
        # old build: ok response WITHOUT the upto echo
        rt = self._runtime([{"ok": True, "columns": ["c"], "rows": []}])
        with _pytest.raises(TpuDecline):
            self._go(rt, upto=True)
        assert 7 in rt._upto_declined
        # next UPTO query on the space declines BEFORE any RPC
        sentence = type("S", (), {})()
        sentence.step = type("T", (), {"steps": 3, "upto": True})()
        assert rt.can_run_go(7, [1], sentence, None, None, [], [],
                             False) is False
        assert len(rt.calls) == 1          # no second round trip

    def test_echo_accepted(self):
        from nebula_tpu.graph.interim import InterimResult
        rt = self._runtime([{"ok": True, "columns": ["c"], "rows": [],
                             "upto": True}])
        out = self._go(rt, upto=True)
        assert isinstance(out, InterimResult)
        assert 7 not in rt._upto_declined

    def test_exact_depth_needs_no_echo(self):
        rt = self._runtime([{"ok": True, "columns": ["c"], "rows": []}])
        out = self._go(rt, upto=False)
        assert out is not None


class TestUptoDeclineCacheHealing:
    """The UPTO negative cache must HEAL: entries lapse after
    upto_decline_ttl_s (a restarted/upgraded storaged gets UPTO traffic
    again without a graphd restart) and drop immediately when a
    placement refresh moves the space's device host."""

    def _declined_runtime(self):
        from nebula_tpu.storage.device import TpuDecline
        helper = TestUptoRpcSkew()
        # old build: ok response WITHOUT the upto echo -> decline cached
        rt = helper._runtime([{"ok": True, "columns": ["c"], "rows": []}])
        with pytest.raises(TpuDecline):
            helper._go(rt, upto=True)
        assert 7 in rt._upto_declined
        return rt

    def _can_run(self, rt):
        sentence = type("S", (), {})()
        sentence.step = type("T", (), {"steps": 3, "upto": True})()
        return rt.can_run_go(7, [1], sentence, None, None, [], [], False)

    def test_decline_lapses_after_ttl(self):
        saved = flags.get("upto_decline_ttl_s")
        flags.set("upto_decline_ttl_s", 0.05)
        try:
            rt = self._declined_runtime()
            assert self._can_run(rt) is False     # cached decline binds
            time.sleep(0.06)
            # TTL lapsed: the space is probed again (entry dropped)
            assert self._can_run(rt) is True
            assert 7 not in rt._upto_declined
        finally:
            flags.set("upto_decline_ttl_s", saved)

    def test_decline_dropped_on_placement_change(self):
        rt = self._declined_runtime()
        assert self._can_run(rt) is False
        # placement refresh moved the space's device host: the old
        # host's decline no longer describes the serving storaged
        rt._device_hosts = lambda sid: [(("h2", 1), [1])]
        assert self._can_run(rt) is True
        assert 7 not in rt._upto_declined

    def test_decline_dropped_on_meta_refresh(self):
        """ADVICE.md round 5: a storaged restarted WITHOUT mesh
        sharding (same host, same placement) must resume UPTO traffic
        as soon as graphd's meta cache refreshes — not only after the
        TTL or a graphd restart.  load_data bumps
        MetaClient.data_generation; any bump drops the entry."""
        from types import SimpleNamespace
        meta = SimpleNamespace(data_generation=41)
        rt = self._declined_runtime()
        rt.meta = meta
        # re-note against the live meta so the entry carries its gen
        rt._note_upto_declined(7, ("h", 1))
        assert self._can_run(rt) is False      # same generation: binds
        meta.data_generation += 1              # a load_data completed
        assert self._can_run(rt) is True
        assert 7 not in rt._upto_declined

    def test_meta_client_load_data_bumps_generation(self):
        """The generation the decline cache keys on really moves on
        every completed load_data."""
        from nebula_tpu.interface.common import HostAddr
        from nebula_tpu.interface.rpc import ClientManager
        from nebula_tpu.meta.client import MetaClient
        from nebula_tpu.meta.service import MetaService

        cm = ClientManager()
        svc = MetaService()
        addr = HostAddr("127.0.0.1", 45990)
        cm.register_loopback(addr, svc)
        mc = MetaClient([addr], client_manager=cm)
        g0 = mc.data_generation
        mc.load_data()
        assert mc.data_generation == g0 + 1
        mc.load_data()
        assert mc.data_generation == g0 + 2
