"""Continuous hop-boundary dispatch — the seat-map tier
(graph/batch_dispatch.py ContinuousGoScheduler + tpu/runtime.py
_ContinuousGoSession, docs/admission.md "Continuous dispatch").

Three layers:

  * _LaneLedger unit suite: join/leave/fragmentation/wraparound — no
    lane is ever double-seated, freed lanes hand out lowest-first.
  * The generative parity differential: the same seeded query mix
    (mixed hop counts, UPTO, LIMIT/COUNT pushdown riders, forced
    mid-flight joins) through ``go_dispatch_mode=windowed`` vs
    ``continuous`` must be bit-exact — the windowed pipeline is the
    oracle.
  * Serving semantics: mid-flight joins journal + count, deadline
    evictions free their lanes typed, the seat map drains to zero, and
    write-fresh generations re-anchor the stream (read-your-writes).
"""
import threading
import time

import numpy as np
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.events import journal
from nebula_tpu.common.flags import flags
from nebula_tpu.common.stats import stats
from nebula_tpu.graph.batch_dispatch import _LaneLedger


# ===================================================== lane ledger
class TestLaneLedger:
    def test_alloc_lowest_first(self):
        led = _LaneLedger(16)
        assert [led.alloc() for _ in range(4)] == [0, 1, 2, 3]
        assert led.seated_count() == 4
        assert led.free_count() == 12

    def test_release_and_wraparound(self):
        led = _LaneLedger(4)
        lanes = [led.alloc() for _ in range(4)]
        assert lanes == [0, 1, 2, 3]
        with pytest.raises(RuntimeError):
            led.alloc()                     # exhausted
        for ln in lanes:
            led.release(ln)
        # full wraparound: every lane usable again, lowest-first
        assert [led.alloc() for _ in range(4)] == [0, 1, 2, 3]

    def test_fragmentation_fills_lowest_hole(self):
        led = _LaneLedger(8)
        lanes = [led.alloc() for _ in range(8)]
        led.release(2)
        led.release(5)
        led.release(3)
        # holes re-seat lowest-first so occupancy clusters into few
        # words (the leave-extract fetch is per WORD)
        assert led.alloc() == 2
        assert led.alloc() == 3
        assert led.alloc() == 5
        assert lanes == list(range(8))

    def test_no_double_seat_or_double_release(self):
        led = _LaneLedger(2)
        a = led.alloc()
        with pytest.raises(RuntimeError):
            led.release(a + 1)              # not seated
        led.release(a)
        with pytest.raises(RuntimeError):
            led.release(a)                  # double release
        seen = set()
        for _ in range(2):
            ln = led.alloc()
            assert ln not in seen
            seen.add(ln)

    def test_interleaved_churn_never_double_seats(self):
        rng = np.random.default_rng(11)
        led = _LaneLedger(16)
        seated = set()
        for _ in range(500):
            if seated and (led.free_count() == 0 or rng.random() < 0.5):
                ln = int(rng.choice(sorted(seated)))
                led.release(ln)
                seated.discard(ln)
            else:
                ln = led.alloc()
                assert ln not in seated
                seated.add(ln)
        assert led.seated_count() == len(seated)


# ===================================================== cluster fixture
def _boot_graph(seed=7, n=40, m=160):
    c = LocalCluster(num_storage=1, tpu_backend=True)
    g = c.client()

    def ok(stmt):
        r = g.execute(stmt)
        assert r.ok(), f"{stmt}: {r.error_msg}"
        return r

    ok("CREATE SPACE s(partition_num=3, replica_factor=1)")
    c.refresh_all()
    ok("USE s")
    ok("CREATE EDGE e(w int)")
    c.refresh_all()
    rng = np.random.default_rng(seed)
    src = rng.integers(1, n + 1, m)
    dst = rng.integers(1, n + 1, m)
    pairs = sorted({(int(a), int(b)) for a, b in zip(src, dst)
                    if a != b})
    vals = ", ".join(f"{a} -> {b}:({(a * 31 + b) % 97})"
                     for a, b in pairs)
    ok(f"INSERT EDGE e(w) VALUES {vals}")
    return c, g, ok


@pytest.fixture(scope="module")
def nba():
    flags.set("go_dispatch_mode", "continuous")
    c, g, ok = _boot_graph()
    yield c, g, ok
    c.stop()
    flags.set("go_dispatch_mode", "continuous")
    flags.set("tpu_sparse_go", True)


def _mix_queries(rng, n_queries=24, max_vid=40):
    """The seeded differential mix: mixed hop counts, multi-start
    roots, UPTO, WHERE, LIMIT/COUNT pushdown riders."""
    out = []
    for _ in range(n_queries):
        starts = ",".join(str(int(v)) for v in
                          rng.integers(1, max_vid + 1,
                                       int(rng.integers(1, 4))))
        steps = int(rng.integers(2, 5))
        kind = int(rng.integers(0, 5))
        if kind == 0:
            out.append(f"GO {steps} STEPS FROM {starts} OVER e "
                       f"YIELD e._dst")
        elif kind == 1:
            out.append(f"GO UPTO {steps} STEPS FROM {starts} OVER e "
                       f"YIELD e._dst")
        elif kind == 2:
            out.append(f"GO {steps} STEPS FROM {starts} OVER e "
                       f"YIELD e._dst | YIELD COUNT(*)")
        elif kind == 3:
            out.append(f"GO {steps} STEPS FROM {starts} OVER e "
                       f"YIELD e._dst | LIMIT {int(rng.integers(1, 6))}")
        else:
            out.append(f"GO {steps} STEPS FROM {starts} OVER e "
                       f"WHERE e.w > 40 YIELD e._dst, e.w")
    return out


class TestParityDifferential:
    def test_windowed_vs_continuous_bit_exact(self, nba):
        """The headline oracle: the same seeded mix through both
        dispatch modes is bit-exact.  Sparse kernels are disabled for
        the windowed leg so LIMIT riders take the dense route in both
        modes — a sparse in-kernel cut may pick a DIFFERENT (legal)
        subset, which is route semantics, not a dispatch-mode
        difference (docs/roofline.md)."""
        c, g, ok = nba
        queries = _mix_queries(np.random.default_rng(3))
        flags.set("tpu_sparse_go", False)
        try:
            flags.set("go_dispatch_mode", "continuous")
            cont = [sorted(map(tuple, ok(q).rows)) for q in queries]
            flags.set("go_dispatch_mode", "windowed")
            wind = [sorted(map(tuple, ok(q).rows)) for q in queries]
        finally:
            flags.set("go_dispatch_mode", "continuous")
            flags.set("tpu_sparse_go", True)
        for q, a, b in zip(queries, cont, wind):
            assert a == b, f"dispatch-mode divergence: {q}\n{a}\n{b}"

    def test_limit_rider_default_flags_membership(self, nba):
        """With default flags a windowed LIMIT may ride the sparse cut
        (route-dependent subset): assert the mode-invariant contract —
        row COUNT matches and every row is in the full result."""
        c, g, ok = nba
        full = set(map(tuple,
                       ok("GO 2 STEPS FROM 1,2,3 OVER e "
                          "YIELD e._dst").rows))
        r = ok("GO 2 STEPS FROM 1,2,3 OVER e YIELD e._dst | LIMIT 3")
        assert len(r.rows) == min(3, len(full))
        assert all(tuple(row) in full for row in r.rows)

    def test_concurrent_mix_parity_with_forced_joins(self, nba):
        """The mid-flight leg: a slow tick cadence forces the burst's
        arrivals to OR-merge into an already-running lane batch, and
        the results must still match the windowed oracle."""
        c, g, ok = nba
        queries = _mix_queries(np.random.default_rng(5), n_queries=12)
        flags.set("tpu_sparse_go", False)
        try:
            flags.set("go_dispatch_mode", "windowed")
            oracle = [sorted(map(tuple, ok(q).rows)) for q in queries]
            flags.set("go_dispatch_mode", "continuous")
            ok("GO 2 STEPS FROM 1 OVER e")      # streams exist
            d = c.tpu_runtime.dispatcher
            for st in d.continuous.streams():
                st.tick_delay_s = 0.02
            joins0 = stats.read_stats(
                "graph.continuous.joins.sum.60") or 0.0
            results = {}
            errors = []
            barrier = threading.Barrier(len(queries))

            def worker(i):
                try:
                    g2 = c.client()
                    g2.execute("USE s")
                    barrier.wait()
                    r = g2.execute(queries[i])
                    assert r.ok(), r.error_msg
                    results[i] = sorted(map(tuple, r.rows))
                except Exception as ex:     # noqa: BLE001
                    errors.append(ex)

            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(len(queries))]
            [t.start() for t in ts]
            [t.join() for t in ts]
            for st in d.continuous.streams():
                st.tick_delay_s = 0.0
        finally:
            flags.set("go_dispatch_mode", "continuous")
            flags.set("tpu_sparse_go", True)
        assert not errors, errors
        for i, q in enumerate(queries):
            assert results[i] == oracle[i], q
        joins1 = stats.read_stats("graph.continuous.joins.sum.60") or 0.0
        assert joins1 > joins0, "burst never rode the seat map"


class TestServingSemantics:
    def test_midflight_join_journaled_and_counted(self, nba):
        c, g, ok = nba
        ok("GO 2 STEPS FROM 1 OVER e")          # stream anchored
        d = c.tpu_runtime.dispatcher
        st = next(iter(d.continuous.streams()))
        st.tick_delay_s = 0.05
        try:
            done = []

            def long_query():
                g2 = c.client()
                g2.execute("USE s")
                r = g2.execute("GO 4 STEPS FROM 1 OVER e YIELD e._dst")
                done.append(r)

            t = threading.Thread(target=long_query)
            t.start()
            time.sleep(0.08)        # the 4-hop rider is mid-flight
            r2 = ok("GO 2 STEPS FROM 2 OVER e YIELD e._dst")
            t.join()
        finally:
            st.tick_delay_s = 0.0
        assert done and done[0].ok(), done
        assert r2.ok()
        kinds = [e["kind"] for e in journal.dump(200)]
        assert "query.joined_midflight" in kinds
        ev = [e for e in journal.dump(200)
              if e["kind"] == "query.joined_midflight"][-1]
        assert "lane=" in ev["detail"]

    def test_profile_carries_continuous_marker(self, nba):
        c, g, ok = nba
        r = ok("PROFILE GO 3 STEPS FROM 1 OVER e YIELD e._dst")
        prof = r.raw.get("profile")
        assert prof

        def walk(n):
            yield n
            for ch in n.get("children", []):
                yield from walk(ch)

        spans = [s for root in prof["roots"] for s in walk(root)]
        cont = [s for s in spans if s["name"] == "graph.continuous"]
        assert cont, [s["name"] for s in spans]
        tags = cont[0]["tags"]
        assert tags.get("lane") is not None
        assert tags.get("hops") == 2

    def test_deadline_eviction_frees_lane_typed(self, nba):
        from nebula_tpu.common.status import ErrorCode
        c, g, ok = nba
        ok("GO 2 STEPS FROM 1 OVER e")
        d = c.tpu_runtime.dispatcher
        st = next(iter(d.continuous.streams()))
        st.tick_delay_s = 0.15
        try:
            t0 = time.perf_counter()
            r = g.execute("TIMEOUT 120 GO 4 STEPS FROM 1 OVER e "
                          "YIELD e._dst")
            wall = time.perf_counter() - t0
        finally:
            st.tick_delay_s = 0.0
        assert r.error_code == ErrorCode.E_DEADLINE_EXCEEDED, \
            r.error_msg
        assert wall < 3.0
        # the evicted rider's lane must drain — no seat leak
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            seated, queued = d.continuous.seat_counts()
            if seated == 0 and queued == 0:
                break
            time.sleep(0.05)
        assert (seated, queued) == (0, 0)

    def test_seat_map_drains_and_balances(self, nba):
        c, g, ok = nba
        for q in _mix_queries(np.random.default_rng(9), n_queries=8):
            ok(q)
        d = c.tpu_runtime.dispatcher
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            seated, queued = d.continuous.seat_counts()
            if seated == 0 and queued == 0:
                break
            time.sleep(0.05)
        assert (seated, queued) == (0, 0), "lane leak"
        joins = stats.read_stats("graph.continuous.joins.sum.600") or 0
        leaves = stats.read_stats("graph.continuous.leaves.sum.600") or 0
        evics = stats.read_stats(
            "graph.continuous.evictions.sum.600") or 0
        assert joins > 0
        assert joins == leaves + evics, (joins, leaves, evics)

    def test_write_fresh_generation_reanchors(self, nba):
        """Read-your-writes across the stream: a write that publishes
        a new mirror generation must be visible to the next continuous
        query (the pump re-anchors instead of serving the stale
        resident tables)."""
        c, g, ok = nba
        before = sorted(map(tuple,
                            ok("GO 2 STEPS FROM 1 OVER e "
                               "YIELD e._dst").rows))
        ok("INSERT EDGE e(w) VALUES 1 -> 39:(1), 39 -> 38:(2)")
        deadline = time.monotonic() + 10.0
        after = None
        while time.monotonic() < deadline:
            after = sorted(map(tuple,
                               ok("GO 2 STEPS FROM 1 OVER e "
                                  "YIELD e._dst").rows))
            if (38,) in after:
                break
            time.sleep(0.1)
        assert after is not None and (38,) in after, (before, after)

    def test_metrics_surface(self, nba):
        """graph.continuous.* and the idle-frac gauges render in the
        Prometheus exposition (the chaos lane-leak assertion's
        surface)."""
        c, g, ok = nba
        ok("GO 3 STEPS FROM 2 OVER e YIELD e._dst")
        text = stats.prometheus_text()
        assert "nebula_graph_continuous_joins_total" in text
        assert "nebula_graph_continuous_seated" in text
        assert "nebula_graph_continuous_lane_occupancy" in text
        assert "nebula_tpu_device_idle_frac" in text
        assert "nebula_graph_autoscale_recommended_replicas" in text

    def test_extract_failure_wakes_leavers_typed(self, nba):
        """Review regression: leavers leave the seat map BEFORE the
        extract/clear ops run, so a device failure there must wake
        them explicitly (the pump-level recovery can no longer reach
        them) — a rider must get a typed error, never a hang, and the
        stream must recover for the next query."""
        c, g, ok = nba
        ok("GO 2 STEPS FROM 1 OVER e")          # stream anchored
        d = c.tpu_runtime.dispatcher
        st = next(s for s in d.continuous.streams()
                  if s.session is not None)

        def boom(*a, **k):
            raise RuntimeError("simulated extract failure")

        st.session.extract = boom
        t0 = time.perf_counter()
        r = g.execute("GO 3 STEPS FROM 2 OVER e YIELD e._dst")
        wall = time.perf_counter() - t0
        assert wall < 10.0, "rider hung on a failed extract"
        assert not r.ok() and "simulated extract failure" in \
            (r.error_msg or "")
        # the pump dropped the broken session; the stream re-anchors
        # and serves again
        r2 = ok("GO 3 STEPS FROM 2 OVER e YIELD e._dst")
        assert r2.ok()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if d.continuous.seat_counts() == (0, 0):
                break
            time.sleep(0.05)
        assert d.continuous.seat_counts() == (0, 0)

    def test_idle_stream_releases_session(self, nba, monkeypatch):
        """Review regression: an idle stream must drop its resident
        device frontier pair after CONTINUOUS_IDLE_RELEASE_S instead
        of holding HBM forever; the next query re-anchors."""
        import nebula_tpu.graph.batch_dispatch as bd
        c, g, ok = nba
        monkeypatch.setattr(bd, "CONTINUOUS_IDLE_RELEASE_S", 0.3)
        ok("GO 2 STEPS FROM 1 OVER e")
        d = c.tpu_runtime.dispatcher
        st = next(s for s in d.continuous.streams()
                  if s.session is not None)
        deadline = time.monotonic() + 6.0
        while time.monotonic() < deadline and st.session is not None:
            time.sleep(0.1)
        assert st.session is None, "idle session never released"
        r = ok("GO 2 STEPS FROM 1 OVER e YIELD e._dst")
        assert r.ok()
        assert st.session is not None or r.rows is not None

    def test_saturated_seat_map_widens_to_next_rung(self, nba):
        """Review regression: a seat map saturated with a backlog
        drains and re-anchors one batch-width rung wider (the same
        pinned ladder the windowed kernels use) instead of pinning
        every stream at the smallest rung forever."""
        c, g, ok = nba
        saved = flags.get("go_batch_widths")
        flags.set("go_batch_widths", "8,16")
        d = c.tpu_runtime.dispatcher
        try:
            # force any session earlier tests anchored on the default
            # ladder to re-anchor against the shrunk one
            for s in d.continuous.streams():
                s._widen = True
            ok("GO 2 STEPS FROM 1 OVER e")      # anchors at rung 8
            deadline = time.monotonic() + 5.0
            st = None
            while time.monotonic() < deadline:
                st = next((s for s in d.continuous.streams()
                           if s.session is not None
                           and s.session.B == 8), None)
                if st is not None:
                    break
                ok("GO 2 STEPS FROM 1 OVER e")
                time.sleep(0.05)
            assert st is not None, "stream never anchored at rung 8"
            st.tick_delay_s = 0.02              # hold lanes busy
            results = {}
            errors = []

            def worker(i):
                try:
                    g2 = c.client()
                    g2.execute("USE s")
                    r = g2.execute(f"GO 3 STEPS FROM {i % 30 + 1} "
                                   f"OVER e YIELD e._dst")
                    assert r.ok(), r.error_msg
                    results[i] = True
                except Exception as ex:         # noqa: BLE001
                    errors.append(ex)

            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(14)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            st.tick_delay_s = 0.0
            assert not errors, errors
            assert len(results) == 14
            # saturation must have forced (or anchored) a wider rung
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                sess = st.session
                if sess is not None and sess.B == 16:
                    break
                time.sleep(0.05)
            sess = st.session
            assert sess is not None and sess.B == 16, \
                (sess.B if sess else None)
        finally:
            flags.set("go_batch_widths", saved)
            # drop the off-ladder session so later tests re-anchor on
            # the restored rung ladder
            d = c.tpu_runtime.dispatcher
            for s in d.continuous.streams():
                s._widen = True

    @pytest.mark.slow
    def test_bench_legs_smoke(self, tmp_path):
        """Slow-marked smoke of the two BENCH_SUITE_r10 legs at tiny
        durations: the continuous-vs-windowed fixed-offered-load leg
        (device_idle_frac recorded per mode, no lane leak) and the
        1-vs-2-graphd horizontal leg (ratios recorded; the >=1.6x
        throughput acceptance is core-count-dependent — the JSON
        carries host_cores and a platform note on small hosts)."""
        from nebula_tpu.tools.bench_suite import (bench_continuous,
                                                  bench_horizontal)
        results: list = []
        bench_continuous(results, persons=800, duration_s=10.0,
                         offered_qps=40.0, workers=4)
        assert len(results) == 2
        modes = {r["dispatch_mode"]: r for r in results}
        assert modes["continuous"]["requests"] > 0
        assert modes["continuous"]["continuous_joins"] > 0
        assert modes["windowed"]["continuous_joins"] == 0
        assert modes["continuous"]["device_idle_frac"] is not None
        hz: list = []
        bench_horizontal(hz, duration_s=20.0, workers=6,
                         n_vertices=120, run_dir=str(tmp_path))
        assert len(hz) == 2
        assert hz[0]["graphds"] == 1 and hz[1]["graphds"] == 2
        assert hz[1]["errors"] == 0 and hz[1]["requests"] > 0
        assert "throughput_ratio" in hz[1]

    def test_windowed_fallback_for_ineligible_space(self, nba):
        """A space with no edges cannot anchor a session: the rider
        bounces to the windowed pipeline typed (ContinuousUnavailable
        never surfaces) and still gets its (empty) answer."""
        c, g, ok = nba
        ok("CREATE SPACE empty_sp(partition_num=1, replica_factor=1)")
        c.refresh_all()
        ok("USE empty_sp")
        ok("CREATE EDGE e2(w int)")
        c.refresh_all()
        r = ok("GO 2 STEPS FROM 1 OVER e2 YIELD e2._dst")
        assert r.rows == [] or list(r.rows) == []
        ok("USE s")
