"""Balancer tests — part movement + leader balance over a replicated
in-process cluster (reference BalanceIntegrationTest / BalanceTest,
SURVEY.md §3.5): BALANCE DATA moves replicas onto a newly added host via
addLearner → catch-up → memberChange → updateMeta → removePart; the plan
persists in the meta kvstore; BALANCE LEADER spreads raft leaders.
"""
import time

import pytest

from nebula_tpu.cluster import LocalCluster, StorageNode
from nebula_tpu.common.flags import flags
from nebula_tpu.interface.common import HostAddr
from nebula_tpu.meta import keys as mk
from nebula_tpu.meta.balancer import _unpk


@pytest.fixture(scope="module", autouse=True)
def fast_raft():
    saved = {n: flags.get(n) for n in
             ("raft_heartbeat_interval_s", "raft_election_timeout_s",
              "balance_catch_up_interval_s")}
    flags.set("raft_heartbeat_interval_s", 0.05)
    flags.set("raft_election_timeout_s", 0.3)
    flags.set("balance_catch_up_interval_s", 0.05)
    yield
    for k, v in saved.items():
        flags.set(k, v)


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_storage=3, use_raft=True)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def client(cluster):
    client = cluster.client()

    def ok(stmt):
        resp = client.execute(stmt)
        assert resp.ok(), f"{stmt}: {resp.error_msg}"
        return resp

    client.ok = ok
    ok("CREATE SPACE bal(partition_num=6, replica_factor=2)")
    cluster.refresh_all()
    _wait_leaders(cluster, 6)
    ok("USE bal")
    ok("CREATE TAG item(name string)")
    cluster.refresh_all()
    for i in range(1, 21):
        ok(f'INSERT VERTEX item(name) VALUES {i}:("item{i}")')
    yield client
    client.disconnect()


def _wait_leaders(cluster, space_parts, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        elected = sum(
            1 for node in cluster.storage_nodes
            if node.raft_service is not None
            for part in node.raft_service.status()
            if part["role"] == "LEADER")
        if elected >= space_parts:
            return
        time.sleep(0.05)
    raise AssertionError("raft groups failed to elect")


def _placement(cluster, space_id):
    out = {}
    for k, v in cluster.meta_service.kv.prefix(
            0, 0, mk.part_prefix(space_id)):
        out[mk.part_id_from_key(k)] = list(_unpk(v))
    return out


def test_balance_moves_parts_to_new_host(cluster, client):
    # grow the fleet: node 3 joins and heartbeats
    new_host = "127.0.0.1:44503"
    cluster.meta_service.rpc_heartBeat({"host": new_host})
    node = StorageNode(new_host, [cluster.meta_addr], cluster.cm,
                       use_raft=True)
    cluster.cm.register_loopback(HostAddr.parse(new_host), node.handler)
    cluster.storage_nodes.append(node)
    cluster.storage_hosts.append(new_host)

    sid = cluster.meta_service.rpc_getSpace({"space_name": "bal"})["id"]
    before = _placement(cluster, sid)
    assert all(new_host not in peers for peers in before.values())

    resp = cluster.meta_service.rpc_balance({})
    plan_id = resp["plan_id"]
    cluster.meta_service.balancer.join(timeout=30.0)

    show = cluster.meta_service.rpc_balance({"plan_id": plan_id})
    assert show["plan_status"] == "SUCCEEDED", show
    assert all(t["status"] == "SUCCEEDED" for t in show["tasks"]), show

    after = _placement(cluster, sid)
    moved = [p for p, peers in after.items() if new_host in peers]
    assert moved, after

    # data still all there through the query path
    cluster.refresh_all()
    resp = client.ok("FETCH PROP ON item 1 YIELD item.name")
    assert resp.rows and resp.rows[0][-1] == "item1"

    # balanced now: a second BALANCE reports E_BALANCED
    from nebula_tpu.interface.rpc import RpcError
    with pytest.raises(RpcError):
        cluster.meta_service.rpc_balance({})


def test_leader_balance_smoke(cluster, client):
    resp = cluster.meta_service.rpc_leaderBalance({})
    assert "moved" in resp


def test_plan_persisted_in_meta_kv(cluster):
    plans = list(cluster.meta_service.kv.prefix(
        0, 0, mk.BALANCE_PLAN_PREFIX))
    assert plans, "balance plan must be persisted for crash recovery"
