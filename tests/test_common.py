"""Unit tests for the common layer (keys, status, stats, clock).

Modeled on the reference's base/test suite (NebulaKeyUtilsTest.cpp,
StatsManagerTest.cpp — SURVEY.md §4 unit tier).
"""
import time

from nebula_tpu.common.clock import Duration, inverted_version, now_micros
from nebula_tpu.common.keys import KeyUtils, id_hash
from nebula_tpu.common.stats import StatsManager
from nebula_tpu.common.status import ErrorCode, Status, StatusOr


class TestKeys:
    def test_vertex_roundtrip(self):
        k = KeyUtils.vertex_key(7, 12345, 3, 999)
        assert KeyUtils.is_vertex(k) and not KeyUtils.is_edge(k)
        assert KeyUtils.parse_vertex(k) == (7, 12345, 3, 999)

    def test_edge_roundtrip_negative(self):
        k = KeyUtils.edge_key(1, -42, -100, -5, 17, 3)
        assert KeyUtils.is_edge(k)
        assert KeyUtils.parse_edge(k) == (1, -42, -100, -5, 17, 3)

    def test_lexicographic_equals_logical_order(self):
        # (src, etype, rank, dst, version) ordering under byte compare
        keys = [
            KeyUtils.edge_key(1, 1, 2, 0, 5, 9),
            KeyUtils.edge_key(1, 1, 2, 0, 6, 1),
            KeyUtils.edge_key(1, 1, 2, 1, 0, 0),
            KeyUtils.edge_key(1, 1, 3, -1, 0, 0),
            KeyUtils.edge_key(1, 2, -9, 0, 0, 0),
        ]
        assert keys == sorted(keys)

    def test_version_inversion_latest_first(self):
        t0 = inverted_version(1000)
        t1 = inverted_version(2000)
        k_old = KeyUtils.vertex_key(1, 1, 1, t0)
        k_new = KeyUtils.vertex_key(1, 1, 1, t1)
        assert k_new < k_old  # newer sorts first in scans

    def test_prefixes(self):
        k = KeyUtils.edge_key(3, 10, 5, 2, 20, 1)
        assert k.startswith(KeyUtils.part_prefix(3))
        assert k.startswith(KeyUtils.edge_prefix(3, 10))
        assert k.startswith(KeyUtils.edge_prefix(3, 10, 5))
        assert k.startswith(KeyUtils.edge_prefix(3, 10, 5, 2))
        assert not k.startswith(KeyUtils.edge_prefix(3, 11))

    def test_id_hash_range(self):
        for vid in (0, 1, -1, 2**62, -(2**62), 123456789):
            p = id_hash(vid, 100)
            assert 1 <= p <= 100
        # deterministic
        assert id_hash(42, 10) == id_hash(42, 10)


class TestStatus:
    def test_ok_singleton(self):
        assert Status.OK().ok()
        assert Status.OK() is Status.OK()

    def test_error(self):
        s = Status.SyntaxError("bad token")
        assert not s.ok()
        assert s.code == ErrorCode.E_SYNTAX_ERROR
        assert "bad token" in s.to_string()

    def test_status_or(self):
        v = StatusOr.of(42)
        assert v.ok() and v.value() == 42
        e = StatusOr.error(Status.NotFound())
        assert not e.ok()
        assert e.value_or(7) == 7


class TestStats:
    def test_counter_windows(self):
        m = StatsManager()
        m.register_stats("rpc.latency")
        now = time.time()
        for v in (10, 20, 30):
            m._stats["rpc.latency"].add(v, now)
        assert m.read_stats("rpc.latency.sum.60", now) == 60
        assert m.read_stats("rpc.latency.count.5", now) == 3
        assert m.read_stats("rpc.latency.avg.60", now) == 20
        assert m.read_stats("rpc.latency.rate.60", now) == 1.0

    def test_percentiles(self):
        m = StatsManager()
        now = time.time()
        for v in range(1, 101):
            m.add_value("lat", v)
        p50 = m.read_stats("lat.p50.60")
        assert 45 <= p50 <= 55
        p99 = m.read_stats("lat.p99.60")
        assert p99 >= 95

    def test_bad_exprs(self):
        m = StatsManager()
        assert m.read_stats("nope.sum.60") is None
        m.add_value("x", 1)
        assert m.read_stats("x.sum.61") is None
        assert m.read_stats("x.wat.60") is None

    def test_dump_exposes_tail_percentiles(self):
        m = StatsManager()
        m.register_stats("lat")
        now = time.time()
        for v in range(1, 101):
            m._stats["lat"].add(v, now)
        d = m.dump(now)["lat"]
        assert d["count.60"] == 100.0
        assert 90 <= d["p95.60"] <= 96
        assert d["p99.60"] >= d["p95.60"]
        # empty reservoir: percentile columns present but zero
        m.register_stats("idle")
        assert m.dump(now)["idle"]["p95.60"] == 0.0

    def test_ring_wrap_stale_bucket_not_leaked(self):
        """A bucket whose stamp is exactly _RING (3600) seconds stale
        lands on the SAME ring index as `now` — window() must see the
        stamp mismatch and skip it, and add() must reset it."""
        m = StatsManager()
        m.register_stats("w")
        st = m._stats["w"]
        now = 1_700_000_000.0
        st.add(7, now)
        assert m.read_stats("w.sum.60", now) == 7
        # one full ring later: same index, stale stamp — no leak in any
        # window, including the full 3600 s one
        later = now + 3600
        assert m.read_stats("w.sum.60", later) == 0
        assert m.read_stats("w.count.3600", later) == 0.0
        assert m.read_stats("w.p99.60", later) == 0.0
        # writing at the wrapped second resets the bucket rather than
        # accumulating onto the stale sums
        st.add(3, later)
        total, count, vals = st.window(60, later)
        assert (total, count, vals) == (3.0, 1, [3])

    def test_ring_wrap_resets_window_minmax(self):
        """Companion to the ring-wrap test for the new per-bucket
        min/max columns: a wrapped bucket's extremes must not leak
        into the fresh second, and dump()'s min.60/max.60 must track
        the reset values (exact, not reservoir-sampled)."""
        m = StatsManager()
        m.register_stats("w")
        st = m._stats["w"]
        now = 1_700_000_000.0
        st.add(7, now)
        st.add(999, now)
        d = m.dump(now)["w"]
        assert (d["min.60"], d["max.60"]) == (7.0, 999.0)
        later = now + 3600
        d = m.dump(later)["w"]
        assert (d["min.60"], d["max.60"]) == (0.0, 0.0)   # empty window
        st.add(3, later)
        d = m.dump(later)["w"]
        assert (d["min.60"], d["max.60"]) == (3.0, 3.0)
        assert (d["count.60"], d["sum.60"]) == (1.0, 3.0)

    def test_dump_histogram_count_sum_min_max(self):
        """Satellite regression: dump() carries count/sum/min/max per
        stat (histograms included), and the cumulative Prometheus cells
        survive window expiry — buckets are since-start, windows slide."""
        m = StatsManager()
        m.register_histogram("h", buckets=(10, 100))
        now = time.time()
        for v in (5, 50, 500):
            m._stats["h"].add(v, now)
        d = m.dump(now)["h"]
        assert d["count.60"] == 3.0 and d["sum.60"] == 555.0
        assert d["min.60"] == 5.0 and d["max.60"] == 500.0
        # an hour later the window is empty but the cumulative cell
        # (what /metrics exposes) still counts everything
        d2 = m.dump(now + 3600)["h"]
        assert d2["count.60"] == 0.0
        cell = m._stats["h"].cells[()]
        assert cell.count == 3 and cell.sum == 555.0
        assert cell.min == 5 and cell.max == 500


class TestClock:
    def test_duration(self):
        d = Duration()
        time.sleep(0.01)
        assert d.elapsed_in_usec() >= 9000

    def test_now(self):
        a = now_micros()
        assert a > 1_600_000_000_000_000


def test_status_hashable():
    assert len({Status.OK(), Status.OK(), Status.NotFound()}) == 2


def test_edge_prefix_noncontiguous_rejected():
    import pytest
    with pytest.raises(ValueError):
        KeyUtils.edge_prefix(1, 2, None, rank=5)
