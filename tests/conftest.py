"""Test config: force an 8-device virtual CPU mesh so multi-chip sharding
paths (frontier all_to_all/psum over a Mesh) run without TPU hardware.

Mirrors the reference's strategy of in-process multi-instance harnesses
(SURVEY.md §4): our "cluster" tests also run all daemons in one process.
"""
import os
import sys

# Must happen before jax is imported anywhere.  FORCE (not setdefault):
# terminal environments ship a sitecustomize that registers a remote
# TPU platform and pins jax_platforms via jax.config — the env var
# alone is overridden, which silently degraded the "8 virtual device"
# mesh tests to 1-device axes on the remote chip.  The config update
# below wins because backends initialize lazily (first jax.devices()),
# which hasn't happened at conftest import time.
os.environ["JAX_PLATFORMS"] = "cpu"
# Persistent compile cache: kernel-shape compiles dominate suite wall
# time; warm reruns skip them (same mechanism serving uses, jax_setup.py)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "nebula_tpu",
                 "xla-tests"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    import jax
    jax.config.update("jax_platforms", "cpu")
    assert jax.devices()[0].platform == "cpu", jax.devices()
except ImportError:
    pass

# Build the native library once per test session (engine default is
# "auto": C++ engine when built, MemEngine otherwise).
try:
    from nebula_tpu.native import ensure_built
    ensure_built()
except Exception:    # noqa: BLE001 — tests fall back to the Python paths
    pass

import pytest  # noqa: E402

# The multi-daemon suites exercise the real 19-thread mesh — run them
# under the lock-order watchdog (common/ordered_lock.py, the runtime
# half of nebulint's static lock-order check) and fail the test if the
# observed acquisition graph ever contains a cycle.
# (test_raftex.py is excluded: its adaptive-pipelining tests assert
# sub-millisecond replication RTTs that per-acquire bookkeeping skews)
_WATCHDOG_FILES = ("test_chaos.py", "test_cluster_replicated.py",
                   "test_metad_replicated.py", "test_proc_chaos.py")


@pytest.fixture(autouse=True)
def _lock_order_watchdog(request):
    fspath = getattr(request.node, "fspath", None)
    if fspath is None or os.path.basename(str(fspath)) not in _WATCHDOG_FILES:
        yield
        return
    from nebula_tpu.common.ordered_lock import watchdog
    was_enabled = watchdog.enabled   # NEBULA_LOCK_WATCHDOG=1 session?
    watchdog.enable()
    try:
        yield
        violations = watchdog.drain()
        assert not violations, (
            "lock-order inversions observed:\n" + "\n".join(violations))
    finally:
        # restore rather than unconditionally disable: an env-var
        # session-wide enable must survive past the first fixture use
        if not was_enabled:
            watchdog.disable()
