"""Daemon wiring + console + importer + webservice + perf-tool tests.

The reference covers this tier with process-level scripts (scripts/
services.sh) and the console's CmdProcessor; here the three daemon
builders are exercised in-process over real TCP sockets (the daemons'
serve_forever loop is signal-driven, so tests use the same build/wiring
functions the mains use).
"""
import io
import json
import os
import threading
import urllib.request

import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.console.repl import Console, render_table
from nebula_tpu.interface.common import HostAddr
from nebula_tpu.webservice import WebService
from nebula_tpu.common.stats import stats


@pytest.fixture(scope="module")
def tcp_cluster():
    c = LocalCluster(num_storage=1, use_tcp=True)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def seeded(tcp_cluster):
    client = tcp_cluster.client()
    for stmt in [
        "CREATE SPACE toolspace(partition_num=3)",
    ]:
        assert client.execute(stmt).ok()
    tcp_cluster.refresh_all()
    assert client.execute("USE toolspace").ok()
    assert client.execute("CREATE TAG person(name string, age int)").ok()
    assert client.execute("CREATE EDGE likes(w int)").ok()
    tcp_cluster.refresh_all()
    return tcp_cluster


class TestConsole:
    def test_render_table(self):
        class R:
            column_names = ["id", "name"]
            rows = [[1, "alice"], [2, "bob"]]
            latency_in_us = 42
        out = render_table(R())
        assert "| id | name  |" in out
        assert "| 1  | alice |" in out
        assert "Got 2 rows" in out

    def test_console_statements_and_batch(self, seeded, tmp_path):
        con = Console(seeded.graph_addr)
        out = io.StringIO()
        assert con.run_statement("USE toolspace", out=out)
        assert con.run_statement(
            'INSERT VERTEX person(name, age) VALUES 7:("carl", 33)',
            out=out)
        assert con.run_statement(
            "FETCH PROP ON person 7 YIELD person.name, person.age",
            out=out)
        text = out.getvalue()
        assert "carl" in text and "33" in text
        # :batch file
        script = tmp_path / "batch.ngql"
        script.write_text("USE toolspace\n"
                          'INSERT VERTEX person(name, age) VALUES '
                          '8:("dora", 44)\n')
        out2 = io.StringIO()
        assert con.run_statement(f":batch {script}", out=out2)
        out3 = io.StringIO()
        con.run_statement("FETCH PROP ON person 8 YIELD person.name",
                          out=out3)
        assert "dora" in out3.getvalue()
        # exit commands terminate
        assert con.run_statement("exit") is False
        # error path prints [ERROR
        out4 = io.StringIO()
        con2 = Console(seeded.graph_addr)
        con2.run_statement("GO GO GADGET", out=out4)
        assert "[ERROR" in out4.getvalue()


class TestImporter:
    def test_csv_vertex_and_edge_import(self, seeded, tmp_path):
        from nebula_tpu.tools.importer import Importer
        vfile = tmp_path / "people.csv"
        vfile.write_text("100,eve,25\n101,frank,31\n102,grace,29\n")
        efile = tmp_path / "likes.csv"
        efile.write_text("100,101,5\n101,102,9\n")
        client = seeded.client()
        imp = Importer(client, "toolspace", batch_size=2)
        import csv
        with open(vfile, newline="") as f:
            n = imp.load_vertices(csv.reader(f), "person", ["name", "age"])
        assert n == 3
        with open(efile, newline="") as f:
            n = imp.load_edges(csv.reader(f), "likes", ["w"])
        assert n == 2
        resp = client.execute(
            "GO FROM 100 OVER likes YIELD likes._dst, likes.w")
        assert resp.ok()
        assert [list(r) for r in resp.rows] == [[101, 5]]

    def test_numeric_looking_string_stays_string(self, seeded, tmp_path):
        """Schema-driven quoting: a string prop valued '007' must not be
        coerced to the integer 7 (DESCRIBE drives the quoting)."""
        from nebula_tpu.tools.importer import Importer
        vfile = tmp_path / "agents.csv"
        vfile.write_text("200,007,35\n201,true,41\n")
        client = seeded.client()
        imp = Importer(client, "toolspace")
        import csv
        with open(vfile, newline="") as f:
            assert imp.load_vertices(csv.reader(f), "person",
                                     ["name", "age"]) == 2
        resp = client.execute("FETCH PROP ON person 200 YIELD person.name")
        assert resp.ok() and resp.rows[0][-1] == "007"
        resp = client.execute("FETCH PROP ON person 201 YIELD person.name")
        assert resp.ok() and resp.rows[0][-1] == "true"


class TestWebService:
    def test_status_flags_stats(self):
        ws = WebService("testd").start()
        base = f"http://127.0.0.1:{ws.port}"
        try:
            st = json.load(urllib.request.urlopen(f"{base}/status"))
            assert st["status"] == "running" and st["name"] == "testd"

            fl = json.load(urllib.request.urlopen(f"{base}/flags"))
            assert "heartbeat_interval_secs" in fl

            one = json.load(urllib.request.urlopen(
                f"{base}/flags?names=heartbeat_interval_secs"))
            assert list(one) == ["heartbeat_interval_secs"]

            # runtime flag write (MUTABLE)
            req = urllib.request.Request(
                f"{base}/flags?name=max_handlers_per_req&value=7",
                method="PUT")
            json.load(urllib.request.urlopen(req))
            from nebula_tpu.common.flags import flags
            assert flags.get("max_handlers_per_req") == 7
            flags.set("max_handlers_per_req", 10)

            stats.add_value("web.test.counter", 5)
            got = json.load(urllib.request.urlopen(f"{base}/get_stats"))
            assert any("web.test.counter" in k for k in got)
            # tail-latency columns from the sample reservoirs
            assert got["web.test.counter"]["p95.60"] == 5.0
            assert got["web.test.counter"]["p99.60"] == 5.0
            txt = urllib.request.urlopen(
                f"{base}/get_stats?format=text").read().decode()
            assert "web.test.counter" in txt

            try:
                urllib.request.urlopen(f"{base}/nope")
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            ws.stop()


class TestDaemonBuilders:
    def test_metad_build_and_flagfile(self, tmp_path):
        from nebula_tpu.daemons.common import load_flagfile
        from nebula_tpu.common.flags import flags
        conf = tmp_path / "metad.conf"
        conf.write_text("# comment\n--heartbeat_interval_secs=3\n")
        load_flagfile(str(conf))
        assert flags.get("heartbeat_interval_secs") in (3, "3")
        flags.set("heartbeat_interval_secs", 10)

    def test_three_daemon_tcp_boot(self, tmp_path):
        """metad + storaged + graphd over real sockets, console on top."""
        import argparse
        from nebula_tpu.daemons import metad
        from nebula_tpu.interface.rpc import ClientManager, RpcServer
        from nebula_tpu.cluster import StorageNode
        from nebula_tpu.graph.service import ExecutionEngine, GraphService
        from nebula_tpu.meta.client import MetaClient
        from nebula_tpu.meta.schema_manager import ServerBasedSchemaManager
        from nebula_tpu.storage.client import StorageClient

        margs = argparse.Namespace(local_ip="127.0.0.1", port=0,
                                   meta_server_addrs="127.0.0.1:0",
                                   wal_path=None)
        meta_service, _cm, meta_handler, _raft = metad.build(margs)
        meta_rpc = RpcServer(meta_handler).start()

        cm = ClientManager()
        storage_rpc = RpcServer(None).start()
        shost = f"127.0.0.1:{storage_rpc.addr.port}"
        meta_service.rpc_heartBeat({"host": shost})
        node = StorageNode(shost, [meta_rpc.addr], cm)
        storage_rpc.handler = node.handler

        meta_client = MetaClient([meta_rpc.addr], client_manager=cm)
        meta_client.wait_for_metad_ready()
        engine = ExecutionEngine(meta_client,
                                 ServerBasedSchemaManager(meta_client),
                                 StorageClient(meta_client,
                                               client_manager=cm))
        graph = GraphService(engine)
        graph_rpc = RpcServer(graph).start()

        con = Console(graph_rpc.addr)
        out = io.StringIO()
        con.run_statement("CREATE SPACE dspace(partition_num=2)", out=out)
        node.meta_client.load_data()
        meta_client.load_data()
        con.run_statement("USE dspace", out=out)
        con.run_statement("CREATE TAG t(x int)", out=out)
        node.meta_client.load_data()
        meta_client.load_data()
        con.run_statement('INSERT VERTEX t(x) VALUES 5:(55)', out=out)
        con.run_statement("FETCH PROP ON t 5 YIELD t.x", out=out)
        assert "55" in out.getvalue()
        assert "[ERROR" not in out.getvalue(), out.getvalue()

        for srv in (graph_rpc, storage_rpc, meta_rpc):
            srv.stop()
        node.stop()
        graph.sessions.stop()
        meta_client.stop()


class TestStoragePerfTool:
    def test_perf_runner_inprocess(self):
        from nebula_tpu.tools.perf_fixture import build_inprocess, vertex, edge
        from nebula_tpu.tools.storage_perf import PerfRunner
        cluster, sc, sid, tag_id, etype = build_inprocess()
        try:
            sc.add_vertices(sid, [vertex(1000 + i, tag_id, i)
                                  for i in range(1, 20)])
            sc.add_edges(sid, [edge(1000 + i, etype, 1000 + i % 19 + 1, i)
                               for i in range(1, 20)])
            r = PerfRunner(sc, sid, "getNeighbors", qps=0, total=50,
                           threads=2, tag_id=tag_id, etype=etype).run()
            assert r["requests"] == 50
            assert r["p50_us"] > 0
            w = PerfRunner(sc, sid, "addVertices", qps=0, total=30,
                           threads=2, tag_id=tag_id, etype=etype).run()
            assert w["requests"] == 30
        finally:
            cluster.stop()


def test_show_create_and_roles_end_to_end():
    """SHOW CREATE TAG/EDGE/SPACE, SHOW USER, SHOW ROLES IN through a
    live cluster (executor halves of the reference-syntax parity)."""
    from nebula_tpu.cluster import LocalCluster
    c = LocalCluster(num_storage=1)
    g = c.client()

    def ok(stmt):
        r = g.execute(stmt)
        assert r.ok(), f"{stmt}: {r.error_msg}"
        return r

    ok("CREATE SPACE sc(partition_num=3, replica_factor=1)")
    c.refresh_all()
    ok("USE sc")
    ok("CREATE TAG person(name string, age int) ttl_duration = 100, "
       "ttl_col = age")
    ok("CREATE EDGE likes(w double)")
    c.refresh_all()

    r = ok("SHOW CREATE TAG person")
    assert r.rows[0][0] == "person"
    assert "CREATE TAG person(name string, age int)" in r.rows[0][1]
    assert "ttl_duration = 100" in r.rows[0][1]
    r = ok("SHOW CREATE EDGE likes")
    assert "CREATE EDGE likes(w double)" in r.rows[0][1]
    r = ok("SHOW CREATE SPACE sc")
    assert "partition_num=3" in r.rows[0][1]

    ok("CREATE USER alice WITH PASSWORD \"pw\"")
    ok("GRANT ROLE ADMIN ON sc TO alice")
    r = ok("SHOW USER alice")
    assert r.rows == [["alice"]]
    r = ok("SHOW ROLES IN sc")
    assert ["alice", "ADMIN"] in [list(x) for x in r.rows]

    # nameless DELETE EDGE across etypes
    ok('INSERT EDGE likes(w) VALUES 1->2:(0.5)')
    r = ok("GO FROM 1 OVER likes")
    assert len(r.rows) == 1
    ok("DELETE EDGE 1 -> 2")
    r = ok("GO FROM 1 OVER likes")
    assert len(r.rows) == 0
    c.stop()


def test_delete_with_where_refuses():
    """DELETE ... WHERE parses (reference grammar) but must refuse at
    execution rather than deleting unconditionally."""
    from nebula_tpu.cluster import LocalCluster
    c = LocalCluster(num_storage=1)
    g = c.client()
    assert g.execute("CREATE SPACE dw(partition_num=1, replica_factor=1)").ok()
    c.refresh_all()
    assert g.execute("USE dw").ok()
    assert g.execute("CREATE EDGE e(w int)").ok()
    c.refresh_all()
    assert g.execute("INSERT EDGE e(w) VALUES 1->2:(5)").ok()
    r = g.execute("DELETE EDGE 1 -> 2 WHERE w > 3")
    assert not r.ok() and "not supported" in r.error_msg
    # nothing was deleted
    assert len(g.execute("GO FROM 1 OVER e").rows) == 1
    r = g.execute("DELETE VERTEX 1 WHERE w > 3")
    assert not r.ok() and "not supported" in r.error_msg
    c.stop()


def test_ldbc_gen_load_and_query(tmp_path):
    """ldbc-gen: generate a community-clustered graph, write CSVs, load
    a cluster, and check TPU/CPU GO parity over the loaded data."""
    from nebula_tpu.cluster import LocalCluster
    from nebula_tpu.common.flags import flags
    from nebula_tpu.tools import ldbc_gen

    src, dst, props = ldbc_gen.generate(300, seed=3)
    assert len(src) and (src != dst).all()
    ppath, kpath = ldbc_gen.write_csv(str(tmp_path), src, dst, props)
    assert sum(1 for _ in open(ppath)) == 301        # header + rows
    assert sum(1 for _ in open(kpath)) == len(src) + 1

    c = LocalCluster(num_storage=1, tpu_backend=True)
    try:
        ldbc_gen.load_cluster(c, "ldbc", src, dst, props, batch=512)
        g = c.client()
        assert g.execute("USE ldbc").ok()
        q = ("GO 2 STEPS FROM 1 OVER knows WHERE $$.person.birthday > 4000 "
             "YIELD knows._dst, $$.person.firstName")
        r_tpu = g.execute(q)
        assert r_tpu.ok(), r_tpu.error_msg
        prev = flags.get("storage_backend")
        flags.set("storage_backend", "cpu")
        try:
            r_cpu = g.execute(q)
        finally:
            flags.set("storage_backend", prev)
        assert sorted(map(tuple, r_tpu.rows)) == sorted(map(tuple, r_cpu.rows))
        assert c.tpu_runtime.stats["go_device"] >= 1
    finally:
        c.stop()


def test_services_sh_cluster(tmp_path):
    """scripts/services.sh boots real metad/storaged/graphd processes
    (the reference's services.sh equivalent) and a client can run the
    full DDL+DML+GO flow against them."""
    import os
    import subprocess
    import time
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ,
               NEBULA_HOME=repo,
               NEBULA_DATA=str(tmp_path / "data"),
               NEBULA_LOGS=str(tmp_path / "logs"),
               JAX_PLATFORMS="cpu",
               META_PORT="45611", STORAGE_PORT="44611", GRAPH_PORT="3799",
               STORAGE_WS_PORT="12611",
               EXTRA_FLAGS="--flag load_data_interval_secs=1")
    sh = os.path.join(repo, "scripts", "services.sh")

    # a previous timed-out run may have leaked daemons whose pidfiles
    # died with its tmp dir — sweep them so this run starts clean
    import signal
    ps = subprocess.run(["ps", "-eo", "pid,args"], capture_output=True,
                        text=True).stdout
    for line in ps.splitlines():
        if "nebula_tpu.daemons" in line:
            try:
                os.kill(int(line.split()[0]), signal.SIGKILL)
            except (ProcessLookupError, ValueError, PermissionError):
                pass
    # file-redirected Popen: the launcher must never share pipes with
    # the daemons it spawns (a capture_output pipe held open by any
    # descendant would block communicate() until the daemons die)
    start_log = tmp_path / "start.log"
    with open(start_log, "w") as lf:
        p = subprocess.Popen(["bash", sh, "start", "all"], env=env,
                             stdout=lf, stderr=lf,
                             stdin=subprocess.DEVNULL)
        rc = p.wait(timeout=420)
    try:
        assert rc == 0, start_log.read_text()
        time.sleep(2)
        from nebula_tpu.clients.graph_client import GraphClient
        from nebula_tpu.interface.common import HostAddr
        from nebula_tpu.interface.rpc import ClientManager
        c = GraphClient(HostAddr("127.0.0.1", 3799),
                        client_manager=ClientManager())
        deadline = time.time() + 30
        while time.time() < deadline:
            if c.connect().ok():
                break
            time.sleep(0.5)
        assert c.execute("CREATE SPACE IF NOT EXISTS "
                         "svc(partition_num=2, replica_factor=1)").ok()
        time.sleep(2.5)
        assert c.execute("USE svc; CREATE EDGE e(w int)").ok()
        time.sleep(2.5)
        rr = c.execute("USE svc; INSERT EDGE e(w) VALUES 1->2:(5)")
        assert rr.ok(), rr.error_msg
        rr = c.execute("USE svc; GO FROM 1 OVER e YIELD e._dst, e.w")
        assert rr.ok() and [list(x) for x in rr.rows] == [[2, 5]]

        # ---- device path across the real process boundary -----------
        # (VERDICT round-1 item 2: graphd ships the whole GO to
        # storaged's device runtime; the storaged-side counter visible
        # on /get_stats proves the device served it, and the rows match
        # the CPU path's answer for this fixture)
        rr = c.execute("USE svc; INSERT EDGE e(w) VALUES "
                       "2->3:(7), 3->4:(9), 2->4:(1)")
        assert rr.ok(), rr.error_msg
        rr = c.execute("USE svc; GO 3 STEPS FROM 1 OVER e "
                       "YIELD e._src, e._dst, e.w")
        assert rr.ok(), rr.error_msg
        assert sorted(map(tuple, rr.rows)) == [(3, 4, 9)]
        got = json.loads(urllib.request.urlopen(
            "http://127.0.0.1:12611/get_stats?stats="
            "storage.device_go.qps.count.3600", timeout=10).read())
        assert got.get("storage.device_go.qps.count.3600", 0) >= 1, got
        # FIND PATH rides the device too
        rr = c.execute("USE svc; FIND SHORTEST PATH FROM 1 TO 4 OVER e "
                       "UPTO 5 STEPS")
        assert rr.ok(), rr.error_msg
        assert rr.rows and "1" in rr.rows[0][0]
        got = json.loads(urllib.request.urlopen(
            "http://127.0.0.1:12611/get_stats?stats="
            "storage.device_path.qps.count.3600", timeout=10).read())
        assert got.get("storage.device_path.qps.count.3600", 0) >= 1, got
    finally:
        with open(tmp_path / "stop.log", "w") as lf:
            subprocess.Popen(["bash", sh, "stop", "all"], env=env,
                             stdout=lf, stderr=lf,
                             stdin=subprocess.DEVNULL).wait(timeout=60)


def test_meta_dispatched_bulk_load(tmp_path):
    """metad /download-dispatch + /ingest-dispatch fan bulk-load files
    out to EVERY storaged's web endpoints (reference
    MetaHttpDownloadHandler/MetaHttpIngestHandler): two storage nodes
    each stage from a shared source dir and ingest, and the loaded
    edges answer a real GO afterwards."""
    import struct
    from nebula_tpu.common.clock import inverted_version
    from nebula_tpu.common.keys import KeyUtils, id_hash
    from nebula_tpu.codec.rows import encode_row
    from nebula_tpu.interface.common import ColumnDef, Schema, SupportedType
    from nebula_tpu.meta.http_dispatch import register_dispatch_handlers
    from nebula_tpu.storage.web import register_web_handlers

    c = LocalCluster(num_storage=2, use_tcp=True,
                     data_paths=[str(tmp_path / "data")])
    web_services = []
    try:
        client = c.client()
        assert client.execute("CREATE SPACE bulk(partition_num=4, "
                              "replica_factor=1)").ok()
        c.refresh_all()
        assert client.execute("USE bulk; CREATE EDGE e(w int)").ok()
        c.refresh_all()
        space_id = c.graph_meta_client.get_space_id_by_name("bulk").value()
        etype = c.graph_meta_client.get_edge_type(space_id, "e").value()

        # per-node web services + ws_port registration via heartbeat info
        for node in c.storage_nodes:
            ws = WebService("storaged-test", host="127.0.0.1").start()
            register_web_handlers(ws, node)
            web_services.append(ws)
            node.meta_client.hb_info["ws_port"] = ws.port
            node.meta_client.heartbeat()
        meta_ws = WebService("metad-test", host="127.0.0.1").start()
        web_services.append(meta_ws)
        register_dispatch_handlers(meta_ws, c.meta_service)

        # build a bulk-load snapshot: 40 edges 1 -> (100..139)
        schema = Schema(columns=[ColumnDef("w", SupportedType.INT)])
        frame = struct.Struct(">II")
        src_dir = tmp_path / "bulk_src"
        src_dir.mkdir()
        kvs = []
        for i in range(40):
            part = id_hash(1, 4)
            key = KeyUtils.edge_key(part, 1, etype, 0, 100 + i,
                                    inverted_version())
            kvs.append((key, encode_row(schema, {"w": i})))
        kvs.sort()
        with open(src_dir / "edges.snap", "wb") as f:
            for k, v in kvs:
                f.write(frame.pack(len(k), len(v)))
                f.write(k)
                f.write(v)

        def get(url):
            return json.loads(urllib.request.urlopen(url, timeout=60).read())

        base = f"http://127.0.0.1:{meta_ws.port}"
        r = get(f"{base}/download-dispatch?space={space_id}"
                f"&url=file://{src_dir}")
        assert r["ok"], r
        assert len(r["hosts"]) == 2
        r = get(f"{base}/ingest-dispatch?space={space_id}")
        assert r["ok"], r

        resp = client.execute("USE bulk; GO FROM 1 OVER e YIELD e._dst")
        assert resp.ok(), resp.error_msg
        assert sorted(x[0] for x in resp.rows) == [100 + i
                                                   for i in range(40)]
    finally:
        for ws in web_services:
            ws.stop()
        c.stop()


def test_download_ingest_statements(tmp_path):
    """The nGQL ``DOWNLOAD HDFS "..."`` / ``INGEST`` statements reach
    metad as the ``download``/``ingest`` RPCs (regression: wirecheck's
    first run found the executors calling methods NO handler served —
    the statements could only fail while the web-dispatch path worked)."""
    import struct
    from nebula_tpu.common.clock import inverted_version
    from nebula_tpu.common.keys import KeyUtils, id_hash
    from nebula_tpu.codec.rows import encode_row
    from nebula_tpu.interface.common import ColumnDef, Schema, SupportedType
    from nebula_tpu.storage.web import register_web_handlers

    c = LocalCluster(num_storage=1, use_tcp=True,
                     data_paths=[str(tmp_path / "data")])
    web_services = []
    try:
        client = c.client()
        assert client.execute("CREATE SPACE bulks(partition_num=4, "
                              "replica_factor=1)").ok()
        c.refresh_all()
        assert client.execute("USE bulks; CREATE EDGE e(w int)").ok()
        c.refresh_all()
        space_id = c.graph_meta_client.get_space_id_by_name(
            "bulks").value()
        etype = c.graph_meta_client.get_edge_type(space_id, "e").value()

        for node in c.storage_nodes:
            ws = WebService("storaged-test", host="127.0.0.1").start()
            register_web_handlers(ws, node)
            web_services.append(ws)
            node.meta_client.hb_info["ws_port"] = ws.port
            node.meta_client.heartbeat()

        schema = Schema(columns=[ColumnDef("w", SupportedType.INT)])
        frame = struct.Struct(">II")
        src_dir = tmp_path / "stmt_src"
        src_dir.mkdir()
        kvs = []
        for i in range(12):
            part = id_hash(1, 4)
            key = KeyUtils.edge_key(part, 1, etype, 0, 200 + i,
                                    inverted_version())
            kvs.append((key, encode_row(schema, {"w": i})))
        kvs.sort()
        with open(src_dir / "edges.snap", "wb") as f:
            for k, v in kvs:
                f.write(frame.pack(len(k), len(v)))
                f.write(k)
                f.write(v)

        r = client.execute(f'USE bulks; DOWNLOAD HDFS "file://{src_dir}"')
        assert r.ok(), r.error_msg
        r = client.execute("USE bulks; INGEST")
        assert r.ok(), r.error_msg

        resp = client.execute("USE bulks; GO FROM 1 OVER e YIELD e._dst")
        assert resp.ok(), resp.error_msg
        assert sorted(x[0] for x in resp.rows) == [200 + i
                                                   for i in range(12)]
    finally:
        for ws in web_services:
            ws.stop()
        c.stop()


def test_hdfs_download_shells_out(tmp_path, monkeypatch):
    """hdfs:// download urls shell out to `hdfs dfs -get` exactly like
    the reference (HdfsCommandHelper.h) — driven here through a fake
    hdfs binary on PATH (the reference's MockHdfsHelper strategy), and
    the staged file ingests + serves a real GO."""
    import os as _os
    import stat
    from nebula_tpu.storage.web import _download, _ingest

    c = LocalCluster(num_storage=1, use_tcp=False,
                     data_paths=[str(tmp_path / "data")])
    try:
        client = c.client()
        assert client.execute("CREATE SPACE h(partition_num=2, "
                              "replica_factor=1)").ok()
        c.refresh_all()
        assert client.execute("USE h; CREATE EDGE e(w int)").ok()
        c.refresh_all()
        space_id = c.graph_meta_client.get_space_id_by_name("h").value()
        etype = c.graph_meta_client.get_edge_type(space_id, "e").value()

        # snapshot source the fake hdfs will "fetch"
        import struct
        from nebula_tpu.common.clock import inverted_version
        from nebula_tpu.common.keys import KeyUtils, id_hash
        from nebula_tpu.codec.rows import encode_row
        from nebula_tpu.interface.common import (ColumnDef, Schema,
                                                 SupportedType)
        schema = Schema(columns=[ColumnDef("w", SupportedType.INT)])
        frame = struct.Struct(">II")
        hdfs_store = tmp_path / "fake_hdfs" / "warehouse"
        hdfs_store.mkdir(parents=True)
        kvs = []
        for i in range(5):
            part = id_hash(1, 2)
            key = KeyUtils.edge_key(part, 1, etype, 0, 50 + i,
                                    inverted_version())
            kvs.append((key, encode_row(schema, {"w": i})))
        kvs.sort()
        with open(hdfs_store / "part.snap", "wb") as f:
            for k, v in kvs:
                f.write(frame.pack(len(k), len(v)))
                f.write(k)
                f.write(v)

        # fake `hdfs` on PATH: `hdfs dfs -get hdfs://nn/<path>/* <dest>`
        bindir = tmp_path / "bin"
        bindir.mkdir()
        shim = bindir / "hdfs"
        shim.write_text(
            "#!/bin/bash\n"
            "# fake hdfs client: dfs -get <url> <dest>\n"
            'src="${3#hdfs://nn}"\n'
            'cp $src "$4"\n')
        shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
        monkeypatch.setenv("PATH",
                           f"{bindir}:{_os.environ.get('PATH', '')}")

        node = c.storage_nodes[0]
        r = _download(node, space_id, f"hdfs://nn{hdfs_store}")
        assert r["ok"], r
        assert "part.snap" in r["staged"]
        r = _ingest(node, space_id, None)
        assert r["ok"], r
        resp = client.execute("USE h; GO FROM 1 OVER e YIELD e._dst")
        assert resp.ok(), resp.error_msg
        assert sorted(x[0] for x in resp.rows) == [50 + i for i in range(5)]

        # missing binary -> clean error, not a crash
        monkeypatch.setenv("PATH", "/nonexistent")
        r = _download(node, space_id, "hdfs://nn/whatever")
        assert not r["ok"] and "hdfs" in r["error"]
    finally:
        c.stop()


def test_graphd_per_statement_stats(tmp_path):
    """Per-statement-kind latency histograms + error counter fill in
    the reference's scaffolded-but-empty production counters
    (SURVEY.md §5.5): recorded per query, readable through the same
    StatsManager that /get_stats exports."""
    from nebula_tpu.common.stats import stats as S
    c = LocalCluster(num_storage=1)
    try:
        g = c.client()
        assert g.execute("CREATE SPACE st(partition_num=2, "
                         "replica_factor=1)").ok()
        c.refresh_all()
        assert g.execute("USE st; CREATE EDGE e(w int)").ok()
        c.refresh_all()
        assert g.execute("INSERT EDGE e(w) VALUES 1->2:(1)").ok()
        assert g.execute("GO FROM 1 OVER e").ok()
        assert (S.read_stats("graph.stmt.GoSentence.latency_us"
                             ".count.3600") or 0) >= 1
        assert (S.read_stats("graph.stmt.InsertEdgeSentence.latency_us"
                             ".count.3600") or 0) >= 1
        # /get_stats (StatsManager.dump) exposes tail latency now —
        # the per-statement histograms must carry real p95/p99 columns
        dump = S.dump()
        go_hist = dump["graph.stmt.GoSentence.latency_us"]
        assert go_hist["p95.60"] > 0 and go_hist["p99.60"] > 0
        assert go_hist["p99.60"] >= go_hist["p95.60"]
        e0 = S.read_stats("graph.error.qps.count.3600") or 0
        r = g.execute("GO FROM 1 OVER nosuch")
        assert not r.ok()
        assert (S.read_stats("graph.error.qps.count.3600") or 0) > e0
        # syntax errors count too
        r = g.execute("THIS IS NOT NGQL")
        assert not r.ok()
        assert (S.read_stats("graph.error.qps.count.3600") or 0) > e0 + 0
    finally:
        c.stop()


def test_micro_bench_tool_runs():
    """tools/micro_bench must produce sane rates for every component
    (the reference's ParserBenchmark/RowReaderBenchmark/
    MultiVersionBenchmark analogues, recorded in BASELINE.md)."""
    from nebula_tpu.tools import micro_bench as MB
    out = {
        "parser": MB.bench_parser(5),
        "row_codec": MB.bench_codec(2000),
        "key_codec": MB.bench_keys(2000),
        "wal": MB.bench_wal(500),
        "query_path": MB.bench_query(5),
        "kernel_roofline": MB.bench_kernel_roofline(2),
    }
    assert out["parser"]["statements_per_s"] > 0
    assert out["row_codec"]["encode_rows_per_s"] > 0
    assert out["wal"]["append_entries_per_s"] > 0
    assert out["query_path"]["go_queries_per_s"] > 0
    # packed-vs-int8 parity is a hard gate; the speed budget is only
    # asserted by the full micro_bench run (tiny CI graphs are noisy)
    assert out["kernel_roofline"]["parity"] is True
    assert out["kernel_roofline"]["packed_ms_per_dispatch"] > 0


class TestStoreTypeGate:
    def test_unknown_store_type_refused(self, tmp_path):
        """--store_type parity: only 'nebula' is served; anything else
        (incl. 'hbase', whose plugin the reference keeps dormant and
        refuses at startup, StorageServer.cpp:44-55) exits with an
        error instead of booting — whether it arrives on the CLI or
        via --flagfile (the reference's conf idiom)."""
        import subprocess
        import sys as _sys
        r = subprocess.run(
            [_sys.executable, "-m", "nebula_tpu.daemons.storaged",
             "--store_type", "hbase", "--port", "45993",
             "--meta_server_addrs", "127.0.0.1:45994"],
            capture_output=True, text=True, timeout=60)
        assert r.returncode == 1
        assert "unknown store type 'hbase'" in r.stderr
        conf = tmp_path / "storaged.conf"
        conf.write_text("store_type=hbase\n")
        r2 = subprocess.run(
            [_sys.executable, "-m", "nebula_tpu.daemons.storaged",
             "--flagfile", str(conf), "--port", "45993",
             "--meta_server_addrs", "127.0.0.1:45994"],
            capture_output=True, text=True, timeout=60)
        assert r2.returncode == 1
        assert "unknown store type 'hbase'" in r2.stderr

    def test_explicit_cli_beats_conf(self):
        """ADVICE round 5: default=None in add_argument keeps an
        explicit CLI --store_type distinguishable from "unset", so CLI
        `nebula` beats a conf-file `hbase` (gflags semantics) instead
        of the conf silently overriding it."""
        from nebula_tpu.common.flags import flags
        from nebula_tpu.daemons.storaged import resolve_store_type
        flags.define("store_type", "")      # what a flagfile load does
        saved = flags.get("store_type")
        try:
            flags.set("store_type", "hbase", force=True)
            assert resolve_store_type("nebula") == "nebula"  # CLI wins
            assert resolve_store_type(None) == "hbase"       # conf fills
            flags.set("store_type", "", force=True)
            assert resolve_store_type(None) == "nebula"      # default
            assert resolve_store_type("hbase") == "hbase"
        finally:
            flags.set("store_type", saved, force=True)
