"""Row codec tests — modeled on the reference's dataman test tier
(RowReaderTest/RowWriterTest/RowUpdaterTest, SURVEY.md §4)."""
import pytest

from nebula_tpu.codec.rows import (RowReader, RowSetReader, RowSetWriter,
                                   RowUpdater, RowWriter, decode_row,
                                   encode_row)
from nebula_tpu.interface.common import ColumnDef, Schema, SupportedType

PLAYER = Schema(columns=[
    ColumnDef("name", SupportedType.STRING),
    ColumnDef("age", SupportedType.INT),
    ColumnDef("mvp", SupportedType.BOOL),
    ColumnDef("ppg", SupportedType.DOUBLE),
], version=0)


def test_roundtrip_all_types():
    row = (RowWriter(PLAYER)
           .set("name", "Tim Duncan")
           .set("age", 42)
           .set("mvp", True)
           .set("ppg", 19.0)
           .encode())
    r = RowReader(row, PLAYER)
    assert r.get("name") == "Tim Duncan"
    assert r.get("age") == 42
    assert r.get("mvp") is True
    assert r.get("ppg") == 19.0
    assert r.to_dict() == {"name": "Tim Duncan", "age": 42, "mvp": True, "ppg": 19.0}


def test_negative_and_large_ints():
    s = Schema(columns=[ColumnDef("x", SupportedType.INT)])
    for v in (0, -1, 1, 2**62, -(2**62), 127, -128):
        row = encode_row(s, {"x": v})
        assert decode_row(row, s)["x"] == v


def test_defaults_for_unset_fields():
    row = RowWriter(PLAYER).set("age", 30).encode()
    r = RowReader(row, PLAYER)
    assert r.get("name") == ""
    assert r.get("mvp") is False
    assert r.get("ppg") == 0.0
    assert r.get("age") == 30


def test_column_default_values():
    s = Schema(columns=[ColumnDef("n", SupportedType.INT, default=7)])
    assert decode_row(encode_row(s, {}), s)["n"] == 7


def test_unknown_field_raises():
    with pytest.raises(KeyError):
        RowWriter(PLAYER).set("nope", 1)
    r = RowReader(RowWriter(PLAYER).encode(), PLAYER)
    with pytest.raises(KeyError):
        r.get("nope")
    assert r.get("nope", default=5) == 5


def test_schema_version_resolution():
    v0 = Schema(columns=[ColumnDef("a", SupportedType.INT)], version=0)
    v1 = Schema(columns=[ColumnDef("a", SupportedType.INT),
                         ColumnDef("b", SupportedType.STRING)], version=1)
    versions = {0: v0, 1: v1}
    row0 = encode_row(v0, {"a": 1})
    row1 = encode_row(v1, {"a": 2, "b": "hi"})
    r0 = RowReader.from_resolver(row0, versions.get)
    r1 = RowReader.from_resolver(row1, versions.get)
    assert r0.row_version == 0 and r0.get("a") == 1
    assert r1.row_version == 1 and r1.get("b") == "hi"


def test_lazy_offsets():
    row = (RowWriter(PLAYER).set("name", "x" * 1000).set("age", 1).encode())
    r = RowReader(row, PLAYER)
    # reading field 0 shouldn't have indexed past field 1
    assert r.get_by_index(0) == "x" * 1000
    assert len(r._offsets) <= 2
    assert r.get_by_index(3) == 0.0
    assert r.size() == len(row)


def test_row_updater():
    row = RowWriter(PLAYER).set("name", "Tony").set("age", 36).encode()
    u = RowUpdater(PLAYER, row)
    u.set("age", 37)
    out = RowReader(u.encode(), PLAYER)
    assert out.get("age") == 37
    assert out.get("name") == "Tony"


def test_rowset_roundtrip():
    w = RowSetWriter()
    rows = [encode_row(PLAYER, {"name": f"p{i}", "age": i}) for i in range(10)]
    for row in rows:
        w.add_row(row)
    assert w.count == 10
    got = list(RowSetReader(w.data()))
    assert got == rows


def test_empty_rowset():
    assert list(RowSetReader(b"")) == []


def test_old_row_reads_new_schema_defaults():
    # ALTER ADD appends columns; rows written before the alter must read
    # the new column's default (reference RowReader semantics).
    v0 = Schema(columns=[ColumnDef("a", SupportedType.INT)], version=0)
    v1 = Schema(columns=[ColumnDef("a", SupportedType.INT),
                         ColumnDef("b", SupportedType.STRING),
                         ColumnDef("c", SupportedType.INT, default=9)], version=1)
    old_row = encode_row(v0, {"a": 4})
    r = RowReader(old_row, v1)
    assert r.get("a") == 4
    assert r.get("b") == ""
    assert r.get("c") == 9


def test_int64_overflow_raises():
    s = Schema(columns=[ColumnDef("x", SupportedType.INT)])
    with pytest.raises(OverflowError):
        encode_row(s, {"x": 2**63})
    with pytest.raises(OverflowError):
        encode_row(s, {"x": -(2**63) - 1})


def test_string_type_check():
    s = Schema(columns=[ColumnDef("s", SupportedType.STRING)])
    with pytest.raises(TypeError):
        encode_row(s, {"s": 5})
    assert decode_row(encode_row(s, {"s": b"raw"}), s)["s"] == "raw"
