"""Flat (columnar) final-hop mode: getBound with flat=True answers with
typed column buffers (storage/processors.py _process_flat) and GO maps
YIELD columns straight onto them (traverse.py _flat_assemble).

Parity contract: every GO shape must return the same row SET whether the
flat path serves it or the per-vertex path does (ordering may differ —
flat emits etype-major, per-vertex emits vertex-major, and the reference
makes no ordering promise for GO either).
"""
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.flags import flags

A, B, C, D = 1, 2, 3, 4


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_storage=1)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def client(cluster):
    client = cluster.client()

    def ok(stmt):
        resp = client.execute(stmt)
        assert resp.ok(), f"{stmt}: {resp.error_msg}"
        return resp

    client.ok = ok
    ok("CREATE SPACE flat(partition_num=4)")
    cluster.refresh_all()
    ok("USE flat")
    ok("CREATE TAG node(name string)")
    ok("CREATE EDGE rel(w int, f double, label string, flagb bool)")
    ok("CREATE EDGE other(x int)")
    cluster.refresh_all()
    ok('INSERT VERTEX node(name) VALUES '
       f'{A}:("a"), {B}:("b"), {C}:("c"), {D}:("d")')
    ok('INSERT EDGE rel(w, f, label, flagb) VALUES '
       f'{A} -> {B}:(10, 1.5, "ab", true), '
       f'{A} -> {C}:(20, 2.5, "ac", false), '
       f'{B} -> {D}:(30, 3.5, "bd", true)')
    ok(f'INSERT EDGE other(x) VALUES {A} -> {D}:(7)')
    yield client
    client.disconnect()


def both_paths(cluster, client, stmt):
    """Row sets via the normal path and via the flat-mode path (flat is
    on by default; the switch here proves both agree)."""
    flags.set("flat_bound_mode", False)
    try:
        slow = {tuple(r) for r in client.ok(stmt).rows}
    finally:
        flags.set("flat_bound_mode", True)
    fast = {tuple(r) for r in client.ok(stmt).rows}
    assert fast == slow, stmt
    return fast


def test_default_yield(cluster, client):
    got = both_paths(cluster, client, f"GO FROM {A} OVER rel")
    assert got == {(B,), (C,)}


def test_pseudo_and_prop_yields(cluster, client):
    got = both_paths(
        cluster, client,
        f"GO FROM {A} OVER rel YIELD rel._src, rel._dst, rel._rank, "
        f"rel.w, rel.f, rel.label, rel.flagb")
    assert got == {(A, B, 0, 10, 1.5, "ab", True),
                   (A, C, 0, 20, 2.5, "ac", False)}


def test_two_hops(cluster, client):
    got = both_paths(cluster, client,
                     f"GO 2 STEPS FROM {A} OVER rel YIELD rel._dst, rel.w")
    assert got == {(D, 30)}


def test_multi_etype_over_pseudo_only(cluster, client):
    # multi-edge OVER with pseudo-col yields is flat-eligible
    got = both_paths(cluster, client,
                     f"GO FROM {A} OVER rel, other YIELD rel._dst")
    assert got == {(B,), (C,), (D,)}


def test_multi_etype_alias_prop_keeps_per_row_semantics(cluster, client):
    # alias prop under multi-edge OVER must raise on the other edge's
    # rows (per-row semantics) — flat mode must not change that
    r = client.execute(f"GO FROM {A} OVER rel, other YIELD rel.w")
    assert not r.ok()


def test_distinct(cluster, client):
    got = both_paths(cluster, client,
                     f"GO FROM {A}, {B} OVER rel YIELD DISTINCT rel._rank")
    assert got == {(0,)}


def test_flat_response_shape(cluster, client):
    """The storage response really is columnar for the eligible shape."""
    space = cluster.graph_meta_client.get_space_id_by_name("flat").value()
    sm = cluster.schema_man
    et = sm.to_edge_type(space, "rel").value()
    resp = cluster.storage_client.get_neighbors(
        space, [A, B], [et], flat=True)
    assert resp.succeeded()
    assert all("flat" in r for r in resp.responses)
    n = sum(ch["n"] for r in resp.responses for ch in r["flat"])
    assert n == 3
