"""Lean native-ABI exerciser for the ASAN/UBSAN build.

Run by tests/test_native.py::test_native_suite_under_asan inside an
instrumented process (LD_PRELOAD=libasan, NEBULA_NATIVE_SO pointing at
the `make asan` artifact).  Deliberately avoids pytest and jax device
work — the instrumented interpreter makes those minutes-slow — while
still driving every native entry point: engine CRUD/scans/snapshot
ingest (fuzzed against MemEngine), the batch column decoder, and the
C++ ELL builder.
"""
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from nebula_tpu.codec.rows import encode_row
from nebula_tpu.interface.common import ColumnDef, Schema, SupportedType
from nebula_tpu.kvstore.engine import MemEngine
from nebula_tpu.kvstore.native import NativeEngine
from nebula_tpu.native import available, batch
from nebula_tpu.tpu.ell import EllIndex


def main(tmp_dir: str) -> None:
    assert available(), "native lib did not load under ASAN"

    # engine: fuzz CRUD + scans against MemEngine
    rng = random.Random(3)
    e, m = NativeEngine(), MemEngine()
    keys = [b"k%02d" % i for i in range(40)]
    for step in range(2000):
        k = rng.choice(keys)
        roll = rng.random()
        if roll < 0.5:
            v = bytes(rng.getrandbits(8)
                      for _ in range(rng.randrange(0, 64)))
            e.put(k, v)
            m.put(k, v)
        elif roll < 0.7:
            e.remove(k)
            m.remove(k)
        elif roll < 0.8:
            e.remove_prefix(k[:2])
            m.remove_prefix(k[:2])
        elif roll < 0.85:
            # bulk ABI: neb_multi_put / neb_multi_remove
            kvs = [(rng.choice(keys),
                    bytes(rng.getrandbits(8)
                          for _ in range(rng.randrange(0, 32))))
                   for _ in range(rng.randrange(1, 8))]
            e.multi_put(kvs)
            m.multi_put(kvs)
        elif roll < 0.9:
            doomed = [rng.choice(keys) for _ in range(rng.randrange(1, 5))]
            e.multi_remove(doomed)
            m.multi_remove(doomed)
        elif roll < 0.95:
            a, b = sorted((rng.choice(keys), rng.choice(keys)))
            e.remove_range(a, b)
            m.remove_range(a, b)
        else:
            assert e.get(k) == m.get(k)
    assert list(e.prefix(b"")) == list(m.prefix(b""))
    # range scan + key count over the ABI (neb_scan_range/neb_total_keys)
    assert list(e.range(b"k10", b"k30")) == list(m.range(b"k10", b"k30"))
    assert e.total_keys() == sum(1 for _ in m.prefix(b""))
    snap = os.path.join(tmp_dir, "snap")
    e.flush(snap)
    e2 = NativeEngine()
    e2.ingest(snap)
    assert list(e2.prefix(b"")) == list(m.prefix(b""))

    # batch codec over the ABI (decode_field + parse_keys)
    schema = Schema(columns=[ColumnDef("a", SupportedType.INT),
                             ColumnDef("s", SupportedType.STRING)])
    rows = [encode_row(schema, {"a": i, "s": "x" * (i % 7)})
            for i in range(500)]
    blob, offs, lens = batch.concat_blobs(rows)
    cols = batch.decode_field(blob, offs, lens, schema, 0)
    if cols is not None:
        assert [int(v) for v in cols.i64[:500]] == list(range(500))
    from nebula_tpu.common.keys import KeyUtils
    ekeys = [KeyUtils.edge_key(1, s, 7, 0, d, 5)
             for s, d in [(1, 2), (3, 4), (5, 6)]]
    kb, ko, kl = batch.concat_blobs(ekeys)
    parsed = batch.parse_keys(kb, ko, kl)
    if parsed is not None:
        assert [int(x) for x in parsed.a[:3]] == [1, 3, 5]

    # multi-prefix bulk scan (round 4): counts and content must match
    # per-prefix scans, including empty and all-0xFF-adjacent prefixes
    e2 = NativeEngine()
    from nebula_tpu.common.keys import KeyUtils as KU
    for part in (1, 2):
        for vid in range(6):
            for ver in (5, 6):
                e2.put(KU.edge_key(part, vid, 3, 0, vid + 1, ver),
                       b"v%d" % ver)
    prefixes = [KU.edge_prefix(1, v, 3) for v in range(8)]   # 6,7 empty
    got = e2.multi_prefix_packed(prefixes)
    if got is not None:
        packed, counts = got
        assert [int(c) for c in counts] == [2] * 6 + [0, 0], counts
        singles = b"".join(e2.scan_prefix_packed(p) for p in prefixes)
        assert packed == singles

    # C++ ELL builder
    es = np.asarray(rng.choices(range(64), k=600), dtype=np.int32)
    ed = np.asarray(rng.choices(range(64), k=600), dtype=np.int32)
    ee = np.ones(600, np.int32)
    ix = EllIndex.build(es, ed, ee, 64)
    assert ix.n == 64
    print("ASAN DRIVER OK")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp")
