"""Deadline-aware admission control, backpressure, and load shedding
(docs/admission.md): the serving path must degrade GRACEFULLY under
overload — bounded queues, typed fast failures (DEADLINE_EXCEEDED with
the partial-result completeness/warning surface), priority-ordered
pipeline slots, a closed-loop batch window, and whole-request deadline
budgets that propagate graphd -> RPC envelope -> storage/meta retries
-> device dispatch.  No waiter ever blocks past its deadline."""
import threading
import time

import pytest

from nebula_tpu.common import deadline as deadlines
from nebula_tpu.common.deadline import Deadline, DeadlineExceeded
from nebula_tpu.common.events import journal
from nebula_tpu.common.flags import flags
from nebula_tpu.common.stats import stats
from nebula_tpu.common.status import ErrorCode, Status
from nebula_tpu.graph.batch_dispatch import (AdmissionShed,
                                             GoBatchDispatcher, _KeyState,
                                             _PrioritySlots, _Request,
                                             _WindowController)


@pytest.fixture(autouse=True)
def _restore_admission_flags():
    names = ("admission_control", "admission_queue_max",
             "admission_window_depth_ref", "go_batch_window_ms",
             "go_batch_inflight", "query_deadline_ms")
    saved = {n: flags.get(n) for n in names}
    yield
    for k, v in saved.items():
        flags.set(k, v)


# ---------------------------------------------------------- deadline core
class TestDeadline:
    def test_remaining_and_expiry(self):
        d = Deadline.after_ms(50)
        assert 0 < d.remaining_s() <= 0.05
        assert not d.expired()
        e = Deadline.after_ms(-1)
        assert e.expired() and e.remaining_s() <= 0

    def test_bind_restores_previous(self):
        assert deadlines.current() is None
        outer = Deadline.after_s(10)
        with deadlines.bind(outer):
            assert deadlines.current() is outer
            with deadlines.bind(None):       # scoped clear
                assert deadlines.current() is None
            inner = Deadline.after_s(1)
            with deadlines.bind(inner):
                assert deadlines.current() is inner
            assert deadlines.current() is outer
        assert deadlines.current() is None

    def test_remaining_or_clamps_and_raises(self):
        with deadlines.bind(Deadline.after_s(0.5)):
            assert deadlines.remaining_or(10.0) <= 0.5
            assert deadlines.remaining_or(None) <= 0.5
        with deadlines.bind(Deadline.after_ms(-5)):
            with pytest.raises(DeadlineExceeded):
                deadlines.remaining_or(1.0)
        assert deadlines.remaining_or(7.0) == 7.0    # unbound


# ------------------------------------------------------- priority slots
class TestPrioritySlots:
    def test_priority_order_under_contention(self):
        """With the single slot held, a priority-0 waiter that arrived
        AFTER a priority-2 waiter still gets the slot first — the
        per-query-class ladder."""
        slots = _PrioritySlots(1)
        slots.acquire(1)                  # occupy
        order = []
        ready = threading.Barrier(3)

        def waiter(prio):
            ready.wait(timeout=5)
            if prio == 0:
                time.sleep(0.05)          # provably arrives second
            slots.acquire(prio)
            order.append(prio)
            slots.release()

        ts = [threading.Thread(target=waiter, args=(2,)),
              threading.Thread(target=waiter, args=(0,))]
        for t in ts:
            t.start()
        ready.wait(timeout=5)
        time.sleep(0.2)                   # both parked on the slot
        slots.release()
        for t in ts:
            t.join(timeout=5)
        assert order == [0, 2]

    def test_back_to_back_releases_wake_successive_heads(self):
        """Missed-wakeup regression: two release()s landing while the
        head waiter is inside one wait() leave a SECOND free slot that
        nobody re-notifies for — the new head must be woken by the
        departing head, not sleep on a free slot for a full batch
        round-trip."""
        for _ in range(20):               # the race is probabilistic
            slots = _PrioritySlots(2)
            slots.acquire(0)
            slots.acquire(1)              # drain both slots
            got = []

            def w(p, slots=slots, got=got):
                slots.acquire(p)
                got.append(p)

            ts = [threading.Thread(target=w, args=(p,)) for p in (0, 1)]
            for t in ts:
                t.start()
            time.sleep(0.02)              # both parked on the heap
            slots.release()
            slots.release()               # back-to-back frees
            for t in ts:
                t.join(timeout=2.0)
            assert not any(t.is_alive() for t in ts), \
                "a waiter slept on a free slot"
            assert sorted(got) == [0, 1]

    def test_release_wakes_fifo_within_class(self):
        slots = _PrioritySlots(2)
        slots.acquire(1)
        slots.acquire(1)
        done = []

        def w():
            slots.acquire(1)
            done.append(1)
            slots.release()

        t = threading.Thread(target=w)
        t.start()
        time.sleep(0.05)
        assert not done
        slots.release()
        t.join(timeout=5)
        assert done == [1]


# ---------------------------------------------------- window controller
class TestWindowController:
    def test_cap_full_when_idle_shrinks_with_depth(self):
        flags.set("go_batch_window_max_ms", 25)
        flags.set("admission_window_depth_ref", 8)
        w = _WindowController()
        full = w.cap_s()
        assert abs(full - 0.025) < 1e-9
        for _ in range(50):
            w.observe_depth(64)           # saturated queue
        assert w.cap_s() < full / 4
        for _ in range(200):
            w.observe_depth(0)            # drains -> cap recovers
        assert w.cap_s() > full * 0.9

    def test_dispatcher_window_obeys_controller_cap(self):
        d = GoBatchDispatcher(runtime=None)
        st = _KeyState()
        st.rt_ema_s = 30.0                # frac * ema would be huge
        flags.set("go_batch_window_ms", -1)
        cap = float(flags.get("go_batch_window_max_ms")) / 1000.0
        assert d._window_s(st.rt_ema_s) == cap     # idle: the full flag cap
        for _ in range(50):
            d.window.observe_depth(100)
        assert d._window_s(st.rt_ema_s) < cap / 4  # loaded: controller shrinks it


# ------------------------------------------------------------- shedding
class _EchoRuntime:
    def __init__(self, delay_s=0.0):
        self.delay_s = delay_s
        self.calls = []

    def exec_batch(self, space_id, payloads):
        self.calls.append(list(payloads))
        if self.delay_s:
            time.sleep(self.delay_s)
        return [p for p in payloads], "m"


class TestShedding:
    def test_queue_full_sheds_fast(self):
        rt = _EchoRuntime()
        d = GoBatchDispatcher(rt)
        flags.set("admission_queue_max", 0)   # explicit 0: shed all
        before = d.stats["sheds"]
        journal.clear_for_tests()
        t0 = time.perf_counter()
        with pytest.raises(AdmissionShed) as ei:
            d.submit_batched(("exec_batch", 1), "x")
        assert (time.perf_counter() - t0) < 0.1, "shed must fail FAST"
        assert ei.value.reason == "queue_full"
        assert isinstance(ei.value, DeadlineExceeded)   # typed surface
        assert ei.value.status.code == ErrorCode.E_DEADLINE_EXCEEDED
        assert d.stats["sheds"] == before + 1
        assert rt.calls == []                 # never reached the device
        kinds = [e["kind"] for e in journal.dump(10)]
        assert "query.shed" in kinds

    def test_unmeetable_deadline_sheds_at_admission(self):
        """A BACKLOG that makes the budget unmeetable is overload —
        an AdmissionShed that feeds the /healthz counters."""
        rt = _EchoRuntime()
        d = GoBatchDispatcher(rt)
        st = d._state(("exec_batch", 1))
        st.rt_ema_s = 5.0                     # measured: ~5 s a batch
        st.queue.append(_Request("backlog"))  # depth 1 ahead of us
        with deadlines.bind(Deadline.after_ms(100)):
            with pytest.raises(AdmissionShed) as ei:
                d.submit_batched(("exec_batch", 1), "x")
        assert ei.value.reason == "deadline_unmeetable"
        assert rt.calls == []

    def test_client_budget_failure_is_not_a_shed(self):
        """The SAME unmeetable budget on an EMPTY queue is the
        client's own choice, not overload: typed DEADLINE_EXCEEDED but
        no shed counter and no query.shed journal entry — a tight
        TIMEOUT on an idle daemon must never degrade /healthz."""
        rt = _EchoRuntime()
        d = GoBatchDispatcher(rt)
        st = d._state(("exec_batch", 1))
        st.rt_ema_s = 5.0
        journal.clear_for_tests()
        sheds_before = d.stats["sheds"]
        for budget_ms in (100, -1):           # unmeetable and expired
            with deadlines.bind(Deadline.after_ms(budget_ms)):
                with pytest.raises(DeadlineExceeded) as ei:
                    d.submit_batched(("exec_batch", 1), "x")
            assert not isinstance(ei.value, AdmissionShed)
        assert d.stats["sheds"] == sheds_before
        assert d.stats["deadline_drops"] >= 2
        assert all(e["kind"] != "query.shed" for e in journal.dump(10))
        assert rt.calls == []

    def test_admission_off_restores_admit_everything(self):
        rt = _EchoRuntime()
        d = GoBatchDispatcher(rt)
        flags.set("admission_control", False)
        flags.set("admission_queue_max", 0)
        st = d._state(("exec_batch", 1))
        st.rt_ema_s = 5.0
        with deadlines.bind(Deadline.after_s(30)):
            r, m = d.submit_batched(("exec_batch", 1), "x")
        assert (r, m) == ("x", "m")

    def test_waiter_never_blocks_past_deadline(self):
        """A request queued behind a slow batch wakes itself with
        DEADLINE_EXCEEDED at its deadline — it does NOT wait for the
        leader, and the runtime never sees its payload."""
        flags.set("go_batch_inflight", 1)
        rt = _EchoRuntime(delay_s=0.6)
        d = GoBatchDispatcher(rt)
        key = ("exec_batch", 1)
        errs = {}

        def occupant():
            d.submit_batched(key, "slow")

        t = threading.Thread(target=occupant)
        t.start()
        time.sleep(0.1)                   # occupant is dispatching

        def victim():
            try:
                with deadlines.bind(Deadline.after_ms(120)):
                    d.submit_batched(key, "victim")
            except DeadlineExceeded as e:
                errs["victim"] = e

        t0 = time.perf_counter()
        v = threading.Thread(target=victim)
        v.start()
        v.join(timeout=5)
        waited = time.perf_counter() - t0
        t.join(timeout=5)
        assert "victim" in errs, "victim hung instead of failing fast"
        assert not isinstance(errs["victim"], AdmissionShed)
        assert waited < 0.45, f"blocked {waited:.2f}s past its deadline"
        assert d.stats["deadline_drops"] >= 1
        assert all("victim" not in call for call in rt.calls)

    def test_run_drops_expired_pre_launch(self):
        """The leader's pre-launch gate: an entry whose budget ran out
        while queued is dropped from the batch (per-query exception
        machinery) while its batch-mates launch normally."""
        rt = _EchoRuntime()
        d = GoBatchDispatcher(rt)
        key = ("exec_batch", 1)
        live = _Request("live", Deadline.after_s(30))
        dead = _Request("dead", Deadline.after_ms(-1))   # already expired
        d._run(key, [live, dead], lambda: None)
        # _run releases one inflight slot it never acquired in this
        # direct-call harness — re-acquire to keep the fixture honest
        d._inflight.acquire(1)
        assert rt.calls == [["live"]]
        assert live.result == "live" and live.error is None
        assert isinstance(dead.error, DeadlineExceeded)
        assert dead.done and live.done
        assert d.stats["deadline_drops"] >= 1


# ------------------------------------------------- wire-level deadlines
class TestWireDeadline:
    def test_deadline_rides_the_rpc_envelope(self):
        """A bound budget crosses the TCP frame as remaining ms and is
        re-anchored server-side; without a binding the server sees no
        deadline (2-element frame contract)."""
        from nebula_tpu.interface.rpc import RpcChannel, RpcServer

        seen = {}

        class H:
            def rpc_probe(self, req):
                dl = deadlines.current()
                seen["rem"] = dl.remaining_ms() if dl else None
                return {"ok": True}

        srv = RpcServer(H()).start()
        try:
            ch = RpcChannel(srv.addr)
            ch.call("probe", {})
            assert seen["rem"] is None
            with deadlines.bind(Deadline.after_ms(500)):
                ch.call("probe", {})
            assert seen["rem"] is not None and 0 < seen["rem"] <= 500
            ch.close()
        finally:
            srv.stop()

    def test_expired_budget_fails_before_dialing(self):
        from nebula_tpu.interface.rpc import RpcChannel, RpcError
        from nebula_tpu.interface.common import HostAddr
        # unroutable port: a dial attempt would error differently/slowly
        ch = RpcChannel(HostAddr("127.0.0.1", 1))
        with deadlines.bind(Deadline.after_ms(-1)):
            with pytest.raises(RpcError) as ei:
                ch.call("probe", {})
        assert ei.value.status.code == ErrorCode.E_DEADLINE_EXCEEDED

    def test_storage_collect_respects_remaining_budget(self):
        """collect() clamps its own retry budget to the thread's
        remaining deadline: an exhausted budget fails every part with
        the typed status instead of dialing."""
        from nebula_tpu.storage.client import StorageClient

        class _Meta:
            def part_num(self, s):
                return 1

            def parts_alloc(self, s):
                return {0: ["127.0.0.1:1"]}

        sc = StorageClient(_Meta())
        with deadlines.bind(Deadline.after_ms(-1)):
            resp = sc.collect(1, {0: [1]},
                              lambda parts: ("getBound", {}))
        assert not resp.succeeded()
        assert all(s.code == ErrorCode.E_DEADLINE_EXCEEDED
                   for s in resp.failed_parts.values())


# ----------------------------------------------------------- TIMEOUT nGQL
class TestTimeoutClause:
    def test_parse_timeout_prefix(self):
        from nebula_tpu.graph.parser import GQLParser
        p = GQLParser()
        r = p.parse("TIMEOUT 1500 GO FROM 1 OVER e")
        assert r.ok() and r.value().timeout_ms == 1500
        r = p.parse("PROFILE TIMEOUT 20 GO FROM 1 OVER e")
        assert r.ok()
        assert r.value().profile and r.value().timeout_ms == 20
        r = p.parse("GO FROM 1 OVER e")
        assert r.ok() and r.value().timeout_ms is None

    def test_timeout_zero_rejected(self):
        from nebula_tpu.graph.parser import GQLParser
        r = GQLParser().parse("TIMEOUT 0 GO FROM 1 OVER e")
        assert not r.ok()

    def test_timeout_stays_usable_as_identifier(self):
        from nebula_tpu.graph.parser import GQLParser
        r = GQLParser().parse("GO FROM 1 OVER timeout")
        assert r.ok()


# ---------------------------------------------------------- observability
class TestObservability:
    def test_admission_metrics_registered_and_exported(self):
        rt = _EchoRuntime()
        d = GoBatchDispatcher(rt)
        flags.set("admission_queue_max", 0)
        with pytest.raises(AdmissionShed):
            d.submit_batched(("exec_batch", 7), "x")
        flags.set("admission_queue_max", 256)
        d.submit_batched(("exec_batch", 7), "y")
        text = stats.prometheus_text()
        assert "nebula_graph_admission_shed_total" in text
        assert "nebula_graph_admission_deadline_exceeded_total" in text
        assert "nebula_graph_admission_wait_us" in text
        # scrape-time gauges: live queue depth per (method, space) +
        # the closed-loop window cap
        assert 'nebula_graph_admission_queue_depth{method="exec_batch"' \
            in text
        assert "nebula_graph_admission_window_ms" in text

    def test_healthz_degrades_while_shedding(self):
        from nebula_tpu.graph.service import admission_health
        ok, _detail = admission_health()     # may be degraded from
        # neighbors in this module — force a fresh reject and check the
        # flip is observable either way
        stats.add_value("graph.admission.rejected.qps")
        ok, detail = admission_health()
        assert ok is False and "shedding" in detail


# --------------------------------------------------------------- e2e
@pytest.fixture
def nba():
    from nebula_tpu.cluster import LocalCluster
    c = LocalCluster(num_storage=1, tpu_backend=True)
    g = c.client()

    def ok(stmt):
        r = g.execute(stmt)
        assert r.ok(), f"{stmt}: {r.error_msg}"
        return r

    ok("CREATE SPACE s(partition_num=3, replica_factor=1)")
    c.refresh_all()
    ok("USE s")
    ok("CREATE EDGE follow(w int)")
    c.refresh_all()
    ok("INSERT EDGE follow(w) VALUES 1->2:(1), 2->3:(1), 3->4:(1), "
       "4->5:(1), 1->6:(1), 6->7:(1), 2->7:(1)")
    yield c, g, ok
    c.stop()


class TestEndToEnd:
    def test_shed_query_fails_fast_with_completeness(self, nba):
        c, g, ok = nba
        ok("GO 2 STEPS FROM 1 OVER follow")       # warm mirror/kernels
        rt = c.tpu_runtime
        orig = rt.go_batch_execute

        def slow(*a, **kw):
            time.sleep(0.4)
            return orig(*a, **kw)

        rt.go_batch_execute = slow
        try:
            t0 = time.perf_counter()
            r = g.execute("TIMEOUT 90 GO 2 STEPS FROM 1 OVER follow")
            wall = time.perf_counter() - t0
        finally:
            rt.go_batch_execute = orig
        assert r.error_code == ErrorCode.E_DEADLINE_EXCEEDED, r.error_msg
        assert wall < 2.0, f"deadline failure took {wall:.2f}s"
        assert r.completeness < 100
        assert r.warnings, "shed/deadline response must carry warnings"

    def test_profile_of_rejected_query_carries_admission_tag(self, nba):
        c, g, ok = nba
        ok("GO 2 STEPS FROM 1 OVER follow")
        # make the budget provably unmeetable: a warm continuous
        # stream with a huge measured hop time (the free-lane
        # feasibility math — docs/admission.md "Continuous dispatch")
        d = c.tpu_runtime.dispatcher
        st = next(iter(d.continuous.streams()))
        with st.cond:
            st.hop_ema_s = 30.0
        try:
            r = g.execute("PROFILE TIMEOUT 50 GO 2 STEPS FROM 1 "
                          "OVER follow")
        finally:
            with st.cond:
                st.hop_ema_s = 0.0
        assert r.error_code == ErrorCode.E_DEADLINE_EXCEEDED

        prof = r.raw.get("profile")
        assert prof, "PROFILE must return the trace even on rejection"

        def walk(n):
            yield n
            for ch in n.get("children", []):
                yield from walk(ch)

        spans = [s for root in prof["roots"] for s in walk(root)]
        admission = [s for s in spans if s["name"] == "graph.admission"]
        assert admission, [s["name"] for s in spans]
        # empty queue + huge measured round trip: a client-budget
        # rejection (not an overload shed) — the marker says which
        assert admission[0]["tags"].get("decision") == \
            "budget_below_round_trip"
        roots = [s for s in spans if s["name"] == "graph.query"]
        assert roots and roots[0]["tags"].get("admission") == "rejected"
        assert roots[0]["tags"].get("deadline_ms") == 50

    def test_show_stats_has_admission_rows(self, nba):
        c, g, ok = nba
        ok("GO FROM 1 OVER follow")              # dispatcher exists
        r = ok("SHOW STATS")
        rows = r.rows if not hasattr(r.rows, "_mat") else r.rows._mat()
        names = {row[1] for row in rows}
        assert "graph.admission.shed" in names
        assert "graph.admission.deadline_exceeded" in names
        assert "graph.admission.queue_depth.live" in names
        # no double counting: each (host, stat) pair appears once
        pairs = [(row[0], row[1]) for row in rows]
        assert len(pairs) == len(set(pairs))

    def test_deadline_statement_succeeds_within_budget(self, nba):
        c, g, ok = nba
        ok("GO 2 STEPS FROM 1 OVER follow")
        r = ok("TIMEOUT 60000 GO 2 STEPS FROM 1 OVER follow "
               "YIELD follow._dst")
        assert sorted(x[0] for x in r.rows) == [3, 7, 7]


@pytest.mark.slow
def test_soak_leg_records_saturation_curve():
    """The bench-suite soak leg (tools/bench_suite.py bench_soak) runs
    end to end on a tiny graph/short budget: every rung reports qps +
    per-class percentiles, the admission-on rungs carry the shed
    counter, and the control rung has the valve off.  The real
    10-minute recording is BENCH_SUITE_r06.json (marked slow so tier-1
    stays fast)."""
    from nebula_tpu.tools.bench_suite import bench_soak
    results = []
    bench_soak(results, persons=400, duration_s=12.0, workers=(4, 8))
    assert len(results) == 3                  # 2 rungs on + 1 control
    for r in results:
        assert r["requests"] > 0 and r["qps"] > 0
        assert r["errors"] == 0, r
        assert r["path_p50_ms"] is None or r["path_p50_ms"] > 0
    assert results[-1]["admission"] == "off"
