"""Wire-protocol conformance for the Go/Java client codecs.

No Go/Java toolchain is available in this environment, so the encoder
scheme both clients implement (clients/go/graphclient.go packInto,
clients/java/GraphClient.java pack) is transcribed here byte-for-byte
and checked against the real msgpack the server speaks — if the scheme
round-trips, the clients' frames are decodable by interface/rpc.py and
vice versa.  When a toolchain IS present, the compile tests below also
build the real sources."""
import math
import shutil
import struct
import subprocess
from pathlib import Path

import msgpack
import pytest

REPO = Path(__file__).resolve().parent.parent


def pack_scheme(v) -> bytes:
    """Byte-for-byte transcription of the Go/Java client encoders."""
    out = bytearray()

    def p(x):
        if x is None:
            out.append(0xC0)
        elif isinstance(x, bool):
            out.append(0xC3 if x else 0xC2)
        elif isinstance(x, int):
            if 0 <= x < 128:
                out.append(x)
            elif -32 <= x < 0:
                out.append(x & 0xFF)
            else:
                out.append(0xD3)
                out.extend(struct.pack(">q", x))
        elif isinstance(x, float):
            out.append(0xCB)
            out.extend(struct.pack(">d", x))
        elif isinstance(x, str):
            b = x.encode("utf-8")
            if len(b) < 32:
                out.append(0xA0 | len(b))
            elif len(b) < 256:
                out.extend([0xD9, len(b)])
            elif len(b) < 1 << 16:
                out.append(0xDA)
                out.extend(struct.pack(">H", len(b)))
            else:
                out.append(0xDB)
                out.extend(struct.pack(">I", len(b)))
            out.extend(b)
        elif isinstance(x, list):
            _len(len(x), 0x90, 0xDC, 0xDD)
            for e in x:
                p(e)
        elif isinstance(x, dict):
            _len(len(x), 0x80, 0xDE, 0xDF)
            for k, e in x.items():
                p(k)
                p(e)
        else:
            raise TypeError(type(x))

    def _len(n, fix, m16, m32):
        if n < 16:
            out.append(fix | n)
        elif n < 1 << 16:
            out.append(m16)
            out.extend(struct.pack(">H", n))
        else:
            out.append(m32)
            out.extend(struct.pack(">I", n))

    p(v)
    return bytes(out)


CASES = [
    None, True, False, 0, 1, 127, 128, -1, -32, -33, 2**40, -(2**40),
    3.14, -0.0, math.inf, "", "x", "s" * 31, "s" * 32, "s" * 300,
    "s" * 70000, ["a", 1, None], list(range(20)), {"k": "v"},
    {f"k{i}": i for i in range(20)},
    ["authenticate", {"username": "user", "password": "password"}],
    ["execute", {"session_id": 12345, "stmt": "GO FROM 1 OVER e"}],
]


@pytest.mark.parametrize("case", CASES, ids=lambda c: repr(c)[:30])
def test_client_encoding_decodes_as_msgpack(case):
    assert msgpack.unpackb(pack_scheme(case), raw=False,
                           strict_map_key=False) == case


def decode_scheme(buf: bytes):
    """Byte-for-byte transcription of the Go/Java client DECODER tag
    dispatch (graphclient.go decode / GraphClient.java Decoder.decode)
    so real server frames round-trip through the exact same logic."""
    pos = [0]

    def u8():
        v = buf[pos[0]]
        pos[0] += 1
        return v

    def take(n):
        v = buf[pos[0]:pos[0] + n]
        assert len(v) == n, "truncated frame"
        pos[0] += n
        return v

    def uN(n):
        return int.from_bytes(take(n), "big")

    def dec():
        t = u8()
        if t < 0x80:
            return t
        if t >= 0xE0:
            return t - 0x100
        if 0xA0 <= t < 0xC0:
            return take(t & 0x1F).decode("utf-8")
        if 0x90 <= t < 0xA0:
            return [dec() for _ in range(t & 0x0F)]
        if 0x80 <= t < 0x90:
            return {dec(): dec() for _ in range(t & 0x0F)}
        if t == 0xC0:
            return None
        if t == 0xC2:
            return False
        if t == 0xC3:
            return True
        if t in (0xCC, 0xCD, 0xCE, 0xCF):
            return uN(1 << (t - 0xCC))
        if t in (0xD0, 0xD1, 0xD2, 0xD3):
            n = 1 << (t - 0xD0)
            v = uN(n)                          # sign-extend like the
            return v - (1 << (8 * n)) \
                if v >= 1 << (8 * n - 1) else v   # clients' shift pair
        if t == 0xCA:
            return struct.unpack(">f", take(4))[0]
        if t == 0xCB:
            return struct.unpack(">d", take(8))[0]
        if t in (0xD9, 0xDA, 0xDB):
            return take(uN(1 << (t - 0xD9))).decode("utf-8")
        if t in (0xC4, 0xC5, 0xC6):
            return take(uN(1 << (t - 0xC4)))
        if t == 0xDC:
            return [dec() for _ in range(uN(2))]
        if t == 0xDD:
            return [dec() for _ in range(uN(4))]
        if t == 0xDE:
            return {dec(): dec() for _ in range(uN(2))}
        if t == 0xDF:
            return {dec(): dec() for _ in range(uN(4))}
        raise AssertionError(f"unsupported msgpack tag 0x{t:02x}")

    v = dec()
    assert pos[0] == len(buf), "trailing bytes"
    return v


SERVER_SHAPES = [
    None, True, 5, -5, 200, 70000, 2**33, 2**47, -200, -70000, -(2**33),
    1.5, "abc", "y" * 300, "z" * 70000, b"bin-blob", [1], {"a": 1},
    list(range(40)), {f"k{i}": i for i in range(40)},
    {"error_code": 0, "error_msg": "", "latency_in_us": 123456,
     "session_id": 2**47 + 3,
     "column_names": ["a" * 40], "rows": [[i, "x", None, 1.25]
                                          for i in range(20)]},
]


@pytest.mark.parametrize("shape", SERVER_SHAPES, ids=lambda c: repr(c)[:30])
def test_client_decoder_round_trips_server_frames(shape):
    """Real server bytes (msgpack-python packb) through the transcribed
    client decoder must reproduce the value exactly — this is what a
    connect/execute response exercises (48-bit session ids emit 0xcf,
    latencies 0xcc+, big rows 0xdc, nil fields 0xc0...)."""
    assert decode_scheme(msgpack.packb(shape, use_bin_type=True)) == shape


@pytest.mark.skipif(shutil.which("go") is None, reason="no go toolchain")
def test_go_client_compiles(tmp_path):
    subprocess.run(["go", "build", "./..."], cwd=REPO / "clients" / "go",
                   check=True, capture_output=True)


@pytest.mark.skipif(shutil.which("javac") is None, reason="no jdk")
def test_java_client_compiles(tmp_path):
    subprocess.run(["javac", "-d", str(tmp_path), "GraphClient.java"],
                   cwd=REPO / "clients" / "java",
                   check=True, capture_output=True)


# ---- pre-generated client frames (replay harness) --------------------
# Byte-exact frames a Go/Java client emits for one full session, fixed
# session id 0x123456789AB (48-bit → the 0xD3 int64 form both encoders
# use for values ≥ 128; clients/go/graphclient.go packInt,
# clients/java/GraphClient.java pack).  Note the Go client may emit map
# keys in any order (Go map iteration); these frames are one valid
# ordering — the server must accept any, which the dynamic e2e below
# also exercises.
REPLAY_SID = 0x123456789AB
REPLAY_FRAMES = [
    ("authenticate", "92ac61757468656e74696361746582a8757365726e616d65a4"
     "75736572a870617373776f7264a870617373776f7264"),
    ("execute", "92a76578656375746582aa73657373696f6e5f6964d30000012345"
     "6789aba473746d74d93243524541544520535041434520727028706172746974"
     "696f6e5f6e756d3d322c207265706c6963615f666163746f723d3129"),
    ("execute", "92a76578656375746582aa73657373696f6e5f6964d30000012345"
     "6789aba473746d74a6555345207270"),
    ("execute", "92a76578656375746582aa73657373696f6e5f6964d30000012345"
     "6789aba473746d74b443524541544520454447452065287720696e7429"),
    ("execute", "92a76578656375746582aa73657373696f6e5f6964d30000012345"
     "6789aba473746d74d92a494e53455254204544474520652877292056414c5545"
     "5320312d3e323a2837292c20322d3e333a283929"),
    ("execute", "92a76578656375746582aa73657373696f6e5f6964d30000012345"
     "6789aba473746d74d92a474f20322053544550532046524f4d2031204f564552"
     "2065205949454c4420652e5f6473742c20652e77"),
    ("signout", "92a77369676e6f757481aa73657373696f6e5f6964d30000012345"
     "6789ab"),
]


def test_golden_frames_match_transcription():
    """The stored replay bytes ARE what the transcribed encoders emit —
    drift in either direction (fixture vs transcription) fails here."""
    regenerated = [("authenticate", pack_scheme(
        ["authenticate", {"username": "user", "password": "password"}]))]
    for s in ("CREATE SPACE rp(partition_num=2, replica_factor=1)",
              "USE rp", "CREATE EDGE e(w int)",
              "INSERT EDGE e(w) VALUES 1->2:(7), 2->3:(9)",
              "GO 2 STEPS FROM 1 OVER e YIELD e._dst, e.w"):
        regenerated.append(("execute", pack_scheme(
            ["execute", {"session_id": REPLAY_SID, "stmt": s}])))
    regenerated.append(("signout", pack_scheme(
        ["signout", {"session_id": REPLAY_SID}])))
    got = [(m, b.hex()) for m, b in regenerated]
    assert got == REPLAY_FRAMES


def test_replay_pregenerated_frames_against_live_server():
    """Protocol-replay harness: the PRE-GENERATED byte frames above are
    sent verbatim to a live TCP graphd (session id pinned so the static
    execute frames authenticate) and every response must decode and
    succeed — the Go/Java clients' exact wire behavior, executed on a
    box with no Go/Java toolchain."""
    import contextlib
    import socket
    from nebula_tpu.cluster import LocalCluster
    from nebula_tpu.graph.service import ClientSession

    c = LocalCluster(num_storage=1, use_tcp=True)
    try:
        # pin the session the static frames carry
        sm = c.graph_service.sessions
        with sm._lock:
            sm._sessions[REPLAY_SID] = ClientSession(REPLAY_SID, "user")
        with contextlib.closing(socket.create_connection(
                ("127.0.0.1", c.graph_addr.port), timeout=30)) as sock:
            results = []
            for method, hexframe in REPLAY_FRAMES:
                body = bytes.fromhex(hexframe)
                sock.sendall(struct.pack(">I", len(body)) + body)
                if method == "signout":
                    break                        # oneway
                hdr = b""
                while len(hdr) < 4:
                    chunk = sock.recv(4 - len(hdr))
                    assert chunk, "server closed"
                    hdr += chunk
                (n,) = struct.unpack(">I", hdr)
                buf = b""
                while len(buf) < n:
                    chunk = sock.recv(n - len(buf))
                    assert chunk, "server closed mid-frame"
                    buf += chunk
                resp = decode_scheme(buf)
                results.append((method, resp))
                if method == "execute":
                    assert resp["error_code"] == 0, resp
                c.refresh_all()    # propagate DDL between statements
            go_resp = results[-1][1]
            assert go_resp["column_names"] == ["e._dst", "e.w"]
            assert [list(r) for r in go_resp["rows"]] == [[3, 9]]
    finally:
        c.stop()


class TestTranscribedClientEndToEnd:
    """The strongest check possible without a Go/Java toolchain in the
    image: run a REAL session against a REAL TCP cluster using the
    transcribed client protocol verbatim — the 4-byte big-endian frame
    header plus pack_scheme/decode_scheme (the exact byte logic of
    clients/go/graphclient.go call() and clients/java GraphClient) —
    and assert full DDL+DML+GO query flow works."""

    def _call(self, sock, method, payload):
        body = pack_scheme([method, payload])
        sock.sendall(struct.pack(">I", len(body)) + body)
        hdr = b""
        while len(hdr) < 4:
            chunk = sock.recv(4 - len(hdr))
            assert chunk, "server closed"
            hdr += chunk
        (n,) = struct.unpack(">I", hdr)
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            assert chunk, "server closed mid-frame"
            buf += chunk
        return decode_scheme(buf)

    def test_full_session_flow(self):
        import socket
        from nebula_tpu.cluster import LocalCluster
        import contextlib
        c = LocalCluster(num_storage=1, use_tcp=True)
        try:
          with contextlib.closing(socket.create_connection(
                  ("127.0.0.1", c.graph_addr.port), timeout=30)) as sock:
            auth = self._call(sock, "authenticate",
                              {"username": "user", "password": "password"})
            assert auth["error_code"] == 0, auth
            sid = auth["session_id"]

            def q(stmt):
                return self._call(sock, "execute",
                                  {"session_id": sid, "stmt": stmt})

            assert q("CREATE SPACE gp(partition_num=2, "
                     "replica_factor=1)")["error_code"] == 0
            c.refresh_all()
            assert q("USE gp")["error_code"] == 0
            assert q("CREATE EDGE e(w int)")["error_code"] == 0
            c.refresh_all()
            assert q("INSERT EDGE e(w) VALUES 1->2:(7), "
                     "2->3:(9)")["error_code"] == 0
            resp = q("GO 2 STEPS FROM 1 OVER e YIELD e._dst, e.w")
            assert resp["error_code"] == 0, resp
            assert resp["column_names"] == ["e._dst", "e.w"]
            assert [list(r) for r in resp["rows"]] == [[3, 9]]
            assert resp["latency_in_us"] >= 0
            # oneway signout ends the session server-side
            body = pack_scheme(["signout", {"session_id": sid}])
            sock.sendall(struct.pack(">I", len(body)) + body)
        finally:
            c.stop()
