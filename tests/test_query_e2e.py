"""End-to-end nGQL tests over the in-process cluster.

Modeled on the reference's graph/test tier: TraverseTestBase loads an NBA
player/team fixture (TraverseTestBase.h:19-60) and GoTest / YieldTest /
OrderByTest / FetchVerticesTest assert row sets (SURVEY.md §4).
"""
import pytest

from nebula_tpu.cluster import LocalCluster

# vids (player 1xx, team 2xx)
TIM, TONY, MANU, LEBRON, KYRIE = 100, 101, 102, 103, 104
SPURS, CAVS = 200, 201


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_storage=1)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def client(cluster):
    client = cluster.client()

    def ok(stmt):
        resp = client.execute(stmt)
        assert resp.ok(), f"{stmt}: {resp.error_msg}"
        return resp

    client.ok = ok
    ok("CREATE SPACE nba(partition_num=6, replica_factor=1)")
    cluster.refresh_all()
    ok("USE nba")
    ok("CREATE TAG player(name string, age int)")
    ok("CREATE TAG team(name string)")
    ok("CREATE EDGE follow(degree int)")
    ok("CREATE EDGE serve(start_year int, end_year int)")
    cluster.refresh_all()
    ok('INSERT VERTEX player(name, age) VALUES '
       f'{TIM}:("Tim Duncan", 42), {TONY}:("Tony Parker", 36), '
       f'{MANU}:("Manu Ginobili", 41), {LEBRON}:("LeBron James", 34), '
       f'{KYRIE}:("Kyrie Irving", 26)')
    ok(f'INSERT VERTEX team(name) VALUES {SPURS}:("Spurs"), {CAVS}:("Cavaliers")')
    ok('INSERT EDGE follow(degree) VALUES '
       f'{TIM} -> {TONY}:(95), {TIM} -> {MANU}:(95), '
       f'{TONY} -> {TIM}:(95), {TONY} -> {MANU}:(90), '
       f'{MANU} -> {TIM}:(90), {LEBRON} -> {KYRIE}:(80), '
       f'{KYRIE} -> {LEBRON}:(85)')
    ok('INSERT EDGE serve(start_year, end_year) VALUES '
       f'{TIM} -> {SPURS}:(1997, 2016), {TONY} -> {SPURS}:(1999, 2018), '
       f'{MANU} -> {SPURS}:(2002, 2018), {LEBRON} -> {CAVS}:(2003, 2010), '
       f'{KYRIE} -> {CAVS}:(2011, 2017)')
    yield client
    client.disconnect()


def rows_set(resp):
    return {tuple(r) for r in resp.rows}


class TestGo:
    def test_one_hop(self, client):
        resp = client.ok(f"GO FROM {TIM} OVER follow")
        assert resp.column_names == ["follow._dst"]
        assert rows_set(resp) == {(TONY,), (MANU,)}

    def test_one_hop_yield_props(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow YIELD follow._dst AS id, "
            f"follow.degree AS d, $^.player.name AS me")
        assert resp.column_names == ["id", "d", "me"]
        assert rows_set(resp) == {(TONY, 95, "Tim Duncan"),
                                  (MANU, 95, "Tim Duncan")}

    def test_dst_props_second_wave(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow YIELD $$.player.name AS n, "
            f"$$.player.age AS a")
        assert rows_set(resp) == {("Tony Parker", 36), ("Manu Ginobili", 41)}

    def test_where_edge_prop(self, client):
        resp = client.ok(
            f"GO FROM {TONY} OVER follow WHERE follow.degree > 92 "
            f"YIELD follow._dst")
        assert rows_set(resp) == {(TIM,)}

    def test_where_src_prop(self, client):
        resp = client.ok(
            f"GO FROM {TIM},{LEBRON} OVER follow "
            f"WHERE $^.player.age > 40 YIELD follow._dst")
        assert rows_set(resp) == {(TONY,), (MANU,)}

    def test_where_dst_prop_graphd_side(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow WHERE $$.player.age > 40 "
            f"YIELD follow._dst AS id, $$.player.name AS n")
        assert rows_set(resp) == {(MANU, "Manu Ginobili")}

    def test_two_hops(self, client):
        resp = client.ok(f"GO 2 STEPS FROM {TIM} OVER follow")
        # Tim -> {Tony, Manu} -> {Tim, Manu} ∪ {Tim}
        assert rows_set(resp) == {(TIM,), (MANU,)}

    def test_three_hops(self, client):
        resp = client.ok(f"GO 3 STEPS FROM {TIM} OVER follow")
        assert rows_set(resp) == {(TONY,), (MANU,), (TIM,)}

    def test_upto_steps_unions_depths(self, client):
        # UPTO N = edges out of the union of frontiers at depths
        # 0..N-1, each edge once (the reference PARSES UPTO but
        # refuses to execute it — GoExecutor.cpp:121-123)
        exact2 = client.ok(f"GO 2 STEPS FROM {TIM} OVER follow")
        assert rows_set(exact2) == {(TIM,), (MANU,)}
        resp = client.ok(f"GO UPTO 2 STEPS FROM {TIM} OVER follow")
        # depth-1 edges (Tim->Tony, Tim->Manu) union depth-2 edges
        assert rows_set(resp) == {(TONY,), (MANU,), (TIM,)}
        # rows are per-EDGE: Manu reached from both Tim (d1) and
        # Tony (d2) contributes both edges
        assert len(resp.rows) == 5
        # props/WHERE ride the same final-hop materialization
        resp = client.ok(
            f"GO UPTO 2 STEPS FROM {TIM} OVER follow "
            f"WHERE follow.degree > 90 YIELD follow._dst, "
            f"$$.player.name")
        assert (TONY, "Tony Parker") in rows_set(resp)

    def test_upto_frontier_exhausts_early(self, client):
        # LeBron -> Cavs is a dead end over `serve`; UPTO 5 must
        # still materialize the union instead of returning empty
        resp = client.ok(f"GO UPTO 5 STEPS FROM {LEBRON} OVER serve")
        assert rows_set(resp) == {(CAVS,)}

    def test_reversely(self, client):
        resp = client.ok(f"GO FROM {MANU} OVER follow REVERSELY")
        assert rows_set(resp) == {(TIM,), (TONY,)}

    def test_over_multiple_edges(self, client):
        resp = client.ok(f"GO FROM {TIM} OVER follow, serve "
                         f"YIELD follow._dst AS f, serve._dst AS s")
        # rows for follow edges have serve._dst unavailable -> error?
        # reference yields empty/default for non-matching edge columns
        assert resp.ok()

    def test_over_star(self, client):
        resp = client.ok(f"GO FROM {KYRIE} OVER *")
        vals = {v for row in resp.rows for v in row if v is not None}
        assert LEBRON in vals and CAVS in vals

    def test_distinct(self, client):
        resp = client.ok(
            f"GO FROM {TONY},{MANU} OVER follow YIELD DISTINCT follow._dst")
        assert rows_set(resp) == {(TIM,), (MANU,)}

    def test_pipe_go(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow YIELD follow._dst AS id | "
            f"GO FROM $-.id OVER follow YIELD follow._dst")
        assert rows_set(resp) == {(TIM,), (MANU,)}

    def test_pipe_with_input_prop(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow YIELD follow._dst AS id, "
            f"follow.degree AS d | "
            f"GO FROM $-.id OVER follow YIELD $-.d AS prev, follow._dst AS nxt")
        assert (95, TIM) in rows_set(resp)

    def test_var_assignment(self, client):
        resp = client.ok(
            f"$a = GO FROM {TIM} OVER follow YIELD follow._dst AS id; "
            f"GO FROM $a.id OVER follow YIELD follow._dst")
        assert rows_set(resp) == {(TIM,), (MANU,)}

    def test_empty_frontier(self, client):
        resp = client.ok(f"GO FROM 99999 OVER follow")
        assert resp.rows == []

    def test_go_from_nonexistent_space_error(self, cluster):
        c2 = cluster.client()
        resp = c2.execute("GO FROM 1 OVER follow")
        assert not resp.ok()  # no USE yet
        c2.disconnect()


class TestSetOps:
    def test_union(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow YIELD follow._dst AS id UNION "
            f"GO FROM {TONY} OVER follow YIELD follow._dst AS id")
        assert rows_set(resp) == {(TONY,), (MANU,), (TIM,)}

    def test_union_all(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow YIELD follow._dst AS id UNION ALL "
            f"GO FROM {TONY} OVER follow YIELD follow._dst AS id")
        assert len(resp.rows) == 4

    def test_intersect(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow YIELD follow._dst AS id INTERSECT "
            f"GO FROM {TONY} OVER follow YIELD follow._dst AS id")
        assert rows_set(resp) == {(MANU,)}

    def test_minus(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow YIELD follow._dst AS id MINUS "
            f"GO FROM {TONY} OVER follow YIELD follow._dst AS id")
        assert rows_set(resp) == {(TONY,)}


class TestYieldOrderLimit:
    def test_const_yield(self, client):
        resp = client.ok('YIELD 1+2 AS sum, "x" AS s, 2.0 * 2 AS d')
        assert resp.rows == [[3, "x", 4.0]]

    def test_order_by(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow YIELD follow._dst AS id, "
            f"follow.degree AS d | ORDER BY $-.id DESC")
        ids = [r[0] for r in resp.rows]
        assert ids == sorted(ids, reverse=True)

    def test_limit(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow YIELD follow._dst AS id | "
            f"ORDER BY $-.id | LIMIT 1")
        assert len(resp.rows) == 1

    def test_group_by(self, client):
        resp = client.ok(
            f"GO FROM {TIM},{TONY} OVER follow YIELD follow._dst AS id, "
            f"follow.degree AS d | GROUP BY $-.id YIELD $-.id AS id, "
            f"count(1) AS c, avg($-.d) AS avg_d")
        got = {r[0]: (r[1], r[2]) for r in resp.rows}
        assert got[MANU] == (2, 92.5)  # 95 from Tim, 90 from Tony


class TestFetch:
    def test_fetch_vertices(self, client):
        resp = client.ok(f"FETCH PROP ON player {TIM}, {TONY}")
        assert resp.column_names == ["VertexID", "player.name", "player.age"]
        assert rows_set(resp) == {(TIM, "Tim Duncan", 42),
                                  (TONY, "Tony Parker", 36)}

    def test_fetch_vertices_yield(self, client):
        resp = client.ok(f"FETCH PROP ON player {TIM} YIELD player.age AS a")
        assert resp.rows == [[TIM, 42]]

    def test_fetch_star(self, client):
        resp = client.ok(f"FETCH PROP ON * {SPURS}")
        assert resp.rows[0][0] == SPURS
        assert "Spurs" in resp.rows[0]

    def test_fetch_edges(self, client):
        resp = client.ok(f"FETCH PROP ON serve {TIM} -> {SPURS}")
        assert resp.column_names[:3] == ["serve._src", "serve._dst",
                                         "serve._rank"]
        row = resp.rows[0]
        assert row[0] == TIM and row[1] == SPURS
        assert 1997 in row and 2016 in row

    def test_fetch_pipe(self, client):
        resp = client.ok(
            f"GO FROM {TIM} OVER follow YIELD follow._dst AS id | "
            f"FETCH PROP ON player $-.id YIELD player.name AS n")
        assert {r[1] for r in resp.rows} == {"Tony Parker", "Manu Ginobili"}


class TestFindPath:
    def test_shortest_direct(self, client):
        resp = client.ok(f"FIND SHORTEST PATH FROM {TIM} TO {MANU} OVER follow")
        assert resp.column_names == ["path"]
        assert resp.rows == [[f"{TIM} <follow,0> {MANU}"]]

    def test_shortest_two_hop(self, client):
        resp = client.ok(
            f"FIND SHORTEST PATH FROM {LEBRON} TO {CAVS} OVER * UPTO 3 STEPS")
        assert any("serve" in r[0] for r in resp.rows)

    def test_no_path(self, client):
        resp = client.ok(f"FIND SHORTEST PATH FROM {TIM} TO {CAVS} OVER follow")
        assert resp.rows == []

    def test_all_paths(self, client):
        resp = client.ok(
            f"FIND ALL PATH FROM {TONY} TO {MANU} OVER follow UPTO 2 STEPS")
        # direct (Tony->Manu) and via Tim (Tony->Tim->Manu)
        assert len(resp.rows) == 2


class TestMutations:
    def test_update_vertex(self, client):
        client.ok(f'INSERT VERTEX player(name, age) VALUES 150:("Temp", 20)')
        client.ok("UPDATE VERTEX 150 SET age = $^.player.age + 5")
        resp = client.ok("FETCH PROP ON player 150 YIELD player.age AS a")
        assert resp.rows == [[150, 25]]

    def test_update_edge(self, client):
        client.ok('INSERT EDGE follow(degree) VALUES 150 -> 100:(10)')
        client.ok("UPDATE EDGE 150 -> 100 OF follow SET degree = 20")
        resp = client.ok("FETCH PROP ON follow 150 -> 100 YIELD follow.degree AS d")
        assert resp.rows[0][-1] == 20

    def test_delete_edge(self, client):
        client.ok('INSERT EDGE follow(degree) VALUES 150 -> 101:(10)')
        client.ok("DELETE EDGE follow 150 -> 101")
        resp = client.ok("GO FROM 150 OVER follow YIELD follow._dst")
        assert (101,) not in rows_set(resp)

    def test_delete_vertex(self, client):
        client.ok('INSERT VERTEX player(name, age) VALUES 151:("Doomed", 1)')
        client.ok('INSERT EDGE follow(degree) VALUES 151 -> 100:(1)')
        client.ok("DELETE VERTEX 151")
        resp = client.ok("FETCH PROP ON player 151")
        assert resp.rows == []

    def test_upsert_nonexistent(self, client):
        client.ok("UPSERT VERTEX 152 SET age = 30")
        resp = client.ok("FETCH PROP ON player 152 YIELD player.age AS a")
        assert resp.rows == [[152, 30]]


class TestDDLAndAdmin:
    def test_show_spaces(self, client):
        resp = client.ok("SHOW SPACES")
        assert ["nba"] in resp.rows

    def test_show_tags_edges(self, client):
        resp = client.ok("SHOW TAGS")
        names = {r[1] for r in resp.rows}
        assert names == {"player", "team"}
        resp = client.ok("SHOW EDGES")
        assert {r[1] for r in resp.rows} == {"follow", "serve"}

    def test_describe(self, client):
        resp = client.ok("DESCRIBE TAG player")
        assert resp.rows == [["name", "string"], ["age", "int"]]
        resp = client.ok("DESCRIBE EDGE serve")
        assert [r[0] for r in resp.rows] == ["start_year", "end_year"]
        resp = client.ok("DESCRIBE SPACE nba")
        assert resp.rows[0][1] == "nba"
        assert resp.rows[0][2] == 6

    def test_show_hosts_parts(self, client):
        resp = client.ok("SHOW HOSTS")
        assert len(resp.rows) >= 1
        resp = client.ok("SHOW PARTS")
        assert len(resp.rows) == 6

    def test_alter_tag(self, client, cluster):
        client.ok("CREATE TAG coach(name string)")
        cluster.refresh_all()
        client.ok("ALTER TAG coach ADD (years int)")
        cluster.refresh_all()
        resp = client.ok("DESCRIBE TAG coach")
        assert ["years", "int"] in resp.rows
        client.ok("DROP TAG coach")
        cluster.refresh_all()
        resp = client.execute("DESCRIBE TAG coach")
        assert not resp.ok()

    def test_users(self, client):
        client.ok('CREATE USER alice WITH PASSWORD "pw"')
        client.ok("GRANT ROLE ADMIN ON nba TO alice")
        resp = client.ok("SHOW USERS")
        assert ["alice"] in resp.rows
        client.ok("DROP USER alice")

    def test_configs(self, client):
        resp = client.ok("UPDATE CONFIGS graph:session_idle_timeout_secs = 999")
        resp = client.ok("GET CONFIGS graph:session_idle_timeout_secs")
        assert resp.rows[0][2] == "999"

    def test_match_non_basic_pattern_unsupported(self, client):
        # a lone node pattern is outside the lowered basic shape
        # (TestMatchLowering covers the supported subset)
        resp = client.execute("MATCH (v) RETURN v")
        assert not resp.ok()
        assert "MATCH" in resp.error_msg

    def test_syntax_error_reported(self, client):
        resp = client.execute("GO GO GO")
        assert not resp.ok()
        assert "syntax" in resp.error_msg.lower()


class TestSessions:
    def test_bad_auth_rejected(self, cluster):
        from nebula_tpu.clients.graph_client import GraphClient
        c = GraphClient(cluster.graph_addr, client_manager=cluster.cm)
        st = c.connect(username="bad", password="bad")
        assert not st.ok()

    def test_invalid_session(self, cluster):
        from nebula_tpu.clients.graph_client import GraphClient
        c = GraphClient(cluster.graph_addr, client_manager=cluster.cm)
        c.session_id = 424242
        resp = c.execute("SHOW SPACES")
        assert not resp.ok()


class TestReviewRegressions:
    def test_shortest_path_multi_target_different_depths(self, client):
        # Tim->Tony is 1 hop; Tim->Spurs (serve) is 1 hop; Tim->Cavs needs
        # follow*->serve — targets at different depths must all resolve
        resp = client.ok(
            f"FIND SHORTEST PATH FROM {TONY} TO {TIM},{SPURS} OVER * UPTO 3 STEPS")
        found = "\n".join(r[0] for r in resp.rows)
        assert f"<follow,0> {TIM}" in found
        assert f"{SPURS}" in found

    def test_fetch_edges_src_attribution(self, client):
        # two edges sharing (dst, rank) must keep distinct _src
        resp = client.ok(f"FETCH PROP ON follow {TIM} -> {MANU}, {TONY} -> {MANU} "
                         f"YIELD follow.degree AS d")
        srcs = {r[0] for r in resp.rows}
        assert srcs == {TIM, TONY}

    def test_delete_vertex_removes_neighbor_mirrors(self, client):
        client.ok('INSERT VERTEX player(name, age) VALUES 160:("Ghost", 1)')
        client.ok(f'INSERT EDGE follow(degree) VALUES {TIM} -> 160:(5), 160 -> {TONY}:(6)')
        client.ok("DELETE VERTEX 160")
        # no traversal reaches 160 anymore, in either direction
        resp = client.ok(f"GO FROM {TIM} OVER follow")
        assert (160,) not in rows_set(resp)
        resp = client.ok(f"GO FROM {TONY} OVER follow REVERSELY")
        assert (160,) not in rows_set(resp)


class TestMatchLowering:
    """Basic MATCH lowers onto the GO planner (beyond the reference,
    which rejects all MATCH — MatchExecutor.cpp:19-21)."""

    @pytest.fixture(scope="class")
    def mcluster(self):
        from nebula_tpu.cluster import LocalCluster
        c = LocalCluster(num_storage=1, tpu_backend=True)
        g = c.client()
        assert g.execute(
            "CREATE SPACE mtch(partition_num=3, replica_factor=1)").ok()
        c.refresh_all()
        g.execute("USE mtch")
        g.execute("CREATE TAG player(name string, age int)")
        g.execute("CREATE EDGE follow(degree int)")
        c.refresh_all()
        g.execute('INSERT VERTEX player(name, age) VALUES '
                  '1:("a", 40), 2:("b", 30), 3:("c", 20)')
        g.execute('INSERT EDGE follow(degree) VALUES '
                  '1->2:(95), 1->3:(50), 2->3:(80)')
        yield c, g
        c.stop()

    @pytest.mark.parametrize("q,exp", [
        ('MATCH (a:player)-[e:follow]->(b:player) WHERE id(a) == 1 '
         'RETURN id(b), e.degree', [(2, 95), (3, 50)]),
        ('MATCH (a:player)-[e:follow]->(b:player) WHERE id(a) == 1 '
         'AND e.degree > 60 RETURN b.name, e.degree', [("b", 95)]),
        ('MATCH (a)-[e:follow]->(b:player) WHERE id(a) == 1 '
         'AND b.age < 25 RETURN id(b)', [(3,)]),
        ('MATCH (a:player)-[e:follow]->(b) WHERE id(a) == 1 '
         'AND a.age > 30 RETURN id(b)', [(2,), (3,)]),
        # contradictory anchors: unsatisfiable -> empty
        ('MATCH (x)-[r:follow]->(y) WHERE id(x) == 1 AND id(x) == 2 '
         'RETURN id(y)', []),
        ('MATCH (a)-[e:follow]->(b) WHERE id(a) == 2 '
         'RETURN id(a), id(b)', [(2, 3)]),
        # anchor on the edge's HEAD -> lowers onto OVER ... REVERSELY
        ('MATCH (a)-[e:follow]->(b) WHERE id(b) == 3 '
         'RETURN id(a), e.degree', [(1, 50), (2, 80)]),
        # reverse pattern (edge runs b -> a), anchored either side
        ('MATCH (a)<-[e:follow]-(b) WHERE id(a) == 3 '
         'RETURN id(b), e.degree', [(1, 50), (2, 80)]),
        ('MATCH (a)<-[e:follow]-(b) WHERE id(b) == 1 '
         'RETURN id(a), e.degree', [(2, 95), (3, 50)]),
        # vertex props resolve to the right side under REVERSELY
        ('MATCH (a:player)<-[e:follow]-(b:player) WHERE id(a) == 3 '
         'AND b.age > 25 RETURN b.name, a.name',
         [("a", "c"), ("b", "c")]),
        # both vertices anchored: forward traversal, head anchor kept
        # as an equality filter
        ('MATCH (a)-[e:follow]->(b) WHERE id(a) == 1 AND id(b) == 2 '
         'RETURN id(b), e.degree', [(2, 95)]),
    ])
    def test_match_rows(self, mcluster, q, exp):
        _, g = mcluster
        r = g.execute(q)
        assert r.ok(), f"{q}: {r.error_msg}"
        assert sorted(map(tuple, r.rows)) == sorted(exp), q

    def test_match_cpu_tpu_parity(self, mcluster):
        from nebula_tpu.common.flags import flags
        _, g = mcluster
        queries = [
            ('MATCH (a:player)-[e:follow]->(b:player) WHERE id(a) == 1 '
             'AND e.degree >= 50 RETURN id(b), b.age, e.degree', 2),
            # REVERSELY lowering rides the same device seams
            ('MATCH (a:player)<-[e:follow]-(b:player) WHERE id(a) == 3 '
             'AND e.degree >= 50 RETURN id(b), b.age, e.degree', 2),
        ]
        for q, n in queries:
            flags.set("storage_backend", "cpu")
            try:
                a = sorted(map(tuple, g.execute(q).rows))
            finally:
                flags.set("storage_backend", "tpu")
            b = sorted(map(tuple, g.execute(q).rows))
            assert a == b and len(a) == n, q

    @pytest.mark.parametrize("q,frag", [
        ("MATCH (a)-[e]->(b) WHERE id(a) == 1 RETURN id(b)",
         "typed edge"),
        ("MATCH (a)-[e:follow]->(b)-[f:follow]->(z) RETURN id(z)",
         "basic"),
        ("MATCH (a)-[e:follow]->(b) RETURN id(b)", "anchor"),
        ("MATCH (a)-[e:follow]->(b) WHERE id(a) == 1 RETURN b.age",
         "label"),
    ])
    def test_match_unsupported_shapes_error(self, mcluster, q, frag):
        _, g = mcluster
        r = g.execute(q)
        assert not r.ok(), q
        assert frag in r.error_msg, (q, r.error_msg)

    def test_match_prefers_missing_anchor_error(self, mcluster):
        """ADVICE round 5: when one direction's rewrite fails (here:
        anchor-vertex props across a variable-length pattern) but the
        OTHER direction rewrites cleanly without finding an id()
        anchor, the surfaced error must be the clearer missing-anchor
        message, not the losing direction's incidental rewrite error."""
        _, g = mcluster
        r = g.execute("MATCH (a:player)-[e:follow*2]->(b:player) "
                      "WHERE a.age > 0 RETURN id(b)")
        assert not r.ok()
        assert "anchor" in r.error_msg, r.error_msg

    def test_match_string_literal_collides_with_var_name(self, mcluster):
        # a literal spelling a pattern-variable name must NOT be
        # rewritten (the substitution is token-level)
        _, g = mcluster
        q = ('MATCH (a:player)-[e:follow]->(b:player) WHERE id(a) == 1 '
             'AND b.name == "b" RETURN id(b), b.name')
        r = g.execute(q)
        assert r.ok(), r.error_msg
        assert sorted(map(tuple, r.rows)) == [(2, "b")]


class TestMatchVarLength:
    """Variable-length MATCH patterns lower onto GO N STEPS / GO UPTO:
    [e:t*N] = exact depth N, [e:t*1..N] = every neighbor within N hops
    (both beyond the reference, which rejects all MATCH)."""

    @pytest.fixture(scope="class")
    def vcluster(self):
        from nebula_tpu.cluster import LocalCluster
        c = LocalCluster(num_storage=1, tpu_backend=True)
        g = c.client()
        assert g.execute(
            "CREATE SPACE vl(partition_num=3, replica_factor=1)").ok()
        c.refresh_all()
        g.execute("USE vl")
        g.execute("CREATE TAG p(name string)")
        g.execute("CREATE EDGE knows(w int)")
        c.refresh_all()
        g.execute('INSERT VERTEX p(name) VALUES '
                  '1:("a"), 2:("b"), 3:("c"), 4:("d")')
        # a chain 1 -> 2 -> 3 -> 4
        g.execute("INSERT EDGE knows(w) VALUES "
                  "1->2:(12), 2->3:(23), 3->4:(34)")
        yield c, g
        c.stop()

    @pytest.mark.parametrize("q,exp", [
        # *N = exact depth
        ('MATCH (a)-[e:knows*2]->(b) WHERE id(a) == 1 RETURN id(b)',
         [(3,)]),
        ('MATCH (a)-[e:knows*3]->(b) WHERE id(a) == 1 '
         'RETURN id(b)', [(4,)]),
        # *1..N = union of depths (GO UPTO)
        ('MATCH (a)-[e:knows*1..3]->(b) WHERE id(a) == 1 RETURN id(b)',
         [(2,), (3,), (4,)]),
        # end-vertex props and filters ride the final hop
        ('MATCH (a)-[e:knows*1..3]->(b:p) WHERE id(a) == 1 '
         'AND b.name != "b" RETURN id(b), b.name',
         [(3, "c"), (4, "d")]),
        # reverse pattern composes with var length (head anchor ->
        # REVERSELY multi-hop)
        ('MATCH (a)<-[e:knows*2]-(b) WHERE id(a) == 4 RETURN id(b)',
         [(2,)]),
        # plain single-hop unchanged
        ('MATCH (a)-[e:knows*1]->(b) WHERE id(a) == 2 RETURN id(b)',
         [(3,)]),
    ])
    def test_var_length_rows(self, vcluster, q, exp):
        _, g = vcluster
        r = g.execute(q)
        assert r.ok(), f"{q}: {r.error_msg}"
        assert sorted(map(tuple, r.rows)) == sorted(exp), q

    @pytest.mark.parametrize("q,frag", [
        # lower bounds other than 1/N have no GO lowering
        ('MATCH (a)-[e:knows*2..3]->(b) WHERE id(a) == 1 RETURN id(b)',
         "variable-length"),
        # anchor props across multi-hop would read the final hop's src
        ('MATCH (a:p)-[e:knows*2]->(b) WHERE id(a) == 1 '
         'RETURN a.name', "anchor-vertex"),
        # non-anchor id(a) use across multi-hop
        ('MATCH (a)-[e:knows*1..2]->(b) WHERE id(a) == 1 '
         'RETURN id(a), id(b)', "final hop"),
        # edge props across multi-hop bind only the final edge —
        # rejected rather than silently serving one edge's value
        ('MATCH (a)-[e:knows*2]->(b) WHERE id(a) == 1 AND e.w == 12 '
         'RETURN id(b)', "edge properties"),
        ('MATCH (a)-[e:knows*1..3]->(b) WHERE id(a) == 1 '
         'RETURN id(b), e.w', "edge properties"),
    ])
    def test_var_length_unsupported(self, vcluster, q, frag):
        _, g = vcluster
        r = g.execute(q)
        assert not r.ok(), q
        assert frag in r.error_msg, (q, r.error_msg)

    def test_var_length_cpu_tpu_parity(self, vcluster):
        from nebula_tpu.common.flags import flags
        _, g = vcluster
        for q in ('MATCH (a)-[e:knows*2]->(b) WHERE id(a) == 1 '
                  'RETURN id(b)',
                  'MATCH (a)-[e:knows*1..3]->(b) WHERE id(a) == 1 '
                  'RETURN id(b)'):
            flags.set("storage_backend", "cpu")
            try:
                a = sorted(map(tuple, g.execute(q).rows))
            finally:
                flags.set("storage_backend", "tpu")
            b = sorted(map(tuple, g.execute(q).rows))
            assert a == b and a, q

    def test_var_length_walk_semantics_documented(self, vcluster):
        # deliberate scope: *N means reachable by an N-edge WALK (GO
        # semantics) — on a 2-cycle, *3 revisits the edge and returns
        # a row where Cypher's edge-distinct trails would return none
        _, g = vcluster
        g.execute("INSERT EDGE knows(w) VALUES 9->8:(98), 8->9:(89)")
        r = g.execute('MATCH (a)-[e:knows*3]->(b) WHERE id(a) == 9 '
                      'RETURN id(b)')
        assert r.ok(), r.error_msg
        assert sorted(map(tuple, r.rows)) == [(8,)]
