"""nebulint self-tests: each of the six checks must fire on a minimal
fixture snippet, honor inline suppression, and the whole-package run is
the tier-1 gate (zero unsuppressed violations).  Also the runtime half:
the OrderedLock watchdog must detect a deliberately seeded inversion.

Run just these: ``pytest -m lint``.
"""
import json
import os
import textwrap
import threading

import pytest

from nebula_tpu.tools.lint import (ALL_CHECKS, Baseline, LintError,
                                   lint_paths, run_lint)
from nebula_tpu.tools.lint.core import DEFAULT_BASELINE

pytestmark = pytest.mark.lint

PKG_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "nebula_tpu")
FIXTURE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "lint_fixtures")


def fixture_src(name):
    """One deliberately-broken module from tests/lint_fixtures/."""
    with open(os.path.join(FIXTURE_DIR, name), encoding="utf-8") as fh:
        return fh.read()


def run_fixture(tmp_path, files, checks=None):
    """Write {relpath: source} under a fake package root and lint it."""
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return lint_paths(str(root), checks=checks, repo_root=str(tmp_path))


def names(violations):
    return [v.check for v in violations]


# ================================================== 1 · lock-discipline
_UNGUARDED = """
    import threading

    class Daemon:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def process_put(self, req):
            self.count = self.count + 1
"""


def test_lock_discipline_unguarded_mutation(tmp_path):
    vs = run_fixture(tmp_path, {"daemon.py": _UNGUARDED},
                     checks=["lock-discipline"])
    assert names(vs) == ["lock-discipline"]
    assert "self.count" in vs[0].message


def test_lock_discipline_guarded_is_clean(tmp_path):
    ok = _UNGUARDED.replace(
        "            self.count = self.count + 1",
        "            with self._lock:\n"
        "                self.count = self.count + 1")
    assert run_fixture(tmp_path, {"daemon.py": ok},
                       checks=["lock-discipline"]) == []


def test_lock_discipline_caller_holds_contract(tmp_path):
    ok = _UNGUARDED.replace(
        "        def process_put(self, req):",
        "        def process_put(self, req):\n"
        '            """Caller holds the lock."""')
    assert run_fixture(tmp_path, {"daemon.py": ok},
                       checks=["lock-discipline"]) == []


def test_lock_discipline_blocking_call_under_lock(tmp_path):
    vs = run_fixture(tmp_path, {"daemon.py": """
        import threading
        import time

        class Daemon:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1)
    """}, checks=["lock-discipline"])
    assert names(vs) == ["lock-discipline"]
    assert "blocking call" in vs[0].message


def test_lock_discipline_inline_suppression(tmp_path):
    sup = _UNGUARDED.replace(
        "            self.count = self.count + 1",
        "            self.count = self.count + 1  "
        "# nebulint: disable=lock-discipline")
    assert run_fixture(tmp_path, {"daemon.py": sup},
                       checks=["lock-discipline"]) == []


# ===================================================== 2 · lock-order
_CYCLE = """
    import threading

    class Pair:
        def __init__(self):
            self.la = threading.Lock()
            self.lb = threading.Lock()

        def one(self):
            with self.la:
                with self.lb:
                    pass

        def two(self):
            with self.lb:
                with self.la:
                    pass
"""


def test_lock_order_cycle(tmp_path):
    vs = run_fixture(tmp_path, {"pair.py": _CYCLE}, checks=["lock-order"])
    assert names(vs) == ["lock-order"]
    assert "Pair.la" in vs[0].message and "Pair.lb" in vs[0].message


def test_lock_order_consistent_is_clean(tmp_path):
    ok = _CYCLE.replace(
        "            with self.lb:\n                with self.la:",
        "            with self.la:\n                with self.lb:")
    assert run_fixture(tmp_path, {"pair.py": ok},
                       checks=["lock-order"]) == []


def test_lock_order_file_suppression(tmp_path):
    sup = "# nebulint: disable-file=lock-order\n" + textwrap.dedent(_CYCLE)
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "pair.py").write_text(sup)
    assert lint_paths(str(root), checks=["lock-order"],
                      repo_root=str(tmp_path)) == []


# ================================================== 3 · status-discard
_DISCARD = """
    from common.status import Status

    def save() -> Status:
        return Status.OK()

    def caller():
        save()
"""


def test_status_discard(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": _DISCARD},
                     checks=["status-discard"])
    assert names(vs) == ["status-discard"]
    assert "save" in vs[0].message


def test_status_used_is_clean(tmp_path):
    ok = _DISCARD.replace("    save()", "    st = save()\n    return st")
    assert run_fixture(tmp_path, {"mod.py": ok},
                       checks=["status-discard"]) == []


def test_status_discard_suppression(tmp_path):
    sup = _DISCARD.replace(
        "    save()", "    save()  # nebulint: disable=status-discard")
    assert run_fixture(tmp_path, {"mod.py": sup},
                       checks=["status-discard"]) == []


def test_status_fixpoint_through_wrappers(tmp_path):
    """A function returning another status-returning function's result
    is itself status-returning (the MUST_USE_RESULT fixpoint)."""
    vs = run_fixture(tmp_path, {"mod.py": """
        def inner():
            return Status.OK()

        def outer():
            return inner()

        def caller():
            outer()
    """}, checks=["status-discard"])
    assert [v.symbol for v in vs] == ["caller"]


# ==================================================== 4 · jax-hotpath
def test_hotpath_jit_in_loop(tmp_path):
    vs = run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        def traverse(frontiers):
            for f in frontiers:
                step = jax.jit(lambda x: x)
                f = step(f)
    """}, checks=["jax-hotpath"])
    assert names(vs) == ["jax-hotpath"]
    assert "loop" in vs[0].message


def test_hotpath_host_sync_on_device_value(tmp_path):
    vs = run_fixture(tmp_path, {"tpu/kernels.py": """
        def drain(frontier_dev):
            total = 0
            while total < 10:
                total += int(frontier_dev)
            return total
    """}, checks=["jax-hotpath"])
    assert names(vs) == ["jax-hotpath"]
    assert "frontier_dev" in vs[0].message


def test_hotpath_outside_hot_files_ignored(tmp_path):
    assert run_fixture(tmp_path, {"graph/parser/x.py": """
        import jax

        def setup(items):
            for i in items:
                f = jax.jit(lambda x: x)
    """}, checks=["jax-hotpath"]) == []


def test_hotpath_jit_outside_loop_is_clean(tmp_path):
    assert run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        step = jax.jit(lambda x: x)

        def traverse(frontiers):
            for f in frontiers:
                f = step(f)
    """}, checks=["jax-hotpath"]) == []


# ================================================== 5 · flag-registry
def test_flag_registry_missing_define(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": """
        from common.flags import flags

        def f():
            return flags.get("never_defined_anywhere")
    """}, checks=["flag-registry"])
    assert names(vs) == ["flag-registry"]
    assert "never_defined_anywhere" in vs[0].message


def test_flag_registry_dead_define(tmp_path):
    vs = run_fixture(tmp_path, {"flagdefs.py": """
        from common.flags import flags

        flags.define("dead_knob", 1, "never read")
    """}, checks=["flag-registry"])
    assert names(vs) == ["flag-registry"]
    assert "dead_knob" in vs[0].message


def test_flag_registry_defined_and_read_is_clean(tmp_path):
    assert run_fixture(tmp_path, {"flagdefs.py": """
        from common.flags import flags

        flags.define("live_knob", 1, "read below")

        def f():
            return flags.get("live_knob")
    """}, checks=["flag-registry"]) == []


# ================================================== 6 · span-registry
_SPAN_REG = """
    from common import tracing

    SPAN_NAMES = ("graph.query", "rpc.client")

    def f():
        with tracing.span("rpc.client"):
            pass

    def g():
        with tracing.start_trace("graph.query", forced=True):
            pass
"""


def test_span_registry_clean(tmp_path):
    assert run_fixture(tmp_path, {"tracing.py": _SPAN_REG},
                       checks=["span-registry"]) == []


def test_span_registry_unknown_name(tmp_path):
    bad = _SPAN_REG.replace('tracing.span("rpc.client")',
                            'tracing.span("rpc.mystery")')
    vs = run_fixture(tmp_path, {"tracing.py": bad},
                     checks=["span-registry"])
    msgs = [v.message for v in vs]
    assert any("rpc.mystery" in m and "not in the SPAN_NAMES" in m
               for m in msgs)
    # the now-unused registry entry is flagged dead too
    assert any("'rpc.client'" in m and "never used" in m for m in msgs)


def test_span_registry_dynamic_name_rejected(tmp_path):
    bad = _SPAN_REG.replace('tracing.span("rpc.client")',
                            'tracing.span(name)')
    vs = run_fixture(tmp_path, {"tracing.py": bad},
                     checks=["span-registry"])
    assert any("literal" in v.message for v in vs)


def test_span_registry_requires_single_registry(tmp_path):
    files = {"tracing.py": _SPAN_REG,
             "other.py": 'SPAN_NAMES = ("dup.reg",)\n'}
    vs = run_fixture(tmp_path, files, checks=["span-registry"])
    assert any("ONE registry" in v.message for v in vs)


def test_span_registry_missing_registry(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": """
        from common import tracing

        def f():
            with tracing.span("orphan.name"):
                pass
    """}, checks=["span-registry"])
    assert any("no SPAN_NAMES registry" in v.message for v in vs)


def test_span_registry_ignores_unrelated_span_calls(tmp_path):
    """A local helper also called span() (numpy span, etc.) must not
    trip the check — only tracing.* receivers count."""
    assert run_fixture(tmp_path, {"mod.py": """
        def span(x):
            return x

        def f():
            return span("whatever")
    """}, checks=["span-registry"]) == []


# ================================================ 7 · metric-registry
_METRIC_REG = """
    from common.stats import stats

    METRIC_NAMES = ("graph.qps", "graph.stmt.*", "raft.term")

    def f(kind):
        stats.add_value("graph.qps")
        stats.observe(f"graph.stmt.{kind}.latency_us", 1.0)
        stats.set_gauge("raft.term", 3, space=1)
"""


def test_metric_registry_clean(tmp_path):
    assert run_fixture(tmp_path, {"stats.py": _METRIC_REG},
                       checks=["metric-registry"]) == []


def test_metric_registry_unknown_name(tmp_path):
    bad = _METRIC_REG.replace('stats.add_value("graph.qps")',
                              'stats.add_value("graph.mystery")')
    vs = run_fixture(tmp_path, {"stats.py": bad},
                     checks=["metric-registry"])
    msgs = [v.message for v in vs]
    assert any("graph.mystery" in m and "not in the METRIC_NAMES" in m
               for m in msgs)
    # the now-unused registry entry is flagged dead too
    assert any("'graph.qps'" in m and "never used" in m for m in msgs)


def test_metric_registry_fstring_needs_wildcard(tmp_path):
    bad = _METRIC_REG.replace(
        'stats.observe(f"graph.stmt.{kind}.latency_us", 1.0)',
        'stats.observe(f"rogue.family.{kind}", 1.0)')
    vs = run_fixture(tmp_path, {"stats.py": bad},
                     checks=["metric-registry"])
    msgs = [v.message for v in vs]
    assert any("rogue.family." in m and "not in the METRIC_NAMES" in m
               for m in msgs)
    assert any("'graph.stmt.*'" in m and "never used" in m for m in msgs)


def test_metric_registry_short_fstring_head_rejected(tmp_path):
    """An f-string whose literal head is a PREFIX of a wildcard entry
    ("graph." under "graph.stmt.*") could name any family — it must
    NOT satisfy the registry."""
    bad = _METRIC_REG.replace(
        'stats.observe(f"graph.stmt.{kind}.latency_us", 1.0)',
        'stats.observe(f"graph.{kind}", 1.0)')
    vs = run_fixture(tmp_path, {"stats.py": bad},
                     checks=["metric-registry"])
    assert any("'graph.'" in v.message and "not in the METRIC_NAMES"
               in v.message for v in vs)


def test_metric_registry_dynamic_name_rejected(tmp_path):
    bad = _METRIC_REG.replace('stats.add_value("graph.qps")',
                              'stats.add_value(kind)')
    vs = run_fixture(tmp_path, {"stats.py": bad},
                     checks=["metric-registry"])
    assert any("literal" in v.message for v in vs)


def test_metric_registry_ifexp_literals_resolved(tmp_path):
    ok = _METRIC_REG.replace(
        'stats.add_value("graph.qps")',
        'stats.add_value("graph.qps" if kind else "raft.term")')
    # both arms resolve; raft.term now has a second use — still clean
    assert run_fixture(tmp_path, {"stats.py": ok},
                       checks=["metric-registry"]) == []


def test_metric_registry_requires_single_registry(tmp_path):
    files = {"stats.py": _METRIC_REG,
             "other.py": 'METRIC_NAMES = ("dup.reg",)\n'}
    vs = run_fixture(tmp_path, files, checks=["metric-registry"])
    assert any("ONE registry" in v.message for v in vs)


def test_metric_registry_missing_registry(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": """
        from common.stats import stats

        def f():
            stats.add_value("orphan.metric")
    """}, checks=["metric-registry"])
    assert any("no METRIC_NAMES registry" in v.message for v in vs)


def test_metric_registry_ignores_unrelated_receivers(tmp_path):
    """Only stats-ish receivers count — a runtime's own `self.stats`
    dict ops or random add_value helpers must not trip the check."""
    assert run_fixture(tmp_path, {"mod.py": """
        def add_value(x):
            return x

        class R:
            def f(self):
                return add_value("whatever")
    """}, checks=["metric-registry"]) == []


def test_metric_registry_suppression_round_trip(tmp_path):
    bad = _METRIC_REG.replace(
        'stats.add_value("graph.qps")',
        'stats.add_value("graph.qps")\n'
        '        stats.add_value(kind)  '
        '# nebulint: disable=metric-registry')
    assert run_fixture(tmp_path, {"stats.py": bad},
                       checks=["metric-registry"]) == []


# ====================================================== baseline rules
def test_baseline_entry_requires_reason():
    with pytest.raises(LintError):
        Baseline([{"check": "status-discard", "file": "x.py",
                   "symbol": "f", "reason": "  "}])


def test_baseline_matches_and_reports_stale(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": _DISCARD},
                     checks=["status-discard"])
    bl = Baseline([
        {"check": "status-discard", "file": "pkg/mod.py",
         "symbol": "caller", "reason": "fixture"},
        {"check": "status-discard", "file": "pkg/gone.py",
         "symbol": "f", "reason": "stale entry"},
    ])
    assert [v for v in vs if not bl.match(v)] == []
    assert [e["file"] for e in bl.unused()] == ["pkg/gone.py"]


# ============================================== whole-package tier-1 gate
def test_package_is_clean():
    """THE gate: nebulint over nebula_tpu reports zero unsuppressed
    violations (suppressions and baseline entries each carry a reason)."""
    vs, _bl = run_lint(PKG_ROOT, baseline_path=DEFAULT_BASELINE)
    assert vs == [], "unsuppressed nebulint violations:\n" + "\n".join(
        repr(v) for v in vs)


def test_package_has_no_stale_baseline_entries():
    vs, bl = run_lint(PKG_ROOT, baseline_path=DEFAULT_BASELINE)
    if bl is not None:
        stale = bl.unused()
        assert stale == [], f"stale baseline entries: {stale}"


def test_all_checks_registered():
    assert set(ALL_CHECKS) == {"lock-discipline", "lock-order",
                               "status-discard", "jax-hotpath",
                               "flag-registry", "span-registry",
                               "metric-registry", "event-registry",
                               "guard-inference", "blocking-under-lock",
                               "context-capture", "jaxpr-audit",
                               "mesh-audit", "carveout-inventory",
                               "wire-contract", "obligation-tracking",
                               "protocol-registry", "mc-coverage",
                               "stale-suppression"}


# ========================================== OrderedLock runtime watchdog
def test_watchdog_detects_seeded_inversion():
    """The mini-TSan self-test demanded by the acceptance criteria: two
    threads acquiring two ranks in opposite orders — even without losing
    the race — must produce a recorded inversion."""
    from nebula_tpu.common.ordered_lock import OrderedLock, watchdog
    a = OrderedLock("selftest.A")
    b = OrderedLock("selftest.B")
    watchdog.enable()
    try:
        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        for fn in (ab, ba):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        violations = watchdog.drain()
    finally:
        watchdog.disable()
    assert violations, "seeded inversion went undetected"
    assert "selftest.A" in violations[0] and "selftest.B" in violations[0]


def test_watchdog_consistent_order_is_clean():
    from nebula_tpu.common.ordered_lock import OrderedLock, watchdog
    a = OrderedLock("clean.A")
    b = OrderedLock("clean.B")
    watchdog.enable()
    try:
        for _ in range(3):
            with a:
                with b:
                    pass
        violations = watchdog.drain()
    finally:
        watchdog.disable()
    assert violations == []


def test_watchdog_strict_raises():
    from nebula_tpu.common.ordered_lock import (LockOrderError, OrderedLock,
                                                watchdog)
    a = OrderedLock("strict.A")
    b = OrderedLock("strict.B")
    watchdog.enable(strict=True)
    try:
        with a:
            with b:
                pass
        with pytest.raises(LockOrderError):
            with b:
                with a:
                    pass
    finally:
        watchdog.drain()
        watchdog.disable()


def test_ordered_lock_works_with_condition():
    """raftex wraps its part lock in a Condition — the OrderedLock must
    support wait/notify (full reentrant unwind mirrored in the
    watchdog's held stack)."""
    from nebula_tpu.common.ordered_lock import OrderedLock, watchdog
    lk = OrderedLock("cond.part", reentrant=True)
    cond = threading.Condition(lk)
    state = {"ready": False}
    watchdog.enable()
    try:
        def producer():
            with cond:
                state["ready"] = True
                cond.notify_all()

        t = threading.Thread(target=producer)
        with cond:
            with lk:   # reentrant: wait() must unwind BOTH levels
                t.start()
                assert cond.wait_for(lambda: state["ready"], timeout=5)
        t.join()
        assert watchdog.drain() == []
    finally:
        watchdog.disable()


def test_hotpath_mutable_static_args_flagged(tmp_path):
    vs = run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        f = jax.jit(lambda x: x, static_argnums=[0])
    """}, checks=["jax-hotpath"])
    assert names(vs) == ["jax-hotpath"]


def test_hotpath_mutable_literal_in_other_kwarg_not_flagged(tmp_path):
    """Only the static_arg* value itself may trip the mutable-literal
    rule — a list in donate_argnums/in_shardings must not."""
    assert run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        f = jax.jit(lambda x: x, static_argnums=(0,), donate_argnums=[1])
    """}, checks=["jax-hotpath"]) == []


def test_missing_explicit_baseline_is_config_error(tmp_path):
    with pytest.raises(LintError):
        run_lint(PKG_ROOT, baseline_path=str(tmp_path / "typo.json"))


# ================================================== 7 · jaxpr-audit
def _audit(specs, phases, span_names=("tpu.kernel",)):
    from nebula_tpu.tools.lint.jaxaudit import audit_specs
    vs, _kinds = audit_specs(specs, None, phases,
                             span_names, lambda s: ("pkg/fake.py", 1))
    return vs


def _spec(fn, avals, *, name="k", budget=4, donate=(), dispatch=(),
          frontier=(), buckets=None):
    from nebula_tpu.tpu.kernels import KernelSpec
    return KernelSpec(
        name, fn, phase_kind="k", budget=budget,
        instantiate=(buckets or (lambda fx: [(("k",), fn, avals)])),
        donate=donate, dispatch=dispatch, frontier=frontier)


_PHASES_1IN_1OUT = {"k": {"phases": ("tpu.kernel",), "h2d": 1, "d2h": 1}}


def test_jaxaudit_flags_loop_callback():
    """Seeded violation: a pure_callback inside the hop loop — the
    exact host-round-trip-per-hop class the audit exists to block."""
    import jax
    import numpy as np

    @jax.jit
    def bad(x):
        def body(i, acc):
            return acc + jax.pure_callback(
                lambda v: v, jax.ShapeDtypeStruct((8,), np.int32), x)
        return jax.lax.fori_loop(0, 4, body, x)

    vs = _audit([_spec(bad, (jax.ShapeDtypeStruct((8,), np.int32),),
                       dispatch=(0,))], _PHASES_1IN_1OUT)
    assert any("host callback" in v.message for v in vs), vs


def test_jaxaudit_flags_64bit_promotion():
    """Seeded violation: an int64 loop-carried buffer (visible because
    the audit traces under enable_x64)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def bad(x):
        def body(i, acc):
            return acc + x.astype(jnp.int64)
        acc0 = jnp.zeros(x.shape, jnp.int64)
        return jax.lax.fori_loop(0, 3, body, acc0).astype(jnp.int32)

    vs = _audit([_spec(bad, (jax.ShapeDtypeStruct((8,), np.int32),),
                       dispatch=(0,))], _PHASES_1IN_1OUT)
    assert any("int64" in v.message and "carry" in v.message
               for v in vs), vs


def test_jaxaudit_flags_unbounded_bucket_space():
    """Seeded violation: more distinct (cache key, signature) pairs
    than the declared retrace budget."""
    import jax
    import numpy as np

    @jax.jit
    def k(x):
        return x + 1

    def buckets(fx):
        return [((("k", s)), k, (jax.ShapeDtypeStruct((s,), np.int32),))
                for s in (8, 16, 32, 64)]

    vs = _audit([_spec(k, None, budget=2, dispatch=(0,),
                       buckets=buckets)], _PHASES_1IN_1OUT)
    assert any("retrace budget" in v.message for v in vs), vs


def test_jaxaudit_flags_donation_drift():
    """Seeded violations, both directions: claiming donation the jit
    doesn't perform, and donating what the spec says is cached."""
    import jax
    import numpy as np

    @jax.jit
    def undonated(x):
        return x + 1

    donated = jax.jit(lambda x: x + 1, donate_argnums=(0,))
    av = (jax.ShapeDtypeStruct((8,), np.int8),)
    vs = _audit([_spec(undonated, av, donate=(0,), dispatch=(0,))],
                _PHASES_1IN_1OUT)
    assert any("donation drift" in v.message for v in vs), vs
    vs = _audit([_spec(donated, av, donate=(), dispatch=(0,))],
                _PHASES_1IN_1OUT)
    assert any("donation drift" in v.message for v in vs), vs


def test_jaxaudit_flags_transfer_drift():
    """Seeded violation: a kernel growing a second output (an extra
    device->host fetch) without updating DEVICE_PHASES."""
    import jax
    import numpy as np

    @jax.jit
    def two_out(x):
        return x + 1, x * 2

    vs = _audit([_spec(two_out, (jax.ShapeDtypeStruct((8,), np.int32),),
                       dispatch=(0,))], _PHASES_1IN_1OUT)
    assert any("output fetches" in v.message for v in vs), vs


def test_jaxaudit_flags_wide_frontier():
    """Seeded violation: a declared frontier bitmap that is int32."""
    import jax
    import numpy as np

    @jax.jit
    def k(f):
        return f

    vs = _audit([_spec(k, (jax.ShapeDtypeStruct((8,), np.int32),),
                       dispatch=(0,), frontier=(0,))], _PHASES_1IN_1OUT)
    assert any("frontier argument" in v.message for v in vs), vs


def test_jaxaudit_package_registry_is_clean_within_budgets():
    """Acceptance: the auditor runs over EVERY registered kernel
    factory across all shape buckets and the per-kernel retrace-budget
    table holds — zero violations on the real registry."""
    from nebula_tpu.common.tracing import SPAN_NAMES
    from nebula_tpu.tools.lint.jaxaudit import audit_specs
    from nebula_tpu.tpu import runtime as rt
    from nebula_tpu.tpu.kernels import AuditFixture, kernel_registry

    registry = kernel_registry()
    assert {"go", "go_filtered", "bfs", "sharded_go", "ell_go",
            "sparse_go", "adaptive_go", "ell_bfs", "ell_absorb",
            "ell_absorb_sharded", "expr_filter"} <= set(registry)
    fx = AuditFixture()
    vs, kinds = audit_specs(registry.values(), fx, rt.DEVICE_PHASES,
                            SPAN_NAMES, lambda s: ("x", 1))
    assert vs == [], "\n".join(repr(v) for v in vs)
    # every spec declares a positive budget (the table is the proof
    # surface TestRetraceBudget's runtime smoke test now leans on)
    assert all(s.budget >= 1 for s in registry.values())


def test_jaxaudit_skips_fixture_roots(tmp_path):
    """Fixture packages have no device path: the package check is a
    no-op there (the self-tests above drive audit_specs directly)."""
    assert run_fixture(tmp_path, {"mod.py": "x = 1"},
                       checks=["jaxpr-audit"]) == []


# ================================================== 8 · wire-contract
_WIRE_ORPHANS = """
    class Client:
        def fetch(self, addr):
            resp = self.cm.call(addr, "fetchThing", {"space_id": 1})
            return resp

    class Service:
        def rpc_storeThing(self, req):
            return {"ok": True}
"""


def test_wirecheck_orphan_method_and_handler(tmp_path):
    vs = run_fixture(tmp_path, {"svc.py": _WIRE_ORPHANS},
                     checks=["wire-contract"])
    msgs = [v.message for v in vs]
    assert any("no rpc_fetchThing handler" in m for m in msgs), msgs
    assert any("rpc_storeThing has no in-tree caller" in m
               for m in msgs), msgs


_WIRE_DRIFT = """
    class Client:
        def put(self, addr):
            resp = self.cm.call(addr, "putThing",
                                {"space_id": 1, "stale_key": 2})
            return resp.get("phantom_field")

    class Service:
        def rpc_putThing(self, req):
            part = req["part_id"]
            return {"ok": True, "latency_us": 1}
"""


def test_wirecheck_argument_and_envelope_drift(tmp_path):
    vs = run_fixture(tmp_path, {"svc.py": _WIRE_DRIFT},
                     checks=["wire-contract"])
    msgs = [v.message for v in vs]
    # arity drift: required key never sent
    assert any("never sends key 'part_id'" in m for m in msgs), msgs
    # dead payload: sent key never read
    assert any("sends key 'stale_key'" in m for m in msgs), msgs
    # phantom envelope field: read but never written
    assert any("reads response field 'phantom_field'" in m
               for m in msgs), msgs
    # dead envelope field: written but no caller reads it
    assert any("'latency_us'" in m and "no caller reads" in m
               for m in msgs), msgs


def test_wirecheck_matched_contract_is_clean(tmp_path):
    ok = """
    class Client:
        def put(self, addr):
            resp = self.cm.call(addr, "putThing",
                                {"space_id": 1, "part_id": 2})
            return resp.get("ok")

    class Service:
        def rpc_putThing(self, req):
            part = req["part_id"]
            space = req.get("space_id")
            return {"ok": True}
    """
    assert run_fixture(tmp_path, {"svc.py": ok},
                       checks=["wire-contract"]) == []


def test_wirecheck_open_handlers_exempt_from_key_checks(tmp_path):
    """A handler that hands the request to non-self code (the storage
    processors) cannot be key-checked exactly — no false positives."""
    open_h = """
    class Client:
        def put(self, addr):
            return self.cm.call(addr, "putThing", {"anything": 1})

    class Service:
        def rpc_putThing(self, req):
            return process(req)
    """
    assert run_fixture(tmp_path, {"svc.py": open_h},
                       checks=["wire-contract"]) == []


def test_wirecheck_suppression_roundtrip(tmp_path):
    """Inline suppression silences a wire-contract finding like any
    other check."""
    suppressed = _WIRE_ORPHANS.replace(
        'resp = self.cm.call(addr, "fetchThing", {"space_id": 1})',
        'resp = self.cm.call(  # nebulint: disable=wire-contract\n'
        '                addr, "fetchThing", {"space_id": 1})').replace(
        "def rpc_storeThing(self, req):",
        "def rpc_storeThing(self, req):"
        "  # nebulint: disable=wire-contract")
    assert run_fixture(tmp_path, {"svc.py": suppressed},
                       checks=["wire-contract"]) == []


def test_wirecheck_delegation_resolves_alias_handlers(tmp_path):
    """rpc_X bodies that forward to rpc_Y inherit Y's request/response
    contract (the meta.thrift spelling aliases)."""
    alias = """
    class Client:
        def put(self, addr):
            resp = self.cm.call(addr, "createTag", {"name": "t"})
            return resp.get("id")

    class Service:
        def rpc_createTagSchema(self, req):
            name = req["name"]
            return {"id": 7}

        def rpc_createTag(self, req):
            return self.rpc_createTagSchema(req)
    """
    vs = run_fixture(tmp_path, {"svc.py": alias},
                     checks=["wire-contract"])
    # rpc_createTagSchema has no DIRECT caller but IS a delegation
    # target; the alias's contract resolves through it
    assert vs == [], vs


def test_wirecheck_scatter_gather_make_req_tuples(tmp_path):
    """The ``return "method", {...}`` make_req closures count as call
    sites (the StorageClient collect contract)."""
    sg = """
    class Client:
        def get_props(self):
            def make(parts):
                return "bulkFetch", {"space_id": 1}
            return self.collect(make)
    """
    vs = run_fixture(tmp_path, {"svc.py": sg}, checks=["wire-contract"])
    assert any("no rpc_bulkFetch handler" in v.message for v in vs), vs


# ================================================ lint wall-time guard
def test_lint_wall_time_budget():
    """The whole-package analysis (all eight checks, jaxpr tracing
    included) must stay fast enough to gate tier-1 — micro_bench's
    lint component enforces the tighter interactive budget."""
    import time
    t0 = time.perf_counter()
    run_lint(PKG_ROOT, baseline_path=DEFAULT_BASELINE)
    elapsed = time.perf_counter() - t0
    assert elapsed < 60.0, f"nebulint took {elapsed:.1f}s"


def test_wirecheck_frame_contract_drops_untraced_frame(tmp_path):
    """Seeded violation: interface/rpc.py losing the 2-element untraced
    frame (every call would pay the trace envelope)."""
    rpc = """
    _TRACED = "__spans__"
    _RESP = "__resp__"

    def client_call(method, payload, sp):
        return _pack([method, payload, [sp.trace_id, sp.span_id]])

    def server(frame):
        parts = _unpack(frame)
        method, payload = parts[0], parts[1]
        wctx = parts[2] if len(parts) > 2 else None
        return {_TRACED: [], _RESP: payload}

    def absorb(resp):
        return resp.get(_TRACED), resp.get(_RESP)
    """
    vs = run_fixture(tmp_path, {"interface/rpc.py": rpc},
                     checks=["wire-contract"])
    assert any("2-element" in v.message for v in vs), vs


def test_wirecheck_frame_contract_envelope_constant_drift(tmp_path):
    """Seeded violation: an envelope constant written server-side but
    never read by the client (dead piggyback payload)."""
    rpc = """
    _TRACED = "__spans__"
    _RESP = "__resp__"

    def client_call(method, payload):
        return _pack([method, payload])

    def client_traced(method, payload, sp):
        return _pack([method, payload, [sp.trace_id, sp.span_id]])

    def server(frame):
        parts = _unpack(frame)
        return {_TRACED: [], _RESP: parts[1]}

    def absorb(resp):
        return resp.get(_RESP)      # __spans__ never read
    """
    vs = run_fixture(tmp_path, {"interface/rpc.py": rpc},
                     checks=["wire-contract"])
    assert any("_TRACED" in v.message and "never read" in v.message
               for v in vs), vs


def test_wirecheck_endpoint_contract_drift(tmp_path):
    """Seeded violation: a contract endpoint returning a payload key
    the ENDPOINT_CONTRACT declaration doesn't name."""
    ws = """
    class WebService:
        def __init__(self):
            self.register_handler("/faults", self._faults)
            self.register_handler("/get_stats", self._get_stats)
            self.register_handler("/traces", self._traces)

        def _faults(self, q, body):
            return 200, {"seed": 1, "rules": [], "bogus_field": 2}

        def _get_stats(self, q, body):
            return 200, dump()

        def _traces(self, q, body):
            return 200, {"traces": []}
    """
    vs = run_fixture(tmp_path, {"webservice/service.py": ws},
                     checks=["wire-contract"])
    assert any("bogus_field" in v.message and "/faults" in v.message
               for v in vs), vs


# ================================================ 10 · event-registry
_EVENT_REG = """
    from common.events import journal

    EVENT_KINDS = ("raft.leader_elected", "query.shed")

    def f():
        journal.record("raft.leader_elected", detail="x")
        journal.record("query.shed", detail="y", space=1)
"""


def test_event_registry_clean(tmp_path):
    assert run_fixture(tmp_path, {"events.py": _EVENT_REG},
                       checks=["event-registry"]) == []


def test_event_registry_unknown_kind(tmp_path):
    bad = _EVENT_REG.replace('journal.record("query.shed"',
                             'journal.record("query.mystery"')
    vs = run_fixture(tmp_path, {"events.py": bad},
                     checks=["event-registry"])
    msgs = [v.message for v in vs]
    assert any("query.mystery" in m and "not in the EVENT_KINDS" in m
               for m in msgs)
    # the now-unrecorded registry entry is flagged dead too
    assert any("'query.shed'" in m and "never recorded" in m
               for m in msgs)


def test_event_registry_dynamic_kind_rejected(tmp_path):
    bad = _EVENT_REG.replace('journal.record("query.shed"',
                             'journal.record(kind')
    vs = run_fixture(tmp_path, {"events.py": bad},
                     checks=["event-registry"])
    assert any("literal" in v.message for v in vs)


def test_event_registry_single_registry(tmp_path):
    files = {"events.py": _EVENT_REG,
             "other.py": 'EVENT_KINDS = ("dup.kind",)\n'}
    vs = run_fixture(tmp_path, files, checks=["event-registry"])
    assert any("ONE registry" in v.message for v in vs)


def test_event_registry_ignores_unrelated_record_calls(tmp_path):
    """slow-log / router `.record` methods are out of scope — only a
    journal-named receiver is the event seam."""
    assert run_fixture(tmp_path, {"mod.py": """
        class R:
            def f(self, slow_log, router):
                slow_log.record("not an event", 12)
                router.record(("k",), "device", 1.0)
    """}, checks=["event-registry"]) == []


def test_event_registry_suppression_round_trip(tmp_path):
    bad = _EVENT_REG.replace(
        'journal.record("query.shed", detail="y", space=1)',
        'journal.record("query.mystery", detail="y")  '
        '# nebulint: disable=event-registry — fixture')
    vs = run_fixture(tmp_path, {"events.py": bad},
                     checks=["event-registry"])
    assert not any("query.mystery" in v.message for v in vs)


# ================================================ 11 · guard-inference
def test_guards_seeded_fixture_fires(tmp_path):
    """The checked-in deliberately-racy module must trip BOTH rules:
    the unguarded read and the mixed-lock access."""
    vs = run_fixture(tmp_path,
                     {"kvstore/racy.py": fixture_src("guards_racy.py")},
                     checks=["guard-inference"])
    msgs = [v.message for v in vs]
    assert any("unguarded read of self._entries" in m for m in msgs), msgs
    assert any("mixed-lock write of self._seq" in m and "_side" in m
               for m in msgs), msgs


def test_guards_fixed_fixture_is_clean(tmp_path):
    """Taking the right lock at both seeded sites silences the pass."""
    fixed = fixture_src("guards_racy.py").replace(
        "        return list(self._entries)",
        "        with self._lock:\n"
        "            return list(self._entries)").replace(
        "        with self._side:\n            self._seq = 0",
        "        with self._lock:\n            self._seq = 0")
    assert run_fixture(tmp_path, {"kvstore/racy.py": fixed},
                       checks=["guard-inference"]) == []


def test_guards_out_of_scope_path_ignored(tmp_path):
    """The same racy class outside the concurrency-bearing packages
    (GUARD_SCOPE) is not analysed — inference needs real threaded
    access patterns to be meaningful."""
    assert run_fixture(tmp_path,
                       {"parser/racy.py": fixture_src("guards_racy.py")},
                       checks=["guard-inference"]) == []


def test_guards_guarded_by_pin_overrides_majority(tmp_path):
    """A minority-guarded attribute is unflagged by inference; the
    guarded-by declaration pins it and the bare accesses light up."""
    src = """
        import threading

        class Pinned:
            def __init__(self):
                self._lock = threading.Lock()
                # nebulint: guarded-by=_lock
                self._cache = {}

            def fill(self, k, v):
                with self._lock:
                    self._cache[k] = v

            def peek_a(self, k):
                return self._cache.get(k)

            def peek_b(self, k):
                return self._cache.get(k)

            def peek_c(self, k):
                return self._cache.get(k)
    """
    # without the pin: 1 guarded / 3 bare -> no majority, clean
    unpinned = src.replace("                # nebulint: guarded-by=_lock\n",
                           "")
    assert run_fixture(tmp_path, {"kvstore/mod.py": unpinned},
                       checks=["guard-inference"]) == []
    vs = run_fixture(tmp_path, {"kvstore/mod.py": src},
                     checks=["guard-inference"])
    assert len([v for v in vs
                if "unguarded read of self._cache" in v.message]) == 3, vs


def test_guards_guarded_by_none_exempts(tmp_path):
    """guarded-by=none declares a deliberately unguarded attribute —
    majority inference is overridden the other way."""
    racy = fixture_src("guards_racy.py").replace(
        "        self._entries = []",
        "        # nebulint: guarded-by=none\n"
        "        self._entries = []")
    vs = run_fixture(tmp_path, {"kvstore/racy.py": racy},
                     checks=["guard-inference"])
    assert not any("_entries" in v.message for v in vs), vs


def test_guards_unknown_lock_name_flagged(tmp_path):
    """A pin naming a lock the class does not declare is itself a
    violation — stale declarations must not disable the analysis."""
    vs = run_fixture(tmp_path, {"kvstore/mod.py": """
        import threading

        class Typo:
            def __init__(self):
                self._lock = threading.Lock()
                # nebulint: guarded-by=_lok
                self._x = 0

            def a(self):
                with self._lock:
                    self._x += 1

            def b(self):
                with self._lock:
                    self._x += 1
    """}, checks=["guard-inference"])
    assert any("no lock named '_lok'" in v.message for v in vs), vs


def test_guards_caller_holds_contract(tmp_path):
    """A documented caller-holds method is analysed as holding every
    class lock (the locks.py convention, shared)."""
    ok = fixture_src("guards_racy.py").replace(
        "    def peek(self):",
        "    def peek(self):\n"
        '        """Caller holds the lock."""')
    vs = run_fixture(tmp_path, {"kvstore/racy.py": ok},
                     checks=["guard-inference"])
    assert not any("unguarded read" in v.message for v in vs), vs


def test_guards_suppression_round_trip(tmp_path):
    sup = fixture_src("guards_racy.py").replace(
        "        return list(self._entries)",
        "        return list(self._entries)  "
        "# nebulint: disable=guard-inference").replace(
        "            self._seq = 0",
        "            self._seq = 0  # nebulint: disable=guard-inference")
    assert run_fixture(tmp_path, {"kvstore/racy.py": sup},
                       checks=["guard-inference"]) == []


def test_guards_init_only_attrs_exempt(tmp_path):
    """Configuration wired in __init__ before threads exist is never
    flagged, even when other attrs establish a guard."""
    vs = run_fixture(tmp_path, {"kvstore/mod.py": """
        import threading

        class Cfg:
            def __init__(self):
                self._lock = threading.Lock()
                self.limit = 10
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def bump2(self):
                with self._lock:
                    self._n += 1

            def read(self):
                return self.limit
    """}, checks=["guard-inference"])
    assert vs == [], vs


# ============================================ 12 · blocking-under-lock
def test_blocking_seeded_fixture_fires(tmp_path):
    """The PR 6 bug class, reconstructed: an RPC fan-out reached only
    THROUGH a helper call while the catalog-style lock is held."""
    vs = run_fixture(tmp_path,
                     {"svc.py": fixture_src("blocking_racy.py")},
                     checks=["blocking-under-lock"])
    assert len(vs) == 1, vs
    v = vs[0]
    assert "rpc" in v.message and "_fan_out()" in v.message
    assert v.symbol == "RacyCatalog.rpc_download"


def test_blocking_fixed_fixture_is_clean(tmp_path):
    """Moving the fan-out OUT of the locked region (snapshot under the
    lock, dial outside — the rpc_download fix shape) silences it."""
    fixed = fixture_src("blocking_racy.py").replace(
        """    def rpc_download(self, req):
        with self._lock:
            # 120 s of peer dials under the write lock
            self._fan_out("download")
            return {"ok": True}""",
        """    def rpc_download(self, req):
        with self._lock:
            pending = list(self.hosts)
        del pending
        self._fan_out("download")
        return {"ok": True}""")
    assert run_fixture(tmp_path, {"svc.py": fixed},
                       checks=["blocking-under-lock"]) == []


def test_blocking_direct_sleep_left_to_lock_discipline(tmp_path):
    """A DIRECT sleep under a lock is lock-discipline's finding — this
    pass must not duplicate it (only interprocedural reachability and
    the new effect classes are its job)."""
    assert run_fixture(tmp_path, {"svc.py": """
        import threading
        import time

        class D:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1)
    """}, checks=["blocking-under-lock"]) == []


def test_blocking_untimed_wait_on_other_lock(tmp_path):
    """Waiting (no timeout) on some OTHER condition while holding a
    lock is an unbounded stall; waiting on the condition that wraps
    the single held lock is how Conditions work — clean."""
    vs = run_fixture(tmp_path, {"svc.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.other = threading.Condition()

            def stall(self):
                with self._lock:
                    self.other.wait()
    """}, checks=["blocking-under-lock"])
    assert len(vs) == 1 and "cond-wait" in vs[0].message, vs
    assert run_fixture(tmp_path, {"svc.py": """
        import threading

        class W:
            def __init__(self):
                self.cond = threading.Condition()

            def ok(self):
                with self.cond:
                    self.cond.wait()
    """}, checks=["blocking-under-lock"]) == []


def test_blocking_timed_wait_is_clean(tmp_path):
    assert run_fixture(tmp_path, {"svc.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.other = threading.Condition()

            def bounded(self):
                with self._lock:
                    self.other.wait(0.5)
    """}, checks=["blocking-under-lock"]) == []


def test_blocking_device_sync_under_lock(tmp_path):
    vs = run_fixture(tmp_path, {"svc.py": """
        import threading

        class D:
            def __init__(self):
                self._lock = threading.Lock()

            def publish(self, arrs):
                with self._lock:
                    for a in arrs:
                        a.block_until_ready()
    """}, checks=["blocking-under-lock"])
    assert len(vs) == 1 and "device" in vs[0].message, vs


def test_blocking_caller_holds_vouches_file_io_not_rpc(tmp_path):
    """A caller-holds docstring vouches for bounded disk I/O (the raft
    hard-state fsync pattern) but can NEVER vouch for an RPC dial."""
    vouched_io = """
        import threading
        import os

        class P:
            def __init__(self):
                self._lock = threading.Lock()

            def _persist(self):
                \"\"\"Caller holds the lock.\"\"\"
                with open("/tmp/x", "w") as f:
                    os.fsync(f.fileno())

            def commit(self):
                with self._lock:
                    self._persist()
    """
    assert run_fixture(tmp_path, {"svc.py": vouched_io},
                       checks=["blocking-under-lock"]) == []
    vouched_rpc = vouched_io.replace(
        'with open("/tmp/x", "w") as f:\n'
        '                    os.fsync(f.fileno())',
        'self.cm.call("h", "persist", {})')
    vs = run_fixture(tmp_path, {"svc.py": vouched_rpc},
                     checks=["blocking-under-lock"])
    assert len(vs) == 1 and "rpc" in vs[0].message, vs


def test_blocking_nested_def_not_charged_to_encloser(tmp_path):
    """A closure DEFINED under the lock runs later on its own stack —
    defining it is free; only calling it under the lock blocks."""
    assert run_fixture(tmp_path, {"svc.py": """
        import threading
        import time

        class D:
            def __init__(self):
                self._lock = threading.Lock()

            def arm(self):
                with self._lock:
                    def later():
                        time.sleep(1)
                    self.cb = later
    """}, checks=["blocking-under-lock"]) == []


def test_blocking_suppression_round_trip(tmp_path):
    sup = fixture_src("blocking_racy.py").replace(
        '            self._fan_out("download")',
        '            # nebulint: disable=blocking-under-lock\n'
        '            self._fan_out("download")')
    assert run_fixture(tmp_path, {"svc.py": sup},
                       checks=["blocking-under-lock"]) == []


# ============================================== 13 · context-capture
def test_capture_seeded_fixture_fires_all_three(tmp_path):
    """The checked-in fixture drops the trace AND the deadline at the
    submission, and consults the dead binding in the worker."""
    vs = run_fixture(tmp_path,
                     {"client.py": fixture_src("capture_racy.py")},
                     checks=["context-capture"])
    msgs = [v.message for v in vs]
    assert any("never calls tracing.attach_captured" in m
               for m in msgs), msgs
    assert any("never rebinds the budget" in m for m in msgs), msgs
    assert any("consulted on a pool thread" in m for m in msgs), msgs


def test_capture_rebinding_worker_is_clean(tmp_path):
    """The storage/client.py collect/_call_host idiom — capture on the
    submitting side, attach + bind in the worker — is the clean
    shape."""
    fixed = fixture_src("capture_racy.py").replace(
        """    def _worker(self, host, dl):
        # consults the submitting thread's binding, which is gone
        timeout = deadlines.remaining_or(10.0)
        return self.cm.call(host, "bulkGet", {}, timeout=timeout)""",
        """    def _worker(self, host, dl, tctx=None):
        with tracing.attach_captured(tctx):
            with deadlines.bind(dl):
                timeout = deadlines.remaining_or(10.0)
                return self.cm.call(host, "bulkGet", {},
                                    timeout=timeout)""")
    assert run_fixture(tmp_path, {"client.py": fixed},
                       checks=["context-capture"]) == []


def test_capture_unbound_background_thread_is_clean(tmp_path):
    """A daemon background thread started OUTSIDE any span/deadline
    scope carries no context to drop — never flagged."""
    assert run_fixture(tmp_path, {"daemon.py": """
        import threading

        class Rebuilder:
            def kick(self, space_id):
                t = threading.Thread(target=self._rebuild,
                                     args=(space_id,), daemon=True)
                t.start()

            def _rebuild(self, space_id):
                return space_id
    """}, checks=["context-capture"]) == []


def test_capture_thread_target_from_span_scope(tmp_path):
    """Thread(target=...) inside a span is a submission too."""
    vs = run_fixture(tmp_path, {"mod.py": """
        import threading
        from common import tracing

        class T:
            def go(self):
                with tracing.span("graph.query"):
                    threading.Thread(target=self._work).start()

            def _work(self):
                return 1
    """}, checks=["context-capture"])
    assert len(vs) == 1 and "attach_captured" in vs[0].message, vs


def test_capture_unresolvable_worker_skipped(tmp_path):
    """An externally imported worker can't be proven either way — the
    pass stays package-local and silent."""
    assert run_fixture(tmp_path, {"mod.py": """
        from common import tracing
        from elsewhere import external_worker

        class T:
            def go(self, pool):
                with tracing.span("graph.query"):
                    pool.submit(external_worker, 1)
    """}, checks=["context-capture"]) == []


def test_capture_suppression_round_trip(tmp_path):
    sup = fixture_src("capture_racy.py").replace(
        "            futs = [self.pool.submit(self._worker, h, dl) "
        "for h in hosts]",
        "            # background probe: budget deliberately not "
        "inherited\n"
        "            # nebulint: disable=context-capture\n"
        "            futs = [self.pool.submit(self._worker, h, dl) "
        "for h in hosts]").replace(
        "        timeout = deadlines.remaining_or(10.0)",
        "        timeout = deadlines.remaining_or(10.0)  "
        "# nebulint: disable=context-capture")
    assert run_fixture(tmp_path, {"client.py": sup},
                       checks=["context-capture"]) == []


# ============================================ 14 · stale-suppression
def test_stale_suppression_flags_fossil(tmp_path):
    """A disable= comment whose check runs clean at that site is
    itself a violation."""
    src = _DISCARD.replace(
        "    save()",
        "    st = save()  # nebulint: disable=status-discard\n"
        "    return st")
    vs = run_fixture(tmp_path, {"mod.py": src},
                     checks=["status-discard", "stale-suppression"])
    assert len(vs) == 1, vs
    assert vs[0].check == "stale-suppression"
    assert "status-discard" in vs[0].message


def test_stale_suppression_live_comment_not_flagged(tmp_path):
    """A suppression that actually suppresses is not stale."""
    src = _DISCARD.replace(
        "    save()", "    save()  # nebulint: disable=status-discard")
    assert run_fixture(tmp_path, {"mod.py": src},
                       checks=["status-discard",
                               "stale-suppression"]) == []


def test_stale_suppression_only_for_checks_that_ran(tmp_path):
    """A fossil for a check that did NOT run this invocation is not
    judged — partial runs must not produce false staleness."""
    src = _DISCARD.replace(
        "    save()",
        "    st = save()  # nebulint: disable=status-discard\n"
        "    return st")
    assert run_fixture(tmp_path, {"mod.py": src},
                       checks=["lock-order", "stale-suppression"]) == []


def test_stale_suppression_disable_all_exempt(tmp_path):
    """disable=all cannot be attributed to one check — never stale."""
    src = _DISCARD.replace(
        "    save()",
        "    st = save()  # nebulint: disable=all\n    return st")
    assert run_fixture(tmp_path, {"mod.py": src},
                       checks=["status-discard",
                               "stale-suppression"]) == []


def test_stale_suppression_stale_file_disable(tmp_path):
    import textwrap as _tw
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "mod.py").write_text(
        "# nebulint: disable-file=lock-order\nx = 1\n")
    vs = lint_paths(str(root), checks=["lock-order", "stale-suppression"],
                    repo_root=str(tmp_path))
    assert len(vs) == 1 and "disable-file" in vs[0].message, vs


# ====================================== 15 · jaxpr-audit: HBM budget
def _hbm_audit(specs, hbm):
    from nebula_tpu.common.tracing import SPAN_NAMES  # noqa: F401
    from nebula_tpu.tools.lint.jaxaudit import audit_specs
    vs, _k = audit_specs(specs, None, _PHASES_1IN_1OUT, ("tpu.kernel",),
                         lambda s: ("pkg/fake.py", 1), hbm=hbm)
    return vs


def test_hbm_budget_seeded_violation():
    """Seeded violation: a bucket whose resident bytes exceed the
    declared per-device budget fails the rung gate."""
    import jax
    import numpy as np

    @jax.jit
    def k(x):
        return x + 1

    av = (jax.ShapeDtypeStruct((1 << 16,), np.int32),)   # 256 KiB
    vs = _hbm_audit([_spec(k, av, dispatch=(0,))],
                    {"device_hbm_bytes": 1 << 10})
    assert any("per-device HBM budget" in v.message for v in vs), vs
    # and the same spec fits a real-sized budget
    vs = _hbm_audit([_spec(k, av, dispatch=(0,))],
                    {"device_hbm_bytes": 1 << 30})
    assert not any("HBM budget" in v.message for v in vs), vs


def test_hbm_donation_accounting():
    """A donated single-use input's buffer is reused for the output —
    the peak must not double-count it."""
    import jax
    import numpy as np

    donated = jax.jit(lambda x: x + 1, donate_argnums=(0,))
    n = 1 << 14
    av = (jax.ShapeDtypeStruct((n,), np.int8),)
    # budget fits input+0 extra but NOT input+output undonated
    budget = int(n * 1.5)
    vs = _hbm_audit([_spec(donated, av, donate=(0,), dispatch=(0,))],
                    {"device_hbm_bytes": budget})
    assert not any("HBM budget" in v.message for v in vs), vs
    undonated = jax.jit(lambda x: x + 1)
    vs = _hbm_audit([_spec(undonated, av, dispatch=(0,))],
                    {"device_hbm_bytes": budget})
    assert any("per-device HBM budget" in v.message for v in vs), vs


def test_hbm_ceiling_arithmetic():
    """The published-capacity proof: ceiling x bytes/edge must fit the
    table budget, which must fit the device."""
    from nebula_tpu.tools.lint.jaxaudit import hbm_ceiling_findings
    ok = {"device_hbm_bytes": 16 * 1000**3,
          "table_budget_bytes": 14 * 1000**3,
          "table_bytes_per_edge": 21.9,
          "edge_ceiling": 639_000_000}
    assert hbm_ceiling_findings(ok) == []
    over = dict(ok, edge_ceiling=800_000_000)
    assert any("capacity claim" in m for m in hbm_ceiling_findings(over))
    squeezed = dict(ok, table_budget_bytes=17 * 1000**3)
    assert any("headroom" in m for m in hbm_ceiling_findings(squeezed))


def test_hbm_model_consistent_and_enforced_package_wide():
    """Acceptance: the shipped HBM_MODEL is arithmetically consistent,
    every registered kernel rung fits it, and the audit path is ARMED
    (a 1-byte budget makes every rung fail)."""
    from nebula_tpu.common.tracing import SPAN_NAMES
    from nebula_tpu.tools.lint.jaxaudit import (audit_specs,
                                                hbm_ceiling_findings)
    from nebula_tpu.tpu import runtime as rt
    from nebula_tpu.tpu.kernels import AuditFixture, kernel_registry

    assert hbm_ceiling_findings(rt.HBM_MODEL) == []
    registry = kernel_registry()
    fx = AuditFixture()
    vs, _ = audit_specs(registry.values(), fx, rt.DEVICE_PHASES,
                        SPAN_NAMES, lambda s: ("x", 1),
                        hbm=rt.HBM_MODEL)
    assert vs == [], "\n".join(repr(v) for v in vs)
    vs, _ = audit_specs(registry.values(), fx, rt.DEVICE_PHASES,
                        SPAN_NAMES, lambda s: ("x", 1),
                        hbm={"device_hbm_bytes": 1})
    assert any("per-device HBM budget" in v.message for v in vs)


def test_hbm_residency_rows_positive():
    """The docs budget table's source: every registered kernel bucket
    reports a positive peak with mirror+dispatch+output parts."""
    import jax
    from jax.experimental import enable_x64
    from nebula_tpu.tools.lint.jaxaudit import hbm_residency
    from nebula_tpu.tpu.kernels import AuditFixture, kernel_registry

    fx = AuditFixture()
    spec = kernel_registry()["ell_go"]
    key, fn, avals = spec.instantiate(fx)[0]
    with enable_x64():
        closed = jax.make_jaxpr(fn)(*avals)
    mirror_b, dispatch_b, out_b, peak = hbm_residency(spec, closed, avals)
    assert mirror_b > 0 and dispatch_b > 0 and out_b > 0
    assert peak >= mirror_b + dispatch_b


# ==================== round-10 audit regressions (named fixes)
def test_guards_regression_device_ready_shape(tmp_path):
    """Regression for the round-10 audit fix in storage/service.py
    device_ready: a health probe reading lock-guarded runtime handles
    WITHOUT the lock.  The old shape must fire; the fixed (locked)
    shape must be clean."""
    racy = """
        import threading

        class Service:
            def __init__(self):
                self._device_rt_lock = threading.Lock()
                self._device_rt = None

            def rpc_a(self):
                with self._device_rt_lock:
                    self._device_rt = object()

            def rpc_b(self):
                with self._device_rt_lock:
                    self._device_rt = None

            def device_ready(self):
                return self._device_rt is not None
    """
    vs = run_fixture(tmp_path, {"storage/service.py": racy},
                     checks=["guard-inference"])
    assert any("unguarded read of self._device_rt" in v.message
               for v in vs), vs
    fixed = racy.replace(
        "                return self._device_rt is not None",
        "                with self._device_rt_lock:\n"
        "                    return self._device_rt is not None")
    assert run_fixture(tmp_path, {"storage/service.py": fixed},
                       checks=["guard-inference"]) == []


def test_window_s_takes_snapshot_value():
    """Regression for the round-10 audit fix in batch_dispatch: the
    pooling window computes from an EMA value the leader SNAPSHOTTED
    under the key's condition — the helper must not reach back into
    shared _KeyState after the lock was released."""
    import inspect
    from nebula_tpu.common.flags import flags
    from nebula_tpu.graph.batch_dispatch import GoBatchDispatcher

    d = GoBatchDispatcher(runtime=None)
    prev = flags.get("go_batch_window_ms")
    try:
        flags.set("go_batch_window_ms", -1)
        frac = float(flags.get("go_batch_window_frac"))
        # a plain float in, deterministic window out — no shared state
        assert abs(d._window_s(0.1) - min(
            0.1 * frac, d.window.cap_s())) < 1e-9
        assert d._window_s(0.0) == 0.0
    finally:
        flags.set("go_batch_window_ms", prev)
    params = list(inspect.signature(d._window_s).parameters)
    assert params == ["rt_ema_s"]


def test_stale_baseline_judged_only_for_ran_checks(tmp_path):
    """A partial --check run must not condemn baseline entries whose
    check never ran (caught by the round-10 verify drive: --check
    guard-inference reported all 24 wire-contract parity entries as
    stale and exited 1)."""
    vs, bl = run_lint(PKG_ROOT, baseline_path=DEFAULT_BASELINE,
                      checks=["guard-inference", "stale-suppression"])
    assert vs == []
    assert bl is not None and bl.unused() == []


def test_guards_wrapped_pin_attaches(tmp_path):
    """Review regression: a guarded-by pin whose comment wraps onto a
    continuation line must still attach to the first code line below
    it (the breaker's _cells pin is written exactly this way)."""
    src = """
        import threading

        class Pinned:
            def __init__(self):
                self._lock = threading.Lock()
                # nebulint: guarded-by=_lock (state transitions; the
                # fast paths below are documented exceptions)
                self._cache = {}

            def fill(self, k, v):
                with self._lock:
                    self._cache[k] = v

            def peek_a(self, k):
                return self._cache.get(k)

            def peek_b(self, k):
                return self._cache.get(k)

            def peek_c(self, k):
                return self._cache.get(k)
    """
    vs = run_fixture(tmp_path, {"kvstore/mod.py": src},
                     checks=["guard-inference"])
    assert len([v for v in vs
                if "unguarded read of self._cache" in v.message]) == 3, vs


def test_guards_orphan_pin_flagged(tmp_path):
    """A pin that attaches to no attribute line is itself a violation
    — a silently detached declaration would fake enforcement."""
    vs = run_fixture(tmp_path, {"kvstore/mod.py": """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                # nebulint: guarded-by=_lock

            def noop(self):
                return None
    """}, checks=["guard-inference"])
    assert any("attaches to no" in v.message for v in vs), vs


def test_capture_escape_deduped_across_submitters(tmp_path):
    """Review regression: one worker submitted from two sites is ONE
    escaped-deadline defect, not two."""
    src = fixture_src("capture_racy.py").replace(
        "    def _worker(self, host, dl):",
        "    def collect2(self, hosts):\n"
        "        with tracing.span(\"storage.collect.pass\"):\n"
        "            return [self.pool.submit(self._worker, h, None)\n"
        "                    for h in hosts]\n"
        "\n"
        "    def _worker(self, host, dl):")
    vs = run_fixture(tmp_path, {"client.py": src},
                     checks=["context-capture"])
    escapes = [v for v in vs if "consulted on a pool thread" in v.message]
    assert len(escapes) == 1, vs


def test_guards_mutator_counts_once(tmp_path):
    """Review regression: `self._q.append(x)` is ONE write access, not
    a write plus a read of the receiver — double-counting dilutes the
    majority below inference threshold and hides the race."""
    vs = run_fixture(tmp_path, {"kvstore/mod.py": """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []

            def a(self):
                with self._lock:
                    x = self._q

            def b(self):
                with self._lock:
                    y = self._q

            def push(self, x):
                self._q.append(x)
    """}, checks=["guard-inference"])
    # true counts: 2 guarded reads vs 1 unguarded write -> strict
    # majority -> exactly ONE violation (the write), not two
    assert len(vs) == 1, vs
    assert "unguarded write of self._q" in vs[0].message


def test_guards_pin_scoped_to_owning_class(tmp_path):
    """Review regression: a pin inside class A must not bleed onto a
    same-named attribute of class B in the same file."""
    vs = run_fixture(tmp_path, {"kvstore/mod.py": """
        import threading

        class A:
            def __init__(self):
                self._mu = threading.Lock()
                # nebulint: guarded-by=_mu
                self._cells = {}

            def w1(self):
                with self._mu:
                    self._cells[1] = 1

            def w2(self):
                with self._mu:
                    self._cells[2] = 2

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self._cells = {}

            def w1(self):
                with self._lock:
                    self._cells[1] = 1

            def w2(self):
                with self._lock:
                    self._cells[2] = 2
    """}, checks=["guard-inference"])
    # B must NOT report "declares no lock named '_mu'" from A's pin
    assert vs == [], vs


def test_blocking_mixed_with_items_alignment(tmp_path):
    """Review regression: `with tracing.span(...), self.cond:` then
    `self.cond.wait()` is the normal Condition idiom — the span item
    must not shift the rank/source pairing and fake a stall."""
    assert run_fixture(tmp_path, {"svc.py": """
        import threading

        class W:
            def __init__(self):
                self.cond = threading.Condition()

            def ok(self, tracing):
                with tracing.span("x"), self.cond:
                    self.cond.wait()
    """}, checks=["blocking-under-lock"]) == []


# ================================================ 15 · mesh-audit (v4)
def _mesh_fixture():
    """A tiny shared mesh-audit fixture: 2 devices are enough to make
    collectives real (tier-1 forces 8 virtual CPU devices)."""
    from nebula_tpu.tpu.kernels import AuditFixture
    return AuditFixture()


def _mesh_spec(fn, avals, *, name="mk", collective=None, ici=None,
               donate=(), shard_args=(), shard_outs=(), packed=(),
               frontier=()):
    from nebula_tpu.tpu.kernels import KernelSpec
    return KernelSpec(
        name, fn, phase_kind="mk", budget=4,
        instantiate=lambda fx: [],
        mesh_instantiate=lambda fx, mesh: [(("mk",
                                             mesh.shape["parts"]),
                                            fn, avals)],
        collective=collective, ici_bytes=ici, donate=donate,
        shard_args=shard_args, shard_outs=shard_outs, packed=packed,
        frontier=frontier)


def _mesh_audit(specs, hbm=None, sizes=(2,)):
    from nebula_tpu.tools.lint.meshaudit import mesh_audit_specs
    return mesh_audit_specs(specs, _mesh_fixture(),
                            lambda s: ("pkg/fake.py", 1), hbm=hbm,
                            sizes=sizes)


def _psum_kernel(fx, mesh):
    """A shard_map kernel whose ONLY collective is a psum over parts."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from nebula_tpu.tpu.compat import shard_map

    def per_shard(x):
        return jax.lax.psum(x, "parts")

    return jax.jit(shard_map(per_shard, mesh=mesh, in_specs=(P("parts"),),
                             out_specs=P(), check_vma=False))


def test_meshaudit_flags_undeclared_collective():
    """Seeded violation: the trace psums but the COLLECTIVE_MODEL
    declares nothing — undeclared ICI traffic."""
    import numpy as np
    fx = _mesh_fixture()
    mesh = fx.mesh(2)
    kern = _psum_kernel(fx, mesh)
    spec = _mesh_spec(kern, (fx.aval((16,), np.float32),),
                      collective=(), ici=lambda fx, k: 1 << 20)
    vs = _mesh_audit([spec])
    assert any("UNDECLARED collective" in v.message
               and "psum" in v.message for v in vs), vs


def test_meshaudit_flags_implicit_resharding():
    """Seeded violation: a with_sharding_constraint re-replication the
    model does not declare — the implicit-all-gather class."""
    import numpy as np
    fx = _mesh_fixture()
    mesh = fx.mesh(2)
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    replicate = NamedSharding(mesh, P())

    @jax.jit
    def kern(x):
        return jax.lax.with_sharding_constraint(x * 2, replicate)

    spec = _mesh_spec(kern, (fx.aval((16, 8), np.uint8),),
                      collective=(("psum", ("parts",)),),
                      ici=lambda fx, k: 1 << 20)
    vs = _mesh_audit([spec])
    assert any("UNDECLARED collective" in v.message
               and "sharding_constraint" in v.message for v in vs), vs


def test_meshaudit_flags_stale_declared_collective():
    """A declared collective absent from the trace is a stale model."""
    import numpy as np
    import jax

    @jax.jit
    def kern(x):
        return x + 1

    spec = _mesh_spec(kern, (_mesh_fixture().aval((16,), np.float32),),
                      collective=(("psum", ("parts",)),),
                      ici=lambda fx, k: 1 << 20)
    vs = _mesh_audit([spec])
    assert any("absent from the k=2 trace" in v.message for v in vs), vs


def test_meshaudit_flags_ici_over_bound():
    """Seeded violation: measured exchange bytes above the declared
    ici_bytes bound."""
    import numpy as np
    fx = _mesh_fixture()
    kern = _psum_kernel(fx, fx.mesh(2))
    spec = _mesh_spec(kern, (fx.aval((1 << 12,), np.float32),),
                      collective=(("psum", ("parts",)),),
                      ici=lambda fx, k: 4)
    vs = _mesh_audit([spec])
    assert any("above the declared ici_bytes bound" in v.message
               for v in vs), vs


def test_meshaudit_flags_missing_ici_model():
    import numpy as np
    fx = _mesh_fixture()
    kern = _psum_kernel(fx, fx.mesh(2))
    spec = _mesh_spec(kern, (fx.aval((16,), np.float32),),
                      collective=(("psum", ("parts",)),))
    vs = _mesh_audit([spec])
    assert any("no ici_bytes bound declared" in v.message for v in vs), vs


def test_meshaudit_flags_over_budget_mesh_rung():
    """Seeded violation: per-shard residency (replicated arg dominates)
    over a tiny device budget."""
    import numpy as np
    import jax

    @jax.jit
    def kern(x):
        return x + 1

    fx = _mesh_fixture()
    spec = _mesh_spec(kern, (fx.aval((1 << 12,), np.float32),),
                      collective=())
    vs = _mesh_audit([spec], hbm={"device_hbm_bytes": 64})
    assert any("this mesh rung cannot serve" in v.message
               for v in vs), vs


def test_meshaudit_flags_closure_captured_buffer():
    """Seeded violation: a table closed over instead of passed as an
    argument — every chip would pin a replica."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    big = jnp.asarray(np.zeros((1 << 18,), np.float32))

    @jax.jit
    def kern(x):
        return x + big[:16]

    fx = _mesh_fixture()
    spec = _mesh_spec(kern, (fx.aval((16,), np.float32),),
                      collective=())
    vs = _mesh_audit([spec])
    assert any("closes over" in v.message for v in vs), vs


def test_meshaudit_int8_sharded_frontier_regression_fails():
    """THE layout gate the issue names: a sharded family regressing to
    the int8-per-lane frontier fails on the aval dtype at every mesh
    size."""
    import numpy as np
    import jax

    @jax.jit
    def kern(f):
        return f

    fx = _mesh_fixture()
    spec = _mesh_spec(kern, (fx.aval((49, 128), np.int8),),
                      collective=(), packed=(0,), frontier=(0,))
    vs = _mesh_audit([spec])
    assert any("not a bit-packed uint8 lane matrix" in v.message
               for v in vs), vs


def test_meshaudit_undeclared_sharded_family_flagged():
    """mesh_instantiate without a COLLECTIVE_MODEL (and vice versa)
    is itself a violation — no sharded family goes unaudited."""
    import numpy as np
    import jax

    @jax.jit
    def kern(x):
        return x

    fx = _mesh_fixture()
    spec = _mesh_spec(kern, (fx.aval((8,), np.float32),),
                      collective=None)
    vs = _mesh_audit([spec])
    assert any("without a declared COLLECTIVE_MODEL" in v.message
               for v in vs), vs
    from nebula_tpu.tpu.kernels import KernelSpec
    spec2 = KernelSpec("mk2", kern, phase_kind="mk", budget=1,
                       instantiate=lambda fx: [],
                       collective=(("psum", ("parts",)),))
    vs2 = _mesh_audit([spec2])
    assert any("unprovable" in v.message for v in vs2), vs2


def test_meshaudit_clean_declared_kernel_passes():
    """The fixed variant: declared psum + sane bounds = clean."""
    import numpy as np
    fx = _mesh_fixture()
    kern = _psum_kernel(fx, fx.mesh(2))
    spec = _mesh_spec(kern, (fx.aval((16,), np.float32),),
                      collective=(("psum", ("parts",)),),
                      ici=lambda fx, k: 1 << 20, shard_args=(0,))
    assert _mesh_audit([spec],
                       hbm={"device_hbm_bytes": 16 * 1000**3}) == []


def test_meshaudit_capacity_table_arithmetic():
    """The published multi-chip capacity table is arithmetic over the
    declarations: an over-claimed rung, a shrinking rung, and a k=1
    row disagreeing with HBM_MODEL all fire."""
    from nebula_tpu.tools.lint.meshaudit import mesh_capacity_findings
    hbm = {"table_bytes_per_edge": 20.0,
           "table_budget_bytes": 1000, "edge_ceiling": 50}
    ok = {"mesh_sizes": (1, 2), "capacity_edges": {1: 50, 2: 100}}
    assert mesh_capacity_findings(hbm, ok) == []
    over = {"mesh_sizes": (1, 2), "capacity_edges": {1: 50, 2: 200}}
    assert any("exceeds" in m for m in mesh_capacity_findings(hbm, over))
    shrink = {"mesh_sizes": (1, 2), "capacity_edges": {1: 50, 2: 40}}
    msgs = mesh_capacity_findings(hbm, shrink)
    assert any("below the previous rung" in m for m in msgs), msgs
    drift = {"mesh_sizes": (1, 2), "capacity_edges": {1: 40, 2: 80}}
    assert any("disagrees" in m for m in mesh_capacity_findings(
        hbm, drift))
    missing = {"mesh_sizes": (1, 2, 4), "capacity_edges": {1: 50}}
    assert any("do not match mesh_sizes" in m
               for m in mesh_capacity_findings(hbm, missing))


def test_meshaudit_package_registry_is_clean():
    """Every registered sharded family proves its COLLECTIVE_MODEL,
    ICI bound and per-shard residency at every audited mesh size —
    the tier-1 half of the acceptance criteria (mesh shapes {1,2,4,8}
    under the conftest-forced 8-device platform)."""
    import jax
    assert len(jax.devices()) >= 8, jax.devices()
    vs = lint_paths(PKG_ROOT, checks=["mesh-audit"])
    assert vs == [], "\n".join(repr(v) for v in vs)


def test_meshaudit_registry_covers_all_sharded_families():
    """Every kernel family whose factory builds on a Mesh must carry
    mesh_instantiate — a new sharded kernel cannot ship unaudited."""
    from nebula_tpu.tpu.kernels import kernel_registry
    reg = kernel_registry()
    sharded = {name for name, s in reg.items()
               if "sharded" in name or "mesh" in name}
    assert sharded == {"sharded_go", "ell_go_sharded",
                       "ell_bfs_sharded", "mesh_sparse_go",
                       "mesh_sparse_bfs", "ell_absorb_sharded"}
    for name in sharded:
        assert reg[name].mesh_instantiate is not None, name
        assert reg[name].collective is not None, name
        assert reg[name].ici_bytes is not None, name


def test_meshaudit_suppression_roundtrip(tmp_path):
    """A justified mesh finding suppresses like any other check: the
    capacity-table finding anchors at MESH_MODEL in a fixture
    runtime.py (fixture roots carry no kernel registry, so only the
    declaration checks run there)."""
    bad = """
    MESH_CARVEOUTS = {}
    """
    vs = run_fixture(tmp_path, {"tpu/runtime.py": bad},
                     checks=["mesh-audit"])
    assert vs == []        # no registry module -> no trace findings


# ====================================== 16 · carveout-inventory (v4)
def test_carveout_fixture_fires_all_three():
    src = fixture_src("carveout_racy.py")
    import tempfile
    import textwrap
    with tempfile.TemporaryDirectory() as td:
        root = os.path.join(td, "pkg")
        os.makedirs(os.path.join(root, "tpu"))
        with open(os.path.join(root, "tpu", "runtime.py"), "w") as fh:
            fh.write(textwrap.dedent(src))
        vs = lint_paths(root, checks=["carveout-inventory"],
                        repo_root=td)
    msgs = [v.message for v in vs]
    assert any("untagged carve-out" in m for m in msgs), msgs
    assert any("unknown carve-out reason "
               "'not-a-registered-reason'" in m for m in msgs), msgs
    assert any("dead carve-out registry entry 'ghost-reason'" in m
               for m in msgs), msgs
    # exactly two untagged sites (one gate return, one raise)
    assert sum("untagged carve-out" in m for m in msgs) == 2, msgs


def test_carveout_clean_module_passes(tmp_path):
    clean = """
    class TpuDecline(Exception):
        pass

    MESH_CARVEOUTS = {
        "plan-decline": "planner cannot reproduce the query",
    }

    def can_run_go(space_id):
        if space_id < 0:
            return False        # nebulint: carveout=plan-decline
        return True

    def serve(space_id):
        if space_id == 1:
            # nebulint: carveout=plan-decline
            raise TpuDecline("nope")
    """
    assert run_fixture(tmp_path, {"tpu/runtime.py": clean},
                       checks=["carveout-inventory"]) == []


def test_carveout_missing_registry_flagged(tmp_path):
    src = """
    class TpuDecline(Exception):
        pass

    def serve():
        raise TpuDecline("nope")
    """
    vs = run_fixture(tmp_path, {"tpu/runtime.py": src},
                     checks=["carveout-inventory"])
    assert any("no MESH_CARVEOUTS registry" in v.message for v in vs), vs


def test_carveout_reason_without_justification_flagged(tmp_path):
    src = """
    class TpuDecline(Exception):
        pass

    MESH_CARVEOUTS = {"x": ""}

    def serve():
        # nebulint: carveout=x
        raise TpuDecline("nope")
    """
    vs = run_fixture(tmp_path, {"tpu/runtime.py": src},
                     checks=["carveout-inventory"])
    assert any("carries no justification" in v.message for v in vs), vs


def test_carveout_scope_is_runtime_only(tmp_path):
    """TpuDecline raises OUTSIDE tpu/runtime.py are other modules'
    business (storage/device.py defines the type) — not this pass's."""
    src = """
    class TpuDecline(Exception):
        pass

    def serve():
        raise TpuDecline("nope")
    """
    assert run_fixture(tmp_path, {"storage/device.py": src},
                       checks=["carveout-inventory"]) == []


def test_carveout_suppression_roundtrip(tmp_path):
    src = """
    class TpuDecline(Exception):
        pass

    MESH_CARVEOUTS = {"y": "kept for the suppression round-trip"}

    def can_run_go(s):
        if s:
            return False        # nebulint: carveout=y
        return True

    def serve():  # noqa
        raise TpuDecline("x")  # nebulint: disable=carveout-inventory
    """
    assert run_fixture(tmp_path, {"tpu/runtime.py": src},
                       checks=["carveout-inventory"]) == []


def test_carveout_package_sites_all_tagged():
    vs = lint_paths(PKG_ROOT, checks=["carveout-inventory"])
    assert vs == [], "\n".join(repr(v) for v in vs)


# ================================================ 17 · incremental cache
def _cached_lint(root, repo_root, cache_dir):
    from nebula_tpu.tools.lint.cache import LintCache
    cache = LintCache(path=os.path.join(str(cache_dir), "cache.json"))
    vs = lint_paths(str(root), checks=["flag-registry"],
                    repo_root=str(repo_root), cache=cache)
    return vs, cache


def test_cache_hit_and_invalidation_on_edit(tmp_path):
    """The correctness contract: a warm cache replays, an EDIT to an
    in-scope file forces re-analysis and surfaces the new violation."""
    import textwrap
    root = tmp_path / "pkg"
    root.mkdir()
    mod = root / "m.py"
    mod.write_text(textwrap.dedent("""
        from common.flags import flags

        def f():
            return flags.get("undefined_flag_a")
    """))
    cdir = tmp_path / "cache"
    vs1, c1 = _cached_lint(root, tmp_path, cdir)
    assert c1.misses == 1 and c1.hits == 0
    n1 = len(vs1)
    vs2, c2 = _cached_lint(root, tmp_path, cdir)
    assert c2.hits == 1 and c2.misses == 0
    assert [repr(v) for v in vs2] == [repr(v) for v in vs1]
    # edit the file: new flag read must be re-discovered, not replayed
    mod.write_text(mod.read_text().replace(
        'flags.get("undefined_flag_a")',
        'flags.get("undefined_flag_a"), flags.get("undefined_flag_b")'))
    vs3, c3 = _cached_lint(root, tmp_path, cdir)
    assert c3.misses == 1 and c3.hits == 0
    assert len(vs3) > n1
    assert any("undefined_flag_b" in v.message for v in vs3), vs3


def test_cache_suppression_still_live_on_replay(tmp_path):
    """A suppression added AFTER the cache was written must apply on
    replay (raw violations are cached pre-suppression) — and its
    suppress hit feeds stale-suppression as usual."""
    import textwrap
    root = tmp_path / "pkg"
    root.mkdir()
    mod = root / "m.py"
    mod.write_text(textwrap.dedent("""
        from common.flags import flags

        def f():
            return flags.get("undefined_flag_a")
    """))
    cdir = tmp_path / "cache"
    vs1, _ = _cached_lint(root, tmp_path, cdir)
    assert vs1, "fixture must fire"
    # suppressing the line EDITS the file -> miss; the point is the
    # round trip stays coherent through the cache layer
    mod.write_text(mod.read_text().replace(
        'return flags.get("undefined_flag_a")',
        'return flags.get("undefined_flag_a")  '
        '# nebulint: disable=flag-registry'))
    vs2, c2 = _cached_lint(root, tmp_path, cdir)
    assert vs2 == [] and c2.misses == 1
    # replay (no edit): suppression applies against CACHED raw results
    vs3, c3 = _cached_lint(root, tmp_path, cdir)
    assert vs3 == [] and c3.hits == 1


def test_cache_invalidated_by_lint_source_change(tmp_path, monkeypatch):
    """Check-version invalidation: a different lint-package sha drops
    every entry."""
    import textwrap
    import nebula_tpu.tools.lint.cache as cache_mod
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "m.py").write_text(textwrap.dedent("""
        def f():
            return 1
    """))
    cdir = tmp_path / "cache"
    _vs, c1 = _cached_lint(root, tmp_path, cdir)
    assert c1.misses == 1
    monkeypatch.setattr(cache_mod, "_LINT_SHA", "deadbeef")
    _vs, c2 = _cached_lint(root, tmp_path, cdir)
    assert c2.misses == 1 and c2.hits == 0


def test_cli_no_cache_flag(tmp_path, monkeypatch):
    """--no-cache runs clean end-to-end (and never writes the store)."""
    from nebula_tpu.tools.lint.__main__ import main
    import textwrap
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "m.py").write_text(textwrap.dedent("""
        def f():
            return 1
    """))
    monkeypatch.setenv("NEBULINT_CACHE_DIR", str(tmp_path / "cc"))
    rc = main(["--no-cache", "--no-baseline", str(root)])
    assert rc == 0
    assert not (tmp_path / "cc").exists()


# ==================================================== 18 · SARIF output
SARIF_GOLDEN = os.path.join(FIXTURE_DIR, "golden.sarif")


def _sarif_fixture_run(tmp_path, capsys):
    """One seeded flag-registry violation plus one seeded
    obligation-tracking violation through the CLI in SARIF mode; paths
    are repo-root-relative, so the payload is stable."""
    from nebula_tpu.tools.lint.__main__ import main
    import textwrap
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "mod.py").write_text(textwrap.dedent("""
        from common.flags import flags

        def f():
            return flags.get("undefined_flag_a")

        def seat(self):
            lane = self.ledger.alloc()
            return lane
    """))
    rc = main(["--format=sarif", "--no-baseline", "--no-cache",
               "--check", "flag-registry",
               "--check", "obligation-tracking", str(root)])
    out = capsys.readouterr().out
    return rc, json.loads(out)


def test_sarif_golden_file(tmp_path, capsys):
    """Golden-file contract: the SARIF payload for a seeded violation
    is byte-stable (modulo the JSON round trip) — CI annotation
    surfaces parse exactly this."""
    rc, doc = _sarif_fixture_run(tmp_path, capsys)
    assert rc == 1
    with open(SARIF_GOLDEN, encoding="utf-8") as fh:
        golden = json.load(fh)
    assert doc == golden, json.dumps(doc, indent=2, sort_keys=True)


def test_sarif_clean_run_is_valid_and_empty(tmp_path, capsys):
    from nebula_tpu.tools.lint.__main__ import main
    import textwrap
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "mod.py").write_text(textwrap.dedent("""
        def f():
            return 1
    """))
    rc = main(["--format=sarif", "--no-baseline", "--no-cache",
               str(root)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"] == []

# ============================================= 19 · obligation-tracking
def test_obligation_fixture_fires_all_historical_bugs(tmp_path):
    """The three review-record bug classes (PR 7 unreleased probe
    token, PR 6 missed wakeup, PR 15 stranded seat on extract failure)
    plus the annotation edge cases — six violations, no more: the
    decline branch, the handler settle, the canonical _PrioritySlots
    shape, the named handoff and the with-bound deadline all pass."""
    vs = run_fixture(tmp_path,
                     {"graph/stream.py": fixture_src(
                         "obligations_racy.py")},
                     checks=["obligation-tracking"])
    msgs = {v.symbol: v.message for v in vs}
    assert len(vs) == 6, "\n".join(repr(v) for v in vs)
    assert "probe token" in msgs["Stream.go_via_device"]
    assert "leaks the obligation" in msgs["Stream.go_via_device"]
    assert "wakes nobody" in msgs["Stream.finish"]
    assert "exception edge" in msgs["Stream.tick"]
    assert "never discharged" in msgs["Stream.seat_forever"]
    assert "without a reason" in msgs["Stream.handoff_unnamed"]
    assert "binds a thread context" in msgs["Stream.poison_thread"]


def test_obligation_historical_fixes_restore_clean(tmp_path):
    """Each historical bug's FIX, re-applied to the fixture, silences
    exactly its violation — the fixture is the reverted-fix state."""
    src = fixture_src("obligations_racy.py")
    # PR 7: settle the probe token before the early return
    src = src.replace(
        "            return None             "
        "# PR 7: the probe token leaks here",
        "            self.breaker.release_probe(key)\n"
        "            return None")
    # PR 6: notify under the same condition
    src = src.replace(
        "            rider.done = True       # PR 6: nobody is notified",
        "            rider.done = True\n"
        "            self.cond.notify_all()")
    # PR 15: release the seat on the extract exception edge too
    src = src.replace(
        "        resolver = self.sess.extract([(lane, rider)])\n"
        "        self.ledger.release(lane)",
        "        try:\n"
        "            resolver = self.sess.extract([(lane, rider)])\n"
        "        except BaseException:\n"
        "            self.ledger.release(lane)\n"
        "            raise\n"
        "        self.ledger.release(lane)")
    vs = run_fixture(tmp_path, {"graph/stream.py": src},
                     checks=["obligation-tracking"])
    symbols = sorted(v.symbol for v in vs)
    assert symbols == ["Stream.handoff_unnamed", "Stream.poison_thread",
                       "Stream.seat_forever"], \
        "\n".join(repr(v) for v in vs)


def test_obligation_handed_off_annotation_waives(tmp_path):
    src = """
    class S:
        def seat(self, r):
            # nebulint: obligation=handed-off/released-by-the-pump
            lane = self.ledger.alloc()
            self.seated[lane] = r
    """
    assert run_fixture(tmp_path, {"m.py": src},
                       checks=["obligation-tracking"]) == []


def test_obligation_callee_discharge_propagates(tmp_path):
    """The blocking.py call-graph reuse: submit's slot is settled by
    the _run it hands the batch to — no violation at the acquire."""
    src = """
    class D:
        def submit(self, req):
            self._inflight.acquire(1)
            try:
                return self._run(req)
            except BaseException:
                self._inflight.release()
                raise

        def _run(self, req):
            try:
                return req
            finally:
                self._inflight.release()
    """
    assert run_fixture(tmp_path, {"m.py": src},
                       checks=["obligation-tracking"]) == []


def test_obligation_suppression_roundtrip(tmp_path):
    src = """
    class S:
        def seat(self):
            lane = self.ledger.alloc()  # nebulint: disable=obligation-tracking
            return lane
    """
    assert run_fixture(tmp_path, {"m.py": src},
                       checks=["obligation-tracking"]) == []


def test_obligation_package_sites_all_discharged():
    vs = lint_paths(PKG_ROOT, checks=["obligation-tracking"])
    assert vs == [], "\n".join(repr(v) for v in vs)


# ============================================== 20 · protocol-registry
_PROTO_REGISTRY = """
    ABSORB_PART_MOVED = "part-moved"
    ABSORB_DELTA_OVERFLOW = "delta-overflow"
    SHED_QUEUE_FULL = "queue_full"
    DEAD_REASON = "never-emitted"

    PROTOCOL_REASONS = {
        "absorb-decline": (ABSORB_PART_MOVED, ABSORB_DELTA_OVERFLOW),
        "shed": (SHED_QUEUE_FULL,),
        "dead": (DEAD_REASON,),
    }

    TYPED_RAISES = ("AdmissionShed",)

    STATE_MACHINES = {
        "breaker-cell": {
            "module": "storage/device.py",
            "fields": ("state",),
            "writers": ("__init__", "record_failure"),
        },
    }
"""


def test_protocol_fixture_fires_every_leg(tmp_path):
    vs = run_fixture(tmp_path, {
        "common/protocol.py": _PROTO_REGISTRY,
        "storage/device.py": fixture_src("protocol_racy.py"),
    }, checks=["protocol-registry"])
    msgs = [v.message for v in vs]
    assert any("bare literal 'queue_full' at a typed _shed site" in m
               for m in msgs), msgs
    assert any("unknown reason 'weird-reason'" in m for m in msgs), msgs
    assert any("AdmissionShed(...) constructed without a typed reason"
               in m for m in msgs), msgs
    assert any("bare literal 'part-moved' at a typed reason site" in m
               for m in msgs), msgs
    assert any("bare literal 'delta-overflow' duplicates" in m
               for m in msgs), msgs
    assert any("write to breaker-cell state field .state outside" in m
               for m in msgs), msgs
    assert any("'never-emitted' (DEAD_REASON) is registered but never"
               in m for m in msgs), msgs
    assert len(vs) == 7, "\n".join(repr(v) for v in vs)


def test_protocol_constants_everywhere_is_clean(tmp_path):
    sites = """
    class AdmissionShed(Exception):
        pass


    def _shed(key, reason, depth):
        raise AdmissionShed(f"shed ({reason})", reason)


    def admit(key, depth):
        if depth > 10:
            _shed(key, protocol.SHED_QUEUE_FULL, depth)


    def note(space_id):
        journal(reason=protocol.ABSORB_PART_MOVED)


    def count_overflow(reason):
        if reason == protocol.ABSORB_DELTA_OVERFLOW:
            return 1
        return 0


    def legacy():
        return protocol.DEAD_REASON


    class Breaker:
        def __init__(self):
            self.state = "closed"

        def record_failure(self, key, reason):
            self.state = "open"
    """
    assert run_fixture(tmp_path, {
        "common/protocol.py": _PROTO_REGISTRY,
        "storage/device.py": sites,
    }, checks=["protocol-registry"]) == []


def test_protocol_unknown_reason_flagged(tmp_path):
    sites = """
    def _shed(key, reason, depth):
        pass

    def admit(key, depth):
        _shed(key, "mystery", depth)
    """
    vs = run_fixture(tmp_path, {
        "common/protocol.py": _PROTO_REGISTRY,
        "graph/dispatch.py": sites,
    }, checks=["protocol-registry"])
    assert any("unknown reason 'mystery'" in v.message for v in vs), vs


def test_protocol_second_registry_flagged(tmp_path):
    vs = run_fixture(tmp_path, {
        "common/protocol.py": _PROTO_REGISTRY,
        "common/protocol_copy.py": _PROTO_REGISTRY,
    }, checks=["protocol-registry"])
    assert any("second PROTOCOL_REASONS registry" in v.message
               for v in vs), vs


def test_protocol_suppression_roundtrip(tmp_path):
    reg = """
    SHED_QUEUE_FULL = "queue_full"
    PROTOCOL_REASONS = {"shed": (SHED_QUEUE_FULL,)}
    """
    sites = """
    def _shed(key, reason, depth):
        pass

    def admit(key, depth):
        _shed(key, "queue_full", depth)  # nebulint: disable=protocol-registry
    """
    assert run_fixture(tmp_path, {
        "common/protocol.py": reg,
        "graph/dispatch.py": sites,
    }, checks=["protocol-registry"]) == []


def test_protocol_package_vocabulary_closed():
    vs = lint_paths(PKG_ROOT, checks=["protocol-registry"])
    assert vs == [], "\n".join(repr(v) for v in vs)


# ================================================= 21 · mc-coverage (v6)
_MC_PROTO = """
    STATE_MACHINES = {
        "breaker-cell": {
            "module": "storage/device.py",
            "fields": ("state",),
            "writers": ("admit", "record_success"),
        },
    }

    OBLIGATIONS = {
        "probe-token": {
            "acquire": "DeviceCircuitBreaker.admit",
            "discharge": ("release_probe",),
            "quiescence": "no probe token outstanding",
        },
    }
    """

_MC_FULL_COVERS = ("machine:breaker-cell", "obligation:probe-token")


def _mc_scen(covers=(), classes=()):
    """A fake Scenario — mc-coverage only reads .covers/.classes."""
    import types
    return types.SimpleNamespace(covers=tuple(covers),
                                 classes=tuple(classes))


def _mc_lint(tmp_path, files, registry):
    """check_mc_coverage over a fake package with an injected scenario
    registry (the live tools/mc import is exactly what fixtures must
    not depend on)."""
    from nebula_tpu.tools.lint.core import load_package
    from nebula_tpu.tools.lint.mccheck import check_mc_coverage
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    ctx = load_package(str(root), str(tmp_path))
    return check_mc_coverage(ctx, registry=registry)


def test_mc_uncovered_entries_flagged_at_their_key_lines(tmp_path):
    vs = _mc_lint(tmp_path, {"common/protocol.py": _MC_PROTO},
                  registry={})
    assert len(vs) == 2, vs
    machine = next(v for v in vs if v.symbol == "breaker-cell")
    assert "covered by no registered nebulamc scenario" in machine.message
    assert machine.line > 1, "must point at the key, not the file header"
    oblig = next(v for v in vs if v.symbol == "probe-token")
    assert "quiescence property is never asserted" in oblig.message
    assert oblig.line > machine.line


def test_mc_full_coverage_is_clean(tmp_path):
    reg = {"breaker-probe": _mc_scen(covers=_MC_FULL_COVERS)}
    assert _mc_lint(tmp_path, {"common/protocol.py": _MC_PROTO},
                    reg) == []


def test_mc_stale_and_malformed_tags_flagged(tmp_path):
    reg = {"ghost": _mc_scen(
        covers=_MC_FULL_COVERS + ("machine:ghost", "bogus-tag"))}
    vs = _mc_lint(tmp_path, {"common/protocol.py": _MC_PROTO}, reg)
    assert len(vs) == 2, vs
    assert any("stale tag claims coverage" in v.message for v in vs)
    assert any("malformed tag" in v.message for v in vs)
    assert all(v.symbol == "ghost" for v in vs)


_MC_LEDGER = """
    class Ledger:
        def __init__(self):
            self._lock = object()
            self.count = 0          # __init__ precedes concurrency

        def alloc(self):
            with self._lock:
                self.count += 1     # under the lock: schedulable

        def tick(self):
            mc_yield("ledger.tick")
            self.count += 1         # yield point: schedulable

        def evict(self):
            self.count -= 1         # naked: invisible to the scheduler
    """


def test_mc_naked_write_flagged_sync_ops_silence(tmp_path):
    reg = {"churn": _mc_scen(covers=_MC_FULL_COVERS,
                             classes=("pkg.graph.ledger.Ledger",))}
    vs = _mc_lint(tmp_path, {
        "common/protocol.py": _MC_PROTO,
        "graph/ledger.py": _MC_LEDGER,
    }, reg)
    assert len(vs) == 1, vs
    v = vs[0]
    assert v.symbol == "Ledger.evict"
    assert v.path.endswith("graph/ledger.py")
    assert "cannot preempt inside evict()" in v.message
    assert "mc=caller-synced" in v.message


def test_mc_method_waiver_is_not_a_class_waiver(tmp_path):
    """A caller-synced annotation above ONE def silences that method
    only — the next naked method in the same class still fires."""
    src = """
    class Brief:
        # single collector thread owns this mark
        # nebulint: mc=caller-synced/metrics scrape is single-threaded
        def scrape(self):
            self.mark = 1

        def rogue(self):
            self.mark = 2
    """
    reg = {"s": _mc_scen(covers=_MC_FULL_COVERS,
                         classes=("pkg.graph.brief.Brief",))}
    vs = _mc_lint(tmp_path, {
        "common/protocol.py": _MC_PROTO,
        "graph/brief.py": src,
    }, reg)
    assert [v.symbol for v in vs] == ["Brief.rogue"], vs


def test_mc_class_header_waiver_blankets_the_class(tmp_path):
    """The _LaneLedger idiom: the annotation between the docstring and
    the first statement waives every method."""
    src = """
    class Brief:
        '''Caller-sequenced read-side brief.'''
        # nebulint: mc=caller-synced/all writers hold the dispatcher lock

        def scrape(self):
            self.mark = 1

        def rogue(self):
            self.mark = 2
    """
    reg = {"s": _mc_scen(covers=_MC_FULL_COVERS,
                         classes=("pkg.graph.brief.Brief",))}
    assert _mc_lint(tmp_path, {
        "common/protocol.py": _MC_PROTO,
        "graph/brief.py": src,
    }, reg) == []


def test_mc_waiver_inside_a_method_does_not_blanket(tmp_path):
    """An annotation buried in a method BODY is not a class waiver —
    other methods' naked writes still fire."""
    src = """
    class Brief:
        def scrape(self):
            x = 1  # nebulint: mc=caller-synced/only about this line
            self.mark = x

        def rogue(self):
            self.mark = 2
    """
    reg = {"s": _mc_scen(covers=_MC_FULL_COVERS,
                         classes=("pkg.graph.brief.Brief",))}
    vs = _mc_lint(tmp_path, {
        "common/protocol.py": _MC_PROTO,
        "graph/brief.py": src,
    }, reg)
    assert "Brief.rogue" in {v.symbol for v in vs}, vs


def test_mc_missing_class_flagged(tmp_path):
    reg = {"s": _mc_scen(covers=_MC_FULL_COVERS,
                         classes=("pkg.graph.nosuch.Ghost",))}
    vs = _mc_lint(tmp_path, {"common/protocol.py": _MC_PROTO}, reg)
    assert len(vs) == 1
    assert "not in the linted package" in vs[0].message


def test_mc_registry_import_failure_is_one_violation(tmp_path,
                                                     monkeypatch):
    """A broken scenarios.py fails the lint with a pointer, it does
    not crash the whole run."""
    import nebula_tpu.tools.lint.mccheck as mccheck_mod

    def boom():
        raise ImportError("scenario module is on fire")
    monkeypatch.setattr(mccheck_mod, "_scenario_registry", boom)
    vs = _mc_lint(tmp_path, {"common/protocol.py": _MC_PROTO},
                  registry=None)
    assert len(vs) == 1
    assert "cannot import the nebulamc scenario registry" in vs[0].message
    assert "on fire" in vs[0].message


def test_mc_package_coverage_closed():
    """The real gate: every live STATE_MACHINES/OBLIGATIONS entry is
    covered by a registered scenario and every scenario-driven class
    is fully instrumented (or carries a reasoned waiver)."""
    vs = lint_paths(PKG_ROOT, checks=["mc-coverage"])
    assert vs == [], "\n".join(repr(v) for v in vs)
