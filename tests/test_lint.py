"""nebulint self-tests: each of the six checks must fire on a minimal
fixture snippet, honor inline suppression, and the whole-package run is
the tier-1 gate (zero unsuppressed violations).  Also the runtime half:
the OrderedLock watchdog must detect a deliberately seeded inversion.

Run just these: ``pytest -m lint``.
"""
import os
import textwrap
import threading

import pytest

from nebula_tpu.tools.lint import (ALL_CHECKS, Baseline, LintError,
                                   lint_paths, run_lint)
from nebula_tpu.tools.lint.core import DEFAULT_BASELINE

pytestmark = pytest.mark.lint

PKG_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "nebula_tpu")


def run_fixture(tmp_path, files, checks=None):
    """Write {relpath: source} under a fake package root and lint it."""
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return lint_paths(str(root), checks=checks, repo_root=str(tmp_path))


def names(violations):
    return [v.check for v in violations]


# ================================================== 1 · lock-discipline
_UNGUARDED = """
    import threading

    class Daemon:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def process_put(self, req):
            self.count = self.count + 1
"""


def test_lock_discipline_unguarded_mutation(tmp_path):
    vs = run_fixture(tmp_path, {"daemon.py": _UNGUARDED},
                     checks=["lock-discipline"])
    assert names(vs) == ["lock-discipline"]
    assert "self.count" in vs[0].message


def test_lock_discipline_guarded_is_clean(tmp_path):
    ok = _UNGUARDED.replace(
        "            self.count = self.count + 1",
        "            with self._lock:\n"
        "                self.count = self.count + 1")
    assert run_fixture(tmp_path, {"daemon.py": ok},
                       checks=["lock-discipline"]) == []


def test_lock_discipline_caller_holds_contract(tmp_path):
    ok = _UNGUARDED.replace(
        "        def process_put(self, req):",
        "        def process_put(self, req):\n"
        '            """Caller holds the lock."""')
    assert run_fixture(tmp_path, {"daemon.py": ok},
                       checks=["lock-discipline"]) == []


def test_lock_discipline_blocking_call_under_lock(tmp_path):
    vs = run_fixture(tmp_path, {"daemon.py": """
        import threading
        import time

        class Daemon:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1)
    """}, checks=["lock-discipline"])
    assert names(vs) == ["lock-discipline"]
    assert "blocking call" in vs[0].message


def test_lock_discipline_inline_suppression(tmp_path):
    sup = _UNGUARDED.replace(
        "            self.count = self.count + 1",
        "            self.count = self.count + 1  "
        "# nebulint: disable=lock-discipline")
    assert run_fixture(tmp_path, {"daemon.py": sup},
                       checks=["lock-discipline"]) == []


# ===================================================== 2 · lock-order
_CYCLE = """
    import threading

    class Pair:
        def __init__(self):
            self.la = threading.Lock()
            self.lb = threading.Lock()

        def one(self):
            with self.la:
                with self.lb:
                    pass

        def two(self):
            with self.lb:
                with self.la:
                    pass
"""


def test_lock_order_cycle(tmp_path):
    vs = run_fixture(tmp_path, {"pair.py": _CYCLE}, checks=["lock-order"])
    assert names(vs) == ["lock-order"]
    assert "Pair.la" in vs[0].message and "Pair.lb" in vs[0].message


def test_lock_order_consistent_is_clean(tmp_path):
    ok = _CYCLE.replace(
        "            with self.lb:\n                with self.la:",
        "            with self.la:\n                with self.lb:")
    assert run_fixture(tmp_path, {"pair.py": ok},
                       checks=["lock-order"]) == []


def test_lock_order_file_suppression(tmp_path):
    sup = "# nebulint: disable-file=lock-order\n" + textwrap.dedent(_CYCLE)
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "pair.py").write_text(sup)
    assert lint_paths(str(root), checks=["lock-order"],
                      repo_root=str(tmp_path)) == []


# ================================================== 3 · status-discard
_DISCARD = """
    from common.status import Status

    def save() -> Status:
        return Status.OK()

    def caller():
        save()
"""


def test_status_discard(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": _DISCARD},
                     checks=["status-discard"])
    assert names(vs) == ["status-discard"]
    assert "save" in vs[0].message


def test_status_used_is_clean(tmp_path):
    ok = _DISCARD.replace("    save()", "    st = save()\n    return st")
    assert run_fixture(tmp_path, {"mod.py": ok},
                       checks=["status-discard"]) == []


def test_status_discard_suppression(tmp_path):
    sup = _DISCARD.replace(
        "    save()", "    save()  # nebulint: disable=status-discard")
    assert run_fixture(tmp_path, {"mod.py": sup},
                       checks=["status-discard"]) == []


def test_status_fixpoint_through_wrappers(tmp_path):
    """A function returning another status-returning function's result
    is itself status-returning (the MUST_USE_RESULT fixpoint)."""
    vs = run_fixture(tmp_path, {"mod.py": """
        def inner():
            return Status.OK()

        def outer():
            return inner()

        def caller():
            outer()
    """}, checks=["status-discard"])
    assert [v.symbol for v in vs] == ["caller"]


# ==================================================== 4 · jax-hotpath
def test_hotpath_jit_in_loop(tmp_path):
    vs = run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        def traverse(frontiers):
            for f in frontiers:
                step = jax.jit(lambda x: x)
                f = step(f)
    """}, checks=["jax-hotpath"])
    assert names(vs) == ["jax-hotpath"]
    assert "loop" in vs[0].message


def test_hotpath_host_sync_on_device_value(tmp_path):
    vs = run_fixture(tmp_path, {"tpu/kernels.py": """
        def drain(frontier_dev):
            total = 0
            while total < 10:
                total += int(frontier_dev)
            return total
    """}, checks=["jax-hotpath"])
    assert names(vs) == ["jax-hotpath"]
    assert "frontier_dev" in vs[0].message


def test_hotpath_outside_hot_files_ignored(tmp_path):
    assert run_fixture(tmp_path, {"graph/parser/x.py": """
        import jax

        def setup(items):
            for i in items:
                f = jax.jit(lambda x: x)
    """}, checks=["jax-hotpath"]) == []


def test_hotpath_jit_outside_loop_is_clean(tmp_path):
    assert run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        step = jax.jit(lambda x: x)

        def traverse(frontiers):
            for f in frontiers:
                f = step(f)
    """}, checks=["jax-hotpath"]) == []


# ================================================== 5 · flag-registry
def test_flag_registry_missing_define(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": """
        from common.flags import flags

        def f():
            return flags.get("never_defined_anywhere")
    """}, checks=["flag-registry"])
    assert names(vs) == ["flag-registry"]
    assert "never_defined_anywhere" in vs[0].message


def test_flag_registry_dead_define(tmp_path):
    vs = run_fixture(tmp_path, {"flagdefs.py": """
        from common.flags import flags

        flags.define("dead_knob", 1, "never read")
    """}, checks=["flag-registry"])
    assert names(vs) == ["flag-registry"]
    assert "dead_knob" in vs[0].message


def test_flag_registry_defined_and_read_is_clean(tmp_path):
    assert run_fixture(tmp_path, {"flagdefs.py": """
        from common.flags import flags

        flags.define("live_knob", 1, "read below")

        def f():
            return flags.get("live_knob")
    """}, checks=["flag-registry"]) == []


# ================================================== 6 · span-registry
_SPAN_REG = """
    from common import tracing

    SPAN_NAMES = ("graph.query", "rpc.client")

    def f():
        with tracing.span("rpc.client"):
            pass

    def g():
        with tracing.start_trace("graph.query", forced=True):
            pass
"""


def test_span_registry_clean(tmp_path):
    assert run_fixture(tmp_path, {"tracing.py": _SPAN_REG},
                       checks=["span-registry"]) == []


def test_span_registry_unknown_name(tmp_path):
    bad = _SPAN_REG.replace('tracing.span("rpc.client")',
                            'tracing.span("rpc.mystery")')
    vs = run_fixture(tmp_path, {"tracing.py": bad},
                     checks=["span-registry"])
    msgs = [v.message for v in vs]
    assert any("rpc.mystery" in m and "not in the SPAN_NAMES" in m
               for m in msgs)
    # the now-unused registry entry is flagged dead too
    assert any("'rpc.client'" in m and "never used" in m for m in msgs)


def test_span_registry_dynamic_name_rejected(tmp_path):
    bad = _SPAN_REG.replace('tracing.span("rpc.client")',
                            'tracing.span(name)')
    vs = run_fixture(tmp_path, {"tracing.py": bad},
                     checks=["span-registry"])
    assert any("literal" in v.message for v in vs)


def test_span_registry_requires_single_registry(tmp_path):
    files = {"tracing.py": _SPAN_REG,
             "other.py": 'SPAN_NAMES = ("dup.reg",)\n'}
    vs = run_fixture(tmp_path, files, checks=["span-registry"])
    assert any("ONE registry" in v.message for v in vs)


def test_span_registry_missing_registry(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": """
        from common import tracing

        def f():
            with tracing.span("orphan.name"):
                pass
    """}, checks=["span-registry"])
    assert any("no SPAN_NAMES registry" in v.message for v in vs)


def test_span_registry_ignores_unrelated_span_calls(tmp_path):
    """A local helper also called span() (numpy span, etc.) must not
    trip the check — only tracing.* receivers count."""
    assert run_fixture(tmp_path, {"mod.py": """
        def span(x):
            return x

        def f():
            return span("whatever")
    """}, checks=["span-registry"]) == []


# ====================================================== baseline rules
def test_baseline_entry_requires_reason():
    with pytest.raises(LintError):
        Baseline([{"check": "status-discard", "file": "x.py",
                   "symbol": "f", "reason": "  "}])


def test_baseline_matches_and_reports_stale(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": _DISCARD},
                     checks=["status-discard"])
    bl = Baseline([
        {"check": "status-discard", "file": "pkg/mod.py",
         "symbol": "caller", "reason": "fixture"},
        {"check": "status-discard", "file": "pkg/gone.py",
         "symbol": "f", "reason": "stale entry"},
    ])
    assert [v for v in vs if not bl.match(v)] == []
    assert [e["file"] for e in bl.unused()] == ["pkg/gone.py"]


# ============================================== whole-package tier-1 gate
def test_package_is_clean():
    """THE gate: nebulint over nebula_tpu reports zero unsuppressed
    violations (suppressions and baseline entries each carry a reason)."""
    vs, _bl = run_lint(PKG_ROOT, baseline_path=DEFAULT_BASELINE)
    assert vs == [], "unsuppressed nebulint violations:\n" + "\n".join(
        repr(v) for v in vs)


def test_package_has_no_stale_baseline_entries():
    vs, bl = run_lint(PKG_ROOT, baseline_path=DEFAULT_BASELINE)
    if bl is not None:
        stale = bl.unused()
        assert stale == [], f"stale baseline entries: {stale}"


def test_all_checks_registered():
    assert set(ALL_CHECKS) == {"lock-discipline", "lock-order",
                               "status-discard", "jax-hotpath",
                               "flag-registry", "span-registry"}


# ========================================== OrderedLock runtime watchdog
def test_watchdog_detects_seeded_inversion():
    """The mini-TSan self-test demanded by the acceptance criteria: two
    threads acquiring two ranks in opposite orders — even without losing
    the race — must produce a recorded inversion."""
    from nebula_tpu.common.ordered_lock import OrderedLock, watchdog
    a = OrderedLock("selftest.A")
    b = OrderedLock("selftest.B")
    watchdog.enable()
    try:
        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        for fn in (ab, ba):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        violations = watchdog.drain()
    finally:
        watchdog.disable()
    assert violations, "seeded inversion went undetected"
    assert "selftest.A" in violations[0] and "selftest.B" in violations[0]


def test_watchdog_consistent_order_is_clean():
    from nebula_tpu.common.ordered_lock import OrderedLock, watchdog
    a = OrderedLock("clean.A")
    b = OrderedLock("clean.B")
    watchdog.enable()
    try:
        for _ in range(3):
            with a:
                with b:
                    pass
        violations = watchdog.drain()
    finally:
        watchdog.disable()
    assert violations == []


def test_watchdog_strict_raises():
    from nebula_tpu.common.ordered_lock import (LockOrderError, OrderedLock,
                                                watchdog)
    a = OrderedLock("strict.A")
    b = OrderedLock("strict.B")
    watchdog.enable(strict=True)
    try:
        with a:
            with b:
                pass
        with pytest.raises(LockOrderError):
            with b:
                with a:
                    pass
    finally:
        watchdog.drain()
        watchdog.disable()


def test_ordered_lock_works_with_condition():
    """raftex wraps its part lock in a Condition — the OrderedLock must
    support wait/notify (full reentrant unwind mirrored in the
    watchdog's held stack)."""
    from nebula_tpu.common.ordered_lock import OrderedLock, watchdog
    lk = OrderedLock("cond.part", reentrant=True)
    cond = threading.Condition(lk)
    state = {"ready": False}
    watchdog.enable()
    try:
        def producer():
            with cond:
                state["ready"] = True
                cond.notify_all()

        t = threading.Thread(target=producer)
        with cond:
            with lk:   # reentrant: wait() must unwind BOTH levels
                t.start()
                assert cond.wait_for(lambda: state["ready"], timeout=5)
        t.join()
        assert watchdog.drain() == []
    finally:
        watchdog.disable()


def test_hotpath_mutable_static_args_flagged(tmp_path):
    vs = run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        f = jax.jit(lambda x: x, static_argnums=[0])
    """}, checks=["jax-hotpath"])
    assert names(vs) == ["jax-hotpath"]


def test_hotpath_mutable_literal_in_other_kwarg_not_flagged(tmp_path):
    """Only the static_arg* value itself may trip the mutable-literal
    rule — a list in donate_argnums/in_shardings must not."""
    assert run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        f = jax.jit(lambda x: x, static_argnums=(0,), donate_argnums=[1])
    """}, checks=["jax-hotpath"]) == []


def test_missing_explicit_baseline_is_config_error(tmp_path):
    with pytest.raises(LintError):
        run_lint(PKG_ROOT, baseline_path=str(tmp_path / "typo.json"))
