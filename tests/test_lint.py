"""nebulint self-tests: each of the six checks must fire on a minimal
fixture snippet, honor inline suppression, and the whole-package run is
the tier-1 gate (zero unsuppressed violations).  Also the runtime half:
the OrderedLock watchdog must detect a deliberately seeded inversion.

Run just these: ``pytest -m lint``.
"""
import os
import textwrap
import threading

import pytest

from nebula_tpu.tools.lint import (ALL_CHECKS, Baseline, LintError,
                                   lint_paths, run_lint)
from nebula_tpu.tools.lint.core import DEFAULT_BASELINE

pytestmark = pytest.mark.lint

PKG_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "nebula_tpu")


def run_fixture(tmp_path, files, checks=None):
    """Write {relpath: source} under a fake package root and lint it."""
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return lint_paths(str(root), checks=checks, repo_root=str(tmp_path))


def names(violations):
    return [v.check for v in violations]


# ================================================== 1 · lock-discipline
_UNGUARDED = """
    import threading

    class Daemon:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def process_put(self, req):
            self.count = self.count + 1
"""


def test_lock_discipline_unguarded_mutation(tmp_path):
    vs = run_fixture(tmp_path, {"daemon.py": _UNGUARDED},
                     checks=["lock-discipline"])
    assert names(vs) == ["lock-discipline"]
    assert "self.count" in vs[0].message


def test_lock_discipline_guarded_is_clean(tmp_path):
    ok = _UNGUARDED.replace(
        "            self.count = self.count + 1",
        "            with self._lock:\n"
        "                self.count = self.count + 1")
    assert run_fixture(tmp_path, {"daemon.py": ok},
                       checks=["lock-discipline"]) == []


def test_lock_discipline_caller_holds_contract(tmp_path):
    ok = _UNGUARDED.replace(
        "        def process_put(self, req):",
        "        def process_put(self, req):\n"
        '            """Caller holds the lock."""')
    assert run_fixture(tmp_path, {"daemon.py": ok},
                       checks=["lock-discipline"]) == []


def test_lock_discipline_blocking_call_under_lock(tmp_path):
    vs = run_fixture(tmp_path, {"daemon.py": """
        import threading
        import time

        class Daemon:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1)
    """}, checks=["lock-discipline"])
    assert names(vs) == ["lock-discipline"]
    assert "blocking call" in vs[0].message


def test_lock_discipline_inline_suppression(tmp_path):
    sup = _UNGUARDED.replace(
        "            self.count = self.count + 1",
        "            self.count = self.count + 1  "
        "# nebulint: disable=lock-discipline")
    assert run_fixture(tmp_path, {"daemon.py": sup},
                       checks=["lock-discipline"]) == []


# ===================================================== 2 · lock-order
_CYCLE = """
    import threading

    class Pair:
        def __init__(self):
            self.la = threading.Lock()
            self.lb = threading.Lock()

        def one(self):
            with self.la:
                with self.lb:
                    pass

        def two(self):
            with self.lb:
                with self.la:
                    pass
"""


def test_lock_order_cycle(tmp_path):
    vs = run_fixture(tmp_path, {"pair.py": _CYCLE}, checks=["lock-order"])
    assert names(vs) == ["lock-order"]
    assert "Pair.la" in vs[0].message and "Pair.lb" in vs[0].message


def test_lock_order_consistent_is_clean(tmp_path):
    ok = _CYCLE.replace(
        "            with self.lb:\n                with self.la:",
        "            with self.la:\n                with self.lb:")
    assert run_fixture(tmp_path, {"pair.py": ok},
                       checks=["lock-order"]) == []


def test_lock_order_file_suppression(tmp_path):
    sup = "# nebulint: disable-file=lock-order\n" + textwrap.dedent(_CYCLE)
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "pair.py").write_text(sup)
    assert lint_paths(str(root), checks=["lock-order"],
                      repo_root=str(tmp_path)) == []


# ================================================== 3 · status-discard
_DISCARD = """
    from common.status import Status

    def save() -> Status:
        return Status.OK()

    def caller():
        save()
"""


def test_status_discard(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": _DISCARD},
                     checks=["status-discard"])
    assert names(vs) == ["status-discard"]
    assert "save" in vs[0].message


def test_status_used_is_clean(tmp_path):
    ok = _DISCARD.replace("    save()", "    st = save()\n    return st")
    assert run_fixture(tmp_path, {"mod.py": ok},
                       checks=["status-discard"]) == []


def test_status_discard_suppression(tmp_path):
    sup = _DISCARD.replace(
        "    save()", "    save()  # nebulint: disable=status-discard")
    assert run_fixture(tmp_path, {"mod.py": sup},
                       checks=["status-discard"]) == []


def test_status_fixpoint_through_wrappers(tmp_path):
    """A function returning another status-returning function's result
    is itself status-returning (the MUST_USE_RESULT fixpoint)."""
    vs = run_fixture(tmp_path, {"mod.py": """
        def inner():
            return Status.OK()

        def outer():
            return inner()

        def caller():
            outer()
    """}, checks=["status-discard"])
    assert [v.symbol for v in vs] == ["caller"]


# ==================================================== 4 · jax-hotpath
def test_hotpath_jit_in_loop(tmp_path):
    vs = run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        def traverse(frontiers):
            for f in frontiers:
                step = jax.jit(lambda x: x)
                f = step(f)
    """}, checks=["jax-hotpath"])
    assert names(vs) == ["jax-hotpath"]
    assert "loop" in vs[0].message


def test_hotpath_host_sync_on_device_value(tmp_path):
    vs = run_fixture(tmp_path, {"tpu/kernels.py": """
        def drain(frontier_dev):
            total = 0
            while total < 10:
                total += int(frontier_dev)
            return total
    """}, checks=["jax-hotpath"])
    assert names(vs) == ["jax-hotpath"]
    assert "frontier_dev" in vs[0].message


def test_hotpath_outside_hot_files_ignored(tmp_path):
    assert run_fixture(tmp_path, {"graph/parser/x.py": """
        import jax

        def setup(items):
            for i in items:
                f = jax.jit(lambda x: x)
    """}, checks=["jax-hotpath"]) == []


def test_hotpath_jit_outside_loop_is_clean(tmp_path):
    assert run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        step = jax.jit(lambda x: x)

        def traverse(frontiers):
            for f in frontiers:
                f = step(f)
    """}, checks=["jax-hotpath"]) == []


# ================================================== 5 · flag-registry
def test_flag_registry_missing_define(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": """
        from common.flags import flags

        def f():
            return flags.get("never_defined_anywhere")
    """}, checks=["flag-registry"])
    assert names(vs) == ["flag-registry"]
    assert "never_defined_anywhere" in vs[0].message


def test_flag_registry_dead_define(tmp_path):
    vs = run_fixture(tmp_path, {"flagdefs.py": """
        from common.flags import flags

        flags.define("dead_knob", 1, "never read")
    """}, checks=["flag-registry"])
    assert names(vs) == ["flag-registry"]
    assert "dead_knob" in vs[0].message


def test_flag_registry_defined_and_read_is_clean(tmp_path):
    assert run_fixture(tmp_path, {"flagdefs.py": """
        from common.flags import flags

        flags.define("live_knob", 1, "read below")

        def f():
            return flags.get("live_knob")
    """}, checks=["flag-registry"]) == []


# ================================================== 6 · span-registry
_SPAN_REG = """
    from common import tracing

    SPAN_NAMES = ("graph.query", "rpc.client")

    def f():
        with tracing.span("rpc.client"):
            pass

    def g():
        with tracing.start_trace("graph.query", forced=True):
            pass
"""


def test_span_registry_clean(tmp_path):
    assert run_fixture(tmp_path, {"tracing.py": _SPAN_REG},
                       checks=["span-registry"]) == []


def test_span_registry_unknown_name(tmp_path):
    bad = _SPAN_REG.replace('tracing.span("rpc.client")',
                            'tracing.span("rpc.mystery")')
    vs = run_fixture(tmp_path, {"tracing.py": bad},
                     checks=["span-registry"])
    msgs = [v.message for v in vs]
    assert any("rpc.mystery" in m and "not in the SPAN_NAMES" in m
               for m in msgs)
    # the now-unused registry entry is flagged dead too
    assert any("'rpc.client'" in m and "never used" in m for m in msgs)


def test_span_registry_dynamic_name_rejected(tmp_path):
    bad = _SPAN_REG.replace('tracing.span("rpc.client")',
                            'tracing.span(name)')
    vs = run_fixture(tmp_path, {"tracing.py": bad},
                     checks=["span-registry"])
    assert any("literal" in v.message for v in vs)


def test_span_registry_requires_single_registry(tmp_path):
    files = {"tracing.py": _SPAN_REG,
             "other.py": 'SPAN_NAMES = ("dup.reg",)\n'}
    vs = run_fixture(tmp_path, files, checks=["span-registry"])
    assert any("ONE registry" in v.message for v in vs)


def test_span_registry_missing_registry(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": """
        from common import tracing

        def f():
            with tracing.span("orphan.name"):
                pass
    """}, checks=["span-registry"])
    assert any("no SPAN_NAMES registry" in v.message for v in vs)


def test_span_registry_ignores_unrelated_span_calls(tmp_path):
    """A local helper also called span() (numpy span, etc.) must not
    trip the check — only tracing.* receivers count."""
    assert run_fixture(tmp_path, {"mod.py": """
        def span(x):
            return x

        def f():
            return span("whatever")
    """}, checks=["span-registry"]) == []


# ================================================ 7 · metric-registry
_METRIC_REG = """
    from common.stats import stats

    METRIC_NAMES = ("graph.qps", "graph.stmt.*", "raft.term")

    def f(kind):
        stats.add_value("graph.qps")
        stats.observe(f"graph.stmt.{kind}.latency_us", 1.0)
        stats.set_gauge("raft.term", 3, space=1)
"""


def test_metric_registry_clean(tmp_path):
    assert run_fixture(tmp_path, {"stats.py": _METRIC_REG},
                       checks=["metric-registry"]) == []


def test_metric_registry_unknown_name(tmp_path):
    bad = _METRIC_REG.replace('stats.add_value("graph.qps")',
                              'stats.add_value("graph.mystery")')
    vs = run_fixture(tmp_path, {"stats.py": bad},
                     checks=["metric-registry"])
    msgs = [v.message for v in vs]
    assert any("graph.mystery" in m and "not in the METRIC_NAMES" in m
               for m in msgs)
    # the now-unused registry entry is flagged dead too
    assert any("'graph.qps'" in m and "never used" in m for m in msgs)


def test_metric_registry_fstring_needs_wildcard(tmp_path):
    bad = _METRIC_REG.replace(
        'stats.observe(f"graph.stmt.{kind}.latency_us", 1.0)',
        'stats.observe(f"rogue.family.{kind}", 1.0)')
    vs = run_fixture(tmp_path, {"stats.py": bad},
                     checks=["metric-registry"])
    msgs = [v.message for v in vs]
    assert any("rogue.family." in m and "not in the METRIC_NAMES" in m
               for m in msgs)
    assert any("'graph.stmt.*'" in m and "never used" in m for m in msgs)


def test_metric_registry_short_fstring_head_rejected(tmp_path):
    """An f-string whose literal head is a PREFIX of a wildcard entry
    ("graph." under "graph.stmt.*") could name any family — it must
    NOT satisfy the registry."""
    bad = _METRIC_REG.replace(
        'stats.observe(f"graph.stmt.{kind}.latency_us", 1.0)',
        'stats.observe(f"graph.{kind}", 1.0)')
    vs = run_fixture(tmp_path, {"stats.py": bad},
                     checks=["metric-registry"])
    assert any("'graph.'" in v.message and "not in the METRIC_NAMES"
               in v.message for v in vs)


def test_metric_registry_dynamic_name_rejected(tmp_path):
    bad = _METRIC_REG.replace('stats.add_value("graph.qps")',
                              'stats.add_value(kind)')
    vs = run_fixture(tmp_path, {"stats.py": bad},
                     checks=["metric-registry"])
    assert any("literal" in v.message for v in vs)


def test_metric_registry_ifexp_literals_resolved(tmp_path):
    ok = _METRIC_REG.replace(
        'stats.add_value("graph.qps")',
        'stats.add_value("graph.qps" if kind else "raft.term")')
    # both arms resolve; raft.term now has a second use — still clean
    assert run_fixture(tmp_path, {"stats.py": ok},
                       checks=["metric-registry"]) == []


def test_metric_registry_requires_single_registry(tmp_path):
    files = {"stats.py": _METRIC_REG,
             "other.py": 'METRIC_NAMES = ("dup.reg",)\n'}
    vs = run_fixture(tmp_path, files, checks=["metric-registry"])
    assert any("ONE registry" in v.message for v in vs)


def test_metric_registry_missing_registry(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": """
        from common.stats import stats

        def f():
            stats.add_value("orphan.metric")
    """}, checks=["metric-registry"])
    assert any("no METRIC_NAMES registry" in v.message for v in vs)


def test_metric_registry_ignores_unrelated_receivers(tmp_path):
    """Only stats-ish receivers count — a runtime's own `self.stats`
    dict ops or random add_value helpers must not trip the check."""
    assert run_fixture(tmp_path, {"mod.py": """
        def add_value(x):
            return x

        class R:
            def f(self):
                return add_value("whatever")
    """}, checks=["metric-registry"]) == []


def test_metric_registry_suppression_round_trip(tmp_path):
    bad = _METRIC_REG.replace(
        'stats.add_value("graph.qps")',
        'stats.add_value("graph.qps")\n'
        '        stats.add_value(kind)  '
        '# nebulint: disable=metric-registry')
    assert run_fixture(tmp_path, {"stats.py": bad},
                       checks=["metric-registry"]) == []


# ====================================================== baseline rules
def test_baseline_entry_requires_reason():
    with pytest.raises(LintError):
        Baseline([{"check": "status-discard", "file": "x.py",
                   "symbol": "f", "reason": "  "}])


def test_baseline_matches_and_reports_stale(tmp_path):
    vs = run_fixture(tmp_path, {"mod.py": _DISCARD},
                     checks=["status-discard"])
    bl = Baseline([
        {"check": "status-discard", "file": "pkg/mod.py",
         "symbol": "caller", "reason": "fixture"},
        {"check": "status-discard", "file": "pkg/gone.py",
         "symbol": "f", "reason": "stale entry"},
    ])
    assert [v for v in vs if not bl.match(v)] == []
    assert [e["file"] for e in bl.unused()] == ["pkg/gone.py"]


# ============================================== whole-package tier-1 gate
def test_package_is_clean():
    """THE gate: nebulint over nebula_tpu reports zero unsuppressed
    violations (suppressions and baseline entries each carry a reason)."""
    vs, _bl = run_lint(PKG_ROOT, baseline_path=DEFAULT_BASELINE)
    assert vs == [], "unsuppressed nebulint violations:\n" + "\n".join(
        repr(v) for v in vs)


def test_package_has_no_stale_baseline_entries():
    vs, bl = run_lint(PKG_ROOT, baseline_path=DEFAULT_BASELINE)
    if bl is not None:
        stale = bl.unused()
        assert stale == [], f"stale baseline entries: {stale}"


def test_all_checks_registered():
    assert set(ALL_CHECKS) == {"lock-discipline", "lock-order",
                               "status-discard", "jax-hotpath",
                               "flag-registry", "span-registry",
                               "metric-registry", "event-registry",
                               "jaxpr-audit", "wire-contract"}


# ========================================== OrderedLock runtime watchdog
def test_watchdog_detects_seeded_inversion():
    """The mini-TSan self-test demanded by the acceptance criteria: two
    threads acquiring two ranks in opposite orders — even without losing
    the race — must produce a recorded inversion."""
    from nebula_tpu.common.ordered_lock import OrderedLock, watchdog
    a = OrderedLock("selftest.A")
    b = OrderedLock("selftest.B")
    watchdog.enable()
    try:
        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        for fn in (ab, ba):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        violations = watchdog.drain()
    finally:
        watchdog.disable()
    assert violations, "seeded inversion went undetected"
    assert "selftest.A" in violations[0] and "selftest.B" in violations[0]


def test_watchdog_consistent_order_is_clean():
    from nebula_tpu.common.ordered_lock import OrderedLock, watchdog
    a = OrderedLock("clean.A")
    b = OrderedLock("clean.B")
    watchdog.enable()
    try:
        for _ in range(3):
            with a:
                with b:
                    pass
        violations = watchdog.drain()
    finally:
        watchdog.disable()
    assert violations == []


def test_watchdog_strict_raises():
    from nebula_tpu.common.ordered_lock import (LockOrderError, OrderedLock,
                                                watchdog)
    a = OrderedLock("strict.A")
    b = OrderedLock("strict.B")
    watchdog.enable(strict=True)
    try:
        with a:
            with b:
                pass
        with pytest.raises(LockOrderError):
            with b:
                with a:
                    pass
    finally:
        watchdog.drain()
        watchdog.disable()


def test_ordered_lock_works_with_condition():
    """raftex wraps its part lock in a Condition — the OrderedLock must
    support wait/notify (full reentrant unwind mirrored in the
    watchdog's held stack)."""
    from nebula_tpu.common.ordered_lock import OrderedLock, watchdog
    lk = OrderedLock("cond.part", reentrant=True)
    cond = threading.Condition(lk)
    state = {"ready": False}
    watchdog.enable()
    try:
        def producer():
            with cond:
                state["ready"] = True
                cond.notify_all()

        t = threading.Thread(target=producer)
        with cond:
            with lk:   # reentrant: wait() must unwind BOTH levels
                t.start()
                assert cond.wait_for(lambda: state["ready"], timeout=5)
        t.join()
        assert watchdog.drain() == []
    finally:
        watchdog.disable()


def test_hotpath_mutable_static_args_flagged(tmp_path):
    vs = run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        f = jax.jit(lambda x: x, static_argnums=[0])
    """}, checks=["jax-hotpath"])
    assert names(vs) == ["jax-hotpath"]


def test_hotpath_mutable_literal_in_other_kwarg_not_flagged(tmp_path):
    """Only the static_arg* value itself may trip the mutable-literal
    rule — a list in donate_argnums/in_shardings must not."""
    assert run_fixture(tmp_path, {"tpu/runtime.py": """
        import jax

        f = jax.jit(lambda x: x, static_argnums=(0,), donate_argnums=[1])
    """}, checks=["jax-hotpath"]) == []


def test_missing_explicit_baseline_is_config_error(tmp_path):
    with pytest.raises(LintError):
        run_lint(PKG_ROOT, baseline_path=str(tmp_path / "typo.json"))


# ================================================== 7 · jaxpr-audit
def _audit(specs, phases, span_names=("tpu.kernel",)):
    from nebula_tpu.tools.lint.jaxaudit import audit_specs
    vs, _kinds = audit_specs(specs, None, phases,
                             span_names, lambda s: ("pkg/fake.py", 1))
    return vs


def _spec(fn, avals, *, name="k", budget=4, donate=(), dispatch=(),
          frontier=(), buckets=None):
    from nebula_tpu.tpu.kernels import KernelSpec
    return KernelSpec(
        name, fn, phase_kind="k", budget=budget,
        instantiate=(buckets or (lambda fx: [(("k",), fn, avals)])),
        donate=donate, dispatch=dispatch, frontier=frontier)


_PHASES_1IN_1OUT = {"k": {"phases": ("tpu.kernel",), "h2d": 1, "d2h": 1}}


def test_jaxaudit_flags_loop_callback():
    """Seeded violation: a pure_callback inside the hop loop — the
    exact host-round-trip-per-hop class the audit exists to block."""
    import jax
    import numpy as np

    @jax.jit
    def bad(x):
        def body(i, acc):
            return acc + jax.pure_callback(
                lambda v: v, jax.ShapeDtypeStruct((8,), np.int32), x)
        return jax.lax.fori_loop(0, 4, body, x)

    vs = _audit([_spec(bad, (jax.ShapeDtypeStruct((8,), np.int32),),
                       dispatch=(0,))], _PHASES_1IN_1OUT)
    assert any("host callback" in v.message for v in vs), vs


def test_jaxaudit_flags_64bit_promotion():
    """Seeded violation: an int64 loop-carried buffer (visible because
    the audit traces under enable_x64)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def bad(x):
        def body(i, acc):
            return acc + x.astype(jnp.int64)
        acc0 = jnp.zeros(x.shape, jnp.int64)
        return jax.lax.fori_loop(0, 3, body, acc0).astype(jnp.int32)

    vs = _audit([_spec(bad, (jax.ShapeDtypeStruct((8,), np.int32),),
                       dispatch=(0,))], _PHASES_1IN_1OUT)
    assert any("int64" in v.message and "carry" in v.message
               for v in vs), vs


def test_jaxaudit_flags_unbounded_bucket_space():
    """Seeded violation: more distinct (cache key, signature) pairs
    than the declared retrace budget."""
    import jax
    import numpy as np

    @jax.jit
    def k(x):
        return x + 1

    def buckets(fx):
        return [((("k", s)), k, (jax.ShapeDtypeStruct((s,), np.int32),))
                for s in (8, 16, 32, 64)]

    vs = _audit([_spec(k, None, budget=2, dispatch=(0,),
                       buckets=buckets)], _PHASES_1IN_1OUT)
    assert any("retrace budget" in v.message for v in vs), vs


def test_jaxaudit_flags_donation_drift():
    """Seeded violations, both directions: claiming donation the jit
    doesn't perform, and donating what the spec says is cached."""
    import jax
    import numpy as np

    @jax.jit
    def undonated(x):
        return x + 1

    donated = jax.jit(lambda x: x + 1, donate_argnums=(0,))
    av = (jax.ShapeDtypeStruct((8,), np.int8),)
    vs = _audit([_spec(undonated, av, donate=(0,), dispatch=(0,))],
                _PHASES_1IN_1OUT)
    assert any("donation drift" in v.message for v in vs), vs
    vs = _audit([_spec(donated, av, donate=(), dispatch=(0,))],
                _PHASES_1IN_1OUT)
    assert any("donation drift" in v.message for v in vs), vs


def test_jaxaudit_flags_transfer_drift():
    """Seeded violation: a kernel growing a second output (an extra
    device->host fetch) without updating DEVICE_PHASES."""
    import jax
    import numpy as np

    @jax.jit
    def two_out(x):
        return x + 1, x * 2

    vs = _audit([_spec(two_out, (jax.ShapeDtypeStruct((8,), np.int32),),
                       dispatch=(0,))], _PHASES_1IN_1OUT)
    assert any("output fetches" in v.message for v in vs), vs


def test_jaxaudit_flags_wide_frontier():
    """Seeded violation: a declared frontier bitmap that is int32."""
    import jax
    import numpy as np

    @jax.jit
    def k(f):
        return f

    vs = _audit([_spec(k, (jax.ShapeDtypeStruct((8,), np.int32),),
                       dispatch=(0,), frontier=(0,))], _PHASES_1IN_1OUT)
    assert any("frontier argument" in v.message for v in vs), vs


def test_jaxaudit_package_registry_is_clean_within_budgets():
    """Acceptance: the auditor runs over EVERY registered kernel
    factory across all shape buckets and the per-kernel retrace-budget
    table holds — zero violations on the real registry."""
    from nebula_tpu.common.tracing import SPAN_NAMES
    from nebula_tpu.tools.lint.jaxaudit import audit_specs
    from nebula_tpu.tpu import runtime as rt
    from nebula_tpu.tpu.kernels import AuditFixture, kernel_registry

    registry = kernel_registry()
    assert {"go", "go_filtered", "bfs", "sharded_go", "ell_go",
            "sparse_go", "adaptive_go", "ell_bfs", "ell_go_delta",
            "expr_filter"} <= set(registry)
    fx = AuditFixture()
    vs, kinds = audit_specs(registry.values(), fx, rt.DEVICE_PHASES,
                            SPAN_NAMES, lambda s: ("x", 1))
    assert vs == [], "\n".join(repr(v) for v in vs)
    # every spec declares a positive budget (the table is the proof
    # surface TestRetraceBudget's runtime smoke test now leans on)
    assert all(s.budget >= 1 for s in registry.values())


def test_jaxaudit_skips_fixture_roots(tmp_path):
    """Fixture packages have no device path: the package check is a
    no-op there (the self-tests above drive audit_specs directly)."""
    assert run_fixture(tmp_path, {"mod.py": "x = 1"},
                       checks=["jaxpr-audit"]) == []


# ================================================== 8 · wire-contract
_WIRE_ORPHANS = """
    class Client:
        def fetch(self, addr):
            resp = self.cm.call(addr, "fetchThing", {"space_id": 1})
            return resp

    class Service:
        def rpc_storeThing(self, req):
            return {"ok": True}
"""


def test_wirecheck_orphan_method_and_handler(tmp_path):
    vs = run_fixture(tmp_path, {"svc.py": _WIRE_ORPHANS},
                     checks=["wire-contract"])
    msgs = [v.message for v in vs]
    assert any("no rpc_fetchThing handler" in m for m in msgs), msgs
    assert any("rpc_storeThing has no in-tree caller" in m
               for m in msgs), msgs


_WIRE_DRIFT = """
    class Client:
        def put(self, addr):
            resp = self.cm.call(addr, "putThing",
                                {"space_id": 1, "stale_key": 2})
            return resp.get("phantom_field")

    class Service:
        def rpc_putThing(self, req):
            part = req["part_id"]
            return {"ok": True, "latency_us": 1}
"""


def test_wirecheck_argument_and_envelope_drift(tmp_path):
    vs = run_fixture(tmp_path, {"svc.py": _WIRE_DRIFT},
                     checks=["wire-contract"])
    msgs = [v.message for v in vs]
    # arity drift: required key never sent
    assert any("never sends key 'part_id'" in m for m in msgs), msgs
    # dead payload: sent key never read
    assert any("sends key 'stale_key'" in m for m in msgs), msgs
    # phantom envelope field: read but never written
    assert any("reads response field 'phantom_field'" in m
               for m in msgs), msgs
    # dead envelope field: written but no caller reads it
    assert any("'latency_us'" in m and "no caller reads" in m
               for m in msgs), msgs


def test_wirecheck_matched_contract_is_clean(tmp_path):
    ok = """
    class Client:
        def put(self, addr):
            resp = self.cm.call(addr, "putThing",
                                {"space_id": 1, "part_id": 2})
            return resp.get("ok")

    class Service:
        def rpc_putThing(self, req):
            part = req["part_id"]
            space = req.get("space_id")
            return {"ok": True}
    """
    assert run_fixture(tmp_path, {"svc.py": ok},
                       checks=["wire-contract"]) == []


def test_wirecheck_open_handlers_exempt_from_key_checks(tmp_path):
    """A handler that hands the request to non-self code (the storage
    processors) cannot be key-checked exactly — no false positives."""
    open_h = """
    class Client:
        def put(self, addr):
            return self.cm.call(addr, "putThing", {"anything": 1})

    class Service:
        def rpc_putThing(self, req):
            return process(req)
    """
    assert run_fixture(tmp_path, {"svc.py": open_h},
                       checks=["wire-contract"]) == []


def test_wirecheck_suppression_roundtrip(tmp_path):
    """Inline suppression silences a wire-contract finding like any
    other check."""
    suppressed = _WIRE_ORPHANS.replace(
        'resp = self.cm.call(addr, "fetchThing", {"space_id": 1})',
        'resp = self.cm.call(  # nebulint: disable=wire-contract\n'
        '                addr, "fetchThing", {"space_id": 1})').replace(
        "def rpc_storeThing(self, req):",
        "def rpc_storeThing(self, req):"
        "  # nebulint: disable=wire-contract")
    assert run_fixture(tmp_path, {"svc.py": suppressed},
                       checks=["wire-contract"]) == []


def test_wirecheck_delegation_resolves_alias_handlers(tmp_path):
    """rpc_X bodies that forward to rpc_Y inherit Y's request/response
    contract (the meta.thrift spelling aliases)."""
    alias = """
    class Client:
        def put(self, addr):
            resp = self.cm.call(addr, "createTag", {"name": "t"})
            return resp.get("id")

    class Service:
        def rpc_createTagSchema(self, req):
            name = req["name"]
            return {"id": 7}

        def rpc_createTag(self, req):
            return self.rpc_createTagSchema(req)
    """
    vs = run_fixture(tmp_path, {"svc.py": alias},
                     checks=["wire-contract"])
    # rpc_createTagSchema has no DIRECT caller but IS a delegation
    # target; the alias's contract resolves through it
    assert vs == [], vs


def test_wirecheck_scatter_gather_make_req_tuples(tmp_path):
    """The ``return "method", {...}`` make_req closures count as call
    sites (the StorageClient collect contract)."""
    sg = """
    class Client:
        def get_props(self):
            def make(parts):
                return "bulkFetch", {"space_id": 1}
            return self.collect(make)
    """
    vs = run_fixture(tmp_path, {"svc.py": sg}, checks=["wire-contract"])
    assert any("no rpc_bulkFetch handler" in v.message for v in vs), vs


# ================================================ lint wall-time guard
def test_lint_wall_time_budget():
    """The whole-package analysis (all eight checks, jaxpr tracing
    included) must stay fast enough to gate tier-1 — micro_bench's
    lint component enforces the tighter interactive budget."""
    import time
    t0 = time.perf_counter()
    run_lint(PKG_ROOT, baseline_path=DEFAULT_BASELINE)
    elapsed = time.perf_counter() - t0
    assert elapsed < 60.0, f"nebulint took {elapsed:.1f}s"


def test_wirecheck_frame_contract_drops_untraced_frame(tmp_path):
    """Seeded violation: interface/rpc.py losing the 2-element untraced
    frame (every call would pay the trace envelope)."""
    rpc = """
    _TRACED = "__spans__"
    _RESP = "__resp__"

    def client_call(method, payload, sp):
        return _pack([method, payload, [sp.trace_id, sp.span_id]])

    def server(frame):
        parts = _unpack(frame)
        method, payload = parts[0], parts[1]
        wctx = parts[2] if len(parts) > 2 else None
        return {_TRACED: [], _RESP: payload}

    def absorb(resp):
        return resp.get(_TRACED), resp.get(_RESP)
    """
    vs = run_fixture(tmp_path, {"interface/rpc.py": rpc},
                     checks=["wire-contract"])
    assert any("2-element" in v.message for v in vs), vs


def test_wirecheck_frame_contract_envelope_constant_drift(tmp_path):
    """Seeded violation: an envelope constant written server-side but
    never read by the client (dead piggyback payload)."""
    rpc = """
    _TRACED = "__spans__"
    _RESP = "__resp__"

    def client_call(method, payload):
        return _pack([method, payload])

    def client_traced(method, payload, sp):
        return _pack([method, payload, [sp.trace_id, sp.span_id]])

    def server(frame):
        parts = _unpack(frame)
        return {_TRACED: [], _RESP: parts[1]}

    def absorb(resp):
        return resp.get(_RESP)      # __spans__ never read
    """
    vs = run_fixture(tmp_path, {"interface/rpc.py": rpc},
                     checks=["wire-contract"])
    assert any("_TRACED" in v.message and "never read" in v.message
               for v in vs), vs


def test_wirecheck_endpoint_contract_drift(tmp_path):
    """Seeded violation: a contract endpoint returning a payload key
    the ENDPOINT_CONTRACT declaration doesn't name."""
    ws = """
    class WebService:
        def __init__(self):
            self.register_handler("/faults", self._faults)
            self.register_handler("/get_stats", self._get_stats)
            self.register_handler("/traces", self._traces)

        def _faults(self, q, body):
            return 200, {"seed": 1, "rules": [], "bogus_field": 2}

        def _get_stats(self, q, body):
            return 200, dump()

        def _traces(self, q, body):
            return 200, {"traces": []}
    """
    vs = run_fixture(tmp_path, {"webservice/service.py": ws},
                     checks=["wire-contract"])
    assert any("bogus_field" in v.message and "/faults" in v.message
               for v in vs), vs


# ================================================ 10 · event-registry
_EVENT_REG = """
    from common.events import journal

    EVENT_KINDS = ("raft.leader_elected", "query.shed")

    def f():
        journal.record("raft.leader_elected", detail="x")
        journal.record("query.shed", detail="y", space=1)
"""


def test_event_registry_clean(tmp_path):
    assert run_fixture(tmp_path, {"events.py": _EVENT_REG},
                       checks=["event-registry"]) == []


def test_event_registry_unknown_kind(tmp_path):
    bad = _EVENT_REG.replace('journal.record("query.shed"',
                             'journal.record("query.mystery"')
    vs = run_fixture(tmp_path, {"events.py": bad},
                     checks=["event-registry"])
    msgs = [v.message for v in vs]
    assert any("query.mystery" in m and "not in the EVENT_KINDS" in m
               for m in msgs)
    # the now-unrecorded registry entry is flagged dead too
    assert any("'query.shed'" in m and "never recorded" in m
               for m in msgs)


def test_event_registry_dynamic_kind_rejected(tmp_path):
    bad = _EVENT_REG.replace('journal.record("query.shed"',
                             'journal.record(kind')
    vs = run_fixture(tmp_path, {"events.py": bad},
                     checks=["event-registry"])
    assert any("literal" in v.message for v in vs)


def test_event_registry_single_registry(tmp_path):
    files = {"events.py": _EVENT_REG,
             "other.py": 'EVENT_KINDS = ("dup.kind",)\n'}
    vs = run_fixture(tmp_path, files, checks=["event-registry"])
    assert any("ONE registry" in v.message for v in vs)


def test_event_registry_ignores_unrelated_record_calls(tmp_path):
    """slow-log / router `.record` methods are out of scope — only a
    journal-named receiver is the event seam."""
    assert run_fixture(tmp_path, {"mod.py": """
        class R:
            def f(self, slow_log, router):
                slow_log.record("not an event", 12)
                router.record(("k",), "device", 1.0)
    """}, checks=["event-registry"]) == []


def test_event_registry_suppression_round_trip(tmp_path):
    bad = _EVENT_REG.replace(
        'journal.record("query.shed", detail="y", space=1)',
        'journal.record("query.mystery", detail="y")  '
        '# nebulint: disable=event-registry — fixture')
    vs = run_fixture(tmp_path, {"events.py": bad},
                     checks=["event-registry"])
    assert not any("query.mystery" in v.message for v in vs)
