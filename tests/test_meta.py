"""Meta service + client tests — modeled on the reference's
meta/test/ProcessorTest.cpp + MetaClient tests (SURVEY.md §4)."""
import time

import pytest

from nebula_tpu.common.status import ErrorCode
from nebula_tpu.interface.common import (AlterSchemaOp, ConfigMode,
                                         ConfigModule, HostAddr, RoleType,
                                         Schema, ColumnDef, SupportedType,
                                         schema_to_wire)
from nebula_tpu.interface.rpc import ClientManager, RpcError, RpcServer
from nebula_tpu.meta.client import MetaChangedListener, MetaClient
from nebula_tpu.meta.part_manager import MetaServerBasedPartManager
from nebula_tpu.meta.schema_manager import AdHocSchemaManager, ServerBasedSchemaManager
from nebula_tpu.meta.service import MetaService
from nebula_tpu.kvstore import KVOptions, NebulaStore


PLAYER_WIRE = schema_to_wire(Schema(columns=[
    ColumnDef("name", SupportedType.STRING),
    ColumnDef("age", SupportedType.INT),
]))
FOLLOW_WIRE = schema_to_wire(Schema(columns=[
    ColumnDef("degree", SupportedType.INT),
]))


@pytest.fixture
def svc():
    return MetaService()


def register_hosts(svc, n=3):
    for i in range(n):
        svc.rpc_heartBeat({"host": f"127.0.0.1:{45000+i}"})


class TestMetaService:
    def test_create_space_assigns_parts(self, svc):
        register_hosts(svc, 3)
        resp = svc.rpc_createSpace({"space_name": "nba", "partition_num": 6,
                                    "replica_factor": 3})
        sid = resp["id"]
        alloc = svc.rpc_getPartsAlloc({"space_id": sid})["parts"]
        assert len(alloc) == 6
        for part, peers in alloc.items():
            assert len(peers) == 3
            assert len(set(peers)) == 3

    def test_create_space_needs_hosts(self, svc):
        with pytest.raises(RpcError) as ei:
            svc.rpc_createSpace({"space_name": "x"})
        assert ei.value.status.code == ErrorCode.E_NO_HOSTS

    def test_replica_exceeds_hosts(self, svc):
        register_hosts(svc, 2)
        with pytest.raises(RpcError) as ei:
            svc.rpc_createSpace({"space_name": "x", "partition_num": 1,
                                 "replica_factor": 3})
        assert ei.value.status.code == ErrorCode.E_NO_VALID_HOST

    def test_duplicate_space(self, svc):
        register_hosts(svc)
        svc.rpc_createSpace({"space_name": "nba"})
        with pytest.raises(RpcError) as ei:
            svc.rpc_createSpace({"space_name": "nba"})
        assert ei.value.status.code == ErrorCode.E_EXISTED

    def test_drop_space(self, svc):
        register_hosts(svc)
        svc.rpc_createSpace({"space_name": "nba"})
        svc.rpc_dropSpace({"space_name": "nba"})
        assert svc.rpc_listSpaces({})["spaces"] == []
        with pytest.raises(RpcError):
            svc.rpc_dropSpace({"space_name": "nba"})

    def test_schema_crud_and_versioning(self, svc):
        register_hosts(svc)
        sid = svc.rpc_createSpace({"space_name": "nba"})["id"]
        tid = svc.rpc_createTagSchema({"space_id": sid, "name": "player",
                                       "schema": PLAYER_WIRE})["id"]
        schemas = svc.rpc_listTagSchemas({"space_id": sid})["schemas"]
        assert len(schemas) == 1 and schemas[0]["version"] == 0

        # ALTER ADD a column -> version 1
        resp = svc.rpc_alterTagSchema({
            "space_id": sid, "name": "player",
            "items": [{"op": int(AlterSchemaOp.ADD),
                       "schema": {"columns": [["height", int(SupportedType.DOUBLE), None]]}}]})
        assert resp["version"] == 1
        schemas = svc.rpc_listTagSchemas({"space_id": sid})["schemas"]
        assert len(schemas) == 2
        newest = max(schemas, key=lambda s: s["version"])
        assert [c[0] for c in newest["schema"]["columns"]] == ["name", "age", "height"]

        # DROP a column -> version 2
        svc.rpc_alterTagSchema({
            "space_id": sid, "name": "player",
            "items": [{"op": int(AlterSchemaOp.DROP),
                       "schema": {"columns": [["age", int(SupportedType.INT), None]]}}]})
        schemas = svc.rpc_listTagSchemas({"space_id": sid})["schemas"]
        newest = max(schemas, key=lambda s: s["version"])
        assert [c[0] for c in newest["schema"]["columns"]] == ["name", "height"]

        svc.rpc_dropTagSchema({"space_id": sid, "name": "player"})
        assert svc.rpc_listTagSchemas({"space_id": sid})["schemas"] == []

    def test_edge_schema(self, svc):
        register_hosts(svc)
        sid = svc.rpc_createSpace({"space_name": "nba"})["id"]
        et = svc.rpc_createEdgeSchema({"space_id": sid, "name": "follow",
                                       "schema": FOLLOW_WIRE})["id"]
        assert et > 0
        schemas = svc.rpc_listEdgeSchemas({"space_id": sid})["schemas"]
        assert schemas[0]["name"] == "follow"

    def test_custom_kv(self, svc):
        svc.rpc_multiPut({"segment": "s1", "pairs": [["k1", b"v1"], ["k2", b"v2"]]})
        assert svc.rpc_get({"segment": "s1", "key": "k1"})["value"] == b"v1"
        got = svc.rpc_scan({"segment": "s1", "start": "k1", "end": "kz"})["values"]
        assert [k for k, _ in got] == ["k1", "k2"]
        svc.rpc_remove({"segment": "s1", "key": "k1"})
        with pytest.raises(RpcError):
            svc.rpc_get({"segment": "s1", "key": "k1"})
        # segment isolation
        svc.rpc_multiPut({"segment": "s2", "pairs": [["k9", b"x"]]})
        got = svc.rpc_scan({"segment": "s1", "start": "a", "end": "z"})["values"]
        assert [k for k, _ in got] == ["k2"]

    def test_users_and_roles(self, svc):
        svc.rpc_createUser({"account": "alice", "password": "pw"})
        assert svc.rpc_checkPassword({"account": "alice", "password": "pw"})["ok"]
        assert not svc.rpc_checkPassword({"account": "alice", "password": "no"})["ok"]
        svc.rpc_grantRole({"account": "alice", "space_id": 1,
                           "role": int(RoleType.ADMIN)})
        users = svc.rpc_listUsers({})["users"]
        assert users[0]["roles"] == {"1": int(RoleType.ADMIN)}
        svc.rpc_changePassword({"account": "alice", "old_password": "pw",
                                "new_password": "pw2"})
        assert svc.rpc_checkPassword({"account": "alice", "password": "pw2"})["ok"]
        svc.rpc_dropUser({"account": "alice"})
        assert svc.rpc_listUsers({})["users"] == []

    def test_config_registry(self, svc):
        svc.rpc_regConfig({"items": [
            {"module": int(ConfigModule.GRAPH), "name": "f1",
             "mode": int(ConfigMode.MUTABLE), "value": 10},
            {"module": int(ConfigModule.GRAPH), "name": "f2",
             "mode": int(ConfigMode.IMMUTABLE), "value": "x"},
        ]})
        assert svc.rpc_getConfig({"module": int(ConfigModule.GRAPH),
                                  "name": "f1"})["value"] == 10
        svc.rpc_setConfig({"module": int(ConfigModule.GRAPH), "name": "f1",
                           "value": 42})
        assert svc.rpc_getConfig({"module": int(ConfigModule.GRAPH),
                                  "name": "f1"})["value"] == 42
        with pytest.raises(RpcError):
            svc.rpc_setConfig({"module": int(ConfigModule.GRAPH), "name": "f2",
                               "value": "y"})
        items = svc.rpc_listConfigs({"module": int(ConfigModule.GRAPH)})["items"]
        assert {i["name"] for i in items} == {"f1", "f2"}

    def test_cluster_id_persists(self):
        svc = MetaService()
        cid = svc.cluster_id
        svc2 = MetaService(kv=svc.kv)
        assert svc2.cluster_id == cid

    def test_heartbeat_wrong_cluster(self, svc):
        with pytest.raises(RpcError) as ei:
            svc.rpc_heartBeat({"host": "h:1", "cluster_id": 12345})
        assert ei.value.status.code == ErrorCode.E_WRONGCLUSTER


class TestMetaClient:
    def make_client(self, svc, **kw):
        cm = ClientManager()
        addr = HostAddr("meta", 9559)
        cm.register_loopback(addr, svc)
        return MetaClient([addr], client_manager=cm, **kw)

    def test_caches(self, svc):
        register_hosts(svc)
        client = self.make_client(svc)
        assert client.wait_for_metad_ready()
        sid = client.create_space("nba", partition_num=4).value()
        client.create_tag_schema(sid, "player", PLAYER_WIRE)
        client.create_edge_schema(sid, "follow", FOLLOW_WIRE)

        assert client.get_space_id_by_name("nba").value() == sid
        assert client.part_num(sid) == 4
        tid = client.get_tag_id(sid, "player").value()
        schema = client.get_tag_schema(sid, tid)
        assert schema.names() == ["name", "age"]
        et = client.get_edge_type(sid, "follow").value()
        assert client.get_edge_schema(sid, et).names() == ["degree"]
        assert not client.get_tag_id(sid, "nope").ok()

    def test_listener_diff(self, svc):
        register_hosts(svc, 1)
        client = self.make_client(svc, local_host="127.0.0.1:45000")
        events = []

        class L(MetaChangedListener):
            def on_space_added(self, sid): events.append(("space+", sid))
            def on_part_added(self, sid, pid, peers): events.append(("part+", sid, pid))
            def on_space_removed(self, sid): events.append(("space-", sid))
            def on_part_removed(self, sid, pid): events.append(("part-", sid, pid))

        client.listener = L()
        client.wait_for_metad_ready()
        sid = client.create_space("nba", partition_num=2).value()
        assert ("space+", sid) in events
        assert ("part+", sid, 1) in events and ("part+", sid, 2) in events
        client.drop_space("nba")
        assert ("space-", sid) in events

    def test_meta_server_based_part_manager(self, svc):
        register_hosts(svc, 1)
        client = self.make_client(svc, local_host="127.0.0.1:45000")
        pm = MetaServerBasedPartManager(client, "127.0.0.1:45000")
        store = NebulaStore(KVOptions(part_man=pm))
        client.wait_for_metad_ready()
        sid = client.create_space("nba", partition_num=3).value()
        # parts materialize on the local store via listener callbacks
        assert store.part_ids(sid) == [1, 2, 3]
        client.drop_space("nba")
        assert store.part_ids(sid) == []

    def test_over_real_tcp(self, svc):
        server = RpcServer(svc).start()
        try:
            register_hosts(svc)
            client = MetaClient([server.addr], client_manager=ClientManager())
            assert client.wait_for_metad_ready()
            sid = client.create_space("tcp_space", partition_num=2).value()
            assert client.part_num(sid) == 2
        finally:
            server.stop()

    def test_schema_manager_server_based(self, svc):
        register_hosts(svc)
        client = self.make_client(svc)
        client.wait_for_metad_ready()
        sid = client.create_space("nba").value()
        client.create_tag_schema(sid, "player", PLAYER_WIRE)
        sm = ServerBasedSchemaManager(client)
        tid = sm.to_tag_id(sid, "player").value()
        assert sm.get_tag_schema(sid, tid).names() == ["name", "age"]
        assert sm.tag_name(sid, tid) == "player"


class TestAdHocSchemaManager:
    def test_basic(self):
        sm = AdHocSchemaManager()
        s = Schema(columns=[ColumnDef("x", SupportedType.INT)])
        sm.add_tag_schema(1, 10, "t", s)
        sm.add_edge_schema(1, 100, "e", s)
        assert sm.to_tag_id(1, "t").value() == 10
        assert sm.to_edge_type(1, "e").value() == 100
        assert sm.get_tag_schema(1, 10).names() == ["x"]
        assert sm.all_edge_types(1) == [100]
        assert sm.all_tag_ids(1) == [10]
        assert sm.tag_name(1, 10) == "t"


def test_reference_idl_name_aliases():
    """meta.thrift:499-546 method names (createTag/listTags/getTag/
    getUser/listRoles/alterUser...) must answer alongside our canonical
    Schema-suffixed spellings."""
    from nebula_tpu.meta.service import MetaService
    from nebula_tpu.interface.common import schema_to_wire, Schema, ColumnDef, SupportedType
    ms = MetaService()
    ms.rpc_heartBeat({"host": "127.0.0.1:1"})
    sid = ms.rpc_createSpace({"space_name": "al", "partition_num": 1,
                              "replica_factor": 1})["id"]
    wire = schema_to_wire(Schema(columns=[ColumnDef("x", SupportedType.INT)]))
    ms.rpc_createTag({"space_id": sid, "name": "t", "schema": wire})
    ms.rpc_createEdge({"space_id": sid, "name": "e", "schema": wire})
    assert any(r["name"] == "t" for r in ms.rpc_listTags({"space_id": sid})["schemas"])
    assert any(r["name"] == "e" for r in ms.rpc_listEdges({"space_id": sid})["schemas"])
    got = ms.rpc_getTag({"space_id": sid, "name": "t"})
    assert got["schema"]["columns"][0][0] == "x"
    got = ms.rpc_getEdge({"space_id": sid, "name": "e", "version": 0})
    assert got["version"] == 0
    # a missing exact version must error, not substitute the newest
    # (reference GetTagProcessor semantics)
    import pytest as _pytest
    from nebula_tpu.interface.rpc import RpcError
    with _pytest.raises(RpcError):
        ms.rpc_getTag({"space_id": sid, "name": "t", "version": 99})

    ms.rpc_createUser({"account": "bob", "password": "p1"})
    ms.rpc_grantRole({"account": "bob", "space_id": sid, "role": 3})
    assert ms.rpc_getUser({"account": "bob"})["user"]["account"] == "bob"
    roles = ms.rpc_listRoles({"space_id": sid})["roles"]
    assert roles == [{"account": "bob", "role": 3}]
    ms.rpc_alterUser({"account": "bob", "new_password": "p2"})
    assert ms.rpc_checkPassword({"account": "bob", "password": "p2"})["ok"]
