"""Streamed peer-delta absorption + the replica failover ladder
(ISSUE 13) — unit tier, no daemons.

Covers the delta-stream edge cases as units (the partition chaos cells
in tests/test_proc_chaos.py prove the same seams under real link
death):

  * the store's typed delta window verdicts (ok / truncated / opaque /
    ahead) and the fused (epoch, led_gen, version) cursor codec;
  * RemoteStoreView.delta_since against a scripted peer: cursor gap,
    leader change mid-stream, truncated log, peer restart, peer
    unreachable — each a TYPED decline; plus the /healthz stall
    tracking those declines feed;
  * duplicate delivery: re-applying an already-absorbed window is
    idempotent (the overlay collapses per edge identity);
  * absorb-vs-rebuild oracle parity on the REMOTE path (mirroring
    tests/test_absorb.py's differential for the local one);
  * the failover ladder: degraded/transport declines retry the next
    replica, semantic declines do not, the TTL decline cache reorders,
    heartbeat device briefs rank freshest-healthy first.
"""
import time

import pytest

from nebula_tpu.common.flags import flags
from nebula_tpu.common.status import ErrorCode, Status
from nebula_tpu.interface.common import HostAddr
from nebula_tpu.interface.rpc import RpcError
from nebula_tpu.kvstore.store import KVOptions, NebulaStore
from nebula_tpu.storage.device import (RemoteStoreView, TpuDecline,
                                       fuse_peer_version,
                                       split_peer_version)


# ===================================================== store delta window
class TestDeltaWindow:
    def _store(self):
        s = NebulaStore(KVOptions())
        s.delta_cap = 8
        return s

    def test_ok_window_and_upto_bound(self):
        s = self._store()
        for i in range(5):
            s._bump(1, [("put", b"k%d" % i, b"v")])
        evs, reason, ver = s.delta_window(1, 0)
        assert reason == "ok" and ver == 5 and len(evs) == 5
        evs, reason, ver = s.delta_window(1, 2, upto=4)
        assert reason == "ok" and ver == 4
        assert [e[1] for e in evs] == [b"k2", b"k3"]

    def test_truncated_cursor(self):
        s = self._store()
        for i in range(12):                  # cap 8: base advances to 4
            s._bump(1, [("put", b"k%d" % i, b"v")])
        evs, reason, _ver = s.delta_window(1, 2)
        assert evs is None and reason == "truncated"
        evs, reason, _ver = s.delta_window(1, 4)
        assert reason == "ok" and len(evs) == 8

    def test_opaque_window(self):
        s = self._store()
        s._bump(1, [("put", b"k", b"v")])
        s._bump(1, None)                     # ingest/compaction: opaque
        evs, reason, _ver = s.delta_window(1, 0)
        assert evs is None and reason == "opaque"

    def test_cursor_ahead(self):
        s = self._store()
        s._bump(1, [("put", b"k", b"v")])
        evs, reason, _ver = s.delta_window(1, 9)
        assert evs is None and reason == "ahead"

    def test_boot_epoch_randomized(self):
        a, b = NebulaStore(KVOptions()), NebulaStore(KVOptions())
        assert a.boot_epoch >= 1 and b.boot_epoch >= 1
        # 30 random bits: two boots virtually never collide (and the
        # codec below would catch a restart even on version replay)
        assert a.boot_epoch != b.boot_epoch or a is b


class TestFusedCursorCodec:
    def test_round_trip(self):
        for tup in [(1, 1, 0), (923_441_123, 13, 7_654_321),
                    (2 ** 30 - 1, 2 ** 14 - 1, 2 ** 34 - 1)]:
            assert split_peer_version(fuse_peer_version(*tup)) == tup

    def test_each_component_moves_the_fused_value(self):
        base = fuse_peer_version(7, 3, 100)
        assert fuse_peer_version(8, 3, 100) != base
        assert fuse_peer_version(7, 4, 100) != base
        assert fuse_peer_version(7, 3, 101) != base

    def test_led_gen_wraps_in_the_ring(self):
        """led_gen rides the cursor modulo 2^14; both comparison sides
        reduce into the ring, so a long-flapping peer (16384+ led-set
        changes) still streams instead of rebuilding forever."""
        fused = fuse_peer_version(7, (1 << 14) + 3, 9)
        assert split_peer_version(fused) == (7, 3, 9)
        peer = _ScriptedPeer()
        peer.led_gen = (1 << 14) + 3         # raw counter past the ring
        v = _view(peer)
        v.mutation_version(1)
        peer.write(b"k1")
        time.sleep(RemoteStoreView.POLL_REUSE_S + 0.01)
        anchor = fuse_peer_version(peer.epoch, peer.led_gen, 0)
        v.mutation_version(1)
        evs = v.delta_since(1, anchor)
        assert evs is not None and [e[1] for e in evs] == [b"k1"]


# ================================================ RemoteStoreView stream
class _ScriptedPeer:
    """ClientManager double serving deviceVersion/deviceScanDelta from
    an in-memory delta log, with knobs for every stream break."""

    def __init__(self):
        self.epoch = 41
        self.led_gen = 1
        self.led = [0, 1]
        self.version = 0
        self.log = []                        # one event list per version
        self.base = 0
        self.unreachable = False
        self.calls = []

    def write(self, key=b"k", value=b"v"):
        self.version += 1
        self.log.append([["put", key, value]])

    def trim(self, upto):
        drop = upto - self.base
        del self.log[:drop]
        self.base = upto

    def call(self, addr, method, payload, timeout=None):
        self.calls.append(method)
        if self.unreachable:
            raise RpcError(Status(ErrorCode.E_FAIL_TO_CONNECT, "down"))
        if method == "deviceVersion":
            return {"version": self.version, "led_parts": self.led,
                    "epoch": self.epoch, "led_gen": self.led_gen}
        assert method == "deviceScanDelta"
        if int(payload["epoch"]) != self.epoch:
            return {"ok": False, "reason": "peer-restarted"}
        # mirror the real server: led_gen compares in the fused ring
        if int(payload["led_gen"]) != self.led_gen % (1 << 14):
            return {"ok": False, "reason": "peer-leader-changed"}
        cur = int(payload["cursor"])
        upto = min(int(payload["upto"]), self.version)
        if cur > self.version:
            return {"ok": False, "reason": "peer-cursor-gap"}
        if cur < self.base:
            return {"ok": False, "reason": "peer-cursor-truncated"}
        out = []
        for entry in self.log[cur - self.base:upto - self.base]:
            out.extend(entry)
        return {"ok": True, "events": out, "version": upto}


def _view(peer):
    return RemoteStoreView(HostAddr("p", 1), 1, peer)


class TestPeerDeltaStream:
    def test_window_streams_typed_events(self):
        peer = _ScriptedPeer()
        v = _view(peer)
        anchor = v.mutation_version(1)       # polls: version 0
        peer.write(b"k1")
        peer.write(b"k2")
        time.sleep(RemoteStoreView.POLL_REUSE_S + 0.01)
        now = v.mutation_version(1)          # re-polls: version 2
        assert now != anchor
        evs = v.delta_since(1, anchor)
        assert [e[1] for e in evs] == [b"k1", b"k2"]
        assert all(isinstance(e, tuple) for e in evs)
        assert v.last_delta_decline is None
        assert v.stalled_for_s() == 0.0

    def _advance(self, peer, v, writes=1):
        anchor = v.mutation_version(1)
        for _ in range(writes):
            peer.write()
        time.sleep(RemoteStoreView.POLL_REUSE_S + 0.01)
        v.mutation_version(1)                # fresh poll
        return anchor

    def test_truncated_log_is_typed(self):
        peer = _ScriptedPeer()
        v = _view(peer)
        anchor = self._advance(peer, v, writes=6)
        peer.trim(5)
        assert v.delta_since(1, anchor) is None
        assert v.last_delta_decline == "peer-cursor-truncated"
        assert v.stalled_for_s() > 0.0

    def test_leader_change_mid_stream_is_typed(self):
        peer = _ScriptedPeer()
        v = _view(peer)
        anchor = self._advance(peer, v)
        peer.led_gen += 1                    # leadership moved
        peer.led = [0]
        time.sleep(RemoteStoreView.POLL_REUSE_S + 0.01)
        v.mutation_version(1)                # poll sees the new led_gen
        assert v.delta_since(1, anchor) is None
        assert v.last_delta_decline == "peer-leader-changed"

    def test_peer_restart_is_typed_even_on_version_replay(self):
        peer = _ScriptedPeer()
        v = _view(peer)
        anchor = self._advance(peer, v)
        old_version = peer.version
        peer.epoch = 42                      # reboot...
        peer.version = old_version           # ...replays to the SAME
        peer.log = [[["put", b"x", b"y"]]] * old_version  # number
        time.sleep(RemoteStoreView.POLL_REUSE_S + 0.01)
        assert v.mutation_version(1) != fuse_peer_version(
            41, 1, old_version)              # fused version moved
        assert v.delta_since(1, anchor) is None
        assert v.last_delta_decline == "peer-restarted"

    def test_cursor_gap_is_typed(self):
        peer = _ScriptedPeer()
        v = _view(peer)
        self._advance(peer, v)
        ahead = fuse_peer_version(peer.epoch, peer.led_gen,
                                  peer.version + 5)
        assert v.delta_since(1, ahead) is None
        assert v.last_delta_decline == "peer-cursor-gap"

    def test_unreachable_peer_stalls_then_heals(self):
        peer = _ScriptedPeer()
        v = _view(peer)
        v.mutation_version(1)
        peer.unreachable = True
        time.sleep(RemoteStoreView.POLL_REUSE_S + 0.01)
        with pytest.raises(RpcError):
            v.mutation_version(1)
        assert v.last_delta_decline == "peer-unreachable"
        assert v.stalled_for_s() > 0.0
        peer.unreachable = False
        time.sleep(RemoteStoreView.POLL_REUSE_S + 0.01)
        v.mutation_version(1)                # the peer is back
        assert v.stalled_for_s() == 0.0

    def test_full_scan_completion_clears_a_stream_stall(self):
        peer = _ScriptedPeer()
        v = _view(peer)
        anchor = self._advance(peer, v, writes=3)
        peer.trim(2)
        assert v.delta_since(1, anchor) is None
        assert v.stalled_for_s() > 0.0

        class _ScanPeer:
            def call(self, addr, method, payload, timeout=None):
                assert method == "deviceScan"
                return {"ok": True, "rows": [(b"a", b"b")],
                        "cursor": b"a", "done": True, "version": 9}

        v.cm = _ScanPeer()
        # the rebuild's full part scan completes -> cursor re-anchors
        assert list(v.prefix(1, 0, b"")) == [(b"a", b"b")]
        assert v.stalled_for_s() == 0.0


# ====================================== duplicate delivery (idempotence)
class TestDuplicateDelivery:
    def test_duplicated_window_absorbs_idempotently(self):
        """A replayed delta window (same events delivered twice — the
        reply-lost re-poll case) must fold to the SAME state: the
        overlay collapses per edge identity, so re-applied puts/dels
        are no-ops.  Checked against the CPU loop AND the rebuild
        oracle."""
        from nebula_tpu.cluster import LocalCluster
        prev = flags.get("storage_backend")
        flags.set("storage_backend", "tpu")
        c = LocalCluster(num_storage=1, tpu_backend=True)
        try:
            cl = c.client()

            def ok(s):
                r = cl.execute(s)
                assert r.ok(), f"{s}: {r.error_msg}"
                return r

            ok("CREATE SPACE dup(partition_num=2, replica_factor=1)")
            c.refresh_all()
            ok("USE dup")
            ok("CREATE EDGE e(w int)")
            c.refresh_all()
            ok("INSERT EDGE e(w) VALUES "
               + ", ".join(f"{i}->{i % 12 + 1}:({i})"
                           for i in range(1, 13)))
            q = "GO 2 STEPS FROM 1, 5 OVER e YIELD e._dst"
            ok(q)                            # mirror builds
            kv = c.storage_nodes[0].kv
            orig = kv.delta_since
            kv.delta_since = lambda sid, ver: (
                lambda evs: evs + evs if evs else evs)(orig(sid, ver))
            try:
                rt = c.tpu_runtime
                builds0 = rt.stats["mirror_builds"]
                ok("INSERT EDGE e(w) VALUES 1->7@9:(70), 5->2@9:(52)")
                ok("DELETE EDGE e 1 -> 2@0")
                rows_dev = sorted(map(tuple, ok(q).rows))
                flags.set("storage_backend", "cpu")
                try:
                    rows_cpu = sorted(map(tuple, ok(q).rows))
                finally:
                    flags.set("storage_backend", "tpu")
                assert rows_dev == rows_cpu
                assert rt.stats["mirror_builds"] == builds0, \
                    "duplicate delivery forced a rebuild"
                assert rt.stats["mirror_absorbs"] > 0
                # rebuild oracle: a from-scratch scan agrees
                with rt._lock:
                    rt.mirrors.clear()
                assert sorted(map(tuple, ok(q).rows)) == rows_dev
            finally:
                kv.delta_since = orig
        finally:
            flags.set("storage_backend", prev)
            c.stop()


# ==================================== remote absorb-vs-rebuild parity
class TestRemoteAbsorbParity:
    def test_peer_writes_absorb_over_the_wire_with_parity(self):
        """The remote differential, mirroring tests/test_absorb.py: a
        2-storaged space served across the RPC boundary folds PEER
        writes through the delta stream — peer_absorbs grows, the
        steady window pays zero rebuilds, and every step stays
        bit-exact with the CPU loop (plus the final rebuild oracle)."""
        from nebula_tpu.cluster import LocalCluster
        prev = flags.get("storage_backend")
        flags.set("storage_backend", "tpu")
        c = LocalCluster(num_storage=2, tpu_backend="remote")
        try:
            cl = c.client()

            def ok(s):
                r = cl.execute(s)
                assert r.ok(), f"{s}: {r.error_msg}"
                return r

            ok("CREATE SPACE rp(partition_num=4, replica_factor=1)")
            c.refresh_all()
            ok("USE rp")
            ok("CREATE EDGE e(w int)")
            c.refresh_all()
            n = 24
            ok("INSERT EDGE e(w) VALUES "
               + ", ".join(f"{i}->{i % n + 1}:({i})"
                           for i in range(1, n + 1)))
            qs = ["GO 2 STEPS FROM 1, 9 OVER e YIELD e._dst",
                  "GO FROM 3, 4, 5 OVER e YIELD e._dst, e.w",
                  "GO FROM 2 OVER e REVERSELY YIELD e._dst"]
            for q in qs:
                ok(q)                        # device mirror builds

            def serving_rt():
                # the storaged-side deviceGo runtime that actually built
                rts = [node.service._device_rt for node in c.storage_nodes
                       if node.service._device_rt is not None]
                rts = [rt for rt in rts if rt.mirrors]
                assert rts, "no device runtime built a mirror"
                return rts[0]

            rt = serving_rt()
            builds0 = rt.stats["mirror_builds"]
            import random
            rng = random.Random(29)
            for step in range(8):
                s, d = rng.randrange(n) + 1, rng.randrange(n) + 1
                ok(f"INSERT EDGE e(w) VALUES {s}->{d}@{50 + step}"
                   f":({step})")
                q = qs[step % len(qs)]
                rows_dev = sorted(map(tuple, ok(q).rows))
                flags.set("storage_backend", "cpu")
                try:
                    rows_cpu = sorted(map(tuple, ok(q).rows))
                finally:
                    flags.set("storage_backend", "tpu")
                assert rows_dev == rows_cpu, q
            assert rt.stats["mirror_builds"] == builds0, \
                "peer writes forced remote rebuilds"
            assert rt.stats["peer_absorbs"] > 0, \
                "no write window folded events streamed from the peer"
            assert rt.stats["peer_absorb_events"] > 0
            # rebuild oracle on the remote path
            finals = [sorted(map(tuple, ok(q).rows)) for q in qs]
            with rt._lock:
                rt.mirrors.clear()
            assert [sorted(map(tuple, ok(q).rows)) for q in qs] == finals
        finally:
            flags.set("storage_backend", prev)
            c.stop()


# ============================================== failover ladder units
class _LadderRt:
    """RemoteDeviceRuntime with scripted per-host responses."""

    def __new__(cls, script):
        from nebula_tpu.storage.device import RemoteDeviceRuntime
        rt = RemoteDeviceRuntime(meta_client=None, schema_man=None,
                                 client_manager=None)
        rt.attempts = []

        def fake_call(host, method, req, ExcType):
            rt.attempts.append(str(host))
            out = script[str(host)]
            if isinstance(out, Exception):
                raise out
            return out

        rt._call = fake_call
        return rt


def _go(rt, ladder):
    from types import SimpleNamespace
    rt._device_hosts = lambda sid: ladder
    sentence = SimpleNamespace(step=SimpleNamespace(steps=1, upto=False))
    executor = SimpleNamespace(sentence=sentence)
    return rt.run_go(executor, 5, [1], [1], 1, {1: "e"}, [], False,
                     None, {}, [])


class TestFailoverLadder:
    LADDER = [(("h1", 1), [1, 2]), (("h2", 1), [1, 2])]

    def test_degraded_decline_retries_replica(self):
        ok = {"ok": True, "columns": ["c"], "rows": []}
        rt = _LadderRt({"('h1', 1)": TpuDecline("sick", degraded=True,
                                                retriable=True),
                        "('h2', 1)": ok})
        out = _go(rt, list(self.LADDER))
        assert out is not None
        assert rt.attempts == ["('h1', 1)", "('h2', 1)"]
        # the sick replica is decline-cached for the TTL window
        assert rt._dev_decline_active(5, "('h1', 1)")
        assert not rt._dev_decline_active(5, "('h2', 1)")

    def test_transport_failure_retries_replica(self):
        ok = {"ok": True, "columns": ["c"], "rows": []}
        rt = _LadderRt({"('h1', 1)": TpuDecline("rpc failed",
                                                retriable=True),
                        "('h2', 1)": ok})
        assert _go(rt, list(self.LADDER)) is not None
        assert len(rt.attempts) == 2

    def test_semantic_decline_goes_straight_to_cpu(self):
        rt = _LadderRt({"('h1', 1)": TpuDecline("mesh-sharded"),
                        "('h2', 1)": {"ok": True, "columns": [],
                                      "rows": []}})
        with pytest.raises(TpuDecline):
            _go(rt, list(self.LADDER))
        assert rt.attempts == ["('h1', 1)"], \
            "a semantic decline must not burn replica round trips"

    def test_exhausted_ladder_raises_last_degraded(self):
        rt = _LadderRt({"('h1', 1)": TpuDecline("a", degraded=True,
                                                retriable=True),
                        "('h2', 1)": TpuDecline("b", degraded=True,
                                                retriable=True)})
        with pytest.raises(TpuDecline) as ei:
            _go(rt, list(self.LADDER))
        assert ei.value.degraded
        assert len(rt.attempts) == 2

    def test_fully_declined_ladder_probes_only_primary(self):
        """During a fleet-wide outage the decline cache must cheapen
        the ladder to ONE probe per query (the primary), not one
        failed RPC per rung for the whole TTL window."""
        rt = _LadderRt({k: TpuDecline("sick", degraded=True,
                                      retriable=True)
                        for k in ("('h1', 1)", "('h2', 1)")})
        with pytest.raises(TpuDecline):
            _go(rt, list(self.LADDER))        # both probed + noted
        assert len(rt.attempts) == 2
        rt.attempts.clear()
        with pytest.raises(TpuDecline):
            _go(rt, list(self.LADDER))        # within the TTL window
        assert len(rt.attempts) == 1, \
            "later rungs inside a decline window must be skipped"

    def test_semantic_decline_blames_the_raising_host(self):
        """A semantic decline raised by rung 2 after rung 1's
        transport failure carries rung 2's host, so UPTO-style
        negative caches never pin the healthy primary."""
        rt = _LadderRt({"('h1', 1)": TpuDecline("rpc failed",
                                                retriable=True),
                        "('h2', 1)": TpuDecline("mesh-sharded there")})
        with pytest.raises(TpuDecline) as ei:
            _go(rt, list(self.LADDER))
        assert str(ei.value.host) == "('h2', 1)"

    def test_replica_cap_bounds_the_ladder(self):
        saved = flags.get("device_failover_replicas")
        flags.set("device_failover_replicas", 1)
        try:
            rt = _LadderRt({"('h1', 1)": TpuDecline("a", degraded=True,
                                                    retriable=True),
                            "('h2', 1)": {"ok": True, "columns": [],
                                          "rows": []}})
            with pytest.raises(TpuDecline):
                _go(rt, list(self.LADDER))
            assert len(rt.attempts) == 1, "ladder must be off at 1"
        finally:
            flags.set("device_failover_replicas", saved)

    def test_decline_ttl_lapses(self):
        saved = flags.get("device_decline_ttl_s")
        flags.set("device_decline_ttl_s", 0.05)
        try:
            rt = _LadderRt({})
            rt._note_dev_declined(5, "h1")
            assert rt._dev_decline_active(5, "h1")
            time.sleep(0.06)
            assert not rt._dev_decline_active(5, "h1")
        finally:
            flags.set("device_decline_ttl_s", saved)


class TestLadderOrdering:
    def _rt(self, alloc, briefs, declined=()):
        from types import SimpleNamespace

        from nebula_tpu.storage.device import RemoteDeviceRuntime
        meta = SimpleNamespace(parts_alloc=lambda sid: alloc,
                               device_briefs=lambda: briefs)
        rt = RemoteDeviceRuntime(meta_client=meta, schema_man=None,
                                 client_manager=None)
        for h in declined:
            rt._note_dev_declined(7, h)
        return rt

    ALLOC = {1: ["127.0.0.1:1", "127.0.0.1:2"],
             2: ["127.0.0.1:1", "127.0.0.1:2"]}

    def test_freshest_healthy_replica_first(self):
        briefs = {"127.0.0.1:1": {"7": {"generation": 3}},
                  "127.0.0.1:2": {"7": {"generation": 9}}}
        rt = self._rt(self.ALLOC, briefs)
        ladder = rt._device_hosts(7)
        assert [str(h) for h, _p in ladder] == \
            ["127.0.0.1:2", "127.0.0.1:1"]
        assert ladder[0][1] == [1, 2]        # the SAME parts, any rung

    def test_open_breaker_ranks_behind_healthy(self):
        briefs = {"127.0.0.1:1": {"7": {"generation": 9,
                                        "breaker_open": True}},
                  "127.0.0.1:2": {"7": {"generation": 1}}}
        rt = self._rt(self.ALLOC, briefs)
        assert str(rt._device_hosts(7)[0][0]) == "127.0.0.1:2"

    def test_declined_replica_sorts_last_but_stays(self):
        rt = self._rt(self.ALLOC, {}, declined=("127.0.0.1:1",))
        ladder = rt._device_hosts(7)
        assert [str(h) for h, _p in ladder] == \
            ["127.0.0.1:2", "127.0.0.1:1"]
        assert len(ladder) == 2, "declined replicas stay as last resort"

    def test_briefs_failure_is_advisory(self):
        from types import SimpleNamespace

        def boom():
            raise RuntimeError("metad away")

        from nebula_tpu.storage.device import RemoteDeviceRuntime
        meta = SimpleNamespace(parts_alloc=lambda sid: self.ALLOC,
                               device_briefs=boom)
        rt = RemoteDeviceRuntime(meta_client=meta, schema_man=None,
                                 client_manager=None)
        assert len(rt._device_hosts(7)) == 2
