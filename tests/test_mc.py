"""nebulamc gate + engine unit tests (docs/static_analysis.md "The
model-checking layer").

Three tiers in one module:

* scheduler/explorer unit tests — mutual exclusion bookkeeping,
  wait/notify hand-off, deterministic replay (same schedule, same
  trace), deadlock detection, the state-machine monitor catching a
  rogue write, schedule-id round-trips;
* the REGRESSION gate: the three historical soak bugs reconstructed in
  tests/lint_fixtures/mc_racy.py (PR 6 missed wakeup, PR 7 leaked
  probe token, PR 15 stranded lane seat) must each be FOUND within a
  bounded budget, replay deterministically from their schedule ids,
  and the fixed control must pass the same exploration exhaustively;
* the tier-1 smoke: every registered production scenario explored at
  its small smoke budget — the exhaustive full-budget sweep is the
  slow-marked test at the bottom (scripts/chaos.sh runs it).
"""
import os
import subprocess
import sys

import pytest

from nebula_tpu.common import mc_hooks
from nebula_tpu.tools.mc import (McViolation, Monitor, SCENARIOS,
                                 Schedule, Scheduler, decode_schedule,
                                 encode_schedule, explore,
                                 explore_scenario, run_scenario)

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures",
                        "mc_racy.py")


def _load_fixtures():
    import importlib.util
    spec = importlib.util.spec_from_file_location("_mc_racy", FIXTURES)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.FIXTURE_SCENARIOS


# ================================================== scheduler unit tier
class TestScheduler:
    def test_lock_mutual_exclusion_bookkeeping(self):
        """Two logical threads bumping a counter under an McLock: every
        interleaving serializes the critical sections."""
        def run_one(schedule):
            sched = Scheduler(schedule)
            state = {"lock": None, "n": 0, "max_in": 0, "in_cs": 0}

            def build():
                state["lock"] = mc_hooks.Lock("t.lock")
            sched.construct(build)

            def body():
                with state["lock"]:
                    state["in_cs"] += 1
                    state["max_in"] = max(state["max_in"],
                                          state["in_cs"])
                    sched.yield_point("t.cs")
                    state["n"] += 1
                    state["in_cs"] -= 1
            r = sched.run([("a", body), ("b", body)])
            assert r.violation is None, r.violation
            assert state["n"] == 2 and state["max_in"] == 1
            return r
        res = explore(run_one, max_preemptions=2)
        assert res.ok and res.exhausted and res.executions >= 2

    def test_wait_notify_handoff(self):
        """A waiter parked on a condition wakes only after the notify,
        and reacquires the lock before its wait() returns."""
        def run_one(schedule):
            sched = Scheduler(schedule)
            box = {}

            def build():
                box["cond"] = mc_hooks.Condition("t.cond")
                box["ready"] = False
                box["order"] = []
            sched.construct(build)
            cond, order = box["cond"], box["order"]

            def waiter():
                with cond:
                    while not box["ready"]:
                        cond.wait()
                    order.append("woke")

            def notifier():
                with cond:
                    box["ready"] = True
                    order.append("notified")
                    cond.notify_all()
            r = sched.run([("w", waiter), ("n", notifier)])
            assert r.violation is None, r.violation
            assert box["order"][-1] == "woke"
            return r
        res = explore(run_one, max_preemptions=2)
        assert res.ok and res.exhausted

    def test_deterministic_replay_same_trace(self):
        """The same schedule prefix produces the identical trace —
        the property every replayable schedule id rests on."""
        scen = SCENARIOS["prioslots-handoff"]
        r1 = run_scenario(scen, Schedule((1, 0, 2)))
        r2 = run_scenario(scen, Schedule((1, 0, 2)))
        assert r1.trace == r2.trace and len(r1.trace) > 3

    def test_deadlock_detected(self):
        """Two threads acquiring two locks in opposite orders: some
        interleaving must deadlock, and the report names both."""
        def run_one(schedule):
            sched = Scheduler(schedule)
            box = {}

            def build():
                box["a"] = mc_hooks.Lock("t.A")
                box["b"] = mc_hooks.Lock("t.B")
            sched.construct(build)
            a, b = box["a"], box["b"]

            def ab():
                with a:
                    sched.yield_point("t.mid")
                    with b:
                        pass

            def ba():
                with b:
                    sched.yield_point("t.mid")
                    with a:
                        pass
            return sched.run([("ab", ab), ("ba", ba)])
        res = explore(run_one, max_preemptions=2)
        assert res.violation is not None
        assert "deadlock" in str(res.violation).lower()

    def test_monitor_flags_rogue_write(self):
        """A write to a declared machine field outside its declared
        writer methods is a violation even on a clean schedule."""
        class Cell:
            def __init__(self):
                self.state = "closed"

            def admit(self):            # declared writer
                self.state = "half_open"

            def poke(self):             # NOT a declared writer
                self.state = "open"

        mon = Monitor()
        mon.bind("breaker-cell", Cell, Cell)
        try:
            c = Cell()
            c.admit()
            assert mon.violations == []
            with pytest.raises(McViolation):
                c.poke()
            assert mon.violations
            assert "outside" in mon.violations[0]
        finally:
            mon.unbind_all()

    def test_schedule_id_roundtrip(self):
        for choices in ((), (0,), (1, 0, 35, 2), tuple(range(12))):
            sid = encode_schedule("lane-churn", choices)
            name, sched = decode_schedule(sid)
            assert name == "lane-churn"
            assert tuple(sched.choices) == choices
        with pytest.raises(ValueError):
            decode_schedule("no-at-sign")


# =============================================== historical-bug gate
class TestHistoricalBugs:
    """Each reconstructed soak bug must be FOUND within its smoke
    budget and must replay deterministically from the reported id."""

    def _find(self, name):
        reg = _load_fixtures()
        s = reg[name]
        res = explore_scenario(s, *s.smoke)
        assert res.violation is not None, \
            f"{name}: bug not found in {res.executions} executions"
        sid = encode_schedule(name, res.failing_choices)
        # replay round-trip: decode the id, re-run, same failure class
        rname, schedule = decode_schedule(sid)
        assert rname == name
        replay = run_scenario(reg[name], schedule)
        assert replay.violation is not None, \
            f"{name}: schedule {sid} did not reproduce on replay"
        return res, replay

    def test_pr6_missed_wakeup_found_and_replays(self):
        res, replay = self._find("pr6-slots-missed-wakeup")
        assert "deadlock" in str(replay.violation).lower()

    def test_pr7_probe_leak_found_and_replays(self):
        res, replay = self._find("pr7-probe-leak")
        assert "probe" in str(replay.violation)

    def test_pr15_lane_strand_found_and_replays(self):
        res, replay = self._find("pr15-lane-strand")
        assert "strand" in str(replay.violation)

    def test_pr15_fixed_control_passes_exhaustively(self):
        reg = _load_fixtures()
        s = reg["pr15-lane-strand-fixed"]
        res = explore_scenario(s, *s.smoke)
        assert res.ok, res.violation
        assert res.exhausted, "control scenario must exhaust its bound"


# ================================================= production smoke
@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_smoke(name):
    """Tier-1: every registered scenario is clean within its small
    smoke budget (bounded preemptions, capped executions/seconds)."""
    s = SCENARIOS[name]
    res = explore_scenario(s, *s.smoke)
    assert res.violation is None, (
        f"{name} FAILED: {res.violation}\n  replay: python -m "
        f"nebula_tpu.tools.mc replay --schedule="
        f"{encode_schedule(name, res.failing_choices)}")


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_exhaustive_sweep(name):
    """The chaos-lane sweep (scripts/chaos.sh --cell mc_sweep): full
    budgets, and the bound must actually be exhausted — a
    budget-truncated 'pass' is not a proof."""
    s = SCENARIOS[name]
    res = explore_scenario(s, *s.full)
    assert res.violation is None, (
        f"{name} FAILED: {res.violation}\n  replay: python -m "
        f"nebula_tpu.tools.mc replay --schedule="
        f"{encode_schedule(name, res.failing_choices)}")
    assert res.exhausted, (
        f"{name}: {res.executions} executions in {res.seconds:.0f}s "
        f"without exhausting bound {res.bound} — raise the budget")


# ========================================================== CLI tier
def _cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "nebula_tpu.tools.mc", *args],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class TestCli:
    def test_list_names_every_scenario(self):
        p = _cli("list")
        assert p.returncode == 0
        for name in SCENARIOS:
            assert name in p.stdout

    def test_run_unknown_scenario_is_usage_error(self):
        p = _cli("run", "no-such-scenario")
        assert p.returncode == 2
        assert "closed" in p.stderr

    def test_run_clean_scenario_exits_zero(self):
        p = _cli("run", "prioslots-handoff", "--smoke")
        assert p.returncode == 0, p.stdout + p.stderr
        assert "ok " in p.stdout

    def test_run_fixture_bug_exits_one_with_replayable_id(self):
        p = _cli("run", "pr7-probe-leak", "--smoke",
                 f"--fixtures={FIXTURES}")
        assert p.returncode == 1, p.stdout + p.stderr
        line = [ln for ln in p.stdout.splitlines()
                if "--schedule=" in ln][0]
        sid = line.split("--schedule=")[1].strip()
        rp = _cli("replay", f"--schedule={sid}",
                  f"--fixtures={FIXTURES}")
        assert rp.returncode == 1, rp.stdout + rp.stderr
        assert "FAIL pr7-probe-leak" in rp.stdout

    def test_run_sarif_shape(self):
        import json
        p = _cli("run", "pr15-lane-strand", "--smoke",
                 "--format=sarif", f"--fixtures={FIXTURES}")
        assert p.returncode == 1
        doc = json.loads(p.stdout)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "nebulamc"
        assert run["results"] and all(
            r["ruleId"] == "mc-violation" for r in run["results"])

    def test_sarif_golden_file(self):
        """Golden-file contract for mc findings: exploration is
        deterministic, so the SARIF payload for the PR 7 probe-leak
        fixture — failing schedule id included — is byte-stable."""
        import json
        p = _cli("run", "pr7-probe-leak", "--smoke",
                 "--format=sarif", f"--fixtures={FIXTURES}")
        assert p.returncode == 1
        doc = json.loads(p.stdout)
        golden_path = os.path.join(os.path.dirname(FIXTURES),
                                   "golden_mc.sarif")
        with open(golden_path, encoding="utf-8") as fh:
            golden = json.load(fh)
        assert doc == golden, json.dumps(doc, indent=2, sort_keys=True)
