"""Bit-packed frontier + reduction-pushdown tests (docs/roofline.md).

Three tiers:
  * kernel parity — randomized dense/absorbed/BFS packed-vs-int8
    differentials across the go_batch_widths ladder, hub-heavy and
    hub-free graphs, donation safety (a donated packed frontier is
    consumed, never aliased), and the sparse LIMIT/COUNT reductions
    against the unreduced kernel;
  * runtime parity — the packed default must serve bit-identical rows
    to the int8 layout through the full launch/assemble pipeline,
    including hops over absorbed-generation tables;
  * pushdown e2e — GO | LIMIT and GO | YIELD COUNT(*) across CPU and
    device backends, with the runtime's go_reduced/fetch_bytes stats
    proving the reduced path actually ran.
"""
import numpy as np
import pytest

from nebula_tpu.tpu import ell as E

ETYPES = (1, 2)


def _graph(seed: int, n: int, m: int, hub: bool, cap: int = 16):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    if hub:
        dst[: m // 8] = 0              # concentrate: spill extra rows
    et = rng.integers(1, 3, m).astype(np.int32)
    s2 = np.concatenate([src, dst]).astype(np.int32)
    d2 = np.concatenate([dst, src]).astype(np.int32)
    e2 = np.concatenate([et, -et]).astype(np.int32)
    ix = E.EllIndex.build(s2, d2, e2, n, cap=cap, use_native=False)
    return ix, s2, d2, e2, rng


def _starts(rng, n, B, per=3):
    return [rng.integers(0, n, per) for _ in range(B)]


class TestPackedKernelParity:
    @pytest.mark.parametrize("hub", [False, True])
    @pytest.mark.parametrize("B", [8, 128])        # widths-ladder rungs
    @pytest.mark.parametrize("steps", [1, 2, 4])
    def test_go_matches_int8(self, hub, B, steps):
        import jax.numpy as jnp
        ix, *_rest, rng = _graph(3 + B + steps, 150, 900, hub)
        f0 = ix.start_frontier(_starts(rng, ix.n, B), B=B)
        ref = np.asarray(E.make_batched_go_kernel(ix, steps, ETYPES)(
            jnp.asarray(f0), *ix.kernel_args()))
        eslot, hrows = ix.hub_merge()
        out = np.asarray(E.make_batched_go_lanes_kernel(
            ix, steps, ETYPES)(
            jnp.asarray(E.pack_lanes_host(f0)), jnp.asarray(eslot),
            jnp.asarray(hrows), *ix.kernel_args()[1:]))
        # hub extra rows may hold junk in BOTH layouts; real rows match
        assert (E.unpack_lanes_host(out, B)[:ix.n]
                == (ref[:ix.n] > 0)).all()

    @pytest.mark.parametrize("hub", [False, True])
    def test_upto_union_matches_int8(self, hub):
        import jax.numpy as jnp
        ix, *_rest, rng = _graph(11, 120, 700, hub)
        B = 32
        f0 = ix.start_frontier(_starts(rng, ix.n, B), B=B)
        ref = np.asarray(E.make_batched_go_kernel(
            ix, 3, ETYPES, upto=True)(jnp.asarray(f0),
                                      *ix.kernel_args()))
        eslot, hrows = ix.hub_merge()
        out = np.asarray(E.make_batched_go_lanes_kernel(
            ix, 3, ETYPES, upto=True)(
            jnp.asarray(E.pack_lanes_host(f0)), jnp.asarray(eslot),
            jnp.asarray(hrows), *ix.kernel_args()[1:]))
        assert (E.unpack_lanes_host(out, B)[:ix.n]
                == (ref[:ix.n] > 0)).all()

    @pytest.mark.parametrize("hub", [False, True])
    @pytest.mark.parametrize("shortest", [True, False])
    def test_bfs_matches_int8(self, hub, shortest):
        import jax.numpy as jnp
        ix, *_rest, rng = _graph(7, 150, 900, hub)
        B = 16
        f0 = ix.start_frontier(_starts(rng, ix.n, B, per=2), B=B)
        t0 = ix.start_frontier(_starts(rng, ix.n, B, per=2), B=B)
        ref = np.asarray(E.make_batched_bfs_kernel(
            ix, 5, ETYPES, stop_when_found=shortest)(
            jnp.asarray(f0), jnp.asarray(t0), *ix.kernel_args()))
        eslot, hrows = ix.hub_merge()
        out = np.asarray(E.make_batched_bfs_lanes_kernel(
            ix, 5, ETYPES, stop_when_found=shortest)(
            jnp.asarray(E.pack_lanes_host(f0)),
            jnp.asarray(E.pack_lanes_host(t0)),
            jnp.asarray(eslot), jnp.asarray(hrows),
            *ix.kernel_args()[1:]))
        assert (ref[:ix.n] == out[:ix.n]).all()

    def test_absorbed_tables_match_int8_and_packed_hops(self):
        """Absorb a delta into the resident tables (plan + host apply
        + device scatter), then both frontier layouts hopping over the
        ABSORBED tables must match the int8 kernel over an EllIndex
        rebuilt from scratch on the merged edge list — slot ORDER may
        differ (absorption refills rows), semantics may not."""
        import bisect
        import jax.numpy as jnp
        ix, s2, d2, e2, rng = _graph(19, 100, 500, hub=True)
        B, steps = 16, 3
        # pick dsts with >= 2 free slots in their main row so the plan
        # is absorbable by construction (and shapes survive the oracle
        # rebuild below); duplicate each dst to exercise multi-insert
        # rows
        bstarts = [0]
        for a in ix.bucket_nbr[:-1]:
            bstarts.append(bstarts[-1] + a.shape[0])

        def slack_of(old: int) -> int:
            r = int(ix.perm[old])
            b = bisect.bisect_right(bstarts, r) - 1
            row = ix.bucket_nbr[b][r - bstarts[b]]
            return int((row == ix.n_rows).sum())

        cand = [v for v in range(ix.n) if slack_of(v) >= 2][:3]
        assert len(cand) == 3
        ins_dst = np.asarray(cand * 2, np.int32)
        k = len(ins_dst)
        ins_src = rng.integers(0, ix.n, k).astype(np.int32)
        ins_et = np.ones(k, np.int32)
        plan = E.plan_ell_absorb(ix, ins_dst, ins_src, ins_et,
                                 np.zeros(0, np.int32),
                                 np.zeros(0, np.int32),
                                 np.zeros(0, np.int32))
        assert plan is not None
        ix2 = E.apply_ell_absorb_host(ix, plan, ix.m + k)
        counts, upd = E.absorb_update_arrays(ix, plan)
        outs = E.make_ell_absorb_kernel(ix, counts)(
            *[jnp.asarray(u[0]) for u in upd],
            *[jnp.asarray(u[1]) for u in upd],
            *[jnp.asarray(u[2]) for u in upd],
            *[jnp.asarray(a) for a in ix.bucket_nbr],
            *[jnp.asarray(a) for a in ix.bucket_et])
        nb = len(ix.bucket_nbr)
        for b in range(nb):     # device scatter == host apply
            assert np.array_equal(np.asarray(outs[b]), ix2.bucket_nbr[b])
            assert np.array_equal(np.asarray(outs[nb + b]),
                                  ix2.bucket_et[b])
        # oracle: rebuild from scratch on the merged edge list (same
        # shapes by construction: inserts stay within slot slack)
        ms = np.concatenate([s2, ins_src])
        md = np.concatenate([d2, ins_dst])
        me = np.concatenate([e2, ins_et])
        ix_ref = E.EllIndex.build(ms, md, me, ix.n, cap=16,
                                  use_native=False)
        assert ix_ref.shape_sig() == ix2.shape_sig()
        f0 = ix.start_frontier(_starts(rng, ix.n, B), B=B)
        ref = np.asarray(E.make_batched_go_kernel(ix_ref, steps, ETYPES)(
            jnp.asarray(f0), *ix_ref.kernel_args()))
        got8 = np.asarray(E.make_batched_go_kernel(ix2, steps, ETYPES)(
            jnp.asarray(f0), *ix2.kernel_args()))
        eslot, hrows = ix2.hub_merge()
        gotp = np.asarray(E.make_batched_go_lanes_kernel(
            ix2, steps, ETYPES)(
            jnp.asarray(E.pack_lanes_host(f0)), jnp.asarray(eslot),
            jnp.asarray(hrows), *ix2.kernel_args()[1:]))
        assert ((got8[:ix.n] > 0) == (ref[:ix.n] > 0)).all()
        assert (E.unpack_lanes_host(gotp, B)[:ix.n]
                == (ref[:ix.n] > 0)).all()

    def test_absorb_update_counts_are_uniform(self):
        """The absorb kernel cache key is the padded-counts tuple: a
        per-bucket pow-2 ladder would make the key space the CROSS
        PRODUCT of rungs across buckets — each novel mix a fresh
        synchronous XLA compile under the per-space build lock —
        so absorb_update_arrays must pad every bucket to ONE shared
        rung (the registry's log2(mirror_delta_max) budget depends on
        it, and the audit fixture instantiates uniform counts)."""
        ix, *_rest, rng = _graph(31, 100, 500, hub=True)
        assert len(ix.bucket_nbr) >= 2

        def mkplan(rows_per_bucket):
            plan = {}
            for b, k in enumerate(rows_per_bucket):
                if not k:
                    continue
                D = ix.bucket_nbr[b].shape[1]
                plan[b] = (np.arange(k, dtype=np.int32),
                           np.full((k, D), ix.n_rows, np.int32),
                           np.zeros((k, D), np.int32))
            return plan

        # a lopsided plan: many updates in one bucket, few elsewhere
        lop = [0] * len(ix.bucket_nbr)
        lop[0], lop[1] = 24, 2
        counts, upd = E.absorb_update_arrays(ix, mkplan(lop))
        assert len(set(counts)) == 1          # one shared rung
        kp = counts[0]
        assert kp >= 24
        assert kp & (kp - 1) == 0             # pow-2 rung
        for (rp, pn, pe) in upd:
            assert len(rp) == kp == len(pn) == len(pe)
        # key stability: a different bucket mix at the same max rung
        # must reuse the same counts tuple (no recompile per novel mix)
        flip = [0] * len(ix.bucket_nbr)
        flip[0], flip[1] = 3, 24
        counts2, _ = E.absorb_update_arrays(ix, mkplan(flip))
        assert counts2 == counts

    def test_donated_packed_frontier_not_aliased(self):
        """donate=True consumes f0p: the caller's jnp buffer must be
        unusable after dispatch, and re-building a fresh frontier must
        give the same result (the runtime builds fresh per dispatch —
        the audit's donation claim is only safe because of that)."""
        import jax.numpy as jnp
        ix, *_rest, rng = _graph(23, 80, 400, hub=False)
        B = 16
        f0 = ix.start_frontier(_starts(rng, ix.n, B), B=B)
        eslot, hrows = ix.hub_merge()
        kern = E.make_batched_go_lanes_kernel(ix, 3, ETYPES,
                                              donate=True)
        f0p = jnp.asarray(E.pack_lanes_host(f0))
        out1 = np.asarray(kern(f0p, jnp.asarray(eslot),
                               jnp.asarray(hrows),
                               *ix.kernel_args()[1:]))
        assert f0p.is_deleted()        # consumed, never aliased
        f0p2 = jnp.asarray(E.pack_lanes_host(f0))
        out2 = np.asarray(kern(f0p2, jnp.asarray(eslot),
                               jnp.asarray(hrows),
                               *ix.kernel_args()[1:]))
        assert (out1 == out2).all()

    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(0)
        f = (rng.random((37, 24)) < 0.3).astype(np.int8)
        assert (E.unpack_lanes_host(E.pack_lanes_host(f), 24)
                == (f > 0)).all()


class TestSparseReductions:
    def _fixture(self, steps=3):
        ix, s2, d2, e2, rng = _graph(31, 300, 1200, hub=False, cap=64)
        deg_old = np.bincount(
            s2[np.isin(e2, np.asarray(ETYPES))], minlength=ix.n)
        deg = np.zeros(ix.n_rows + 1, np.int32)
        deg[ix.perm] = deg_old.astype(np.int32)
        d_max = max(ix.bucket_D)
        caps = E.sparse_caps(64, d_max, steps, 1 << 18)
        ids0 = np.full(64, ix.n_rows, np.int32)
        qid0 = np.zeros(64, np.int32)
        flat, qs = [], []
        for q, st in enumerate(_starts(rng, ix.n, 8, per=2)):
            for v in sorted(set(int(x) for x in st)):
                flat.append(int(ix.perm[v]))
                qs.append(q)
        order = np.lexsort((flat, qs))
        ids0[: len(flat)] = np.asarray(flat, np.int32)[order]
        qid0[: len(flat)] = np.asarray(qs, np.int32)[order]
        return ix, deg, caps, ids0, qid0, steps

    def _run(self, ix, kern, ids0, qid0, extra=()):
        import jax.numpy as jnp
        ecnt, e0 = ix.hub_expansion()
        return kern(jnp.asarray(ids0), jnp.asarray(qid0),
                    jnp.asarray(ecnt), jnp.asarray(e0),
                    *extra, *ix.kernel_args()[1:])

    def test_limit_cut_is_degree_prefix_and_smaller(self):
        import collections
        import jax.numpy as jnp
        ix, deg, caps, ids0, qid0, steps = self._fixture()
        full_k = E.make_batched_sparse_go_kernel(ix, steps, ETYPES,
                                                 caps, qmax=64)
        out_full = np.asarray(self._run(ix, full_k, ids0, qid0))
        _c, ovf, qids, vnew = E.sparse_go_pairs(full_k, out_full)
        assert not ovf
        L = 4
        lim_k = E.make_batched_sparse_go_kernel(
            ix, steps, ETYPES, caps, qmax=64, limit=L)
        out_lim = np.asarray(self._run(ix, lim_k, ids0, qid0,
                                       extra=(jnp.asarray(deg),)))
        assert out_lim.nbytes * 4 <= out_full.nbytes   # >= 4x smaller
        _cl, ovfl, qidl, vnewl = E.sparse_go_pairs(lim_k, out_lim)
        assert not ovfl
        full = collections.defaultdict(list)
        red = collections.defaultdict(list)
        for q, v in zip(qids, vnew):
            full[int(q)].append(int(v))
        for q, v in zip(qidl, vnewl):
            red[int(q)].append(int(v))
        for q in full:
            want, acc = [], 0
            for v in sorted(full[q]):
                if deg[v] == 0:
                    continue
                if acc >= L:
                    break
                want.append(v)
                acc += int(deg[v])
            assert sorted(red.get(q, [])) == want

    def test_count_matches_degree_fold(self):
        import jax.numpy as jnp
        ix, deg, caps, ids0, qid0, steps = self._fixture()
        full_k = E.make_batched_sparse_go_kernel(ix, steps, ETYPES,
                                                 caps, qmax=64)
        out_full = np.asarray(self._run(ix, full_k, ids0, qid0))
        _c, ovf, qids, vnew = E.sparse_go_pairs(full_k, out_full)
        assert not ovf
        cnt_k = E.make_batched_sparse_go_kernel(
            ix, steps, ETYPES, caps, qmax=64, count=True)
        out_cnt = np.asarray(self._run(ix, cnt_k, ids0, qid0,
                                       extra=(jnp.asarray(deg),)))
        assert not bool(out_cnt[1])
        counts = out_cnt[2:]
        want = np.zeros(8, np.int64)
        for q, v in zip(qids, vnew):
            want[int(q)] += int(deg[int(v)])
        assert (counts[:8] == want).all()
        assert out_cnt.nbytes * 4 <= out_full.nbytes


class TestRuntimePackedParity:
    """The full launch/assemble pipeline must serve identical rows in
    both frontier layouts — including hops over a freshly ABSORBED
    mirror generation."""

    def _boot(self):
        from nebula_tpu.cluster import LocalCluster
        c = LocalCluster(num_storage=1, tpu_backend=True)
        cl = c.client()

        def ok(stmt):
            r = cl.execute(stmt)
            assert r.ok(), f"{stmt}: {r.error_msg}"
            return r

        ok("CREATE SPACE pf(partition_num=3, replica_factor=1)")
        c.refresh_all()
        ok("USE pf; CREATE EDGE e(w int)")
        c.refresh_all()
        rng = np.random.default_rng(4)
        edges = ", ".join(
            f"{int(s)} -> {int(d)}:({int(s) % 7})"
            for s, d in zip(rng.integers(1, 60, 300),
                            rng.integers(1, 60, 300)))
        ok(f"INSERT EDGE e(w) VALUES {edges}")
        return c, cl, ok

    def test_layouts_serve_identical_rows(self):
        from nebula_tpu.common.flags import flags
        c, cl, ok = self._boot()
        try:
            qs = ["GO 3 STEPS FROM 1,2,3 OVER e YIELD e._dst, e.w",
                  "GO 2 STEPS FROM 5 OVER e REVERSELY",
                  "GO UPTO 3 STEPS FROM 7 OVER e"]
            for q in qs:
                flags.set("tpu_packed_frontier", True)
                a = sorted(map(tuple, ok(q).rows))
                flags.set("tpu_packed_frontier", False)
                b = sorted(map(tuple, ok(q).rows))
                assert a == b, q
        finally:
            flags.set("tpu_packed_frontier", True)
            c.stop()

    def test_absorbed_generation_path_packed(self):
        """Fresh edge inserts ABSORB into a new mirror generation (no
        rebuild) and must surface identically under both frontier
        layouts and the CPU oracle."""
        from nebula_tpu.common.flags import flags
        c, cl, ok = self._boot()
        try:
            rt = c.tpu_runtime
            q = "GO 2 STEPS FROM 1 OVER e YIELD e._dst"
            ok(q)                                  # build mirror
            builds0 = rt.stats["mirror_builds"]
            ok('INSERT EDGE e(w) VALUES 1 -> 59:(1), 59 -> 2:(2)')
            flags.set("tpu_packed_frontier", True)
            a = sorted(map(tuple, ok(q).rows))
            assert rt.stats["mirror_builds"] == builds0, \
                "insert should absorb into the tables, not rebuild"
            assert rt.stats.get("mirror_absorbs", 0) > 0
            assert rt.stats.get("mirror_deltas", 0) > 0
            flags.set("tpu_packed_frontier", False)
            b = sorted(map(tuple, ok(q).rows))
            assert a == b
            flags.set("storage_backend", "cpu")
            try:
                cpu = sorted(map(tuple, ok(q).rows))
            finally:
                flags.set("storage_backend", "tpu")
            assert a == cpu
        finally:
            flags.set("tpu_packed_frontier", True)
            c.stop()


class TestReductionPushdownE2E:
    def _boot_pair(self):
        from nebula_tpu.cluster import LocalCluster
        out = []
        for tpu in (False, True):
            c = LocalCluster(num_storage=1, tpu_backend=tpu)
            cl = c.client()

            def ok(stmt, _cl=cl):
                r = _cl.execute(stmt)
                assert r.ok(), f"{stmt}: {r.error_msg}"
                return r

            ok("CREATE SPACE rp(partition_num=3, replica_factor=1)")
            c.refresh_all()
            ok("USE rp; CREATE EDGE e(w int)")
            c.refresh_all()
            rng = np.random.default_rng(9)
            edges = ", ".join(
                f"{int(s)} -> {int(d)}:({int(d) % 5})"
                for s, d in zip(rng.integers(1, 40, 250),
                                rng.integers(1, 40, 250)))
            ok(f"INSERT EDGE e(w) VALUES {edges}")
            out.append((c, cl, ok))
        return out

    def test_limit_and_count_parity(self):
        (ccpu, cpu, _okc), (ctpu, tpu, _okt) = self._boot_pair()
        try:
            rt = ctpu.tpu_runtime
            red0 = rt.stats["go_reduced"]
            for steps in (1, 2, 3):
                base = f"GO {steps} STEPS FROM 1,2 OVER e " \
                       f"YIELD e._dst AS d"
                full_rows = cpu.execute(base).rows
                full = {tuple(r) for r in full_rows}
                for lim in (1, 3, 10_000):
                    q = f"{base} | LIMIT {lim}"
                    a, b = cpu.execute(q), tpu.execute(q)
                    assert a.ok() and b.ok(), (q, b.error_msg)
                    assert len(b.rows) == min(lim, len(full_rows)), q
                    assert all(tuple(r) in full for r in b.rows), q
                q = f"{base} | LIMIT 1, 2"
                b = tpu.execute(q)
                assert len(b.rows) == min(2, max(len(full_rows) - 1, 0))
                for cq in (f"{base} | YIELD COUNT(*)",
                           f"{base} | YIELD COUNT(*) AS n",
                           f"{base} | YIELD COUNT()"):
                    a, b = cpu.execute(cq), tpu.execute(cq)
                    assert a.ok() and b.ok(), (cq, b.error_msg)
                    assert a.column_names == b.column_names
                    assert sorted(map(tuple, a.rows)) == \
                        sorted(map(tuple, b.rows)), cq
            # empty-input COUNT: zero groups -> zero rows, both paths
            q0 = "GO FROM 9999 OVER e | YIELD COUNT(*)"
            assert cpu.execute(q0).rows == tpu.execute(q0).rows == []
            assert rt.stats["go_reduced"] > red0, \
                "device reduction never engaged"
        finally:
            ccpu.stop()
            ctpu.stop()

    def test_count_over_sparse_split_path(self):
        """A COUNT batch whose combined start count outgrows the sparse
        ladder must stitch per-group _DeviceCounts instead of slice-
        assigning them as vertex lists (review finding: TypeError fed
        the circuit breaker)."""
        from nebula_tpu.cluster import LocalCluster
        from nebula_tpu.common.flags import flags
        c = LocalCluster(num_storage=1, tpu_backend=True)
        try:
            cl = c.client()

            def ok(stmt):
                r = cl.execute(stmt)
                assert r.ok(), f"{stmt}: {r.error_msg}"
                return r

            ok("CREATE SPACE sp(partition_num=3, replica_factor=1)")
            c.refresh_all()
            ok("USE sp; CREATE EDGE e(w int)")
            c.refresh_all()
            rng = np.random.default_rng(5)
            edges = ", ".join(
                f"{int(s)} -> {int(d)}:(1)"
                for s, d in zip(rng.integers(1, 120, 400),
                                rng.integers(1, 120, 400)))
            ok(f"INSERT EDGE e(w) VALUES {edges}")
            ok("GO FROM 1 OVER e")              # build mirror
            rt = c.tpu_runtime
            sid = c.graph_meta_client.get_space_id_by_name("sp").value()
            m = rt.mirror(sid)
            et = c.schema_man.to_edge_type(sid, "e").value()
            # 48 queries x ~80 distinct starts ≈ 3.8k pairs: over the
            # 2048 ladder top, each query inside it -> split path
            starts = [rng.integers(1, 120, 80) for _ in range(48)]
            resolver = rt._launch_frontiers(
                sid, starts, (et,), 2, reduce=("count",))
            vals, mm = resolver()
            from nebula_tpu.tpu.runtime import _DeviceCounts
            assert isinstance(vals, _DeviceCounts)
            deg = rt._deg_host(mm, (et,))
            fwd = mm.edge_etype == et
            for q, st in enumerate(starts):
                vs = mm.to_dense(sorted({int(v) for v in st}))
                vs = vs[vs >= 0]
                hop1 = np.unique(
                    mm.edge_dst[np.isin(mm.edge_src, vs) & fwd])
                assert int(vals.arr[q]) == int(deg[hop1].sum()), q
        finally:
            c.stop()

    def test_reduction_respects_where_and_distinct_gates(self):
        """Shapes the reduction may NOT push (WHERE / DISTINCT / prop
        YIELD) still serve exact pipe semantics via full rows."""
        (ccpu, cpu, _okc), (ctpu, tpu, _okt) = self._boot_pair()
        try:
            for q in ("GO 2 STEPS FROM 1 OVER e WHERE e.w > 1 "
                      "YIELD e._dst AS d | YIELD COUNT(*)",
                      "GO FROM 1 OVER e YIELD DISTINCT e._dst AS d "
                      "| YIELD COUNT(*)",
                      "GO FROM 1 OVER e YIELD e.w AS w | YIELD COUNT(*)",
                      "GO 2 STEPS FROM 1 OVER e WHERE e.w > 0 "
                      "YIELD e._dst AS d | LIMIT 2"):
                a, b = cpu.execute(q), tpu.execute(q)
                assert a.ok() and b.ok(), (q, a.error_msg, b.error_msg)
                if "COUNT" in q:
                    assert sorted(map(tuple, a.rows)) == \
                        sorted(map(tuple, b.rows)), q
                else:
                    assert len(a.rows) == len(b.rows), q
        finally:
            ccpu.stop()
            ctpu.stop()


class TestShardedPackedParity:
    """The mesh families' frontiers are bit-packed ONLY as of nebulint
    v4 (KernelSpec.packed on ell_go_sharded/ell_bfs_sharded fails lint
    on an int8 regression); these differentials prove the packed
    sharded kernels bit-exact against BOTH the int8 single-chip oracle
    and the packed single-chip kernel, at every audited mesh size."""

    @staticmethod
    def _mesh(k):
        import jax
        from jax.sharding import Mesh
        devs = jax.devices()
        assert len(devs) >= k, devs
        return Mesh(np.array(devs[:k]), ("parts",))

    @pytest.mark.parametrize("hub", [False, True])
    @pytest.mark.parametrize("k", [2, 4, 8])
    def test_sharded_go_matches_int8_and_packed(self, hub, k):
        import jax.numpy as jnp
        ix, *_rest, rng = _graph(21 + k, 150, 900, hub)
        B, steps = 128, 3
        f0 = ix.start_frontier(_starts(rng, ix.n, B), B=B)
        ref = np.asarray(E.make_batched_go_kernel(ix, steps, ETYPES)(
            jnp.asarray(f0), *ix.kernel_args()))
        eslot, hrows = (jnp.asarray(a) for a in ix.hub_merge())
        packed1 = np.asarray(E.make_batched_go_lanes_kernel(
            ix, steps, ETYPES)(
            jnp.asarray(E.pack_lanes_host(f0)), eslot, hrows,
            *ix.kernel_args()[1:]))
        mesh = self._mesh(k)
        nbrs, ets, reals = E.shard_ell(mesh, "parts", ix)
        go = E.make_sharded_batched_go_kernel(
            mesh, "parts", ix, steps, ETYPES, nbrs, ets, reals)
        out = np.asarray(go(jnp.asarray(E.pack_lanes_host(f0)),
                            eslot, hrows, *nbrs, *ets))
        bits = E.unpack_lanes_host(out, B)
        # vs the int8 oracle (real rows; extras may hold junk in both)
        assert (bits[:ix.n] == (ref[:ix.n] > 0)).all()
        # vs the single-chip packed kernel: bit-exact including extras
        assert (bits[:ix.n]
                == E.unpack_lanes_host(packed1, B)[:ix.n]).all()

    @pytest.mark.parametrize("shortest", [True, False])
    @pytest.mark.parametrize("k", [2, 4, 8])
    def test_sharded_bfs_matches_int8(self, shortest, k):
        import jax.numpy as jnp
        ix, *_rest, rng = _graph(31 + k, 140, 800, True)
        B, max_steps = 64, 6
        f0 = ix.start_frontier(_starts(rng, ix.n, B), B=B)
        t0 = ix.start_frontier(
            [rng.integers(0, ix.n, 2) for _ in range(B)], B=B)
        ref = np.asarray(E.make_batched_bfs_kernel(
            ix, max_steps, ETYPES, stop_when_found=shortest)(
            jnp.asarray(f0), jnp.asarray(t0), *ix.kernel_args()))
        eslot, hrows = (jnp.asarray(a) for a in ix.hub_merge())
        mesh = self._mesh(k)
        nbrs, ets, reals = E.shard_ell(mesh, "parts", ix)
        bfs = E.make_sharded_batched_bfs_kernel(
            mesh, "parts", ix, max_steps, ETYPES, nbrs, ets, reals,
            stop_when_found=shortest)
        d = np.asarray(bfs(jnp.asarray(E.pack_lanes_host(f0)),
                           jnp.asarray(E.pack_lanes_host(t0)),
                           eslot, hrows, *nbrs, *ets))
        np.testing.assert_array_equal(d, ref)

    def test_sharded_donation_consumes_frontier(self):
        """donate=True (the runtime's dispatch configuration) must
        survive shard_map — the donated packed frontier is consumed."""
        import jax
        import jax.numpy as jnp
        ix, *_rest, rng = _graph(41, 100, 500, False)
        B = 64
        f0 = ix.start_frontier(_starts(rng, ix.n, B), B=B)
        mesh = self._mesh(2)
        nbrs, ets, reals = E.shard_ell(mesh, "parts", ix)
        go = E.make_sharded_batched_go_kernel(
            mesh, "parts", ix, 3, ETYPES, nbrs, ets, reals,
            donate=True)
        eslot, hrows = (jnp.asarray(a) for a in ix.hub_merge())
        f0p = jnp.asarray(E.pack_lanes_host(f0))
        out = go(f0p, eslot, hrows, *nbrs, *ets)
        jax.block_until_ready(out)
        assert f0p.is_deleted(), \
            "donated sharded frontier must be consumed"

    def test_runtime_mesh_go_serves_packed(self):
        """The runtime's replicated-frontier mesh branch now uploads
        packed and dispatches the packed sharded kernel — rows must
        match the single-device layout AND the CPU loop, and the
        sharded kernel must actually run."""
        from nebula_tpu.cluster import LocalCluster
        from nebula_tpu.common.flags import flags
        c = LocalCluster(num_storage=1, tpu_backend=True)
        cl = c.client()
        try:
            def ok(stmt):
                r = cl.execute(stmt)
                assert r.ok(), f"{stmt}: {r.error_msg}"
                return r

            ok("CREATE SPACE mp(partition_num=3, replica_factor=1)")
            c.refresh_all()
            ok("USE mp; CREATE EDGE e(w int)")
            c.refresh_all()
            rng = np.random.default_rng(6)
            edges = ", ".join(
                f"{int(s)} -> {int(d)}:({int(s) % 5})"
                for s, d in zip(rng.integers(1, 80, 400),
                                rng.integers(1, 80, 400)))
            ok(f"INSERT EDGE e(w) VALUES {edges}")
            qs = ["GO 3 STEPS FROM 1,2,3 OVER e YIELD e._dst, e.w",
                  "GO 2 STEPS FROM 5,9 OVER e REVERSELY"]
            base = [sorted(map(tuple, ok(q).rows)) for q in qs]
            rt = c.tpu_runtime
            flags.set("tpu_mesh_devices", 8)
            flags.set("tpu_mesh_mode", "dense")
            try:
                rt.mirrors.clear()      # rebuild under the mesh gate
                got = [sorted(map(tuple, ok(q).rows)) for q in qs]
            finally:
                flags.set("tpu_mesh_devices", 0)
                flags.set("tpu_mesh_mode", "sparse")
                rt.mirrors.clear()
            assert got == base
            flags.set("storage_backend", "cpu")
            try:
                cpu = [sorted(map(tuple, ok(q).rows)) for q in qs]
            finally:
                flags.set("storage_backend", "tpu")
            assert got == cpu
        finally:
            c.stop()

    def test_sharded_hub_merge_at_shard_boundaries(self):
        """Regression for the scatter-SET partitioning corruption: the
        hub OR-merge must run on the RE-REPLICATED frontier — applied
        to the row-sharded intermediate, the SPMD partitioner clamped
        the out-of-range hub index onto every shard's last row
        (rows k*chunk-1 flipped bits at the LDBC driver shape).  This
        pins the exact failing configuration: heavy-tailed graph,
        default cap, B=512, 4 hops, 8-way mesh."""
        import jax.numpy as jnp
        from nebula_tpu.tools.ldbc_gen import generate
        persons, B, steps = 400, 512, 4
        src, dst, _props = generate(persons)
        src = np.asarray(src, np.int32) - 1
        dst = np.asarray(dst, np.int32) - 1
        es = np.concatenate([src, dst])
        ed = np.concatenate([dst, src])
        ee = np.concatenate([np.ones(len(src), np.int32),
                             -np.ones(len(src), np.int32)])
        ix = E.EllIndex.build(es, ed, ee, persons)
        assert len(ix.extra_owner), "shape must exercise the hub merge"
        rng = np.random.default_rng(1)
        f0 = ix.start_frontier(
            [rng.integers(0, persons, 1, np.int32) for _ in range(B)],
            B=B)
        ref = np.asarray(E.make_batched_go_kernel(ix, steps, (1,))(
            jnp.asarray(f0), *ix.kernel_args()))
        eslot, hrows = (jnp.asarray(a) for a in ix.hub_merge())
        mesh = self._mesh(8)
        nbrs, ets, reals = E.shard_ell(mesh, "parts", ix)
        go = E.make_sharded_batched_go_kernel(
            mesh, "parts", ix, steps, (1,), nbrs, ets, reals)
        out = np.asarray(go(jnp.asarray(E.pack_lanes_host(f0)),
                            eslot, hrows, *nbrs, *ets))
        bits = E.unpack_lanes_host(out, B)
        assert (bits[:ix.n] == (ref[:ix.n] > 0)).all()
