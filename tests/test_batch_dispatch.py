"""GoBatchDispatcher — concurrent GO queries must coalesce into fewer
device dispatches while returning exactly the per-query results.
(The reference has no cross-query batching; the parity oracle is the
CPU executor path on an identical cluster, as in test_tpu_backend.)"""
import threading

import numpy as np
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.flags import flags


@pytest.fixture
def nba():
    # this suite exercises the WINDOWED pipeline's internals (leader
    # election, coalescing, pooling windows); the continuous seat-map
    # tier has its own suite (test_continuous.py)
    flags.set("go_dispatch_mode", "windowed")
    c = LocalCluster(num_storage=1, tpu_backend=True)
    g = c.client()

    def ok(stmt):
        r = g.execute(stmt)
        assert r.ok(), f"{stmt}: {r.error_msg}"
        return r

    ok("CREATE SPACE s(partition_num=3, replica_factor=1)")
    c.refresh_all()
    ok("USE s")
    ok("CREATE EDGE follow(w int)")
    c.refresh_all()
    ok("INSERT EDGE follow(w) VALUES 1->2:(1), 2->3:(1), 3->4:(1), "
       "4->5:(1), 1->6:(1), 6->7:(1), 2->7:(1)")
    yield c, ok
    c.stop()
    flags.set("go_batch_window_ms", 0)
    flags.set("go_dispatch_mode", "continuous")


def test_unfiltered_go_uses_dispatcher(nba):
    c, ok = nba
    r = ok("GO 2 STEPS FROM 1 OVER follow YIELD follow._dst")
    assert sorted(x[0] for x in r.rows) == [3, 7, 7]
    d = c.tpu_runtime.dispatcher
    assert d.stats["batches"] >= 1
    assert d.stats["batched_queries"] >= 1


def test_concurrent_queries_coalesce(nba):
    c, ok = nba
    ok("GO 1 STEPS FROM 1 OVER follow")     # warm mirror + kernel cache
    d = c.tpu_runtime.dispatcher
    flags.set("go_batch_window_ms", 120)    # force a coalescing window

    results = {}
    errors = []

    def worker(vid):
        try:
            g2 = c.client()
            g2.execute("USE s")
            r = g2.execute(f"GO 2 STEPS FROM {vid} OVER follow "
                           f"YIELD follow._dst")
            assert r.ok(), r.error_msg
            results[vid] = sorted(x[0] for x in r.rows)
        except Exception as ex:             # noqa: BLE001
            errors.append(ex)

    before = d.stats["batches"]
    threads = [threading.Thread(target=worker, args=(v,))
               for v in (1, 2, 1, 6, 2, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    flags.set("go_batch_window_ms", 0)

    assert not errors, errors
    assert results[1] == [3, 7, 7]
    assert results[2] == [4]                # 2->3->4 (and 2->7->nothing)
    assert results[6] == []                 # 6->7-> nothing
    batches = d.stats["batches"] - before
    assert batches < 6, f"no coalescing: {batches} batches for 6 queries"
    assert d.stats["max_batch"] >= 2


def test_dispatcher_parity_with_cpu_path(nba):
    c, ok = nba
    r_tpu = ok("GO 3 STEPS FROM 1 OVER follow YIELD follow._dst")
    flags.set("storage_backend", "cpu")
    try:
        r_cpu = ok("GO 3 STEPS FROM 1 OVER follow YIELD follow._dst")
    finally:
        flags.set("storage_backend", "tpu")
    assert sorted(map(tuple, r_tpu.rows)) == sorted(map(tuple, r_cpu.rows))


def test_dispatcher_error_propagates():
    """A failing batch launch must wake every waiter with the error."""
    class Boom(RuntimeError):
        pass

    class FakeRuntime:
        def go_batch_execute(self, *a):
            raise Boom("device fell over")

    from nebula_tpu.graph.batch_dispatch import GoBatchDispatcher
    d = GoBatchDispatcher(FakeRuntime())
    with pytest.raises(Boom):
        d.submit_batched(("go_batch_execute", 1, (1,), 2), [1])
    assert d.stats["batches"] == 1


def test_concurrent_find_path_coalesce(nba):
    """Concurrent same-shaped FIND PATH queries must coalesce into one
    BFS dispatch (submit_batched generalization), with exact per-query
    paths."""
    c, ok = nba
    ok("FIND SHORTEST PATH FROM 1 TO 4 OVER follow")   # warm kernel
    d = c.tpu_runtime.dispatcher
    flags.set("go_batch_window_ms", 120)
    results = {}
    errors = []

    # session setup (connect + USE) staggers threads by whole RPC round
    # trips on a loaded box — the barrier makes the four FIND PATH
    # statements actually CONCURRENT, which is the property under test
    gate = threading.Barrier(4)

    def worker(src, dst):
        try:
            g2 = c.client()
            g2.execute("USE s")
            gate.wait(timeout=10)
            r = g2.execute(f"FIND SHORTEST PATH FROM {src} TO {dst} "
                           f"OVER follow")
            assert r.ok(), r.error_msg
            results[(src, dst)] = sorted(x[0] for x in r.rows)
        except Exception as ex:            # noqa: BLE001
            errors.append(ex)

    before = d.stats["batches"]
    pairs = [(1, 4), (2, 5), (1, 7), (6, 7)]
    ts = [threading.Thread(target=worker, args=p) for p in pairs]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    flags.set("go_batch_window_ms", 0)
    assert not errors, errors
    assert results[(1, 4)] == ["1 <follow,0> 2 <follow,0> 3 <follow,0> 4"]
    assert results[(2, 5)] == ["2 <follow,0> 3 <follow,0> 4 <follow,0> 5"]
    assert results[(6, 7)] == ["6 <follow,0> 7"]
    assert results[(1, 7)]                      # 1->2->7 and/or 1->6->7
    batches = d.stats["batches"] - before
    assert batches < 4, f"no coalescing: {batches} for 4 path queries"


def test_per_query_error_isolation():
    """A poisoned query must fail ALONE; its 50 batch-mates succeed
    (VERDICT round-2 weak #5; reference semantics are per-request
    partial failure — StorageClient.h:22-72).  Also exercises the
    two-phase _Pending path: launch releases leadership, finish maps
    per-query results."""
    from nebula_tpu.graph.batch_dispatch import GoBatchDispatcher

    class Bad(RuntimeError):
        pass

    class _P:
        def __init__(self, fn):
            self.finish = fn

    class FakeRuntime:
        def exec_batch(self, space_id, payloads):
            def finish():
                return [Bad("poisoned") if p == "bad" else p * 2
                        for p in payloads], "mirror"
            return _P(finish)

    d = GoBatchDispatcher(FakeRuntime())
    flags.set("go_batch_window_ms", 80)
    outs, errs = {}, {}

    def worker(i, payload):
        try:
            r, m = d.submit_batched(("exec_batch", 1), payload)
            outs[i] = (r, m)
        except Bad as e:
            errs[i] = e

    try:
        ts = [threading.Thread(target=worker,
                               args=(i, "bad" if i == 3 else i))
              for i in range(51)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        flags.set("go_batch_window_ms", 0)
    assert list(errs) == [3], f"wrong failures: {sorted(errs)}"
    assert len(outs) == 50
    assert outs[5] == (10, "mirror")
    assert d.stats["query_errors"] >= 1


def test_leader_section_failure_resets_dispatching():
    """An exception between taking leadership and entering _run must
    hand leadership back — a stuck `dispatching` flag deadlocks every
    future request on the key (found via a mistyped window flag: the
    leader raised at `window > 0` and the dispatcher wedged forever)."""
    from nebula_tpu.graph.batch_dispatch import GoBatchDispatcher

    class FakeRuntime:
        def exec_batch(self, space_id, payloads):
            return [p for p in payloads], "m"

    d = GoBatchDispatcher(FakeRuntime())
    # simulate a corrupted flag value (flags.set coerces, so poke the
    # registry directly — an early define() with the wrong type did
    # exactly this in the wild)
    flags._flags["go_batch_window_ms"].value = "boom"
    try:
        with pytest.raises(ValueError):
            d.submit_batched(("exec_batch", 1), 7)
    finally:
        flags._flags["go_batch_window_ms"].value = 0
    # the key must still be serviceable
    r, m = d.submit_batched(("exec_batch", 1), 9)
    assert (r, m) == (9, "m")


def test_adaptive_window_scales_with_roundtrip():
    """go_batch_window_ms=-1 (default): the pooling window tracks
    go_batch_window_frac of the key's EMA batch round-trip, capped at
    go_batch_window_max_ms — so a ~100 ms-RTT device link pools wide
    batches while a local chip's ~ms round-trips cost ~no wait.  A key
    with no completed batch yet must never sleep on a guess."""
    from nebula_tpu.graph.batch_dispatch import GoBatchDispatcher, _KeyState

    d = GoBatchDispatcher(runtime=None)
    st = _KeyState()
    prev = flags.get("go_batch_window_ms")
    try:
        flags.set("go_batch_window_ms", -1)
        assert d._window_s(st.rt_ema_s) == 0.0            # no sample yet
        st.rt_ema_s = 0.2                        # 200 ms round trips
        frac = float(flags.get("go_batch_window_frac"))
        assert abs(d._window_s(st.rt_ema_s) - 0.2 * frac) < 1e-9
        st.rt_ema_s = 30.0                       # compile outlier
        cap = float(flags.get("go_batch_window_max_ms")) / 1000.0
        assert d._window_s(st.rt_ema_s) == cap            # capped
        flags.set("go_batch_window_ms", 7)       # fixed override wins
        assert abs(d._window_s(st.rt_ema_s) - 0.007) < 1e-9
        flags.set("go_batch_window_ms", 0)       # immediate mode
        assert d._window_s(st.rt_ema_s) == 0.0
    finally:
        flags.set("go_batch_window_ms", prev)


def test_adaptive_window_ema_updates_from_batches():
    """Completed batches feed the key's round-trip EMA (launch ->
    results materialized), including two-phase _Pending results; a
    regime change re-centers the EMA within a few batches."""
    import time as _time

    from nebula_tpu.graph.batch_dispatch import GoBatchDispatcher

    class FakeRuntime:
        def exec_batch(self, space_id, payloads):
            _time.sleep(0.05)
            return [p for p in payloads], "m"

    d = GoBatchDispatcher(FakeRuntime())
    key = ("exec_batch", 1)
    prev = flags.get("go_batch_window_ms")
    try:
        flags.set("go_batch_window_ms", -1)
        d.submit_batched(key, 1)
        st = d._state(key)
        first = st.rt_ema_s
        assert first >= 0.05
        for _ in range(3):
            d.submit_batched(key, 2)
        assert st.rt_ema_s >= 0.05              # stays in regime
        # the observed window stays proportional and bounded
        w = d._window_s(st.rt_ema_s)
        frac = float(flags.get("go_batch_window_frac"))
        cap = float(flags.get("go_batch_window_max_ms")) / 1000.0
        assert w <= cap and w <= st.rt_ema_s * frac + 1e-9
    finally:
        flags.set("go_batch_window_ms", prev)


def test_adaptive_window_skips_lone_requests_and_honors_zero_caps():
    """A lone request on an idle key must dispatch immediately even
    with a warm high-RTT EMA (nothing to pool with), and an operator's
    EXPLICIT go_batch_window_max_ms=0 / go_batch_window_frac=0 must not
    be silently replaced by defaults."""
    import time as _time

    from nebula_tpu.graph.batch_dispatch import GoBatchDispatcher, _KeyState

    class FakeRuntime:
        def exec_batch(self, space_id, payloads):
            return [p for p in payloads], "m"

    d = GoBatchDispatcher(FakeRuntime())
    key = ("exec_batch", 1)
    prev = flags.get("go_batch_window_ms")
    try:
        flags.set("go_batch_window_ms", -1)
        st = d._state(key)
        st.rt_ema_s = 1.0                       # warm, high-RTT regime
        t0 = _time.perf_counter()
        r, _ = d.submit_batched(key, 5)         # lone request
        solo_ms = (_time.perf_counter() - t0) * 1000
        assert r == 5
        assert solo_ms < 25, f"lone request paid the window: {solo_ms}ms"
        # explicit zeros are respected, not defaulted away
        st2 = _KeyState()
        st2.rt_ema_s = 1.0
        prev_cap = flags.get("go_batch_window_max_ms")
        prev_frac = flags.get("go_batch_window_frac")
        flags.set("go_batch_window_max_ms", 0)
        assert d._window_s(st2.rt_ema_s) == 0.0
        flags.set("go_batch_window_max_ms", prev_cap)
        flags.set("go_batch_window_frac", 0)
        assert d._window_s(st2.rt_ema_s) == 0.0
        flags.set("go_batch_window_frac", prev_frac)
    finally:
        flags.set("go_batch_window_ms", prev)
