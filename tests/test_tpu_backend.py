"""TPU traversal backend tests.

Two tiers, mirroring SURVEY.md §4's pyramid:
  * kernel/CSR units — build_mirror over a hand-rolled store, jitted GO /
    BFS kernels on a known graph, sharded (8-virtual-device) GO kernel
    equivalence against the single-device kernel;
  * end-to-end parity — the SAME nGQL queries against two LocalClusters
    (CPU backend vs TPU backend) must return identical row sets, and the
    TPU cluster's runtime stats must prove the device path actually ran.
"""
import numpy as np
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.tpu import kernels

TIM, TONY, MANU, LEBRON, KYRIE = 100, 101, 102, 103, 104
SPURS, CAVS = 200, 201

FIXTURE = [
    "CREATE TAG player(name string, age int)",
    "CREATE TAG team(name string)",
    "CREATE EDGE follow(degree int)",
    "CREATE EDGE serve(start_year int, end_year int)",
]
DATA = [
    'INSERT VERTEX player(name, age) VALUES '
    f'{TIM}:("Tim Duncan", 42), {TONY}:("Tony Parker", 36), '
    f'{MANU}:("Manu Ginobili", 41), {LEBRON}:("LeBron James", 34), '
    f'{KYRIE}:("Kyrie Irving", 26)',
    f'INSERT VERTEX team(name) VALUES {SPURS}:("Spurs"), {CAVS}:("Cavaliers")',
    'INSERT EDGE follow(degree) VALUES '
    f'{TIM} -> {TONY}:(95), {TIM} -> {MANU}:(95), '
    f'{TONY} -> {TIM}:(95), {TONY} -> {MANU}:(90), '
    f'{MANU} -> {TIM}:(90), {LEBRON} -> {KYRIE}:(80), '
    f'{KYRIE} -> {LEBRON}:(85)',
    'INSERT EDGE serve(start_year, end_year) VALUES '
    f'{TIM} -> {SPURS}:(1997, 2016), {TONY} -> {SPURS}:(1999, 2018), '
    f'{MANU} -> {SPURS}:(2002, 2018), {LEBRON} -> {CAVS}:(2003, 2010), '
    f'{KYRIE} -> {CAVS}:(2011, 2017)',
]


def _boot(tpu_backend: bool):
    c = LocalCluster(num_storage=1, tpu_backend=tpu_backend)
    client = c.client()

    def ok(stmt):
        resp = client.execute(stmt)
        assert resp.ok(), f"{stmt}: {resp.error_msg}"
        return resp

    client.ok = ok
    ok("CREATE SPACE nba(partition_num=6, replica_factor=1)")
    c.refresh_all()
    ok("USE nba")
    for stmt in FIXTURE:
        ok(stmt)
    c.refresh_all()
    for stmt in DATA:
        ok(stmt)
    return c, client


@pytest.fixture(scope="module")
def clusters():
    cpu_c, cpu = _boot(tpu_backend=False)
    tpu_c, tpu = _boot(tpu_backend=True)
    yield cpu_c, cpu, tpu_c, tpu
    cpu.disconnect()
    tpu.disconnect()
    cpu_c.stop()
    tpu_c.stop()


PARITY_QUERIES = [
    f"GO FROM {TIM} OVER follow",
    f"GO FROM {TIM} OVER follow YIELD follow._dst AS id, follow.degree AS d,"
    f" $^.player.name AS me",
    f"GO FROM {TIM} OVER follow YIELD $$.player.name AS n, $$.player.age AS a",
    f"GO 2 STEPS FROM {TIM} OVER follow",
    f"GO 3 STEPS FROM {TIM} OVER follow",
    f"GO FROM {TONY} OVER follow WHERE follow.degree > 92 YIELD follow._dst",
    # numeric (non-bool) WHERE: nonzero = truthy, and the host-filter
    # mask must be bool before it fancy-indexes candidate edges
    f"GO 2 STEPS FROM {TIM} OVER follow WHERE follow.degree "
    f"YIELD follow._dst",
    f"GO FROM {TIM},{TONY} OVER follow WHERE $^.player.age > 40 "
    f"YIELD follow._dst",
    f"GO FROM {TIM} OVER follow WHERE $$.player.age > 40 YIELD follow._dst",
    f"GO FROM {MANU} OVER follow REVERSELY",
    f"GO FROM {TIM} OVER follow, serve",
    f"GO FROM {TIM} OVER follow, serve YIELD follow._dst AS d",
    f"GO FROM {TIM} OVER follow YIELD follow._dst, follow._src, "
    f"follow._rank, follow._type",
    f"GO 2 STEPS FROM {TIM} OVER follow YIELD follow._dst AS id, "
    f"follow.degree AS deg",
    f"GO FROM {TIM} OVER follow WHERE follow.degree > 90 && "
    f"$$.player.age > 40 YIELD follow._dst, follow.degree",
    f"GO FROM {TIM} OVER follow YIELD follow._dst AS id | "
    f"GO FROM $-.id OVER follow",
    f"GO FROM {TONY} OVER follow YIELD DISTINCT follow._dst",
    f"GO FROM {TIM} OVER follow WHERE $$.player.name == \"Tony Parker\" "
    f"YIELD follow._dst, $$.player.name",
    f"GO FROM {TIM} OVER follow WHERE follow._dst == {TONY} "
    f"YIELD follow._dst",
    f"GO FROM {TIM} OVER follow YIELD follow.degree + 1 AS dd",
    f"GO FROM {TIM} OVER follow YIELD $^.player.age / 2 AS h",
    # UPTO rides the cumulative-frontier kernel variants on device
    f"GO UPTO 2 STEPS FROM {TIM} OVER follow",
    f"GO UPTO 3 STEPS FROM {TIM} OVER follow YIELD follow._dst, "
    f"follow.degree",
    f"GO UPTO 2 STEPS FROM {TIM} OVER follow WHERE follow.degree > 90 "
    f"YIELD follow._dst",
    f"FIND SHORTEST PATH FROM {TIM} TO {MANU} OVER follow",
    f"FIND SHORTEST PATH FROM {LEBRON} TO {CAVS} OVER * UPTO 3 STEPS",
    f"FIND SHORTEST PATH FROM {TIM} TO {CAVS} OVER follow",
    f"FIND ALL PATH FROM {TONY} TO {MANU} OVER follow UPTO 2 STEPS",
    f"FIND SHORTEST PATH FROM {TONY} TO {TIM},{SPURS} OVER * UPTO 3 STEPS",
]


class TestParity:
    @pytest.mark.parametrize("query", PARITY_QUERIES)
    def test_same_rows(self, clusters, query):
        _, cpu, _, tpu = clusters
        r_cpu = cpu.execute(query)
        r_tpu = tpu.execute(query)
        assert r_cpu.ok() and r_tpu.ok(), \
            f"{query}: cpu={r_cpu.error_msg} tpu={r_tpu.error_msg}"
        assert r_cpu.column_names == r_tpu.column_names
        assert sorted(map(tuple, r_cpu.rows)) == \
            sorted(map(tuple, r_tpu.rows)), query

    def test_device_path_actually_ran(self, clusters):
        _, _, tpu_c, tpu = clusters
        rt = tpu_c.tpu_runtime
        assert rt is not None
        before = rt.stats["go_device"]
        tpu.execute(f"GO FROM {TIM} OVER follow")
        assert rt.stats["go_device"] == before + 1
        before_p = rt.stats["path_device"]
        tpu.execute(f"FIND SHORTEST PATH FROM {TIM} TO {MANU} OVER follow")
        assert rt.stats["path_device"] == before_p + 1

    def test_error_parity_missing_prop(self, clusters):
        # yielding a prop of a tag the dst doesn't carry errors both ways
        _, cpu, _, tpu = clusters
        q = f"GO FROM {TIM} OVER serve YIELD $$.player.name"
        r_cpu = cpu.execute(q)
        r_tpu = tpu.execute(q)
        assert not r_cpu.ok() and not r_tpu.ok()

    def test_div_zero_pushed_filter_parity(self, clusters):
        # a zero-degree edge: CPU pushed filter drops the row on the
        # ExprError; the device guard must drop it too — not emit inf>1
        _, cpu, _, tpu = clusters
        cpu.ok(f'INSERT EDGE follow(degree) VALUES {MANU} -> {TONY}:(0)')
        tpu.ok(f'INSERT EDGE follow(degree) VALUES {MANU} -> {TONY}:(0)')
        q = (f"GO FROM {MANU} OVER follow WHERE 10 / follow.degree >= 0 "
             f"YIELD follow._dst")
        r_cpu, r_tpu = cpu.execute(q), tpu.execute(q)
        assert r_cpu.ok() and r_tpu.ok()
        # 10/90 == 0 (C-style int division) passes >= 0; the degree-0 edge
        # errors on the CPU path and must be guard-dropped on device
        assert sorted(map(tuple, r_cpu.rows)) == \
            sorted(map(tuple, r_tpu.rows)) == [(TIM,)]
        cpu.ok(f"DELETE EDGE follow {MANU} -> {TONY}")
        tpu.ok(f"DELETE EDGE follow {MANU} -> {TONY}")

    def test_ttl_expired_edges_dropped(self):
        # expired rows are skipped by the CPU read path; the mirror must
        # drop them too.  The clock is INJECTED (clock.advance_for_tests)
        # — racing a 1-second TTL against a busy box made this flake
        # (VERDICT round-2 weak #6)
        import time as _t
        from nebula_tpu.common import clock
        c, client = _boot(tpu_backend=True)
        try:
            client.ok("CREATE EDGE seen(ts timestamp) "
                      "ttl_duration = 3600, ttl_col = ts")
            c.refresh_all()
            now = int(_t.time())
            client.ok(f'INSERT EDGE seen(ts) VALUES {TIM} -> {TONY}:({now}),'
                      f' {TIM} -> {MANU}:({now - 7200})')
            r = client.ok(f"GO FROM {TIM} OVER seen")
            assert sorted(map(tuple, r.rows)) == [(TONY,)], r.rows
        finally:
            clock.reset_for_tests()
            c.stop()

    def test_ttl_expiry_boundary_parity(self):
        """Edges aging out BETWEEN queries must disappear from the
        device path in lockstep with the CPU path — the mirror records
        the earliest future expiry and rebuilds once it passes
        (expired_now), so a snapshot never outlives its rows."""
        import time as _t
        from nebula_tpu.common import clock
        from nebula_tpu.common.flags import flags
        c, client = _boot(tpu_backend=True)
        try:
            client.ok("CREATE EDGE lease(ts timestamp) "
                      "ttl_duration = 3600, ttl_col = ts")
            c.refresh_all()
            now = int(_t.time())
            # expiries now+1800 and now+5400
            client.ok(f'INSERT EDGE lease(ts) VALUES '
                      f'{TIM} -> {TONY}:({now - 1800}), '
                      f'{TIM} -> {MANU}:({now + 1800})')

            def both_paths(q):
                r1 = client.ok(q)
                flags.set("storage_backend", "cpu")
                try:
                    r2 = client.ok(q)
                finally:
                    flags.set("storage_backend", "tpu")
                a = sorted(map(tuple, r1.rows))
                assert a == sorted(map(tuple, r2.rows))
                return a

            q = f"GO FROM {TIM} OVER lease"
            assert both_paths(q) == [(TONY,), (MANU,)]
            clock.advance_for_tests(3600)      # past the first expiry
            assert both_paths(q) == [(MANU,)]
            clock.advance_for_tests(3600)      # past the second
            assert both_paths(q) == []
        finally:
            clock.reset_for_tests()
            c.stop()

    def test_mutation_invalidates_mirror(self, clusters):
        _, _, tpu_c, tpu = clusters
        rt = tpu_c.tpu_runtime
        r = tpu.ok(f"GO FROM {KYRIE} OVER follow")
        assert sorted(map(tuple, r.rows)) == [(LEBRON,)]
        tpu.ok(f'INSERT EDGE follow(degree) VALUES {KYRIE} -> {TIM}:(70)')
        r = tpu.ok(f"GO FROM {KYRIE} OVER follow")
        assert sorted(map(tuple, r.rows)) == [(TIM,), (LEBRON,)]
        # cleanup for other tests
        tpu.ok(f"DELETE EDGE follow {KYRIE} -> {TIM}")


class TestGenerativeWhereDifferential:
    """Generative CPU-vs-device WHERE differential (VERDICT r5 ask #5,
    the tpu_filter_mode=auto default's safety net): seeded-random
    predicates composed from atoms covering int/float/string columns,
    src/dst vertex props MISSING on some vertices, TTL-expired rows,
    modulo and division with a zero divisor present — executed under
    every filter mode (host float64 / fused device / auto) and
    compared against the CPU backend: same rows, or the same error."""

    ATOMS = [
        "rel.i > {a}",
        "rel.i % 3 == {b}",
        "rel.f * 2.0 < {c}",
        "rel.f + rel.i >= {a}",
        'rel.s == "s{b}"',
        "10 / rel.i >= {b}",          # zero divisor present in data
        "rel._rank >= 0",
        "$^.player.age > {d}",
        "$$.player.age < {d}",        # missing on tagless vertices
        "rel.i",                      # numeric truthiness
    ]

    @pytest.fixture(scope="class")
    def gen_cluster(self):
        c, client = _boot(tpu_backend=True)
        client.ok("CREATE EDGE rel(i int, f double, s string)")
        client.ok("CREATE EDGE seen(ts timestamp, v int) "
                  "ttl_duration = 3600, ttl_col = ts")
        c.refresh_all()
        rng = np.random.default_rng(42)
        # vertices 1..30; players tagged only on 1..20 (dst-prop reads
        # on 21..30 are MISSING → skip in pushed mode, raise in graphd
        # mode — both paths must agree either way)
        players = ", ".join(f'{v}:("p{v}", {18 + v})'
                            for v in range(1, 21))
        client.ok(f"INSERT VERTEX player(name, age) VALUES {players}")
        edges = ", ".join(
            f"{int(s)} -> {int(d)}:"
            f"({int(i)}, {float(f):.3f}, \"s{int(i) % 4}\")"
            for s, d, i, f in zip(
                rng.integers(1, 31, 200), rng.integers(1, 31, 200),
                rng.integers(-2, 6, 200),       # zeros present
                rng.normal(0, 3, 200)))
        client.ok(f"INSERT EDGE rel(i, f, s) VALUES {edges}")
        import time as _t
        now = int(_t.time())
        seen = ", ".join(
            f"{int(s)} -> {int(d)}:"
            f"({now - (7200 if k % 3 == 0 else 0)}, {k})"
            for k, (s, d) in enumerate(zip(rng.integers(1, 31, 60),
                                           rng.integers(1, 31, 60))))
        client.ok(f"INSERT EDGE seen(ts, v) VALUES {seen}")
        yield c, client
        from nebula_tpu.common import clock
        clock.reset_for_tests()
        c.stop()

    def _queries(self):
        rng = np.random.default_rng(7)
        out = []
        for i in range(36):
            n = rng.integers(1, 4)
            atoms = [self.ATOMS[int(k)]
                     for k in rng.choice(len(self.ATOMS), n,
                                         replace=False)]
            op = " && " if rng.random() < 0.6 else " || "
            pred = op.join(
                a.format(a=int(rng.integers(-2, 5)),
                         b=int(rng.integers(0, 4)),
                         c=round(float(rng.normal(0, 4)), 2),
                         d=int(rng.integers(18, 50)))
                for a in atoms)
            steps = int(rng.integers(1, 4))
            start = ",".join(str(int(v))
                             for v in rng.integers(1, 31,
                                                   rng.integers(1, 4)))
            out.append(f"GO {steps} STEPS FROM {start} OVER rel "
                       f"WHERE {pred} YIELD rel._dst, rel.i, rel.f")
        # TTL leg: expired rows must be invisible to every mode
        for v in (1, 5, 9):
            out.append(f"GO FROM {v} OVER seen WHERE seen.v >= 0 "
                       f"YIELD seen._dst, seen.v")
        return out

    def test_not_over_conjunction_short_circuit(self, gen_cluster):
        """`!(a && missing)` keeps the row on the CPU path when a is
        false (the && short-circuits, ! flips it) — the validity mask
        can't reproduce that, so _filter_has_or must flag NOT over a
        logical subtree and the row must decline to the per-row path
        (review finding: pure-`&&` detection missed the `!` wrapper)."""
        from nebula_tpu.common.flags import flags
        _c, client = gen_cluster
        qs = [
            # dst prop missing on vertices 21..30 (graphd raise-mode)
            "GO 2 STEPS FROM 3 OVER rel WHERE "
            "!(rel.i > 99 && $$.player.age > 0) YIELD rel._dst, rel.i",
            # src prop missing (pushed skip-mode)
            "GO 2 STEPS FROM 3 OVER rel WHERE "
            "!(rel.i > 99 && $^.player.age > 0) YIELD rel._dst, rel.i",
        ]
        for q in qs:
            flags.set("storage_backend", "cpu")
            r = client.execute(q)
            want = ("error",) if not r.ok() else \
                tuple(sorted(map(tuple, r.rows)))
            flags.set("storage_backend", "tpu")
            for mode in ("host", "device", "auto"):
                flags.set("tpu_filter_mode", mode)
                try:
                    r2 = client.execute(q)
                finally:
                    flags.set("tpu_filter_mode", "auto")
                got = ("error",) if not r2.ok() else \
                    tuple(sorted(map(tuple, r2.rows)))
                assert got == want, (mode, q, want, got)

    def test_all_filter_modes_match_cpu(self, gen_cluster):
        from nebula_tpu.common.flags import flags
        _c, client = gen_cluster

        def run(q):
            r = client.execute(q)
            if not r.ok():
                return ("error",)
            return tuple(sorted(map(tuple, r.rows)))

        mismatches = []
        for q in self._queries():
            flags.set("storage_backend", "cpu")
            want = run(q)
            flags.set("storage_backend", "tpu")
            for mode in ("host", "device", "auto"):
                flags.set("tpu_filter_mode", mode)
                try:
                    got = run(q)
                finally:
                    flags.set("tpu_filter_mode", "auto")
                if got != want:
                    mismatches.append((mode, q, want, got))
        assert not mismatches, mismatches[:3]


class TestKernels:
    """Direct kernel units on a known small graph.

    Graph (dense ids): 0->1, 0->2, 1->3, 2->3, 3->4 all etype 1.
    """

    def _arrays(self):
        import jax.numpy as jnp
        es = jnp.asarray(np.array([0, 0, 1, 2, 3], dtype=np.int32))
        ed = jnp.asarray(np.array([1, 2, 3, 3, 4], dtype=np.int32))
        ee = jnp.asarray(np.ones(5, dtype=np.int32))
        return es, ed, ee

    def test_go_one_hop(self):
        import jax.numpy as jnp
        es, ed, ee = self._arrays()
        kern = kernels.make_go_kernel(5, 1, (1,))
        mask, frontier = kern(es, ed, ee,
                              jnp.asarray(np.array([0, -1], dtype=np.int32)))
        assert np.asarray(mask).tolist() == [True, True, False, False, False]

    def test_go_two_hops(self):
        import jax.numpy as jnp
        es, ed, ee = self._arrays()
        kern = kernels.make_go_kernel(5, 2, (1,))
        mask, frontier = kern(es, ed, ee,
                              jnp.asarray(np.array([0, -1], dtype=np.int32)))
        # hop1 frontier {1,2}; final edges: 1->3, 2->3
        assert np.asarray(mask).tolist() == [False, False, True, True, False]
        assert np.asarray(frontier).tolist() == [False, True, True, False,
                                                 False]

    def test_bfs_depth(self):
        import jax.numpy as jnp
        es, ed, ee = self._arrays()
        kern = kernels.make_bfs_kernel(5, 5, (1,), stop_when_found=False)
        d = kern(es, ed, ee, jnp.asarray(np.array([0], dtype=np.int32)),
                 jnp.asarray(np.array([4], dtype=np.int32)))
        assert np.asarray(d).tolist() == [0, 1, 1, 2, 3]

    def test_sharded_go_matches_single_device(self):
        import jax
        from jax.sharding import Mesh
        rng = np.random.RandomState(7)
        n, m = 64, 400
        es = rng.randint(0, n, m).astype(np.int32)
        ed = rng.randint(0, n, m).astype(np.int32)
        ee = rng.choice([1, 2], m).astype(np.int32)
        start = np.array([3, 11, -1, -1], dtype=np.int32)

        import jax.numpy as jnp
        single = kernels.make_go_kernel(n, 3, (1,))
        mask1, f1 = single(jnp.asarray(es), jnp.asarray(ed), jnp.asarray(ee),
                           jnp.asarray(start))

        devs = np.array(jax.devices())
        mesh = Mesh(devs, ("parts",))
        sharded = kernels.make_sharded_go_kernel(mesh, "parts", n, 3, (1,))
        s_es, s_ed, s_ee, padded = kernels.shard_edges(mesh, "parts", es, ed,
                                                       ee)
        f0 = kernels.bitmap_from_idx(jnp.asarray(start), n)
        mask8, f8 = sharded(s_es, s_ed, s_ee, f0)
        assert np.array_equal(np.asarray(f1), np.asarray(f8))
        assert np.array_equal(np.asarray(mask1),
                              np.asarray(mask8)[:m])


class TestFilterModeParity:
    """tpu_filter_mode=host (dispatcher + float64 host filter) and
    =device (WHERE fused into the XLA hop program) must produce
    identical rows for every WHERE-carrying parity query."""

    def test_same_rows_both_filter_modes(self, clusters):
        from nebula_tpu.common.flags import flags
        _, _, tpu_c, tpu = clusters
        where_queries = [q for q in PARITY_QUERIES if "WHERE" in q]
        assert where_queries
        host_rows = {}
        for q in where_queries:
            r = tpu.execute(q)
            assert r.ok(), f"{q}: {r.error_msg}"
            host_rows[q] = sorted(map(tuple, r.rows))
        flags.set("tpu_filter_mode", "device")
        try:
            for q in where_queries:
                r = tpu.execute(q)
                assert r.ok(), f"{q}: {r.error_msg}"
                assert sorted(map(tuple, r.rows)) == host_rows[q], q
        finally:
            flags.set("tpu_filter_mode", "host")


class TestFrontierEdges:
    """_frontier_edges (CSR row-slice final-hop candidate assembly) must
    equal the flat frontier[edge_src] gather in both density regimes —
    it replaces round 1's per-query O(m) host pass."""

    def _mirror(self, n, m, seed=0):
        from nebula_tpu.tpu.csr import CsrMirror
        rng = np.random.default_rng(seed)
        mir = CsrMirror(1)
        mir.n = n
        mir.m = m
        mir.vids = np.arange(n, dtype=np.int64)
        mir.edge_src = np.sort(rng.integers(0, n, m).astype(np.int32))
        mir.edge_dst = rng.integers(0, n, m).astype(np.int32)
        mir.edge_etype = rng.choice([1, 2], m).astype(np.int32)
        counts = np.bincount(mir.edge_src, minlength=n)
        mir.row_ptr = np.concatenate([[0], np.cumsum(counts)]).astype(
            np.int32)
        return mir

    @pytest.mark.parametrize("density", [0.0, 0.002, 0.05, 0.6, 1.0])
    @pytest.mark.parametrize("et_tuple", [(1,), (1, 2)])
    def test_matches_flat_gather(self, density, et_tuple):
        from nebula_tpu.tpu.runtime import TpuQueryRuntime
        n, m = 4096, 32768
        mir = self._mirror(n, m)
        rng = np.random.default_rng(1)
        frontier = np.zeros(n, dtype=bool)
        k = int(n * density)
        if k:
            frontier[rng.choice(n, k, replace=False)] = True
        flat = np.nonzero(
            frontier[mir.edge_src]
            & np.isin(mir.edge_etype, np.asarray(et_tuple, np.int32)))[0]
        got = TpuQueryRuntime._frontier_edges(
            TpuQueryRuntime.__new__(TpuQueryRuntime), mir,
            np.nonzero(frontier)[0], et_tuple)
        assert np.array_equal(got, flat)


class TestIncrementalDelta:
    """SURVEY §7 hard part (a): committed edge inserts ride a small
    overlay (delta kernel + overlay mirror) instead of forcing the
    O(m) CSR/ELL rebuild per mutation — results must track writes
    exactly, and the rebuild count must stay ~constant under a
    sustained INSERT+GO workload."""

    def _boot(self):
        from nebula_tpu.common.flags import flags
        flags.set("storage_backend", "tpu")
        c = LocalCluster(num_storage=1, tpu_backend=True)
        cl = c.client()

        def ok(s):
            r = cl.execute(s)
            assert r.ok(), f"{s}: {r.error_msg}"
            return r
        ok("CREATE SPACE inc(partition_num=4, replica_factor=1)")
        c.refresh_all()
        ok("USE inc")
        ok("CREATE TAG player(name string, age int)")
        ok("CREATE EDGE follow(degree int)")
        c.refresh_all()
        players = ", ".join(f'{100 + i}:("p{i}", {20 + i})'
                            for i in range(30))
        ok(f'INSERT VERTEX player(name, age) VALUES {players}')
        ok('INSERT EDGE follow(degree) VALUES '
           + ", ".join(f"{100 + i} -> {100 + (i + 1) % 30}:({50 + i})"
                       for i in range(30)))
        return c, cl, ok

    def test_insert_go_workload_tracks_writes_without_rebuilds(self):
        import random
        c, cl, ok = self._boot()
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow")        # build the base mirror
            builds0 = rt.stats["mirror_builds"]
            rng = random.Random(3)
            expected = {(100 + i, 100 + (i + 1) % 30, 50 + i)
                        for i in range(30)}
            for step in range(25):
                s = rng.randrange(0, 30)
                d = rng.randrange(0, 30)
                deg = 200 + step
                ok(f"INSERT EDGE follow(degree) VALUES "
                   f"{100 + s} -> {100 + d}@{1000 + step}:({deg})")
                expected.add((100 + s, 100 + d, deg))
                r = ok("GO FROM 100, 105, 110 OVER follow "
                       "YIELD follow._src, follow._dst, follow.degree")
                # parity vs the CPU executor path every few steps
                if step % 5 == 0:
                    from nebula_tpu.common.flags import flags
                    flags.set("storage_backend", "cpu")
                    r2 = ok("GO FROM 100, 105, 110 OVER follow "
                            "YIELD follow._src, follow._dst, "
                            "follow.degree")
                    flags.set("storage_backend", "tpu")
                    assert sorted(map(tuple, r.rows)) == \
                        sorted(map(tuple, r2.rows)), f"step {step}"
            # the whole workload rode the overlay: no rebuilds
            assert rt.stats["mirror_builds"] == builds0, \
                (builds0, rt.stats["mirror_builds"])
            assert rt.stats["mirror_deltas"] > 0
            # device path actually served
            assert rt.stats["go_device"] > 0
        finally:
            c.stop()

    def test_multi_hop_through_fresh_edges(self):
        """New edges must be traversable mid-path, not only at the
        final hop (the delta rides every kernel hop)."""
        c, cl, ok = self._boot()
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow")
            builds0 = rt.stats["mirror_builds"]
            # bridge: 100 -> 400-ish via two fresh edges... endpoints
            # must already exist, so bridge through existing vertices
            ok("INSERT EDGE follow(degree) VALUES 100 -> 115@7:(99)")
            ok("INSERT EDGE follow(degree) VALUES 115 -> 120@7:(98)")
            r = ok("GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            assert (120,) in set(map(tuple, r.rows))
            from nebula_tpu.common.flags import flags
            flags.set("storage_backend", "cpu")
            r2 = ok("GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            flags.set("storage_backend", "tpu")
            assert sorted(map(tuple, r.rows)) == sorted(map(tuple, r2.rows))
            assert rt.stats["mirror_builds"] == builds0
        finally:
            c.stop()

    def test_delete_absorbed_for_single_hop(self):
        """An edge delete rides the overlay as a base-row tombstone:
        1-hop queries keep serving from the mirror with NO rebuild and
        must not see the dead edge."""
        c, cl, ok = self._boot()
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow")
            ok("INSERT EDGE follow(degree) VALUES 100 -> 110@5:(77)")
            r = ok("GO FROM 100 OVER follow YIELD follow._dst")
            assert (110,) in set(map(tuple, r.rows))
            builds0 = rt.stats["mirror_builds"]
            ok("DELETE EDGE follow 100 -> 110@5")
            r = ok("GO FROM 100 OVER follow YIELD follow._dst")
            assert (110,) not in set(map(tuple, r.rows))
            assert rt.stats["mirror_builds"] == builds0, "tombstone " \
                "should absorb a 1-hop-only delete without a rebuild"
            # the pre-existing ring edge from 100 still serves
            assert (101,) in set(map(tuple, r.rows))
        finally:
            c.stop()

    def test_delete_with_multi_hop_stays_correct(self):
        """Reachability-changing deletes fold into the tables as
        tombstones at absorb time — multi-hop queries stay exact
        (the rebuild-free claim is pinned in tests/test_absorb.py)."""
        c, cl, ok = self._boot()
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow")
            ok("DELETE EDGE follow 101 -> 102@0")
            r = ok("GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            got = set(map(tuple, r.rows))
            assert (102,) not in got, "deleted mid-path edge traversed"
            from nebula_tpu.common.flags import flags
            flags.set("storage_backend", "cpu")
            r2 = ok("GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            flags.set("storage_backend", "tpu")
            assert sorted(map(tuple, r.rows)) == sorted(map(tuple,
                                                            r2.rows))
        finally:
            c.stop()

    def test_update_absorbed_without_rebuild(self):
        """An in-place UPDATE (same edge identity, new props) rides the
        overlay as override rows — multi-hop safe (same dst), fresh
        props visible, no rebuild."""
        c, cl, ok = self._boot()
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow")
            builds0 = rt.stats["mirror_builds"]
            ok("INSERT EDGE follow(degree) VALUES 100 -> 101:(999)")
            r = ok("GO FROM 100 OVER follow "
                   "YIELD follow._dst, follow.degree")
            got = set(map(tuple, r.rows))
            assert (101, 999) in got, got
            assert (101, 50) not in got, "stale pre-update row served"
            # multi-hop still serves from the mirror (dst unchanged)
            r = ok("GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            assert (102,) in set(map(tuple, r.rows))
            assert rt.stats["mirror_builds"] == builds0, \
                "updates must absorb without a rebuild"
            # parity with the CPU path
            from nebula_tpu.common.flags import flags
            flags.set("storage_backend", "cpu")
            r2 = ok("GO FROM 100 OVER follow "
                    "YIELD follow._dst, follow.degree")
            flags.set("storage_backend", "tpu")
            r3 = ok("GO FROM 100 OVER follow "
                    "YIELD follow._dst, follow.degree")
            assert sorted(map(tuple, r3.rows)) == sorted(map(tuple,
                                                             r2.rows))
        finally:
            c.stop()

    def test_new_vertex_insert_absorbs_known_dst_rebuilds_extra_vid(self):
        """An edge to a KNOWN vertex absorbs into the tables (the dst
        row exists — no rebuild); an edge to a vid with NO vertex
        record grows the dense-id space, which only the rebuild can
        serve — and that decline must be OBSERVABLE (mirror_absorb_
        failed + the vertex-plan-change reason), never silent
        (docs/durability.md decision table)."""
        c, cl, ok = self._boot()
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow")
            ok('INSERT VERTEX player(name, age) VALUES 500:("new", 1)')
            # vertex-only write is opaque (rebuild) — anchor the count
            ok("GO FROM 100 OVER follow")
            builds1 = rt.stats["mirror_builds"]
            ok("INSERT EDGE follow(degree) VALUES 100 -> 500:(42)")
            r = ok("GO FROM 100 OVER follow YIELD follow._dst, "
                   "follow.degree")
            assert (500, 42) in set(map(tuple, r.rows))
            assert rt.stats["mirror_builds"] == builds1, \
                "known-dst edge should absorb without a rebuild"
            # an edge to a vid with NO vertex record at all grows the
            # dense-id space: a vertex-plan change — graceful,
            # OBSERVABLE rebuild (results stay exact)
            fails0 = rt.stats["mirror_absorb_failed"]
            ok("INSERT EDGE follow(degree) VALUES 100 -> 600:(44)")
            r = ok("GO FROM 100 OVER follow YIELD follow._dst, "
                   "follow.degree")
            assert (600, 44) in set(map(tuple, r.rows))
            assert rt.stats["mirror_builds"] > builds1, \
                "extra-vid edge changes the vertex plan: rebuild path"
            assert rt.stats["mirror_absorb_failed"] > fails0
            from nebula_tpu.common.events import journal
            kinds = [e for e in journal.dump(200)
                     if e["kind"] == "mirror.absorb_failed"]
            assert any(e.get("reason") == "vertex-plan-change"
                       for e in kinds), kinds
            # starting AT the fresh vertex must be exact too
            ok("INSERT EDGE follow(degree) VALUES 600 -> 103:(43)")
            r = ok("GO FROM 600 OVER follow YIELD follow._dst")
            assert set(map(tuple, r.rows)) == {(103,)}
            from nebula_tpu.common.flags import flags
            flags.set("storage_backend", "cpu")
            r2 = ok("GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            flags.set("storage_backend", "tpu")
            r3 = ok("GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            assert sorted(map(tuple, r3.rows)) == sorted(map(tuple,
                                                             r2.rows))
        finally:
            c.stop()

    def test_vertex_numeric_prop_update_absorbed(self):
        """A numeric tag-prop update on a known vertex applies to the
        mirror IN PLACE (csr.commit_vertex_plan) — no rebuild, and
        device-served $^-filtered queries see the fresh value."""
        c, cl, ok = self._boot()
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow WHERE $^.player.age > 10 "
               "YIELD follow._dst")            # build + device serve
            builds0 = rt.stats["mirror_builds"]
            # p0's age was 20; push it over the new threshold
            ok('INSERT VERTEX player(name, age) VALUES 100:("p0", 77)')
            q = ("GO FROM 100 OVER follow WHERE $^.player.age > 50 "
                 "YIELD follow._dst, $^.player.age")
            r = ok(q)
            got = set(map(tuple, r.rows))
            assert (101, 77) in got, got
            assert rt.stats["mirror_builds"] == builds0, \
                "numeric vertex update must absorb without a rebuild"
            from nebula_tpu.common.flags import flags
            flags.set("storage_backend", "cpu")
            r2 = ok(q)
            flags.set("storage_backend", "tpu")
            assert sorted(map(tuple, r.rows)) == sorted(map(tuple,
                                                            r2.rows))
        finally:
            c.stop()

    def test_vertex_string_prop_update_rebuilds(self):
        """String tag-prop updates stay opaque (dictionaries bake into
        compiled plans) — must rebuild, and results must be fresh."""
        c, cl, ok = self._boot()
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow")
            builds0 = rt.stats["mirror_builds"]
            ok('INSERT VERTEX player(name, age) VALUES 100:("zz", 20)')
            r = ok("GO FROM 100 OVER follow YIELD $^.player.name")
            assert set(map(tuple, r.rows)) == {("zz",)}
            assert rt.stats["mirror_builds"] > builds0
        finally:
            c.stop()

    def test_find_path_sees_fresh_edges(self):
        """FIND PATH forces the rebuild (mirror_full) and must see the
        overlay's edges."""
        c, cl, ok = self._boot()
        try:
            ok("GO FROM 100 OVER follow")
            ok("INSERT EDGE follow(degree) VALUES 100 -> 117@9:(1)")
            r = ok("FIND SHORTEST PATH FROM 100 TO 117 OVER follow "
                   "UPTO 2 STEPS")
            assert r.rows and "117" in r.rows[0][0]
        finally:
            c.stop()


class TestColumnarInterimSeams:
    """Device-served GO results are ColumnarRows (lazy columns); every
    downstream consumer — pipes, $var, ORDER BY, GROUP BY, LIMIT, set
    ops — must read them identically to plain row lists (parity with
    the CPU path pins it)."""

    def _boot(self):
        from nebula_tpu.common.flags import flags
        flags.set("storage_backend", "tpu")
        c = LocalCluster(num_storage=1, tpu_backend=True)
        cl = c.client()

        def ok(s):
            r = cl.execute(s)
            assert r.ok(), f"{s}: {r.error_msg}"
            return r
        ok("CREATE SPACE ci(partition_num=4)")
        c.refresh_all()
        ok("USE ci")
        ok("CREATE EDGE e(w int)")
        c.refresh_all()
        ok("INSERT EDGE e(w) VALUES 1->2:(5), 1->3:(9), 2->4:(7), "
           "3->4:(1), 4->1:(3)")
        return c, ok

    @staticmethod
    def _parity(c, ok, q, expect_rows=None):
        from nebula_tpu.common.flags import flags
        rt = c.tpu_runtime
        dev0 = rt.stats["go_device"]
        a = [tuple(r) for r in ok(q).rows]
        assert rt.stats["go_device"] > dev0, f"device did not serve: {q}"
        flags.set("storage_backend", "cpu")
        b = [tuple(r) for r in ok(q).rows]
        flags.set("storage_backend", "tpu")
        assert a == b, (q, a, b)
        if expect_rows is not None:
            assert a == expect_rows, (q, a)
        return a

    def test_pipe_order_by_limit(self):
        c, ok = self._boot()
        try:
            self._parity(
                c, ok,
                "GO FROM 1 OVER e YIELD e._dst AS id, e.w AS w | "
                "ORDER BY $-.w DESC | LIMIT 1",
                expect_rows=[(3, 9)])
        finally:
            c.stop()

    def test_pipe_group_by_aggregate(self):
        c, ok = self._boot()
        try:
            rows = self._parity(
                c, ok,
                "GO FROM 1, 2, 3 OVER e YIELD e._dst AS id, e.w AS w | "
                "GROUP BY $-.id YIELD $-.id AS id, count(1) AS n, "
                "sum($-.w) AS s")
            assert sorted(rows) == [(2, 1, 5), (3, 1, 9), (4, 2, 8)]
        finally:
            c.stop()

    def test_var_assignment_and_set_op(self):
        c, ok = self._boot()
        try:
            from nebula_tpu.common.flags import flags
            rt = c.tpu_runtime
            dev0 = rt.stats["go_device"]
            r = ok("$a = GO FROM 1 OVER e YIELD e._dst AS id; "
                   "GO FROM $a.id OVER e YIELD e._dst")
            assert rt.stats["go_device"] > dev0
            got = sorted(map(tuple, r.rows))
            flags.set("storage_backend", "cpu")
            r2 = ok("$a = GO FROM 1 OVER e YIELD e._dst AS id; "
                    "GO FROM $a.id OVER e YIELD e._dst")
            flags.set("storage_backend", "tpu")
            assert got == sorted(map(tuple, r2.rows))
            assert got == [(4,), (4,)]
            u = self._parity(
                c, ok,
                "GO FROM 1 OVER e YIELD e._dst AS id UNION "
                "GO FROM 2 OVER e YIELD e._dst AS id")
            assert sorted(u) == [(2,), (3,), (4,)]
        finally:
            c.stop()


class TestSparseSplit:
    """A batch whose TOTAL starts outgrow the sparse c0 ladder splits
    into ladder-sized sparse sub-launches at query boundaries instead
    of falling to the dense pull (whose [n_rows+1, B] frontier upload
    costs minutes at 10^8-edge scale over a tunnel link)."""

    def test_oversized_batch_splits_and_matches_cpu(self):
        import threading

        from nebula_tpu.common.flags import flags

        # the sparse split is a WINDOWED-pipeline path (continuous
        # mode rides the resident dense seat map instead)
        flags.set("go_dispatch_mode", "windowed")
        c, g = _boot(tpu_backend=True)
        try:
            rng = np.random.default_rng(3)
            extra = ", ".join(
                f"{300 + int(a)} -> {300 + int(b)}:({int(i)})"
                for i, (a, b) in enumerate(zip(rng.integers(0, 60, 240),
                                               rng.integers(0, 60, 240))))
            assert g.execute(
                f"INSERT EDGE follow(degree) VALUES {extra}").ok()
            starts = [",".join(str(300 + int(v)) for v in
                               rng.integers(0, 60, 8))
                      for _ in range(12)]
            queries = [f"GO 2 STEPS FROM {s} OVER follow"
                       for s in starts]
            flags.set("storage_backend", "cpu")
            cpu_rows = [sorted(map(tuple, g.execute(q).rows))
                        for q in queries]
            flags.set("storage_backend", "tpu")
            flags.set("tpu_sparse_c0s", "16,32")   # force splitting
            flags.set("go_batch_window_ms", 120)   # coalesce the burst
            try:
                rt = c.tpu_runtime
                base_dense = rt.stats["go_dense"]
                results = {}
                lock = threading.Lock()

                def worker(i):
                    g2 = c.client()
                    g2.execute("USE nba")
                    r = g2.execute(queries[i])
                    assert r.ok(), r.error_msg
                    with lock:
                        results[i] = sorted(map(tuple, r.rows))

                g.execute(queries[0])       # warm kernels
                ts = [threading.Thread(target=worker, args=(i,))
                      for i in range(len(queries))]
                [t.start() for t in ts]
                [t.join() for t in ts]
                for i, rows in results.items():
                    assert rows == cpu_rows[i], queries[i]
                assert rt.stats.get("go_sparse_split", 0) >= 1
                assert rt.stats["go_dense"] == base_dense
            finally:
                flags.set("tpu_sparse_c0s", "256,2048")
                flags.set("go_batch_window_ms", -1)
        finally:
            flags.set("storage_backend", "tpu")
            flags.set("go_dispatch_mode", "continuous")
            c.stop()


class TestUptoDevice:
    """GO UPTO serves on the device via the cumulative-frontier kernel
    variants (sparse union merge / dense OR accumulator) — not a CPU
    fallback."""

    def test_upto_runs_on_device_and_matches_cpu(self):
        from nebula_tpu.common.flags import flags

        # pin the windowed pipeline: this asserts the SPARSE UPTO
        # kernel ran (continuous mode serves UPTO from the dense
        # union accumulator instead — covered in test_continuous.py)
        flags.set("go_dispatch_mode", "windowed")
        c, g = _boot(tpu_backend=True)
        try:
            q = (f"GO UPTO 3 STEPS FROM {TIM} OVER follow "
                 f"YIELD follow._dst, follow.degree")
            flags.set("storage_backend", "cpu")
            cpu_rows = sorted(map(tuple, g.execute(q).rows))
            flags.set("storage_backend", "tpu")
            rt = c.tpu_runtime
            before = rt.stats["go_device"]
            before_sparse = rt.stats["go_sparse"]
            r = g.execute(q)
            assert r.ok(), r.error_msg
            assert sorted(map(tuple, r.rows)) == cpu_rows
            assert rt.stats["go_device"] == before + 1
            assert rt.stats["go_sparse"] == before_sparse + 1
        finally:
            flags.set("storage_backend", "tpu")
            flags.set("go_dispatch_mode", "continuous")
            c.stop()

    def test_upto_dense_kernel_union(self):
        """Dense UPTO variant ORs every depth's frontier."""
        import jax.numpy as jnp

        from nebula_tpu.tpu import ell as E

        rng = np.random.default_rng(5)
        n, m = 200, 900
        es = rng.integers(0, n, m).astype(np.int32)
        ed = rng.integers(0, n, m).astype(np.int32)
        ee = np.ones(m, np.int32)
        both_s = np.concatenate([es, ed])
        both_d = np.concatenate([ed, es])
        both_e = np.concatenate([ee, -ee])
        ix = E.EllIndex.build(both_s, both_d, both_e, n, cap=64, min_d=8)
        f0 = ix.start_frontier([np.asarray([3]), np.asarray([7, 11])],
                               B=8)
        steps = 3
        kern = E.make_batched_go_kernel(ix, steps, (1,), upto=True)
        out = np.asarray(kern(jnp.asarray(f0), *ix.kernel_args()))
        # numpy oracle: OR of frontiers at depths 0..steps-1
        adj = {}
        for s_, d_ in zip(es.tolist(), ed.tolist()):
            adj.setdefault(s_, set()).add(d_)
        for q, starts in enumerate(([3], [7, 11])):
            acc = set(starts)
            cur = set(starts)
            for _ in range(steps - 1):
                cur = set().union(*(adj.get(v, set()) for v in cur)) \
                    if cur else set()
                acc |= cur
            got = set(np.nonzero(ix.to_old(out[:ix.n_rows + 1])
                                 [:n, q])[0].tolist())
            assert got == acc, (q, got, acc)

    def test_upto_sparse_kernel_union(self):
        """Sparse UPTO variant returns the deduped union pair list."""
        import jax.numpy as jnp

        from nebula_tpu.tpu import ell as E

        rng = np.random.default_rng(11)
        n, m = 300, 1500
        es = rng.integers(0, n, m).astype(np.int32)
        ed = rng.integers(0, n, m).astype(np.int32)
        ee = np.ones(m, np.int32)
        both_s = np.concatenate([es, ed])
        both_d = np.concatenate([ed, es])
        both_e = np.concatenate([ee, -ee])
        ix = E.EllIndex.build(both_s, both_d, both_e, n, cap=64, min_d=8)
        steps = 3
        caps = E.sparse_caps(8, max(ix.bucket_D), steps, 1 << 14)
        kern = E.make_batched_sparse_go_kernel(ix, steps, (1,), caps,
                                               qmax=16, upto=True)
        starts = [[3], [7, 11], [42]]
        ids = np.full(caps[0], ix.n_rows, np.int32)
        qid = np.zeros(caps[0], np.int32)
        k = 0
        for q, ss in enumerate(starts):
            for v in ss:
                ids[k] = ix.perm[v]
                qid[k] = q
                k += 1
        ecnt, e0 = ix.hub_expansion()
        out = kern(jnp.asarray(ids), jnp.asarray(qid),
                   jnp.asarray(ecnt), jnp.asarray(e0),
                   *ix.kernel_args()[1:])
        cnt, overflow, qids, vids_new = E.sparse_go_pairs(
            kern, np.asarray(out))
        assert not overflow
        adj = {}
        for s_, d_ in zip(es.tolist(), ed.tolist()):
            adj.setdefault(s_, set()).add(d_)
        got = {}
        for qv, iv in zip(qids.tolist(), ix.inv[vids_new].tolist()):
            got.setdefault(qv, set()).add(iv)
        for q, ss in enumerate(starts):
            acc = set(ss)
            cur = set(ss)
            for _ in range(steps - 1):
                cur = set().union(*(adj.get(v, set()) for v in cur)) \
                    if cur else set()
                acc |= cur
            assert got.get(q, set()) == acc, (q, got.get(q), acc)


class TestRetraceBudget:
    """Runtime half of nebulint's jax-hotpath check: a repeated
    multi-hop traversal over the same space must not grow the jit
    trace cache (or the runtime's kernel memo) after warmup.  Growth
    here is the cache-buster class — jit construction per call,
    unhashable static args, closures over mutables — that silently
    turns every hop into a fresh XLA trace."""

    QUERY = f"GO 3 STEPS FROM {TIM} OVER follow YIELD follow._dst"

    def _snapshot(self, rt):
        with rt._lock:
            kernels = dict(rt._kernels)
        sizes = {}
        for key, kern in kernels.items():
            cs = getattr(kern, "_cache_size", None)
            sizes[key] = cs() if callable(cs) else -1
        return sizes

    def test_jit_cache_stable_after_warmup(self, clusters):
        _cpu_c, _cpu, tpu_c, tpu = clusters
        rt = tpu_c.tpu_runtime
        for _ in range(2):       # warmup: mirror + kernel builds + traces
            assert tpu.execute(self.QUERY).ok()
        before = self._snapshot(rt)
        builds_before = rt.stats["mirror_builds"]
        for _ in range(5):
            assert tpu.execute(self.QUERY).ok()
        after = self._snapshot(rt)
        assert set(after) == set(before), (
            f"kernel memo grew after warmup: {set(after) ^ set(before)}")
        grown = {k: (before[k], after[k]) for k in before
                 if after[k] != before[k]}
        assert not grown, f"jit trace cache grew after warmup: {grown}"
        assert rt.stats["mirror_builds"] == builds_before, \
            "repeat traversal rebuilt the mirror"
