"""Differential tests: the vectorized bulk mirror builder
(tpu/csr_bulk.py) must produce BIT-IDENTICAL mirrors to the per-row
reference builder (tpu/csr._build_mirror_slow) on adversarial fixtures:
multi-version rows, schema evolution (older rows as prefixes), TTL
expiry, string/bool/float/int columns, missing tags, empty blobs,
multi-part + multi-etype spread, and randomized graphs.
"""
import numpy as np
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.flags import flags
from nebula_tpu.native import available
from nebula_tpu.tpu.csr import _build_mirror_slow, build_mirror
from nebula_tpu.tpu.csr_bulk import build_mirror_bulk

pytestmark = pytest.mark.skipif(
    not available(), reason="native library not built")


def _assert_mirrors_equal(a, b):
    np.testing.assert_array_equal(a.vids, b.vids)
    assert a.n == b.n and a.m == b.m
    np.testing.assert_array_equal(a.edge_src, b.edge_src)
    np.testing.assert_array_equal(a.edge_dst, b.edge_dst)
    np.testing.assert_array_equal(a.edge_etype, b.edge_etype)
    np.testing.assert_array_equal(a.edge_rank, b.edge_rank)
    np.testing.assert_array_equal(a.row_ptr, b.row_ptr)
    assert set(a.edge_cols) == set(b.edge_cols)
    for k in a.edge_cols:
        ca, cb = a.edge_cols[k], b.edge_cols[k]
        np.testing.assert_array_equal(ca.valid, cb.valid, err_msg=str(k))
        np.testing.assert_array_equal(ca.values, cb.values,
                                      err_msg=str(k))
        assert ca.device_ok == cb.device_ok, k
        if ca.raw is not None or cb.raw is not None:
            assert [str(x) for x in ca.raw] == [str(x) for x in cb.raw], k
    assert set(a.vertex_cols) == set(b.vertex_cols)
    for k in a.vertex_cols:
        ca, cb = a.vertex_cols[k], b.vertex_cols[k]
        np.testing.assert_array_equal(ca.valid, cb.valid, err_msg=str(k))
        np.testing.assert_array_equal(ca.values, cb.values,
                                      err_msg=str(k))
        if ca.raw is not None or cb.raw is not None:
            assert [str(x) for x in ca.raw] == [str(x) for x in cb.raw], k
    assert set(a.has_tag) == set(b.has_tag)
    for t in a.has_tag:
        np.testing.assert_array_equal(a.has_tag[t], b.has_tag[t])
    # TTL bookkeeping must match so rebuild cadence is identical
    assert (a.expires_at_s is None) == (b.expires_at_s is None)
    if a.expires_at_s is not None:
        assert abs(a.expires_at_s - b.expires_at_s) < 1e-6


def _diff(cluster, space_name):
    sid = cluster.graph_meta_client.get_space_id_by_name(space_name).value()
    stores = [n.kv for n in cluster.storage_nodes]
    slow = _build_mirror_slow(sid, stores, cluster.schema_man)
    fast = build_mirror_bulk(sid, stores, cluster.schema_man)
    assert fast is not None, "bulk builder unexpectedly declined"
    _assert_mirrors_equal(fast, slow)
    return fast


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_storage=1, tpu_backend=False)
    yield c
    c.stop()


class TestBulkMirrorParity:
    def test_rich_fixture(self, cluster):
        g = cluster.client()

        def ok(s):
            r = g.execute(s)
            assert r.ok(), f"{s}: {r.error_msg}"

        ok("CREATE SPACE bulk1(partition_num=5, replica_factor=1)")
        cluster.refresh_all()
        ok("USE bulk1")
        ok("CREATE TAG player(name string, age int, score double, "
           "active bool)")
        ok("CREATE TAG team(name string)")
        ok("CREATE EDGE follow(degree int, note string)")
        ok("CREATE EDGE serve(start_year int)")
        cluster.refresh_all()
        ok('INSERT VERTEX player(name, age, score, active) VALUES '
           '1:("a", 10, 1.5, true), 2:("b", 20, -2.25, false), '
           '3:("c", 30, 0.0, true), 4:("", -1, 1e18, false)')
        ok('INSERT VERTEX team(name) VALUES 100:("t1"), 101:("")')
        ok('INSERT EDGE follow(degree, note) VALUES '
           '1 -> 2:(95, "x"), 2 -> 3:(90, ""), 3 -> 1:(85, "yy"), '
           '1 -> 3@7:(80, "r7"), 1 -> 100:(1, "to-team")')
        ok('INSERT EDGE serve(start_year) VALUES 1 -> 100:(1999), '
           '2 -> 101:(2001)')
        # multi-version: overwrite 1->2 (same identity, fresher version)
        ok('INSERT EDGE follow(degree, note) VALUES 1 -> 2:(96, "x2")')
        ok('INSERT VERTEX player(name, age, score, active) VALUES '
           '2:("b2", 21, -2.25, true)')
        m = _diff(cluster, "bulk1")
        assert m.m > 0 and m.n >= 6
        # spot-check the multi-version winner landed
        d1 = m.to_dense([1])[0]
        e = None
        for i in range(int(m.row_ptr[d1]), int(m.row_ptr[d1 + 1])):
            if (int(m.edge_dst[i]) == m.to_dense([2])[0]
                    and int(m.edge_etype[i]) > 0
                    and int(m.edge_rank[i]) == 0):
                key = (int(m.edge_etype[i]), "degree")
                e = int(m.edge_cols[key].values[i])
        assert e == 96

    def test_schema_evolution_old_rows_as_prefixes(self, cluster):
        g = cluster.client()

        def ok(s):
            r = g.execute(s)
            assert r.ok(), f"{s}: {r.error_msg}"

        ok("CREATE SPACE bulk2(partition_num=3, replica_factor=1)")
        cluster.refresh_all()
        ok("USE bulk2")
        ok("CREATE EDGE rel(w int)")
        cluster.refresh_all()
        ok('INSERT EDGE rel(w) VALUES 1 -> 2:(7), 2 -> 3:(8)')
        ok("ALTER EDGE rel ADD (note2 string)")
        cluster.refresh_all()
        ok('INSERT EDGE rel(w, note2) VALUES 3 -> 4:(9, "new")')
        m = _diff(cluster, "bulk2")
        # old rows miss the appended column; new row carries it
        et = [k[0] for k in m.edge_cols if k[1] == "note2"][0]
        tag_col = m.edge_cols[(et, "note2")]
        assert tag_col.valid.sum() == 1

    def test_ttl_expiry(self, cluster):
        import time as _t
        g = cluster.client()

        def ok(s):
            r = g.execute(s)
            assert r.ok(), f"{s}: {r.error_msg}"

        ok("CREATE SPACE bulk3(partition_num=3, replica_factor=1)")
        cluster.refresh_all()
        ok("USE bulk3")
        ok("CREATE EDGE seen(ts timestamp) ttl_duration = 3600, "
           "ttl_col = ts")
        ok("CREATE TAG mark(ts timestamp) ttl_duration = 3600, "
           "ttl_col = ts")
        cluster.refresh_all()
        now = int(_t.time())
        ok(f'INSERT EDGE seen(ts) VALUES 1 -> 2:({now}), '
           f'1 -> 3:({now - 7200}), 2 -> 3:({now + 50})')
        ok(f'INSERT VERTEX mark(ts) VALUES 1:({now}), 9:({now - 7200})')
        m = _diff(cluster, "bulk3")
        # expired edge 1->3 dropped (both directions), live ones kept
        assert m.m == 4
        # expired tag row on 9: vertex exists (edge endpoints) is false —
        # 9 only existed via the tag row, which expired, but the vid was
        # still collected pre-filter (slow-path parity)
        assert 9 in m.vids.tolist()
        t = list(m.has_tag)[0]
        assert not m.has_tag[t][m.to_dense([9])[0]]

    def test_randomized_graphs(self, cluster):
        g = cluster.client()
        rng = np.random.default_rng(7)

        def ok(s):
            r = g.execute(s)
            assert r.ok(), f"{s}: {r.error_msg}"

        ok("CREATE SPACE bulk4(partition_num=7, replica_factor=1)")
        cluster.refresh_all()
        ok("USE bulk4")
        ok("CREATE EDGE e1(a int, b double)")
        ok("CREATE EDGE e2(s string)")
        ok("CREATE TAG t1(x int)")
        cluster.refresh_all()
        n = 60
        for _ in range(3):
            vals = ", ".join(
                f"{rng.integers(1, n)} -> {rng.integers(1, n)}"
                f"@{rng.integers(0, 3)}:({rng.integers(-5, 5)}, "
                f"{float(rng.integers(-100, 100)) / 4})"
                for _ in range(120))
            ok(f"INSERT EDGE e1(a, b) VALUES {vals}")
            vals2 = ", ".join(
                f'{rng.integers(1, n)} -> {rng.integers(1, n)}:'
                f'("s{rng.integers(0, 9)}")' for _ in range(60))
            ok(f"INSERT EDGE e2(s) VALUES {vals2}")
            vv = ", ".join(f"{v}:({rng.integers(0, 100)})"
                           for v in rng.choice(n - 1, 25, replace=False) + 1)
            ok(f"INSERT VERTEX t1(x) VALUES {vv}")
        _diff(cluster, "bulk4")

    def test_dispatcher_uses_bulk_and_flag_disables(self, cluster):
        g = cluster.client()

        def ok(s):
            r = g.execute(s)
            assert r.ok(), f"{s}: {r.error_msg}"

        ok("CREATE SPACE bulk5(partition_num=3, replica_factor=1)")
        cluster.refresh_all()
        ok("USE bulk5")
        ok("CREATE EDGE r(w int)")
        cluster.refresh_all()
        ok('INSERT EDGE r(w) VALUES 1 -> 2:(1)')
        sid = cluster.graph_meta_client.get_space_id_by_name("bulk5").value()
        stores = [n.kv for n in cluster.storage_nodes]
        m1 = build_mirror(sid, stores, cluster.schema_man)
        flags.set("mirror_bulk_build", False)
        try:
            m2 = build_mirror(sid, stores, cluster.schema_man)
        finally:
            flags.set("mirror_bulk_build", True)
        _assert_mirrors_equal(m1, m2)
