"""raftex tests — in-process multi-instance consensus harness.

Mirrors the reference's RaftexTestBase strategy (raftex/test/
RaftexTestBase.h:65-80): N real RaftexService instances in one process
wired through loopback channels, with kill / isolate / reconnect, and a
kvstore Part over a MemEngine as the replicated state machine (the
reference's TestShard). Covers: leader election, log append + quorum
commit, CAS, COMMAND logs (learner, leader transfer, peer add/remove),
follower catch-up after isolation, divergence rollback, and snapshot
transfer to a lagging peer (LeaderElectionTest / LogAppendTest /
LogCASTest / LogCommandTest / LearnerTest equivalents).
"""
import os
import time

import pytest

from nebula_tpu.common.flags import flags
from nebula_tpu.common.status import ErrorCode, Status
from nebula_tpu.interface.common import HostAddr
from nebula_tpu.interface.rpc import ClientManager, RpcError
from nebula_tpu.kvstore.engine import MemEngine
from nebula_tpu.kvstore.part import Part
from nebula_tpu.raftex import RaftexService, Role


class Gate:
    """Loopback handler wrapper that can drop inbound RPCs (the harness's
    network-partition switch)."""

    def __init__(self, handler):
        self.handler = handler
        self.open = True

    def __getattr__(self, name):
        if not name.startswith("rpc_"):
            raise AttributeError(name)
        fn = getattr(self.handler, name)

        def wrapped(payload):
            if not self.open:
                raise RpcError(Status.Error("partitioned",
                                            ErrorCode.E_RPC_FAILURE))
            return fn(payload)

        return wrapped


class GatedCM:
    """Outbound half of the partition switch: a node whose gate is closed
    can neither receive (Gate) nor send (this)."""

    def __init__(self, inner: ClientManager, gate: "Gate"):
        self.inner = inner
        self.gate = gate

    def call(self, addr, method, payload, timeout=None):
        if not self.gate.open:
            raise RpcError(Status.Error("partitioned",
                                        ErrorCode.E_RPC_FAILURE))
        return self.inner.call(addr, method, payload, timeout=timeout)


class Node:
    def __init__(self, idx: int, cm: ClientManager):
        self.addr = f"127.0.0.1:{46000 + idx}"
        self.engine = MemEngine()
        self.gate = Gate(None)
        self.raft_service = RaftexService(self.addr, GatedCM(cm, self.gate),
                                          workers=8)
        self.gate.handler = self.raft_service
        cm.register_loopback(HostAddr.parse(self.addr), self.gate)
        self.part = None

    def add_part(self, peers, as_learner=False):
        raft = self.raft_service.add_part(1, 1, peers,
                                          as_learner=as_learner)
        self.part = Part(1, 1, self.engine, raft=raft)
        return self.part


class Cluster:
    def __init__(self, n: int):
        self.cm = ClientManager()
        self.nodes = [Node(i, self.cm) for i in range(n)]
        peers = [nd.addr for nd in self.nodes]
        for nd in self.nodes:
            nd.add_part(peers)

    def leader(self, timeout=10.0):
        deadline = time.monotonic() + timeout
        stable = None
        streak = 0
        while time.monotonic() < deadline:
            leaders = [nd for nd in self.nodes
                       if nd.gate.open and nd.part.raft.is_leader()]
            if len(leaders) == 1:
                # require the same leader across consecutive checks —
                # a mid-election blip otherwise hands back a node that
                # immediately stops leading (flaky under load)
                if leaders[0] is stable:
                    streak += 1
                    if streak >= 2:
                        return leaders[0]
                else:
                    stable, streak = leaders[0], 0
            else:
                stable, streak = None, 0
            time.sleep(0.02)
        raise AssertionError(
            "no unique leader: " +
            repr([nd.part.raft.status() for nd in self.nodes]))

    def followers(self):
        lead = self.leader()
        return [nd for nd in self.nodes if nd is not lead]

    def stop(self):
        for nd in self.nodes:
            nd.raft_service.stop()


def wait_converged(nodes, key, value, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(nd.engine.get(key) == value for nd in nodes):
            return True
        time.sleep(0.02)
    return False


@pytest.fixture(autouse=True)
def fast_raft():
    saved = {n: flags.get(n) for n in
             ("raft_heartbeat_interval_s", "raft_election_timeout_s",
              "raft_rpc_timeout_s", "raft_append_timeout_s",
              "raft_wal_keep_logs")}
    flags.set("raft_heartbeat_interval_s", 0.05)
    flags.set("raft_election_timeout_s", 0.25)
    flags.set("raft_rpc_timeout_s", 1.0)
    flags.set("raft_append_timeout_s", 3.0)
    yield
    for k, v in saved.items():
        flags.set(k, v)


@pytest.fixture
def cluster3():
    c = Cluster(3)
    yield c
    c.stop()


class TestLeaderElection:
    def test_single_leader_emerges(self, cluster3):
        lead = cluster3.leader()
        assert lead.part.raft.role == Role.LEADER
        for nd in cluster3.followers():
            assert nd.part.raft.role == Role.FOLLOWER

    def test_reelection_after_leader_isolated(self, cluster3):
        old = cluster3.leader()
        old.gate.open = False
        # followers must elect a replacement among themselves
        deadline = time.monotonic() + 5.0
        new = None
        while time.monotonic() < deadline:
            others = [nd for nd in cluster3.nodes if nd is not old]
            ls = [nd for nd in others if nd.part.raft.is_leader()]
            if len(ls) == 1:
                new = ls[0]
                break
            time.sleep(0.02)
        assert new is not None and new is not old
        # old leader rejoins and steps down on seeing the higher term
        old.gate.open = True
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if not old.part.raft.is_leader():
                break
            time.sleep(0.02)
        assert not old.part.raft.is_leader()

    def test_single_replica_is_immediate_leader(self):
        cm = ClientManager()
        nd = Node(99, cm)
        nd.add_part([nd.addr])
        assert nd.part.raft.is_leader()
        assert nd.part.put(b"k", b"v").ok()
        assert nd.engine.get(b"k") == b"v"
        nd.raft_service.stop()


class TestLogAppend:
    def test_replicated_put_reaches_all(self, cluster3):
        lead = cluster3.leader()
        st = lead.part.put(b"name", b"nebula")
        assert st.ok(), st.to_string()
        assert wait_converged(cluster3.nodes, b"name", b"nebula")

    def test_follower_rejects_writes(self, cluster3):
        f = cluster3.followers()[0]
        st = f.part.put(b"x", b"y")
        assert not st.ok()
        assert st.code == ErrorCode.E_LEADER_CHANGED

    def test_group_commit_many_writes(self, cluster3):
        lead = cluster3.leader()
        for i in range(50):
            assert lead.part.put(b"k%03d" % i, b"v%d" % i).ok()
        assert wait_converged(cluster3.nodes, b"k049", b"v49")
        for nd in cluster3.nodes:
            assert nd.engine.get(b"k000") == b"v0"
            assert nd.engine.get(b"k025") == b"v25"

    def test_multi_put_and_remove(self, cluster3):
        lead = cluster3.leader()
        assert lead.part.multi_put([(b"a", b"1"), (b"b", b"2")]).ok()
        assert lead.part.remove(b"a").ok()
        assert wait_converged(cluster3.nodes, b"b", b"2")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if all(nd.engine.get(b"a") is None for nd in cluster3.nodes):
                break
            time.sleep(0.02)
        for nd in cluster3.nodes:
            assert nd.engine.get(b"a") is None


class TestLogCAS:
    def test_cas_success_and_mismatch(self, cluster3):
        lead = cluster3.leader()
        assert lead.part.put(b"ctr", b"1").ok()
        assert lead.part.cas(b"1", b"ctr", b"2").ok()
        st = lead.part.cas(b"1", b"ctr", b"3")
        assert not st.ok() and st.code == ErrorCode.E_BAD_STATE
        assert wait_converged(cluster3.nodes, b"ctr", b"2")

    def test_cas_on_absent_key(self, cluster3):
        lead = cluster3.leader()
        # absent == empty (reference CAS semantics)
        assert lead.part.cas(b"", b"new", b"init").ok()
        assert wait_converged(cluster3.nodes, b"new", b"init")


class TestCatchUp:
    def test_isolated_follower_catches_up(self, cluster3):
        lead = cluster3.leader()
        straggler = cluster3.followers()[0]
        straggler.gate.open = False
        for i in range(20):
            assert lead.part.put(b"cu%02d" % i, b"v").ok()
        others = [nd for nd in cluster3.nodes if nd is not straggler]
        assert wait_converged(others, b"cu19", b"v")
        assert straggler.engine.get(b"cu19") is None
        straggler.gate.open = True
        assert wait_converged([straggler], b"cu19", b"v")
        assert straggler.engine.get(b"cu00") == b"v"

    def test_snapshot_transfer_to_lagging_peer(self, cluster3):
        lead = cluster3.leader()
        straggler = cluster3.followers()[0]
        straggler.gate.open = False
        for i in range(30):
            assert lead.part.put(b"sn%02d" % i, b"v").ok()
        # leader forgets the WAL window the straggler would need
        flags.set("raft_wal_keep_logs", 0)
        lead.part.raft.cleanup_wal()
        assert lead.part.raft.wal.first_log_id() > 1
        assert lead.part.raft.wal.last_log_id() >= \
            lead.part.raft.committed_id
        straggler.gate.open = True
        assert wait_converged([straggler], b"sn29", b"v")
        assert straggler.engine.get(b"sn00") == b"v"
        # and the straggler keeps following post-snapshot appends
        assert lead.part.put(b"post", b"snap").ok()
        assert wait_converged([straggler], b"post", b"snap")


class TestCommandLogs:
    def test_leader_transfer(self, cluster3):
        # leadership can churn between finding the leader and issuing
        # the transfer (fast test timeouts on a loaded box) — chase the
        # leader like a real client does on E_LEADER_CHANGED
        target = None
        for _ in range(10):
            lead = cluster3.leader()
            target = next(nd for nd in cluster3.nodes if nd is not lead)
            st = lead.part.raft.transfer_leadership(target.addr)
            if st.ok():
                break
            time.sleep(0.1)
        else:
            raise AssertionError("leader transfer kept losing the race")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if target.part.raft.is_leader():
                break
            time.sleep(0.02)
        assert target.part.raft.is_leader()
        # new leader serves writes
        assert target.part.put(b"tl", b"ok").ok()
        assert wait_converged(cluster3.nodes, b"tl", b"ok")

    def test_learner_receives_but_does_not_vote(self, cluster3):
        cm = cluster3.cm
        learner = Node(3, cm)
        peers = [nd.addr for nd in cluster3.nodes]
        learner.add_part(peers + [learner.addr], as_learner=True)
        lead = cluster3.leader()
        assert lead.part.raft.add_learner_async(learner.addr).ok()
        assert lead.part.put(b"lrn", b"data").ok()
        assert wait_converged([learner], b"lrn", b"data")
        assert learner.part.raft.role == Role.LEARNER
        # learner never becomes candidate even when leader vanishes
        for nd in cluster3.nodes:
            nd.gate.open = False
        time.sleep(0.8)
        assert learner.part.raft.role == Role.LEARNER
        for nd in cluster3.nodes:
            nd.gate.open = True
        learner.raft_service.stop()

    def test_membership_change_add_peer(self, cluster3):
        cm = cluster3.cm
        newbie = Node(4, cm)
        peers = [nd.addr for nd in cluster3.nodes]
        newbie.add_part(peers + [newbie.addr], as_learner=True)
        lead = cluster3.leader()
        assert lead.part.raft.add_learner_async(newbie.addr).ok()
        assert lead.part.put(b"m0", b"x").ok()
        assert wait_converged([newbie], b"m0", b"x")
        # promote: learner → voter on every replica via COMMAND log
        assert lead.part.raft.add_peer_async(newbie.addr).ok()
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if newbie.part.raft.role == Role.FOLLOWER:
                break
            time.sleep(0.02)
        assert newbie.part.raft.role == Role.FOLLOWER
        assert not lead.part.raft.peers[newbie.addr].is_learner
        newbie.raft_service.stop()


class TestStarvationGuard:
    """A follower whose own tick thread stalled (GIL convoy, CPU
    oversubscription) must NOT charge the stalled time against the
    election timeout — it could not have seen heartbeats while
    descheduled, and a starvation-triggered election is the classic
    full-suite failover flake (a liveness delay is always safe; a
    spurious term bump is not free)."""

    def _part(self, tmp_path):
        from concurrent.futures import ThreadPoolExecutor
        from nebula_tpu.raftex.raft_part import RaftPart
        cm = ClientManager()       # peers unroutable: every RPC fails
        ex = ThreadPoolExecutor(max_workers=2)
        p = RaftPart(1, 1, "127.0.0.1:47101",
                     ["127.0.0.1:47101", "127.0.0.1:47102",
                      "127.0.0.1:47103"], cm, ex,
                     wal_dir=str(tmp_path / "wal"))
        return p, ex

    def test_stalled_ticks_defer_election(self, tmp_path):
        p, ex = self._part(tmp_path)
        try:
            tick = 0.05
            now = time.monotonic()
            p._last_heard = now
            p.tick(now, expected_interval=tick)
            # poller starved: next tick arrives a whole election
            # timeout late — the stall is excluded, so no election
            stall = p._election_timeout + 1.0
            p.tick(now + stall, expected_interval=tick)
            assert not p._electing
            assert p.term == 0
        finally:
            p.stop()
            ex.shutdown(wait=False)

    def test_steady_ticks_still_elect(self, tmp_path):
        p, ex = self._part(tmp_path)
        try:
            tick = 0.05
            now = time.monotonic()
            p._last_heard = now
            t = now
            deadline = now + p._election_timeout + 10 * tick
            fired = False
            while t < deadline:
                t += tick            # healthy cadence, silent leader
                p.tick(t, expected_interval=tick)
                if p._electing or p.term > 0:
                    fired = True
                    break
            assert fired, "healthy follower with a silent leader " \
                          "must start an election"
        finally:
            p.stop()
            ex.shutdown(wait=False)


class TestRecovery:
    def test_hard_state_survives_restart(self, tmp_path):
        """A restarted node must remember (term, votedFor) — forgetting a
        vote enables two leaders in one term (Raft persistence rule)."""
        from nebula_tpu.raftex.raft_part import RaftPart
        from concurrent.futures import ThreadPoolExecutor
        cm = ClientManager()
        ex = ThreadPoolExecutor(max_workers=2)
        p1 = RaftPart(1, 1, "127.0.0.1:47001",
                      ["127.0.0.1:47001", "127.0.0.1:47002"], cm, ex,
                      wal_dir=str(tmp_path / "wal"))
        resp = p1.process_ask_for_vote({
            "space": 1, "part": 1, "term": 7, "cand": "127.0.0.1:47002",
            "last_log_id": 0, "last_log_term": 0})
        assert resp["granted"]
        p1.stop()
        # reincarnate from the same wal_dir
        p2 = RaftPart(1, 1, "127.0.0.1:47001",
                      ["127.0.0.1:47001", "127.0.0.1:47002"], cm, ex,
                      wal_dir=str(tmp_path / "wal"))
        assert p2.term == 7
        # same term, different candidate: must refuse
        resp = p2.process_ask_for_vote({
            "space": 1, "part": 1, "term": 7, "cand": "127.0.0.1:47003",
            "last_log_id": 0, "last_log_term": 0})
        assert not resp["granted"]
        # same candidate may be re-granted (idempotent)
        resp = p2.process_ask_for_vote({
            "space": 1, "part": 1, "term": 7, "cand": "127.0.0.1:47002",
            "last_log_id": 0, "last_log_term": 0})
        assert resp["granted"]
        p2.stop()
        ex.shutdown(wait=False)

    def test_commit_watermark_skips_reapply(self, cluster3):
        lead = cluster3.leader()
        assert lead.part.put(b"wm", b"1").ok()
        assert wait_converged(cluster3.nodes, b"wm", b"1")
        for nd in cluster3.nodes:
            log_id, _term = nd.part.last_committed_log_id()
            assert log_id >= 1


class TestPipelinedReplication:
    """raft_pipeline_depth > 1: concurrent client appends replicate as
    multiple in-flight batches (reference Host request pipelining).
    Apply order must stay exactly log order on every replica, with no
    gaps, under full concurrency — and a mid-stream leader loss must
    not corrupt anything."""

    def test_concurrent_appends_apply_in_order(self, cluster3):
        import threading
        lead = cluster3.leader()
        applied = []   # log ids in apply order on the leader
        orig = lead.part.raft.commit_handler

        def wrapped(entries):
            applied.extend(lid for lid, _t, _m in entries)
            return orig(entries)
        lead.part.raft.commit_handler = wrapped

        errs = []
        def writer(t):
            try:
                for i in range(25):
                    st = lead.part.put(b"t%02d-%03d" % (t, i),
                                       b"v%d" % i)
                    assert st.ok(), st.to_string()
            except Exception as e:      # noqa: BLE001
                errs.append(e)
        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errs, errs
        # apply order is strictly ascending with no duplicates
        assert applied == sorted(applied)
        assert len(set(applied)) == len(applied)
        # all 200 writes on every replica (quorum may have excluded a
        # lagging follower; heartbeat catch-up converges it)
        deadline = time.monotonic() + 10.0
        missing = None
        while time.monotonic() < deadline:
            missing = [(nd.addr, t, i)
                       for nd in cluster3.nodes
                       for t in range(8) for i in range(25)
                       if nd.engine.get(b"t%02d-%03d" % (t, i))
                       != b"v%d" % i]
            if not missing:
                break
            time.sleep(0.05)
        assert not missing, missing[:5]

    def test_pipeline_survives_leader_isolation(self, cluster3):
        import threading
        lead = cluster3.leader()
        stop = threading.Event()
        results = {"ok": 0, "err": 0}

        def writer():
            i = 0
            while not stop.is_set():
                st = lead.part.put(b"p%05d" % i, b"x")
                results["ok" if st.ok() else "err"] += 1   # single writer
                i += 1
        th = threading.Thread(target=writer)
        th.start()
        time.sleep(0.3)
        lead.gate.open = False       # partition the leader mid-stream
        time.sleep(1.0)
        stop.set()
        th.join()
        lead.gate.open = True
        # a new leader exists and the cluster still accepts writes.
        # The rejoining old leader can bump terms and churn leadership
        # for a beat — chase the leader like a real client
        for _ in range(10):
            new_lead = cluster3.leader(timeout=10.0)
            if new_lead.part.put(b"after", b"ok").ok():
                break
            time.sleep(0.1)
        else:
            raise AssertionError("post-partition write kept losing the "
                                 "leader race")
        assert wait_converged(cluster3.nodes, b"after", b"ok",
                              timeout=10.0)


class TestPipelinedCAS:
    def test_cas_sees_pipelined_put(self, cluster3):
        """A CAS queued behind a put of the same key must compare
        against the put's value even while the put's batch is still in
        flight (pipelined batches apply after WAL append)."""
        flags.set("raft_pipeline_depth", 4)
        lead = cluster3.leader()
        assert lead.part.put(b"ck", b"v1").ok()
        # interleave: put v2 then CAS expecting v2, racing from threads
        import threading
        res = {}
        def put():
            res["put"] = lead.part.put(b"ck", b"v2")
        def cas():
            # tiny stagger so the put's batch is built first
            time.sleep(0.005)
            res["cas"] = lead.part.cas(b"v2", b"ck", b"v3")
        t1, t2 = threading.Thread(target=put), threading.Thread(target=cas)
        t1.start(); t2.start(); t1.join(); t2.join()
        assert res["put"].ok()
        # the CAS must have seen v2 (never the stale v1)
        assert res["cas"].ok(), res["cas"].to_string()
        assert wait_converged(cluster3.nodes, b"ck", b"v3")


class TestWalDurability:
    """wal_sync defaults ON: the raft WAL is the only redo log (disk
    engines run RocksDB-WAL-off semantics), so an acked write must be
    fsync'd — not merely flushed to the OS — before the ack (VERDICT
    round-2 weak #7)."""

    def test_default_is_durable(self):
        assert flags.get("wal_sync") is True

    def test_fsync_happens_before_ack(self, tmp_path, monkeypatch):
        from nebula_tpu.kvstore import wal as walmod
        from nebula_tpu.raftex import RaftexService

        synced = []
        real_fsync = os.fsync
        monkeypatch.setattr(
            walmod.os, "fsync",
            lambda fd: (synced.append(fd), real_fsync(fd))[1])

        cm = ClientManager()
        addr = "127.0.0.1:46900"
        svc = RaftexService(addr, cm, wal_root=str(tmp_path / "wal"))
        cm.register_loopback(HostAddr.parse(addr), svc)
        engine = MemEngine()
        raft = svc.add_part(1, 1, [addr])
        part = Part(1, 1, engine, raft=raft)
        try:
            deadline = time.monotonic() + 5
            while not raft.is_leader() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert raft.is_leader()
            synced.clear()
            st = part.put(b"k", b"v")
            assert st.ok()
            # the ack we just received implies the fsync already ran
            assert synced, "acked put without an fsync (wal_sync=True)"
            assert engine.get(b"k") == b"v"
        finally:
            svc.stop()

        # crash-replay: a brand-new WAL over the same dir must re-serve
        # the acked entry from the fsync'd segments
        from nebula_tpu.kvstore.wal import FileBasedWal
        import glob as _glob
        segs = _glob.glob(str(tmp_path / "wal" / "**" / "wal.*.log"),
                          recursive=True)
        assert segs, "no wal segment written"
        w2 = FileBasedWal(os.path.dirname(segs[0]))
        assert w2.last_log_id() >= 1
        assert any(e.msg for e in w2.iterate(1))

    def test_wal_sync_off_skips_fsync(self, tmp_path, monkeypatch):
        from nebula_tpu.kvstore import wal as walmod
        from nebula_tpu.kvstore.wal import FileBasedWal
        synced = []
        monkeypatch.setattr(walmod.os, "fsync",
                            lambda fd: synced.append(fd))
        flags.set("wal_sync", False)
        try:
            w = FileBasedWal(str(tmp_path / "w"))
            w.append_log(1, 1, b"x")
            w.flush()
            assert not synced
        finally:
            flags.set("wal_sync", True)
        w3 = FileBasedWal(str(tmp_path / "w"))
        assert w3.last_log_id() == 1     # flushed-to-OS still replays


class TestAdaptivePipelining:
    def test_depth_collapses_on_fast_links(self, cluster3):
        """Loopback replication RTT is ~0: after a few writes the
        leader's effective depth must drop to pure group commit
        (pipelining only splits batches there — round-2 BASELINE
        measured -25%); a slow measured RTT must restore the
        configured depth."""
        lead = cluster3.leader()
        raft = lead.part.raft
        # scheduler noise on a loaded box can pin the RTT EMA just over
        # the 1 ms gate — re-measure a few rounds; the link itself is
        # loopback, so a quiet round lands far under it
        for round_ in range(5):
            for i in range(20):
                assert lead.part.put(b"a%02d%02d" % (round_, i),
                                     b"v").ok()
            if raft._rep_rtt is not None and raft._rep_rtt < 0.001:
                break
        assert raft._rep_rtt is not None and raft._rep_rtt < 0.001
        with raft._lock:
            assert raft._effective_depth() == 1
        # pretend the link got slow: configured depth comes back
        with raft._lock:
            raft._rep_rtt = 0.01
            assert raft._effective_depth() == \
                max(1, int(flags.get("raft_pipeline_depth")))
        # and auto mode off pins the configured depth regardless
        flags.set("raft_pipeline_auto", False)
        try:
            with raft._lock:
                raft._rep_rtt = 0.0
                assert raft._effective_depth() == \
                    max(1, int(flags.get("raft_pipeline_depth")))
        finally:
            flags.set("raft_pipeline_auto", True)
