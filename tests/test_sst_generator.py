"""SST generator tests — bulk load path end-to-end.

Mirrors the reference's spark-sstfile-generator + DOWNLOAD/INGEST flow
(SURVEY.md §2.11): offline CSV → partitioned snapshot files → engine
ingest → rows visible to nGQL queries, including the reverse-edge
convention the mutate executors use.
"""
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.tools.sst_generator import SstGenerator, parse_schema


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_storage=1)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def seeded(cluster):
    client = cluster.client()

    def ok(stmt):
        resp = client.execute(stmt)
        assert resp.ok(), f"{stmt}: {resp.error_msg}"
        return resp

    client.ok = ok
    ok("CREATE SPACE bulk(partition_num=4)")
    cluster.refresh_all()
    ok("USE bulk")
    ok("CREATE TAG city(name string, pop int)")
    ok("CREATE EDGE road(km double)")
    cluster.refresh_all()
    return cluster, client


def test_parse_schema_spec():
    s = parse_schema("name:string,age:int,score:double")
    assert [c.name for c in s.columns] == ["name", "age", "score"]


def test_bulk_load_roundtrip(seeded, tmp_path):
    cluster, client = seeded
    mc = cluster.graph_meta_client
    sid = mc.get_space_id_by_name("bulk").value()
    tag_id = mc.get_tag_id(sid, "city").value()
    etype = mc.get_edge_type(sid, "road").value()
    sm = cluster.schema_man
    city = sm.get_tag_schema(sid, tag_id)
    road = sm.get_edge_schema(sid, etype)

    # offline generation from CSVs, using the cluster's real schemas
    vcsv = tmp_path / "cities.csv"
    vcsv.write_text("1,berlin,3600000\n2,paris,2100000\n3,rome,2800000\n")
    ecsv = tmp_path / "roads.csv"
    ecsv.write_text("1,2,1054.1\n2,3,1420.7\n")

    gen = SstGenerator(num_parts=4)
    assert gen.load_vertex_csv(str(vcsv), tag_id, city) == 3
    assert gen.load_edge_csv(str(ecsv), etype, road) == 2
    paths = gen.write(str(tmp_path / "out"))
    assert paths

    # ingest into the running store, then query through nGQL
    node = cluster.storage_nodes[0]
    st = node.kv.ingest(sid, paths)
    assert st.ok(), st.to_string()

    r = client.ok("FETCH PROP ON city 1 YIELD city.name, city.pop")
    assert [list(x) for x in r.rows] == [[1, "berlin", 3600000]]
    r = client.ok("GO FROM 1 OVER road YIELD road._dst, road.km")
    assert [list(x) for x in r.rows] == [[2, 1054.1]]
    # reverse edges landed too
    r = client.ok("GO FROM 3 OVER road REVERSELY YIELD road._dst")
    assert [list(x) for x in r.rows] == [[2]]


def test_per_part_files_sorted(tmp_path):
    schema = parse_schema("x:int")
    gen = SstGenerator(num_parts=4)
    for vid in range(1, 40):
        gen.add_vertex(vid, 10, schema, {"x": vid})
    paths = gen.write(str(tmp_path))
    assert sorted(p.rsplit("/", 1)[1] for p in paths) == \
        ["bulk.part%d.snap" % i for i in range(1, 5)]
    # keys within each file are sorted (engine ingest precondition)
    import struct
    for p in paths:
        data = open(p, "rb").read()
        keys, pos = [], 0
        while pos < len(data):
            kl, vl = struct.unpack_from(">II", data, pos)
            pos += 8
            keys.append(data[pos:pos + kl])
            pos += kl + vl
        assert keys == sorted(keys) and keys


def test_parallel_generation_matches_serial(tmp_path):
    """--workers N must produce the same KV CONTENT as serial generation
    (identity -> value; version timestamps naturally differ) with sorted
    final files — the map/sort/merge equivalent of the reference's
    Spark SST job."""
    import csv as _csv
    import struct
    from nebula_tpu.tools.sst_generator import (_read_frames,
                                                generate_parallel)

    vcsv = tmp_path / "cities.csv"
    ecsv = tmp_path / "roads.csv"
    with open(vcsv, "w", newline="") as f:
        w = _csv.writer(f)
        for i in range(1, 301):
            w.writerow([i, f"c{i}", 1000 + i])
    with open(ecsv, "w", newline="") as f:
        w = _csv.writer(f)
        for i in range(1, 301):
            w.writerow([i, (i % 300) + 1, float(i) / 2])

    serial_dir = tmp_path / "serial"
    par_dir = tmp_path / "par"
    gen = SstGenerator(4)
    gen.load_vertex_csv(str(vcsv), 7, parse_schema("name:string,pop:int"))
    gen.load_edge_csv(str(ecsv), 3, parse_schema("km:double"))
    serial_paths = gen.write(str(serial_dir))
    par_paths, count = generate_parallel(
        str(par_dir), 4,
        [(str(vcsv), 7, "name:string,pop:int")],
        [(str(ecsv), 3, "km:double")], workers=3)
    assert count == gen.count

    def content(paths):
        out = {}
        for p in paths:
            for k, v in _read_frames(p):
                out[k[:-8]] = v      # strip the version suffix
        return out

    assert content(par_paths) == content(serial_paths)
    # final files key-sorted (engine ingest precondition)
    for p in par_paths:
        keys = [k for k, _v in _read_frames(p)]
        assert keys == sorted(keys)
