"""Incremental delta absorption — versioned mirror generations
(docs/durability.md "The generation state machine").

Tiers:
  * randomized absorb-vs-rebuild parity differential — event streams
    mixing inserts / in-place updates / deletes (and, in one stream,
    new-vertex edges that legitimately rebuild) served from ABSORBED
    generations, checked per step against the CPU oracle and at the
    end against the rebuild oracle (mirrors cleared, fresh store
    scan), across packed + int8 layouts and 2/8-way virtual meshes
    (both mesh designs);
  * generation semantics — the published generation is immutable once
    absorbed past (in-flight dispatches finish on the tables they
    captured), read-your-writes ordering holds, and shape signatures
    survive absorption so cached kernels keep serving;
  * delta-budget overflow observability — blowing past
    mirror_delta_max pays an OBSERVABLE rebuild (counter + journaled
    mirror.absorb_failed event), never a silent one.
"""
import threading

import numpy as np
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.flags import flags


def _boot(space="ab", parts=3, n=40):
    flags.set("storage_backend", "tpu")
    c = LocalCluster(num_storage=1, tpu_backend=True)
    cl = c.client()

    def ok(s):
        r = cl.execute(s)
        assert r.ok(), f"{s}: {r.error_msg}"
        return r

    ok(f"CREATE SPACE {space}(partition_num={parts}, replica_factor=1)")
    c.refresh_all()
    ok(f"USE {space}")
    ok("CREATE TAG player(name string, age int)")
    ok("CREATE EDGE follow(degree int)")
    c.refresh_all()
    players = ", ".join(f'{100 + i}:("p{i}", {20 + i})'
                        for i in range(n))
    ok(f"INSERT VERTEX player(name, age) VALUES {players}")
    ok("INSERT EDGE follow(degree) VALUES "
       + ", ".join(f"{100 + i} -> {100 + (i + 1) % n}:({50 + i})"
                   for i in range(n)))
    return c, cl, ok


def _cpu_parity(ok, q):
    r = ok(q)
    flags.set("storage_backend", "cpu")
    try:
        r2 = ok(q)
    finally:
        flags.set("storage_backend", "tpu")
    assert sorted(map(tuple, r.rows)) == sorted(map(tuple, r2.rows)), q
    return sorted(map(tuple, r.rows))


class TestAbsorbDifferential:
    """Randomized event streams: every step must stay bit-exact with
    the CPU loop, the whole stream must cost ZERO full rebuilds, and
    the final absorbed state must equal a from-scratch rebuild."""

    QUERIES = [
        "GO FROM 100, 105, 110 OVER follow "
        "YIELD follow._src, follow._dst, follow.degree",
        "GO 2 STEPS FROM 100 OVER follow YIELD follow._dst",
        "GO 3 STEPS FROM 101, 107 OVER follow YIELD follow._dst",
        "GO FROM 103 OVER follow REVERSELY YIELD follow._dst",
        "GO FROM 100 OVER follow | YIELD COUNT(*)",
        "FIND SHORTEST PATH FROM 100 TO 115 OVER follow UPTO 4 STEPS",
    ]

    @pytest.mark.parametrize("mesh,mesh_mode,packed", [
        (0, "sparse", True),       # single chip, packed default
        (0, "sparse", False),      # single chip, int8 layout
        (2, "sparse", True),       # frontier-sharded mesh design
        (8, "dense", True),        # replicated-frontier mesh design
    ])
    def test_randomized_stream_absorbs_with_parity(self, mesh,
                                                   mesh_mode, packed):
        import random
        c, cl, ok = _boot(space=f"ab{mesh}{int(packed)}")
        saved = {k: flags.get(k) for k in
                 ("tpu_mesh_devices", "tpu_mesh_mode",
                  "tpu_packed_frontier")}
        flags.set("tpu_mesh_devices", mesh)
        flags.set("tpu_mesh_mode", mesh_mode)
        flags.set("tpu_packed_frontier", packed)
        try:
            rt = c.tpu_runtime
            for q in self.QUERIES:
                ok(q)                        # build + warm under mesh
            builds0 = rt.stats["mirror_builds"]
            rng = random.Random(17 + mesh + int(packed))
            live = {(100 + i, 100 + (i + 1) % 40, 0)
                    for i in range(40)}      # (src, dst, rank)
            for step in range(10):
                op = rng.choice(["insert", "insert", "update", "delete"])
                if op == "insert":
                    s, d = rng.randrange(40), rng.randrange(40)
                    r = 1000 + step
                    ok(f"INSERT EDGE follow(degree) VALUES "
                       f"{100 + s} -> {100 + d}@{r}:({200 + step})")
                    live.add((100 + s, 100 + d, r))
                elif op == "update":
                    s, d, r = rng.choice(sorted(live))
                    ok(f"INSERT EDGE follow(degree) VALUES "
                       f"{s} -> {d}@{r}:({900 + step})")
                elif op == "delete" and len(live) > 5:
                    s, d, r = rng.choice(sorted(live))
                    ok(f"DELETE EDGE follow {s} -> {d}@{r}")
                    live.discard((s, d, r))
                q = self.QUERIES[step % len(self.QUERIES)]
                _cpu_parity(ok, q)
            # the whole stream rode absorption: zero O(m) rebuilds
            assert rt.stats["mirror_builds"] == builds0, \
                (builds0, rt.stats["mirror_builds"])
            assert rt.stats["mirror_absorbs"] > 0
            assert rt.stats["mirror_delta_overflow"] == 0
            # rebuild oracle: a from-scratch store scan must serve the
            # exact same rows the absorbed generation does
            final_a = [sorted(map(tuple, ok(q).rows))
                       for q in self.QUERIES]
            with rt._lock:
                rt.mirrors.clear()
            final_b = [sorted(map(tuple, ok(q).rows))
                       for q in self.QUERIES]
            assert final_a == final_b
        finally:
            for k, v in saved.items():
                flags.set(k, v)
            c.stop()

    def test_stream_with_new_vertices_stays_exact(self):
        """New-vertex edges change the vertex plan — those windows pay
        an OBSERVABLE rebuild; every result stays exact throughout."""
        import random
        c, cl, ok = _boot(space="abnv")
        try:
            rt = c.tpu_runtime
            ok(self.QUERIES[0])
            rng = random.Random(23)
            next_vid = 900
            for step in range(8):
                if step % 3 == 2:
                    # edge to a vid with no vertex record: extra_vids
                    ok(f"INSERT EDGE follow(degree) VALUES "
                       f"{100 + rng.randrange(40)} -> {next_vid}:(7)")
                    next_vid += 1
                else:
                    s, d = rng.randrange(40), rng.randrange(40)
                    ok(f"INSERT EDGE follow(degree) VALUES "
                       f"{100 + s} -> {100 + d}@{77 + step}:(9)")
                _cpu_parity(ok, self.QUERIES[step % 4])
            assert rt.stats["mirror_absorbs"] > 0
            assert rt.stats["mirror_absorb_failed"] > 0
        finally:
            c.stop()

    def test_multi_hop_delete_absorbs_without_rebuild(self):
        """Reachability-changing deletes used to force the rebuild for
        multi-hop queries (the overlay could not subtract edges);
        tombstones now fold into the tables at absorb time, so even
        multi-hop traffic keeps serving rebuild-free."""
        c, cl, ok = _boot(space="abdel")
        try:
            rt = c.tpu_runtime
            ok("GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            builds0 = rt.stats["mirror_builds"]
            ok("DELETE EDGE follow 101 -> 102@0")
            rows = _cpu_parity(
                ok, "GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            assert (102,) not in rows, "deleted mid-path edge traversed"
            assert rt.stats["mirror_builds"] == builds0, \
                "a delete must absorb as a tombstone, not rebuild"
            assert rt.stats["mirror_absorbs"] > 0
        finally:
            c.stop()

    def test_reduced_pushdown_serves_from_absorbed_generation(self):
        """The PR 8 gate forced mirror_full for reduced queries under
        a live delta; LIMIT/COUNT pushdown now runs against the
        absorbed generation — correct counts, zero rebuilds."""
        c, cl, ok = _boot(space="abred")
        try:
            rt = c.tpu_runtime
            q = "GO FROM 100 OVER follow | YIELD COUNT(*)"
            ok(q)
            builds0 = rt.stats["mirror_builds"]
            reduced0 = rt.stats.get("go_reduced", 0)
            ok("INSERT EDGE follow(degree) VALUES 100 -> 120@3:(1), "
               "100 -> 121@3:(2)")
            rows = _cpu_parity(ok, q)
            assert rows == [(3,)], rows       # ring edge + 2 fresh
            assert rt.stats["mirror_builds"] == builds0
            assert rt.stats["mirror_absorbs"] > 0
            assert rt.stats.get("go_reduced", 0) > reduced0, \
                "COUNT must still ride the device reduction"
        finally:
            c.stop()


class TestGenerationSemantics:
    def test_absorb_publishes_immutable_generation(self):
        """The old generation's host/device tables stay byte-identical
        after an absorption publishes the next one — in-flight
        dispatches finish on the state they captured — and the shape
        signature survives, so shape-keyed kernels keep serving."""
        c, cl, ok = _boot(space="gen1")
        try:
            rt = c.tpu_runtime
            ok("GO 2 STEPS FROM 100 OVER follow YIELD follow._dst")
            space = next(iter(rt.mirrors))
            m0 = rt.mirrors[space]
            ix0 = rt.ell(m0)
            snap = [a.copy() for a in ix0.bucket_nbr]
            snap_et = [a.copy() for a in ix0.bucket_et]
            g0 = getattr(m0, "generation", 0)
            ok("INSERT EDGE follow(degree) VALUES 100 -> 117@5:(1)")
            rows = set(map(tuple, ok(
                "GO FROM 100 OVER follow YIELD follow._dst").rows))
            assert (117,) in rows            # read-your-writes
            m1 = rt.mirrors[space]
            assert m1 is not m0
            assert m1.generation == g0 + 1
            assert m1._ell is not ix0
            assert m1._ell.shape_sig() == ix0.shape_sig()
            for a, b in zip(ix0.bucket_nbr, snap):
                assert np.array_equal(a, b), \
                    "old generation's host tables mutated in place"
            for a, b in zip(ix0.bucket_et, snap_et):
                assert np.array_equal(a, b)
            # the retired generation still ANSWERS (an in-flight
            # dispatch would): hop over its tables finds the old view
            import jax.numpy as jnp
            from nebula_tpu.tpu import ell as E
            et = rt.sm.to_edge_type(space, "follow").value()
            f0 = ix0.start_frontier([m0.to_dense([100])], B=8)
            out = np.asarray(E.make_batched_go_kernel(
                ix0, 2, (et,))(jnp.asarray(f0), *ix0.kernel_args()))
            assert out[:, 0].any()
        finally:
            c.stop()

    def test_read_your_writes_ordering_under_concurrency(self):
        """A write acked at generation g must be visible to every
        query ADMITTED after g publishes, while concurrent readers
        never observe a half-absorbed table (they see g-1 or g)."""
        c, cl, ok = _boot(space="gen2")
        try:
            ok("GO FROM 100 OVER follow")
            stop = threading.Event()
            errors = []

            def reader():
                g = c.client()
                g.execute("USE gen2")
                while not stop.is_set():
                    r = g.execute("GO FROM 100 OVER follow "
                                  "YIELD follow._dst")
                    if not r.ok():
                        errors.append(r.error_msg)
                        return
                    # either generation is consistent: the ring edge
                    # is ALWAYS there; fresh edges may or may not be
                    if (101,) not in set(map(tuple, r.rows)):
                        errors.append(f"torn read: {r.rows}")
                        return

            ts = [threading.Thread(target=reader) for _ in range(4)]
            for t in ts:
                t.start()
            try:
                for i in range(12):
                    ok(f"INSERT EDGE follow(degree) VALUES "
                       f"100 -> {110 + i}@9:({i})")
                    # acked write -> a query admitted NOW sees it
                    rows = set(map(tuple, ok(
                        "GO FROM 100 OVER follow "
                        "YIELD follow._dst").rows))
                    assert (110 + i,) in rows, (i, rows)
            finally:
                stop.set()
                for t in ts:
                    t.join()
            assert not errors, errors
        finally:
            c.stop()


class TestSlotGrowth:
    """In-place ELL slot growth (ISSUE 13 satellite): degree growth
    past an existing vertex's resident row claims a cap-bucket spare
    (EllIndex.build growth_slack) instead of paying the slot-overflow
    rebuild — narrow scope: existing-vertex extension only."""

    GROW_Q = "GO FROM 117 OVER follow REVERSELY YIELD follow._dst"

    def test_degree_growth_claims_spare_in_place(self):
        c, cl, ok = _boot(space="grow")
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow YIELD follow._dst")
            builds0 = rt.stats["mirror_builds"]
            grows0 = rt.stats["mirror_slot_grows"]
            # vertex 117 holds 2 in-slots (ring fwd + rev) in a D=8
            # row; 9 fresh in-edges in one window overflow it — the
            # spare claim must absorb what used to re-bucket
            ok("INSERT EDGE follow(degree) VALUES "
               + ", ".join(f"{100 + i} -> 117@7:({i})"
                           for i in range(2, 11)))
            rows = _cpu_parity(ok, self.GROW_Q)
            assert len(rows) >= 10
            assert rt.stats["mirror_builds"] == builds0, \
                "degree growth within the slack must absorb, not rebuild"
            assert rt.stats["mirror_slot_grows"] > grows0
            assert rt.stats["mirror_absorbs"] > 0
            # multi-hop + packed paths serve the grown generation
            _cpu_parity(ok, "GO 2 STEPS FROM 116 OVER follow "
                            "YIELD follow._dst")
            # rebuild oracle: a from-scratch scan serves identical rows
            final_a = sorted(map(tuple, ok(self.GROW_Q).rows))
            with rt._lock:
                rt.mirrors.clear()
            assert sorted(map(tuple, ok(self.GROW_Q).rows)) == final_a
        finally:
            c.stop()

    def test_growth_disabled_restores_rebuild(self):
        saved = flags.get("tpu_ell_growth_slack")
        flags.set("tpu_ell_growth_slack", 0)
        c, cl, ok = _boot(space="grow0")
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow YIELD follow._dst")
            builds0 = rt.stats["mirror_builds"]
            ok("INSERT EDGE follow(degree) VALUES "
               + ", ".join(f"{100 + i} -> 117@7:({i})"
                           for i in range(2, 11)))
            rows = _cpu_parity(ok, self.GROW_Q)
            assert len(rows) >= 10
            assert rt.stats["mirror_builds"] > builds0, \
                "slack 0 must restore the slot-overflow rebuild"
            assert rt.stats["mirror_slot_grows"] == 0
        finally:
            flags.set("tpu_ell_growth_slack", saved)
            c.stop()


class TestOverflowObservability:
    def test_delta_overflow_counted_and_journaled(self):
        """A write burst past mirror_delta_max pays the rebuild — and
        says so: tpu.mirror.delta_overflow counts it, the journal
        carries mirror.absorb_failed with the delta-overflow reason,
        and results stay exact."""
        from nebula_tpu.common.events import journal
        c, cl, ok = _boot(space="ovf")
        saved = flags.get("mirror_delta_max")
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow")
            flags.set("mirror_delta_max", 2)
            builds0 = rt.stats["mirror_builds"]
            o0 = rt.stats["mirror_delta_overflow"]
            # 2 edges = 4 stored rows (fwd+rev) > budget 2
            ok("INSERT EDGE follow(degree) VALUES "
               "100 -> 130@1:(1), 100 -> 131@1:(2)")
            rows = _cpu_parity(
                ok, "GO FROM 100 OVER follow YIELD follow._dst")
            assert (130,) in rows and (131,) in rows
            assert rt.stats["mirror_delta_overflow"] > o0
            assert rt.stats["mirror_builds"] > builds0
            evs = [e for e in journal.dump(200)
                   if e["kind"] == "mirror.absorb_failed"]
            assert any(e.get("reason") == "delta-overflow"
                       for e in evs), evs
        finally:
            flags.set("mirror_delta_max", saved)
            c.stop()

    def test_absorb_off_restores_rebuild_per_write(self):
        """mirror_absorb=false is the differential oracle: the same
        write stream pays rebuilds and still serves exact rows."""
        c, cl, ok = _boot(space="aboff")
        saved = flags.get("mirror_absorb")
        try:
            rt = c.tpu_runtime
            ok("GO FROM 100 OVER follow")
            flags.set("mirror_absorb", False)
            builds0 = rt.stats["mirror_builds"]
            ok("INSERT EDGE follow(degree) VALUES 100 -> 125@2:(5)")
            rows = _cpu_parity(
                ok, "GO FROM 100 OVER follow YIELD follow._dst")
            assert (125,) in rows
            assert rt.stats["mirror_builds"] > builds0
        finally:
            flags.set("mirror_absorb", saved)
            c.stop()
