"""nebulaprof — the device flight recorder (docs/observability.md
"The device timeline"):

  * Recorder ring units: wrap at `flight_recorder_size`, newest-first
    dump vs oldest-first export, ring-wrap under concurrent scrape
    (the webservice is threaded; the recorder is process-global),
    deterministic aging through clock.advance_for_tests.
  * Drift fold semantics: a live measurement past its declared bound
    fires the typed tpu.model_drift event ONCE on the transition,
    staying over does not re-fire, returning in-bound re-arms; the
    scrape-time collector publishes the overshoot fraction and
    self-clears to zero (fire-and-clear).
  * chrome_trace is a pure function — the byte-stable golden
    (tests/golden_timeline.json) pins the Perfetto/Chrome-trace
    schema; scripts/ci.sh ships the golden beside the SARIF artifacts.
  * /timeline webservice endpoint (every daemon), plain + ?format=trace.
  * e2e: PROFILE FORMAT=trace returns openable Chrome-trace JSON with
    host spans above device tick rows, SHOW TIMELINE fans out like
    SHOW QUERIES, and a slow continuous rider's slow-log entry anchors
    its [first, last] recorder tick-id window.
"""
import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common import clock, flight
from nebula_tpu.common.events import journal
from nebula_tpu.common.flags import flags
from nebula_tpu.common.stats import PROC_TOKEN, stats
from nebula_tpu.common.tracing import slow_log
from nebula_tpu.webservice import WebService

GOLDEN = Path(__file__).parent / "golden_timeline.json"


# ==================================================== ICI byte model
class TestIciByteModel:
    def test_factors_match_the_static_model(self):
        # docs/static_analysis.md, re-stated for the live path — the
        # same factors meshaudit proves the declared bounds against
        assert flight.ici_exchange_bytes("psum", 1024, 8) == \
            2 * 7 * 1024 // 8
        assert flight.ici_exchange_bytes("all_gather", 1024, 8) == \
            7 * 1024
        for op in ("all_to_all", "reduce_scatter", "psum_scatter",
                   "sharding_constraint"):
            assert flight.ici_exchange_bytes(op, 1024, 8) == \
                7 * 1024 // 8, op
        assert flight.ici_exchange_bytes("ppermute", 1024, 8) == 1024

    def test_single_device_moves_nothing(self):
        for op in ("psum", "all_gather", "all_to_all", "ppermute"):
            assert flight.ici_exchange_bytes(op, 1 << 20, 1) == 0

    def test_collective_rows_shape(self):
        rows = flight.collective_rows(
            [("sharding_constraint", 800), ("psum", 32)], 8)
        assert rows == [{"op": "sharding_constraint", "bytes": 700},
                        {"op": "psum", "bytes": 56}]


# ======================================================= ring units
class TestRecorderRing:
    def test_ring_wraps_at_capacity(self):
        saved = flags.get("flight_recorder_size")
        flags.set("flight_recorder_size", 8)
        r = flight.FlightRecorder()
        try:
            for i in range(20):
                r.note_tick(0, tick=i)
            dump = r.dump(limit=64)
            assert len(dump) == 8
            # newest first, ids monotonic from the 20th note down
            assert [e["id"] for e in dump] == list(range(20, 12, -1))
            assert dump[0]["tick"] == 19
            # export is the oldest-first mirror (trace stitch order)
            exp = r.export()
            assert [e["id"] for e in exp] == list(range(13, 21))
        finally:
            flags.set("flight_recorder_size", saved)

    def test_export_clamped_by_flag(self):
        saved = flags.get("timeline_export_max_ticks")
        flags.set("timeline_export_max_ticks", 4)
        r = flight.FlightRecorder()
        try:
            for i in range(10):
                r.note_dispatch("k", rung=i)
            assert len(r.export()) == 4
            assert len(r.export(limit=2)) == 2      # tighter wins
            assert len(r.export(limit=99)) == 4     # flag caps
        finally:
            flags.set("timeline_export_max_ticks", saved)

    def test_ring_wrap_under_concurrent_scrape(self):
        """Writers wrapping the ring while scrapes run: every scrape's
        tpu.flight.records gauge and every dump snapshot must be
        internally consistent (the webservice is threaded; the
        recorder — like stats — is process-global)."""
        saved = flags.get("flight_recorder_size")
        flags.set("flight_recorder_size", 16)
        rec = flight.recorder
        rec.clear_for_tests()
        stop = threading.Event()
        errors = []

        def writer():
            i = 0
            while not stop.is_set():
                rec.note_tick(i % 3, tick=i)
                rec.note_sharded_dispatch(
                    "unit_wrap_kernel", 8,
                    [("sharding_constraint", 1 << 12)], 1 << 13)
                i += 1

        def scraper():
            try:
                for _ in range(50):
                    rows = {name: v for name, labels, v
                            in stats.gauges() if not labels}
                    n = rows.get("tpu.flight.records")
                    assert n is not None and 0 <= n <= 16, rows
                    dump = rec.dump(limit=32)
                    assert len(dump) <= 16
                    ids = [e["id"] for e in dump]
                    assert ids == sorted(ids, reverse=True), ids
            except Exception as e:    # noqa: BLE001 — surfaced below
                errors.append(e)

        ws = [threading.Thread(target=writer) for _ in range(2)]
        ss = [threading.Thread(target=scraper) for _ in range(3)]
        try:
            for t in ws + ss:
                t.start()
            for t in ss:
                t.join()
        finally:
            stop.set()
            for t in ws:
                t.join()
            flags.set("flight_recorder_size", saved)
            rec.clear_for_tests()
        assert not errors, errors

    def test_clock_advance_ages_records_deterministically(self):
        r = flight.FlightRecorder()
        try:
            a = r.note_tick(0)
            clock.advance_for_tests(2.5)
            b = r.note_timing("ell_go", 10.0, 4096, 0.4)
            recs = {e["id"]: e for e in r.dump()}
            aged = recs[b]["time_us"] - recs[a]["time_us"]
            assert aged >= 2_500_000, aged
            assert aged < 2_600_000, "wall time dwarfed the fake skew?"
        finally:
            clock.reset_for_tests()


# ======================================================= drift folds
class TestDriftFold:
    def _drift_events(self, key):
        return [e for e in journal.dump(limit=500)
                if e["kind"] == "tpu.model_drift" and e.get("key") == key]

    def _gauge(self, axis, key):
        for name, labels, v in stats.gauges():
            if name == f"tpu.model_drift.{axis}" \
                    and labels == (("key", key),):
                return v
        return None

    def test_fires_on_transition_once_then_rearms(self):
        rec = flight.recorder
        rec.clear_for_tests()
        key = "unit_drift_kernel"
        try:
            # in-bound: no cell event, gauge publishes 0.0
            assert rec.fold("ici", key, 80.0, 100.0) is False
            assert not self._drift_events(key)
            assert self._gauge("ici", key) == 0.0
            # the over-bound TRANSITION fires the typed event
            assert rec.fold("ici", key, 160.0, 100.0) is True
            evs = self._drift_events(key)
            assert len(evs) == 1
            assert evs[0]["axis"] == "ici"
            assert evs[0]["live"] == 160.0 and evs[0]["declared"] == 100.0
            # overshoot fraction on the gauge family
            assert self._gauge("ici", key) == pytest.approx(0.6)
            # STAYING over does not re-fire
            assert rec.fold("ici", key, 170.0, 100.0) is False
            assert len(self._drift_events(key)) == 1
            # returning in-bound re-arms and the gauge self-clears —
            # fire-and-clear (the gauge table is re-set every scrape)
            assert rec.fold("ici", key, 50.0, 100.0) is False
            assert self._gauge("ici", key) == 0.0
            assert rec.fold("ici", key, 120.0, 100.0) is True
            assert len(self._drift_events(key)) == 2
        finally:
            rec.clear_for_tests()

    def test_zero_declared_never_fires(self):
        # a kernel with no declared bound can't drift (div-zero guard)
        rec = flight.FlightRecorder()
        assert rec.fold("ici", "unbounded", 1e9, 0.0) is False
        assert rec.drift_cells()["ici/unbounded"]["over"] is False

    def test_sharded_dispatch_records_rows_and_folds(self):
        rec = flight.FlightRecorder()
        rec.note_sharded_dispatch(
            "unit_sharded", 8, [("sharding_constraint", 1 << 13)],
            1 << 13, rung=512)
        (e,) = rec.dump()
        assert e["kernel"] == "unit_sharded" and e["k"] == 8
        assert e["ici"] == [{"op": "sharding_constraint",
                             "bytes": 7 * (1 << 13) // 8}]
        assert e["ici_bytes"] == 7 * (1 << 13) // 8
        assert e["ici_declared"] == 1 << 13
        cell = rec.drift_cells()["ici/unit_sharded"]
        assert cell["over"] is False       # (k-1)/k of the bound


# ============================================== chrome_trace + golden
def _golden_inputs():
    """Fixed inputs for the byte-stable pin: one host span tree with a
    seat marker, one tick with all five pump phases, one sharded
    dispatch, one timing probe, one second-stream tick."""
    tree = {
        "trace_id": "00000000deadbeef",
        "roots": [{
            "name": "graph.query", "start_us": 1000, "duration_us": 900,
            "tags": {"stmt_kind": "GoSentence"},
            "children": [
                {"name": "graph.parse", "start_us": 1010,
                 "duration_us": 40, "tags": {}, "children": []},
                {"name": "graph.executor", "start_us": 1060,
                 "duration_us": 700,
                 "tags": {"executor": "GoExecutor"}, "children": []},
            ],
        }],
    }
    seat = {"lane": 3, "joined_tick": 17, "hops": 2,
            "ending": "left-batch", "timeline": [41, 44]}
    ticks = [
        {"kind": "tick", "stream": 0, "id": 41, "time_us": 1400,
         "dur_us": 260, "join_us": 20, "hop_us": 180, "extract_us": 30,
         "clear_us": 10, "assemble_us": 20, "seats": 2, "joins": 1,
         "leaves": 0, "evictions": 0, "generation": 5},
        {"kind": "dispatch", "kernel": "ell_go_sharded", "id": 42,
         "time_us": 1500, "k": 8, "rung": 1024, "steps": 3,
         "ici_bytes": 917504, "ici_declared": 1048576,
         "ici": [{"op": "sharding_constraint", "bytes": 917504}]},
        {"kind": "timing", "op": "ell_go", "id": 43, "time_us": 1700,
         "wall_us": 120.0, "bytes": 4096, "gbps": 0.034},
        {"kind": "tick", "stream": 1, "id": 44, "time_us": 1900,
         "dur_us": 150, "join_us": 0, "hop_us": 120, "extract_us": 20,
         "clear_us": 0, "assemble_us": 10, "seats": 1},
    ]
    return tree, ticks, seat


class TestChromeTrace:
    def test_golden_is_byte_stable(self):
        """chrome_trace is a PURE function — same inputs, byte-identical
        JSON.  A diff here is a trace-schema change: regenerate with
        `python tests/test_flight.py` and eyeball the golden in
        chrome://tracing before committing (ci.sh ships it as an
        artifact beside the SARIF files)."""
        tree, ticks, seat = _golden_inputs()
        got = json.dumps(flight.chrome_trace(tree=tree, ticks=ticks,
                                             seat=seat),
                         indent=1, sort_keys=True) + "\n"
        assert got == GOLDEN.read_text(), \
            "trace schema drifted from tests/golden_timeline.json"

    def test_structure_host_above_device(self):
        tree, ticks, seat = _golden_inputs()
        trace = flight.chrome_trace(tree=tree, ticks=ticks, seat=seat)
        assert trace["displayTimeUnit"] == "ms"
        ev = trace["traceEvents"]
        # process metadata names both lanes
        meta = {(e["pid"], e["args"]["name"]) for e in ev
                if e["ph"] == "M" and e["name"] == "process_name"}
        assert (1, "host spans") in meta
        assert (2, "nebulaprof device flight recorder") in meta
        # every span in the tree renders as a host "X" slice
        host = {e["name"] for e in ev
                if e["ph"] == "X" and e["pid"] == 1}
        assert host == {"graph.query", "graph.parse", "graph.executor"}
        # the seat instant rides the host lane at the root's start
        seat_ev = [e for e in ev if e["ph"] == "i" and e["pid"] == 1]
        assert seat_ev and seat_ev[0]["ts"] == 1000
        assert seat_ev[0]["args"]["lane"] == 3
        # ticks become stream-thread slices with nested phase slices
        tick_ev = [e for e in ev if e.get("cat") == "tick"]
        assert len(tick_ev) == 2
        t0 = tick_ev[0]
        assert t0["ts"] == 1400 - 260 and t0["dur"] == 260
        phases = [e for e in ev if e.get("cat") == "phase"
                  and e["tid"] == t0["tid"]]
        assert [p["name"] for p in phases] == \
            ["join", "hop", "extract", "clear", "assemble"]
        # phases tile the tick start-to-busy, in pump order
        assert phases[0]["ts"] == t0["ts"]
        for a, b in zip(phases, phases[1:]):
            assert b["ts"] == a["ts"] + a["dur"]
        # dispatches are instant markers on the dispatch thread
        disp = [e for e in ev if e["ph"] == "i" and e["pid"] == 2]
        assert disp and disp[0]["name"] == "ell_go_sharded"
        assert disp[0]["args"]["ici_declared"] == 1048576
        # timing probes are duration slices on the timing thread
        tim = [e for e in ev if e.get("cat") == "timing"]
        assert tim and tim[0]["name"] == "ell_go"
        assert tim[0]["dur"] == 120

    def test_empty_inputs_still_valid(self):
        trace = flight.chrome_trace()
        assert [e["ph"] for e in trace["traceEvents"]] == ["M"] * 4


# ================================================= /timeline endpoint
class TestTimelineEndpoint:
    def test_endpoint_plain_and_trace_formats(self):
        ws = WebService("nebula-graphd", host="127.0.0.1").start()
        base = f"http://127.0.0.1:{ws.port}"
        rid = flight.recorder.note_dispatch("unit_endpoint", rung=64)
        try:
            body = json.load(urllib.request.urlopen(
                f"{base}/timeline", timeout=30))
            mine = [t for t in body["ticks"] if t.get("id") == rid]
            assert mine and mine[0]["kernel"] == "unit_endpoint"
            # newest first, like /events
            times = [t.get("time_us", 0) for t in body["ticks"]]
            assert times == sorted(times, reverse=True)
            # ?format=trace returns an openable Chrome-trace object
            trace = json.load(urllib.request.urlopen(
                f"{base}/timeline?format=trace", timeout=30))
            assert trace["displayTimeUnit"] == "ms"
            assert any(e.get("name") == "unit_endpoint"
                       for e in trace["traceEvents"])
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/timeline?limit=x")
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{base}/timeline?format=trace&trace=nothex")
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{base}/timeline?format=trace&trace=deadbeef")
            assert ei.value.code == 404
        finally:
            ws.stop()


# ============================================================== e2e
@pytest.fixture(scope="module")
def fl():
    c = LocalCluster(num_storage=1, tpu_backend=True)
    g = c.client()

    def ok(stmt):
        r = g.execute(stmt)
        assert r.ok(), f"{stmt}: {r.error_msg}"
        return r

    ok("CREATE SPACE fl(partition_num=3, replica_factor=1)")
    c.refresh_all()
    ok("USE fl")
    ok("CREATE EDGE e(w int)")
    c.refresh_all()
    rng = np.random.default_rng(7)
    pairs = sorted({(int(a), int(b)) for a, b in
                    zip(rng.integers(1, 40, 160),
                        rng.integers(1, 40, 160)) if a != b})
    vals = ", ".join(f"{a} -> {b}:({(a * 31 + b) % 97})"
                     for a, b in pairs)
    ok(f"INSERT EDGE e(w) VALUES {vals}")
    yield c, g, ok
    c.stop()


class TestProfileTraceE2E:
    def test_profile_format_trace_is_openable_chrome_json(self, fl):
        c, g, ok = fl
        r = ok("PROFILE FORMAT=trace GO 2 STEPS FROM 1 OVER e "
               "YIELD e._dst")
        prof = r.profile
        assert prof is not None and prof["displayTimeUnit"] == "ms"
        ev = json.loads(json.dumps(prof))["traceEvents"]   # round-trips
        host = {e["name"] for e in ev
                if e.get("ph") == "X" and e.get("pid") == 1}
        assert {"graph.query", "graph.parse", "graph.executor"} <= host
        # device rows under the host spans: the continuous pump's tick
        # slices (this rider rode a lane batch)
        assert [e for e in ev if e.get("cat") == "tick"], \
            "no device tick rows in the trace"

    def test_plain_profile_still_returns_span_tree(self, fl):
        c, g, ok = fl
        r = ok("PROFILE GO FROM 1 OVER e YIELD e._dst")
        assert r.profile["roots"][0]["name"] == "graph.query"
        assert "critical_path" in r.profile
        r = ok("PROFILE FORMAT=tree GO FROM 1 OVER e YIELD e._dst")
        assert r.profile["roots"][0]["name"] == "graph.query"

    def test_bogus_format_is_a_syntax_error(self, fl):
        c, g, ok = fl
        r = g.execute("PROFILE FORMAT=perfetto GO FROM 1 OVER e")
        assert not r.ok()
        assert "PROFILE FORMAT" in (r.error_msg or "")


class TestShowTimelineE2E:
    def test_shape_ordering_and_count(self, fl):
        c, g, ok = fl
        ok("GO 2 STEPS FROM 2 OVER e YIELD e._dst")     # records exist
        r = ok("SHOW TIMELINE")
        assert r.column_names == ["Host", "Id", "Time(us)", "Kind",
                                  "Source", "Detail"]
        assert r.rows
        times = [row[2] for row in r.rows]
        assert times == sorted(times, reverse=True)      # newest first
        kinds = {row[3] for row in r.rows}
        assert "tick" in kinds
        r5 = ok("SHOW TIMELINE 5")
        assert 0 < len(r5.rows) <= 5
        bad = g.execute("SHOW TIMELINE 0")
        assert not bad.ok()

    def test_metad_fanout_mirrors_show_queries(self, fl):
        c, g, ok = fl
        ok("GO FROM 3 OVER e YIELD e._dst")
        resp = c.meta_service.rpc_showTimeline({"limit": 8})
        assert resp["ticks"], "fan-out returned no recorder rows"
        for t in resp["ticks"]:
            assert t["host"]
        # the graphd-side rpc tags rows with this process' identity so
        # SHOW TIMELINE never double-lists LocalCluster's shared ring
        local = c.graph_service.rpc_listTimeline({"limit": 4})
        assert all(t["proc"] == PROC_TOKEN for t in local["ticks"])


class TestSlowRiderTimelineAnchor:
    def test_slow_log_entry_anchors_recorder_window(self, fl):
        c, g, ok = fl
        saved = flags.get("slow_query_threshold_ms")
        flags.set("slow_query_threshold_ms", 1)
        ok("GO 2 STEPS FROM 1 OVER e")          # stream anchored
        d = c.tpu_runtime.dispatcher
        st = next(iter(d.continuous.streams()))
        st.tick_delay_s = 0.05                  # deliberately slowed
        try:
            ok("GO 4 STEPS FROM 4 OVER e YIELD e._dst")
        finally:
            st.tick_delay_s = 0.0
            flags.set("slow_query_threshold_ms", saved)
        entries = [e for e in slow_log.dump()
                   if "4 STEPS FROM 4" in e["stmt"]]
        assert entries, slow_log.dump()
        e = entries[0]
        # the anchor: [first, last] flight-recorder tick ids for the
        # rider's flight — SHOW TIMELINE (or /timeline) scoped to that
        # id window is the statement's device-side story
        first, last = e["timeline"]
        assert 0 < first <= last
        ids = {t["id"] for t in flight.recorder.dump(limit=1024)}
        assert last in ids, "anchor points past the ring"


if __name__ == "__main__":
    # regenerate the golden after a DELIBERATE trace-schema change:
    #   python tests/test_flight.py
    tree, ticks, seat = _golden_inputs()
    GOLDEN.write_text(json.dumps(
        flight.chrome_trace(tree=tree, ticks=ticks, seat=seat),
        indent=1, sort_keys=True) + "\n")
    print(f"wrote {GOLDEN}")
