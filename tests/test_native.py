"""Native library tests — C++ engine/codec parity with the Python paths.

Mirrors the reference's native-vs-managed parity testing (NebulaCodecTest
for the JNI codec, RocksEngineTest for the engine): every native entry
must agree byte-for-byte / value-for-value with the Python
implementation it accelerates.
"""
import os
import random

import numpy as np
import pytest

from nebula_tpu.codec.rows import RowReader, encode_row
from nebula_tpu.common.keys import KeyUtils
from nebula_tpu.interface.common import ColumnDef, Schema, SupportedType
from nebula_tpu.kvstore.engine import MemEngine
from nebula_tpu.native import available, batch

pytestmark = pytest.mark.skipif(not available(),
                                reason="native lib not built")

SCHEMA = Schema(columns=[
    ColumnDef("flag", SupportedType.BOOL),
    ColumnDef("cnt", SupportedType.INT),
    ColumnDef("name", SupportedType.STRING),
    ColumnDef("score", SupportedType.DOUBLE),
    ColumnDef("ratio", SupportedType.FLOAT),
    ColumnDef("ts", SupportedType.TIMESTAMP),
], version=3)


def make_engine():
    from nebula_tpu.kvstore.native import NativeEngine
    return NativeEngine()


class TestNativeEngine:
    def test_basic_roundtrip(self):
        e = make_engine()
        assert e.get(b"absent") is None
        e.put(b"k1", b"v1")
        assert e.get(b"k1") == b"v1"
        e.put(b"k1", b"v2")
        assert e.get(b"k1") == b"v2"
        e.remove(b"k1")
        assert e.get(b"k1") is None
        assert e.total_keys() == 0

    def test_empty_value_and_binary_keys(self):
        e = make_engine()
        key = bytes([0, 255, 1, 128])
        e.put(key, b"")
        assert e.get(key) == b""
        assert e.total_keys() == 1

    def test_scans_match_memengine(self):
        rng = random.Random(7)
        native, mem = make_engine(), MemEngine()
        kvs = []
        for _ in range(500):
            k = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 12)))
            v = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 9)))
            kvs.append((k, v))
        native.multi_put(kvs)
        mem.multi_put(kvs)
        assert native.total_keys() == mem.total_keys()
        for prefix in (b"", b"\x00", b"\x7f", bytes([255]), b"ab"):
            assert list(native.prefix(prefix)) == list(mem.prefix(prefix))
        assert list(native.range(b"\x10", b"\xe0")) == \
            list(mem.range(b"\x10", b"\xe0"))

    def test_remove_prefix_and_range(self):
        native, mem = make_engine(), MemEngine()
        kvs = [(bytes([i, j]), bytes([i])) for i in range(8)
               for j in range(8)]
        native.multi_put(kvs)
        mem.multi_put(kvs)
        native.remove_prefix(bytes([3]))
        mem.remove_prefix(bytes([3]))
        native.remove_range(bytes([5, 2]), bytes([6, 1]))
        mem.remove_range(bytes([5, 2]), bytes([6, 1]))
        assert list(native.prefix(b"")) == list(mem.prefix(b""))

    def test_remove_prefix_all_ff(self):
        e = make_engine()
        e.put(b"\xff\xff\x01", b"a")
        e.put(b"\xff\xff\xff", b"b")
        e.put(b"\x01", b"keep")
        e.remove_prefix(b"\xff\xff")
        assert list(e.prefix(b"")) == [(b"\x01", b"keep")]

    def test_flush_ingest_interop_with_memengine(self, tmp_path):
        native, mem = make_engine(), MemEngine()
        kvs = [(b"k%03d" % i, b"v%d" % i) for i in range(100)]
        native.multi_put(kvs)
        p1 = str(tmp_path / "native.snap")
        native.flush(p1)
        mem.ingest(p1)
        assert list(mem.prefix(b"")) == kvs
        # and the reverse direction
        mem2 = MemEngine()
        mem2.multi_put(kvs)
        p2 = str(tmp_path / "mem.snap")
        mem2.flush(p2)
        native2 = make_engine()
        native2.ingest(p2)
        assert list(native2.prefix(b"")) == kvs

    def test_ingest_missing_file(self):
        e = make_engine()
        assert not e.ingest("/nonexistent/nope.snap").ok()

    def test_compaction_filter(self):
        from nebula_tpu.kvstore.native import NativeEngine
        e = NativeEngine(compaction_filter=lambda k, v: k.startswith(b"x"))
        e.multi_put([(b"x1", b""), (b"y1", b""), (b"x2", b"")])
        e.compact()
        assert [k for k, _ in e.prefix(b"")] == [b"y1"]


class TestBatchCodec:
    def _rows(self, n=200):
        rng = random.Random(3)
        rows, vals = [], []
        for i in range(n):
            v = {
                "flag": bool(rng.getrandbits(1)),
                "cnt": rng.randrange(-2**40, 2**40),
                "name": f"row-{i}-é{rng.randrange(100)}",
                "score": rng.random() * 1000 - 500,
                "ratio": float(np.float32(rng.random())),
                "ts": rng.randrange(0, 2**33),
            }
            vals.append(v)
            rows.append(encode_row(SCHEMA, v))
        return rows, vals

    def test_decode_field_parity(self):
        rows, vals = self._rows()
        blob, offs, lens = batch.concat_blobs(rows)
        for fi, col in enumerate(SCHEMA.columns):
            res = batch.decode_field(blob, offs, lens, SCHEMA, fi)
            assert res is not None
            assert (res.valid == 1).all()
            for r, v in enumerate(vals):
                expect = v[col.name]
                if col.type == SupportedType.BOOL:
                    assert bool(res.i64[r]) == expect
                elif col.type in (SupportedType.INT, SupportedType.TIMESTAMP):
                    assert int(res.i64[r]) == expect
                elif col.type == SupportedType.STRING:
                    s = res.blob[int(res.str_off[r]):
                                  int(res.str_off[r] + res.str_len[r])]
                    assert s.decode() == expect
                elif col.type == SupportedType.FLOAT:
                    assert res.f64[r] == pytest.approx(expect, rel=1e-6)
                else:
                    assert res.f64[r] == expect

    def test_version_mismatch_flagged(self):
        rows, _ = self._rows(5)
        other = Schema(columns=SCHEMA.columns, version=9)
        mixed = rows[:3] + [encode_row(other, {"cnt": 1})] + rows[3:]
        blob, offs, lens = batch.concat_blobs(mixed)
        res = batch.decode_field(blob, offs, lens, SCHEMA, 1)
        assert res.valid[3] == 2              # wrong version
        assert (np.delete(res.valid, 3) == 1).all()

    def test_older_schema_prefix_row_reads_missing(self):
        short_schema = Schema(columns=SCHEMA.columns[:2], version=3)
        old_row = encode_row(short_schema, {"flag": True, "cnt": 5})
        blob, offs, lens = batch.concat_blobs([old_row])
        res = batch.decode_field(blob, offs, lens, SCHEMA, 2)
        assert res.valid[0] == 0              # missing, like RowReader
        # python reader agrees
        assert RowReader(old_row, SCHEMA).get("name") == ""

    def test_parse_keys_parity(self):
        rng = random.Random(11)
        keys = []
        expect = []
        for _ in range(100):
            if rng.getrandbits(1):
                args = (rng.randrange(1, 100), rng.randrange(-2**62, 2**62),
                        rng.randrange(-500, 500), rng.randrange(0, 2**62))
                keys.append(KeyUtils.vertex_key(*args))
                expect.append(("v",) + args)
            else:
                args = (rng.randrange(1, 100), rng.randrange(-2**62, 2**62),
                        rng.randrange(-500, 500), rng.randrange(-2**30, 2**30),
                        rng.randrange(-2**62, 2**62), rng.randrange(0, 2**62))
                keys.append(KeyUtils.edge_key(*args))
                expect.append(("e",) + args)
        keys.append(b"junk")
        blob, offs, lens = batch.concat_blobs(keys)
        res = batch.parse_keys(blob, offs, lens)
        assert res.kind[-1] == 0
        for i, exp in enumerate(expect):
            if exp[0] == "v":
                assert res.kind[i] == 1
                assert (res.part[i], res.a[i], res.b[i], res.ver[i]) == exp[1:]
            else:
                assert res.kind[i] == 2
                assert (res.part[i], res.a[i], res.b[i], res.c[i],
                        res.d[i], res.ver[i]) == exp[1:]

    def test_split_frames_roundtrip(self):
        from nebula_tpu.kvstore.native import NativeEngine
        e = NativeEngine()
        kvs = [(b"a%02d" % i, b"val%d" % i) for i in range(50)]
        e.multi_put(kvs)
        packed = e.scan_prefix_packed(b"")
        parts = batch.split_frames(packed)
        assert parts is not None
        ko, kl, vo, vl = parts
        got = [(packed[int(o):int(o + l)],
                packed[int(vo[i]):int(vo[i] + vl[i])])
               for i, (o, l) in enumerate(zip(ko, kl))]
        assert got == kvs


class TestNativeEngineInStore:
    def test_store_uses_native_when_auto(self):
        from nebula_tpu.common.flags import flags
        from nebula_tpu.kvstore import KVOptions, MemPartManager, NebulaStore
        from nebula_tpu.kvstore.native import NativeEngine
        pm = MemPartManager()
        kv = NebulaStore(KVOptions(part_man=pm))
        pm.register_handler(kv)
        pm.add_part(1, 1)
        assert isinstance(kv.spaces[1].engines[0], NativeEngine)
        kv.put(1, 1, b"k", b"v")
        got, st = kv.get(1, 1, b"k")
        assert st.ok() and got == b"v"


def test_native_suite_under_asan(tmp_path):
    """Exercise the full native C ABI (engine CRUD/scan/ingest, batch
    codec, ELL builder) under the ASAN+UBSAN build (reference
    ENABLE_ASAN + SanitizerOptions.cpp:8-50 spirit): any heap overflow
    or UB at the ctypes boundary aborts the run.  Runs the lean
    asan_driver.py script, not pytest — the instrumented interpreter is
    too slow for the whole suite."""
    import shutil
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(repo, "native")
    if shutil.which("g++") is None or shutil.which("gcc") is None:
        pytest.skip("no g++/gcc")
    libasan = subprocess.run(
        ["gcc", "-print-file-name=libasan.so"],
        capture_output=True, text=True).stdout.strip()
    if not libasan or not os.path.exists(libasan):
        pytest.skip("no libasan")
    r = subprocess.run(["make", "-C", native, "asan"],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    env = dict(
        os.environ,
        LD_PRELOAD=libasan,
        NEBULA_NATIVE_SO=os.path.join(native, "libnebula_native_asan.so"),
        JAX_PLATFORMS="cpu",
        # reference SanitizerOptions.cpp defaults; leak check off — the
        # Python interpreter itself reports benign leaks at exit
        ASAN_OPTIONS=("strict_init_order=true:"
                      "detect_stack_use_after_return=true:"
                      "detect_container_overflow=true:detect_leaks=0"),
        UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tests", "asan_driver.py"),
         str(tmp_path)],
        capture_output=True, text=True, env=env, cwd=repo, timeout=300)
    assert r.returncode == 0, f"ASAN run failed:\n{r.stdout}\n{r.stderr}"
    assert "ASAN DRIVER OK" in r.stdout
    assert "AddressSanitizer" not in r.stderr, r.stderr


def test_split_rowset_rejects_overflowing_varint():
    """A corrupt row-length varint near 2^64 must fail the split, not
    wrap the bounds check into an out-of-bounds row (review finding)."""
    from nebula_tpu.native import ensure_built
    from nebula_tpu.native.batch import split_rowset
    if not ensure_built():
        import pytest
        pytest.skip("native lib unavailable")
    # uvarint encoding ~2^64-6 (nine 0x80|x bytes + terminator) + junk
    evil = bytes([0xFA, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF,
                  0xFF, 0x01]) + b"abcdef" * 50
    assert split_rowset(evil) is None
    # sane blobs still split
    from nebula_tpu.codec.rows import RowSetWriter, encode_row
    from nebula_tpu.interface.common import ColumnDef, Schema, SupportedType
    sch = Schema(columns=[ColumnDef("x", SupportedType.INT)])
    w = RowSetWriter()
    w.add_row(encode_row(sch, {"x": 5}))
    offs, lens = split_rowset(w.data())
    assert len(offs) == 1
