"""DiskEngine (persistent LSM) + durability seams.

Mirrors the reference's RocksEngine expectations at the KVEngine seam
(RocksEngine.h:94-156): persistence across reopen, ordered scans over
memtable+runs, tombstone shadowing, compaction with a drop filter,
snapshot flush/ingest — plus the raft-WAL-retention contract
(Part.durable_commit_id / RaftPart.cleanup_wal floor) and the
MergeOperator seam (storage/MergeOperator.h equivalent).
"""
import os
import random

import pytest

from nebula_tpu.common.flags import flags
from nebula_tpu.kvstore.disk_engine import DiskEngine
from nebula_tpu.kvstore.engine import MemEngine
from nebula_tpu.kvstore.part import Part
from nebula_tpu.kvstore.store import KVOptions, NebulaStore
from nebula_tpu.kvstore.partman import MemPartManager
from nebula_tpu.interface.common import HostAddr


class TestDiskEngineBasics:
    def test_crud_and_reopen(self, tmp_path):
        d = str(tmp_path / "e")
        e = DiskEngine(d)
        e.put(b"a", b"1")
        e.multi_put([(b"b", b"2"), (b"c", b"3")])
        assert e.get(b"b") == b"2"
        e.remove(b"a")
        assert e.get(b"a") is None
        e.flush_memtable()
        # reopen: state must come back from runs alone
        e2 = DiskEngine(d)
        assert e2.get(b"a") is None
        assert e2.get(b"b") == b"2"
        assert e2.get(b"c") == b"3"

    def test_unflushed_memtable_lost_on_reopen(self, tmp_path):
        """The documented durability model: raft WAL replays what the
        runs don't have (RocksDB-WAL-off deployment)."""
        d = str(tmp_path / "e")
        e = DiskEngine(d)
        e.put(b"k", b"v")
        e2 = DiskEngine(d)          # no flush — simulated crash
        assert e2.get(b"k") is None

    def test_tombstone_shadows_run_across_reopen(self, tmp_path):
        d = str(tmp_path / "e")
        e = DiskEngine(d)
        e.put(b"k", b"v")
        e.flush_memtable()
        e.remove(b"k")
        e.flush_memtable()
        e2 = DiskEngine(d)
        assert e2.get(b"k") is None
        assert list(e2.prefix(b"k")) == []

    def test_newer_run_wins(self, tmp_path):
        e = DiskEngine(str(tmp_path / "e"))
        e.put(b"k", b"old")
        e.flush_memtable()
        e.put(b"k", b"new")
        e.flush_memtable()
        assert e.get(b"k") == b"new"
        assert list(e.prefix(b"k")) == [(b"k", b"new")]

    def test_memtable_shadows_runs(self, tmp_path):
        e = DiskEngine(str(tmp_path / "e"))
        e.put(b"k", b"run")
        e.flush_memtable()
        e.put(b"k", b"mem")
        assert e.get(b"k") == b"mem"

    def test_prefix_range_merge_order(self, tmp_path):
        e = DiskEngine(str(tmp_path / "e"), index_every=2)
        for i in range(0, 100, 2):
            e.put(b"k%03d" % i, b"run")
        e.flush_memtable()
        for i in range(1, 100, 2):
            e.put(b"k%03d" % i, b"mem")
        keys = [k for k, _ in e.prefix(b"k")]
        assert keys == [b"k%03d" % i for i in range(100)]
        sub = list(e.range(b"k010", b"k015"))
        assert [k for k, _ in sub] == [b"k010", b"k011", b"k012",
                                       b"k013", b"k014"]

    def test_auto_flush_on_mem_limit(self, tmp_path):
        e = DiskEngine(str(tmp_path / "e"), mem_limit_bytes=1024)
        for i in range(200):
            e.put(b"key%04d" % i, b"x" * 64)
        assert len(e._runs) >= 1
        assert e.get(b"key0000") == b"x" * 64
        assert e.total_keys() == 200

    def test_remove_prefix_and_range(self, tmp_path):
        e = DiskEngine(str(tmp_path / "e"))
        for i in range(10):
            e.put(b"a%d" % i, b"v")
            e.put(b"b%d" % i, b"v")
        e.flush_memtable()
        e.remove_prefix(b"a")
        e.remove_range(b"b0", b"b5")
        assert list(e.prefix(b"a")) == []
        assert [k for k, _ in e.prefix(b"b")] == \
            [b"b%d" % i for i in range(5, 10)]

    def test_compact_drops_tombstones_and_filtered(self, tmp_path):
        e = DiskEngine(str(tmp_path / "e"),
                       compaction_filter=lambda k, v: k.startswith(b"ttl"))
        e.put(b"keep", b"1")
        e.put(b"ttl1", b"x")
        e.put(b"dead", b"y")
        e.flush_memtable()
        e.remove(b"dead")
        e.compact()
        assert len(e._runs) == 1
        assert e.get(b"keep") == b"1"
        assert e.get(b"ttl1") is None
        assert e.get(b"dead") is None
        # reopen sees compacted state
        e2 = DiskEngine(str(tmp_path / "e"))
        assert e2.get(b"keep") == b"1" and e2.get(b"ttl1") is None

    def test_flush_and_ingest_roundtrip(self, tmp_path):
        e = DiskEngine(str(tmp_path / "e"))
        for i in range(20):
            e.put(b"k%02d" % i, b"v%d" % i)
        snap = str(tmp_path / "snap")
        e.flush(snap)
        e2 = DiskEngine(str(tmp_path / "e2"))
        e2.put(b"k05", b"shadowed")     # ingest must win over memtable
        assert e2.ingest(snap).ok()
        assert e2.get(b"k05") == b"v5"
        assert e2.total_keys() == 20

    def test_ingest_unsorted_file(self, tmp_path):
        mem = MemEngine()
        # MemEngine.flush writes sorted; build an unsorted file by hand
        import struct
        frame = struct.Struct(">II")
        path = str(tmp_path / "unsorted")
        with open(path, "wb") as f:
            for k, v in [(b"z", b"1"), (b"a", b"2"), (b"z", b"3")]:
                f.write(frame.pack(len(k), len(v)))
                f.write(k)
                f.write(v)
        e = DiskEngine(str(tmp_path / "e"))
        assert e.ingest(path).ok()
        assert e.get(b"a") == b"2"
        assert e.get(b"z") == b"3"      # last occurrence wins

    def test_get_durable_reads_runs_only(self, tmp_path):
        e = DiskEngine(str(tmp_path / "e"))
        e.put(b"k", b"flushed")
        e.flush_memtable()
        e.put(b"k", b"volatile")
        assert e.get(b"k") == b"volatile"
        assert e.get_durable(b"k") == b"flushed"


class TestDiskVsMemEquivalence:
    """Randomized op sequence: DiskEngine (with aggressive auto-flush)
    must match MemEngine on every read."""

    def test_fuzz(self, tmp_path):
        rng = random.Random(7)
        disk = DiskEngine(str(tmp_path / "e"), mem_limit_bytes=512,
                          index_every=4)
        mem = MemEngine()
        keys = [b"key%02d" % i for i in range(30)]
        for step in range(600):
            op = rng.random()
            k = rng.choice(keys)
            if op < 0.5:
                v = b"v%d" % step
                disk.put(k, v)
                mem.put(k, v)
            elif op < 0.7:
                disk.remove(k)
                mem.remove(k)
            elif op < 0.8:
                p = k[:4]
                disk.remove_prefix(p)
                mem.remove_prefix(p)
            elif op < 0.9:
                assert disk.get(k) == mem.get(k)
            else:
                assert list(disk.prefix(b"key1")) == \
                    list(mem.prefix(b"key1"))
        assert list(disk.prefix(b"")) == list(mem.prefix(b""))
        # and across a reopen after a clean close (manifests are
        # single-owner: close() quiesces the background compactor the
        # way RocksDB Close() does before a reopen)
        disk.close()
        disk2 = DiskEngine(str(tmp_path / "e"))
        assert list(disk2.prefix(b"")) == list(mem.prefix(b""))


class TestStoreWiring:
    def _store(self, tmp_path, merge_op=None):
        pm = MemPartManager()
        host = HostAddr("127.0.0.1", 44500)
        pm.add_part(1, 1, [host])
        st = NebulaStore(KVOptions(part_man=pm,
                                   data_paths=[str(tmp_path / "data")],
                                   merge_op=merge_op),
                         local_host=host)
        st.init()
        return st

    def test_data_path_gets_disk_engine(self, tmp_path):
        st = self._store(tmp_path)
        assert isinstance(st.spaces[1].engines[0], DiskEngine)
        assert st.multi_put(1, 1, [(b"a", b"1")]).ok()
        assert st.get(1, 1, b"a")[0] == b"1"

    def test_merge_operator_seam(self, tmp_path):
        st = self._store(
            tmp_path,
            merge_op=lambda cur, operand: (cur or b"") + operand)
        assert st.merge(1, 1, b"m", b"ab").ok()
        assert st.merge(1, 1, b"m", b"cd").ok()
        assert st.get(1, 1, b"m")[0] == b"abcd"

    def test_merge_without_operator_errors(self, tmp_path):
        st = self._store(tmp_path)
        assert not st.merge(1, 1, b"m", b"x").ok()


class TestWalSync:
    def test_wal_sync_flag_fsyncs(self, tmp_path, monkeypatch):
        from nebula_tpu.kvstore.wal import FileBasedWal
        calls = []
        real_fsync = os.fsync
        monkeypatch.setattr(os, "fsync",
                            lambda fd: (calls.append(fd), real_fsync(fd)))
        prev = flags.get("wal_sync")
        flags.set("wal_sync", True)
        try:
            w = FileBasedWal(str(tmp_path / "wal"))
            w.append_log(1, 1, b"x")
            w.flush()
            assert calls, "wal_sync=true must fsync on flush"
            n = len(calls)
            flags.set("wal_sync", False)
            w.append_log(2, 1, b"y")
            w.flush()
            assert len(calls) == n, "wal_sync=false must not fsync"
        finally:
            flags.set("wal_sync", prev)
            w.close()


def test_kill9_storaged_recovers_acked_writes(tmp_path):
    """The VERDICT round-1 durability criterion: boot the real
    3-process cluster on disk engines, write through graphd, kill -9
    both storaged and metad mid-flight, restart them, and every acked
    write must still answer.  (Acked = raft-quorum committed; the WAL
    flush-to-OS before each ack is what survives SIGKILL.)"""
    import json
    import signal
    import subprocess
    import time
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ,
               NEBULA_HOME=repo,
               NEBULA_DATA=str(tmp_path / "data"),
               NEBULA_LOGS=str(tmp_path / "logs"),
               JAX_PLATFORMS="cpu",
               META_PORT="45621", STORAGE_PORT="44621", GRAPH_PORT="3821",
               EXTRA_FLAGS="--flag load_data_interval_secs=1 "
                           "--flag wal_sync=true")
    sh = os.path.join(repo, "scripts", "services.sh")

    def run_sh(*argv, timeout=420):
        with open(tmp_path / "sh.log", "a") as lf:
            p = subprocess.Popen(["bash", sh, *argv], env=env,
                                 stdout=lf, stderr=lf,
                                 stdin=subprocess.DEVNULL)
            assert p.wait(timeout=timeout) == 0, \
                (tmp_path / "sh.log").read_text()

    # sweep leaked daemons from previous timed-out runs
    ps = subprocess.run(["ps", "-eo", "pid,args"], capture_output=True,
                        text=True).stdout
    for line in ps.splitlines():
        if "nebula_tpu.daemons" in line and ("45621" in line
                                             or "44621" in line
                                             or "3821" in line):
            try:
                os.kill(int(line.split()[0]), signal.SIGKILL)
            except (ProcessLookupError, ValueError, PermissionError):
                pass

    run_sh("start", "all")
    try:
        from nebula_tpu.clients.graph_client import GraphClient
        from nebula_tpu.interface.common import HostAddr
        from nebula_tpu.interface.rpc import ClientManager
        c = GraphClient(HostAddr("127.0.0.1", 3821),
                        client_manager=ClientManager())
        deadline = time.time() + 30
        while time.time() < deadline:
            if c.connect().ok():
                break
            time.sleep(0.5)
        assert c.execute("CREATE SPACE IF NOT EXISTS "
                         "dur(partition_num=2, replica_factor=1)").ok()
        time.sleep(2.5)
        assert c.execute("USE dur; CREATE EDGE e(w int)").ok()
        time.sleep(2.5)
        acked = []
        for i in range(50):
            r = c.execute(f"USE dur; INSERT EDGE e(w) VALUES 1->{i + 10}:({i})")
            assert r.ok(), r.error_msg
            acked.append(i + 10)

        # SIGKILL storaged AND metad mid-life (no graceful shutdown)
        for name in ("storaged", "metad"):
            pid = int((tmp_path / "data" / f"nebula-{name}.pid").read_text())
            os.kill(pid, signal.SIGKILL)
        time.sleep(1)
        run_sh("start", "metad")
        run_sh("start", "storaged")
        # storaged re-registers + graphd cache refreshes (1s interval)
        time.sleep(6)

        # generous window: restarted storaged cold-starts jax + rebuilds
        # the CSR mirror on its first device query, and each timed-out
        # RPC attempt burns its full 30s budget
        r = None
        deadline = time.time() + 180
        while time.time() < deadline:
            r = c.execute("USE dur; GO FROM 1 OVER e YIELD e._dst")
            if r.ok() and len(r.rows) == len(acked):
                break
            time.sleep(1)
        assert r is not None and r.ok(), getattr(r, "error_msg", "no resp")
        assert sorted(x[0] for x in r.rows) == sorted(acked), \
            f"lost {set(acked) - {x[0] for x in r.rows}}"
    finally:
        with open(tmp_path / "stop.log", "w") as lf:
            subprocess.Popen(["bash", sh, "stop", "all"], env=env,
                             stdout=lf, stderr=lf,
                             stdin=subprocess.DEVNULL).wait(timeout=60)


class TestKillMidCompaction:
    """Kill-anywhere atomicity of the compaction commit point
    (docs/durability.md): a SIGKILL landing between the merged run's
    sstable write and the MANIFEST replace must recover to the
    PRE-compaction view — the orphan run is swept, nothing is lost,
    nothing half-applies.  Extends the torn-frame ingest guards; the
    real-SIGKILL companion lives in tests/test_proc_chaos.py."""

    def _seed(self, d, runs=4):
        e = DiskEngine(d, mem_limit_bytes=1 << 30,
                       compact_after_runs=1 << 30)   # manual control
        for r in range(runs):
            for i in range(25):
                e.put(b"k%03d" % (r * 25 + i), b"v%d" % r)
            e.put(b"shadow", b"gen%d" % r)           # rewritten each run
            e.flush_memtable()
        e.remove(b"k000")                            # a tombstone too
        e.flush_memtable()
        return e

    def test_die_between_run_write_and_manifest_commit(self, tmp_path):
        d = str(tmp_path / "e")
        e = self._seed(d)
        n_runs = len(e._runs)
        assert n_runs >= 5
        before = dict(e._merged(b""))

        # the compaction's merged run hits disk exactly like
        # _compact_offline writes it — then the process "dies" before
        # _commit_manifest: the run file exists, the MANIFEST does not
        # reference it
        def survivors():
            from nebula_tpu.kvstore.disk_engine import (_TOMBSTONE,
                                                        _merge_sources)
            sources = [r.scan(b"") for r in reversed(e._runs)]
            for k, v in _merge_sources(sources):
                if v is _TOMBSTONE:
                    continue
                yield k, v

        orphan = e._write_run(survivors())
        assert orphan is not None
        orphan_name = os.path.basename(orphan.path)
        assert os.path.exists(orphan.path)
        del orphan          # close the fd — the "killed" process's view

        # reopen the directory (the restart): pre-compaction view,
        # orphan swept
        e2 = DiskEngine(d)
        assert dict(e2._merged(b"")) == before
        assert e2.get(b"k000") is None               # tombstone honored
        assert e2.get(b"shadow") == b"gen3"          # newest run wins
        assert not os.path.exists(os.path.join(d, orphan_name)), \
            "orphan compaction run not swept on recovery"
        listed = sorted(os.path.basename(r.path) for r in e2._runs)
        assert orphan_name not in listed
        e2.close()

    def test_committed_compaction_survives_reopen(self, tmp_path):
        """Control cell: the same sequence WITH the manifest commit
        recovers to the post-compaction view."""
        d = str(tmp_path / "e")
        e = self._seed(d)
        before = dict(e._merged(b""))
        assert e.compact().ok()
        assert len(e._runs) == 1
        e.close()
        e2 = DiskEngine(d)
        assert len(e2._runs) == 1
        assert dict(e2._merged(b"")) == before
        e2.close()


class TestBatchAtomicity:
    def test_auto_compaction_bounds_run_count(self, tmp_path):
        # compaction runs on a BACKGROUND thread (the flush happens on
        # the raft commit path; an inline O(dataset) merge there stalls
        # heartbeats into election timeouts) — so the bound is eventual
        import time
        e = DiskEngine(str(tmp_path / "e"), compact_after_runs=4)
        for i in range(20):
            e.put(b"k%02d" % i, b"v")
            e.flush_memtable()
        deadline = time.time() + 10
        while time.time() < deadline and len(e._runs) >= 4:
            time.sleep(0.01)
        assert len(e._runs) < 4
        assert e.total_keys() == 20
        # reads racing the compaction's file deletion must keep working
        # (runs hold their descriptors open)
        assert e.get(b"k00") == b"v"

    def test_reads_survive_concurrent_compaction(self, tmp_path):
        """A scan that captured its run snapshot before a compaction
        deletes those files must complete from the open descriptors
        (ADVICE round 2: FileNotFoundError on the serving path)."""
        e = DiskEngine(str(tmp_path / "e"), compact_after_runs=1000)
        for i in range(8):
            for j in range(50):
                e.put(b"k%03d" % (i * 50 + j), b"v%d" % i)
            e.flush_memtable()
        it = e.range(b"k", b"l")          # lazy: captures run snapshot
        first = next(it)
        assert first[0] == b"k000"
        e.compact()                       # unlinks every captured file
        rest = list(it)                   # must stream from open fds
        assert len(rest) == 8 * 50 - 1

    def test_ingest_rejects_torn_file(self, tmp_path):
        """A truncated snapshot must fail the ingest with an error, not
        silently load garbage keys (ADVICE round 2)."""
        e = DiskEngine(str(tmp_path / "e"))
        e.put(b"a", b"1")
        snap = str(tmp_path / "snap")
        e.flush(snap)
        with open(snap, "ab") as f:       # torn frame: header, short key
            import struct
            f.write(struct.pack(">II", 100, 5))
            f.write(b"short")
        e2 = DiskEngine(str(tmp_path / "e2"))
        st = e2.ingest(snap)
        assert not st.ok()
        assert e2.total_keys() == 0

    def test_write_batch_suppresses_flush_boundary(self, tmp_path):
        e = DiskEngine(str(tmp_path / "e"), mem_limit_bytes=64)
        with e.write_batch():
            e.put(b"big", b"x" * 256)     # over limit — must NOT flush yet
            assert len(e._runs) == 0
            e.put(b"mark", b"m")
        assert len(e._runs) == 1          # one run holding BOTH keys
        e2 = DiskEngine(str(tmp_path / "e"))
        assert e2.get(b"big") == b"x" * 256 and e2.get(b"mark") == b"m"

    def test_merge_replay_exactly_once_across_crash(self, tmp_path):
        """The watermark is batched with the ops it covers, so crash
        replay applies a non-idempotent merge exactly once."""
        import struct
        count_op = lambda cur, operand: struct.pack(
            ">q", struct.unpack(">q", cur or b"\0" * 8)[0]
            + struct.unpack(">q", operand)[0])

        def make_part(d):
            eng = DiskEngine(d, mem_limit_bytes=64)   # flush mid-batch
            return Part(1, 1, eng, merge_op=count_op), eng

        part, eng = make_part(str(tmp_path / "e"))
        ops = []
        # build one committed batch: big put (crosses mem limit) + merge
        from nebula_tpu.kvstore.log_encoder import (LogOp, encode_multi,
                                                    encode_single)
        logs = [
            (1, encode_single(LogOp.OP_PUT, b"pad", b"x" * 256)),
            (2, encode_single(LogOp.OP_MERGE, b"ctr", struct.pack(">q", 5))),
        ]
        part._apply(logs, log_id=2, term=1)
        assert struct.unpack(">q", eng.get(b"ctr"))[0] == 5
        # crash: reopen from runs only (memtable dropped)
        part2, eng2 = make_part(str(tmp_path / "e"))
        durable = part2.durable_commit_id()
        if durable < 2:
            # replay the suffix the WAL would re-deliver
            part2._apply(logs[durable:], log_id=2, term=1)
        assert struct.unpack(">q", eng2.get(b"ctr"))[0] == 5, \
            "merge must not double-apply on replay"

    def test_merge_without_op_refuses(self, tmp_path):
        from nebula_tpu.kvstore.log_encoder import LogOp, encode_single
        part = Part(1, 1, DiskEngine(str(tmp_path / "e")))
        import struct
        with pytest.raises(RuntimeError):
            part._apply([(1, encode_single(LogOp.OP_MERGE, b"k", b"v"))],
                        log_id=1, term=1)


def test_compact_single_run_applies_filter_and_tombstones(tmp_path):
    """compact() must rewrite even a SINGLE run: tombstones and
    filter-rejected (TTL-expired) rows hide nowhere else."""
    doomed = set()
    e = DiskEngine(str(tmp_path / "e"),
                   compaction_filter=lambda k, v: k in doomed)
    for i in range(10):
        e.put(b"k%d" % i, b"v")
    e.remove(b"k3")
    e.compact()                      # single merged run incl. tombstone
    assert len(e._runs) == 1
    doomed.add(b"k5")
    e.compact()                      # single-run input: must still drop
    keys = [k for k, _ in e.prefix(b"")]
    assert b"k5" not in keys and b"k3" not in keys
    assert len(keys) == 8
