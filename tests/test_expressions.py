"""Expression engine tests — modeled on the reference's
ExpressionTest.cpp (eval + encode/decode roundtrip, SURVEY.md §4)."""
import pytest

from nebula_tpu.filter import (AliasPropExpr, ArithmeticExpr, DestPropExpr,
                               EdgeDstIdExpr, EdgeRankExpr, ExprContext,
                               ExprError, FunctionCallExpr, FunctionManager,
                               InputPropExpr, LogicalExpr, PrimaryExpr,
                               RelationalExpr, SourcePropExpr, TypeCastingExpr,
                               UnaryExpr, VariablePropExpr, decode_expr,
                               encode_expr)


def lit(v):
    return PrimaryExpr(v)


def ctx_with(src=None, edge=None, inp=None, var=None, dst=None):
    c = ExprContext()
    if src is not None:
        c.get_src_tag_prop = lambda tag, prop: src[(tag, prop)]
    if edge is not None:
        c.get_alias_prop = lambda alias, prop: edge[prop]
    if inp is not None:
        c.get_input_prop = lambda prop: inp[prop]
    if var is not None:
        c.get_variable_prop = lambda v, p: var[(v, p)]
    if dst is not None:
        c.get_dst_tag_prop = lambda tag, prop: dst[(tag, prop)]
    return c


class TestArithmetic:
    def test_int_ops(self):
        c = ExprContext()
        assert ArithmeticExpr("+", lit(2), lit(3)).eval(c) == 5
        assert ArithmeticExpr("-", lit(2), lit(3)).eval(c) == -1
        assert ArithmeticExpr("*", lit(4), lit(3)).eval(c) == 12
        assert ArithmeticExpr("/", lit(7), lit(2)).eval(c) == 3
        assert ArithmeticExpr("/", lit(-7), lit(2)).eval(c) == -3  # C trunc
        assert ArithmeticExpr("%", lit(7), lit(3)).eval(c) == 1
        assert ArithmeticExpr("%", lit(-7), lit(3)).eval(c) == -1
        assert ArithmeticExpr("^", lit(6), lit(3)).eval(c) == 5

    def test_mixed_promotion(self):
        c = ExprContext()
        assert ArithmeticExpr("+", lit(1), lit(2.5)).eval(c) == 3.5
        assert ArithmeticExpr("/", lit(7), lit(2.0)).eval(c) == 3.5

    def test_string_concat(self):
        c = ExprContext()
        assert ArithmeticExpr("+", lit("a"), lit("b")).eval(c) == "ab"
        assert ArithmeticExpr("+", lit("n"), lit(1)).eval(c) == "n1"

    def test_division_by_zero(self):
        with pytest.raises(ExprError):
            ArithmeticExpr("/", lit(1), lit(0)).eval(ExprContext())
        with pytest.raises(ExprError):
            ArithmeticExpr("%", lit(1), lit(0)).eval(ExprContext())

    def test_bool_not_numeric(self):
        with pytest.raises(ExprError):
            ArithmeticExpr("-", lit(True), lit(1)).eval(ExprContext())


class TestRelationalLogical:
    def test_compare(self):
        c = ExprContext()
        assert RelationalExpr("<", lit(1), lit(2)).eval(c)
        assert RelationalExpr(">=", lit(2.0), lit(2)).eval(c)
        assert RelationalExpr("==", lit("x"), lit("x")).eval(c)
        assert RelationalExpr("!=", lit("x"), lit(1)).eval(c)  # mixed types
        assert not RelationalExpr("==", lit("x"), lit(1)).eval(c)

    def test_mixed_order_compare_raises(self):
        with pytest.raises(ExprError):
            RelationalExpr("<", lit("x"), lit(1)).eval(ExprContext())

    def test_logical_short_circuit(self):
        c = ExprContext()
        # right side would raise (unbound $-), so && must short-circuit
        bad = InputPropExpr("x")
        assert not LogicalExpr("&&", lit(False), bad).eval(c)
        assert LogicalExpr("||", lit(True), bad).eval(c)
        assert LogicalExpr("&&", lit(True), lit(1)).eval(c)

    def test_unary(self):
        c = ExprContext()
        assert UnaryExpr("!", lit(False)).eval(c) is True
        assert UnaryExpr("-", lit(5)).eval(c) == -5
        assert UnaryExpr("+", lit(5)).eval(c) == 5


class TestCasting:
    def test_casts(self):
        c = ExprContext()
        assert TypeCastingExpr("int", lit("42")).eval(c) == 42
        assert TypeCastingExpr("double", lit(2)).eval(c) == 2.0
        assert TypeCastingExpr("string", lit(True)).eval(c) == "true"
        assert TypeCastingExpr("bool", lit(0)).eval(c) is False

    def test_bad_cast(self):
        with pytest.raises(ExprError):
            TypeCastingExpr("int", lit("abc")).eval(ExprContext())


class TestPropertyRefs:
    def test_all_getters(self):
        c = ctx_with(src={("player", "age"): 42},
                     edge={"degree": 7},
                     inp={"name": "Tim"},
                     var={("v1", "x"): 3},
                     dst={("team", "name"): "Spurs"})
        assert SourcePropExpr("player", "age").eval(c) == 42
        assert AliasPropExpr("follow", "degree").eval(c) == 7
        assert InputPropExpr("name").eval(c) == "Tim"
        assert VariablePropExpr("v1", "x").eval(c) == 3
        assert DestPropExpr("team", "name").eval(c) == "Spurs"

    def test_unbound_getter_raises(self):
        with pytest.raises(ExprError):
            SourcePropExpr("t", "p").eval(ExprContext())

    def test_prepare_alias_check(self):
        c = ExprContext()
        c.aliases = {"follow": True}
        AliasPropExpr("follow", "x").prepare(c)
        with pytest.raises(ExprError):
            AliasPropExpr("like", "x").prepare(c)


class TestFunctions:
    def test_math(self):
        c = ExprContext()
        assert FunctionCallExpr("abs", [lit(-3)]).eval(c) == 3
        assert FunctionCallExpr("pow", [lit(2), lit(10)]).eval(c) == 1024
        assert FunctionCallExpr("floor", [lit(2.7)]).eval(c) == 2

    def test_hash_deterministic(self):
        c = ExprContext()
        h1 = FunctionCallExpr("hash", [lit("abc")]).eval(c)
        h2 = FunctionCallExpr("hash", [lit("abc")]).eval(c)
        assert h1 == h2
        assert isinstance(h1, int)

    def test_strcasecmp(self):
        c = ExprContext()
        assert FunctionCallExpr("strcasecmp", [lit("ABC"), lit("abc")]).eval(c) == 0

    def test_arity_checked_at_prepare(self):
        with pytest.raises(ExprError):
            FunctionCallExpr("abs", []).prepare(ExprContext())
        with pytest.raises(ExprError):
            FunctionCallExpr("nosuchfn", [lit(1)]).prepare(ExprContext())
        assert FunctionManager.exists("now")


class TestCodec:
    def test_roundtrip_complex(self):
        # ($^.player.age > 30 && follow.degree < 5.0) || $-.name == "x"
        expr = LogicalExpr(
            "||",
            LogicalExpr(
                "&&",
                RelationalExpr(">", SourcePropExpr("player", "age"), lit(30)),
                RelationalExpr("<", AliasPropExpr("follow", "degree"), lit(5.0))),
            RelationalExpr("==", InputPropExpr("name"), lit("x")))
        data = encode_expr(expr)
        back = decode_expr(data)
        assert back == expr
        c = ctx_with(src={("player", "age"): 35}, edge={"degree": 3.0},
                     inp={"name": "y"})
        assert back.eval(c) is True

    def test_roundtrip_pseudo_and_fn(self):
        expr = RelationalExpr("==", EdgeDstIdExpr("follow"),
                              FunctionCallExpr("abs", [lit(-5)]))
        back = decode_expr(encode_expr(expr))
        c = ExprContext()
        c.get_edge_dst_id = lambda alias: 5
        assert back.eval(c) is True

    def test_corrupt_rejected(self):
        with pytest.raises(ExprError):
            decode_expr(b"\x93\x01\x02")
        with pytest.raises(ExprError):
            decode_expr(b"garbage-not-msgpack\xff")
