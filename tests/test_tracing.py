"""nebulatrace tests — span mechanics, fake-clock determinism, RPC
propagation (loopback + TCP envelope), the /traces endpoint, PROFILE /
EXPLAIN statements, the slow-query log, and the tracing-disabled
overhead guard on RpcChannel.call (tier-1 acceptance:
docs/observability.md)."""
import json
import tracemalloc
import urllib.error
import urllib.request

import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common import clock, tracing
from nebula_tpu.common.flags import flags
from nebula_tpu.common.tracing import slow_log, trace_store
from nebula_tpu.interface.common import HostAddr
from nebula_tpu.interface.rpc import LoopbackChannel, RpcChannel, RpcServer


@pytest.fixture(autouse=True)
def _clean_tracing():
    trace_store.clear_for_tests()
    slow_log.clear_for_tests()
    yield
    clock.reset_for_tests()
    trace_store.clear_for_tests()
    slow_log.clear_for_tests()
    assert tracing.current_context() is None, \
        "a span leaked thread-local trace context"


def _names(tree, out=None):
    out = out if out is not None else set()
    for root in tree["roots"]:
        _walk(root, out)
    return out


def _walk(node, out):
    out.add(node["name"])
    for child in node["children"]:
        _walk(child, out)


# ================================================================ spans
class TestSpanMechanics:
    def test_disabled_is_shared_noop(self):
        assert tracing.span("rpc.client") is tracing._NOOP
        assert tracing.start_trace("graph.query") is tracing._NOOP
        with tracing.span("rpc.client") as s:
            assert s is None
        assert trace_store.summaries() == []

    def test_forced_trace_nests_and_tags(self):
        with tracing.start_trace("graph.query", forced=True) as root:
            with tracing.span("graph.parse", stmt="GO") as child:
                child.tag(tokens=7)
        tree = trace_store.tree(root.trace_id)
        assert len(tree["roots"]) == 1
        r = tree["roots"][0]
        assert r["name"] == "graph.query"
        assert [c["name"] for c in r["children"]] == ["graph.parse"]
        assert r["children"][0]["tags"] == {"stmt": "GO", "tokens": 7}

    def test_exception_tags_error_and_propagates(self):
        with pytest.raises(ValueError):
            with tracing.start_trace("graph.query", forced=True) as root:
                with tracing.span("graph.executor"):
                    raise ValueError("boom")
        tree = trace_store.tree(root.trace_id)
        child = tree["roots"][0]["children"][0]
        assert "ValueError" in child["tags"]["error"]

    def test_sample_rate_one_samples(self):
        saved = flags.get("trace_sample_rate")
        flags.set("trace_sample_rate", 1.0)
        try:
            with tracing.start_trace("graph.query") as root:
                assert root is not None
        finally:
            flags.set("trace_sample_rate", saved)
        assert trace_store.tree(root.trace_id) is not None

    def test_fake_clock_advances_span_duration(self):
        """Satellite: spans ride clock.Duration plus the fake-clock
        offset — advance_for_tests ages a span deterministically."""
        with tracing.start_trace("graph.query", forced=True) as root:
            clock.advance_for_tests(2.5)
        clock.reset_for_tests()
        tree = trace_store.tree(root.trace_id)
        dur = tree["roots"][0]["duration_us"]
        assert 2_500_000 <= dur < 3_000_000

    def test_inflight_trace_pinned_against_ring_pressure(self):
        """A slow traced query must not come back gutted: while its
        root is open the trace cannot be evicted, however many other
        traces land in the ring."""
        saved = flags.get("trace_buffer_size")
        flags.set("trace_buffer_size", 2)
        try:
            with tracing.start_trace("graph.query", forced=True) as root:
                with tracing.span("graph.parse"):
                    pass
                for _ in range(6):   # flood the ring while in flight
                    with tracing.start_trace("graph.query",
                                             forced=True):
                        pass
            tree = trace_store.tree(root.trace_id)
            assert tree is not None and len(tree["roots"]) == 1
            assert [c["name"] for c in tree["roots"][0]["children"]] \
                == ["graph.parse"]
        finally:
            flags.set("trace_buffer_size", saved)

    def test_late_span_never_evicts_its_own_fresh_trace(self):
        """cap=1 with a pinned in-flight trace: a late span for an
        already-evicted trace re-creates its entry, and the victim
        search must not pick that fresh entry (KeyError otherwise)."""
        saved = flags.get("trace_buffer_size")
        flags.set("trace_buffer_size", 1)
        try:
            with tracing.start_trace("graph.query", forced=True) as old:
                pass                      # completed trace in the ring
            with tracing.start_trace("graph.query",
                                     forced=True) as live:
                # live is pinned; a LATE span for the old trace arrives
                # (the pipelined-finish shape) — must not crash
                trace_store.record(
                    {"trace_id": old.trace_id, "span_id": 42,
                     "parent_id": old.span_id, "name": "tpu.fetch",
                     "start_us": 0, "duration_us": 1, "tags": {}})
            assert trace_store.tree(live.trace_id) is not None
        finally:
            flags.set("trace_buffer_size", saved)

    def test_profile_stays_usable_as_identifier(self):
        """PROFILE/EXPLAIN are statement prefixes, NOT reserved words —
        columns/tags named profile/explain must keep parsing."""
        from nebula_tpu.graph.parser import GQLParser
        p = GQLParser()
        assert p.parse("GO FROM 1 OVER e YIELD e.w AS profile "
                       "| ORDER BY profile").ok()
        assert p.parse("CREATE TAG profile(name string)").ok()
        assert p.parse("GO FROM 1 OVER explain").ok()
        assert p.parse("FETCH PROP ON explain 1 "
                       "YIELD explain.profile").ok()

    def test_ring_buffer_evicts_oldest_trace(self):
        saved = flags.get("trace_buffer_size")
        flags.set("trace_buffer_size", 3)
        try:
            ids = []
            for _ in range(5):
                with tracing.start_trace("graph.query",
                                         forced=True) as root:
                    pass
                ids.append(root.trace_id)
            assert trace_store.tree(ids[0]) is None
            assert trace_store.tree(ids[-1]) is not None
            assert len(trace_store.summaries()) == 3
        finally:
            flags.set("trace_buffer_size", saved)

    def test_capture_attach_crosses_threads(self):
        import threading
        got = {}

        def worker(cap):
            with tracing.attach_captured(cap):
                with tracing.span("rpc.client", method="x"):
                    got["ctx"] = tracing.current_context()

        with tracing.start_trace("graph.query", forced=True) as root:
            t = threading.Thread(target=worker,
                                 args=(tracing.capture(),))
            t.start()
            t.join()
        assert got["ctx"][0] == root.trace_id
        names = _names(trace_store.tree(root.trace_id))
        assert "rpc.client" in names


# ====================================================== rpc propagation
class _Handler:
    def rpc_ping(self, req):
        # a server-side child span must join the caller's trace
        with tracing.span("graph.executor", executor="Ping"):
            return {"pong": req.get("n", 0)}

    def rpc_boom(self, req):
        raise RuntimeError("kaput")


class TestLoopbackPropagation:
    def test_client_server_spans_share_trace(self):
        ch = LoopbackChannel(_Handler())
        with tracing.start_trace("graph.query", forced=True) as root:
            assert ch.call("ping", {"n": 1}) == {"pong": 1}
        tree = trace_store.tree(root.trace_id)
        r = tree["roots"][0]
        client = r["children"][0]
        assert client["name"] == "rpc.client"
        server = client["children"][0]
        assert server["name"] == "rpc.server"
        assert [c["name"] for c in server["children"]] == \
            ["graph.executor"]

    def test_untraced_loopback_records_nothing(self):
        ch = LoopbackChannel(_Handler())
        assert ch.call("ping", {"n": 2}) == {"pong": 2}
        assert trace_store.summaries() == []


class TestTcpPropagation:
    def test_envelope_carries_spans_across_the_wire(self):
        srv = RpcServer(_Handler()).start()
        ch = RpcChannel(srv.addr)
        try:
            with tracing.start_trace("graph.query", forced=True) as root:
                assert ch.call("ping", {"n": 3}) == {"pong": 3}
            tree = trace_store.tree(root.trace_id)
            names = _names(tree)
            assert {"rpc.client", "rpc.server",
                    "graph.executor"} <= names
            # server spans absorbed from the envelope parent correctly:
            # rpc.server hangs under rpc.client, one root overall
            assert len(tree["roots"]) == 1
            client = tree["roots"][0]["children"][0]
            assert client["children"][0]["name"] == "rpc.server"
        finally:
            ch.close()
            srv.stop()

    def test_server_error_still_returns_spans(self):
        from nebula_tpu.interface.rpc import RpcError
        srv = RpcServer(_Handler()).start()
        ch = RpcChannel(srv.addr)
        try:
            with tracing.start_trace("graph.query", forced=True) as root:
                with pytest.raises(RpcError):
                    ch.call("boom", {})
            names = _names(trace_store.tree(root.trace_id))
            assert "rpc.server" in names
        finally:
            ch.close()
            srv.stop()

    def test_untraced_call_keeps_plain_frames(self):
        srv = RpcServer(_Handler()).start()
        ch = RpcChannel(srv.addr)
        try:
            assert ch.call("ping", {"n": 4}) == {"pong": 4}
            assert trace_store.summaries() == []
        finally:
            ch.close()
            srv.stop()


# ====================================================== overhead guard
class TestDisabledOverheadGuard:
    def test_rpc_call_disabled_path_allocates_nothing_in_tracing(self):
        """Tier-1 acceptance: with tracing off (no context, sample rate
        0) RpcChannel.call must not allocate in the tracing module —
        the disabled hot path is one thread-local read."""
        srv = RpcServer(_Handler()).start()
        ch = RpcChannel(srv.addr)
        try:
            for _ in range(20):                       # warm pool + code
                ch.call("ping", {"n": 0})
            tracemalloc.start()
            try:
                snap1 = tracemalloc.take_snapshot()
                for _ in range(100):
                    ch.call("ping", {"n": 0})
                snap2 = tracemalloc.take_snapshot()
            finally:
                tracemalloc.stop()
            filt = [tracemalloc.Filter(True, "*/common/tracing.py")]
            grew = [s for s in
                    snap2.filter_traces(filt).compare_to(
                        snap1.filter_traces(filt), "lineno")
                    if s.size_diff > 0 or s.count_diff > 0]
            assert grew == [], \
                f"tracing allocated on the disabled path: {grew}"
            assert trace_store.summaries() == []
        finally:
            ch.close()
            srv.stop()


# ====================================================== /traces endpoint
class TestTracesEndpoint:
    def test_listing_fetch_and_slow_log(self):
        from nebula_tpu.webservice import WebService
        with tracing.start_trace("graph.query", forced=True) as root:
            with tracing.span("graph.parse"):
                pass
        slow_log.record("GO FROM 1 OVER e", 123456, root.trace_id)
        ws = WebService("test").start()
        base = f"http://127.0.0.1:{ws.port}"
        try:
            listing = json.load(urllib.request.urlopen(f"{base}/traces"))
            tid = f"{root.trace_id:016x}"
            assert any(t["id"] == tid and t["name"] == "graph.query"
                       and t["spans"] == 2 for t in listing["traces"])
            tree = json.load(urllib.request.urlopen(
                f"{base}/traces?id={tid}"))
            assert tree["trace_id"] == tid
            assert tree["roots"][0]["children"][0]["name"] == \
                "graph.parse"
            slow = json.load(urllib.request.urlopen(
                f"{base}/traces?slow=1"))
            assert slow["slow_queries"][0]["trace_id"] == tid
            assert slow["slow_queries"][0]["latency_us"] == 123456
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/traces?id=nothex")
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/traces?id=deadbeef")
            assert ei.value.code == 404
        finally:
            ws.stop()


# ============================================== PROFILE / EXPLAIN e2e
@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_storage=2)
    cl = c.client()

    def ok(stmt):
        r = cl.execute(stmt)
        assert r.ok(), f"{stmt}: {r.error_msg}"
        return r

    ok("CREATE SPACE tr(partition_num=4, replica_factor=1)")
    c.refresh_all()
    ok("USE tr")
    ok("CREATE TAG player(name string, age int)")
    ok("CREATE EDGE follow(degree int)")
    c.refresh_all()
    ok('INSERT VERTEX player(name, age) VALUES 100:("Tim", 42), '
       '101:("Tony", 36), 102:("Manu", 41)')
    ok("INSERT EDGE follow(degree) VALUES 100->101:(95), "
       "101->102:(90), 102->100:(90)")
    cl.ok = ok
    yield c, cl
    cl.disconnect()
    c.stop()


class TestProfileStatement:
    def test_profile_go_returns_span_tree(self, cluster):
        _, cl = cluster
        r = cl.ok("PROFILE GO FROM 100 OVER follow YIELD follow._dst")
        assert sorted(map(tuple, r.rows)) == [(101,)]
        prof = r.profile
        assert prof is not None
        assert len(prof["roots"]) == 1
        root = prof["roots"][0]
        assert root["name"] == "graph.query"
        assert root["tags"].get("stmt_kind") == "GoSentence"
        names = set()
        _walk(root, names)
        # parse → executor → scatter-gather pass → per-storage-node RPC
        assert {"graph.parse", "graph.executor", "storage.collect.pass",
                "rpc.client", "rpc.server"} <= names

    def test_profile_renders_in_console(self, cluster):
        from nebula_tpu.console.repl import render_profile
        _, cl = cluster
        r = cl.ok("PROFILE GO FROM 100 OVER follow")
        text = render_profile(r.profile)
        assert "graph.query" in text and "rpc.client" in text
        assert "us" in text

    def test_unprofiled_query_attaches_nothing(self, cluster):
        _, cl = cluster
        r = cl.ok("GO FROM 100 OVER follow")
        assert r.profile is None

    def test_profile_multi_partition_fanout_shares_one_trace(self,
                                                             cluster):
        """Multi-start GO fans out to several parts across BOTH
        storage nodes — every rpc.client span must hang in the same
        tree (one trace id)."""
        _, cl = cluster
        r = cl.ok("PROFILE GO FROM 100,101,102 OVER follow "
                  "YIELD follow._dst")
        prof = r.profile
        assert len(prof["roots"]) == 1      # nothing orphaned
        rpc_spans = []

        def collect(node):
            if node["name"] == "rpc.client":
                rpc_spans.append(node)
            for ch in node["children"]:
                collect(ch)

        collect(prof["roots"][0])
        assert rpc_spans, "no RPC spans in the profile tree"

    def test_piped_profile_shows_per_half_spans_with_rows_in(self,
                                                             cluster):
        """A piped statement profiles as PipeExecutor plus one span per
        half, and the right half's rows_in is the left half's output."""
        _, cl = cluster
        r = cl.ok("PROFILE GO FROM 100 OVER follow YIELD follow._dst "
                  "AS id | GO FROM $-.id OVER follow YIELD follow._dst")
        execs = []

        def collect(node):
            if node["name"] == "graph.executor":
                execs.append(node["tags"])
            for ch in node["children"]:
                collect(ch)

        collect(r.profile["roots"][0])
        kinds = [t["executor"] for t in execs]
        assert kinds.count("GoExecutor") == 2 and "PipeExecutor" in kinds
        right = [t for t in execs
                 if t["executor"] == "GoExecutor" and t["rows_in"] > 0]
        assert right and right[0]["rows_in"] == 1  # 100 -> {101}

    def test_union_profile_shows_both_arms(self, cluster):
        _, cl = cluster
        r = cl.ok("PROFILE GO FROM 100 OVER follow UNION "
                  "GO FROM 101 OVER follow")
        execs = []

        def collect(node):
            if node["name"] == "graph.executor":
                execs.append(node["tags"]["executor"])
            for ch in node["children"]:
                collect(ch)

        collect(r.profile["roots"][0])
        assert execs.count("GoExecutor") == 2 and "SetExecutor" in execs

    def test_profile_after_leading_comment(self, cluster):
        """The parser accepts leading comments — the forced-trace
        sniff must agree, or the PROFILE silently returns no tree."""
        _, cl = cluster
        r = cl.ok("/* hint */ PROFILE GO FROM 100 OVER follow")
        assert r.profile is not None
        assert r.profile["roots"][0]["name"] == "graph.query"

    def test_sniff_is_token_aware(self):
        """The word PROFILE INSIDE a leading comment must not force a
        trace; real prefixes in any comment/whitespace shape must."""
        from nebula_tpu.graph.service import ExecutionEngine
        sniff = ExecutionEngine._sniff_profile
        assert sniff("PROFILE GO FROM 1 OVER e")
        assert sniff("/* c */ profile $a = GO FROM 1 OVER e")
        assert sniff("-- x\n# y\n  PROFILE GO")
        assert not sniff("-- PROFILE later\nGO FROM 1 OVER e")
        assert not sniff("/* PROFILE */ GO FROM 1 OVER e")
        assert not sniff("PROFILER GO")
        assert not sniff("\n" + " " * 3000 + "GO FROM 1 OVER e")

    def test_comment_mentioning_profile_stays_untraced(self, cluster):
        _, cl = cluster
        r = cl.ok("-- PROFILE someday\nGO FROM 100 OVER follow")
        assert r.profile is None
        assert trace_store.summaries() == []

    def test_profile_assignment_statement(self, cluster):
        """PROFILE must accept every statement form — `$var = ...`
        assignments included."""
        _, cl = cluster
        r = cl.ok("PROFILE $a = GO FROM 100 OVER follow "
                  "YIELD follow._dst")
        assert r.profile is not None
        names = set()
        _walk(r.profile["roots"][0], names)
        assert "graph.executor" in names

    def test_sniffed_profile_that_fails_parse_discards_trace(self,
                                                             cluster):
        """A force-started trace whose statement turns out not to be a
        valid PROFILE must not squat in the ring buffer."""
        _, cl = cluster
        r = cl.execute("PROFILE 123")
        assert not r.ok()
        assert trace_store.summaries() == []

    def test_explain_returns_plan_without_executing(self, cluster):
        _, cl = cluster
        r = cl.ok("EXPLAIN INSERT EDGE follow(degree) VALUES "
                  "100->999:(1)")
        assert r.column_names == ["step", "sentence", "executor"]
        assert r.rows == [[0, "InsertEdgeSentence",
                           "InsertEdgeExecutor"]]
        # the insert did NOT run
        check = cl.ok("GO FROM 100 OVER follow YIELD follow._dst")
        assert (999,) not in set(map(tuple, check.rows))
        # and EXPLAIN does not trace: no junk entries in the ring
        assert trace_store.summaries() == []


class TestSlowQueryLog:
    def test_password_statements_redacted(self):
        """/traces?slow=1 is unauthenticated — credential literals must
        never land in the log verbatim."""
        slow_log.record('CREATE USER u WITH PASSWORD "s3cret"', 99, None)
        slow_log.record("CHANGE PASSWORD 'old1' TO 'new2' FOR u", 99,
                        None)
        dumped = json.dumps(slow_log.dump())
        for secret in ("s3cret", "old1", "new2"):
            assert secret not in dumped
        assert '***' in dumped

    def test_huge_statements_truncated(self):
        slow_log.record("INSERT EDGE e(w) VALUES " + "x" * 100_000,
                        99, None)
        entry = slow_log.dump()[0]
        assert len(entry["stmt"]) < 5000
        assert entry["stmt"].endswith("chars]")

    def test_slow_statement_lands_in_log(self, cluster):
        _, cl = cluster
        saved = flags.get("slow_query_threshold_ms")
        flags.set("slow_query_threshold_ms", 1)
        try:
            cl.ok("PROFILE GO 2 STEPS FROM 100,101,102 OVER follow")
            entries = slow_log.dump()
            assert entries, "slow query did not land in the log"
            assert "GO 2 STEPS" in entries[0]["stmt"]
            # the PROFILEd statement was traced, so the log links it
            assert entries[0]["trace_id"] is not None
        finally:
            flags.set("slow_query_threshold_ms", saved)


class TestProfileTpuPhases:
    def test_profile_covers_device_phases(self):
        """Acceptance: PROFILE GO on a multi-partition space served by
        the (remote) device runtime shows mirror/transfer/kernel/gather
        phases in the same trace as the RPC hops, and /traces serves
        the trace on the daemons' webservices."""
        from nebula_tpu.common.stats import stats
        from nebula_tpu.webservice import WebService
        prev = flags.get("storage_backend")
        flags.set("storage_backend", "tpu")
        c = LocalCluster(num_storage=2, tpu_backend="remote")
        try:
            cl = c.client()

            def ok(stmt):
                r = cl.execute(stmt)
                assert r.ok(), f"{stmt}: {r.error_msg}"
                return r

            ok("CREATE SPACE devtr(partition_num=4, replica_factor=1)")
            c.refresh_all()
            ok("USE devtr")
            ok("CREATE EDGE follow(degree int)")
            c.refresh_all()
            ok("INSERT EDGE follow(degree) VALUES 100->101:(95), "
               "101->102:(90), 102->100:(90), 100->102:(80)")
            go0 = stats.read_stats("storage.device_go.qps.count.3600") \
                or 0
            r = ok("PROFILE GO 2 STEPS FROM 100 OVER follow "
                   "YIELD follow._dst")
            assert sorted(map(tuple, r.rows)) == [(100,), (102,)]
            assert (stats.read_stats("storage.device_go.qps.count.3600")
                    or 0) > go0, "device path did not serve the query"
            prof = r.profile
            assert prof is not None and len(prof["roots"]) == 1
            names = set()
            _walk(prof["roots"][0], names)
            assert {"graph.parse", "graph.executor", "rpc.client",
                    "rpc.server", "tpu.mirror.build", "tpu.transfer",
                    "tpu.launch", "tpu.kernel", "tpu.fetch",
                    "tpu.assemble"} <= names, names
            # the trace is fetchable over /traces on both daemons' web
            # surfaces (same built-in handler graphd and storaged mount)
            tid = prof["trace_id"]
            for daemon in ("nebula-graphd", "nebula-storaged"):
                ws = WebService(daemon).start()
                try:
                    tree = json.load(urllib.request.urlopen(
                        f"http://127.0.0.1:{ws.port}/traces?id={tid}"))
                    got = set()
                    for root in tree["roots"]:
                        _walk(root, got)
                    assert "tpu.kernel" in got
                finally:
                    ws.stop()
        finally:
            flags.set("storage_backend", prev)
            c.stop()
