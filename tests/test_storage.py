"""Storage processor + client tests.

Modeled on the reference's storage/test tier (QueryBoundTest, AddEdgesTest,
QueryStatsTest with TestUtils::initKV + AdHocSchemaManager fakes,
SURVEY.md §4)."""
import pytest

from nebula_tpu.codec.rows import RowReader, RowSetReader, encode_row
from nebula_tpu.common.keys import id_hash
from nebula_tpu.common.status import ErrorCode
from nebula_tpu.filter import (AliasPropExpr, PrimaryExpr, RelationalExpr,
                               SourcePropExpr, DestPropExpr, encode_expr)
from nebula_tpu.interface.common import (ColumnDef, HostAddr, Schema,
                                         SupportedType, schema_from_wire)
from nebula_tpu.interface.rpc import ClientManager, RpcError
from nebula_tpu.kvstore import KVOptions, MemPartManager, NebulaStore
from nebula_tpu.meta.client import MetaClient
from nebula_tpu.meta.schema_manager import AdHocSchemaManager
from nebula_tpu.meta.service import MetaService
from nebula_tpu.storage.client import StorageClient
from nebula_tpu.storage.service import StorageService

SPACE = 1
NUM_PARTS = 6
TAG_PLAYER = 10
EDGE_FOLLOW = 101

PLAYER = Schema(columns=[ColumnDef("name", SupportedType.STRING),
                         ColumnDef("age", SupportedType.INT)])
FOLLOW = Schema(columns=[ColumnDef("degree", SupportedType.INT)])


def make_storage():
    """initKV-equivalent: real store + MemPartManager + AdHoc schemas."""
    pm = MemPartManager()
    kv = NebulaStore(KVOptions(part_man=pm))
    pm.register_handler(kv)
    for p in range(1, NUM_PARTS + 1):
        pm.add_part(SPACE, p)
    sm = AdHocSchemaManager()
    sm.add_tag_schema(SPACE, TAG_PLAYER, "player", PLAYER)
    sm.add_edge_schema(SPACE, EDGE_FOLLOW, "follow", FOLLOW)
    return StorageService(kv, sm)


def insert_graph(svc, n_vertices=10, fanout=3):
    """vertex i follows (i+1..i+fanout) % n, degree = 10*i+j."""
    verts, edges = [], []
    for i in range(n_vertices):
        verts.append({"id": i, "tags": [[TAG_PLAYER, encode_row(
            PLAYER, {"name": f"p{i}", "age": 20 + i})]]})
        for j in range(1, fanout + 1):
            dst = (i + j) % n_vertices
            edges.append({"src": i, "etype": EDGE_FOLLOW, "rank": 0,
                          "dst": dst,
                          "props": encode_row(FOLLOW, {"degree": 10 * i + j})})
    by_part_v, by_part_e = {}, {}
    for v in verts:
        by_part_v.setdefault(str(id_hash(v["id"], NUM_PARTS)), []).append(v)
    for e in edges:
        by_part_e.setdefault(str(id_hash(e["src"], NUM_PARTS)), []).append(e)
    svc.rpc_addVertices({"space_id": SPACE, "parts": by_part_v,
                         "overwritable": True})
    svc.rpc_addEdges({"space_id": SPACE, "parts": by_part_e,
                      "overwritable": True})


def get_bound(svc, vids, **kw):
    req = {"space_id": SPACE,
           "parts": {}, "edge_types": [EDGE_FOLLOW],
           "vertex_props": kw.get("vertex_props", []),
           "edge_props": kw.get("edge_props", {}),
           "filter": kw.get("filter")}
    for vid in vids:
        req["parts"].setdefault(str(id_hash(vid, NUM_PARTS)), []).append(vid)
    return svc.rpc_getBound(req)


def edge_rows(resp):
    """-> {src_vid: [decoded edge row dicts]}"""
    out = {}
    for v in resp["vertices"]:
        rows = []
        for et, blob in v["edges"].items():
            schema = schema_from_wire(resp["edge_schemas"][int(et)])
            for raw in RowSetReader(blob):
                rows.append(RowReader(raw, schema).to_dict())
        out[v["id"]] = rows
    return out


class TestQueryBound:
    def test_simple_expand(self):
        svc = make_storage()
        insert_graph(svc)
        resp = get_bound(svc, [0])
        rows = edge_rows(resp)
        assert sorted(r["_dst"] for r in rows[0]) == [1, 2, 3]

    def test_edge_props_and_src_props(self):
        svc = make_storage()
        insert_graph(svc)
        resp = get_bound(svc, [2],
                         vertex_props=[[TAG_PLAYER, "age"]],
                         edge_props={EDGE_FOLLOW: ["degree"]})
        rows = edge_rows(resp)
        assert sorted(r["degree"] for r in rows[2]) == [21, 22, 23]
        vschema = schema_from_wire(resp["vertex_schema"])
        v = [x for x in resp["vertices"] if x["id"] == 2][0]
        assert RowReader(v["vdata"], vschema).get("age") == 22

    def test_multi_version_dedup(self):
        svc = make_storage()
        insert_graph(svc)
        # re-insert edge 0->1 with a newer version and different degree
        part = str(id_hash(0, NUM_PARTS))
        svc.rpc_addEdges({"space_id": SPACE, "parts": {part: [
            {"src": 0, "etype": EDGE_FOLLOW, "rank": 0, "dst": 1,
             "props": encode_row(FOLLOW, {"degree": 999})}]},
            "overwritable": True})
        rows = edge_rows(get_bound(svc, [0],
                                   edge_props={EDGE_FOLLOW: ["degree"]}))
        by_dst = {r["_dst"]: r["degree"] for r in rows[0]}
        assert by_dst[1] == 999  # latest wins
        assert len(rows[0]) == 3  # still one row per (rank,dst)

    def test_filter_pushdown_edge_prop(self):
        svc = make_storage()
        insert_graph(svc)
        flt = encode_expr(RelationalExpr(
            ">", AliasPropExpr("follow", "degree"), PrimaryExpr(1)))
        rows = edge_rows(get_bound(svc, [0], filter=flt,
                                   edge_props={EDGE_FOLLOW: ["degree"]}))
        assert sorted(r["degree"] for r in rows.get(0, [])) == [2, 3]

    def test_filter_pushdown_src_prop(self):
        svc = make_storage()
        insert_graph(svc)
        flt = encode_expr(RelationalExpr(
            ">", SourcePropExpr("player", "age"), PrimaryExpr(24)))
        resp = get_bound(svc, [0, 5], filter=flt,
                         vertex_props=[[TAG_PLAYER, "age"]])
        rows = edge_rows(resp)
        assert rows.get(0, []) == []     # age 20 filtered
        assert len(rows.get(5, [])) == 3  # age 25 passes

    def test_dst_ref_rejected_in_pushdown(self):
        svc = make_storage()
        insert_graph(svc)
        flt = encode_expr(RelationalExpr(
            ">", DestPropExpr("player", "age"), PrimaryExpr(0)))
        with pytest.raises(RpcError) as ei:
            get_bound(svc, [0], filter=flt)
        assert ei.value.status.code == ErrorCode.E_INVALID_FILTER

    def test_unknown_prop_rejected(self):
        svc = make_storage()
        insert_graph(svc)
        with pytest.raises(RpcError) as ei:
            get_bound(svc, [0], edge_props={EDGE_FOLLOW: ["nope"]})
        assert ei.value.status.code == ErrorCode.E_EDGE_PROP_NOT_FOUND

    def test_part_not_found(self):
        # bulk RPCs report unowned parts per-part (reference per-part
        # ResultCode, storage.thrift:57-62) so one bad part cannot fail
        # — or poison the client's leader cache for — the good ones
        svc = make_storage()
        resp = svc.rpc_getBound({"space_id": SPACE,
                                 "parts": {"99": [1],
                                           str(id_hash(0, NUM_PARTS)): [0]},
                                 "edge_types": [EDGE_FOLLOW],
                                 "vertex_props": [], "edge_props": {},
                                 "filter": None})
        assert resp["failed_parts"]["99"]["code"] == \
            int(ErrorCode.E_PART_NOT_FOUND)
        assert "vertices" in resp          # the owned part still answered


class TestOtherProcessors:
    def test_get_props(self):
        svc = make_storage()
        insert_graph(svc)
        req = {"space_id": SPACE,
               "parts": {str(id_hash(3, NUM_PARTS)): [3]},
               "vertex_props": [[TAG_PLAYER, "name"], [TAG_PLAYER, "age"]]}
        resp = svc.rpc_getProps(req)
        schema = schema_from_wire(resp["vertex_schema"])
        row = RowReader(resp["vertices"][0]["vdata"], schema)
        assert row.get("name") == "p3" and row.get("age") == 23

    def test_get_props_all_tags(self):
        svc = make_storage()
        insert_graph(svc)
        req = {"space_id": SPACE,
               "parts": {str(id_hash(3, NUM_PARTS)): [3]}}
        resp = svc.rpc_getProps(req)
        schema = schema_from_wire(resp["vertex_schema"])
        assert RowReader(resp["vertices"][0]["vdata"], schema).get("name") == "p3"

    def test_get_edge_props(self):
        svc = make_storage()
        insert_graph(svc)
        req = {"space_id": SPACE,
               "parts": {str(id_hash(0, NUM_PARTS)): [[0, EDGE_FOLLOW, 0, 2]]},
               "props": ["degree"]}
        resp = svc.rpc_getEdgeProps(req)
        schema = schema_from_wire(resp["edge_schemas"][EDGE_FOLLOW])
        rows = [RowReader(r, schema).to_dict()
                for r in RowSetReader(resp["edges"][EDGE_FOLLOW])]
        assert rows[0]["degree"] == 2 and rows[0]["_dst"] == 2

    def test_bound_stats(self):
        svc = make_storage()
        insert_graph(svc)
        req = {"space_id": SPACE,
               "parts": {str(id_hash(0, NUM_PARTS)): [0]},
               "edge_types": [EDGE_FOLLOW],
               "stat_props": {"d": [EDGE_FOLLOW, "degree"]}}
        resp = svc.rpc_boundStats(req)
        assert resp["degree"] == 3
        assert resp["stats"]["d"]["sum"] == 1 + 2 + 3
        assert resp["stats"]["d"]["count"] == 3
        assert resp["stats"]["d"]["avg"] == 2.0

    def test_delete_vertex(self):
        svc = make_storage()
        insert_graph(svc)
        part = id_hash(0, NUM_PARTS)
        svc.rpc_deleteVertex({"space_id": SPACE, "part": part, "vid": 0})
        resp = get_bound(svc, [0])
        assert resp["vertices"] == []


class _Cluster:
    """MetaService + one StorageService wired through loopback channels —
    the mock-server idiom (reference common/test/ServerContext.h)."""

    def __init__(self, num_parts=NUM_PARTS):
        self.cm = ClientManager()
        self.meta_svc = MetaService()
        meta_addr = HostAddr("meta", 9559)
        self.cm.register_loopback(meta_addr, self.meta_svc)
        self.storage_host = "127.0.0.1:44500"
        self.meta_svc.rpc_heartBeat({"host": self.storage_host})
        self.meta_client = MetaClient([meta_addr], client_manager=self.cm)
        self.meta_client.wait_for_metad_ready()


class TestStorageClient:
    def make_cluster(self):
        from nebula_tpu.interface.common import schema_to_wire
        cl = _Cluster()
        sid = cl.meta_client.create_space("nba", partition_num=NUM_PARTS).value()
        cl.meta_client.create_tag_schema(sid, "player", schema_to_wire(PLAYER))
        cl.meta_client.create_edge_schema(sid, "follow", schema_to_wire(FOLLOW))
        from nebula_tpu.meta.schema_manager import ServerBasedSchemaManager
        pm = MemPartManager()
        kv = NebulaStore(KVOptions(part_man=pm))
        pm.register_handler(kv)
        for p in range(1, NUM_PARTS + 1):
            pm.add_part(sid, p)
        sm = ServerBasedSchemaManager(cl.meta_client)
        svc = StorageService(kv, sm, local_host=cl.storage_host)
        cl.cm.register_loopback(HostAddr.parse(cl.storage_host), svc)
        client = StorageClient(cl.meta_client, client_manager=cl.cm)
        return cl, sid, client, sm

    def test_scatter_gather_roundtrip(self):
        cl, sid, client, sm = self.make_cluster()
        tid = sm.to_tag_id(sid, "player").value()
        et = sm.to_edge_type(sid, "follow").value()
        verts = [{"id": i, "tags": [[tid, encode_row(PLAYER,
                  {"name": f"p{i}", "age": 20 + i})]]} for i in range(20)]
        edges = [{"src": i, "etype": et, "rank": 0, "dst": (i + 1) % 20,
                  "props": encode_row(FOLLOW, {"degree": i})}
                 for i in range(20)]
        r1 = client.add_vertices(sid, verts)
        assert r1.succeeded(), r1.failed_parts
        r2 = client.add_edges(sid, edges)
        assert r2.succeeded()

        resp = client.get_neighbors(sid, list(range(20)), [et],
                                    edge_props={et: ["degree"]})
        assert resp.succeeded()
        assert resp.completeness() == 100
        all_dsts = set()
        for r in resp.responses:
            for v in r["vertices"]:
                schema = schema_from_wire(r["edge_schemas"][et])
                for raw in RowSetReader(v["edges"][et]):
                    all_dsts.add(RowReader(raw, schema).get("_dst"))
        assert all_dsts == set(range(20))

    def test_failed_part_tracking(self):
        cl, sid, client, sm = self.make_cluster()
        et = sm.to_edge_type(sid, "follow").value()
        vids = list(range(20))  # covers all parts

        # (a) with retries disabled, a dead leader is tracked as a failed
        # part — with the REAL observed error, not a leader-changed mask
        client.update_leader(sid, 1, "127.0.0.1:1")  # nothing listens
        resp = client.get_neighbors(sid, vids, [et], retries=0)
        assert not resp.succeeded()
        assert 1 in resp.failed_parts
        assert resp.failed_parts[1].code == ErrorCode.E_FAIL_TO_CONNECT
        assert resp.completeness() < 100

        # (b) normal calls self-heal: connect failure means the request
        # never executed, so the client invalidates the cached leader and
        # re-routes from meta placement within the same call
        client.update_leader(sid, 1, "127.0.0.1:1")
        resp2 = client.get_neighbors(sid, vids, [et])
        assert resp2.succeeded()
        assert resp2.completeness() == 100


def test_reference_idl_bound_aliases():
    """storage.thrift's getOutBound/getInBound/outBoundStats/inBoundStats
    spellings answer alongside getBound/boundStats (direction = etype
    sign in our requests), with reverse rows written so the In forms
    return real data."""
    svc = make_storage()
    insert_graph(svc, n_vertices=6, fanout=2)
    # write the reverse rows the mutate path would (insert_graph writes
    # only +etype): 0's out-edges mirrored under their dsts as -etype
    rev = []
    for j in (1, 2):
        rev.append({"src": j, "etype": -EDGE_FOLLOW, "rank": 0, "dst": 0,
                    "props": encode_row(FOLLOW, {"degree": j})})
    by_part = {}
    for e in rev:
        by_part.setdefault(str(id_hash(e["src"], NUM_PARTS)), []).append(e)
    svc.rpc_addEdges({"space_id": SPACE, "parts": by_part,
                      "overwritable": True})

    req = {"space_id": SPACE, "edge_types": [EDGE_FOLLOW],
           "vertex_props": [], "edge_props": {EDGE_FOLLOW: ["degree"]},
           "filter": None,
           "parts": {str(id_hash(0, NUM_PARTS)): [0]}}
    out = svc.rpc_getOutBound(dict(req))
    assert out["vertices"], out

    # vertex 1 has a reverse row (-etype) for 0->1: getInBound sees it
    inb = svc.rpc_getInBound({
        "space_id": SPACE, "edge_types": [EDGE_FOLLOW],
        "vertex_props": [], "edge_props": {-EDGE_FOLLOW: ["degree"]},
        "filter": None, "parts": {str(id_hash(1, NUM_PARTS)): [1]}})
    assert any(v["edges"] for v in inb["vertices"]), inb

    # aggregates: outBoundStats over vertex 0's two out-edges
    sreq = {"space_id": SPACE, "edge_types": [EDGE_FOLLOW],
            "parts": {str(id_hash(0, NUM_PARTS)): [0]},
            "stat_props": {"d": [EDGE_FOLLOW, "degree"]}}
    s1 = svc.rpc_outBoundStats(dict(sreq))
    assert s1["stats"]["d"]["count"] == 2 and s1["stats"]["d"]["sum"] == 3
    # inBoundStats over vertex 1's one in-edge (degree=1)
    s2 = svc.rpc_inBoundStats({
        "space_id": SPACE, "edge_types": [EDGE_FOLLOW],
        "parts": {str(id_hash(1, NUM_PARTS)): [1]},
        "stat_props": {"d": [EDGE_FOLLOW, "degree"]}})
    assert s2["stats"]["d"]["count"] == 1 and s2["stats"]["d"]["sum"] == 1, s2
