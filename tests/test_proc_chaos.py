"""Process-level kill/restart chaos — the kill matrix (docs/durability.md).

Unlike tests/test_chaos.py (wire-level fault injection over IN-PROCESS
daemons), every scenario here SIGKILLs a real subprocess booted by
tools/proc_cluster.py: half-written WALs, uncommitted MANIFESTs and
warm leader caches are real, and recovery is gated on the PR 5
/healthz + /metrics probes.

Matrix invariants (ISSUE acceptance):
  * every acked write survives the kill + restart (CRC'd WAL replay —
    no replayed garbage frames),
  * recovered state never contains rows nobody attempted to write,
  * during the failure window every query ends within its deadline in
    success, a typed partial, or a typed error — never a hang,
  * after recovery the SAME query returns complete, correct results.

One smoke cell runs in tier-1; the full matrix is slow-marked and
driven by scripts/chaos.sh (under the lock watchdog via
NEBULA_LOCK_WATCHDOG, which the subprocesses inherit).
"""
import signal
import threading
import time

import pytest

from nebula_tpu.common.keys import id_hash
from nebula_tpu.tools.proc_cluster import ProcCluster

pytestmark = pytest.mark.chaos

FAST_RAFT = {"raft_heartbeat_interval_s": 0.1,
             "raft_election_timeout_s": 0.8}


def _ok(cl, stmt, tries=40, sleep=0.25):
    """Execute with retry — metadata propagation and failover windows
    resolve within a bounded poll, or the scenario fails loudly."""
    last = None
    for _ in range(tries):
        last = cl.execute(stmt)
        if last.ok():
            return last
        time.sleep(sleep)
    raise AssertionError(f"{stmt}: {last.error_msg}")


def _seed_space(cl, name, partition_num=2, replica_factor=1):
    _ok(cl, f"CREATE SPACE {name}(partition_num={partition_num}, "
            f"replica_factor={replica_factor})")
    _ok(cl, f"USE {name}")
    _ok(cl, "CREATE EDGE e(w int)")
    # schema propagation to storaged rides the shrunk load_data
    # interval; the first INSERT polls it in
    _ok(cl, "INSERT EDGE e(w) VALUES 900001->900002:(1)")


def _dst_set(resp):
    return sorted(x[0] for x in resp.rows)


# ================================================= tier-1 smoke cell
class TestProcSmoke:
    def test_sigkill_storaged_acked_writes_survive_restart(self, tmp_path):
        """THE smoke cell: boot real daemons over TCP, ack writes,
        SIGKILL the storaged (half-written WAL and all), restart, and
        recover — acked rows back, node.recovered journaled,
        recovery metrics exposed, /healthz green again."""
        with ProcCluster(str(tmp_path), num_storage=1) as c:
            cl = c.client()
            _seed_space(cl, "pk")
            _ok(cl, "INSERT EDGE e(w) VALUES 1->2:(7), 2->3:(8), "
                    "3->4:(9)")
            q = "GO FROM 1,2,3 OVER e YIELD e._dst"
            assert _dst_set(_ok(cl, q)) == [2, 3, 4]

            c.kill("storaged0", signal.SIGKILL)
            c.wait_down("storaged0")
            # the dead window: typed failure within the deadline, no hang
            t0 = time.monotonic()
            r = cl.execute("TIMEOUT 4000 " + q)
            assert time.monotonic() - t0 < 12.0
            assert not r.ok() or r.completeness < 100

            c.restart("storaged0")          # gates on /healthz
            deadline = time.monotonic() + 30
            good = None
            while time.monotonic() < deadline:
                r = cl.execute(q)
                if r.ok() and r.completeness == 100 \
                        and _dst_set(r) == [2, 3, 4]:
                    good = r
                    break
                time.sleep(0.3)
            assert good is not None, "acked writes lost or never served"
            # recovery observability: event + metric
            assert any(e["kind"] == "node.recovered"
                       for e in c.events("storaged0"))
            assert "nebula_recovery_node_restarts_total" \
                in c.metrics("storaged0")
            # and the cluster keeps taking writes
            _ok(cl, "INSERT EDGE e(w) VALUES 4->5:(10)")
            assert _dst_set(_ok(cl, "GO FROM 4 OVER e YIELD e._dst")) \
                == [5]


# ==================================================== full kill matrix
@pytest.mark.slow
class TestKillMatrix:
    def test_kill_storaged_mid_append_no_acked_loss(self, tmp_path):
        """Writer acks ride WAL flushes; SIGKILL lands mid-append
        stream.  After restart every ACKED edge is served and nothing
        appears that was never written (no replayed garbage)."""
        with ProcCluster(str(tmp_path), num_storage=1) as c:
            cl = c.client()
            _seed_space(cl, "ma")
            acked = []
            attempted = []
            stop = threading.Event()

            def writer():
                i = 0
                while not stop.is_set() and i < 2000:
                    i += 1
                    attempted.append(i)
                    r = cl.execute(
                        f"INSERT EDGE e(w) VALUES {i}->{i + 10000}:({i})")
                    if r.ok():
                        acked.append(i)

            t = threading.Thread(target=writer, daemon=True)
            t.start()
            while len(acked) < 25:          # a real stream is in flight
                time.sleep(0.02)
            c.kill("storaged0", signal.SIGKILL)
            c.wait_down("storaged0")
            stop.set()
            t.join(timeout=60)
            assert len(acked) >= 25
            c.restart("storaged0")
            # every acked write survives; reads converge complete
            vids = ",".join(str(i) for i in acked)
            deadline = time.monotonic() + 30
            rows = None
            while time.monotonic() < deadline:
                r = cl.execute(f"GO FROM {vids} OVER e YIELD e._dst")
                if r.ok() and r.completeness == 100:
                    rows = _dst_set(r)
                    break
                time.sleep(0.3)
            assert rows is not None, "reads never converged after restart"
            missing = [i for i in acked if i + 10000 not in rows]
            assert not missing, f"ACKED writes lost after SIGKILL: {missing}"
            # nothing recovered that was never attempted (garbage guard)
            allowed = {i + 10000 for i in attempted}
            garbage = [d for d in rows if d not in allowed]
            assert not garbage, f"recovered rows nobody wrote: {garbage}"

    def test_kill_storaged_mid_flush_and_compaction(self, tmp_path):
        """Disk-engine cell: a tiny memtable + aggressive compaction
        threshold put the SIGKILL inside flush / MANIFEST-replace
        windows.  Recovery must come back to a committed view holding
        every acked write — the raft WAL replays above the engine's
        durable watermark (extends the in-proc manifest test in
        test_disk_engine.py to a real process death)."""
        extra = {"disk_engine_mem_limit_bytes": 2048,
                 "disk_engine_compact_after_runs": 3}
        with ProcCluster(str(tmp_path), num_storage=1,
                         extra_flags=extra) as c:
            cl = c.client()
            _seed_space(cl, "mc")
            acked = []
            stop = threading.Event()

            def writer():
                i = 0
                while not stop.is_set() and i < 3000:
                    i += 1
                    r = cl.execute(
                        f"INSERT EDGE e(w) VALUES {i}->{i + 20000}:({i})")
                    if r.ok():
                        acked.append(i)

            t = threading.Thread(target=writer, daemon=True)
            t.start()
            while len(acked) < 120:     # enough for several flush cycles
                time.sleep(0.02)
            c.kill("storaged0", signal.SIGKILL)
            c.wait_down("storaged0")
            stop.set()
            t.join(timeout=60)
            c.restart("storaged0")
            vids = ",".join(str(i) for i in acked)
            deadline = time.monotonic() + 40
            rows = None
            while time.monotonic() < deadline:
                r = cl.execute(f"GO FROM {vids} OVER e YIELD e._dst")
                if r.ok() and r.completeness == 100:
                    rows = _dst_set(r)
                    break
                time.sleep(0.3)
            assert rows is not None
            missing = [i for i in acked if i + 20000 not in rows]
            assert not missing, f"acked writes lost mid-flush: {missing}"

    def test_leader_kill_under_live_go_traffic(self, tmp_path):
        """Replicated cell: SIGKILL the storaged LEADING the queried
        part while GO traffic is live.  Every in-window response ends
        within its deadline as success, typed partial, or typed error;
        the client's leader-cache invalidation + re-discovery converge
        on the new leader; acked data never disappears."""
        with ProcCluster(str(tmp_path), num_storage=3,
                         extra_flags=FAST_RAFT) as c:
            cl = c.client()
            _seed_space(cl, "lk", partition_num=2, replica_factor=3)
            _ok(cl, "INSERT EDGE e(w) VALUES 1->2:(7), 2->3:(8)")
            q = "GO FROM 1,2 OVER e YIELD e._dst"
            assert _dst_set(_ok(cl, q)) == [2, 3]

            # the part vid 1 hashes to, and the storaged leading it
            part = id_hash(1, 2)
            victim = None
            for name in c.storage_names:
                import json
                admin = json.loads(c.daemons[name]._http("/admin"))
                for st in admin["parts"]:
                    if st["part"] == part and st["role"] == "LEADER" \
                            and st["space"] > 0:
                        victim = name
                if victim:
                    break
            assert victim, "no leader found for the queried part"

            results = []
            stop = threading.Event()

            def reader():
                rcl = c.client()
                while not stop.is_set():
                    t0 = time.monotonic()
                    r = rcl.execute("TIMEOUT 6000 " + q)
                    dt = time.monotonic() - t0
                    results.append((r.ok(), r.completeness if r.ok()
                                    else r.error_msg, dt))
                rcl.disconnect()

            threads = [threading.Thread(target=reader, daemon=True)
                       for _ in range(2)]
            for t in threads:
                t.start()
            time.sleep(0.5)
            c.kill(victim, signal.SIGKILL)
            c.wait_down(victim)
            time.sleep(6.0)                 # failover + re-discovery
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert results
            # no hangs: every response (ok or typed) bounded — the 6 s
            # statement TIMEOUT plus transport/retry slack (generous:
            # chaos runs share loaded CI boxes, and the invariant is
            # "ends typed", not "ends fast")
            worst = max(dt for _ok_, _d, dt in results)
            assert worst < 30.0, f"a query hung {worst:.1f}s"
            for okf, detail, _dt in results:
                if not okf:
                    assert isinstance(detail, str) and detail, (
                        "failure without a typed message")
            # convergence: the surviving quorum serves complete results
            deadline = time.monotonic() + 60
            final = None
            while time.monotonic() < deadline:
                r = cl.execute(q)
                if r.ok() and r.completeness == 100 \
                        and _dst_set(r) == [2, 3]:
                    final = r
                    break
                time.sleep(0.3)
            assert final is not None, "failover never converged"
            # writes keep working through the surviving quorum, and the
            # killed node comes back healthy
            _ok(cl, "INSERT EDGE e(w) VALUES 3->4:(9)")
            c.restart(victim)
            assert _dst_set(_ok(cl, "GO FROM 3 OVER e YIELD e._dst")) \
                == [4]

    def test_metad_sigkill_and_restart(self, tmp_path):
        """Control-plane cell: SIGKILL metad.  Cached metadata keeps
        reads serving, DDL fails TYPED (no hang), and after restart
        (catalog WAL replay) DDL works and heartbeats re-register."""
        with ProcCluster(str(tmp_path), num_storage=1) as c:
            cl = c.client()
            _seed_space(cl, "mk")
            _ok(cl, "INSERT EDGE e(w) VALUES 1->2:(7)")
            q = "GO FROM 1 OVER e YIELD e._dst"
            assert _dst_set(_ok(cl, q)) == [2]

            c.kill("metad", signal.SIGKILL)
            c.wait_down("metad")
            # reads ride the cached metadata
            r = cl.execute(q)
            assert r.ok() and _dst_set(r) == [2]
            # DDL: typed error within a bounded window, not a hang
            t0 = time.monotonic()
            r = cl.execute("CREATE SPACE nope(partition_num=1)")
            assert not r.ok()
            assert time.monotonic() - t0 < 60.0
            assert isinstance(r.error_msg, str) and r.error_msg

            c.restart("metad")
            # the catalog recovered: the OLD space is still known
            # (durable catalog WAL) and NEW DDL works
            deadline = time.monotonic() + 40
            created = False
            while time.monotonic() < deadline:
                if cl.execute("CREATE SPACE mk2(partition_num=1, "
                              "replica_factor=1)").ok():
                    created = True
                    break
                time.sleep(0.5)
            assert created, "DDL never recovered after metad restart"
            assert any(e["kind"] == "node.recovered"
                       for e in c.events("metad"))
            # data-plane still intact end to end
            assert _dst_set(_ok(cl, q)) == [2]

    def test_kill_storaged_mid_absorption_zero_acked_loss(self, tmp_path):
        """Write-while-serve crash cell (ISSUE 11): the storaged
        device-serves GO traffic while absorbing a live write stream
        into mirror generations; SIGKILL lands with absorptions
        verifiably in flight.  Restart must recover to a CONSISTENT
        generation: every acked write visible (and deleted edges
        gone), completeness 100 after convergence, and the absorb path
        re-engaged post-recovery."""
        from nebula_tpu.tools.bench_suite import _prom_value
        with ProcCluster(str(tmp_path), num_storage=1,
                         storage_backend="tpu") as c:
            cl = c.client()
            _ok(cl, "CREATE SPACE ka(partition_num=2, replica_factor=1)")
            _ok(cl, "USE ka")
            _ok(cl, "CREATE EDGE e(w int)")
            n = 60
            _ok(cl, "INSERT EDGE e(w) VALUES "
                    + ", ".join(f"{i}->{i % n + 1}@0:({i})"
                                for i in range(1, n + 1)))
            goq = "GO 2 STEPS FROM 1, 7, 13 OVER e YIELD e._dst"
            _ok(cl, goq)                      # device mirror builds

            acked: list = []        # (src, dst, rank, w)
            deleted: list = []
            murky: list = []        # delete attempted, ack unknown
            stop = threading.Event()

            def writer():
                g = c.client()
                g.execute("USE ka")
                i = 0
                cursor = [0]
                while not stop.is_set() and i < 4000:
                    i += 1
                    s, d, w = i % n + 1, (i * 7 + 3) % n + 1, 40000 + i
                    r = g.execute(f"INSERT EDGE e(w) VALUES "
                                  f"{s}->{d}@{w}:({w})")
                    if r.ok():
                        acked.append((s, d, w, w))
                    if i % 5 == 0 and len(acked) > cursor[0] + 4:
                        ent = acked[cursor[0]]
                        cursor[0] += 1
                        s2, d2, r2, _w2 = ent
                        if g.execute(f"DELETE EDGE e {s2}->{d2}@{r2}") \
                                .ok():
                            deleted.append(ent)
                        else:
                            murky.append(ent)   # outcome unknown

            def reader():
                g = c.client()
                g.execute("USE ka")
                while not stop.is_set():
                    g.execute(goq)            # keeps absorptions firing

            ts = [threading.Thread(target=writer, daemon=True),
                  threading.Thread(target=reader, daemon=True)]
            for t in ts:
                t.start()
            # kill only once absorptions are PROVABLY in flight
            deadline = time.monotonic() + 30
            absorbs = 0.0
            while time.monotonic() < deadline:
                absorbs = _prom_value(c.metrics("storaged0"),
                                      "nebula_tpu_absorb_count")
                if absorbs >= 3 and len(acked) >= 30:
                    break
                time.sleep(0.2)
            assert absorbs >= 3, "absorption never engaged pre-kill"
            c.kill("storaged0", signal.SIGKILL)
            c.wait_down("storaged0")
            stop.set()
            for t in ts:
                t.join(timeout=60)
            c.restart("storaged0")

            # recovery: acked edges visible, acked deletes gone,
            # completeness 100 — on the REBUILT + re-absorbing mirror
            snap_acked = list(acked)
            snap_deleted = set(deleted)
            snap_murky = set(murky)     # unacked deletes: either way
            live = [e for e in snap_acked
                    if e not in snap_deleted and e not in snap_murky]
            deadline = time.monotonic() + 40
            rows = None
            srcs = ",".join(str(s)
                            for s in sorted({e[0] for e in live}))
            while time.monotonic() < deadline:
                r = cl.execute(f"GO FROM {srcs} OVER e "
                               f"YIELD e._dst, e.w")
                if r.ok() and r.completeness == 100:
                    rows = set(map(tuple, r.rows))
                    break
                time.sleep(0.4)
            assert rows is not None, "reads never converged"
            lost = [e for e in live if (e[1], e[3]) not in rows]
            assert not lost, f"acked writes lost mid-absorption: {lost[:5]}"
            zombies = [e for e in snap_deleted
                       if (e[1], e[3]) in rows]
            assert not zombies, f"acked deletes resurrected: {zombies[:5]}"
            # the absorb path re-engages on the recovered generation
            _ok(cl, f"INSERT EDGE e(w) VALUES 1->{n // 2}@99999:(99999)")
            deadline = time.monotonic() + 20
            post = 0.0
            while time.monotonic() < deadline:
                _ok(cl, goq)
                post = _prom_value(c.metrics("storaged0"),
                                   "nebula_tpu_absorb_count")
                if post > 0:
                    break
                time.sleep(0.2)
            assert post > 0, "absorption did not resume after recovery"

    def test_kill_storaged_mid_continuous_flight(self, tmp_path):
        """Continuous-dispatch crash cell (ISSUE 15): the storaged
        device-serves multi-hop GO through the continuous seat map
        (docs/admission.md "Continuous dispatch"); SIGKILL lands with
        the lane batch provably in flight.  Acceptance: every
        in-flight rider ends TYPED within its deadline (never a
        hang), and after restart the seat map drains cleanly — zero
        seated/queued lanes on /metrics and joins balancing
        leaves + evictions.  No lane leak."""
        from nebula_tpu.tools.bench_suite import _prom_value
        with ProcCluster(str(tmp_path), num_storage=1,
                         storage_backend="tpu") as c:
            cl = c.client()
            _ok(cl, "CREATE SPACE ck(partition_num=2, "
                    "replica_factor=1)")
            _ok(cl, "USE ck")
            _ok(cl, "CREATE EDGE e(w int)")
            n = 60
            _ok(cl, "INSERT EDGE e(w) VALUES "
                    + ", ".join(f"{i}->{i % n + 1}@0:({i})"
                                for i in range(1, n + 1))
                    + ", " + ", ".join(
                        f"{i}->{(i * 7 + 3) % n + 1}@1:({i})"
                        for i in range(1, n + 1, 3)))
            goq = "GO 3 STEPS FROM 1, 7, 13 OVER e YIELD e._dst"
            _ok(cl, goq)                  # device mirror + stream warm

            # the continuous tier is provably serving before the kill
            deadline = time.monotonic() + 20
            joins = 0.0
            while time.monotonic() < deadline:
                _ok(cl, goq)
                joins = _prom_value(c.metrics("storaged0"),
                                    "nebula_graph_continuous_joins_total")
                if joins >= 3:
                    break
                time.sleep(0.2)
            assert joins >= 3, "continuous dispatch never engaged"

            stop = threading.Event()
            outcomes: list = []       # (wall_s, ok, completeness)

            def reader(wid: int):
                g = c.client(connect_timeout_s=60)
                g.execute("USE ck")
                while not stop.is_set():
                    t0 = time.monotonic()
                    r = g.execute("TIMEOUT 4000 " + goq)
                    outcomes.append((time.monotonic() - t0, r.ok(),
                                     r.completeness if r.ok() else 0))

            ts = [threading.Thread(target=reader, args=(w,),
                                   daemon=True) for w in range(6)]
            for t in ts:
                t.start()
            time.sleep(1.0)           # riders in flight
            n_pre = len(outcomes)
            c.kill("storaged0", signal.SIGKILL)
            c.wait_down("storaged0")
            time.sleep(3.0)           # the dead window
            c.restart("storaged0")
            deadline = time.monotonic() + 40
            converged = False
            while time.monotonic() < deadline:
                r = cl.execute(goq)
                if r.ok() and r.completeness == 100:
                    converged = True
                    break
                time.sleep(0.4)
            stop.set()
            for t in ts:
                t.join(timeout=60)
            assert converged, "continuous serving never recovered"
            # every response across the kill window ended within a
            # bounded multiple of its deadline — typed, never a hang
            walls = [w for w, _ok_, _c in outcomes[n_pre:]]
            assert walls, "no traffic crossed the kill window"
            assert max(walls) < 15.0, f"rider hung {max(walls):.1f}s"

            # seat-map drain on the RECOVERED storaged: run traffic,
            # stop, and the ledger must empty with joins balancing
            # leaves + evictions (post-restart counters are fresh)
            for _ in range(5):
                _ok(cl, goq)
            deadline = time.monotonic() + 15
            seated = queued = -1.0
            while time.monotonic() < deadline:
                mtx = c.metrics("storaged0")
                seated = _prom_value(mtx,
                                     "nebula_graph_continuous_seated")
                queued = _prom_value(mtx,
                                     "nebula_graph_continuous_queued")
                if seated == 0.0 and queued == 0.0:
                    break
                time.sleep(0.3)
            assert (seated, queued) == (0.0, 0.0), "lane leak"
            mtx = c.metrics("storaged0")
            joins2 = _prom_value(mtx,
                                 "nebula_graph_continuous_joins_total")
            leaves2 = _prom_value(mtx,
                                  "nebula_graph_continuous_leaves_total")
            evic2 = _prom_value(
                mtx, "nebula_graph_continuous_evictions_total")
            assert joins2 > 0
            assert joins2 == leaves2 + evic2, (joins2, leaves2, evic2)

    def test_partitioned_raft_leader_zero_acked_loss(self, tmp_path):
        """Partition cell (ISSUE 13): the raft leader of the queried
        part is netsplit away from its followers while a write stream
        is live.  The survivors elect, the client's leader chase
        converges on the new leader, writes keep acking — and after
        the heal, EVERY acked write is served (zero acked loss) with
        nothing present that was never attempted (no split-brain
        divergence)."""
        with ProcCluster(str(tmp_path), num_storage=3,
                         extra_flags=FAST_RAFT) as c:
            cl = c.client()
            _seed_space(cl, "pl", partition_num=1, replica_factor=3)
            import json
            leader = None
            for name in c.storage_names:
                admin = json.loads(c.daemons[name]._http("/admin"))
                if any(st["space"] > 0 and st["role"] == "LEADER"
                       for st in admin["parts"]):
                    leader = name
                    break
            assert leader, "no data-part leader found"
            followers = [n for n in c.storage_names if n != leader]

            acked, attempted = [], []
            stop = threading.Event()

            def writer():
                g = c.client()
                g.execute("USE pl")
                i = 0
                while not stop.is_set() and i < 3000:
                    i += 1
                    attempted.append(i)
                    # a statement budget keeps every write attempt
                    # bounded while the deposed leader still thinks it
                    # leads (its quorum-less appends fail typed, the
                    # client re-discovers) — a timed-out write is
                    # simply not acked
                    if g.execute(f"TIMEOUT 4000 INSERT EDGE e(w) "
                                 f"VALUES {i}->{i + 50000}:({i})").ok():
                        acked.append(i)
                g.disconnect()

            t = threading.Thread(target=writer, daemon=True)
            t.start()
            while len(acked) < 20:          # stream provably live
                time.sleep(0.02)
            # the split: leader alone vs both followers, both
            # directions cut; graphd + metad keep full connectivity
            c.netsplit([leader], followers)
            # net.partitioned journaled inside the leader (the /events
            # chaos timeline)
            assert any(e["kind"] == "net.partitioned"
                       for e in c.events(leader))
            pre_heal = len(acked)
            # the surviving majority must elect and resume acking —
            # generously bounded: the client must first burn typed
            # failures against the deposed leader, invalidate its
            # leader cache, and chase hints to the new one
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline \
                    and len(acked) < pre_heal + 10:
                time.sleep(0.2)
            assert len(acked) >= pre_heal + 10, \
                "writes never resumed through the surviving quorum"
            c.heal()
            assert any(e["kind"] == "net.healed"
                       for e in c.events(leader))
            time.sleep(2.0)                 # deposed leader rejoins
            stop.set()
            t.join(timeout=60)

            vids = ",".join(str(i) for i in acked)
            deadline = time.monotonic() + 40
            rows = None
            while time.monotonic() < deadline:
                r = cl.execute(f"GO FROM {vids} OVER e YIELD e._dst")
                if r.ok() and r.completeness == 100:
                    rows = _dst_set(r)
                    break
                time.sleep(0.3)
            assert rows is not None, "reads never converged after heal"
            missing = [i for i in acked if i + 50000 not in rows]
            assert not missing, \
                f"ACKED writes lost across the partition: {missing[:5]}"
            allowed = {i + 50000 for i in attempted}
            garbage = [d for d in rows if d >= 50000 and d not in allowed]
            assert not garbage, f"split-brain rows nobody wrote: {garbage}"

    def test_mirror_host_partitioned_mid_delta_stream(self, tmp_path):
        """Partition cell (ISSUE 13): the device-serving storaged is
        split from the peer whose delta log feeds its mirror, while
        writes keep landing on the peer.  During the split every query
        still completes at 100 (ladder / CPU loop).  The shrunk delta
        log trims past the wedged cursor, so the heal surfaces a TYPED
        mirror.absorb_failed (peer-cursor-truncated / peer-cursor-gap)
        that degrades to the rebuild — and absorption then RESUMES
        (peer_absorb counter grows past its pre-split value)."""
        from nebula_tpu.tools.bench_suite import _prom_value
        extra = {"store_delta_log_cap": 8, "device_decline_ttl_s": 1.0}
        with ProcCluster(str(tmp_path), num_storage=2,
                         storage_backend="tpu", extra_flags=extra) as c:
            cl = c.client()
            _ok(cl, "CREATE SPACE md(partition_num=4, replica_factor=1)")
            _ok(cl, "USE md")
            _ok(cl, "CREATE EDGE e(w int)")
            n = 40
            _ok(cl, "INSERT EDGE e(w) VALUES "
                + ", ".join(f"{i}->{i % n + 1}@0:({i})"
                            for i in range(1, n + 1)))
            goq = "GO 2 STEPS FROM 1, 9, 17 OVER e YIELD e._dst"
            _ok(cl, goq)                    # the device mirror builds

            def peer_absorbs_total():
                return sum(_prom_value(c.metrics(s),
                                       "nebula_tpu_peer_absorb_count")
                           for s in c.storage_names)

            # prove the STREAM works before the chaos: writes landing
            # on peer-led parts absorb at O(delta), no remote rebuild
            deadline = time.monotonic() + 30
            i = 0
            while time.monotonic() < deadline \
                    and peer_absorbs_total() == 0:
                i += 1
                _ok(cl, f"INSERT EDGE e(w) VALUES "
                        f"{i % n + 1}->{(i * 7) % n + 1}@{100 + i}"
                        f":({i})")
                _ok(cl, goq)
            assert peer_absorbs_total() > 0, \
                "peer-delta absorption never engaged pre-partition"
            pre_split = peer_absorbs_total()

            # the serving host is whichever built a device mirror
            server = max(c.storage_names, key=lambda s: _prom_value(
                c.metrics(s), "nebula_tpu_mirror_builds",
                'runtime="device"'))
            peer = next(s for s in c.storage_names if s != server)
            # vids whose part the PEER leads: writes during the split
            # (BOTH endpoints — the reverse in-edge lands on the dst's
            # part) stay in the peer's delta log, so the trim wedges
            # exactly the STREAMED cursor (a local-log trim on the
            # server would mask the typed peer reason)
            import json
            admin = json.loads(c.daemons[peer]._http("/admin"))
            peer_parts = {st["part"] for st in admin["parts"]
                          if st["space"] > 0 and st["role"] == "LEADER"}
            assert peer_parts, "peer leads no parts"
            peer_srcs = [v for v in range(1, n + 1)
                         if id_hash(v, 4) in peer_parts]
            assert len(peer_srcs) >= 2
            c.netsplit([server], [peer])

            # during the split: writes keep acking (graphd reaches
            # both) and every read completes at 100 — ladder or CPU.
            # 30 single-edge commits to peer-led parts drive the
            # peer's delta log far past the shrunk cap, so the wedged
            # cursor is trimmed and the post-heal stream break is the
            # TYPED truncation, not a seamless catch-up
            for j in range(30):
                s = peer_srcs[j % len(peer_srcs)]
                d = peer_srcs[(j + 1) % len(peer_srcs)]
                _ok(cl, f"INSERT EDGE e(w) VALUES "
                        f"{s}->{d}@{500 + j}:({j})")
            r = _ok(cl, goq)
            assert r.completeness == 100, \
                "query lost completeness during the partition"

            c.heal()
            # post-heal: the wedged cursor is typed and the rebuild
            # re-anchors; fresh writes then absorb again
            deadline = time.monotonic() + 40
            resumed = False
            k = 0
            while time.monotonic() < deadline:
                k += 1
                s = peer_srcs[k % len(peer_srcs)]
                d = peer_srcs[(k + 1) % len(peer_srcs)]
                _ok(cl, f"INSERT EDGE e(w) VALUES "
                        f"{s}->{d}@{900 + k}:({k})")
                _ok(cl, goq)
                if peer_absorbs_total() > pre_split:
                    resumed = True
                    break
                time.sleep(0.2)
            assert resumed, \
                "peer-delta absorption did not resume after the heal"
            evs = [e for e in c.events(server) + c.events(peer)
                   if e["kind"] == "mirror.absorb_failed"
                   and str(e.get("reason", "")).startswith("peer-")]
            assert evs, ("no TYPED peer-delta stream break journaled "
                         "across the partition")
            # parity after the chaos: device rows == CPU rows
            rows_dev = _dst_set(_ok(cl, goq))
            cpu_addr = c.add_graphd("graphd-cpu",
                                    {"storage_backend": "cpu"})
            cpu = c.client(addr=cpu_addr)
            _ok(cpu, "USE md")
            assert _dst_set(_ok(cpu, goq)) == rows_dev, \
                "device/CPU divergence after partition chaos"

    def test_graphd_partitioned_from_storaged_ladder_serves(
            self, tmp_path):
        """Partition cell (ISSUE 13): graphd loses its link to the
        PREFERRED device-serving storaged while the replica one RPC
        away stays healthy.  The failover ladder must retry the same
        parts on that replica — device-path completeness stays 100 and
        the failover counters prove a replica (not the CPU loop)
        served."""
        from nebula_tpu.tools.bench_suite import _prom_value
        with ProcCluster(str(tmp_path), num_storage=2,
                         storage_backend="tpu") as c:
            cl = c.client()
            _ok(cl, "CREATE SPACE gp(partition_num=4, replica_factor=1)")
            _ok(cl, "USE gp")
            _ok(cl, "CREATE EDGE e(w int)")
            n = 30
            _ok(cl, "INSERT EDGE e(w) VALUES "
                + ", ".join(f"{i}->{i % n + 1}@0:({i})"
                            for i in range(1, n + 1)))
            goq = "GO 2 STEPS FROM 1, 5 OVER e YIELD e._dst"
            want = _dst_set(_ok(cl, goq))
            # the preferred rung: the storaged that has device-served
            server = max(c.storage_names, key=lambda s: _prom_value(
                c.metrics(s), "nebula_storage_device_go_qps_total"))
            other = next(s for s in c.storage_names if s != server)
            served0 = _prom_value(c.metrics(other),
                                  "nebula_storage_device_go_qps_total")
            c.partition("graphd", server)

            # the ladder serves the SAME parts from the other replica:
            # complete rows, device-served, failover counters move
            deadline = time.monotonic() + 30
            good = None
            while time.monotonic() < deadline:
                r = cl.execute(goq)
                if r.ok() and r.completeness == 100 \
                        and _dst_set(r) == want \
                        and _prom_value(
                            c.metrics(other),
                            "nebula_storage_device_go_qps_total") \
                        > served0:
                    good = r
                    break
                time.sleep(0.3)
            assert good is not None, \
                "replica never device-served behind the partition"
            gm = c.metrics("graphd")
            assert _prom_value(
                gm, "nebula_graph_device_failover_retries_total") > 0, \
                "ladder never retried"
            assert _prom_value(
                gm, "nebula_graph_device_failover_served_total") > 0, \
                "no query was served by a replica via the ladder"
            c.heal()
            assert _dst_set(_ok(cl, goq)) == want

    def test_kill_follower_mid_snapshot_install(self, tmp_path):
        """Snapshot cell: a follower dead long enough for the leader's
        WAL to trim past it must catch up via snapshot transfer on
        restart; SIGKILL it again MID-INSTALL, restart once more, and
        the group still converges with zero acked loss."""
        extra = dict(FAST_RAFT)
        extra["raft_wal_keep_logs"] = 5
        with ProcCluster(str(tmp_path), num_storage=3,
                         extra_flags=extra) as c:
            cl = c.client()
            _seed_space(cl, "sn", partition_num=1, replica_factor=3)
            # find a FOLLOWER of the lone data part and kill it
            import json
            follower = None
            for name in c.storage_names:
                admin = json.loads(c.daemons[name]._http("/admin"))
                if any(st["space"] > 0 and st["role"] == "FOLLOWER"
                       for st in admin["parts"]):
                    follower = name
                    break
            assert follower, "no follower found"
            c.kill(follower, signal.SIGKILL)
            c.wait_down(follower)
            # outrun the WAL keep window, then let the ~10 s cleanup
            # pass actually trim it
            for i in range(60):
                _ok(cl, f"INSERT EDGE e(w) VALUES {i}->{i + 30000}:({i})")
            time.sleep(12.0)
            _ok(cl, "INSERT EDGE e(w) VALUES 777->30777:(1)")

            # restart; the catch-up now requires a snapshot — kill the
            # follower again INSIDE the transfer/install window
            c.restart(follower, wait=False)
            time.sleep(1.0)
            c.kill(follower, signal.SIGKILL)
            c.wait_down(follower)
            c.restart(follower)             # final recovery, gated green

            vids = ",".join(str(i) for i in range(60))
            deadline = time.monotonic() + 40
            rows = None
            while time.monotonic() < deadline:
                r = cl.execute(f"GO FROM {vids},777 OVER e YIELD e._dst")
                if r.ok() and r.completeness == 100:
                    rows = _dst_set(r)
                    break
                time.sleep(0.4)
            assert rows is not None
            expect = sorted([i + 30000 for i in range(60)] + [30777])
            assert rows == expect, "acked writes lost across snapshot chaos"
