"""Chaos suite — deterministic wire-level fault injection over real
multi-daemon clusters (docs/fault_injection.md).

The retry seams this exercises exist for exactly these failures
(reference StorageClient.inl:120-133 leader chases, MetaClient
failover, raftex elections); the FaultInjector (interface/faults.py)
finally injects them on demand: every scenario asserts queries either
return correct (possibly reported-partial) results or a clean typed
error — never a hang, never a duplicated non-idempotent write.

Scenarios use p=1 rules with times/skip bounds (deterministic by
construction) or the seeded RNG (reproducible per seed); backoff and
deadline flags are shrunk in fixtures so nothing sleeps longer than
the configured caps.
"""
import json
import time
import urllib.request

import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.flags import flags
from nebula_tpu.common.stats import stats
from nebula_tpu.common.status import ErrorCode, Status
from nebula_tpu.interface.common import HostAddr
from nebula_tpu.interface.faults import FaultInjector, default_injector
from nebula_tpu.interface.rpc import ClientManager, RpcError

pytestmark = pytest.mark.chaos


def _stat(name: str) -> float:
    return stats.read_stats(f"{name}.sum.60") or 0.0


# ---------------------------------------------------------------- fixtures
@pytest.fixture(scope="module", autouse=True)
def fast_retries():
    names = ("storage_client_retry_backoff_ms",
             "storage_client_retry_backoff_max_ms",
             "storage_client_request_deadline_ms",
             "meta_client_retry_backoff_ms",
             "meta_client_retry_backoff_max_ms")
    saved = {n: flags.get(n) for n in names}
    flags.set("storage_client_retry_backoff_ms", 5)
    flags.set("storage_client_retry_backoff_max_ms", 50)
    flags.set("storage_client_request_deadline_ms", 5000)
    flags.set("meta_client_retry_backoff_ms", 5)
    flags.set("meta_client_retry_backoff_max_ms", 50)
    yield
    for k, v in saved.items():
        flags.set(k, v)


@pytest.fixture(autouse=True)
def clean_faults():
    default_injector.clear()
    yield
    default_injector.clear()


@pytest.fixture(scope="module")
def duo():
    """2 storaged (no raft, loopback) + a seeded space: edges
    i -> i+100 for i in 1..8 over partition_num=4 spread across both
    hosts."""
    c = LocalCluster(num_storage=2)
    cl = c.client()

    def ok(stmt):
        r = cl.execute(stmt)
        assert r.ok(), f"{stmt}: {r.error_msg}"
        return r

    ok("CREATE SPACE chaos(partition_num=4, replica_factor=1)")
    c.refresh_all()
    ok("USE chaos")
    ok("CREATE TAG person(name string)")
    ok("CREATE EDGE knows(w int)")
    c.refresh_all()
    ok("INSERT EDGE knows(w) VALUES " +
       ", ".join(f"{i}->{i + 100}:({i})" for i in range(1, 9)))
    cl.ok = ok
    yield c, cl
    cl.disconnect()
    c.stop()


ALL_SRC = "GO FROM 1,2,3,4,5,6,7,8 OVER knows YIELD knows._dst"
ALL_DST = sorted(range(101, 109))


# ============================================================ unit layer
class TestInjectorUnit:
    def test_seeded_probability_is_reproducible(self):
        rules = [{"kind": "rpc_failure", "method": "m", "p": 0.5}]
        fi = FaultInjector(seed=123)
        fi.configure(rules, seed=123)
        first = [fi.intercept("h:1", "m") is not None for _ in range(30)]
        # same seed + rules -> identical fault schedule
        fi.configure(rules, seed=123)
        again = [fi.intercept("h:1", "m") is not None for _ in range(30)]
        assert first == again
        assert any(first) and not all(first)   # p=0.5 actually sampled
        # a different seed produces a different schedule
        fi.configure(rules, seed=124)
        other = [fi.intercept("h:1", "m") is not None for _ in range(30)]
        assert other != first

    def test_times_and_skip_bounds(self):
        fi = FaultInjector()
        fi.configure([{"kind": "rpc_failure", "method": "m",
                       "skip": 1, "times": 1}])
        assert fi.intercept("h:1", "m") is None          # skipped
        assert fi.intercept("h:1", "m") is not None      # fired
        assert fi.intercept("h:1", "m") is None          # times spent
        dump = fi.dump()["rules"][0]
        assert dump["hits"] == 2 and dump["fired"] == 1

    def test_kind_taxonomy(self):
        fi = FaultInjector()
        fi.configure([{"kind": "refuse_connect", "method": "a"},
                      {"kind": "rpc_failure", "method": "b"},
                      {"kind": "rpc_failure_after", "method": "c"},
                      {"kind": "leader_changed", "method": "d",
                       "leader": "x:1"}])
        assert fi.intercept("h:1", "a")[:2] == \
            ("before", ErrorCode.E_FAIL_TO_CONNECT)
        assert fi.intercept("h:1", "b")[:2] == \
            ("before", ErrorCode.E_RPC_FAILURE)
        assert fi.intercept("h:1", "c")[:2] == \
            ("after", ErrorCode.E_RPC_FAILURE)
        assert fi.intercept("h:1", "d") == \
            ("before", ErrorCode.E_LEADER_CHANGED, "x:1")
        assert fi.intercept("h:1", "nomatch") is None

    def test_delay_injects_latency_then_proceeds(self):
        fi = FaultInjector()
        fi.configure([{"kind": "delay", "method": "m", "delay_s": 0.05}])
        t0 = time.monotonic()
        assert fi.intercept("h:1", "m") is None
        assert time.monotonic() - t0 >= 0.05

    def test_bad_rules_rejected(self):
        fi = FaultInjector()
        with pytest.raises(ValueError):
            fi.configure([{"kind": "meteor_strike"}])
        with pytest.raises(ValueError):
            fi.configure([{"kind": "delay", "surprise": 1}])
        with pytest.raises(ValueError):
            fi.configure([{"method": "m"}])

    def test_flag_watcher_configures_default_injector(self):
        flags.set("fault_injection_rules",
                  '[{"kind": "delay", "method": "zz"}]')
        try:
            assert [r["method"] for r in
                    default_injector.dump()["rules"]] == ["zz"]
            # the seed flag alone reconfigures too: flagfiles apply
            # line by line, so a seed listed AFTER the rules must not
            # be silently ignored (determinism promise)
            flags.set("fault_injection_seed", 777)
            assert default_injector.dump()["seed"] == 777
        finally:
            flags.set("fault_injection_seed", 0)
            flags.set("fault_injection_rules", "")
        assert default_injector.dump()["rules"] == []


# ====================================================== storage hardening
class TestStorageRetries:
    def test_transient_connect_refusal_retried_to_success(self, duo):
        c, cl = duo
        before = _stat("storage.client.retry_attempts")
        injected = _stat("rpc.fault.injected")
        default_injector.configure(
            [{"kind": "refuse_connect", "method": "getBound", "times": 1}])
        r = cl.ok(ALL_SRC)
        assert sorted(x[0] for x in r.rows) == ALL_DST
        assert r.completeness == 100
        assert _stat("storage.client.retry_attempts") > before
        assert _stat("rpc.fault.injected") > injected

    def test_injected_leader_flap_with_bogus_hint_heals(self, duo):
        """E_LEADER_CHANGED hinting at the WRONG host: the client must
        chase the hint, get per-part E_PART_NOT_FOUND there, re-route
        from meta placement, and still deliver the full result."""
        c, cl = duo
        hosts = [n.host for n in c.storage_nodes]
        default_injector.configure(
            [{"kind": "leader_changed", "method": "getBound",
              "host": hosts[0], "times": 1, "leader": hosts[1]},
             {"kind": "leader_changed", "method": "getBound",
              "host": hosts[1], "times": 1, "leader": hosts[0]}])
        r = cl.ok(ALL_SRC)
        assert sorted(x[0] for x in r.rows) == ALL_DST
        assert r.completeness == 100

    def test_retry_exhaustion_respects_deadline_no_tight_loop(self, duo):
        """An endless leader flap must neither hang nor spin: the
        collect deadline budget bounds the whole request and the
        exhaustion is counted."""
        c, cl = duo
        saved = flags.get("storage_client_request_deadline_ms")
        flags.set("storage_client_request_deadline_ms", 400)
        try:
            default_injector.configure(
                [{"kind": "leader_changed", "method": "getBound"}])
            sid = c.graph_meta_client.get_space_id_by_name("chaos").value()
            before_exh = _stat("storage.client.retry_exhausted")
            t0 = time.monotonic()
            resp = c.storage_client.get_neighbors(sid, list(range(1, 9)),
                                                  [1], retries=1000)
            elapsed = time.monotonic() - t0
            assert elapsed < 3.0                    # deadline, not retries
            assert not resp.succeeded()
            assert resp.completeness() == 0
            assert _stat("storage.client.retry_exhausted") > before_exh
            assert _stat("storage.client.backoff_ms") > 0
        finally:
            flags.set("storage_client_request_deadline_ms", saved)

    def test_reply_loss_on_write_is_not_resent(self):
        """rpc_failure_after = the storaged EXECUTED the write and the
        reply was lost.  The client must surface a typed error, NOT
        resend (non-idempotent duplication risk) — the op lands exactly
        once."""
        c = LocalCluster(num_storage=1)
        cl = c.client()
        try:
            for stmt in ("CREATE SPACE once(partition_num=2, "
                         "replica_factor=1)",):
                assert cl.execute(stmt).ok()
            c.refresh_all()
            assert cl.execute("USE once").ok()
            assert cl.execute("CREATE EDGE e(w int)").ok()
            c.refresh_all()
            node = c.storage_nodes[0]
            calls = []
            real = node.service.rpc_addEdges

            def counting(req):
                calls.append(req)
                return real(req)

            node.service.rpc_addEdges = counting
            default_injector.configure(
                [{"kind": "rpc_failure_after", "method": "addEdges",
                  "times": 1}])
            r = cl.execute("INSERT EDGE e(w) VALUES 1->2:(7)")
            assert not r.ok()
            assert "E_RPC_FAILURE" in r.error_msg
            assert len(calls) == 1          # executed once, never resent
            default_injector.clear()
            # the write really landed (reply was lost, op was not)
            rows = cl.execute("GO FROM 1 OVER e YIELD e._dst").rows
            assert [x[0] for x in rows] == [2]
        finally:
            cl.disconnect()
            c.stop()

    def test_partial_results_report_completeness(self, duo):
        """Fan-out where one host is blackholed: the response keeps the
        surviving parts' rows AND reports completeness < 100 + a
        warning instead of silently degrading."""
        c, cl = duo
        sid = c.graph_meta_client.get_space_id_by_name("chaos").value()
        alloc = c.graph_meta_client.parts_alloc(sid)
        dead_host = c.storage_nodes[1].host
        surviving = sorted(
            i + 100 for i in range(1, 9)
            if alloc[c.storage_client.part_id(sid, i)][0] != dead_host)
        assert surviving and len(surviving) < 8     # both hosts hold parts
        before_partial = _stat("graph.partial_result.qps")
        default_injector.configure(
            [{"kind": "blackhole", "method": "getBound",
              "host": dead_host}])
        r = cl.execute(ALL_SRC)
        assert r.ok()
        assert sorted(x[0] for x in r.rows) == surviving
        assert 0 < r.completeness < 100
        assert r.warnings and "parts failed" in r.warnings[0]
        assert _stat("graph.partial_result.qps") > before_partial
        # recovery: faults off -> full results, no completeness field
        default_injector.clear()
        r = cl.ok(ALL_SRC)
        assert sorted(x[0] for x in r.rows) == ALL_DST
        assert r.completeness == 100 and not r.warnings


# ========================================================= meta hardening
class TestMetaResilience:
    def test_metad_blackhole_degrades_to_cached_metadata(self, duo):
        """metad down mid-flight: reads on cached metadata keep working,
        heartbeats fail with a clean Status, DDL errors cleanly (typed,
        no hang), cache misses error cleanly — and everything recovers
        when the fault lifts."""
        c, cl = duo
        default_injector.configure(
            [{"kind": "blackhole", "host": str(c.meta_addr)}])
        # cached read path unaffected
        r = cl.ok(ALL_SRC)
        assert sorted(x[0] for x in r.rows) == ALL_DST
        # heartbeat: clean Status error, not an exception
        hb = c.storage_nodes[0].meta_client.heartbeat()
        assert not hb.ok()
        # DDL: clean typed error
        before_exh = _stat("meta.client.retry_exhausted")
        r = cl.execute("CREATE SPACE nope(partition_num=1)")
        assert not r.ok()
        assert r.error_code != ErrorCode.SUCCEEDED
        assert _stat("meta.client.retry_exhausted") > before_exh
        # cache miss: clean error (space was never cached)
        r = cl.execute("USE never_created")
        assert not r.ok()
        # recovery
        default_injector.clear()
        assert cl.execute("CREATE SPACE nope(partition_num=1)").ok()
        assert c.storage_nodes[0].meta_client.heartbeat().ok()

    def test_hint_chase_is_bounded(self):
        """A chain of metads bouncing not-a-leader hints at each other
        must terminate within meta_client_max_hint_chase per pass
        instead of chasing forever."""
        cm = ClientManager()
        called = []

        class Bouncer:
            def __init__(self, me, nxt):
                self.me, self.nxt = me, nxt

            def rpc_listSpaces(self, payload):
                called.append(self.me)
                raise RpcError(Status(ErrorCode.E_NOT_A_LEADER, self.nxt))

        n = 10
        for i in range(n):
            cm.register_loopback(
                HostAddr(f"m{i}", 1),
                Bouncer(f"m{i}:1", f"m{i + 1}:1" if i + 1 < n else ""))
        from nebula_tpu.meta.client import MetaClient
        mc = MetaClient([HostAddr("m0", 1)], client_manager=cm)
        max_chase = flags.get("meta_client_max_hint_chase", 3)
        with pytest.raises(RpcError) as ei:
            mc._call("listSpaces", {})
        assert ei.value.status.code == ErrorCode.E_NOT_A_LEADER
        per_pass = 1 + max_chase
        assert len(called) <= mc._CALL_PASSES * per_pass
        assert len(set(called)) <= per_pass   # never walked the chain


# ==================================================== device fallback
class TestTpuFallback:
    def test_storaged_blackhole_falls_back_to_cpu(self):
        """deviceGo blackholed: the remote device runtime declines and
        the per-hop CPU scatter-gather path serves the same rows."""
        c = LocalCluster(num_storage=1, tpu_backend="remote")
        cl = c.client()
        try:
            def ok(stmt):
                r = cl.execute(stmt)
                assert r.ok(), f"{stmt}: {r.error_msg}"
                return r

            ok("CREATE SPACE dev(partition_num=2, replica_factor=1)")
            c.refresh_all()
            ok("USE dev")
            ok("CREATE EDGE follow(d int)")
            c.refresh_all()
            ok("INSERT EDGE follow(d) VALUES 1->2:(5), 2->3:(6), 1->3:(7)")
            q = "GO 2 STEPS FROM 1 OVER follow YIELD follow._dst"
            expect = sorted(x[0] for x in ok(q).rows)
            injected = _stat("rpc.fault.injected")
            default_injector.configure(
                [{"kind": "blackhole", "method": "deviceGo"},
                 {"kind": "blackhole", "method": "deviceFindPath"}])
            r = ok(q)
            assert sorted(x[0] for x in r.rows) == expect
            assert r.completeness == 100
            assert _stat("rpc.fault.injected") > injected
        finally:
            cl.disconnect()
            c.stop()


# ===================================================== replicated chaos
@pytest.fixture()
def fast_raft():
    saved = {n: flags.get(n) for n in
             ("raft_heartbeat_interval_s", "raft_election_timeout_s")}
    flags.set("raft_heartbeat_interval_s", 0.1)
    flags.set("raft_election_timeout_s", 0.8)
    yield
    for k, v in saved.items():
        flags.set(k, v)


def _wait_leaders(cluster, space_parts, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        elected = sum(1 for node in cluster.storage_nodes
                      if node.raft_service is not None
                      for st in node.raft_service.status()
                      if st["role"] == "LEADER")
        if elected >= space_parts:
            return
        time.sleep(0.05)
    raise AssertionError("raft groups failed to elect")


class TestReplicatedChaos:
    def test_leader_kill_mid_go_returns_complete_results(self, fast_raft):
        """Kill the storaged leading the queried part between two GOs:
        every response during failover is ok (possibly partial) or a
        typed error — and once raft re-elects, the SAME query returns
        complete (completeness == 100) correct results."""
        c = LocalCluster(num_storage=3, use_raft=True)
        cl = c.client()
        try:
            def ok(stmt, tries=40):
                last = None
                for _ in range(tries):
                    last = cl.execute(stmt)
                    if last.ok():
                        return last
                    time.sleep(0.25)
                raise AssertionError(f"{stmt}: {last.error_msg}")

            ok("CREATE SPACE rk(partition_num=2, replica_factor=3)")
            c.refresh_all()
            _wait_leaders(c, space_parts=2)
            ok("USE rk")
            ok("CREATE EDGE e(w int)")
            c.refresh_all()
            ok("INSERT EDGE e(w) VALUES 1->2:(7), 2->3:(8)")
            q = "GO FROM 1,2 OVER e YIELD e._dst"
            r = ok(q)
            assert sorted(x[0] for x in r.rows) == [2, 3]

            # find and hard-kill the node leading vid 1's part
            sid = c.graph_meta_client.get_space_id_by_name("rk").value()
            part = c.storage_client.part_id(sid, 1)
            victim = next(
                node for node in c.storage_nodes
                for st in node.raft_service.status()
                if st["space"] == sid and st["part"] == part
                and st["role"] == "LEADER")
            c.cm.unregister_loopback(HostAddr.parse(victim.host))
            victim.stop()

            # failover window: responses are clean (ok-or-typed-error,
            # never a hang — the deadline budget bounds each attempt);
            # eventually the result is COMPLETE and correct again
            deadline = time.monotonic() + 25
            final = None
            while time.monotonic() < deadline:
                r = cl.execute(q)
                if r.ok() and r.completeness == 100 \
                        and sorted(x[0] for x in r.rows) == [2, 3]:
                    final = r
                    break
                assert isinstance(r.error_msg, str)
                time.sleep(0.2)
            assert final is not None, "failover never converged"
            # writes keep working through the surviving quorum
            ok("INSERT EDGE e(w) VALUES 3->4:(9)")
            r = ok("GO FROM 3 OVER e YIELD e._dst")
            assert sorted(x[0] for x in r.rows) == [4]
        finally:
            cl.disconnect()
            c.stop()

    @pytest.mark.slow
    def test_slow_peer_triggers_election_queries_survive(self, fast_raft):
        """Delay every raft RPC to one follower past the election
        timeout: terms churn, and queries still answer correctly once
        the fault lifts (wall-clock-heavy: real election waits)."""
        c = LocalCluster(num_storage=3, use_raft=True)
        cl = c.client()
        try:
            def ok(stmt, tries=40):
                last = None
                for _ in range(tries):
                    last = cl.execute(stmt)
                    if last.ok():
                        return last
                    time.sleep(0.25)
                raise AssertionError(f"{stmt}: {last.error_msg}")

            ok("CREATE SPACE sp(partition_num=1, replica_factor=3)")
            c.refresh_all()
            _wait_leaders(c, space_parts=1)
            ok("USE sp")
            ok("CREATE EDGE e(w int)")
            c.refresh_all()
            ok("INSERT EDGE e(w) VALUES 1->2:(7)")
            leader_node = next(
                node for node in c.storage_nodes
                for st in node.raft_service.status()
                if st["role"] == "LEADER")
            term0 = max(st["term"]
                        for st in leader_node.raft_service.status())
            # stall the LEADER's outbound heartbeats: followers time
            # out.  The per-call delay must clear the WORST-case
            # randomized election timeout (base * 2, raft_part.py
            # _reset_election_timeout) or the scenario is a coin flip
            # on the follower's draw
            stall_s = 2 * flags.get("raft_election_timeout_s") + 0.5
            default_injector.configure(
                [{"kind": "delay", "method": "raftAppendLog",
                  "delay_s": stall_s, "times": 10}])
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                terms = [st["term"] for node in c.storage_nodes
                         if node.raft_service
                         for st in node.raft_service.status()]
                if terms and max(terms) > term0:
                    break
                time.sleep(0.1)
            assert max(
                st["term"] for node in c.storage_nodes
                if node.raft_service
                for st in node.raft_service.status()) > term0
            default_injector.clear()
            # the new leader commits/applies the entry on its first
            # heartbeat round — an ok-but-empty response in that window
            # is legal, so poll for the converged result
            deadline = time.monotonic() + 15
            rows = None
            while time.monotonic() < deadline:
                r = cl.execute("GO FROM 1 OVER e YIELD e._dst")
                if r.ok() and r.completeness == 100:
                    rows = sorted(x[0] for x in r.rows)
                    if rows == [2]:
                        break
                time.sleep(0.2)
            assert rows == [2]
        finally:
            cl.disconnect()
            c.stop()


# ======================================================== ops surface
class TestFaultsEndpoint:
    def test_faults_roundtrip_over_http(self):
        from nebula_tpu.webservice import WebService
        ws = WebService("test").start()
        base = f"http://127.0.0.1:{ws.port}"
        try:
            got = json.load(urllib.request.urlopen(f"{base}/faults"))
            assert got["rules"] == []
            body = json.dumps({"seed": 99, "rules": [
                {"kind": "delay", "method": "getBound",
                 "delay_s": 0.01}]}).encode()
            req = urllib.request.Request(f"{base}/faults", data=body,
                                         method="PUT")
            got = json.load(urllib.request.urlopen(req))
            assert got["seed"] == 99
            assert got["rules"][0]["kind"] == "delay"
            # the process-global injector picked it up
            assert default_injector.dump()["seed"] == 99
            default_injector.intercept("h:1", "getBound")
            got = json.load(urllib.request.urlopen(f"{base}/faults"))
            assert got["rules"][0]["hits"] == 1
            assert got["rules"][0]["fired"] == 1
            # bad kinds are refused with a 400
            bad = urllib.request.Request(
                f"{base}/faults",
                data=json.dumps([{"kind": "nope"}]).encode(),
                method="PUT")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad)
            assert ei.value.code == 400
            # empty rule list turns injection off
            off = urllib.request.Request(
                f"{base}/faults", data=b'{"rules": []}', method="PUT")
            assert json.load(urllib.request.urlopen(off))["rules"] == []
            assert not default_injector.active()
        finally:
            ws.stop()

    def test_injected_fault_kinds_visible_per_method(self, duo):
        """Which faults did a query actually absorb?  The injector
        bumps a per-method counter and drops a marker span on the
        active trace, so chaos runs can assert the schedule landed."""
        from nebula_tpu.common.tracing import trace_store
        c, cl = duo
        m0 = stats.read_stats(
            "rpc.fault_injected.getBound.count.3600") or 0
        default_injector.configure(
            [{"kind": "refuse_connect", "method": "getBound",
              "times": 1}])
        r = cl.execute("PROFILE " + ALL_SRC)
        default_injector.clear()
        assert r.ok()
        assert sorted(v for (v,) in map(tuple, r.rows)) == ALL_DST
        assert (stats.read_stats("rpc.fault_injected.getBound"
                                 ".count.3600") or 0) > m0
        # the PROFILE tree carries the fault marker with its kind
        spans = trace_store.spans(int(r.profile["trace_id"], 16))
        marks = [s for s in spans if s["name"] == "rpc.fault"]
        assert marks and marks[0]["tags"]["fault"] == "refuse_connect"
        assert marks[0]["tags"]["method"] == "getBound"

    def test_retry_counters_visible_on_get_stats(self, duo):
        c, cl = duo
        default_injector.configure(
            [{"kind": "refuse_connect", "method": "getBound",
              "times": 1}])
        cl.ok(ALL_SRC)
        default_injector.clear()
        from nebula_tpu.webservice import WebService
        ws = WebService("test").start()
        try:
            got = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{ws.port}/get_stats"))
            assert got["storage.client.retry_attempts"]["sum.60"] > 0
            assert "meta.client.retry_attempts" in got
            assert got["rpc.fault.injected"]["sum.60"] > 0
        finally:
            ws.stop()


# ===================================================== deadline budgets
class TestWholeRequestDeadline:
    """Retry-storm guard (docs/admission.md): injected latency must
    never let retries outlive the whole-request deadline — shed/expired
    queries return DEADLINE_EXCEEDED with completeness < 100 instead of
    hanging, and the backoff ladders consume only the remaining
    budget."""

    def test_injected_latency_cannot_outlive_query_deadline(self, duo):
        c, cl = duo
        default_injector.configure(
            [{"kind": "delay", "method": "getBound", "delay_s": 0.5}])
        t0 = time.monotonic()
        r = cl.execute("TIMEOUT 300 GO FROM 1,2,3,4,5,6,7,8 OVER knows "
                       "YIELD knows._dst")
        elapsed = time.monotonic() - t0
        # the injected 0.5 s/call latency x 4 parts x retry passes
        # would run for many seconds unbounded — the 300 ms budget
        # caps the whole statement (one absorbed delay + fast failure)
        assert elapsed < 3.0, f"retries outlived the deadline: {elapsed}s"
        assert r.error_code == ErrorCode.E_DEADLINE_EXCEEDED, (
            r.error_code, r.error_msg)
        assert r.completeness < 100
        assert r.warnings, "deadline failure must carry warnings"

    def test_storage_retry_passes_consume_remaining_budget_only(self, duo):
        """A flapping leader under a bound budget: the collect loop's
        backoff + passes fit the remaining deadline (never extend it)
        and the exhaustion surfaces as the typed deadline status."""
        from nebula_tpu.common import deadline as deadlines
        from nebula_tpu.common.deadline import Deadline
        c, cl = duo
        default_injector.configure(
            [{"kind": "leader_changed", "method": "getBound"}])
        sid = c.graph_meta_client.get_space_id_by_name("chaos").value()
        saved = flags.get("storage_client_request_deadline_ms")
        flags.set("storage_client_request_deadline_ms", 60000)
        try:
            t0 = time.monotonic()
            with deadlines.bind(Deadline.after_ms(350)):
                resp = c.storage_client.get_neighbors(
                    sid, list(range(1, 9)), [1], retries=1000)
            elapsed = time.monotonic() - t0
        finally:
            flags.set("storage_client_request_deadline_ms", saved)
        # the 60 s collect flag did NOT win: the narrower thread budget
        # clamped the whole retry ladder
        assert elapsed < 3.0, f"budget not honored: {elapsed}s"
        assert not resp.succeeded() and resp.completeness() == 0

    def test_meta_retry_backoff_fits_remaining_budget(self, duo):
        from nebula_tpu.common import deadline as deadlines
        from nebula_tpu.common.deadline import Deadline
        c, cl = duo
        saved = {n: flags.get(n) for n in
                 ("meta_client_retry_backoff_ms",
                  "meta_client_retry_backoff_max_ms")}
        flags.set("meta_client_retry_backoff_ms", 800)
        flags.set("meta_client_retry_backoff_max_ms", 800)
        default_injector.configure(
            [{"kind": "blackhole", "method": "listSpaces"}])
        before = _stat("meta.client.deadline_exceeded")
        try:
            t0 = time.monotonic()
            with deadlines.bind(Deadline.after_ms(250)):
                r = c.graph_meta_client.call("listSpaces", {})
            elapsed = time.monotonic() - t0
        finally:
            for k, v in saved.items():
                flags.set(k, v)
        assert not r.ok()
        # without the budget, 4 whole-peer passes at ~0.8 s backoff
        # would run ~2.4 s — the 250 ms budget refuses the first sleep
        assert elapsed < 1.5, f"backoff outlived the budget: {elapsed}s"
        assert r.status.code == ErrorCode.E_DEADLINE_EXCEEDED
        assert _stat("meta.client.deadline_exceeded") > before

    def test_no_deadline_means_no_behavior_change(self, duo):
        """The whole plumbing is pay-for-what-you-use: with no binding
        and query_deadline_ms=0 the statement runs exactly as before
        (chaos-free sanity guard for the default path)."""
        c, cl = duo
        saved = flags.get("query_deadline_ms")
        flags.set("query_deadline_ms", 0)
        try:
            r = cl.execute("GO FROM 1,2,3,4,5,6,7,8 OVER knows "
                           "YIELD knows._dst")
        finally:
            flags.set("query_deadline_ms", saved)
        assert r.ok(), r.error_msg
        assert sorted(x[0] for x in r.rows) == ALL_DST
