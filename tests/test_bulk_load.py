"""Bulk loader parity: a space loaded via vectorized ingest files
(tools/bulk_load.py) must be indistinguishable — scan-for-scan and
query-for-query — from the same data loaded through INSERT statements.
"""
import numpy as np
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.codec.rows import encode_row
from nebula_tpu.tools import bulk_load as BL


@pytest.fixture()
def cluster():
    c = LocalCluster(num_storage=1, tpu_backend=False)
    yield c
    c.stop()


def _mk_space(c, g, name):
    def ok(s):
        r = g.execute(s)
        assert r.ok(), f"{s}: {r.error_msg}"

    ok(f"CREATE SPACE {name}(partition_num=4, replica_factor=1)")
    c.refresh_all()
    ok(f"USE {name}")
    ok("CREATE TAG person(age int)")
    ok("CREATE EDGE knows(w int)")
    c.refresh_all()
    sid = c.graph_meta_client.get_space_id_by_name(name).value()
    tag = c.schema_man.to_tag_id(sid, "person").value()
    et = c.schema_man.to_edge_type(sid, "knows").value()
    return ok, sid, tag, et


def test_bulk_load_matches_insert_load(cluster, tmp_path):
    c = cluster
    g = c.client()
    rng = np.random.default_rng(3)
    n, m = 50, 200
    src = rng.integers(1, n + 1, m)
    dst = rng.integers(1, n + 1, m)
    w = rng.integers(0, 7, m)
    vids = np.arange(1, n + 1)
    ages = rng.integers(18, 25, n)

    # ---- reference: INSERT statements -------------------------------
    ok, _, _, _ = _mk_space(c, g, "ins")
    vv = ", ".join(f"{v}:({a})" for v, a in zip(vids, ages))
    ok(f"INSERT VERTEX person(age) VALUES {vv}")
    ev = ", ".join(f"{s} -> {d}:({x})" for s, d, x in zip(src, dst, w))
    ok(f"INSERT EDGE knows(w) VALUES {ev}")

    # ---- bulk: vectorized ingest ------------------------------------
    ok2, sid, tag, et = _mk_space(c, g, "blk")
    schema_e = c.schema_man.get_edge_schema(sid, et)
    schema_t = c.schema_man.get_tag_schema(sid, tag)
    # low-cardinality blobs + per-row index (fixed-width requirement)
    e_blobs = [encode_row(schema_e, {"w": int(i)}) for i in range(7)]
    t_blobs = [encode_row(schema_t, {"age": int(a)})
               for a in range(18, 25)]
    store = c.storage_nodes[0].kv
    nparts = len(store.part_ids(sid))
    groups = [
        BL.edge_frames(nparts, et, src, dst, e_blobs, w),
        BL.vertex_frames(nparts, tag, vids, t_blobs, ages - 18),
    ]
    st = BL.bulk_load(store, sid, str(tmp_path), groups)
    assert st.ok(), st

    # ---- parity: same queries, same rows ----------------------------
    for q in [
        "GO FROM 1 OVER knows YIELD knows._dst, knows.w",
        "GO 2 STEPS FROM 5 OVER knows",
        "GO FROM 7 OVER knows WHERE knows.w > 3 YIELD knows._dst",
        "GO FROM 3 OVER knows YIELD $$.person.age AS a",
        "GO FROM 11 OVER knows REVERSELY",
        "FETCH PROP ON person 9 YIELD person.age",
    ]:
        g.execute("USE ins")
        a = g.execute(q)
        g.execute("USE blk")
        b = g.execute(q)
        assert a.ok() and b.ok(), (q, a.error_msg, b.error_msg)
        assert sorted(map(tuple, a.rows)) == sorted(map(tuple, b.rows)), q

    # ---- parity at the mirror level ---------------------------------
    from nebula_tpu.tpu.csr import build_mirror
    sid_ins = c.graph_meta_client.get_space_id_by_name("ins").value()
    m_ins = build_mirror(sid_ins, [store], c.schema_man)
    m_blk = build_mirror(sid, [store], c.schema_man)
    np.testing.assert_array_equal(m_ins.vids, m_blk.vids)
    np.testing.assert_array_equal(m_ins.edge_src, m_blk.edge_src)
    np.testing.assert_array_equal(m_ins.edge_dst, m_blk.edge_dst)
    # etype ids differ across spaces (meta assigns per space); the
    # direction structure must match
    np.testing.assert_array_equal(np.sign(m_ins.edge_etype),
                                  np.sign(m_blk.edge_etype))


def test_bulk_load_bumps_version_and_serves_device(cluster, tmp_path):
    """Ingest must invalidate mirrors (store version bump) and the
    bulk-loaded graph must serve on the device path."""
    c = cluster
    g = c.client()
    ok, sid, tag, et = _mk_space(c, g, "blk2")
    store = c.storage_nodes[0].kv
    v0 = store.mutation_version(sid)
    src = np.asarray([1, 2, 3])
    dst = np.asarray([2, 3, 4])
    schema_e = c.schema_man.get_edge_schema(sid, et)
    blobs = [encode_row(schema_e, {"w": 1})]
    st = BL.bulk_load(store, sid, str(tmp_path),
                      [BL.edge_frames(len(store.part_ids(sid)), et,
                                      src, dst, blobs,
                                      np.zeros(3, np.int64))])
    assert st.ok()
    assert store.mutation_version(sid) > v0
    r = g.execute("GO 3 STEPS FROM 1 OVER knows")
    assert r.ok() and sorted(map(tuple, r.rows)) == [(4,)]
