"""Adaptive backend router (graph/backend_router.py): per query family
it measures both paths and routes to the cheaper one, with a probe
stream keeping the loser's estimate fresh.  Results never change —
both paths are exact — only where the work runs.
"""
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.flags import flags
from nebula_tpu.graph.backend_router import BackendRouter


def test_unit_converges_to_cheaper_path_and_probes():
    r = BackendRouter()
    key = (1, (2,), 3)
    # feed: device consistently 10ms, cpu 2ms
    for _ in range(60):
        pick = r.choose(key)
        r.record(key, pick, 0.010 if pick == "device" else 0.002)
    # steady state: overwhelmingly cpu, with a live probe stream
    routed = {"device": 0, "cpu": 0}
    for _ in range(100):
        pick = r.choose(key)
        routed[pick] += 1
        r.record(key, pick, 0.010 if pick == "device" else 0.002)
    assert routed["cpu"] > 80, routed
    assert routed["device"] >= 1, "probe stream must keep measuring"

    # regime change: device becomes fast — the router must follow
    for _ in range(200):
        pick = r.choose(key)
        r.record(key, pick, 0.001 if pick == "device" else 0.002)
    routed = {"device": 0, "cpu": 0}
    for _ in range(100):
        pick = r.choose(key)
        routed[pick] += 1
        r.record(key, pick, 0.001 if pick == "device" else 0.002)
    assert routed["device"] > 80, routed


def test_e2e_routing_preserves_results():
    c = LocalCluster(num_storage=1, tpu_backend=True)
    prev = flags.get("go_backend_router")
    flags.set("go_backend_router", True)
    try:
        g = c.client()
        assert g.execute("CREATE SPACE rtr(partition_num=4)").ok()
        c.refresh_all()
        assert g.execute("USE rtr").ok()
        assert g.execute("CREATE EDGE e(w int)").ok()
        c.refresh_all()
        assert g.execute(
            "INSERT EDGE e(w) VALUES 1->2:(7), 2->3:(9), 3->4:(5)").ok()
        expect = [(3,)]
        for i in range(30):   # alternating warmup routes both paths
            r = g.execute("GO 2 STEPS FROM 1 OVER e")
            assert r.ok(), r.error_msg
            assert sorted(map(tuple, r.rows)) == expect, f"iter {i}"
        st = c.graph_service.engine.router.stats
        assert st["routed_device"] > 0 and st["routed_cpu"] > 0, st
    finally:
        flags.set("go_backend_router", prev)
        c.stop()


class TestRouterSoak:
    """Regime-change convergence + probe overhead — the soak the
    default-on decision rests on (etc/*.conf.default ships
    go_backend_router=true)."""

    def test_converges_after_regime_flip(self):
        from nebula_tpu.graph.backend_router import BackendRouter
        r = BackendRouter()
        key = (1, (1,), 3)
        # regime 1: device 2 ms, cpu 10 ms -> router must settle device
        for _ in range(200):
            pick = r.choose(key)
            r.record(key, pick, 0.002 if pick == "device" else 0.010)
        d0 = r.stats["routed_device"]
        c0 = r.stats["routed_cpu"]
        assert d0 > 4 * c0, (d0, c0)
        # regime 2 (graph grew 100x: the dense dispatch now dominates):
        # device 50 ms, cpu 5 ms -> must converge to cpu within a few
        # probe periods
        flip_at = None
        for i in range(300):
            pick = r.choose(key)
            r.record(key, pick, 0.050 if pick == "device" else 0.005)
            if flip_at is None and pick == "cpu" \
                    and r._fams[key].device_s > r._fams[key].cpu_s:
                flip_at = i
        assert flip_at is not None and flip_at <= 100, flip_at
        # after convergence the slower path only sees the probe stream
        d1, c1 = r.stats["routed_device"], r.stats["routed_cpu"]
        for _ in range(200):
            pick = r.choose(key)
            r.record(key, pick, 0.050 if pick == "device" else 0.005)
        probes_to_device = r.stats["routed_device"] - d1
        assert probes_to_device <= 200 // 20, probes_to_device

    def test_probe_overhead_bounded(self):
        from nebula_tpu.common.flags import flags
        from nebula_tpu.graph.backend_router import BackendRouter
        r = BackendRouter()
        key = (2, (1,), 2)
        n = 2000
        probe_every = int(flags.get("go_router_probe_every") or 25)
        for _ in range(n):
            pick = r.choose(key)
            r.record(key, pick, 0.001 if pick == "device" else 0.008)
        # probe stream = 1/probe_every of steady-state traffic (+ the
        # cold-start alternation)
        assert r.stats["probes"] <= n // probe_every + 2
        assert r.stats["routed_cpu"] <= n // probe_every + 10
