"""Adaptive backend router (graph/backend_router.py): per query family
it measures both paths and routes to the cheaper one, with a probe
stream keeping the loser's estimate fresh.  Results never change —
both paths are exact — only where the work runs.
"""
import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.flags import flags
from nebula_tpu.graph.backend_router import BackendRouter


def test_unit_converges_to_cheaper_path_and_probes():
    r = BackendRouter()
    key = (1, (2,), 3)
    # feed: device consistently 10ms, cpu 2ms
    for _ in range(60):
        pick = r.choose(key)
        r.record(key, pick, 0.010 if pick == "device" else 0.002)
    # steady state: overwhelmingly cpu, with a live probe stream
    routed = {"device": 0, "cpu": 0}
    for _ in range(100):
        pick = r.choose(key)
        routed[pick] += 1
        r.record(key, pick, 0.010 if pick == "device" else 0.002)
    assert routed["cpu"] > 80, routed
    assert routed["device"] >= 1, "probe stream must keep measuring"

    # regime change: device becomes fast — the router must follow
    for _ in range(200):
        pick = r.choose(key)
        r.record(key, pick, 0.001 if pick == "device" else 0.002)
    routed = {"device": 0, "cpu": 0}
    for _ in range(100):
        pick = r.choose(key)
        routed[pick] += 1
        r.record(key, pick, 0.001 if pick == "device" else 0.002)
    assert routed["device"] > 80, routed


def test_e2e_routing_preserves_results():
    c = LocalCluster(num_storage=1, tpu_backend=True)
    prev = flags.get("go_backend_router")
    flags.set("go_backend_router", True)
    try:
        g = c.client()
        assert g.execute("CREATE SPACE rtr(partition_num=4)").ok()
        c.refresh_all()
        assert g.execute("USE rtr").ok()
        assert g.execute("CREATE EDGE e(w int)").ok()
        c.refresh_all()
        assert g.execute(
            "INSERT EDGE e(w) VALUES 1->2:(7), 2->3:(9), 3->4:(5)").ok()
        expect = [(3,)]
        for i in range(30):   # alternating warmup routes both paths
            r = g.execute("GO 2 STEPS FROM 1 OVER e")
            assert r.ok(), r.error_msg
            assert sorted(map(tuple, r.rows)) == expect, f"iter {i}"
        st = c.graph_service.engine.router.stats
        assert st["routed_device"] > 0 and st["routed_cpu"] > 0, st
    finally:
        flags.set("go_backend_router", prev)
        c.stop()
