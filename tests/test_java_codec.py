"""Java-encode -> Python-decode differential for
clients/java/NativeCodec.java (ROADMAP carried-over debt: the JVM
binding had never been compiled by any test).

``NativeCodec.encodeRow`` is a pure-Java encoder of the framework's
row wire format (codec/rows.py) — so the differential needs no native
library and no JVM FFI at runtime: a tiny Java driver encodes a fixed
row set and prints hex; Python asserts byte-exact equality with its
own ``encode_row`` AND decodes the Java bytes through ``RowReader``.
Both directions of drift (format change here, transcription bug there)
fail the test.  Skips cleanly when javac is absent or predates JDK 22
(the file uses the finalized FFM API for its decode half, which the
compiler must accept even though the driver never calls it).
"""
import re
import shutil
import subprocess
from pathlib import Path

import pytest

from nebula_tpu.codec.rows import RowReader, encode_row
from nebula_tpu.interface.common import ColumnDef, Schema, SupportedType

REPO = Path(__file__).resolve().parent.parent
JAVA_DIR = REPO / "clients" / "java"

# one column of every wire type, exercising varint edge shapes
# (negative zigzag, >32-bit magnitude), float vs double width, utf-8
# multibyte strings, and both bool values across the row set
COLUMNS = [
    ("name", SupportedType.STRING),
    ("age", SupportedType.INT),
    ("vid", SupportedType.VID),
    ("ratio", SupportedType.FLOAT),
    ("score", SupportedType.DOUBLE),
    ("active", SupportedType.BOOL),
    ("ts", SupportedType.TIMESTAMP),
]
SCHEMA_VER = 3
ROWS = [
    {"name": "héllo☃", "age": -42, "vid": 1 << 40, "ratio": 1.25,
     "score": 3.5, "active": True, "ts": 1_700_000_000},
    {"name": "", "age": 0, "vid": 0, "ratio": -0.5, "score": -2.0,
     "active": False, "ts": 0},
    {"name": "x" * 200, "age": (1 << 62), "vid": -7, "ratio": 0.0,
     "score": 1e300, "active": True, "ts": -1},
]

_DRIVER = """
package com.nebulatpu.client;

import java.util.List;

public final class EncodeMain {
    public static void main(String[] args) {
        byte[] types = {NativeCodec.T_STRING, NativeCodec.T_INT,
                        NativeCodec.T_VID, NativeCodec.T_FLOAT,
                        NativeCodec.T_DOUBLE, NativeCodec.T_BOOL,
                        NativeCodec.T_TIMESTAMP};
        Object[][] rows = {
            {"héllo☃", -42L, 1L << 40, 1.25f, 3.5d, true,
             1700000000L},
            {"", 0L, 0L, -0.5f, -2.0d, false, 0L},
            {"x".repeat(200), 1L << 62, -7L, 0.0f, 1e300d, true, -1L},
        };
        for (Object[] row : rows) {
            byte[] b = NativeCodec.encodeRow(3L, types, List.of(row));
            StringBuilder sb = new StringBuilder();
            for (byte x : b) sb.append(String.format("%02x", x));
            System.out.println(sb);
        }
    }
}
"""


def _javac_major():
    out = subprocess.run(["javac", "--version"], capture_output=True,
                         text=True)
    m = re.search(r"(\d+)", out.stdout or out.stderr or "")
    return int(m.group(1)) if m else 0


@pytest.mark.skipif(shutil.which("javac") is None
                    or shutil.which("java") is None, reason="no jdk")
def test_java_encode_python_decode_differential(tmp_path):
    if _javac_major() < 22:
        pytest.skip("NativeCodec.java needs the JDK 22 FFM API")
    driver = tmp_path / "EncodeMain.java"
    driver.write_text(_DRIVER, encoding="utf-8")
    subprocess.run(
        ["javac", "-encoding", "utf-8", "-d", str(tmp_path),
         str(JAVA_DIR / "NativeCodec.java"), str(driver)],
        check=True, capture_output=True)
    out = subprocess.run(
        ["java", "-cp", str(tmp_path), "-Dfile.encoding=UTF-8",
         "com.nebulatpu.client.EncodeMain"],
        check=True, capture_output=True, text=True, encoding="utf-8")
    blobs = [bytes.fromhex(line)
             for line in out.stdout.strip().splitlines()]
    assert len(blobs) == len(ROWS)

    schema = Schema(columns=[ColumnDef(n, t) for n, t in COLUMNS],
                    version=SCHEMA_VER)
    for blob, expect in zip(blobs, ROWS):
        # byte-exact: the Java encoder IS the Python wire format
        assert blob == encode_row(schema, expect)
        # and the Python reader round-trips every field
        r = RowReader(blob, schema)
        for name, typ in COLUMNS:
            got = r.get(name)
            if typ == SupportedType.FLOAT:
                assert got == pytest.approx(expect[name], rel=1e-6)
            elif typ == SupportedType.BOOL:
                assert bool(got) is expect[name]
            else:
                assert got == expect[name], (name, got, expect[name])
