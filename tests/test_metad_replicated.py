"""Replicated metad: a 3-instance catalog raft group in one process
(the reference replicates metad over the same raftex as storage,
MetaDaemon.cpp:58-78).  Proves: DDL through the leader replicates;
followers refuse with the leader hint; killing the leader re-elects and
DDL continues; clients (and their caches) follow the new leader.
"""
import time
from types import SimpleNamespace

import pytest

from nebula_tpu.daemons import metad
from nebula_tpu.interface.common import HostAddr, Schema, ColumnDef, \
    SupportedType, schema_to_wire
from nebula_tpu.interface.rpc import ClientManager, RpcError
from nebula_tpu.meta.client import MetaClient
from nebula_tpu.meta.schema_manager import ServerBasedSchemaManager
from nebula_tpu.meta.service import META_PART, META_SPACE


def _margs(port, metas, tmp_path):
    return SimpleNamespace(
        local_ip="127.0.0.1", port=port,
        meta_server_addrs=",".join(metas),
        data_path=None, wal_path=str(tmp_path / f"wal{port}"))


class Quorum:
    def __init__(self, tmp_path):
        self.cm = ClientManager()
        self.addrs = [f"127.0.0.1:{45600 + i}" for i in range(3)]
        self.nodes = []
        for i, a in enumerate(self.addrs):
            svc, _cm, handler, raft = metad.build(
                _margs(45600 + i, self.addrs, tmp_path), cm=self.cm)
            self.cm.register_loopback(HostAddr.parse(a), handler)
            self.nodes.append(SimpleNamespace(addr=a, service=svc,
                                              raft=raft))

    def leader_idx(self, deadline_s=15):
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            for i, n in enumerate(self.nodes):
                p = n.service.kv.part(META_SPACE, META_PART)
                if p is not None and p.raft is not None and p.is_leader():
                    return i
            time.sleep(0.1)
        raise AssertionError("no catalog leader elected")

    def kill(self, idx):
        n = self.nodes[idx]
        self.cm.unregister_loopback(HostAddr.parse(n.addr))
        n.raft.stop()

    def stop(self):
        for n in self.nodes:
            if n.raft is not None:
                try:
                    n.raft.stop()
                except Exception:   # noqa: BLE001 — already stopped
                    pass


@pytest.fixture()
def quorum(tmp_path):
    q = Quorum(tmp_path)
    yield q
    q.stop()


def test_metad_quorum_failover(quorum):
    q = quorum
    lead = q.leader_idx()
    assert all(n.raft is not None for n in q.nodes), \
        "3-peer metads must boot the catalog raft group"

    client = MetaClient([HostAddr.parse(a) for a in q.addrs],
                        client_manager=q.cm)
    assert client.wait_for_metad_ready()

    # register fake storage hosts so createSpace can place parts
    for h in ("127.0.0.1:47771", "127.0.0.1:47772"):
        r = client._call_status("heartBeat", {"host": h, "cluster_id": 0})
        assert r.ok(), r.status.to_string()

    r = client.create_space("fo_space", partition_num=2, replica_factor=1)
    assert r.ok(), r.status.to_string()
    sid = r.value()

    # DDL replicated to follower state machines (applied local kv)
    deadline = time.time() + 5
    while time.time() < deadline:
        if all(n.service._space_id("fo_space") == sid for n in q.nodes):
            break
        time.sleep(0.1)
    assert all(n.service._space_id("fo_space") == sid for n in q.nodes), \
        "create-space not applied on every catalog replica"

    # a follower refuses with the leader's address as the hint
    follower = next(i for i in range(3) if i != lead)
    with pytest.raises(RpcError) as ei:
        q.cm.call(HostAddr.parse(q.addrs[follower]), "listSpaces", {})
    assert q.addrs[lead] in (ei.value.status.msg or ""), ei.value.status

    # kill the leader: the survivors elect, DDL continues
    q.kill(lead)
    deadline = time.time() + 25
    new_lead = None
    while time.time() < deadline:
        for i, n in enumerate(q.nodes):
            if i == lead:
                continue
            p = n.service.kv.part(META_SPACE, META_PART)
            if p.is_leader():
                new_lead = i
                break
        if new_lead is not None:
            break
        time.sleep(0.2)
    assert new_lead is not None, "no new catalog leader after the kill"

    wire = schema_to_wire(Schema(
        columns=[ColumnDef("name", SupportedType.STRING)]))
    r = client.create_tag_schema(sid, "t1", wire)
    assert r.ok(), r.status.to_string()

    # client caches follow the new leader
    client.load_data()
    sp = client.get_space_id_by_name("fo_space")
    assert sp.ok() and sp.value() == sid
    sm = ServerBasedSchemaManager(client)
    tr = sm.to_tag_id(sid, "t1")
    assert tr.ok(), "post-failover DDL must be visible through caches"

    # both survivors applied the post-failover DDL
    tag_id = tr.value()
    deadline = time.time() + 5
    survivors = [n for i, n in enumerate(q.nodes) if i != lead]

    def applied(n):
        resp = None
        p = n.service.kv.part(META_SPACE, META_PART)
        # read the local applied state regardless of leadership
        from nebula_tpu.meta import keys as mk
        raw = list(n.service.kv.prefix(META_SPACE, META_PART,
                                       mk.tag_prefix(sid)))
        return len(raw) > 0

    while time.time() < deadline:
        if all(applied(n) for n in survivors):
            break
        time.sleep(0.1)
    assert all(applied(n) for n in survivors), \
        "post-failover DDL not replicated to the surviving follower"
