"""KV store tier tests — engine, part, store, WAL, log encoding.

Modeled on the reference's kvstore test tier (NebulaStoreTest,
LogEncoderTest, FileBasedWalTest — SURVEY.md §4)."""
import os

import pytest

from nebula_tpu.common.keys import KeyUtils
from nebula_tpu.kvstore import KVOptions, MemEngine, MemPartManager, NebulaStore
from nebula_tpu.kvstore.log_encoder import LogOp, decode, encode_host, encode_multi, encode_single
from nebula_tpu.kvstore.wal import FileBasedWal, LogEntry


class TestMemEngine:
    def test_put_get_remove(self):
        e = MemEngine()
        e.put(b"k1", b"v1")
        assert e.get(b"k1") == b"v1"
        assert e.get(b"nope") is None
        e.remove(b"k1")
        assert e.get(b"k1") is None

    def test_prefix_scan_order(self):
        e = MemEngine()
        keys = [KeyUtils.edge_key(1, 1, 2, 0, d, 0) for d in range(10)]
        e.multi_put([(k, b"x%d" % i) for i, k in enumerate(keys)])
        e.put(KeyUtils.edge_key(1, 2, 2, 0, 0, 0), b"other")
        got = [k for k, _ in e.prefix(KeyUtils.edge_prefix(1, 1, 2))]
        assert got == keys  # sorted by dst

    def test_range_scan_half_open(self):
        e = MemEngine()
        e.multi_put([(bytes([i]), b"v") for i in range(10)])
        got = [k[0] for k, _ in e.range(bytes([3]), bytes([7]))]
        assert got == [3, 4, 5, 6]

    def test_remove_prefix_and_range(self):
        e = MemEngine()
        e.multi_put([(b"a" + bytes([i]), b"v") for i in range(5)])
        e.multi_put([(b"b" + bytes([i]), b"v") for i in range(5)])
        e.remove_prefix(b"a")
        assert e.total_keys() == 5
        e.remove_range(b"b\x01", b"b\x03")
        assert e.total_keys() == 3

    def test_flush_ingest_roundtrip(self, tmp_path):
        e = MemEngine()
        e.multi_put([(b"k%d" % i, b"v%d" % i) for i in range(100)])
        snap = str(tmp_path / "x.snap")
        e.flush(snap)
        e2 = MemEngine()
        assert e2.ingest(snap).ok()
        assert e2.total_keys() == 100
        assert e2.get(b"k42") == b"v42"
        assert not e2.ingest(str(tmp_path / "missing.snap")).ok()

    def test_compaction_filter(self):
        e = MemEngine(compaction_filter=lambda k, v: v == b"expired")
        e.put(b"a", b"ok")
        e.put(b"b", b"expired")
        e.compact()
        assert e.get(b"a") == b"ok"
        assert e.get(b"b") is None


class TestLogEncoder:
    def test_single_roundtrip(self):
        op, payload = decode(encode_single(LogOp.OP_PUT, b"k", b"v"))
        assert op == LogOp.OP_PUT and payload == (b"k", b"v")
        op, payload = decode(encode_single(LogOp.OP_REMOVE, b"k"))
        assert op == LogOp.OP_REMOVE and payload == b"k"

    def test_multi_roundtrip(self):
        kvs = [(b"a", b"1"), (b"b", b"2")]
        assert decode(encode_multi(LogOp.OP_MULTI_PUT, kvs)) == (LogOp.OP_MULTI_PUT, kvs)
        keys = [b"x", b"y"]
        assert decode(encode_multi(LogOp.OP_MULTI_REMOVE, keys)) == (LogOp.OP_MULTI_REMOVE, keys)
        assert decode(encode_multi(LogOp.OP_REMOVE_RANGE, (b"s", b"e"))) == (
            LogOp.OP_REMOVE_RANGE, (b"s", b"e"))

    def test_host_ops(self):
        for op in (LogOp.OP_ADD_LEARNER, LogOp.OP_TRANS_LEADER,
                   LogOp.OP_ADD_PEER, LogOp.OP_REMOVE_PEER):
            got_op, host = decode(encode_host(op, "10.0.0.1:44500"))
            assert got_op == op and host == "10.0.0.1:44500"


class TestWal:
    def test_append_iterate(self, tmp_path):
        wal = FileBasedWal(str(tmp_path / "wal"))
        for i in range(1, 11):
            assert wal.append_log(i, 1, b"msg%d" % i)
        assert wal.first_log_id() == 1 and wal.last_log_id() == 10
        got = [(e.log_id, e.msg) for e in wal.iterate(3, 5)]
        assert got == [(3, b"msg3"), (4, b"msg4"), (5, b"msg5")]

    def test_gap_rejected(self, tmp_path):
        wal = FileBasedWal(str(tmp_path / "wal"))
        wal.append_log(1, 1, b"a")
        assert not wal.append_log(3, 1, b"c")

    def test_recovery_across_restart(self, tmp_path):
        d = str(tmp_path / "wal")
        wal = FileBasedWal(d)
        for i in range(1, 6):
            wal.append_log(i, 2, b"m%d" % i)
        wal.close()
        wal2 = FileBasedWal(d)
        assert wal2.last_log_id() == 5
        assert wal2.last_log_term() == 2
        assert [e.msg for e in wal2.iterate(1)] == [b"m%d" % i for i in range(1, 6)]

    def test_rollback_durable(self, tmp_path):
        d = str(tmp_path / "wal")
        wal = FileBasedWal(d)
        for i in range(1, 11):
            wal.append_log(i, 1, b"x%d" % i)
        wal.rollback_to_log(4)
        assert wal.last_log_id() == 4
        # diverged entries replaced by new leader's entries
        wal.append_log(5, 2, b"new5")
        wal.close()
        wal2 = FileBasedWal(d)
        assert wal2.last_log_id() == 5
        assert wal2.get_term(5) == 2
        assert list(e.msg for e in wal2.iterate(4)) == [b"x4", b"new5"]


class TestNebulaStore:
    def make_store(self, nparts=3):
        pm = MemPartManager()
        store = NebulaStore(KVOptions(part_man=pm))
        pm.register_handler(store)
        for p in range(1, nparts + 1):
            pm.add_part(1, p)
        return store

    def test_parts_created_via_partman(self):
        store = self.make_store()
        assert store.part_ids(1) == [1, 2, 3]
        assert store.part(1, 2) is not None
        assert store.part(1, 9) is None

    def test_write_read(self):
        store = self.make_store()
        assert store.multi_put(1, 1, [(b"k", b"v")]).ok()
        val, st = store.get(1, 1, b"k")
        assert st.ok() and val == b"v"

    def test_missing_space_and_part(self):
        store = self.make_store()
        _, st = store.get(9, 1, b"k")
        assert not st.ok()
        st2 = store.multi_put(1, 99, [(b"k", b"v")])
        assert not st2.ok()

    def test_part_isolation(self):
        store = self.make_store()
        store.put(1, 1, b"k", b"p1")
        store.put(1, 2, b"k", b"p2")
        # parts share an engine by default but keys are part-prefixed in
        # real usage; raw same-key writes do collide on a shared engine —
        # use KeyUtils part prefixes as production code does
        k1 = KeyUtils.vertex_key(1, 10, 1, 0)
        k2 = KeyUtils.vertex_key(2, 10, 1, 0)
        store.put(1, 1, k1, b"a")
        store.put(1, 2, k2, b"b")
        assert list(store.prefix(1, 1, KeyUtils.part_prefix(1)))[0][1] == b"a"

    def test_remove_part(self):
        store = self.make_store()
        store.remove_part(1, 2)
        assert store.part_ids(1) == [1, 3]

    def test_cas(self):
        store = self.make_store()
        assert store.cas(1, 1, b"", b"k", b"v1").ok()   # create if absent
        assert not store.cas(1, 1, b"bad", b"k", b"v2").ok()
        assert store.cas(1, 1, b"v1", b"k", b"v2").ok()
        assert store.get(1, 1, b"k")[0] == b"v2"

    def test_commit_listener(self):
        store = self.make_store()
        seen = []
        store.part(1, 1).listeners.append(lambda part, ops: seen.append(ops))
        store.multi_put(1, 1, [(b"a", b"1")])
        assert len(seen) == 1
        op, kvs = seen[0][0]
        assert op == LogOp.OP_MULTI_PUT and kvs == [(b"a", b"1")]


def test_apply_order_put_then_remove():
    # PUT then REMOVE of the same key in one committed batch must end absent
    from nebula_tpu.kvstore import MemEngine
    from nebula_tpu.kvstore.part import Part
    from nebula_tpu.kvstore.log_encoder import encode_single, encode_multi
    part = Part(1, 1, MemEngine())
    part.commit_logs([
        (1, 1, encode_single(LogOp.OP_PUT, b"k", b"v")),
        (2, 1, encode_single(LogOp.OP_REMOVE, b"k")),
    ])
    assert part.engine.get(b"k") is None
    # and PUT inside a prefix then REMOVE_PREFIX must also end absent
    part.commit_logs([
        (3, 1, encode_single(LogOp.OP_PUT, b"p/x", b"v")),
        (4, 1, encode_single(LogOp.OP_REMOVE_PREFIX, b"p/")),
        (5, 1, encode_single(LogOp.OP_PUT, b"p/y", b"v2")),
    ])
    assert part.engine.get(b"p/x") is None
    assert part.engine.get(b"p/y") == b"v2"


def test_store_flush_ingest_multi_engine(tmp_path):
    from nebula_tpu.kvstore import KVOptions, MemPartManager, NebulaStore
    pm = MemPartManager()
    store = NebulaStore(KVOptions(part_man=pm, data_paths=[str(tmp_path / "d1"),
                                                           str(tmp_path / "d2")]))
    pm.register_handler(store)
    pm.add_part(1, 1)
    pm.add_part(1, 2)  # lands on engine 1
    k1 = KeyUtils.vertex_key(1, 10, 1, 0)
    k2 = KeyUtils.vertex_key(2, 20, 1, 0)
    store.put(1, 1, k1, b"a")
    store.put(1, 2, k2, b"b")
    prefix = str(tmp_path / "snap")
    assert store.flush(1, prefix).ok()

    store2 = NebulaStore(KVOptions(part_man=MemPartManager(),
                                   data_paths=[str(tmp_path / "r1"),
                                               str(tmp_path / "r2")]))
    store2.options.part_man.register_handler(store2)
    store2.options.part_man.add_part(1, 1)
    store2.options.part_man.add_part(1, 2)
    assert store2.ingest(1, [prefix + ".engine0.snap",
                             prefix + ".engine1.snap"]).ok()
    assert store2.get(1, 1, k1)[0] == b"a"
    assert store2.get(1, 2, k2)[0] == b"b"  # part 2 reads engine 1


def test_wal_clean_up_deletes_segments(tmp_path):
    import os as _os
    d = str(tmp_path / "wal")
    wal = FileBasedWal(d, buffer_size=1)  # flush every record
    # force tiny segments
    import nebula_tpu.kvstore.wal as walmod
    old = walmod._SEGMENT_BYTES
    walmod._SEGMENT_BYTES = 64
    try:
        for i in range(1, 51):
            wal.append_log(i, 1, b"x" * 32)
        nseg_before = len(wal._segments())
        assert nseg_before > 2
        wal.clean_up_to(40)
        assert wal.first_log_id() == 41
        assert len(wal._segments()) < nseg_before
        # tail still intact
        assert [e.log_id for e in wal.iterate(41)] == list(range(41, 51))
    finally:
        walmod._SEGMENT_BYTES = old
