"""Replicated-cluster e2e: 3 storaged with raft consensus per partition.

The reference's equivalent tier is NebulaStoreTest::ThreeCopiesTest +
BalanceIntegrationTest (SURVEY.md §4): real replication under the full
query stack — DDL → meta part allocation with replica_factor=3 → raft
groups spin up via the PartManager seam → writes quorum-commit →
reads chase leaders; leader transfer keeps queries working.
"""
import time

import pytest

from nebula_tpu.cluster import LocalCluster
from nebula_tpu.common.flags import flags


@pytest.fixture(scope="module", autouse=True)
def fast_raft():
    saved = {n: flags.get(n) for n in
             ("raft_heartbeat_interval_s", "raft_election_timeout_s")}
    # fast enough for quick tests, loose enough that full-suite CPU
    # contention doesn't make elections flap (0.3s proved too tight)
    flags.set("raft_heartbeat_interval_s", 0.1)
    flags.set("raft_election_timeout_s", 0.8)
    yield
    for k, v in saved.items():
        flags.set(k, v)


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_storage=3, use_raft=True)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def client(cluster):
    client = cluster.client()

    def ok(stmt, tries=40):
        # raft leadership may still be settling right after elections;
        # storage-client retries are bounded, so retry here too
        last = None
        for _ in range(tries):
            last = client.execute(stmt)
            if last.ok():
                return last
            time.sleep(0.25)
        raise AssertionError(f"{stmt}: {last.error_msg}")

    client.ok = ok
    ok("CREATE SPACE rep(partition_num=4, replica_factor=3)")
    cluster.refresh_all()
    _wait_leaders(cluster, space_parts=4)
    ok("USE rep")
    ok("CREATE TAG person(name string)")
    ok("CREATE EDGE knows(weight int)")
    cluster.refresh_all()
    yield client
    client.disconnect()


def _space_id(cluster, name="rep"):
    r = cluster.graph_meta_client.space_id_by_name(name) \
        if hasattr(cluster.graph_meta_client, "space_id_by_name") else None
    if r is not None:
        return r
    # fallback: scan caches
    with cluster.graph_meta_client._cache_lock:
        for sid, c in cluster.graph_meta_client.spaces.items():
            if getattr(c, "name", None) == name:
                return sid
    return 1


def _wait_leaders(cluster, space_parts, timeout=30.0):
    """Every raft group must elect before writes can quorum."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        elected = 0
        for node in cluster.storage_nodes:
            if node.raft_service is None:
                continue
            for part in node.raft_service.status():
                if part["role"] == "LEADER":
                    elected += 1
        if elected >= space_parts:
            return
        time.sleep(0.05)
    raise AssertionError("raft groups failed to elect: " + repr([
        node.raft_service.status() for node in cluster.storage_nodes]))


def test_replica_factor_respected(cluster, client):
    # every part must be placed on 3 distinct hosts
    mc = cluster.graph_meta_client
    with mc._cache_lock:
        (sid, cache), = [(s, c) for s, c in mc.spaces.items()]
        for part, peers in cache.parts_alloc.items():
            assert len(set(peers)) == 3, (part, peers)


def test_write_replicates_to_all_copies(cluster, client):
    client.ok('INSERT VERTEX person(name) VALUES 1:("alice"), 2:("bob")')
    client.ok('INSERT EDGE knows(weight) VALUES 1 -> 2:(7)')
    # engine-level check: the rows exist on all three storage nodes.
    # Follower catch-up is async; a loaded CI box can take a while, so
    # the deadline is generous (the loop exits as soon as it converges)
    deadline = time.monotonic() + 30.0
    counts = []
    while time.monotonic() < deadline:
        try:
            counts = []
            for node in cluster.storage_nodes:
                n = 0
                for sid in list(node.kv.spaces):
                    for pid in node.kv.part_ids(sid):
                        part = node.kv.part(sid, pid)
                        if part is None:       # part still spinning up
                            raise LookupError(pid)
                        n += sum(1 for k, _v in part.engine.prefix(b"")
                                 if not k.startswith(b"__"))
                counts.append(n)
        except (LookupError, RuntimeError):    # transient mid-replication
            counts = []                        # partial scan — not a verdict
            time.sleep(0.05)
            continue
        if all(c == counts[0] and c > 0 for c in counts):
            break
        time.sleep(0.05)
    assert counts and all(c == counts[0] and c > 0 for c in counts), counts


def test_query_reads_through_leaders(client):
    resp = client.ok("GO FROM 1 OVER knows YIELD knows._dst, knows.weight")
    assert [list(r) for r in resp.rows] == [[2, 7]]


def test_leader_transfer_keeps_queries_working(cluster, client):
    # move every leader off node 0, then query again
    node0 = cluster.storage_nodes[0]
    moved = 0
    for st in node0.raft_service.status():
        if st["role"] != "LEADER":
            continue
        part = node0.kv.part(st["space"], st["part"])
        others = [a for a in part.raft.peers]
        if others:
            part.raft.transfer_leadership(others[0])
            moved += 1
    deadline = time.monotonic() + 5.0
    while moved and time.monotonic() < deadline:
        if all(s["role"] != "LEADER" for s in node0.raft_service.status()):
            break
        time.sleep(0.05)
    # queries keep working by chasing the new leaders
    resp = client.ok("GO FROM 1 OVER knows YIELD knows._dst")
    assert [list(r) for r in resp.rows] == [[2]]
    client.ok('INSERT VERTEX person(name) VALUES 3:("carol")')
    resp = client.ok("FETCH PROP ON person 3 YIELD person.name")
    assert resp.rows and resp.rows[0][-1] == "carol"


def test_node_crash_failover():
    """Kill one of three storage nodes mid-traffic: reads and writes
    must keep working through the remaining 2/3 quorum (the reference's
    failure-detection + leader-chase loop, SURVEY.md §5.3 — clients
    retry on E_LEADER_CHANGED / RPC failure and raft re-elects)."""
    c = LocalCluster(num_storage=3, use_raft=True)
    try:
        g = c.client()

        def ok(stmt, tries=40):
            last = None
            for _ in range(tries):       # leaders may be re-electing
                r = g.execute(stmt)
                if r.ok():
                    return r
                last = r
                time.sleep(0.25)
            raise AssertionError(f"{stmt}: {last.error_msg}")

        ok("CREATE SPACE fo(partition_num=4, replica_factor=3)")
        c.refresh_all()
        _wait_leaders(c, space_parts=4)
        ok("USE fo")
        ok("CREATE EDGE e(w int)")
        c.refresh_all()
        ok("INSERT EDGE e(w) VALUES 1->2:(7), 2->3:(8)")
        assert sorted(x[0] for x in
                      ok("GO FROM 1 OVER e YIELD e._dst").rows) == [2]

        # crash node 2: hard stop AND unroute it — a dead process is
        # unreachable, not politely error-returning
        from nebula_tpu.interface.common import HostAddr
        dead = c.storage_nodes[2]
        c.cm.unregister_loopback(HostAddr.parse(dead.host))
        dead.stop()

        # reads and writes still work through the surviving quorum.
        # A read racing the re-election can return PARTIAL results
        # (completeness < 100 is tolerated, reference
        # GoExecutor.cpp:356-366) — retry until failover lands
        deadline = time.time() + 20
        while time.time() < deadline:
            r = ok("GO FROM 2 OVER e YIELD e._dst")
            if sorted(x[0] for x in r.rows) == [3]:
                break
            time.sleep(0.2)
        assert sorted(x[0] for x in r.rows) == [3]
        ok("INSERT EDGE e(w) VALUES 3->4:(9)")
        r = ok("GO FROM 3 OVER e YIELD e._dst")
        assert sorted(x[0] for x in r.rows) == [4]
    finally:
        c.stop()


def test_step_down_records_election_event_and_show_parts(cluster, client):
    """Observability acceptance: a forced step-down (leader transfer)
    must surface as a raft.leader_elected journal event, visible
    through SHOW EVENTS, and SHOW PARTS must carry the replication
    columns (term/committed/last log) sourced from heartbeat briefs."""
    from nebula_tpu.common.events import journal

    moved = None
    # an earlier test may have drained node 0's leaderships — take the
    # first led part on ANY node (module-scoped cluster)
    for node in cluster.storage_nodes:
        for st in node.raft_service.status():
            if st["role"] == "LEADER" and st["peers"]:
                part = node.kv.part(st["space"], st["part"])
                target = next(iter(part.raft.peers))
                part.raft.transfer_leadership(target)
                moved = st
                break
        if moved is not None:
            break
    assert moved is not None, "no node leads anything to transfer"

    # the target's election (a term beyond the pre-transfer one) must
    # land in the process journal
    deadline = time.monotonic() + 20
    elected = []
    while time.monotonic() < deadline and not elected:
        elected = [e for e in journal.dump(limit=500)
                   if e["kind"] == "raft.leader_elected"
                   and e.get("space") == moved["space"]
                   and e.get("part") == moved["part"]
                   and e.get("term", 0) > moved["term"]]
        time.sleep(0.05)
    assert elected, "no raft.leader_elected event after forced step-down"
    # the deposed leader journals its step-down too (same-term append
    # or higher-term vote — either way the role change is recorded)
    kinds = {e["kind"] for e in journal.dump(limit=500)}
    assert "raft.step_down" in kinds

    resp = client.ok("SHOW EVENTS")
    assert "raft.leader_elected" in {r[2] for r in resp.rows}

    # replication columns ride the heartbeat brief into metad
    cluster.refresh_all()
    resp = client.ok("SHOW PARTS")
    assert resp.column_names == ["Partition ID", "Leader", "Term",
                                 "Committed", "Last Log", "Peers"]
    with_leader = [r for r in resp.rows if r[1] != "-"]
    assert with_leader, "no part reported a leader over heartbeats"
    for r in with_leader:
        assert isinstance(r[2], int) and r[2] >= 1      # elected terms
        assert isinstance(r[3], int) and isinstance(r[4], int)
