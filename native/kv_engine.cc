// Native KV engine — the RocksEngine-equivalent storage core.
//
// Capability parity with the reference's KVEngine/RocksEngine seam
// (/root/reference/src/kvstore/RocksEngine.h:94-156): point get/put,
// batched writes, prefix/range iteration, range deletes, snapshot
// flush/ingest files, key count. Byte-ordered std::map under a
// shared_mutex; the order-preserving key codec (keys.cc) guarantees the
// map iterates edges in CSR order, so scans feed the TPU mirror with no
// sort.
//
// C ABI, handle-based; buffers returned via neb_buf_free. Snapshot file
// format matches the Python MemEngine exactly (big-endian u32 klen,vlen
// frames) so flush/ingest interops across engines.
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <vector>

namespace {

struct Engine {
  std::map<std::string, std::string> table;
  mutable std::shared_mutex mu;
};

inline uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

inline void put_be32(uint8_t* p, uint32_t v) {
  p[0] = uint8_t(v >> 24);
  p[1] = uint8_t(v >> 16);
  p[2] = uint8_t(v >> 8);
  p[3] = uint8_t(v);
}

// next lexicographic string after all keys with this prefix
bool prefix_upper_bound(const std::string& prefix, std::string* out) {
  std::string ub = prefix;
  while (!ub.empty()) {
    if (uint8_t(ub.back()) != 0xFF) {
      ub.back() = char(uint8_t(ub.back()) + 1);
      *out = ub;
      return true;
    }
    ub.pop_back();
  }
  return false;  // prefix is all 0xFF — scan to end
}

uint8_t* pack_kvs(const std::vector<std::pair<const std::string*,
                                              const std::string*>>& rows,
                  uint64_t* out_len) {
  uint64_t total = 0;
  for (auto& kv : rows) total += 8 + kv.first->size() + kv.second->size();
  uint8_t* buf = static_cast<uint8_t*>(malloc(total ? total : 1));
  uint8_t* p = buf;
  for (auto& kv : rows) {
    put_be32(p, uint32_t(kv.first->size()));
    put_be32(p + 4, uint32_t(kv.second->size()));
    p += 8;
    memcpy(p, kv.first->data(), kv.first->size());
    p += kv.first->size();
    memcpy(p, kv.second->data(), kv.second->size());
    p += kv.second->size();
  }
  *out_len = total;
  return buf;
}

}  // namespace

extern "C" {

void* neb_engine_create() { return new Engine(); }

void neb_engine_destroy(void* h) { delete static_cast<Engine*>(h); }

void neb_buf_free(uint8_t* p) { free(p); }

int neb_put(void* h, const uint8_t* k, uint64_t klen, const uint8_t* v,
            uint64_t vlen) {
  auto* e = static_cast<Engine*>(h);
  std::unique_lock<std::shared_mutex> g(e->mu);
  e->table[std::string(reinterpret_cast<const char*>(k), klen)] =
      std::string(reinterpret_cast<const char*>(v), vlen);
  return 0;
}

// frames: (u32be klen | u32be vlen | k | v)*
//
// Sorted-run fast path: bulk ingest files arrive as one
// ascending-by-key run per part (tools/bulk_load.py sorts them), so
// each insert's position is immediately after the previous one —
// emplace_hint with the successor of the last inserted node is then
// amortized O(1) instead of O(log n).  Wrong hints (unsorted input,
// interleaved existing keys) just degrade to the ordinary lookup;
// semantics (last write wins) are unchanged.
int neb_multi_put(void* h, const uint8_t* buf, uint64_t len) {
  auto* e = static_cast<Engine*>(h);
  std::unique_lock<std::shared_mutex> g(e->mu);
  uint64_t pos = 0;
  auto hint = e->table.end();
  bool have_hint = false;
  while (pos + 8 <= len) {
    uint32_t klen = be32(buf + pos), vlen = be32(buf + pos + 4);
    pos += 8;
    if (pos + klen + vlen > len) return -1;
    std::string key(reinterpret_cast<const char*>(buf + pos), klen);
    auto it = have_hint
                  ? e->table.emplace_hint(hint, std::move(key),
                                          std::string())
                  : e->table.emplace(std::move(key), std::string()).first;
    it->second.assign(reinterpret_cast<const char*>(buf + pos + klen),
                      vlen);
    hint = std::next(it);
    have_hint = true;
    pos += klen + vlen;
  }
  return 0;
}

// returns value length, or -1 if absent; *out malloc'd (free via neb_buf_free)
int64_t neb_get(void* h, const uint8_t* k, uint64_t klen, uint8_t** out) {
  auto* e = static_cast<Engine*>(h);
  std::shared_lock<std::shared_mutex> g(e->mu);
  auto it = e->table.find(std::string(reinterpret_cast<const char*>(k), klen));
  if (it == e->table.end()) return -1;
  *out = static_cast<uint8_t*>(malloc(it->second.size() ? it->second.size() : 1));
  memcpy(*out, it->second.data(), it->second.size());
  return int64_t(it->second.size());
}

int neb_remove(void* h, const uint8_t* k, uint64_t klen) {
  auto* e = static_cast<Engine*>(h);
  std::unique_lock<std::shared_mutex> g(e->mu);
  e->table.erase(std::string(reinterpret_cast<const char*>(k), klen));
  return 0;
}

// frames: (u32be klen | k)*
int neb_multi_remove(void* h, const uint8_t* buf, uint64_t len) {
  auto* e = static_cast<Engine*>(h);
  std::unique_lock<std::shared_mutex> g(e->mu);
  uint64_t pos = 0;
  while (pos + 4 <= len) {
    uint32_t klen = be32(buf + pos);
    pos += 4;
    if (pos + klen > len) return -1;
    e->table.erase(std::string(reinterpret_cast<const char*>(buf + pos), klen));
    pos += klen;
  }
  return 0;
}

int64_t neb_remove_range(void* h, const uint8_t* s, uint64_t slen,
                         const uint8_t* t, uint64_t tlen) {
  auto* e = static_cast<Engine*>(h);
  std::unique_lock<std::shared_mutex> g(e->mu);
  auto lo = e->table.lower_bound(
      std::string(reinterpret_cast<const char*>(s), slen));
  auto hi = e->table.lower_bound(
      std::string(reinterpret_cast<const char*>(t), tlen));
  int64_t n = std::distance(lo, hi);
  e->table.erase(lo, hi);
  return n;
}

int64_t neb_remove_prefix(void* h, const uint8_t* p, uint64_t plen) {
  auto* e = static_cast<Engine*>(h);
  std::string prefix(reinterpret_cast<const char*>(p), plen);
  std::string ub;
  std::unique_lock<std::shared_mutex> g(e->mu);
  auto lo = e->table.lower_bound(prefix);
  auto hi = prefix_upper_bound(prefix, &ub) ? e->table.lower_bound(ub)
                                            : e->table.end();
  int64_t n = std::distance(lo, hi);
  e->table.erase(lo, hi);
  return n;
}

// packed (u32be klen | u32be vlen | k | v)* of the prefix scan
uint8_t* neb_scan_prefix(void* h, const uint8_t* p, uint64_t plen,
                         uint64_t* out_len, uint64_t* out_count) {
  auto* e = static_cast<Engine*>(h);
  std::string prefix(reinterpret_cast<const char*>(p), plen);
  std::string ub;
  bool bounded = prefix_upper_bound(prefix, &ub);
  std::shared_lock<std::shared_mutex> g(e->mu);
  std::vector<std::pair<const std::string*, const std::string*>> rows;
  auto it = e->table.lower_bound(prefix);
  auto end = bounded ? e->table.lower_bound(ub) : e->table.end();
  for (; it != end; ++it) rows.emplace_back(&it->first, &it->second);
  *out_count = rows.size();
  return pack_kvs(rows, out_len);
}

// N prefix scans in one call (the getNeighbors hot path: every
// requested vertex's edge range of one part in one lock acquisition and
// one packed buffer).  Prefixes arrive concatenated with offsets and
// (uniform or per-entry) lengths; out_counts[i] = rows of prefix i.
uint8_t* neb_scan_multi_prefix(void* h, const uint8_t* blob,
                               const uint64_t* offs, const uint64_t* lens,
                               int64_t n, uint64_t* out_len,
                               uint64_t* out_counts) {
  auto* e = static_cast<Engine*>(h);
  std::shared_lock<std::shared_mutex> g(e->mu);
  std::vector<std::pair<const std::string*, const std::string*>> rows;
  std::string prefix, ub;
  for (int64_t i = 0; i < n; i++) {
    prefix.assign(reinterpret_cast<const char*>(blob + offs[i]), lens[i]);
    bool bounded = prefix_upper_bound(prefix, &ub);
    auto it = e->table.lower_bound(prefix);
    auto end = bounded ? e->table.lower_bound(ub) : e->table.end();
    uint64_t c = 0;
    for (; it != end; ++it, ++c) rows.emplace_back(&it->first, &it->second);
    out_counts[i] = c;
  }
  return pack_kvs(rows, out_len);
}

uint8_t* neb_scan_range(void* h, const uint8_t* s, uint64_t slen,
                        const uint8_t* t, uint64_t tlen, uint64_t* out_len,
                        uint64_t* out_count) {
  auto* e = static_cast<Engine*>(h);
  std::shared_lock<std::shared_mutex> g(e->mu);
  std::vector<std::pair<const std::string*, const std::string*>> rows;
  auto it = e->table.lower_bound(
      std::string(reinterpret_cast<const char*>(s), slen));
  auto end = e->table.lower_bound(
      std::string(reinterpret_cast<const char*>(t), tlen));
  for (; it != end; ++it) rows.emplace_back(&it->first, &it->second);
  *out_count = rows.size();
  return pack_kvs(rows, out_len);
}

int64_t neb_total_keys(void* h) {
  auto* e = static_cast<Engine*>(h);
  std::shared_lock<std::shared_mutex> g(e->mu);
  return int64_t(e->table.size());
}

// snapshot files: identical format to the Python MemEngine (">II" frames)
int neb_flush(void* h, const char* path) {
  auto* e = static_cast<Engine*>(h);
  std::string tmp = std::string(path) + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return -1;
  {
    std::shared_lock<std::shared_mutex> g(e->mu);
    uint8_t hdr[8];
    for (auto& kv : e->table) {
      put_be32(hdr, uint32_t(kv.first.size()));
      put_be32(hdr + 4, uint32_t(kv.second.size()));
      if (fwrite(hdr, 1, 8, f) != 8 ||
          fwrite(kv.first.data(), 1, kv.first.size(), f) != kv.first.size() ||
          fwrite(kv.second.data(), 1, kv.second.size(), f) !=
              kv.second.size()) {
        fclose(f);
        remove(tmp.c_str());
        return -1;
      }
    }
  }
  fclose(f);
  return rename(tmp.c_str(), path);
}

int neb_ingest(void* h, const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> data(static_cast<size_t>(n), 0);
  if (n && fread(data.data(), 1, size_t(n), f) != size_t(n)) {
    fclose(f);
    return -1;
  }
  fclose(f);
  return neb_multi_put(h, data.data(), uint64_t(n));
}

}  // extern "C"
