// Native row/key codec — batch decode for the CSR mirror fold and bulk
// encode for SST generation.
//
// Capability parity with the reference's dataman + NebulaCodec native ABI
// (/root/reference/src/dataman/NebulaCodecImpl.h:1-30, RowReader.h:24):
// same wire format as nebula_tpu/codec/rows.py —
//   row   := uvarint(schema_ver) | field*
//   field := BOOL 1B | INT/VID/TS zigzag-varint | FLOAT 4B LE
//          | DOUBLE 8B LE | STRING uvarint len + bytes
// and the order-preserving key layout of common/keys.py (big-endian,
// sign-flipped — see keys comment there).
//
// The hot entry is neb_decode_field: one schema column across N rows in
// one C pass (the Python per-row RowReader loop this replaces dominates
// CSR mirror build time).
#include <cstdint>
#include <cstring>

namespace {

// type codes (mirror interface/common.py SupportedType)
enum : uint8_t {
  T_BOOL = 1,
  T_INT = 2,
  T_VID = 3,
  T_FLOAT = 4,
  T_DOUBLE = 5,
  T_STRING = 6,
  T_TIMESTAMP = 21,
};

inline bool read_uvarint(const uint8_t* d, uint64_t len, uint64_t* pos,
                         uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (*pos < len && shift < 64) {
    uint8_t b = d[(*pos)++];
    v |= uint64_t(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

inline int64_t unzigzag(uint64_t v) {
  return int64_t(v >> 1) ^ -int64_t(v & 1);
}

inline uint64_t be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

inline uint32_t be32u(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

// skip one field of type t at *pos; false on truncation
inline bool skip_field(const uint8_t* d, uint64_t len, uint64_t* pos,
                       uint8_t t) {
  uint64_t u;
  switch (t) {
    case T_BOOL:
      *pos += 1;
      return *pos <= len;
    case T_INT:
    case T_VID:
    case T_TIMESTAMP:
      return read_uvarint(d, len, pos, &u);
    case T_FLOAT:
      *pos += 4;
      return *pos <= len;
    case T_DOUBLE:
      *pos += 8;
      return *pos <= len;
    case T_STRING:
      if (!read_uvarint(d, len, pos, &u)) return false;
      *pos += u;
      return *pos <= len;
    default:
      return false;
  }
}

}  // namespace

extern "C" {

// Decode column `field` across n rows.
//   blob           concatenated row bytes
//   row_off/row_len  per-row slices into blob
//   types[nfields] schema column type codes
//   expect_ver     only rows with this embedded schema_ver decode; others
//                  get valid=2 (caller falls back per-row with the right
//                  schema — multi-version rows are rare)
//   out_i64        BOOL/INT/VID/TIMESTAMP values (bool as 0/1)
//   out_f64        FLOAT/DOUBLE values
//   str_off/str_len  STRING slices into blob (caller decodes utf-8)
//   valid          1 decoded, 0 missing (older-schema prefix row), 2 ver
//                  mismatch, 3 corrupt
// Returns number of rows with valid==1.
int64_t neb_decode_field(const uint8_t* blob, const uint64_t* row_off,
                         const uint64_t* row_len, int64_t n,
                         const uint8_t* types, int32_t nfields,
                         int32_t field, uint64_t expect_ver,
                         int64_t* out_i64, double* out_f64,
                         uint64_t* str_off, uint64_t* str_len,
                         uint8_t* valid) {
  if (field < 0 || field >= nfields) return 0;
  uint8_t t = types[field];
  int64_t ok = 0;
  for (int64_t r = 0; r < n; r++) {
    const uint8_t* d = blob + row_off[r];
    uint64_t len = row_len[r];
    uint64_t pos = 0, ver;
    valid[r] = 0;
    if (!read_uvarint(d, len, &pos, &ver)) {
      valid[r] = 3;
      continue;
    }
    if (ver != expect_ver) {
      valid[r] = 2;
      continue;
    }
    bool bad = false;
    for (int32_t i = 0; i < field; i++) {
      if (!skip_field(d, len, &pos, types[i])) {
        bad = true;
        break;
      }
    }
    if (bad || pos >= len) {
      // truncated mid-skip == corrupt; clean end == older-schema row
      valid[r] = bad && pos < len ? 3 : 0;
      continue;
    }
    uint64_t u;
    switch (t) {
      case T_BOOL:
        out_i64[r] = d[pos] ? 1 : 0;
        break;
      case T_INT:
      case T_VID:
      case T_TIMESTAMP:
        if (!read_uvarint(d, len, &pos, &u)) {
          valid[r] = 3;
          continue;
        }
        out_i64[r] = unzigzag(u);
        break;
      case T_FLOAT: {
        if (pos + 4 > len) {
          valid[r] = 3;
          continue;
        }
        float f;
        memcpy(&f, d + pos, 4);
        out_f64[r] = double(f);
        break;
      }
      case T_DOUBLE: {
        if (pos + 8 > len) {
          valid[r] = 3;
          continue;
        }
        double f;
        memcpy(&f, d + pos, 8);
        out_f64[r] = f;
        break;
      }
      case T_STRING: {
        if (!read_uvarint(d, len, &pos, &u) || pos + u > len) {
          valid[r] = 3;
          continue;
        }
        str_off[r] = (d - blob) + pos;
        str_len[r] = u;
        break;
      }
      default:
        valid[r] = 3;
        continue;
    }
    valid[r] = 1;
    ok++;
  }
  return ok;
}

// Batch-parse order-preserving storage keys (common/keys.py layout).
// kind: 1 vertex (24B: part,vid,tag,ver), 2 edge (40B: part,src,etype,
// rank,dst,ver), 0 other. Fields are sign-flip-decoded.
void neb_parse_keys(const uint8_t* blob, const uint64_t* off,
                    const uint64_t* len, int64_t n, uint8_t* kind,
                    int32_t* part, int64_t* a, int32_t* b, int64_t* c,
                    int64_t* d_, int64_t* ver) {
  const uint64_t S32 = 1ull << 31, S64 = 1ull << 63;
  for (int64_t r = 0; r < n; r++) {
    const uint8_t* k = blob + off[r];
    if (len[r] == 24) {
      kind[r] = 1;
      part[r] = int32_t(be32u(k) - S32);
      a[r] = int64_t(be64(k + 4) - S64);
      b[r] = int32_t(be32u(k + 12) - S32);
      ver[r] = int64_t(be64(k + 16) - S64);
      c[r] = 0;
      d_[r] = 0;
    } else if (len[r] == 40) {
      kind[r] = 2;
      part[r] = int32_t(be32u(k) - S32);
      a[r] = int64_t(be64(k + 4) - S64);
      b[r] = int32_t(be32u(k + 12) - S32);
      c[r] = int64_t(be64(k + 16) - S64);
      d_[r] = int64_t(be64(k + 24) - S64);
      ver[r] = int64_t(be64(k + 32) - S64);
    } else {
      kind[r] = 0;
    }
  }
}

// Split a packed kv frame buffer ((u32be klen | u32be vlen | k | v)* —
// the engine scan / snapshot format) into per-row offsets. Returns row
// count, or -1 if capacity is insufficient / buffer corrupt.
int64_t neb_split_frames(const uint8_t* buf, uint64_t len,
                         uint64_t* key_off, uint64_t* key_len,
                         uint64_t* val_off, uint64_t* val_len,
                         int64_t capacity) {
  uint64_t pos = 0;
  int64_t n = 0;
  while (pos + 8 <= len) {
    uint32_t kl = be32u(buf + pos), vl = be32u(buf + pos + 4);
    pos += 8;
    if (pos + kl + vl > len || n >= capacity) return -1;
    key_off[n] = pos;
    key_len[n] = kl;
    val_off[n] = pos + kl;
    val_len[n] = vl;
    pos += kl + vl;
    n++;
  }
  return n;
}

// Split a RowSetWriter blob (uvarint(row_len) | row)* into per-row
// offsets (reference RowSetReader.h).  Returns row count, or -1 on
// corrupt framing / insufficient capacity.  The graphd per-hop loop
// decodes ONE column (_dst) out of every edge rowset — splitting +
// neb_decode_field replaces a Python RowReader per row, which
// dominated the CPU executor path's profile.
int64_t neb_split_rowset(const uint8_t* blob, uint64_t len,
                         uint64_t* row_off, uint64_t* row_len,
                         int64_t capacity) {
  uint64_t pos = 0;
  int64_t n = 0;
  while (pos < len) {
    uint64_t rl;
    if (!read_uvarint(blob, len, &pos, &rl)) return -1;
    // rl > len - pos, NOT pos + rl > len: a corrupt varint near 2^64
    // would wrap the addition past the bound and hand decode_field an
    // out-of-bounds row length
    if (rl > len - pos || n >= capacity) return -1;
    row_off[n] = pos;
    row_len[n] = rl;
    pos += rl;
    n++;
  }
  return n;
}

namespace {

inline void put_uvarint(uint8_t* out, uint64_t* pos, uint64_t v) {
  while (v >= 0x80) {
    out[(*pos)++] = uint8_t(v) | 0x80;
    v >>= 7;
  }
  out[(*pos)++] = uint8_t(v);
}

inline uint64_t zigzag(int64_t v) {
  return (uint64_t(v) << 1) ^ uint64_t(v >> 63);
}

}  // namespace

// Encode a whole pseudo-column edge rowset — rows of exactly
// (_dst VID, _rank INT, _type INT) under schema version `ver` — in one
// call: the intermediate hops of a GO request no real props, so the
// storage side can skip RowReader/encode_row entirely and emit the
// response blob straight from parsed keys.  Returns bytes written, or
// -1 if `cap` is too small (caller sizes cap = n * 48: worst-case row
// is 4 max-width varints = 40 bytes + frame varint).
int64_t neb_encode_pseudo_rowset(const int64_t* dst, const int64_t* rank,
                                 int64_t etype, uint64_t ver, int64_t n,
                                 uint8_t* out, int64_t cap) {
  uint64_t pos = 0;
  uint8_t row[40];
  for (int64_t i = 0; i < n; i++) {
    uint64_t rp = 0;
    put_uvarint(row, &rp, ver);
    put_uvarint(row, &rp, zigzag(dst[i]));
    put_uvarint(row, &rp, zigzag(rank[i]));
    put_uvarint(row, &rp, zigzag(etype));
    if (int64_t(pos + rp + 10) > cap) return -1;
    put_uvarint(out, &pos, rp);
    std::memcpy(out + pos, row, rp);
    pos += rp;
  }
  return int64_t(pos);
}

}  // extern "C"
