// ell_build — native ELL slot-table construction for the TPU batched
// traversal engine (the C++ counterpart of nebula_tpu/tpu/ell.py
// EllIndex.build; the numpy path stays as the fallback and as the
// differential-test oracle).
//
// Same layout contract as the Python builder:
//   * rows grouped by DST (slots = in-edges over both stored
//     directions), vertices relabeled so each degree bucket is
//     contiguous (new id = rank in (bucket_D, old_id) order)
//   * bucket width D = clamp(next_pow2(min(deg, cap)), min_d, cap)
//   * hub vertices (deg > cap) get extra rows appended after all real
//     vertices; extra_owner maps each extra row to its owner's new id
//   * slot padding: nbr = n_rows (the pinned-zero frontier row),
//     etype = 0 (never a real etype)
//
// ABI (ctypes, two-phase):
//   ell_build(src, dst, et, m, n, cap, min_d) -> handle (>=0) or -1
//   ell_counts(handle, out int64[4])   -> {n_rows, n_extras, n_buckets,
//                                          total_cells}
//   ell_bucket_dims(handle, out int64[2*n_buckets])  (rows_b, D_b)...
//   ell_fill(handle, perm, inv, extra_owner, nbr_flat, et_flat)
//       fills caller-allocated buffers; bucket tables are concatenated
//       row-major in ascending-D order inside nbr_flat/et_flat.
//   ell_free(handle)
#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <numeric>
#include <vector>

namespace {

struct EllResult {
  int64_t n = 0;
  int64_t n_rows = 0;
  std::vector<int32_t> perm, inv, extra_owner;
  std::vector<int64_t> bucket_rows, bucket_D;
  std::vector<int32_t> nbr_flat, et_flat;   // concatenated bucket tables
};

std::mutex g_mu;
std::map<int64_t, EllResult*> g_results;
int64_t g_next = 1;

int64_t next_pow2(int64_t x) {
  if (x <= 1) return 1;
  int64_t p = 1;
  while (p < x) p <<= 1;
  return p;
}

}  // namespace

extern "C" {

int64_t ell_build(const int32_t* src, const int32_t* dst,
                  const int32_t* et, int64_t m, int64_t n,
                  int64_t cap, int64_t min_d) {
  if (n < 0 || m < 0 || cap <= 0 || min_d <= 0) return -1;
  if (cap < min_d) cap = min_d;
  // out-of-range vertex ids would corrupt the heap here where the
  // numpy fallback raises cleanly — reject so the wrapper falls back
  for (int64_t i = 0; i < m; i++) {
    if (src[i] < 0 || src[i] >= n || dst[i] < 0 || dst[i] >= n) return -1;
  }
  auto* r = new EllResult();
  r->n = n;
  if (n == 0) {
    std::lock_guard<std::mutex> lk(g_mu);
    g_results[g_next] = r;
    return g_next++;
  }

  // order edges by dst (stable; counting sort via per-vertex offsets)
  std::vector<int64_t> deg(n, 0);
  for (int64_t i = 0; i < m; i++) deg[dst[i]]++;
  std::vector<int64_t> row_start(n + 1, 0);
  for (int64_t v = 0; v < n; v++) row_start[v + 1] = row_start[v] + deg[v];

  // bucket width per vertex + relabeling (stable sort by D, old id)
  std::vector<int64_t> D_v(n);
  for (int64_t v = 0; v < n; v++) {
    int64_t per_row = std::min(deg[v], cap);
    D_v[v] = std::min(std::max(next_pow2(per_row), min_d), cap);
  }
  std::vector<int32_t> vorder(n);
  std::iota(vorder.begin(), vorder.end(), 0);
  std::stable_sort(vorder.begin(), vorder.end(),
                   [&](int32_t a, int32_t b) { return D_v[a] < D_v[b]; });
  r->inv.assign(vorder.begin(), vorder.end());
  r->perm.resize(n);
  for (int64_t i = 0; i < n; i++) r->perm[vorder[i]] = int32_t(i);

  // hub extra rows
  std::vector<int64_t> first_extra(n, 0);
  int64_t n_extras = 0;
  for (int64_t v = 0; v < n; v++) {
    first_extra[v] = n + n_extras;
    if (deg[v] > cap) n_extras += (deg[v] + cap - 1) / cap - 1;
  }
  r->n_rows = n + n_extras;
  r->extra_owner.reserve(n_extras);
  for (int64_t v = 0; v < n; v++) {
    int64_t k = (deg[v] > cap) ? (deg[v] + cap - 1) / cap - 1 : 0;
    for (int64_t j = 0; j < k; j++) r->extra_owner.push_back(r->perm[v]);
  }

  // bucket layout (ascending D; extras live in the cap bucket)
  std::vector<int64_t> Ds;
  for (int64_t v = 0; v < n; v++) Ds.push_back(D_v[v]);
  std::sort(Ds.begin(), Ds.end());
  Ds.erase(std::unique(Ds.begin(), Ds.end()), Ds.end());
  std::map<int64_t, int64_t> rows_of;   // D -> row count
  for (int64_t v = 0; v < n; v++) rows_of[D_v[v]]++;
  if (n_extras) rows_of[cap] += n_extras;

  int64_t total_cells = 0;
  std::map<int64_t, int64_t> cell_base;  // D -> offset into flat arrays
  std::map<int64_t, int64_t> row_base;   // D -> first global row index
  int64_t row_cursor = 0;
  for (int64_t D : Ds) {
    cell_base[D] = total_cells;
    row_base[D] = row_cursor;
    total_cells += rows_of[D] * D;
    row_cursor += rows_of[D];
    r->bucket_rows.push_back(rows_of[D]);
    r->bucket_D.push_back(D);
  }
  int32_t sentinel = int32_t(r->n_rows);
  r->nbr_flat.assign(total_cells, sentinel);
  r->et_flat.assign(total_cells, 0);

  // fill slots: bucket-local row = global row - row_base[D]
  std::vector<int64_t> fill(n, 0);
  for (int64_t i = 0; i < m; i++) {
    int64_t v = dst[i];
    int64_t off = fill[v]++;
    int64_t k_of = off / cap;
    int64_t col = (k_of == 0) ? off : off % cap;
    int64_t D = D_v[v];
    int64_t grow = (k_of == 0) ? int64_t(r->perm[v])
                               : first_extra[v] + k_of - 1;
    // extra rows sit in the cap bucket after its real vertices
    int64_t base = (k_of == 0) ? row_base[D] : row_base[cap];
    int64_t local = grow - ((k_of == 0) ? base : row_base[cap]);
    int64_t cell = cell_base[(k_of == 0) ? D : cap]
        + local * ((k_of == 0) ? D : cap) + col;
    r->nbr_flat[size_t(cell)] = r->perm[src[i]];
    r->et_flat[size_t(cell)] = et[i];
  }

  std::lock_guard<std::mutex> lk(g_mu);
  g_results[g_next] = r;
  return g_next++;
}

int64_t ell_counts(int64_t handle, int64_t* out4) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_results.find(handle);
  if (it == g_results.end()) return -1;
  auto* r = it->second;
  out4[0] = r->n_rows;
  out4[1] = int64_t(r->extra_owner.size());
  out4[2] = int64_t(r->bucket_D.size());
  out4[3] = int64_t(r->nbr_flat.size());
  return 0;
}

int64_t ell_bucket_dims(int64_t handle, int64_t* out) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_results.find(handle);
  if (it == g_results.end()) return -1;
  auto* r = it->second;
  for (size_t b = 0; b < r->bucket_D.size(); b++) {
    out[2 * b] = r->bucket_rows[b];
    out[2 * b + 1] = r->bucket_D[b];
  }
  return 0;
}

int64_t ell_fill(int64_t handle, int32_t* perm, int32_t* inv,
                 int32_t* extra_owner, int32_t* nbr_flat,
                 int32_t* et_flat) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_results.find(handle);
  if (it == g_results.end()) return -1;
  auto* r = it->second;
  std::memcpy(perm, r->perm.data(), r->perm.size() * 4);
  std::memcpy(inv, r->inv.data(), r->inv.size() * 4);
  if (!r->extra_owner.empty())
    std::memcpy(extra_owner, r->extra_owner.data(),
                r->extra_owner.size() * 4);
  if (!r->nbr_flat.empty()) {
    std::memcpy(nbr_flat, r->nbr_flat.data(), r->nbr_flat.size() * 4);
    std::memcpy(et_flat, r->et_flat.data(), r->et_flat.size() * 4);
  }
  return 0;
}

void ell_free(int64_t handle) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_results.find(handle);
  if (it != g_results.end()) {
    delete it->second;
    g_results.erase(it);
  }
}

}  // extern "C"
