#!/usr/bin/env bash
# One-shot merge gate (docs/STATUS.md "round 19"): everything a PR
# must hold, in the order a failure is cheapest to see.
#
#   1. tier-1 — the fast test suite on the forced-CPU jax platform
#      (the same invocation the driver scores; `-m 'not slow'` keeps
#      the chaos soaks and bench legs out of the gate);
#   2. nebulint — the nineteen-check static/semantic/flow suite, run
#      ONCE in SARIF mode with the baseline applied; the JSON lands in
#      $CI_ARTIFACT_DIR (default build/) so CI uploads it as an
#      annotation artifact, and a non-empty `results` array fails the
#      gate exactly like the plain CLI would;
#   3. nebulamc — the deterministic interleaving model checker at
#      smoke budgets, also in SARIF mode; a found violation ships its
#      replayable schedule id inside the SARIF message text and fails
#      the gate (the exhaustive sweep lives in chaos.sh);
#   4. micro_bench — the performance-budget components (`--quick`
#      statistics are noisier but the budgets are sized for it); the
#      lint cold-wall budget (40 s), the mc smoke-sweep budget, the
#      admission/recovery/absorb/continuous/timeline path budgets and
#      the kernel roofline all gate here via micro_bench's own exit
#      status.
#
# The Perfetto golden (tests/golden_timeline.json, the byte-stable
# chrome_trace pin) rides along to $CI_ARTIFACT_DIR beside the SARIF
# artifacts so a reviewer can open the reference timeline in
# chrome://tracing without checking the branch out.
#
# scripts/lint.sh remains the interactive lint + sanitizer entry
# point; this script is the merge gate CI calls.
set -euo pipefail
cd "$(dirname "$0")/.."

ARTIFACT_DIR="${CI_ARTIFACT_DIR:-build}"
mkdir -p "${ARTIFACT_DIR}"

echo "== tier-1 (pytest, JAX_PLATFORMS=cpu) =="
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly

echo "== nebulint (SARIF artifact -> ${ARTIFACT_DIR}/nebulint.sarif) =="
JAX_PLATFORMS=cpu python -m nebula_tpu.tools.lint --format=sarif \
  > "${ARTIFACT_DIR}/nebulint.sarif"

echo "== nebulamc (SARIF artifact -> ${ARTIFACT_DIR}/nebulamc.sarif) =="
JAX_PLATFORMS=cpu python -m nebula_tpu.tools.mc run --smoke --format=sarif \
  > "${ARTIFACT_DIR}/nebulamc.sarif"

echo "== micro_bench (budget components, --quick) =="
JAX_PLATFORMS=cpu python -m nebula_tpu.tools.micro_bench --quick \
  > "${ARTIFACT_DIR}/micro_bench.json"

echo "== perfetto golden -> ${ARTIFACT_DIR}/golden_timeline.json =="
cp tests/golden_timeline.json "${ARTIFACT_DIR}/golden_timeline.json"

echo "ci.sh: merge gate green (artifacts in ${ARTIFACT_DIR}/)"
