#!/usr/bin/env bash
# Static-analysis + sanitizer gate (docs/static_analysis.md):
#   1. nebulint — the nineteen whole-package checks over nebula_tpu:
#      the AST checks (lock discipline, lock-order cycles, Status
#      discipline, JAX hot-path hygiene, flag/span/metric/event
#      registries), the two SEMANTIC passes — the jaxpr device-path
#      auditor (traces every registered kernel across its shape
#      buckets, proves the per-device HBM budget; needs jax but no
#      accelerator, hence JAX_PLATFORMS=cpu) and the RPC
#      wire-contract checker — the v3 FLOW passes: guard
#      inference (static mini-TSan), interprocedural
#      blocking-under-lock, Deadline/trace context-capture escape
#      analysis, plus the stale-suppression fossil detector — and the
#      v4 MESH layer: the SPMD collective/ICI-traffic/capacity
#      auditor (2/4/8-way CPU-mesh traces) and the carve-out
#      inventory over tpu/runtime.py's CPU-decline sites — and the
#      v5 OBLIGATION layer: must-call-on-all-paths tracking over the
#      acquire/release registry (lane seats, probe tokens, pipeline
#      slots, waiter heaps, the busy meter, rebuild markers, rider
#      wakeups, context binds) and the typed-protocol registry
#      closing every reason string + state-machine transition
#      (common/protocol.py) — and the v6 MC layer: mc-coverage, the
#      registry-to-scenario closure check (every STATE_MACHINES /
#      OBLIGATIONS entry modeled by a nebulamc scenario, no stale
#      covers tags, scenario classes fully instrumented);
#   2. nebulamc — the deterministic interleaving model checker
#      (tools/mc/) at each scenario's tier-1 smoke budget; failures
#      print replayable schedule ids (the exhaustive full-budget
#      sweep is scripts/chaos.sh --cell mc_sweep);
#   3. asan_driver — the native C ABI driven under the ASan+UBSan build,
#      when `make -C native asan` has produced the instrumented .so and
#      libasan is present (skipped, loudly, otherwise).
# Exit status is non-zero when any gate fails.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== nebulint (static + semantic analysis) =="
JAX_PLATFORMS=cpu python -m nebula_tpu.tools.lint

echo "== nebulamc (bounded interleaving model-check, smoke budgets) =="
JAX_PLATFORMS=cpu python -m nebula_tpu.tools.mc run --smoke

if [ -f native/libnebula_native_asan.so ]; then
  libasan="$(gcc -print-file-name=libasan.so 2>/dev/null || true)"
  if [ -n "${libasan}" ] && [ -f "${libasan}" ]; then
    echo "== asan_driver (native ABI under ASan+UBSan) =="
    tmp="$(mktemp -d)"
    trap 'rm -rf "${tmp}"' EXIT
    LD_PRELOAD="${libasan}" \
      NEBULA_NATIVE_SO="${PWD}/native/libnebula_native_asan.so" \
      JAX_PLATFORMS=cpu \
      ASAN_OPTIONS="strict_init_order=true:detect_stack_use_after_return=true:detect_container_overflow=true:detect_leaks=0" \
      UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1" \
      python tests/asan_driver.py "${tmp}"
  else
    echo "== asan_driver skipped (no libasan on this toolchain) =="
  fi
else
  echo "== asan_driver skipped (run 'make -C native asan' first) =="
fi

echo "lint.sh: all gates green"
