#!/usr/bin/env bash
# Cluster start/stop — the reference's scripts/services.sh equivalent.
#   scripts/services.sh start|stop|status|restart [graphd|storaged|metad|all]
# Env: NEBULA_HOME (repo root, default: script's parent), NEBULA_DATA
# (default $NEBULA_HOME/data), NEBULA_LOGS, PYTHON.
set -u
HERE="$(cd "$(dirname "$0")/.." && pwd)"
NEBULA_HOME="${NEBULA_HOME:-$HERE}"
NEBULA_DATA="${NEBULA_DATA:-$NEBULA_HOME/data}"
NEBULA_LOGS="${NEBULA_LOGS:-$NEBULA_HOME/logs}"
PYTHON="${PYTHON:-python3}"
EXTRA_FLAGS="${EXTRA_FLAGS:-}"   # e.g. "--flag load_data_interval_secs=1"
mkdir -p "$NEBULA_DATA" "$NEBULA_LOGS"

META_PORT="${META_PORT:-45500}"
STORAGE_PORT="${STORAGE_PORT:-44500}"
GRAPH_PORT="${GRAPH_PORT:-3699}"
META_ADDRS="127.0.0.1:${META_PORT}"

pidfile() { echo "$NEBULA_DATA/nebula-$1.pid"; }

start_one() {
    local name="$1"; shift
    local pf; pf="$(pidfile "$name")"
    if [ -f "$pf" ] && kill -0 "$(cat "$pf")" 2>/dev/null; then
        echo "[$name] already running (pid $(cat "$pf"))"
        return 0
    fi
    # setsid + full fd redirection: the daemon must not keep the
    # launcher's stdio alive (a caller capturing our output would
    # otherwise block on pipe EOF until the daemon dies)
    (cd "$NEBULA_HOME" && setsid nohup "$PYTHON" \
        -m "nebula_tpu.daemons.$name" \
        --flagfile "$NEBULA_HOME/etc/nebula-$name.conf.default" \
        --pid_file "$pf" "$@" $EXTRA_FLAGS \
        </dev/null >"$NEBULA_LOGS/nebula-$name.log" 2>&1 &)
    # first import of the device stack can take tens of seconds
    for _ in $(seq 1 600); do
        [ -f "$pf" ] && kill -0 "$(cat "$pf")" 2>/dev/null && break
        sleep 0.1
    done
    if [ -f "$pf" ] && kill -0 "$(cat "$pf")" 2>/dev/null; then
        echo "[$name] started (pid $(cat "$pf"))"
    else
        echo "[$name] FAILED to start — see $NEBULA_LOGS/nebula-$name.log"
        return 1
    fi
}

stop_one() {
    local name="$1"
    local pf; pf="$(pidfile "$name")"
    if [ -f "$pf" ] && kill -0 "$(cat "$pf")" 2>/dev/null; then
        kill "$(cat "$pf")"
        for _ in $(seq 1 100); do
            kill -0 "$(cat "$pf")" 2>/dev/null || break
            sleep 0.1
        done
        if kill -0 "$(cat "$pf")" 2>/dev/null; then
            kill -9 "$(cat "$pf")" 2>/dev/null   # graceful window expired
            sleep 0.2
        fi
        echo "[$name] stopped"
    else
        echo "[$name] not running"
    fi
    rm -f "$pf"
}

status_one() {
    local name="$1"
    local pf; pf="$(pidfile "$name")"
    if [ -f "$pf" ] && kill -0 "$(cat "$pf")" 2>/dev/null; then
        echo "[$name] running (pid $(cat "$pf"))"
    else
        echo "[$name] stopped"
    fi
}

cmd="${1:-status}"
target="${2:-all}"

run() {
    local action="$1" name="$2"
    case "$name" in
        metad)    case "$action" in
                      start) start_one metad --port "$META_PORT" \
                          --meta_server_addrs "$META_ADDRS" \
                          ${META_WS_PORT:+--ws_http_port "$META_WS_PORT"} \
                          --data_path "$NEBULA_DATA/meta" ;;
                      stop) stop_one metad ;;
                      status) status_one metad ;;
                  esac ;;
        storaged) case "$action" in
                      start) start_one storaged --port "$STORAGE_PORT" \
                          --meta_server_addrs "$META_ADDRS" \
                          ${STORAGE_WS_PORT:+--ws_http_port "$STORAGE_WS_PORT"} \
                          --data_path "$NEBULA_DATA/storage" ;;
                      stop) stop_one storaged ;;
                      status) status_one storaged ;;
                  esac ;;
        graphd)   case "$action" in
                      start) start_one graphd --port "$GRAPH_PORT" \
                          ${GRAPH_WS_PORT:+--ws_http_port "$GRAPH_WS_PORT"} \
                          --meta_server_addrs "$META_ADDRS" ;;
                      stop) stop_one graphd ;;
                      status) status_one graphd ;;
                  esac ;;
    esac
}

names() {
    case "$target" in
        all) echo "metad storaged graphd" ;;
        *)   echo "$target" ;;
    esac
}

case "$cmd" in
    start)   for n in $(names); do run start "$n" || exit 1; done ;;
    stop)    # stop in reverse dependency order
             for n in graphd storaged metad; do
                 case " $(names) " in *" $n "*) run stop "$n" ;; esac
             done ;;
    status)  for n in $(names); do run status "$n"; done ;;
    restart) "$0" stop "$target"; "$0" start "$target" ;;
    *) echo "usage: $0 start|stop|status|restart [graphd|storaged|metad|all]"
       exit 2 ;;
esac
