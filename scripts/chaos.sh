#!/usr/bin/env bash
# Crash-recovery chaos driver (docs/durability.md).
#
# Runs the FULL kill matrix — real SIGKILL'd subprocess daemons
# (tests/test_proc_chaos.py over tools/proc_cluster.py) plus the
# wire-level fault-injection chaos suite (tests/test_chaos.py) — under
# the runtime lock-order watchdog: NEBULA_LOCK_WATCHDOG=1 arms
# common/ordered_lock.py in THIS process and is inherited by every
# daemon subprocess ProcCluster spawns, so an inversion inside a
# recovering storaged fails its scenario too.
#
# Usage: scripts/chaos.sh [extra pytest args]
#   scripts/chaos.sh -k mid_append      # one matrix cell
#   scripts/chaos.sh -m 'chaos and not slow'   # smoke cells only
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export NEBULA_LOCK_WATCHDOG=1

exec python -m pytest tests/test_proc_chaos.py tests/test_chaos.py \
    tests/test_crash_recovery.py tests/test_write_serve.py \
    -v -m chaos -p no:cacheprovider "$@"
