#!/usr/bin/env bash
# Crash-recovery + network-partition chaos driver (docs/durability.md,
# docs/fault_injection.md).
#
# Runs the kill matrix — real SIGKILL'd subprocess daemons
# (tests/test_proc_chaos.py over tools/proc_cluster.py), the partition
# cells (directional link cuts via the /faults endpoint), the
# wire-level fault-injection chaos suite (tests/test_chaos.py), and
# the nebulamc exhaustive interleaving sweep (mc_sweep: every
# registered scenario at its full schedule budget, bound exhausted or
# red — docs/static_analysis.md "The model-checking layer") — under
# the runtime lock-order watchdog: NEBULA_LOCK_WATCHDOG=1 arms
# common/ordered_lock.py in THIS process and is inherited by every
# daemon subprocess ProcCluster spawns, so an inversion inside a
# recovering storaged fails its scenario too.
#
# Usage:
#   scripts/chaos.sh                      full matrix, per-cell summary
#   scripts/chaos.sh --cell list          name the cells
#   scripts/chaos.sh --cell partition_delta [--cell smoke ...]
#                                         selected cells only
#   scripts/chaos.sh [--cell ...] [extra pytest args]
#
# Every run ends with a per-cell PASS/FAIL table; any red cell makes
# the exit code nonzero.
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export NEBULA_LOCK_WATCHDOG=1

PROC=tests/test_proc_chaos.py
CELLS=(
  "smoke|${PROC}::TestProcSmoke"
  "mid_append|${PROC}::TestKillMatrix::test_kill_storaged_mid_append_no_acked_loss"
  "mid_flush|${PROC}::TestKillMatrix::test_kill_storaged_mid_flush_and_compaction"
  "leader_kill|${PROC}::TestKillMatrix::test_leader_kill_under_live_go_traffic"
  "metad_kill|${PROC}::TestKillMatrix::test_metad_sigkill_and_restart"
  "mid_absorb|${PROC}::TestKillMatrix::test_kill_storaged_mid_absorption_zero_acked_loss"
  "mid_continuous|${PROC}::TestKillMatrix::test_kill_storaged_mid_continuous_flight"
  "partition_leader|${PROC}::TestKillMatrix::test_partitioned_raft_leader_zero_acked_loss"
  "partition_delta|${PROC}::TestKillMatrix::test_mirror_host_partitioned_mid_delta_stream"
  "partition_graphd|${PROC}::TestKillMatrix::test_graphd_partitioned_from_storaged_ladder_serves"
  "snapshot_kill|${PROC}::TestKillMatrix::test_kill_follower_mid_snapshot_install"
  "wire_faults|tests/test_chaos.py"
  "crash_recovery|tests/test_crash_recovery.py"
  "write_serve|tests/test_write_serve.py"
  "mc_sweep|tests/test_mc.py::test_scenario_exhaustive_sweep"
)

cell_target() {
  local name=$1 entry
  for entry in "${CELLS[@]}"; do
    if [[ "${entry%%|*}" == "$name" ]]; then
      echo "${entry#*|}"
      return 0
    fi
  done
  return 1
}

selected=()
extra=()
while [[ $# -gt 0 ]]; do
  case "$1" in
    --cell)
      shift
      [[ $# -gt 0 ]] || { echo "--cell needs a name" >&2; exit 2; }
      if [[ "$1" == "list" ]]; then
        for entry in "${CELLS[@]}"; do echo "${entry%%|*}"; done
        exit 0
      fi
      cell_target "$1" >/dev/null || {
        echo "unknown cell '$1' (scripts/chaos.sh --cell list)" >&2
        exit 2
      }
      selected+=("$1")
      shift
      ;;
    *)
      extra+=("$1")
      shift
      ;;
  esac
done

if [[ ${#selected[@]} -eq 0 ]]; then
  for entry in "${CELLS[@]}"; do selected+=("${entry%%|*}"); done
fi

names=()
results=()
secs=()
red=0
for name in "${selected[@]}"; do
  target=$(cell_target "$name")
  echo
  echo "==== chaos cell: ${name} -> ${target}"
  t0=$SECONDS
  if python -m pytest "$target" -v -m chaos -p no:cacheprovider \
      ${extra[@]+"${extra[@]}"}; then
    results+=("PASS")
  else
    results+=("FAIL")
    red=1
  fi
  names+=("$name")
  secs+=($((SECONDS - t0)))
done

echo
echo "==== chaos matrix summary"
printf '%-20s %-6s %8s\n' CELL RESULT SECONDS
for i in "${!names[@]}"; do
  printf '%-20s %-6s %8s\n' "${names[$i]}" "${results[$i]}" "${secs[$i]}"
done
if [[ $red -ne 0 ]]; then
  echo "RED: at least one chaos cell failed" >&2
fi
exit $red
