"""Expression engine — typed AST with eval, encode/decode, and pushdown.

Capability parity with /root/reference/src/common/filter/Expressions.h:
  * the full node tree (property refs $^ $$ $- $var edge.prop, pseudo-props
    _type/_src/_dst/_rank, literals, function calls, unary, type casting,
    arithmetic, relational, logical — Expressions.h:284-812);
  * ExprContext with pluggable getters — the one mechanism powering both
    graphd-side eval and storaged-side pushdown eval (Expressions.h:24-115);
  * binary encode/decode so filters travel inside GetNeighbors requests
    (Expressions.h:117-235) — ours is a msgpack'd prefix tree;
  * prepare() semantic checks (aliases known, functions exist, arity).

TPU-first extra: the AST is deliberately data-only (node = op tag +
children), so tpu/expr_compile.py can lower the same tree to a vectorized
jax mask kernel over CSR property columns — one expression, three
backends (python eval, pushdown eval, XLA).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import msgpack

from .functions import FunctionManager

Value = Union[bool, int, float, str]


class ExprError(Exception):
    """Semantic/eval error (becomes Status at service boundaries)."""


class ExprContext:
    """Pluggable getters (reference ExpressionContext).

    Executors/processors install only the getters valid in their position;
    a missing getter raises ExprError at eval (like the reference's
    prepare-time rejection of out-of-position refs).
    """

    __slots__ = ("get_src_tag_prop", "get_dst_tag_prop", "get_alias_prop",
                 "get_input_prop", "get_variable_prop", "get_edge_type",
                 "get_edge_rank", "get_edge_src_id", "get_edge_dst_id",
                 "aliases")

    def __init__(self):
        self.get_src_tag_prop: Optional[Callable[[str, str], Value]] = None
        self.get_dst_tag_prop: Optional[Callable[[str, str], Value]] = None
        self.get_alias_prop: Optional[Callable[[str, str], Value]] = None
        self.get_input_prop: Optional[Callable[[str], Value]] = None
        self.get_variable_prop: Optional[Callable[[str, str], Value]] = None
        self.get_edge_type: Optional[Callable[[str], Value]] = None
        self.get_edge_rank: Optional[Callable[[str], Value]] = None
        self.get_edge_src_id: Optional[Callable[[str], Value]] = None
        self.get_edge_dst_id: Optional[Callable[[str], Value]] = None
        self.aliases: Dict[str, bool] = {}  # known edge aliases


def _require(getter, kind: str):
    if getter is None:
        raise ExprError(f"{kind} reference not allowed here")
    return getter


# ---------------------------------------------------------------- nodes
class Expression:
    KIND = "base"
    __slots__ = ()

    def eval(self, ctx: ExprContext) -> Value:
        raise NotImplementedError

    def prepare(self, ctx: ExprContext) -> None:
        """Static checks; default recurses children."""
        for c in self.children():
            c.prepare(ctx)

    def children(self) -> List["Expression"]:
        return []

    def to_wire(self) -> list:
        raise NotImplementedError

    def __str__(self) -> str:
        return self.KIND

    def __eq__(self, other):
        return (isinstance(other, Expression) and
                self.to_wire() == other.to_wire())

    def __hash__(self):
        return hash(str(self.to_wire()))


class PrimaryExpr(Expression):
    KIND = "lit"
    __slots__ = ("value",)

    def __init__(self, value: Value):
        self.value = value

    def eval(self, ctx):
        return self.value

    def to_wire(self):
        return [self.KIND, self.value]

    def __str__(self):
        return repr(self.value)


class SourcePropExpr(Expression):
    KIND = "src"  # $^.tag.prop
    __slots__ = ("tag", "prop")

    def __init__(self, tag: str, prop: str):
        self.tag, self.prop = tag, prop

    def eval(self, ctx):
        return _require(ctx.get_src_tag_prop, "$^ source")(self.tag, self.prop)

    def to_wire(self):
        return [self.KIND, self.tag, self.prop]

    def __str__(self):
        return f"$^.{self.tag}.{self.prop}"


class DestPropExpr(Expression):
    KIND = "dst"  # $$.tag.prop
    __slots__ = ("tag", "prop")

    def __init__(self, tag: str, prop: str):
        self.tag, self.prop = tag, prop

    def eval(self, ctx):
        return _require(ctx.get_dst_tag_prop, "$$ dest")(self.tag, self.prop)

    def to_wire(self):
        return [self.KIND, self.tag, self.prop]

    def __str__(self):
        return f"$$.{self.tag}.{self.prop}"


class AliasPropExpr(Expression):
    KIND = "edge"  # edge.prop
    __slots__ = ("alias", "prop")

    def __init__(self, alias: str, prop: str):
        self.alias, self.prop = alias, prop

    def eval(self, ctx):
        return _require(ctx.get_alias_prop, "edge prop")(self.alias, self.prop)

    def prepare(self, ctx):
        if ctx.aliases and self.alias not in ctx.aliases:
            raise ExprError(f"unknown edge alias `{self.alias}'")

    def to_wire(self):
        return [self.KIND, self.alias, self.prop]

    def __str__(self):
        return f"{self.alias}.{self.prop}"


class InputPropExpr(Expression):
    KIND = "input"  # $-.prop
    __slots__ = ("prop",)

    def __init__(self, prop: str):
        self.prop = prop

    def eval(self, ctx):
        return _require(ctx.get_input_prop, "$- input")(self.prop)

    def to_wire(self):
        return [self.KIND, self.prop]

    def __str__(self):
        return f"$-.{self.prop}"


class VariablePropExpr(Expression):
    KIND = "var"  # $var.prop
    __slots__ = ("var", "prop")

    def __init__(self, var: str, prop: str):
        self.var, self.prop = var, prop

    def eval(self, ctx):
        return _require(ctx.get_variable_prop, "$var")(self.var, self.prop)

    def to_wire(self):
        return [self.KIND, self.var, self.prop]

    def __str__(self):
        return f"${self.var}.{self.prop}"


class _EdgePseudoExpr(Expression):
    __slots__ = ("alias",)
    GETTER = ""

    def __init__(self, alias: str = ""):
        self.alias = alias

    def eval(self, ctx):
        return _require(getattr(ctx, self.GETTER), self.KIND)(self.alias)

    def to_wire(self):
        return [self.KIND, self.alias]

    def __str__(self):
        return f"{self.alias or ''}._{self.KIND.split('_')[-1]}"


class EdgeTypeExpr(_EdgePseudoExpr):
    KIND = "e_type"
    GETTER = "get_edge_type"


class EdgeSrcIdExpr(_EdgePseudoExpr):
    KIND = "e_src"
    GETTER = "get_edge_src_id"


class EdgeDstIdExpr(_EdgePseudoExpr):
    KIND = "e_dst"
    GETTER = "get_edge_dst_id"


class EdgeRankExpr(_EdgePseudoExpr):
    KIND = "e_rank"
    GETTER = "get_edge_rank"


class FunctionCallExpr(Expression):
    KIND = "fn"
    __slots__ = ("name", "args")

    def __init__(self, name: str, args: List[Expression]):
        self.name = name
        self.args = args

    def children(self):
        return self.args

    def prepare(self, ctx):
        FunctionManager.get(self.name, len(self.args))  # raises if bad
        super().prepare(ctx)

    def eval(self, ctx):
        fn = FunctionManager.get(self.name, len(self.args))
        return fn(*[a.eval(ctx) for a in self.args])

    def to_wire(self):
        return [self.KIND, self.name, [a.to_wire() for a in self.args]]

    def __str__(self):
        return f"{self.name}({', '.join(map(str, self.args))})"


class UnaryExpr(Expression):
    KIND = "unary"
    __slots__ = ("op", "operand")
    OPS = ("+", "-", "!")

    def __init__(self, op: str, operand: Expression):
        if op not in self.OPS:
            raise ExprError(f"bad unary op {op}")
        self.op, self.operand = op, operand

    def children(self):
        return [self.operand]

    def eval(self, ctx):
        v = self.operand.eval(ctx)
        if self.op == "!":
            return not _as_bool(v)
        _check_numeric(v, self.op)
        return v if self.op == "+" else -v

    def to_wire(self):
        return [self.KIND, self.op, self.operand.to_wire()]

    def __str__(self):
        return f"{self.op}({self.operand})"


class TypeCastingExpr(Expression):
    KIND = "cast"
    __slots__ = ("type_name", "operand")
    TYPES = ("int", "double", "string", "bool")

    def __init__(self, type_name: str, operand: Expression):
        if type_name not in self.TYPES:
            raise ExprError(f"bad cast type {type_name}")
        self.type_name, self.operand = type_name, operand

    def children(self):
        return [self.operand]

    def eval(self, ctx):
        v = self.operand.eval(ctx)
        try:
            if self.type_name == "int":
                return int(v)
            if self.type_name == "double":
                return float(v)
            if self.type_name == "string":
                return _to_string(v)
            return _as_bool(v)
        except (TypeError, ValueError) as e:
            raise ExprError(f"cannot cast {v!r} to {self.type_name}") from e

    def to_wire(self):
        return [self.KIND, self.type_name, self.operand.to_wire()]

    def __str__(self):
        return f"({self.type_name}){self.operand}"


class ArithmeticExpr(Expression):
    KIND = "arith"
    __slots__ = ("op", "left", "right")
    OPS = ("+", "-", "*", "/", "%", "^")

    def __init__(self, op: str, left: Expression, right: Expression):
        if op not in self.OPS:
            raise ExprError(f"bad arithmetic op {op}")
        self.op, self.left, self.right = op, left, right

    def children(self):
        return [self.left, self.right]

    def eval(self, ctx):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        op = self.op
        if op == "+":
            if isinstance(a, str) or isinstance(b, str):
                return _to_string(a) + _to_string(b)
            _check_numeric(a, op), _check_numeric(b, op)
            return a + b
        _check_numeric(a, op), _check_numeric(b, op)
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            if b == 0:
                raise ExprError("division by zero")
            if isinstance(a, int) and isinstance(b, int):
                q = abs(a) // abs(b)  # C-style truncation toward zero
                return q if (a >= 0) == (b >= 0) else -q
            return a / b
        if op == "%":
            if b == 0:
                raise ExprError("division by zero")
            if isinstance(a, int) and isinstance(b, int):
                r = abs(a) % abs(b)
                return r if a >= 0 else -r
            return math_fmod(a, b)
        # ^ — XOR on ints (reference uses bit_xor for ^)
        if not isinstance(a, int) or not isinstance(b, int):
            raise ExprError("^ requires integers")
        return a ^ b

    def to_wire(self):
        return [self.KIND, self.op, self.left.to_wire(), self.right.to_wire()]

    def __str__(self):
        return f"({self.left}{self.op}{self.right})"


class RelationalExpr(Expression):
    KIND = "rel"
    __slots__ = ("op", "left", "right")
    OPS = ("<", "<=", ">", ">=", "==", "!=")

    def __init__(self, op: str, left: Expression, right: Expression):
        if op not in self.OPS:
            raise ExprError(f"bad relational op {op}")
        self.op, self.left, self.right = op, left, right

    def children(self):
        return [self.left, self.right]

    def eval(self, ctx):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        # mixed numeric compares fine; string vs number is an error except ==/!=
        num_a = isinstance(a, (int, float)) and not isinstance(a, bool)
        num_b = isinstance(b, (int, float)) and not isinstance(b, bool)
        if num_a != num_b or (isinstance(a, bool) != isinstance(b, bool)):
            if self.op == "==":
                return False
            if self.op == "!=":
                return True
            raise ExprError(f"type mismatch in {a!r} {self.op} {b!r}")
        if self.op == "<":
            return a < b
        if self.op == "<=":
            return a <= b
        if self.op == ">":
            return a > b
        if self.op == ">=":
            return a >= b
        if self.op == "==":
            return a == b
        return a != b

    def to_wire(self):
        return [self.KIND, self.op, self.left.to_wire(), self.right.to_wire()]

    def __str__(self):
        return f"({self.left}{self.op}{self.right})"


class LogicalExpr(Expression):
    KIND = "logic"
    __slots__ = ("op", "left", "right")
    OPS = ("&&", "||")

    def __init__(self, op: str, left: Expression, right: Expression):
        if op not in self.OPS:
            raise ExprError(f"bad logical op {op}")
        self.op, self.left, self.right = op, left, right

    def children(self):
        return [self.left, self.right]

    def eval(self, ctx):
        a = _as_bool(self.left.eval(ctx))
        if self.op == "&&":
            return a and _as_bool(self.right.eval(ctx))
        return a or _as_bool(self.right.eval(ctx))

    def to_wire(self):
        return [self.KIND, self.op, self.left.to_wire(), self.right.to_wire()]

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


# ---------------------------------------------------------------- helpers
def math_fmod(a, b):
    import math
    return math.fmod(a, b)


def _as_bool(v: Value) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return v != 0
    raise ExprError(f"cannot use {v!r} as a boolean")


def _to_string(v: Value) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v == int(v):
        return f"{v:.6f}".rstrip("0").rstrip(".") if "." in f"{v:.6f}" else str(v)
    return str(v)


def _check_numeric(v, op):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise ExprError(f"non-numeric operand {v!r} for {op}")


# ---------------------------------------------------------------- codec
_KIND_MAP: Dict[str, Any] = {}


def _register_kinds():
    for cls in (PrimaryExpr, SourcePropExpr, DestPropExpr, AliasPropExpr,
                InputPropExpr, VariablePropExpr, EdgeTypeExpr, EdgeSrcIdExpr,
                EdgeDstIdExpr, EdgeRankExpr, FunctionCallExpr, UnaryExpr,
                TypeCastingExpr, ArithmeticExpr, RelationalExpr, LogicalExpr):
        _KIND_MAP[cls.KIND] = cls


_register_kinds()


def _from_wire(w: list) -> Expression:
    kind = w[0]
    cls = _KIND_MAP.get(kind)
    if cls is None:
        raise ExprError(f"bad encoded expression kind {kind!r}")
    if cls is PrimaryExpr:
        return PrimaryExpr(w[1])
    if cls in (SourcePropExpr, DestPropExpr, AliasPropExpr, VariablePropExpr):
        return cls(w[1], w[2])
    if cls is InputPropExpr:
        return InputPropExpr(w[1])
    if cls in (EdgeTypeExpr, EdgeSrcIdExpr, EdgeDstIdExpr, EdgeRankExpr):
        return cls(w[1])
    if cls is FunctionCallExpr:
        return FunctionCallExpr(w[1], [_from_wire(a) for a in w[2]])
    if cls in (UnaryExpr, TypeCastingExpr):
        return cls(w[1], _from_wire(w[2]))
    # binary
    return cls(w[1], _from_wire(w[2]), _from_wire(w[3]))


def encode_expr(expr: Expression) -> bytes:
    """Binary form for filter pushdown (reference Expression::encode)."""
    return msgpack.packb(expr.to_wire(), use_bin_type=True)


def decode_expr(data: bytes) -> Expression:
    try:
        wire = msgpack.unpackb(data, raw=False)
        return _from_wire(wire)
    except (msgpack.UnpackException, ValueError, IndexError, TypeError) as e:
        raise ExprError(f"corrupt encoded expression: {e}") from e
