from .expressions import (Expression, ExprContext, ExprError, PrimaryExpr,
                          SourcePropExpr, DestPropExpr, AliasPropExpr,
                          InputPropExpr, VariablePropExpr, EdgeTypeExpr,
                          EdgeSrcIdExpr, EdgeDstIdExpr, EdgeRankExpr,
                          FunctionCallExpr, UnaryExpr, TypeCastingExpr,
                          ArithmeticExpr, RelationalExpr, LogicalExpr,
                          encode_expr, decode_expr)
from .functions import FunctionManager
