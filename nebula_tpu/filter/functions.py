"""FunctionManager — built-in scalar functions.

Capability parity with /root/reference/src/common/filter/FunctionManager.cpp:
abs/floor/ceil/round/sqrt/cbrt/hypot/pow/exp/exp2/log/log10/log2, trig
(sin/asin/cos/acos/tan/atan), rand32/rand64, now, hash, strcasecmp.
Arity-checked at prepare time like the reference (min/max args).
"""
from __future__ import annotations

import math
import random
import time
from typing import Callable, Dict, List, Tuple


def _hash(v) -> int:
    """Deterministic 64-bit hash (MurmurHash-like finalizer over the
    string form — stable across processes, unlike Python's hash())."""
    if isinstance(v, bool):
        data = b"\x01" if v else b"\x00"
    elif isinstance(v, (int, float)):
        data = repr(v).encode()
    else:
        data = str(v).encode()
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    # signed int64
    return h - (1 << 64) if h >= (1 << 63) else h


_FUNCS: Dict[str, Tuple[int, int, Callable]] = {
    # name: (min_arity, max_arity, fn)
    "abs": (1, 1, lambda a: abs(a)),
    "floor": (1, 1, lambda a: math.floor(a)),
    "ceil": (1, 1, lambda a: math.ceil(a)),
    "round": (1, 1, lambda a: float(round(a))),
    "sqrt": (1, 1, lambda a: math.sqrt(a)),
    "cbrt": (1, 1, lambda a: math.copysign(abs(a) ** (1.0 / 3.0), a)),
    "hypot": (2, 2, lambda a, b: math.hypot(a, b)),
    "pow": (2, 2, lambda a, b: a ** b),
    "exp": (1, 1, lambda a: math.exp(a)),
    "exp2": (1, 1, lambda a: 2.0 ** a),
    "log": (1, 1, lambda a: math.log(a)),
    "log2": (1, 1, lambda a: math.log2(a)),
    "log10": (1, 1, lambda a: math.log10(a)),
    "sin": (1, 1, lambda a: math.sin(a)),
    "asin": (1, 1, lambda a: math.asin(a)),
    "cos": (1, 1, lambda a: math.cos(a)),
    "acos": (1, 1, lambda a: math.acos(a)),
    "tan": (1, 1, lambda a: math.tan(a)),
    "atan": (1, 1, lambda a: math.atan(a)),
    "rand32": (0, 2, lambda *a: _rand32(*a)),
    "rand64": (0, 2, lambda *a: _rand64(*a)),
    "now": (0, 0, lambda: int(time.time())),
    "hash": (1, 1, _hash),
    "strcasecmp": (2, 2, lambda a, b: _strcasecmp(a, b)),
    "length": (1, 1, lambda a: len(a)),
    "lower": (1, 1, lambda a: str(a).lower()),
    "upper": (1, 1, lambda a: str(a).upper()),
}


def _rand32(*args) -> int:
    if len(args) == 0:
        return random.randint(-(1 << 31), (1 << 31) - 1)
    if len(args) == 1:
        return random.randrange(args[0])
    return random.randrange(args[0], args[1])


def _rand64(*args) -> int:
    if len(args) == 0:
        return random.randint(-(1 << 63), (1 << 63) - 1)
    return _rand32(*args)


def _strcasecmp(a, b) -> int:
    x, y = str(a).lower(), str(b).lower()
    return 0 if x == y else (-1 if x < y else 1)


class FunctionManager:
    @staticmethod
    def get(name: str, arity: int) -> Callable:
        """Resolve + arity-check (raises ExprError on failure)."""
        from .expressions import ExprError
        rec = _FUNCS.get(name.lower())
        if rec is None:
            raise ExprError(f"unknown function {name}()")
        lo, hi, fn = rec
        if not lo <= arity <= hi:
            raise ExprError(f"{name}() expects {lo}..{hi} args, got {arity}")
        return fn

    @staticmethod
    def exists(name: str) -> bool:
        return name.lower() in _FUNCS

    @staticmethod
    def names() -> List[str]:
        return sorted(_FUNCS)
