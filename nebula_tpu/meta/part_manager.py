"""MetaServerBasedPartManager — meta-driven part placement.

Capability parity with /root/reference/src/kvstore/PartManager.h:132: a
MetaChangedListener that translates MetaClient cache diffs into
add/remove-part calls on the local store, so `CREATE SPACE` on metad makes
partitions (and their raft groups) appear on the right storaged hosts
within one refresh interval (SURVEY.md §3.4).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..interface.common import GraphSpaceID, HostAddr, PartitionID
from ..kvstore.partman import PartManager
from .client import MetaChangedListener, MetaClient


class MetaServerBasedPartManager(PartManager, MetaChangedListener):
    def __init__(self, meta_client: MetaClient, local_host: str):
        PartManager.__init__(self)
        self.meta = meta_client
        self.local_host = local_host
        meta_client.listener = self

    # ---- PartManager reads (from meta cache) -------------------------
    def parts(self, host: Optional[HostAddr] = None) -> Dict[GraphSpaceID, List[PartitionID]]:
        out: Dict[GraphSpaceID, List[PartitionID]] = {}
        with self.meta._cache_lock:
            for sid, cache in self.meta.spaces.items():
                mine = [p for p, peers in cache.parts_alloc.items()
                        if self.local_host in peers]
                if mine:
                    out[sid] = sorted(mine)
        return out

    def peers(self, space_id: GraphSpaceID, part_id: PartitionID) -> List[str]:
        c = self.meta.space_cache(space_id)
        return list(c.parts_alloc.get(part_id, [])) if c else []

    def part_exists(self, space_id, part_id) -> bool:
        c = self.meta.space_cache(space_id)
        return bool(c) and part_id in c.parts_alloc

    def space_exists(self, space_id) -> bool:
        return self.meta.space_cache(space_id) is not None

    # ---- MetaChangedListener (push into the store) -------------------
    def on_space_added(self, space_id: int) -> None:
        if self.handler:
            self.handler.add_space(space_id)

    def on_space_removed(self, space_id: int) -> None:
        if self.handler:
            self.handler.remove_space(space_id)

    def on_part_added(self, space_id: int, part_id: int, peers: List[str]) -> None:
        if self.handler:
            self.handler.add_space(space_id)
            self.handler.add_part(space_id, part_id,
                                  [HostAddr.parse(p) for p in peers])

    def on_part_removed(self, space_id: int, part_id: int) -> None:
        if self.handler:
            self.handler.remove_part(space_id, part_id)

    def on_part_updated(self, space_id: int, part_id: int, peers: List[str]) -> None:
        part = None
        if self.handler and hasattr(self.handler, "part"):
            part = self.handler.part(space_id, part_id)
        if part is not None and part.raft is not None:
            part.raft.update_peers([HostAddr.parse(p) for p in peers])
