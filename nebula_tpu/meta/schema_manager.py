"""SchemaManager — schema resolution seam shared by graphd and storaged.

Capability parity with /root/reference/src/meta/SchemaManager.h:18 and
ServerBasedSchemaManager.h:18 (resolve via MetaClient cache), plus the
test-double AdHocSchemaManager idiom (storage/test/AdHocSchemaManager.h)
used throughout our test pyramid.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..common.status import ErrorCode, Status, StatusOr
from ..interface.common import Schema


class SchemaManager:
    """Interface."""

    def get_tag_schema(self, space_id: int, tag_id: int, ver: int = -1) -> Optional[Schema]:
        raise NotImplementedError

    def get_edge_schema(self, space_id: int, etype: int, ver: int = -1) -> Optional[Schema]:
        raise NotImplementedError

    def to_tag_id(self, space_id: int, name: str) -> StatusOr[int]:
        raise NotImplementedError

    def to_edge_type(self, space_id: int, name: str) -> StatusOr[int]:
        raise NotImplementedError

    def tag_name(self, space_id: int, tag_id: int) -> Optional[str]:
        raise NotImplementedError

    def edge_name(self, space_id: int, etype: int) -> Optional[str]:
        raise NotImplementedError

    def all_edge_types(self, space_id: int) -> List[int]:
        raise NotImplementedError

    def all_tag_ids(self, space_id: int) -> List[int]:
        raise NotImplementedError


class ServerBasedSchemaManager(SchemaManager):
    """Resolves through a MetaClient's cache."""

    def __init__(self, meta_client):
        self.meta = meta_client

    def get_tag_schema(self, space_id, tag_id, ver=-1):
        return self.meta.get_tag_schema(space_id, tag_id, ver)

    def get_edge_schema(self, space_id, etype, ver=-1):
        return self.meta.get_edge_schema(space_id, etype, ver)

    def to_tag_id(self, space_id, name):
        return self.meta.get_tag_id(space_id, name)

    def to_edge_type(self, space_id, name):
        return self.meta.get_edge_type(space_id, name)

    def tag_name(self, space_id, tag_id):
        c = self.meta.space_cache(space_id)
        return c.tag_id_to_name.get(tag_id) if c else None

    def edge_name(self, space_id, etype):
        c = self.meta.space_cache(space_id)
        return c.edge_type_to_name.get(etype) if c else None

    def all_edge_types(self, space_id):
        return self.meta.all_edge_types(space_id)

    def all_tag_ids(self, space_id):
        return self.meta.all_tag_ids(space_id)


class AdHocSchemaManager(SchemaManager):
    """Schemas injected directly — no metad (test seam)."""

    def __init__(self):
        self.tags: Dict[Tuple[int, int, int], Schema] = {}
        self.edges: Dict[Tuple[int, int, int], Schema] = {}
        self.tag_names: Dict[Tuple[int, str], int] = {}
        self.edge_names: Dict[Tuple[int, str], int] = {}
        self.newest_tag: Dict[Tuple[int, int], int] = {}
        self.newest_edge: Dict[Tuple[int, int], int] = {}

    def add_tag_schema(self, space_id: int, tag_id: int, name: str,
                       schema: Schema) -> None:
        self.tags[(space_id, tag_id, schema.version)] = schema
        self.tag_names[(space_id, name)] = tag_id
        cur = self.newest_tag.get((space_id, tag_id), -1)
        self.newest_tag[(space_id, tag_id)] = max(cur, schema.version)

    def add_edge_schema(self, space_id: int, etype: int, name: str,
                        schema: Schema) -> None:
        self.edges[(space_id, etype, schema.version)] = schema
        self.edge_names[(space_id, name)] = etype
        cur = self.newest_edge.get((space_id, etype), -1)
        self.newest_edge[(space_id, etype)] = max(cur, schema.version)

    def get_tag_schema(self, space_id, tag_id, ver=-1):
        if ver < 0:
            ver = self.newest_tag.get((space_id, tag_id), -1)
        return self.tags.get((space_id, tag_id, ver))

    def get_edge_schema(self, space_id, etype, ver=-1):
        if ver < 0:
            ver = self.newest_edge.get((space_id, etype), -1)
        return self.edges.get((space_id, etype, ver))

    def to_tag_id(self, space_id, name):
        tid = self.tag_names.get((space_id, name))
        if tid is None:
            return StatusOr.error(Status(ErrorCode.E_SCHEMA_NOT_FOUND, f"tag {name}"))
        return StatusOr.of(tid)

    def to_edge_type(self, space_id, name):
        et = self.edge_names.get((space_id, name))
        if et is None:
            return StatusOr.error(Status(ErrorCode.E_SCHEMA_NOT_FOUND, f"edge {name}"))
        return StatusOr.of(et)

    def tag_name(self, space_id, tag_id):
        for (sid, name), tid in self.tag_names.items():
            if sid == space_id and tid == tag_id:
                return name
        return None

    def edge_name(self, space_id, etype):
        for (sid, name), et in self.edge_names.items():
            if sid == space_id and et == etype:
                return name
        return None

    def all_edge_types(self, space_id):
        return sorted({k[1] for k in self.newest_edge if k[0] == space_id})

    def all_tag_ids(self, space_id):
        return sorted({k[1] for k in self.newest_tag if k[0] == space_id})
